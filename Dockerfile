# Pinned test/dev environment for tensor2robot_tpu.
# Reference parity: the reference shipped a docker/ + CI setup pinning
# its TF1 environment (SURVEY.md §3 last row); this is the jax-era
# equivalent. TPU production images swap jax for jax[tpu] at the same
# pinned version.
#
# Build:  docker build -t tensor2robot-tpu .
# Test:   docker run --rm tensor2robot-tpu
# Shell:  docker run --rm -it tensor2robot-tpu bash

FROM python:3.12-slim

ENV PIP_NO_CACHE_DIR=1 \
    PYTHONDONTWRITEBYTECODE=1 \
    # Tests run on a virtual 8-device CPU mesh (multi-chip sharding
    # without TPU hardware); conftest.py re-asserts these.
    JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    TF_CPP_MIN_LOG_LEVEL=2

WORKDIR /workspace

COPY requirements.txt .
RUN pip install -r requirements.txt

COPY tensor2robot_tpu/ tensor2robot_tpu/
COPY tests/ tests/
COPY bench.py __graft_entry__.py ./

CMD ["python", "-m", "pytest", "tests/", "-q"]
