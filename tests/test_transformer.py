"""Transformer trunk + long-context episode BC model.

The long-context consumer path: pluggable exact-attention backends
(reference / flash / ring) behind one trunk, and a vrgripper model
that clones actions conditioned on full episode history with a
length-masked loss.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tensor2robot_tpu import train_eval
from tensor2robot_tpu.data.abstract_input_generator import Mode
from tensor2robot_tpu.data.tfrecord_input_generator import (
    TFRecordEpisodeInputGenerator,
)
from tensor2robot_tpu.layers import CausalTransformer
from tensor2robot_tpu.models import optimizers as opt_lib
from tensor2robot_tpu.research.vrgripper import (
    VRGripperTransformerModel,
    collect_demo_episodes,
)
from tensor2robot_tpu.specs import TensorSpecStruct
from tensor2robot_tpu.telemetry.records import read_records

IMG = 24  # matches the per-step BC closed-loop test scale


def tiny_model(**kwargs):
  kwargs.setdefault(
      "create_optimizer_fn",
      lambda: opt_lib.create_optimizer(learning_rate=3e-3))
  return VRGripperTransformerModel(
      image_size=IMG, filters=(8, 16), embedding_size=32, width=48,
      depth=1, num_heads=2, max_context_length=64,
      attention_impl="reference", **kwargs)


class TestCausalTransformer:

  def test_shapes_and_finite(self):
    net = CausalTransformer(width=32, depth=2, num_heads=2, max_len=64,
                            attention_impl="reference")
    x = jnp.asarray(
        np.random.default_rng(0).standard_normal((2, 16, 8)),
        jnp.float32)
    variables = net.init(jax.random.PRNGKey(0), x)
    out = net.apply(variables, x)
    assert out.shape == (2, 16, 32)
    assert np.isfinite(np.asarray(out)).all()

  def test_causality(self):
    """Perturbing step t must not change outputs before t."""
    net = CausalTransformer(width=32, depth=2, num_heads=2, max_len=64,
                            attention_impl="reference",
                            dtype=jnp.float32)
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((1, 12, 8)), jnp.float32)
    variables = net.init(jax.random.PRNGKey(0), x)
    base = np.asarray(net.apply(variables, x))
    x2 = x.at[0, 7].add(5.0)
    pert = np.asarray(net.apply(variables, x2))
    np.testing.assert_allclose(pert[0, :7], base[0, :7], atol=1e-5)
    assert np.abs(pert[0, 7:] - base[0, 7:]).max() > 1e-3

  def test_flash_impl_matches_reference(self):
    """Backend swap keeps outputs (checkpoint portability)."""
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.standard_normal((2, 64, 8)), jnp.float32)
    ref_net = CausalTransformer(width=32, depth=1, num_heads=2,
                                max_len=64,
                                attention_impl="reference",
                                dtype=jnp.float32)
    variables = ref_net.init(jax.random.PRNGKey(0), x)
    ref = ref_net.apply(variables, x)
    # Flash kernel in interpret mode shares the variables verbatim.
    import tensor2robot_tpu.layers.transformer as tr

    orig = tr._attend
    tr._attend = lambda q, k, v, *, impl, causal, mesh: (
        __import__("tensor2robot_tpu.ops", fromlist=["flash_attention"])
        .flash_attention(q, k, v, causal=causal, block_q=32,
                         block_k=32, interpret=True))
    try:
      flash_net = CausalTransformer(width=32, depth=1, num_heads=2,
                                    max_len=64,
                                    attention_impl="flash",
                                    dtype=jnp.float32)
      flash = flash_net.apply(variables, x)
    finally:
      tr._attend = orig
    np.testing.assert_allclose(np.asarray(flash), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)

  def test_max_len_enforced(self):
    net = CausalTransformer(width=16, depth=1, num_heads=2, max_len=8,
                            attention_impl="reference")
    x = jnp.zeros((1, 16, 4))
    with pytest.raises(ValueError, match="max_len"):
      net.init(jax.random.PRNGKey(0), x)

  def test_width_not_divisible_by_heads_raises(self):
    net = CausalTransformer(width=30, depth=1, num_heads=4, max_len=16,
                            attention_impl="reference")
    with pytest.raises(ValueError, match="heads"):
      net.init(jax.random.PRNGKey(0), jnp.zeros((1, 8, 4)))

  def test_ring_without_mesh_raises(self):
    """impl="ring" with no mesh must fail loudly, not silently fall
    back to single-device attention."""
    net = CausalTransformer(width=32, depth=1, num_heads=2, max_len=16,
                            attention_impl="ring")
    with pytest.raises(ValueError, match="mesh"):
      net.init(jax.random.PRNGKey(0), jnp.zeros((1, 8, 4)))

  @pytest.mark.slow
  def test_ring_flash_forward_and_gradients_match_reference(self):
    """Train through the pod path: ring over the seq mesh with flash
    blocks (pallas interpreter on CPU). Outputs AND parameter
    gradients must match the single-device reference backend — the
    claim that checkpoints are portable between "train with ring on a
    pod" and "serve with flash on one chip"."""
    from tensor2robot_tpu.parallel import SEQ_AXIS, create_mesh

    mesh = create_mesh({SEQ_AXIS: 8})
    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.standard_normal((2, 16, 8)), jnp.float32)
    kwargs = dict(width=32, depth=1, num_heads=2, max_len=16,
                  dtype=jnp.float32)
    ring_net = CausalTransformer(attention_impl="ring_flash",
                                 mesh=mesh, **kwargs)
    ref_net = CausalTransformer(attention_impl="reference", **kwargs)
    variables = ref_net.init(jax.random.PRNGKey(0), x)

    np.testing.assert_allclose(
        np.asarray(ring_net.apply(variables, x)),
        np.asarray(ref_net.apply(variables, x)),
        atol=1e-5, rtol=1e-5)

    ring_grads = jax.grad(
        lambda p: jnp.sum(ring_net.apply(p, x) ** 2))(variables)
    ref_grads = jax.grad(
        lambda p: jnp.sum(ref_net.apply(p, x) ** 2))(variables)
    flat_ring = jax.tree_util.tree_leaves_with_path(ring_grads)
    flat_ref = jax.tree.leaves(ref_grads)
    assert flat_ring and len(flat_ring) == len(flat_ref)
    for (path, rg), eg in zip(flat_ring, flat_ref):
      np.testing.assert_allclose(
          np.asarray(rg), np.asarray(eg), atol=5e-4, rtol=5e-4,
          err_msg=str(path))


def _train_bc_run(tmp_path_factory, name, demo_seed, **model_kwargs):
  """Shared BC train harness: demos → train_eval → (model, model_dir).

  One copy of the harness config so the dense and MoE families cannot
  silently diverge."""
  root = tmp_path_factory.mktemp(name)
  data = collect_demo_episodes(
      str(root / "demos.tfrecord"), num_episodes=96, image_size=IMG,
      seed=demo_seed, action_noise=0.1)
  model = tiny_model(**model_kwargs)
  model_dir = str(root / "model")
  train_eval.train_eval_model(
      model=model,
      model_dir=model_dir,
      input_generator_train=TFRecordEpisodeInputGenerator(
          file_patterns=data, sequence_length=16, batch_size=16,
          shuffle_buffer_size=96, seed=1),
      max_train_steps=400,
      batch_size=8,
      save_checkpoints_steps=400,
      log_every_steps=10,
  )
  return model, model_dir


def _restored_context_policy(model, model_dir, context_length=16):
  """Restore-from-checkpoint → full-history policy, one copy."""
  from tensor2robot_tpu.utils import checkpoints as ckpt_lib

  state = model.create_inference_state(jax.random.PRNGKey(0))
  variables = ckpt_lib.restore_variables(
      model_dir, like={"params": state.params,
                       "batch_stats": state.batch_stats or {}})
  state = state.replace(params=variables["params"])
  return model.make_context_policy(state,
                                   context_length=context_length)


@pytest.mark.slow
class TestTransformerBC:

  @pytest.fixture(scope="class")
  def run(self, tmp_path_factory):
    return _train_bc_run(tmp_path_factory, "tf_bc", demo_seed=0)

  def test_loss_decreases(self, run):
    _, model_dir = run
    records = read_records(
        os.path.join(model_dir, "metrics_train.jsonl"))
    assert records[-1]["mse"] < records[0]["mse"] * 0.7

  def test_beats_zero_action_baseline(self, run):
    """The clone must beat predicting zeros on held-out episodes."""
    from tensor2robot_tpu.predictors import CheckpointPredictor
    from tensor2robot_tpu.research.vrgripper.vrgripper_env import (
        VRGripperEnv,
        collect_expert_episode,
    )

    model, model_dir = run
    predictor = CheckpointPredictor(model, checkpoint_dir=model_dir)
    assert predictor.restore(timeout_secs=0)
    env = VRGripperEnv(image_size=IMG, seed=99)
    rng = np.random.default_rng(99)
    t = 16
    errors, baselines = [], []
    for _ in range(6):
      ep = collect_expert_episode(env, action_noise=0.0, min_steps=8,
                                  rng=rng)
      steps = min(t, len(ep["action"]))
      pad = lambda x: np.pad(  # noqa: E731
          x[:steps], [(0, t - steps)] + [(0, 0)] * (x.ndim - 1))
      out = predictor.predict({
          "image": pad(ep["image"])[None],
          "gripper_pose": pad(ep["gripper_pose"])[None],
      })
      predicted = np.asarray(out["action"])[0, :steps]
      target = ep["action"][:steps]
      errors.append(np.abs(predicted - target).mean())
      baselines.append(np.abs(target).mean())
    assert np.mean(errors) < 0.6 * np.mean(baselines), (
        np.mean(errors), np.mean(baselines))

  def test_closed_loop_context_policy(self, run):
    """Full-history policy drives the env: history accumulates, resets
    at episode boundaries, and the clone closes the loop."""
    from tensor2robot_tpu.research.vrgripper import (
        evaluate_gripper_policy,
    )

    model, model_dir = run
    policy = _restored_context_policy(model, model_dir)
    metrics = evaluate_gripper_policy(
        policy, num_episodes=10, image_size=IMG, seed=33)
    assert metrics["num_episodes"] == 10.0
    # The scripted task is easy for a working clone; a broken history
    # buffer (stale context, missing resets) tanks this immediately.
    assert metrics["success_rate"] >= 0.4, metrics

  def test_savedmodel_export_round_trip(self, run):
    """The long-context family serves through the SAME jax2tf
    SavedModel handoff as every other model: exported per-step
    actions must match checkpoint serving over a full episode batch
    (sequence specs ride the export signature as [B, T, ...])."""
    from tensor2robot_tpu.export import SavedModelExportGenerator
    from tensor2robot_tpu.predictors import (
        CheckpointPredictor,
        SavedModelPredictor,
    )
    from tensor2robot_tpu.utils import checkpoints as ckpt_lib

    model, model_dir = run
    state = model.create_inference_state(jax.random.PRNGKey(0))
    variables = ckpt_lib.restore_variables(
        model_dir, like={"params": state.params,
                         "batch_stats": state.batch_stats or {}})
    state = state.replace(params=variables["params"])
    export_dir = SavedModelExportGenerator(
        include_tf_example_signature=False).export(
            model, jax.device_get(state), model_dir)
    predictor = SavedModelPredictor(export_dir.rsplit("/", 1)[0])
    assert predictor.restore(timeout_secs=0)

    rng = np.random.default_rng(17)
    t = 16
    batch = {
        "image": rng.integers(0, 255, (2, t, IMG, IMG, 3)
                              ).astype(np.uint8),
        "gripper_pose": rng.standard_normal((2, t, 3)
                                            ).astype(np.float32),
    }
    exported = predictor.predict(batch)
    checkpoint = CheckpointPredictor(model, checkpoint_dir=model_dir)
    assert checkpoint.restore(timeout_secs=0)
    native = checkpoint.predict(batch)
    assert np.asarray(exported["action"]).shape == (2, t, 3)
    np.testing.assert_allclose(
        np.asarray(exported["action"]), np.asarray(native["action"]),
        atol=2e-2, rtol=2e-2)

  def test_default_export_skips_proto_signature_with_warning(
      self, run):
    """Sequence specs can't ride the tf.Example wire: the DEFAULT
    exporter config (include_tf_example_signature=True, as
    create_default_exporters builds it) must still succeed — warning
    and skipping the proto signature instead of crashing in
    build_feature_map."""
    import tensorflow as tf

    from tensor2robot_tpu.export import SavedModelExportGenerator
    from tensor2robot_tpu.utils import checkpoints as ckpt_lib

    model, model_dir = run
    state = model.create_inference_state(jax.random.PRNGKey(0))
    variables = ckpt_lib.restore_variables(
        model_dir, like={"params": state.params,
                         "batch_stats": state.batch_stats or {}})
    state = state.replace(params=variables["params"])
    with pytest.warns(RuntimeWarning, match="SequenceExample"):
      export_dir = SavedModelExportGenerator().export(
          model, jax.device_get(state), model_dir)
    loaded = tf.saved_model.load(export_dir)
    assert "serving_default" in loaded.signatures
    assert "parse_tf_example" not in loaded.signatures
    assert "parse_tf_sequence_example" not in loaded.signatures

  def test_sequence_example_signature_round_trip(self, run):
    """With a declared static episode length the exporter emits a
    tf.SequenceExample proto signature whose outputs match the numpy
    serving path on same-length episodes."""
    import tensorflow as tf

    from tensor2robot_tpu.data import tfexample
    from tensor2robot_tpu.export import SavedModelExportGenerator
    from tensor2robot_tpu.predictors import SavedModelPredictor
    from tensor2robot_tpu.utils import checkpoints as ckpt_lib

    model, model_dir = run
    state = model.create_inference_state(jax.random.PRNGKey(0))
    variables = ckpt_lib.restore_variables(
        model_dir, like={"params": state.params,
                         "batch_stats": state.batch_stats or {}})
    state = state.replace(params=variables["params"])
    t = 16
    export_dir = SavedModelExportGenerator(
        sequence_example_length=t).export(
            model, jax.device_get(state), model_dir)
    loaded = tf.saved_model.load(export_dir)
    assert "parse_tf_sequence_example" in loaded.signatures

    feature_spec = model.preprocessor.get_in_feature_specification(
        Mode.PREDICT)
    rng = np.random.default_rng(29)
    batch = {
        "image": rng.integers(0, 255, (2, t, IMG, IMG, 3)
                              ).astype(np.uint8),
        "gripper_pose": rng.standard_normal((2, t, 3)
                                            ).astype(np.float32),
    }
    serialized = [
        tfexample.encode_sequence_example(
            {k: v[i] for k, v in batch.items()}, feature_spec)
        for i in range(2)
    ]
    from_protos = loaded.signatures["parse_tf_sequence_example"](
        examples=tf.constant(serialized))
    predictor = SavedModelPredictor(export_dir.rsplit("/", 1)[0])
    assert predictor.restore(timeout_secs=0)
    from_numpy = predictor.predict(batch)
    np.testing.assert_allclose(
        np.asarray(from_protos["action"]),
        np.asarray(from_numpy["action"]), atol=1e-4, rtol=1e-4)

  def test_masked_loss_ignores_padding(self):
    model = tiny_model()
    state = model.create_train_state(jax.random.PRNGKey(0))
    rng = np.random.default_rng(3)
    t = 8
    feats = {
        "image": rng.integers(0, 255, (2, t, IMG, IMG, 3)
                              ).astype(np.uint8),
        "gripper_pose": rng.standard_normal((2, t, 3)
                                            ).astype(np.float32),
        "sequence_length": np.array([4, 6], np.int32),
    }
    labels = {"action": rng.standard_normal((2, t, 3)
                                            ).astype(np.float32)}
    loss1, _ = model.loss_fn(
        state.params, state.batch_stats,
        TensorSpecStruct.from_flat_dict(feats),
        TensorSpecStruct.from_flat_dict(labels), None, Mode.EVAL)
    # Corrupt ONLY padding-step labels: the masked loss must not move.
    labels2 = {"action": labels["action"].copy()}
    labels2["action"][0, 4:] += 100.0
    labels2["action"][1, 6:] -= 100.0
    loss2, _ = model.loss_fn(
        state.params, state.batch_stats,
        TensorSpecStruct.from_flat_dict(feats),
        TensorSpecStruct.from_flat_dict(labels2), None, Mode.EVAL)
    np.testing.assert_allclose(float(loss1), float(loss2), rtol=1e-6)


class TestMoETransformerBC:
  """MoE through the research family: trains, aux loss in the loop."""

  @pytest.fixture(scope="class")
  def run_moe(self, tmp_path_factory):
    """Train the MoE variant through the SAME harness as the dense
    family (one config, two model kwargs)."""
    return _train_bc_run(tmp_path_factory, "tf_moe_bc", demo_seed=5,
                         moe_experts=2, moe_every=1)

  @pytest.mark.slow
  def test_moe_clone_closes_the_loop(self, run_moe):
    """Routed-expert BC must actually learn the task, not just run:
    same closed-loop success bar as the dense transformer family."""
    from tensor2robot_tpu.research.vrgripper import (
        evaluate_gripper_policy,
    )

    model, model_dir = run_moe
    records = read_records(
        os.path.join(model_dir, "metrics_train.jsonl"))
    assert records[-1]["mse"] < records[0]["mse"] * 0.7
    assert "aux_loss" in records[-1]  # experts routed during training
    policy = _restored_context_policy(model, model_dir)
    metrics = evaluate_gripper_policy(
        policy, num_episodes=10, image_size=IMG, seed=37)
    assert metrics["success_rate"] >= 0.4, metrics

  def test_train_steps_include_aux_loss_and_predict_strips_it(self):
    model = tiny_model(moe_experts=2, moe_every=1)
    state = model.create_train_state(jax.random.PRNGKey(0))
    rng = np.random.default_rng(11)
    t = 8
    feats = TensorSpecStruct.from_flat_dict({
        "image": rng.integers(0, 255, (2, t, IMG, IMG, 3)
                              ).astype(np.uint8),
        "gripper_pose": rng.standard_normal((2, t, 3)
                                            ).astype(np.float32),
    })
    labels = TensorSpecStruct.from_flat_dict({
        "action": rng.standard_normal((2, t, 3)).astype(np.float32)})
    step = jax.jit(model.train_step)
    for i in range(3):
      state, metrics = step(state, feats, labels,
                            jax.random.PRNGKey(i))
    # The load-balance aux is a training metric and part of the loss.
    assert "aux_loss" in metrics
    assert float(metrics["aux_loss"]) >= 1.0 - 1e-4
    assert np.isfinite(float(metrics["loss"]))
    # Serving outputs never carry the private aux key.
    out = model.predict_step(state, feats)
    assert "_aux_loss" not in out
    assert out["action"].shape == (2, t, 3)

  def test_moe_gin_config_parses(self):
    from tensor2robot_tpu import config as gin
    import tensor2robot_tpu.train_eval  # noqa: F401
    import tensor2robot_tpu.research.vrgripper  # noqa: F401
    import tensor2robot_tpu.data  # noqa: F401
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tensor2robot_tpu", "research", "vrgripper", "configs",
        "train_vrgripper_transformer_moe.gin")
    gin.clear_config()
    try:
      gin.parse_config_files_and_bindings([path], [])
      model = gin.query_parameter("train_eval_model.model").resolve()
      assert model._moe_experts == 8
      net = model.create_network()
      assert net.moe_experts == 8
    finally:
      gin.clear_config()


class TestShippedConfig:

  def test_config_parses_and_builds_model(self):
    from tensor2robot_tpu import config as gin
    import tensor2robot_tpu.train_eval  # noqa: F401
    import tensor2robot_tpu.research.vrgripper  # noqa: F401
    import tensor2robot_tpu.data  # noqa: F401
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tensor2robot_tpu", "research", "vrgripper", "configs",
        "train_vrgripper_transformer.gin")
    gin.clear_config()
    try:
      gin.parse_config_files_and_bindings([path], [])
      model = gin.query_parameter("train_eval_model.model").resolve()
      assert model.get_feature_specification(
          Mode.TRAIN).image.is_sequence
    finally:
      gin.clear_config()


class TestAuxLossKeyReservation:
  """'aux_loss' is reserved for the network-sown auxiliary loss: a
  subclass scalar/metric of the same name raised silently-overwritten
  metrics until round 5; now it's a loud ValueError (advisor
  finding)."""

  def _batch(self, t=8):
    rng = np.random.default_rng(2)
    feats = TensorSpecStruct.from_flat_dict({
        "image": rng.integers(0, 255, (2, t, IMG, IMG, 3)
                              ).astype(np.uint8),
        "gripper_pose": rng.standard_normal((2, t, 3)
                                            ).astype(np.float32),
    })
    labels = TensorSpecStruct.from_flat_dict({
        "action": rng.standard_normal((2, t, 3)).astype(np.float32)})
    return feats, labels

  def test_train_scalar_collision_raises(self):
    model = tiny_model(moe_experts=2, moe_every=1)
    orig = model.model_train_fn

    def clashing(features, labels, outputs, mode):
      loss, scalars = orig(features, labels, outputs, mode)
      return loss, {**scalars, "aux_loss": jnp.zeros(())}

    model.model_train_fn = clashing
    state = model.create_train_state(jax.random.PRNGKey(0))
    feats, labels = self._batch()
    with pytest.raises(ValueError, match="reserved"):
      model.train_step(state, feats, labels, jax.random.PRNGKey(1))

  def test_eval_metric_collision_raises(self):
    model = tiny_model(moe_experts=2, moe_every=1)
    orig = model.model_eval_fn

    def clashing(features, labels, outputs):
      return {**orig(features, labels, outputs),
              "aux_loss": jnp.zeros(())}

    model.model_eval_fn = clashing
    state = model.create_train_state(jax.random.PRNGKey(0))
    feats, labels = self._batch()
    with pytest.raises(ValueError, match="reserved"):
      model.eval_step(state, feats, labels)
