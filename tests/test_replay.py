"""Tests for the replay data plane: store, service, sampler, rewiring.

The contracts under pin:
  * the sharded `ReplayStore`'s 1-shard uniform mode is BIT-IDENTICAL
    to the legacy in-process ring buffer (an inline copy of the
    retired 106-line implementation is the oracle), and a full QT-Opt
    training run through the new plane reproduces the legacy path's
    parameters exactly;
  * failure paths: an actor crash mid-episode leaves the store
    consistent (no partial episode), queue overflow increments drop
    counters and never blocks the learner, and a crashed actor's
    restart resumes ingestion;
  * the staleness metric measures what it claims (known-age fixtures);
  * the prefetch lookahead depth defaults to 1 in the online regime
    (the round-5 K>1 sampling-lead finding) and is configurable.
"""

import json
import os
import threading
import time

import jax
import numpy as np
import pytest

from tensor2robot_tpu.replay import (
    STALENESS_BUCKETS,
    ReplayBatchSampler,
    ReplayStore,
    ReplayWriteService,
    make_stream,
)
from tensor2robot_tpu.research.qtopt import (
    GraspActor,
    GraspingQModel,
    QTOptLearner,
    ReplayBuffer,
    ToyGraspEnv,
    train_qtopt,
)
from tensor2robot_tpu.specs import TensorSpecStruct, make_random_tensors
from tensor2robot_tpu.telemetry.records import read_records

RNG = jax.random.PRNGKey(0)


def _tiny_learner(**kwargs):
  model = GraspingQModel(
      image_size=16, torso_filters=(8,), head_filters=(8,),
      dense_sizes=(16,), action_dim=2, **kwargs)
  return QTOptLearner(model, cem_population=8, cem_iterations=1,
                      cem_elites=2)


def _spec():
  return _tiny_learner().transition_specification()


class _LegacyReplayBuffer:
  """The retired single-process ring buffer, verbatim semantics — the
  oracle the adapter/store must match bit-for-bit at one shard."""

  def __init__(self, transition_spec, capacity=100_000, seed=0):
    from tensor2robot_tpu import specs as specs_lib

    self._spec = specs_lib.flatten_spec_structure(transition_spec)
    self._capacity = int(capacity)
    self._storage = {}
    for key, spec in self._spec.to_flat_dict().items():
      self._storage[key] = np.zeros(
          (self._capacity,) + tuple(spec.shape), dtype=spec.dtype)
    self._rng = np.random.default_rng(seed)
    self._insert_index = 0
    self._size = 0

  def __len__(self):
    return self._size

  @property
  def capacity(self):
    return self._capacity

  def add(self, transitions):
    flat = (transitions.to_flat_dict()
            if isinstance(transitions, TensorSpecStruct)
            else dict(transitions))
    n = next(iter(flat.values())).shape[0]
    if n > self._capacity:
      flat = {k: v[-self._capacity:] for k, v in flat.items()}
      n = self._capacity
    start = self._insert_index
    idx = (start + np.arange(n)) % self._capacity
    for key, store in self._storage.items():
      store[idx] = np.ascontiguousarray(flat[key])
    self._insert_index = int((start + n) % self._capacity)
    self._size = int(min(self._size + n, self._capacity))

  def sample(self, batch_size):
    idx = self._rng.integers(0, self._size, size=batch_size)
    return TensorSpecStruct.from_flat_dict(
        {key: store[idx] for key, store in self._storage.items()})

  def as_stream(self, batch_size):
    while True:
      yield self.sample(batch_size)

  def wait_until_size(self, min_size, timeout_secs=None):
    return self._size >= min_size


class TestReplayStore:

  def test_add_sample_round_trip_wire_dtypes(self):
    store = ReplayStore(_spec(), capacity=64, num_shards=2)
    store.add(make_random_tensors(_spec(), batch_size=32, seed=0))
    assert len(store) == 32
    flat = store.sample(16).to_flat_dict()
    assert flat["image"].shape == (16, 16, 16, 3)
    assert flat["image"].dtype == np.uint8  # stored in wire dtype

  def test_shard_routing_balances(self):
    store = ReplayStore(_spec(), capacity=256, num_shards=4)
    for i in range(4):
      store.add(make_random_tensors(_spec(), batch_size=16, seed=i))
    assert store.shard_sizes() == (16, 16, 16, 16)

  def test_eviction_counted_on_wraparound(self):
    store = ReplayStore(_spec(), capacity=16, num_shards=1)
    for seed in range(3):
      store.add(make_random_tensors(_spec(), batch_size=10, seed=seed))
    assert len(store) == 16
    assert store.evictions_total == 14  # 30 added, 16 live

  def test_batch_larger_than_shard_keeps_tail(self):
    store = ReplayStore(_spec(), capacity=8, num_shards=1)
    batch = make_random_tensors(_spec(), batch_size=20, seed=0)
    store.add(batch)
    assert len(store) == 8
    sampled = store.sample(4).to_flat_dict()["image"]
    # Every sampled row must come from the LAST 8 rows of the batch.
    tail = batch.to_flat_dict()["image"][-8:]
    for row in sampled:
      assert any(np.array_equal(row, t) for t in tail)

  def test_oversized_batch_splits_across_shards(self):
    """A batch bigger than one shard must use the TOTAL capacity
    (split round-robin), not silently truncate to shard capacity."""
    store = ReplayStore(_spec(), capacity=64, num_shards=2, seed=0)
    store.add(make_random_tensors(_spec(), batch_size=48, seed=0))
    assert len(store) == 48
    assert store.evictions_total == 0
    assert set(store.shard_sizes()) == {32, 16}

  def test_negative_priority_raises(self):
    store = ReplayStore(_spec(), capacity=32, sampling="prioritized")
    with pytest.raises(ValueError, match="priority"):
      store.add(make_random_tensors(_spec(), batch_size=4, seed=0),
                priority=-2.0)

  def test_missing_key_and_empty_raise(self):
    store = ReplayStore(_spec(), capacity=8)
    with pytest.raises(KeyError):
      store.add({"image": np.zeros((2, 16, 16, 3), np.uint8)})
    with pytest.raises(ValueError, match="empty"):
      store.sample(2)

  def test_one_shard_uniform_bitwise_matches_legacy(self):
    """The adapter's compatibility contract: same seeded rng call,
    same physical layout, same rows — across interleaved adds and
    wraparound."""
    legacy = _LegacyReplayBuffer(_spec(), capacity=48, seed=7)
    store = ReplayStore(_spec(), capacity=48, num_shards=1, seed=7)
    for seed in range(4):
      batch = make_random_tensors(_spec(), batch_size=20, seed=seed)
      legacy.add(batch)
      store.add(batch)
      a = legacy.sample(16).to_flat_dict()
      b = store.sample(16).to_flat_dict()
      assert set(a) == set(b)
      for key in a:
        np.testing.assert_array_equal(a[key], b[key], err_msg=key)

  def test_fifo_returns_oldest_first(self):
    store = ReplayStore(_spec(), capacity=64, num_shards=2,
                        sampling="fifo")
    flat0 = make_random_tensors(_spec(), batch_size=8, seed=0)
    flat1 = make_random_tensors(_spec(), batch_size=8, seed=1)
    store.add(flat0)  # shard 0, add_seq 0..7
    store.add(flat1)  # shard 1, add_seq 8..15
    batch = store.sample(8).to_flat_dict()
    np.testing.assert_array_equal(batch["image"],
                                  flat0.to_flat_dict()["image"])
    batch2 = store.sample(8).to_flat_dict()
    np.testing.assert_array_equal(batch2["image"],
                                  flat1.to_flat_dict()["image"])
    # Exhausted: wraps back to the oldest live rows.
    batch3 = store.sample(8).to_flat_dict()
    np.testing.assert_array_equal(batch3["image"],
                                  flat0.to_flat_dict()["image"])

  def test_prioritized_sampling_biases_toward_priority(self):
    store = ReplayStore(_spec(), capacity=128, num_shards=2, seed=0,
                        sampling="prioritized")
    store.add(make_random_tensors(_spec(), batch_size=32, seed=0),
              priority=1.0)   # shard 0
    store.add(make_random_tensors(_spec(), batch_size=32, seed=1),
              priority=9.0)   # shard 1
    _, _, row_ids = store.sample_with_ages(512)
    high = np.mean(row_ids >= store.shard_capacity)
    assert 0.8 < high < 1.0  # ~0.9 expected

  def test_spill_preserves_evicted_rows(self, tmp_path):
    spill = str(tmp_path / "spill")
    store = ReplayStore(_spec(), capacity=8, num_shards=1, seed=0,
                        spill_dir=spill)
    first = make_random_tensors(_spec(), batch_size=8, seed=0)
    store.add(first)
    store.add(make_random_tensors(_spec(), batch_size=4, seed=1))
    assert store.evictions_total == 4
    assert store.spilled_total == 4
    files = sorted(os.listdir(spill))
    assert len(files) == 1 and files[0].endswith(".npz")
    arrays = np.load(os.path.join(spill, files[0]))
    # The evicted rows are the OLDEST four (ring head).
    np.testing.assert_array_equal(
        arrays["image"], first.to_flat_dict()["image"][:4])

  def test_staleness_ages_from_learner_step(self):
    store = ReplayStore(_spec(), capacity=64, num_shards=1)
    store.set_learner_step(10)
    store.add(make_random_tensors(_spec(), batch_size=8, seed=0))
    store.set_learner_step(25)
    _, ages, _ = store.sample_with_ages(8)
    np.testing.assert_array_equal(ages, np.full(8, 15))

  def test_multi_shard_sampling_deterministic_given_seed(self):
    def draw(seed):
      store = ReplayStore(_spec(), capacity=64, num_shards=4,
                          seed=seed)
      for i in range(4):
        store.add(make_random_tensors(_spec(), batch_size=16, seed=i))
      _, _, ids = store.sample_with_ages(32)
      return ids

    np.testing.assert_array_equal(draw(3), draw(3))
    assert not np.array_equal(draw(3), draw(4))


class TestReplayWriteService:

  def test_put_flush_commits(self):
    store = ReplayStore(_spec(), capacity=128)
    service = ReplayWriteService(store, queue_batches=4)
    assert service.put(make_random_tensors(_spec(), batch_size=16,
                                           seed=0))
    assert service.flush(timeout_secs=10)
    assert len(store) == 16
    assert service.committed_transitions == 16
    service.close()

  def test_overflow_drop_counts_and_never_blocks(self, monkeypatch):
    """Queue overflow under the drop policy: producers get False +
    counters, and the LEARNER's sample path stays un-blocked even
    with the writer wedged mid-add."""
    store = ReplayStore(_spec(), capacity=128)
    store.add(make_random_tensors(_spec(), batch_size=32, seed=9))
    gate = threading.Event()
    real_add = store.add

    def wedged_add(*args, **kwargs):
      gate.wait(timeout=30)
      return real_add(*args, **kwargs)

    monkeypatch.setattr(store, "add", wedged_add)
    service = ReplayWriteService(store, queue_batches=2,
                                 overflow="drop")
    batch = make_random_tensors(_spec(), batch_size=8, seed=0)
    # Fill: one batch wedges in the writer, two sit in the queue.
    results = [service.put(batch) for _ in range(5)]
    t0 = time.perf_counter()
    dropped = [service.put(batch) for _ in range(3)]
    put_secs = time.perf_counter() - t0
    assert put_secs < 1.0  # drop policy never blocks a producer
    assert not all(dropped)
    assert service.dropped_batches >= 3
    assert service.dropped_transitions >= 24
    # The learner samples the store directly: wedged ingestion is
    # invisible to it.
    t0 = time.perf_counter()
    store.sample(16)
    assert time.perf_counter() - t0 < 1.0
    gate.set()
    service.close()
    assert results[0] is True

  def test_overflow_block_applies_backpressure(self, monkeypatch):
    store = ReplayStore(_spec(), capacity=128)
    gate = threading.Event()
    real_add = store.add
    monkeypatch.setattr(
        store, "add",
        lambda *a, **k: (gate.wait(timeout=30), real_add(*a, **k)))
    service = ReplayWriteService(store, queue_batches=1,
                                 overflow="block",
                                 block_timeout_secs=0.2)
    batch = make_random_tensors(_spec(), batch_size=4, seed=0)
    service.put(batch)  # will wedge in the writer
    deadline = time.monotonic() + 10
    while service.queue_depth > 0 and time.monotonic() < deadline:
      time.sleep(0.005)  # writer must HOLD batch 1 before we fill
    service.put(batch)  # fills the queue
    t0 = time.perf_counter()
    accepted = service.put(batch)  # must WAIT ~block_timeout, then drop
    waited = time.perf_counter() - t0
    assert not accepted
    assert waited >= 0.15
    gate.set()
    service.close()

  def test_session_commits_whole_episodes(self):
    store = ReplayStore(_spec(), capacity=128)
    service = ReplayWriteService(store, queue_batches=4)
    session = service.session("actor-a")
    session.begin_episode()
    session.append(make_random_tensors(_spec(), batch_size=4, seed=0))
    session.append(make_random_tensors(_spec(), batch_size=4, seed=1))
    assert len(store) == 0  # staged only — nothing visible mid-episode
    assert session.end_episode()
    service.flush()
    assert len(store) == 8
    service.close()

  def test_crash_mid_episode_leaves_store_consistent(self):
    store = ReplayStore(_spec(), capacity=128)
    service = ReplayWriteService(store, queue_batches=4)
    session = service.session("actor-a")
    session.add(make_random_tensors(_spec(), batch_size=8, seed=0))
    session.begin_episode()
    session.append(make_random_tensors(_spec(), batch_size=4, seed=1))
    # Crash: the episode never ends; abort is what the actor's crash
    # handler (and a restart's session reopen) performs.
    session.abort()
    service.flush()
    assert len(store) == 8  # the committed episode only, no partial
    assert service.aborted_episodes == 1
    service.close()

  def test_restart_resumes_ingestion(self):
    store = ReplayStore(_spec(), capacity=128)
    service = ReplayWriteService(store, queue_batches=4)
    dead = service.session("actor-a")
    dead.begin_episode()
    dead.append(make_random_tensors(_spec(), batch_size=4, seed=0))
    # Restart: reopening the id aborts the dead incarnation's staged
    # rows and returns a working session.
    fresh = service.session("actor-a")
    assert service.restarts == 1
    assert service.aborted_episodes == 1
    with pytest.raises(RuntimeError, match="closed"):
      dead.append(make_random_tensors(_spec(), batch_size=4, seed=1))
    assert fresh.add(make_random_tensors(_spec(), batch_size=8, seed=2))
    service.flush()
    assert len(store) == 8
    service.close()


class TestActorOnThePlane:
  """GraspActor wired through the ingestion service."""

  def test_actor_crash_discards_partial_and_restart_resumes(self):
    learner = _tiny_learner()
    store = ReplayStore(learner.transition_specification(),
                        capacity=2048)
    service = ReplayWriteService(store, queue_batches=8)
    env = ToyGraspEnv(image_size=16, action_dim=2, seed=3)
    actor = GraspActor(learner, service, env=env, batch_episodes=16,
                       epsilon=0.0, seed=3)
    # Sabotage the env after one good batch: the collection thread
    # must crash cleanly (partial episode discarded, flag set).
    actor.collect_once()
    service.flush()
    committed = len(store)
    assert committed == 16

    real_grade = env.grade
    calls = {"n": 0}

    def failing_grade(actions, positions):
      calls["n"] += 1
      raise RuntimeError("sim died mid-episode")

    env.grade = failing_grade
    actor.start()
    deadline = time.monotonic() + 30
    while not actor.crashed and time.monotonic() < deadline:
      time.sleep(0.01)
    assert actor.crashed
    assert calls["n"] >= 1
    service.flush()
    assert len(store) == committed  # nothing partial landed

    # Restart: same actor object, env healed; ingestion resumes.
    env.grade = real_grade
    actor.start()
    assert not actor.crashed
    deadline = time.monotonic() + 30
    while len(store) <= committed and time.monotonic() < deadline:
      time.sleep(0.01)
    actor.stop()
    service.flush()
    assert len(store) > committed
    assert service.restarts == 1
    service.close()


class TestReplayBatchSampler:

  def test_stream_feeds_prefetcher_wire_spec(self):
    from tensor2robot_tpu.data.prefetch import (
        ShardedPrefetcher,
        make_data_sharding,
    )
    from tensor2robot_tpu.parallel import create_mesh

    store = ReplayStore(_spec(), capacity=128, num_shards=2)
    store.add(make_random_tensors(_spec(), batch_size=64, seed=0))
    stream, sampler = make_stream(store, batch_size=16)
    mesh = create_mesh({"data": 1}, devices=jax.devices()[:1])
    prefetcher = ShardedPrefetcher(stream, make_data_sharding(mesh),
                                   buffer_size=1)
    try:
      placed = next(prefetcher)
      flat = placed.to_flat_dict()
      assert flat["image"].shape == (16, 16, 16, 3)
      assert sampler.staleness_snapshot()["batches"] >= 1
    finally:
      prefetcher.close()

  def test_staleness_histogram_buckets(self):
    store = ReplayStore(_spec(), capacity=64)
    store.set_learner_step(0)
    store.add(make_random_tensors(_spec(), batch_size=32, seed=0))
    sampler = ReplayBatchSampler(store, batch_size=8)
    store.set_learner_step(3)   # ages 3 → "<=4" bucket
    sampler.sample()
    store.set_learner_step(100)  # ages 100 → "<=128" bucket
    sampler.sample()
    snap = sampler.staleness_snapshot()
    assert snap["histogram"]["<=4"] == 8
    assert snap["histogram"]["<=128"] == 8
    assert snap["rows"] == 16
    assert snap["max_age_steps"] == 100
    labels = list(snap["histogram"])
    assert labels[0] == "<=0"
    assert labels[-1] == f">{STALENESS_BUCKETS[-1]}"

  def test_schedule_digest_reproducible(self):
    def digest(seed):
      store = ReplayStore(_spec(), capacity=128, num_shards=2,
                          seed=seed)
      store.add(make_random_tensors(_spec(), batch_size=64, seed=0))
      sampler = ReplayBatchSampler(store, batch_size=16,
                                   record_schedule=True)
      for _ in range(4):
        sampler.sample()
      return sampler.schedule_digest()

    assert digest(5) == digest(5)
    assert digest(5) != digest(6)

  def test_metrics_scalars_shape(self):
    store = ReplayStore(_spec(), capacity=64)
    store.add(make_random_tensors(_spec(), batch_size=32, seed=0))
    sampler = ReplayBatchSampler(store, batch_size=8)
    sampler.sample()
    scalars = sampler.metrics_scalars()
    assert set(scalars) == {
        "replay_staleness_mean_steps", "replay_staleness_max_steps",
        "replay_staleness_batch_p95_steps", "replay_sampled_batches"}


class TestAdapterAndTrainerEquivalence:
  """The acceptance pin: QT-Opt through the new data plane reproduces
  the legacy in-process ReplayBuffer path exactly."""

  def _train(self, replay, tmp_path, name):
    learner = _tiny_learner()
    return train_qtopt(
        learner=learner,
        model_dir=str(tmp_path / name),
        replay_buffer=replay,
        max_train_steps=6,
        batch_size=8,
        save_checkpoints_steps=6,
        log_every_steps=3,
    )

  def test_offline_training_bitwise_matches_legacy(self, tmp_path):
    batch = make_random_tensors(_spec(), batch_size=64, seed=3)
    legacy = _LegacyReplayBuffer(_spec(), capacity=64, seed=7)
    legacy.add(batch)
    plane = ReplayBuffer(_spec(), capacity=64, seed=7)
    plane.add(batch)
    base = self._train(legacy, tmp_path, "legacy")
    new = self._train(plane, tmp_path, "plane")
    for (path, a), b in zip(
        jax.tree_util.tree_leaves_with_path(
            jax.device_get(base.train_state.params)),
        jax.tree_util.tree_leaves(
            jax.device_get(new.train_state.params))):
      np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                    err_msg=str(path))

  def test_single_actor_online_ingestion_matches_direct_add(self):
    """Single-actor online collection through the SERVICE (sessioned,
    queued, writer-thread committed) must land the store in exactly
    the state the legacy direct-add path lands it in — same rows, same
    slots, same sample schedule — so a training run over either is
    identical (plane→params equality is pinned by the offline bitwise
    test above; this one pins the ingestion leg without paying two
    more XLA compiles)."""
    def run(via_service):
      learner = _tiny_learner()
      spec = learner.transition_specification()
      buf = ReplayBuffer(spec, capacity=1024, seed=7)
      env = ToyGraspEnv(image_size=16, action_dim=2, seed=5)
      if via_service:
        service = ReplayWriteService(buf.store, queue_batches=8)
        sink = service
      else:
        service, sink = None, buf
      actor = GraspActor(learner, sink, env=env, batch_episodes=32,
                         epsilon=0.2, seed=5)
      for _ in range(4):
        actor.collect_once()
      if service is not None:
        assert service.flush(timeout_secs=30)
        service.close()
      return buf

    base = run(False)
    new = run(True)
    assert len(base) == len(new) == 128
    # Identically-seeded samplers over identically-ingested stores
    # must draw identical rows from identical slots.
    a = base.sample(64).to_flat_dict()
    b = new.sample(64).to_flat_dict()
    assert set(a) == set(b)
    for key in a:
      np.testing.assert_array_equal(a[key], b[key], err_msg=key)

  def test_adapter_keeps_legacy_surface(self):
    buf = ReplayBuffer(_spec(), capacity=32, seed=0)
    with pytest.raises(ValueError, match="empty replay buffer"):
      buf.sample(2)
    buf.add(make_random_tensors(_spec(), batch_size=8, seed=0))
    assert len(buf) == 8
    assert buf.capacity == 32
    assert buf.wait_until_size(8, timeout_secs=1)
    stream = buf.as_stream(4)
    batch = next(stream)
    assert batch.to_flat_dict()["image"].shape == (4, 16, 16, 3)
    assert "replay_fill" in buf.metrics_scalars()


class TestPrefetchDepth:

  def test_resolver_defaults_and_override(self):
    from tensor2robot_tpu.data.prefetch import prefetch_buffer_size

    assert prefetch_buffer_size(None, online=False) == 2
    assert prefetch_buffer_size(None, online=True) == 1
    assert prefetch_buffer_size(5, online=True) == 5
    with pytest.raises(ValueError):
      prefetch_buffer_size(0)

  def test_resolver_gin_configurable(self):
    from tensor2robot_tpu import config as gin
    from tensor2robot_tpu.data.prefetch import prefetch_buffer_size

    gin.bind_parameter("prefetch_buffer_size.online_default", 3)
    try:
      assert prefetch_buffer_size(None, online=True) == 3
    finally:
      gin.clear_config()
    # The binding train_qtopt's docstring advertises: it must apply
    # through the trainer's call shape (buffer_size NOT forwarded when
    # unset — a positional None would shadow the binding in ginlite).
    gin.bind_parameter("prefetch_buffer_size.buffer_size", 7)
    try:
      assert prefetch_buffer_size(online=True) == 7
    finally:
      gin.clear_config()

  def test_train_qtopt_online_uses_depth_1_and_logs_replay_metrics(
      self, tmp_path, monkeypatch):
    """An online run (a hook drives collection) must construct the
    prefetcher at depth 1 — the K>1 sampling-lead default — and the
    train log must carry the data-plane scalars next to the loop's
    own (one shared train run keeps the suite's compile bill down)."""
    from tensor2robot_tpu.data import prefetch as prefetch_lib
    from tensor2robot_tpu.hooks import Hook

    seen = {}
    real = prefetch_lib.ShardedPrefetcher

    class Recording(real):

      def __init__(self, iterator, sharding, buffer_size=2):
        seen["buffer_size"] = buffer_size
        super().__init__(iterator, sharding, buffer_size=buffer_size)

    monkeypatch.setattr(prefetch_lib, "ShardedPrefetcher", Recording)

    class OnlineMarker(Hook):
      drives_online_collection = True

    learner = _tiny_learner()
    buf = ReplayBuffer(learner.transition_specification(),
                       capacity=64, seed=1)
    buf.add(make_random_tensors(
        learner.transition_specification(), batch_size=64, seed=0))
    train_qtopt(
        learner=learner,
        model_dir=str(tmp_path / "depth"),
        replay_buffer=buf,
        max_train_steps=4,
        batch_size=8,
        save_checkpoints_steps=4,
        log_every_steps=2,
        hooks=[OnlineMarker()],
    )
    assert seen["buffer_size"] == 1
    records = read_records(os.path.join(str(tmp_path / "depth"),
                                         "metrics_train.jsonl"))
    last = records[-1]
    assert "replay_fill" in last
    assert "replay_staleness_mean_steps" in last
    assert "replay_samples_per_sec" in last
    assert last["replay_fill"] == 1.0
    # Ages are non-negative and the sampler saw every consumed batch
    # (positivity under a controlled clock is pinned in
    # TestReplayBatchSampler — here prefetch timing makes the exact
    # mean scheduling-dependent).
    assert last["replay_staleness_mean_steps"] >= 0
    assert last["replay_sampled_batches"] >= 4


class TestReplayBenchSmoke:
  """`bench.py --replay --dry-run` must keep working on CPU — the
  tier-1 guard on the replay bench path itself."""

  def test_dry_run_smoke(self):
    import importlib
    import sys as _sys

    _sys.path.insert(0, ".")
    try:
      bench = importlib.import_module("bench")
    finally:
      _sys.path.pop(0)
    detail = bench.bench_replay_plane(dry_run=True)
    shard_axis = detail["sample_throughput_vs_shards"]
    assert "1" in shard_axis and "2" in shard_axis
    assert shard_axis["1"]["uncontended_sample_batches_per_sec"] > 0
    assert shard_axis["1"][
        "loaded_goodput_transitions_speedup_vs_1_shard"] == 1.0
    assert shard_axis["2"]["loaded_sample_batches_per_sec"] > 0
    assert "host_memcpy_scaling" in detail
    actors = detail["throughput_vs_actors"]
    assert actors["1"]["committed_transitions_per_sec"] > 0
    hist = detail["online_staleness"]["histogram"]
    assert sum(hist.values()) > 0
