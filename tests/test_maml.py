"""Tests for the MAML meta-learning wrapper (SURVEY.md §4.5 parity).

The sine-regression sanity task is the canonical MAML check: a model
meta-trained over random-phase sinusoids must do better AFTER inner
adaptation than before.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensor2robot_tpu import train_eval
from tensor2robot_tpu.data.abstract_input_generator import Mode
from tensor2robot_tpu.data.random_input_generator import (
    RandomInputGenerator,
)
from tensor2robot_tpu.meta_learning import (
    MAMLModel,
    MetaExampleInputGenerator,
    make_meta_batch,
)
from tensor2robot_tpu.models import optimizers as opt_lib
from tensor2robot_tpu.specs import (
    ExtendedTensorSpec,
    TensorSpecStruct,
)
from tensor2robot_tpu.utils.mocks import MockT2RModel
from tensor2robot_tpu.telemetry.records import read_records


def _meta_model(**kwargs):
  kwargs.setdefault("num_condition_samples_per_task", 4)
  kwargs.setdefault("num_inference_samples_per_task", 4)
  return MAMLModel(base_model=MockT2RModel(), **kwargs)


class TestSpecsAndData:

  def test_nested_specs(self):
    model = _meta_model()
    feat = model.get_feature_specification(Mode.TRAIN).to_flat_dict()
    assert set(feat) == {"condition/x", "inference/x"}
    assert feat["condition/x"].shape == (4, 3)
    labels = model.get_label_specification(Mode.TRAIN).to_flat_dict()
    assert labels["inference/target"].shape == (4, 2)

  def test_make_meta_batch(self):
    feats = TensorSpecStruct.from_flat_dict(
        {"x": np.arange(16, dtype=np.float32).reshape(16, 1)})
    labels = TensorSpecStruct.from_flat_dict(
        {"y": np.arange(16, dtype=np.float32).reshape(16, 1)})
    mf, ml = make_meta_batch(feats, labels, num_condition=3,
                             num_inference=1)
    flat = mf.to_flat_dict()
    assert flat["condition/x"].shape == (4, 3, 1)
    assert flat["inference/x"].shape == (4, 1, 1)
    # Task 0 gets samples 0..3; inference sample is #3.
    assert float(flat["inference/x"][0, 0, 0]) == 3.0

  def test_indivisible_batch_raises(self):
    feats = TensorSpecStruct.from_flat_dict(
        {"x": np.zeros((10, 1), np.float32)})
    with pytest.raises(ValueError, match="divisible"):
      make_meta_batch(feats, None, 4, 4)

  def test_wire_names_are_distinct_per_split(self):
    # condition/x and inference/x must be different tf.Example keys or
    # the feature map silently collides.
    from tensor2robot_tpu.data import tfexample
    model = _meta_model()
    fmap = tfexample.build_feature_map(
        model.get_feature_specification(Mode.TRAIN))
    assert len(fmap) == 2

  def test_predict_spec_carries_optional_demo_labels(self):
    model = _meta_model()
    flat = model.get_feature_specification(Mode.PREDICT).to_flat_dict()
    assert "condition_labels/target" in flat
    assert flat["condition_labels/target"].is_optional
    # Train spec stays demo-free.
    train_flat = model.get_feature_specification(
        Mode.TRAIN).to_flat_dict()
    assert "condition_labels/target" not in train_flat

  def test_base_preprocessor_lifts_over_splits(self):
    # A base model with a real wire!=model preprocessor: the meta wire
    # spec must reflect the BASE IN spec, and preprocess must produce
    # model-side shapes per split.
    from functools import partial
    from tensor2robot_tpu.preprocessors.image_preprocessor import (
        ImagePreprocessor,
    )
    from tensor2robot_tpu.research.pose_env import (
        PoseEnvRegressionModel,
    )

    base = PoseEnvRegressionModel(
        image_size=16, filters=(8,), embedding_size=16,
        hidden_sizes=(8,), use_batch_norm=False,
        preprocessor_cls=partial(ImagePreprocessor, src_height=20,
                                 src_width=20, distort=False))
    model = MAMLModel(base_model=base,
                      num_condition_samples_per_task=2,
                      num_inference_samples_per_task=3)
    wire = model.preprocessor.get_in_feature_specification(
        Mode.TRAIN).to_flat_dict()
    assert wire["condition/image"].shape == (2, 20, 20, 3)
    assert wire["inference/image"].shape == (3, 20, 20, 3)
    assert wire["condition/image"].dtype == np.uint8
    # The nested image spec must be raw on the wire (no jpeg format).
    assert wire["condition/image"].data_format is None

    from tensor2robot_tpu.specs import make_random_tensors
    feats = make_random_tensors(
        model.preprocessor.get_in_feature_specification(Mode.TRAIN),
        batch_size=4, seed=0)
    labels = make_random_tensors(
        model.preprocessor.get_in_label_specification(Mode.TRAIN),
        batch_size=4, seed=1)
    feats = jax.tree_util.tree_map(jnp.asarray, feats)
    labels = jax.tree_util.tree_map(jnp.asarray, labels)
    out_f, out_l = model.preprocessor.preprocess(
        feats, labels, Mode.TRAIN, jax.random.PRNGKey(0))
    flat = out_f.to_flat_dict()
    assert flat["condition/image"].shape == (4, 2, 16, 16, 3)
    assert flat["inference/image"].shape == (4, 3, 16, 16, 3)
    # And the full train step runs through the lifted preprocessor.
    state = model.create_train_state(jax.random.PRNGKey(0))
    state, metrics = jax.jit(model.train_step)(
        state, feats, labels, jax.random.PRNGKey(1))
    assert np.isfinite(float(metrics["loss"]))

  def test_eval_step_runs(self):
    model = _meta_model()
    state = model.create_train_state(jax.random.PRNGKey(0))
    gen = MetaExampleInputGenerator(RandomInputGenerator(), batch_size=8)
    gen.set_specification_from_model(model, Mode.EVAL)
    features, labels = next(iter(gen.create_dataset(Mode.EVAL)))
    metrics = jax.jit(model.eval_step)(state, features, labels)
    assert np.isfinite(float(metrics["loss"]))
    assert "post_adaptation_loss" in metrics

  def test_meta_generator_wraps_flat_generator(self):
    model = _meta_model()
    gen = MetaExampleInputGenerator(
        RandomInputGenerator(), num_condition_samples_per_task=4,
        num_inference_samples_per_task=4, batch_size=8)
    gen.set_specification_from_model(model, Mode.TRAIN)
    features, labels = next(iter(gen.create_dataset(Mode.TRAIN)))
    assert features.to_flat_dict()["condition/x"].shape == (8, 4, 3)
    assert labels.to_flat_dict()["inference/target"].shape == (8, 4, 2)


class TestMAMLTraining:

  def test_train_step_runs_and_reports_adaptation(self):
    model = _meta_model(num_inner_steps=2, inner_lr=0.05,
                        report_pre_adaptation_loss=True)
    state = model.create_train_state(jax.random.PRNGKey(0))
    gen = MetaExampleInputGenerator(
        RandomInputGenerator(), batch_size=8,
        num_condition_samples_per_task=4,
        num_inference_samples_per_task=4)
    gen.set_specification_from_model(model, Mode.TRAIN)
    features, labels = next(iter(gen.create_dataset(Mode.TRAIN)))
    state, metrics = jax.jit(model.train_step)(
        state, features, labels, jax.random.PRNGKey(1))
    assert np.isfinite(float(metrics["loss"]))
    assert "pre_adaptation_loss" in metrics
    assert "post_adaptation_loss" in metrics

  def test_first_order_mode(self):
    model = _meta_model(first_order=True)
    state = model.create_train_state(jax.random.PRNGKey(0))
    gen = MetaExampleInputGenerator(RandomInputGenerator(), batch_size=8)
    gen.set_specification_from_model(model, Mode.TRAIN)
    features, labels = next(iter(gen.create_dataset(Mode.TRAIN)))
    _, metrics = jax.jit(model.train_step)(
        state, features, labels, jax.random.PRNGKey(1))
    assert np.isfinite(float(metrics["loss"]))

  def test_learnable_inner_lr_param_exists_and_trains(self):
    model = _meta_model(learn_inner_lr=True)
    state = model.create_train_state(jax.random.PRNGKey(0))
    assert "inner_lr_log" in state.params
    gen = MetaExampleInputGenerator(RandomInputGenerator(), batch_size=8)
    gen.set_specification_from_model(model, Mode.TRAIN)
    features, labels = next(iter(gen.create_dataset(Mode.TRAIN)))
    before = np.asarray(state.params["inner_lr_log"]).copy()
    new_state, _ = jax.jit(model.train_step)(
        state, features, labels, jax.random.PRNGKey(1))
    after = np.asarray(new_state.params["inner_lr_log"])
    # The learnable rate must actually receive outer gradients.
    assert not np.allclose(after, before)

  @pytest.mark.slow
  def test_maml_beats_pre_adaptation_on_sine_tasks(self):
    """The canonical sanity check on random-phase sine regression."""

    class SineModel(MockT2RModel):

      def get_feature_specification(self, mode):
        st = TensorSpecStruct()
        st.x = ExtendedTensorSpec(shape=(1,), dtype=np.float32,
                                  name="x")
        return st

      def get_label_specification(self, mode):
        st = TensorSpecStruct()
        st.target = ExtendedTensorSpec(shape=(1,), dtype=np.float32,
                                       name="target")
        return st

    model = MAMLModel(
        base_model=SineModel(output_size=1, hidden_sizes=(32, 32)),
        num_inner_steps=3, inner_lr=0.1,
        num_condition_samples_per_task=8,
        num_inference_samples_per_task=8,
        report_pre_adaptation_loss=True,
        create_optimizer_fn=lambda: opt_lib.create_optimizer(
            optimizer_name="adam", learning_rate=1e-3),
    )
    state = model.create_train_state(jax.random.PRNGKey(0))
    train_step = jax.jit(model.train_step)

    rng = np.random.default_rng(0)

    def sample_meta_batch(num_tasks=16, n=16):
      phases = rng.uniform(0, np.pi, (num_tasks, 1, 1))
      amps = rng.uniform(0.5, 2.0, (num_tasks, 1, 1))
      x = rng.uniform(-np.pi, np.pi, (num_tasks, n, 1))
      y = (amps * np.sin(x + phases)).astype(np.float32)
      feats = TensorSpecStruct.from_flat_dict({
          "condition/x": x[:, :8].astype(np.float32),
          "inference/x": x[:, 8:].astype(np.float32)})
      labels = TensorSpecStruct.from_flat_dict({
          "condition/target": y[:, :8], "inference/target": y[:, 8:]})
      return feats, labels

    metrics = None
    for i in range(150):
      feats, labels = sample_meta_batch()
      state, metrics = train_step(state, feats, labels,
                                  jax.random.PRNGKey(i))
    pre = float(metrics["pre_adaptation_loss"])
    post = float(metrics["post_adaptation_loss"])
    # Adaptation must help substantially once meta-trained.
    assert post < pre * 0.75, (pre, post)


@pytest.mark.slow
class TestPoseEnvMAML:

  def test_pose_maml_end_to_end(self, tmp_path):
    from tensor2robot_tpu.research.pose_env.pose_env_maml_models import (
        PoseEnvRegressionModelMAML,
    )

    model = PoseEnvRegressionModelMAML(
        image_size=32, filters=(8,), embedding_size=16,
        hidden_sizes=(16,), num_condition_samples_per_task=2,
        num_inference_samples_per_task=2)
    gen = MetaExampleInputGenerator(
        RandomInputGenerator(), batch_size=8,
        num_condition_samples_per_task=2,
        num_inference_samples_per_task=2)
    train_eval.train_eval_model(
        model=model,
        model_dir=str(tmp_path / "pose_maml"),
        input_generator_train=gen,
        max_train_steps=2,
        batch_size=8,
        log_every_steps=1,
    )
    path = os.path.join(str(tmp_path / "pose_maml"),
                        "metrics_train.jsonl")
    record = read_records(path)[-1]
    assert "post_adaptation_loss" in record
