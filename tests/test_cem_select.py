"""Fused CEM select kernel: interpret-mode parity vs the lax oracle.

The kernel's compiled path is exercised on real TPU hardware (bench
--mfu / --verify); here the pallas interpreter verifies the math —
running-top-k exactness against `cem_select_lax` (which shares the
f32 numerics policy), lax.top_k tie semantics, odd shapes where the
population does not divide the sample block, and block-size
independence.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tensor2robot_tpu.ops import cem_select_lax, fused_cem_select


def _inputs(b=4, p=64, c=32, a=4, seed=0, dtype=jnp.float32):
  rng = np.random.default_rng(seed)
  pooled = jnp.asarray(rng.standard_normal((p, b, c)) * 0.3, dtype)
  samples = jnp.asarray(rng.standard_normal((b, p, a)), jnp.float32)
  dense = tuple(
      (jnp.asarray(rng.standard_normal(s) * 0.3, dtype),
       jnp.asarray(rng.standard_normal(s[1]) * 0.3, dtype))
      for s in ((c, 16), (16, 1)))
  return pooled, samples, dense


def _assert_matches(got, want, atol=1e-5):
  for g, w, name in zip(got, want, ("mean", "std", "best_action",
                                    "best_score")):
    np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                               atol=atol, rtol=1e-5, err_msg=name)


class TestFusedCEMSelect:

  @pytest.mark.parametrize("sigmoid", [False, True])
  def test_matches_lax_reference(self, sigmoid):
    pooled, samples, dense = _inputs()
    want = cem_select_lax(pooled, samples, dense, num_elites=6,
                          sigmoid=sigmoid)
    got = fused_cem_select(pooled, samples, dense, num_elites=6,
                           sigmoid=sigmoid, interpret=True)
    _assert_matches(got, want)

  @pytest.mark.parametrize("p,block_p", [(48, 32), (7, 8), (65, 64),
                                         (33, 16)])
  def test_odd_population_vs_block(self, p, block_p):
    """P not a multiple of the sample block: the tail block is masked,
    never selected, and parity holds exactly."""
    pooled, samples, dense = _inputs(p=p, seed=p)
    want = cem_select_lax(pooled, samples, dense, num_elites=5)
    got = fused_cem_select(pooled, samples, dense, num_elites=5,
                           block_p=block_p, interpret=True)
    _assert_matches(got, want)

  def test_elite_ties_match_top_k_order(self):
    """Duplicate scores: selection must break ties toward the lower
    sample index, exactly like lax.top_k — including ties that
    straddle a running-merge block boundary."""
    b, p, c, a = 2, 32, 8, 3
    rng = np.random.default_rng(3)
    # Whole population scores tie in pairs: rows 2k and 2k+1 share
    # identical pooled features (identical scores), and the pairs
    # straddle the block_p=8 boundaries at rows 7/8, 15/16, 23/24.
    base = rng.standard_normal((p // 2, b, c)).astype(np.float32)
    pooled = jnp.asarray(np.repeat(base, 2, axis=0))
    samples = jnp.asarray(rng.standard_normal((b, p, a)), jnp.float32)
    dense = ((jnp.asarray(rng.standard_normal((c, 1)) * 0.5,
                          jnp.float32),
              jnp.zeros((1,), jnp.float32)),)
    want = cem_select_lax(pooled, samples, dense, num_elites=6)
    for block_p in (8, 16, 32):
      got = fused_cem_select(pooled, samples, dense, num_elites=6,
                             block_p=block_p, interpret=True)
      _assert_matches(got, want)

  def test_block_size_independence(self):
    pooled, samples, dense = _inputs(b=6, p=40, seed=9)
    outs = [fused_cem_select(pooled, samples, dense, num_elites=4,
                             block_p=bp, block_b=bb, interpret=True)
            for bp, bb in ((40, 2), (16, 3), (8, 1))]
    for other in outs[1:]:
      _assert_matches(outs[0], other)

  def test_min_std_floor(self):
    """All elites identical → std collapses to the min_std floor."""
    b, p, c, a = 1, 8, 4, 2
    pooled = jnp.ones((p, b, c), jnp.float32)
    samples = jnp.ones((b, p, a), jnp.float32) * 0.5
    dense = ((jnp.ones((c, 1), jnp.float32),
              jnp.zeros((1,), jnp.float32)),)
    mean, std, best, _ = fused_cem_select(
        pooled, samples, dense, num_elites=3, min_std=0.07,
        interpret=True)
    np.testing.assert_allclose(np.asarray(std), 0.07, atol=1e-7)
    np.testing.assert_allclose(np.asarray(mean), 0.5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(best), 0.5, atol=1e-6)

  def test_bf16_operands_accumulate_f32(self):
    """bf16 pooled/params (the production dtype) stay within bf16
    tolerance of the f32 oracle — the f32-accumulation contract."""
    pooled, samples, dense = _inputs(dtype=jnp.bfloat16, seed=5)
    want = cem_select_lax(pooled, samples, dense, num_elites=6)
    got = fused_cem_select(pooled, samples, dense, num_elites=6,
                           interpret=True)
    # Selection may only diverge on genuine bf16 score ties; the
    # statistics must agree to bf16 resolution.
    _assert_matches(got, want, atol=2e-2)

  def test_guards(self):
    pooled, samples, dense = _inputs(p=4)
    with pytest.raises(ValueError, match="num_elites"):
      fused_cem_select(pooled, samples, dense, num_elites=5,
                       interpret=True)
    with pytest.raises(ValueError, match="width 1"):
      bad = ((jnp.ones((32, 2), jnp.float32),
              jnp.zeros((2,), jnp.float32)),)
      fused_cem_select(pooled, samples, bad, num_elites=2,
                       interpret=True)


class TestCEMMaximizeFusedPath:
  """cem_maximize(select_fn=...) must reproduce the default score_fn
  path exactly when the select_fn implements the same contract."""

  def test_select_fn_equals_default_path(self):
    from tensor2robot_tpu.research.qtopt import cem

    b, p, a, c = 3, 16, 2, 8
    rng = np.random.default_rng(11)
    w = jnp.asarray(rng.standard_normal((a, 1)), jnp.float32)

    def score_fn(actions):  # [B, P, A] -> [B, P]
      return (actions @ w)[..., 0] - jnp.sum(actions ** 2, -1)

    def select_fn(actions, min_std):
      scores = score_fn(actions)
      es, ei = jax.lax.top_k(scores, 3)
      elites = jnp.take_along_axis(actions, ei[..., None], axis=1)
      return (jnp.mean(elites, axis=1),
              jnp.maximum(jnp.std(elites, axis=1), min_std),
              elites[:, 0], es[:, 0])

    key = jax.random.PRNGKey(0)
    kwargs = dict(batch_size=b, action_dim=a, iterations=3,
                  population=p, num_elites=3)
    base = cem.cem_maximize(score_fn, key, **kwargs)
    fused = cem.cem_maximize(None, key, select_fn=select_fn, **kwargs)
    for x, y in zip(base, fused):
      np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
