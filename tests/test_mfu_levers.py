"""The MFU levers: int8 CEM tower parity, sharded weight update pins,
remat-policy exactness, and the train_qtopt wiring.

Gates (ISSUE 7): the int8 tower must pass END-METRIC parity against
bf16 (action agreement / value regret, not just tensor closeness); the
sharded optimizer step must be BITWISE equal to the replicated one on
a 1-device mesh (the constraint-only contract) and numerically equal
across an 8-device mesh; remat recompute is exact arithmetic and must
be bitwise.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tensor2robot_tpu import specs
from tensor2robot_tpu.research.qtopt import GraspingQModel, QTOptLearner


def _learner(cem_inference="bf16", cem_select="lax", dtype=jnp.float32,
             **model_kwargs):
  kwargs = dict(image_size=16, torso_filters=(8, 8),
                head_filters=(8, 8), dense_sizes=(16,), action_dim=3,
                device_dtype=dtype)
  kwargs.update(model_kwargs)
  model = GraspingQModel(**kwargs)
  return QTOptLearner(model, cem_population=16, cem_iterations=2,
                      cem_elites=4, cem_inference=cem_inference,
                      cem_select=cem_select)


def _batch(learner, batch_size=8, seed=0):
  tr = specs.make_random_tensors(learner.transition_specification(),
                                 batch_size=batch_size, seed=seed)
  return jax.tree_util.tree_map(jnp.asarray, tr)


class TestInt8TowerParity:
  """int8 vs bf16 CEM tower: end-metric parity, not bit equality."""

  def _pair(self):
    base = _learner()
    i8 = _learner(cem_inference="int8")
    state = base.create_state(jax.random.PRNGKey(0), batch_size=2)
    tr = _batch(base)
    i8.calibrate(state, tr)
    return base, i8, state, tr

    # (scores are f32 models here so the only divergence IS the int8
    # quantization — the property under test)

  def test_score_parity(self):
    """Quantized population scores track the exact ones."""
    base, i8, state, tr = self._pair()
    flat = {k: v for k, v in tr.to_flat_dict().items()
            if not k.startswith("next_") and k not in ("reward",
                                                       "done")}
    feats = specs.TensorSpecStruct.from_flat_dict(flat)
    variables = {"params": state.train_state.params,
                 "batch_stats": state.train_state.batch_stats}
    actions = jnp.asarray(
        np.random.default_rng(3).uniform(-1, 1, (8, 16, 3)),
        jnp.float32)
    exact = jax.jit(base._cem_fns(variables, feats)[0])(actions)
    quant = jax.jit(i8._cem_fns(variables, feats)[0])(actions)
    err = np.max(np.abs(np.asarray(exact) - np.asarray(quant)))
    spread = float(np.ptp(np.asarray(exact))) + 1e-6
    assert err / spread < 0.05, (err, spread)

  def test_action_value_regret(self):
    """End-metric gate: actions the int8 CEM picks must be (near-)
    optimal under the EXACT scorer — value regret, robust to ties."""
    base, i8, state, tr = self._pair()
    obs = specs.make_random_tensors(base.observation_specification(),
                                    batch_size=8, seed=1)
    obs = jax.tree_util.tree_map(jnp.asarray, obs)
    rng = jax.random.PRNGKey(7)
    a_exact = np.asarray(base.build_policy()(state, obs, rng))
    a_quant = np.asarray(i8.build_policy()(state, obs, rng))

    variables = {"params": state.train_state.params,
                 "batch_stats": state.train_state.batch_stats}
    score_fn = base._cem_fns(variables, obs)[0]
    q_exact = np.asarray(score_fn(jnp.asarray(a_exact[:, None])))[:, 0]
    q_quant = np.asarray(score_fn(jnp.asarray(a_quant[:, None])))[:, 0]
    regret = q_exact - q_quant  # >0 where int8 picked a worse action
    spread = float(np.ptp(q_exact)) + 1e-6
    assert float(np.max(regret)) / spread < 0.05, (regret, spread)

  def test_bellman_target_parity(self):
    """The learner-level end metric: CEM Bellman targets agree."""
    base, i8, state, tr = self._pair()
    _, m_exact = jax.jit(base.train_step)(state, tr,
                                          jax.random.PRNGKey(1))
    _, m_quant = jax.jit(i8.train_step)(state, tr,
                                        jax.random.PRNGKey(1))
    np.testing.assert_allclose(float(m_quant["q_next_mean"]),
                               float(m_exact["q_next_mean"]),
                               atol=5e-3)
    np.testing.assert_allclose(float(m_quant["target_mean"]),
                               float(m_exact["target_mean"]),
                               atol=5e-3)

  def test_needs_calibration_contract(self):
    i8 = _learner(cem_inference="int8")
    state = i8.create_state(jax.random.PRNGKey(0), batch_size=2)
    assert i8.needs_calibration
    with pytest.raises(RuntimeError, match="calibrate"):
      jax.jit(i8.train_step)(state, _batch(i8), jax.random.PRNGKey(1))
    i8.ensure_calibrated(state.train_state)
    assert not i8.needs_calibration
    jax.jit(i8.train_step)(state, _batch(i8), jax.random.PRNGKey(1))

  def test_fused_select_matches_lax_select_end_to_end(self):
    """cem_select='fused' (the Pallas kernel through the select seam)
    reproduces the default path's training metrics on an f32 model."""
    base = _learner()
    fused = _learner(cem_select="fused")
    state = base.create_state(jax.random.PRNGKey(0), batch_size=2)
    tr = _batch(base)
    _, m0 = jax.jit(base.train_step)(state, tr, jax.random.PRNGKey(1))
    _, m1 = jax.jit(fused.train_step)(state, tr, jax.random.PRNGKey(1))
    np.testing.assert_allclose(float(m1["q_next_mean"]),
                               float(m0["q_next_mean"]), atol=1e-5)
    np.testing.assert_allclose(float(m1["loss"]), float(m0["loss"]),
                               atol=1e-5)


class TestShardedWeightUpdate:

  def _jit_step(self, learner, mesh, sharded):
    from tensor2robot_tpu.models import optimizers as opt_lib
    from tensor2robot_tpu.parallel import (
        batch_sharding,
        replicated,
        train_state_update_sharding,
    )
    if sharded:
      learner.model.wrap_optimizer(
          lambda tx: opt_lib.shard_weight_update(tx, mesh))
    state = learner.create_state(jax.random.PRNGKey(0), batch_size=2)
    repl = replicated(mesh)
    state_sharding = (train_state_update_sharding(mesh, state)
                      if sharded else repl)
    state = jax.device_put(state, state_sharding)
    step = jax.jit(learner.train_step,
                   in_shardings=(state_sharding,
                                 batch_sharding(mesh), repl),
                   out_shardings=(state_sharding, repl))
    return step, state

  def test_one_device_mesh_bitwise(self):
    """On a 1-device mesh every sharding constraint is a no-op: the
    sharded step must be BITWISE identical to the replicated one."""
    from tensor2robot_tpu.parallel import create_mesh
    mesh = create_mesh({"data": 1}, devices=jax.devices()[:1])
    tr = _batch(_learner())
    rng = jax.random.PRNGKey(2)

    results = []
    for sharded in (False, True):
      learner = _learner(dense_sizes=(128,))
      step, state = self._jit_step(learner, mesh, sharded)
      new_state, metrics = step(state, tr, rng)
      results.append((jax.device_get(new_state), metrics))
    (s0, m0), (s1, m1) = results
    jax.tree_util.tree_map(np.testing.assert_array_equal,
                           s0.train_state.params,
                           s1.train_state.params)
    jax.tree_util.tree_map(np.testing.assert_array_equal,
                           s0.train_state.opt_state,
                           s1.train_state.opt_state)
    np.testing.assert_array_equal(np.asarray(m0["loss"]),
                                  np.asarray(m1["loss"]))

  def test_eight_device_mesh_shards_moments_and_matches(self):
    """On the 8-device mesh the adam moments actually live sharded on
    the data axis and the step matches the replicated math."""
    from jax.sharding import PartitionSpec as P
    from tensor2robot_tpu.parallel import DATA_AXIS, create_mesh
    mesh = create_mesh({DATA_AXIS: 8})
    tr = _batch(_learner())
    rng = jax.random.PRNGKey(2)

    learner_r = _learner(dense_sizes=(128,))
    step_r, state_r = self._jit_step(learner_r, mesh, sharded=False)
    ref, m_ref = step_r(state_r, tr, rng)

    learner_s = _learner(dense_sizes=(128,))
    step_s, state_s = self._jit_step(learner_s, mesh, sharded=True)
    got, m_got = step_s(state_s, tr, rng)

    # The q-head hidden kernel [16, 128] optimizer moments shard 128
    # over the 8 data replicas (ZeRO contract, not just a no-op).
    mu = None
    for leaf in jax.tree_util.tree_leaves_with_path(
        got.train_state.opt_state):
      path, val = leaf
      if "dense_0" in jax.tree_util.keystr(path) and val.ndim == 2 \
          and val.shape[-1] == 128:
        mu = val
        break
    assert mu is not None
    assert mu.sharding.spec in (P(None, DATA_AXIS), P(None, "data")), \
        mu.sharding
    np.testing.assert_allclose(np.asarray(m_got["loss"]),
                               np.asarray(m_ref["loss"]), rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-5),
        jax.device_get(got.train_state.params),
        jax.device_get(ref.train_state.params))

  def test_train_qtopt_shard_weight_update_smoke(self, tmp_path):
    """The gin-level wiring: a short train_qtopt run with the flag on
    completes and checkpoints on the default (1-device-per-axis) mesh."""
    from tensor2robot_tpu.research.qtopt.train_qtopt import train_qtopt
    learner = _learner()
    state = train_qtopt(
        learner=learner, model_dir=str(tmp_path), max_train_steps=2,
        batch_size=8, save_checkpoints_steps=2, log_every_steps=2,
        prefill_random=True, seed=0, shard_weight_update=True)
    assert int(np.asarray(jax.device_get(state.step))) == 2


class TestRematPolicy:

  @pytest.mark.parametrize("policy", ["full", "dots", "dots_no_batch"])
  def test_bitwise_equal_to_no_remat(self, policy):
    """Remat recompute is exact arithmetic: every policy must produce
    bitwise-identical params/metrics, only the memory schedule moves."""
    tr = _batch(_learner())
    rng = jax.random.PRNGKey(3)
    outs = []
    for p in (None, policy):
      learner = _learner(remat_policy=p)
      state = learner.create_state(jax.random.PRNGKey(0), batch_size=2)
      new_state, metrics = jax.jit(learner.train_step)(state, tr, rng)
      outs.append((jax.device_get(new_state.train_state.params),
                   jax.device_get(metrics)))
    (p0, m0), (p1, m1) = outs
    jax.tree_util.tree_map(np.testing.assert_array_equal, p0, p1)
    np.testing.assert_array_equal(np.asarray(m0["loss"]),
                                  np.asarray(m1["loss"]))

  def test_unknown_policy_raises(self):
    learner = _learner(remat_policy="everything")
    state = learner.create_state(jax.random.PRNGKey(0), batch_size=2)
    with pytest.raises(ValueError, match="remat_policy"):
      jax.jit(learner.train_step)(state, _batch(learner),
                                  jax.random.PRNGKey(1))
