"""Tests for network building blocks (vision, resnet, mdn, snail)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensor2robot_tpu import layers


RNG = jax.random.PRNGKey(0)


def _init_apply(module, *args, train=False, **kwargs):
  variables = module.init({"params": RNG, "dropout": RNG}, *args,
                          train=train, **kwargs) if _wants_train(module) \
      else module.init({"params": RNG}, *args, **kwargs)
  if _wants_train(module):
    out = module.apply(variables, *args, train=train, **kwargs,
                       mutable=["batch_stats"] if train else False)
    return out[0] if train else out
  return module.apply(variables, *args, **kwargs)


def _wants_train(module):
  import inspect
  return "train" in inspect.signature(module.__call__).parameters


class TestVisionLayers:

  def test_conv_tower_shapes(self):
    images = jnp.zeros((2, 64, 64, 3))
    out = _init_apply(layers.ConvTower(filters=(8, 16, 32)), images)
    assert out.shape == (2, 8, 8, 32)

  def test_conv_tower_no_bn(self):
    images = jnp.zeros((2, 32, 32, 3))
    out = _init_apply(layers.ConvTower(filters=(8,), use_batch_norm=False),
                      images)
    assert out.shape == (2, 16, 16, 8)

  def test_spatial_softmax_peak(self):
    # A delta at (row 2, col 5) in an 8x8 map -> expected coords near
    # the normalized grid position of that cell.
    fmap = np.full((1, 8, 8, 1), -1e9, np.float32)
    fmap[0, 2, 5, 0] = 1e9
    out = layers.spatial_softmax(jnp.asarray(fmap))
    x, y = float(out[0, 0]), float(out[0, 1])
    assert np.isclose(x, -1 + 2 * 5 / 7, atol=1e-3)
    assert np.isclose(y, -1 + 2 * 2 / 7, atol=1e-3)

  def test_spatial_softmax_module(self):
    fmap = jnp.ones((2, 4, 4, 6))
    out = _init_apply(layers.SpatialSoftmax(), fmap)
    assert out.shape == (2, 12)

  def test_film_identity_at_init(self):
    x = jax.random.normal(RNG, (2, 4, 4, 8))
    cond = jnp.zeros((2, 3))
    film = layers.FiLM()
    variables = film.init(RNG, x, cond)
    # Zero-init dense -> gamma=beta=0 -> identity.
    out = film.apply(variables, x, cond)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x), atol=1e-6)

  @pytest.mark.parametrize("pooling", ["spatial_softmax", "mean", "flatten"])
  def test_image_encoder(self, pooling):
    images = jnp.zeros((2, 32, 32, 3))
    enc = layers.ImageEncoder(filters=(8, 16), embedding_size=24,
                              pooling=pooling)
    out = _init_apply(enc, images)
    assert out.shape == (2, 24)
    assert out.dtype == jnp.float32

  def test_image_encoder_film(self):
    images = jnp.zeros((2, 32, 32, 3))
    cond = jnp.ones((2, 5))
    enc = layers.ImageEncoder(filters=(8,), embedding_size=16, film=True)
    variables = enc.init(RNG, images, conditioning=cond, train=False)
    out = enc.apply(variables, images, conditioning=cond, train=False)
    assert out.shape == (2, 16)


class TestResNet:

  def test_resnet18_features(self):
    images = jnp.zeros((2, 64, 64, 3))
    net = layers.resnet18(num_filters=8)
    out = _init_apply(net, images)
    assert out.shape == (2, 64)  # 8 * 2**3

  def test_resnet18_classes(self):
    images = jnp.zeros((2, 64, 64, 3))
    net = layers.resnet18(num_filters=8, num_classes=10)
    out = _init_apply(net, images)
    assert out.shape == (2, 10)

  def test_resnet50_bottleneck(self):
    images = jnp.zeros((1, 64, 64, 3))
    net = layers.ResNet(stage_sizes=(1, 1, 1, 1),
                        block_cls=layers.BottleneckBlock, num_filters=8)
    out = _init_apply(net, images)
    assert out.shape == (1, 8 * 2 ** 3 * 4)

  def test_film_resnet(self):
    images = jnp.zeros((2, 64, 64, 3))
    cond = jnp.ones((2, 7))
    net = layers.ResNet(stage_sizes=(1, 1), num_filters=8, use_film=True)
    variables = net.init(RNG, images, conditioning=cond, train=False)
    out = net.apply(variables, images, conditioning=cond, train=False)
    assert out.shape == (2, 16)

  def test_train_mode_updates_batch_stats(self):
    images = jax.random.normal(RNG, (2, 32, 32, 3))
    net = layers.resnet18(num_filters=8)
    variables = net.init(RNG, images, train=False)
    _, updates = net.apply(variables, images, train=True,
                           mutable=["batch_stats"])
    assert "batch_stats" in updates


class TestMDN:

  def _params(self, batch=4, k=3, d=2):
    head = layers.MDNHead(num_components=k, output_size=d)
    feats = jax.random.normal(RNG, (batch, 16))
    variables = head.init(RNG, feats)
    return head.apply(variables, feats)

  def test_head_shapes(self):
    params = self._params(batch=4, k=3, d=2)
    assert params.logits.shape == (4, 3)
    assert params.means.shape == (4, 3, 2)
    assert params.log_scales.shape == (4, 3, 2)

  def test_log_prob_matches_single_gaussian(self):
    # One component -> plain diagonal Gaussian log prob.
    logits = jnp.zeros((2, 1))
    means = jnp.zeros((2, 1, 3))
    log_scales = jnp.zeros((2, 1, 3))
    params = layers.MDNParams(logits, means, log_scales)
    targets = jnp.zeros((2, 3))
    lp = layers.mdn_log_prob(params, targets)
    expected = -0.5 * 3 * np.log(2 * np.pi)
    np.testing.assert_allclose(np.asarray(lp), expected, rtol=1e-5)

  def test_loss_decreases_toward_target(self):
    params = self._params()
    t_at_mean = layers.mdn_mode(params)
    t_far = t_at_mean + 100.0
    assert float(layers.mdn_loss(params, t_at_mean)) < float(
        layers.mdn_loss(params, t_far))

  def test_mode_mean_sample_shapes(self):
    params = self._params(batch=5, k=4, d=3)
    assert layers.mdn_mode(params).shape == (5, 3)
    assert layers.mdn_mean(params).shape == (5, 3)
    assert layers.mdn_sample(params, RNG).shape == (5, 3)

  def test_mixture_mean_weighted(self):
    logits = jnp.log(jnp.asarray([[0.25, 0.75]]))
    means = jnp.asarray([[[0.0], [4.0]]])
    params = layers.MDNParams(logits, means, jnp.zeros((1, 2, 1)))
    np.testing.assert_allclose(np.asarray(layers.mdn_mean(params)),
                               [[3.0]], rtol=1e-5)


class TestSNAIL:

  def test_causal_conv_shapes(self):
    x = jnp.zeros((2, 10, 4))
    conv = layers.CausalConv1D(8, dilation=2)
    variables = conv.init(RNG, x)
    assert conv.apply(variables, x).shape == (2, 10, 8)

  def test_causality(self):
    # Changing the future must not change the past output.
    x1 = jax.random.normal(RNG, (1, 8, 4))
    x2 = x1.at[0, 5:].set(99.0)
    snail = layers.SNAIL(seq_len=8, filters=4, key_size=8, value_size=4,
                         output_size=3)
    variables = snail.init(RNG, x1)
    o1 = snail.apply(variables, x1)
    o2 = snail.apply(variables, x2)
    np.testing.assert_allclose(np.asarray(o1[0, :5]),
                               np.asarray(o2[0, :5]), atol=1e-5)

  def test_tc_block_growth(self):
    x = jnp.zeros((2, 16, 4))
    tc = layers.TCBlock(seq_len=16, filters=8)
    variables = tc.init(RNG, x)
    out = tc.apply(variables, x)
    # ceil(log2(16)) = 4 dense blocks, each adds 8 channels.
    assert out.shape == (2, 16, 4 + 4 * 8)

  def test_snail_output(self):
    x = jnp.zeros((2, 6, 5))
    snail = layers.SNAIL(seq_len=6, filters=4, key_size=8, value_size=4,
                         output_size=7)
    variables = snail.init(RNG, x)
    assert snail.apply(variables, x).shape == (2, 6, 7)
