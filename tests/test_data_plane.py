"""Process-parallel host data plane: ring layout, worker lifecycle,
failure paths, and the num_workers∈{0,1} determinism contract.

The worker source classes live in `_plane_sources` (a minimal
numpy-only module) because they cross the spawn boundary by qualified
name and every import that module makes is paid per worker spawn.
"""

import os
import time

import numpy as np
import pytest

from _plane_sources import (
    CountSource,
    CrashSource,
    DieWhileSiblingsProduceSource,
    HardDeathSource,
    SilentExitSource,
    StallSource,
)
from tensor2robot_tpu.data.abstract_input_generator import Mode
from tensor2robot_tpu.data.plane import HostDataPlane
from tensor2robot_tpu.data.prefetch import (
    ShardedPrefetcher,
    make_data_sharding,
    stack_batches,
)
from tensor2robot_tpu.data.shm_ring import ShmRing, WireLayout
from tensor2robot_tpu.data.tfrecord_input_generator import (
    TFRecordEpisodeInputGenerator,
    TFRecordInputGenerator,
    _PlaneStream,
    write_episode_tfrecord,
    write_tfrecord,
)
from tensor2robot_tpu.specs import ExtendedTensorSpec, TensorSpecStruct

LAYOUT = WireLayout([("x", (4, 3), "float32"), ("y", (4,), "int64")])


def _wait_workers_dead(plane, timeout=10.0):
  deadline = time.monotonic() + timeout
  while plane.workers_alive() and time.monotonic() < deadline:
    time.sleep(0.05)
  return plane.workers_alive()


class TestWireLayout:

  def test_offsets_aligned_and_disjoint(self):
    layout = WireLayout([("a", (3,), "uint8"), ("b", (2, 2), "float32"),
                         ("c", (1,), "int64")])
    offsets = [layout.offsets[k] for k, _, _ in layout.fields]
    assert all(o % 64 == 0 for o in offsets)
    assert offsets == sorted(offsets)
    assert layout.slot_bytes % 64 == 0

  def test_duplicate_key_rejected(self):
    with pytest.raises(ValueError, match="Duplicate"):
      WireLayout([("a", (1,), "float32"), ("a", (2,), "float32")])

  def test_write_checks_shape_and_dtype(self):
    ring = ShmRing(LAYOUT, num_slots=1)
    try:
      with pytest.raises(ValueError, match="layout says"):
        ring.write(0, {"x": np.zeros((4, 3), np.float64),
                       "y": np.zeros((4,), np.int64)})
      with pytest.raises(ValueError, match="layout says"):
        ring.write(0, {"x": np.zeros((5, 3), np.float32),
                       "y": np.zeros((4,), np.int64)})
    finally:
      ring.close()


class TestShmRing:

  def test_roundtrip_and_zero_copy_views(self):
    ring = ShmRing(LAYOUT, num_slots=2)
    try:
      batch = {"x": np.arange(12, dtype=np.float32).reshape(4, 3),
               "y": np.arange(4, dtype=np.int64)}
      ring.write(0, batch)
      views = ring.views(0)
      np.testing.assert_array_equal(views["x"], batch["x"])
      np.testing.assert_array_equal(views["y"], batch["y"])
      # Views ALIAS the segment: a second write to the same slot is
      # visible through previously returned views (which is exactly
      # why the consumer must not hold them past slot recycling).
      ring.write(0, {"x": np.full((4, 3), 9, np.float32),
                     "y": np.full((4,), 9, np.int64)})
      assert float(views["x"][0, 0]) == 9.0
    finally:
      ring.close()


class TestHostDataPlane:

  def test_finite_stream_all_batches_then_stopiteration(self):
    plane = HostDataPlane(CountSource(10), LAYOUT, num_workers=2,
                          copy=True)
    try:
      got = sorted(int(b["x"][0, 0]) for b in plane)
      assert got == list(range(10))
      with pytest.raises(StopIteration):
        next(plane)
    finally:
      plane.close()

  def test_single_worker_preserves_order(self):
    plane = HostDataPlane(CountSource(6), LAYOUT, num_workers=1,
                          copy=False)
    try:
      assert [int(next(plane)["x"][0, 0]) for _ in range(6)] == \
          list(range(6))
    finally:
      plane.close()

  def test_worker_crash_mid_batch_reraises_and_latches(self):
    plane = HostDataPlane(CrashSource(), LAYOUT, num_workers=1,
                          copy=True)
    try:
      next(plane)  # the good batch
      with pytest.raises(RuntimeError, match="boom from worker 0"):
        next(plane)
      # Latched: every later pull re-raises instead of hanging.
      with pytest.raises(RuntimeError):
        next(plane)
    finally:
      plane.close()

  def test_worker_hard_death_detected(self):
    plane = HostDataPlane(HardDeathSource(), LAYOUT, num_workers=1,
                          copy=True)
    try:
      # os._exit(3) races the queue feeder thread: the good batch may
      # or may not have been flushed into the pipe before death, so
      # the exit-code detection may fire on the first or second pull —
      # either way it must fire, with the exit code named.
      with pytest.raises(RuntimeError, match="exit code 3"):
        next(plane)
        next(plane)
      # And latch: the stream is dead from here on, never hanging.
      with pytest.raises(RuntimeError):
        next(plane)
    finally:
      plane.close()

  def test_worker_silent_exit0_death_detected(self):
    # os._exit(0) mid-stream: no exception message, no done marker,
    # and a CLEAN exit code — the consumer must still latch a death
    # (after one confirmation poll window for the marker-flush race)
    # instead of waiting on the full queue forever.
    plane = HostDataPlane(SilentExitSource(), LAYOUT, num_workers=1,
                          copy=True)
    try:
      with pytest.raises(RuntimeError, match="without sending"):
        next(plane)
        next(plane)
      with pytest.raises(RuntimeError):  # and it latches
        next(plane)
    finally:
      plane.close()

  def test_worker_crash_detected_while_siblings_keep_queue_busy(self):
    # Worker 1 is hard-killed while worker 0 streams forever: the full
    # queue never goes empty, so detection must NOT depend on the
    # empty-window poll — a crashed worker means its file shard
    # silently stops being produced, which must surface as an error,
    # not as biased data.
    plane = HostDataPlane(DieWhileSiblingsProduceSource(), LAYOUT,
                          num_workers=2, copy=True)
    try:
      with pytest.raises(RuntimeError, match="exit code 5"):
        for _ in range(100):  # span the 0.5s poll gate, queue kept full
          next(plane)
          time.sleep(0.02)
      with pytest.raises(RuntimeError):  # and it latches
        next(plane)
    finally:
      plane.close()

  def test_close_while_workers_blocked_on_full_ring(self):
    # 1000 pending batches against a tiny ring: both workers are
    # parked waiting for free slots when close() lands.
    plane = HostDataPlane(CountSource(1000), LAYOUT, num_workers=2,
                          copy=True)
    next(plane)
    time.sleep(0.3)  # let workers fill the ring and block
    plane.close()
    assert plane.workers_alive() == 0
    with pytest.raises(StopIteration):
      next(plane)

  def test_close_is_idempotent(self):
    plane = HostDataPlane(CountSource(4), LAYOUT, num_workers=1,
                          copy=True)
    plane.close()
    plane.close()
    assert plane.workers_alive() == 0


def _write_image_dataset(tmp, num_files=4, per_file=48):
  spec = TensorSpecStruct()
  spec.image = ExtendedTensorSpec(shape=(16, 16, 3), dtype=np.uint8,
                                  name="image", data_format="jpeg")
  spec.action = ExtendedTensorSpec(shape=(4,), dtype=np.float32,
                                   name="action")
  rng = np.random.default_rng(0)
  for f in range(num_files):
    write_tfrecord(
        os.path.join(tmp, f"part-{f}.tfrecord"),
        [{"image": rng.integers(0, 255, (16, 16, 3)).astype(np.uint8),
          "action": rng.standard_normal(4).astype(np.float32)}
         for _ in range(per_file)],
        spec)
  return spec, os.path.join(tmp, "part-*.tfrecord")


def _collect(spec, pattern, num_workers, n, batch_size=16):
  gen = TFRecordInputGenerator(
      file_patterns=pattern, batch_size=batch_size,
      shuffle_buffer_size=64, seed=7, num_workers=num_workers)
  gen.set_specification(spec, None)
  stream = gen.create_dataset(Mode.TRAIN)
  try:
    out = []
    for _ in range(n):
      features, labels = next(stream)
      assert labels is None
      out.append({k: np.array(v)
                  for k, v in features.to_flat_dict().items()})
    return out
  finally:
    closer = getattr(stream, "close", None)
    if closer is not None:
      closer()


class TestGeneratorThroughPlane:

  def test_num_workers_0_and_1_bitwise_identical(self, tmp_path):
    """THE determinism pin: the plane with one worker reproduces the
    in-process stream bit for bit under a fixed seed (same file
    order, same tf.data graph, same shuffle seeds — the ring is a
    pure transport)."""
    spec, pattern = _write_image_dataset(str(tmp_path))
    base = _collect(spec, pattern, num_workers=0, n=5)
    plane = _collect(spec, pattern, num_workers=1, n=5)
    assert len(base) == len(plane)
    for a, b in zip(base, plane):
      assert sorted(a) == sorted(b)
      for key in a:
        np.testing.assert_array_equal(a[key], b[key])

  @pytest.mark.slow
  def test_two_workers_stream_conforming_batches(self, tmp_path):
    spec, pattern = _write_image_dataset(str(tmp_path))
    batches = _collect(spec, pattern, num_workers=2, n=4)
    for batch in batches:
      assert batch["image"].shape == (16, 16, 16, 3)
      assert batch["image"].dtype == np.uint8
      assert batch["action"].shape == (16, 4)

  def test_prefetcher_close_does_not_leak_workers(self):
    """Abandoning the ShardedPrefetcher mid-stream must tear the
    whole chain down: prefetcher thread → plane stream → worker
    PROCESSES → shared segment. (Numpy-source plane: the TF pipeline
    adds nothing to the teardown path and costs a TF import per
    spawned worker.)"""
    import jax

    from tensor2robot_tpu.parallel import create_mesh

    plane = HostDataPlane(CountSource(10_000), LAYOUT, num_workers=2,
                          copy=True)
    stream = _PlaneStream(plane, lambda parsed: (parsed, None))
    mesh = create_mesh({"data": 1}, devices=jax.devices()[:1])
    prefetcher = ShardedPrefetcher(stream, make_data_sharding(mesh),
                                   buffer_size=2)
    next(prefetcher)  # the chain is live: worker → ring → device
    prefetcher.close()
    assert _wait_workers_dead(plane) == 0

  def test_prefetcher_close_unblocks_stalled_thread(self):
    """close() while the prefetch thread is BLOCKED inside the plane's
    __next__ (stalled worker — slow decode, loaded host) must still
    tear the chain down: closing the source cross-thread unblocks the
    thread, so neither it nor the worker processes leak."""
    import jax

    from tensor2robot_tpu.parallel import create_mesh

    plane = HostDataPlane(StallSource(n=1), LAYOUT, num_workers=1,
                          copy=True)
    stream = _PlaneStream(plane, lambda parsed: (parsed, None))
    mesh = create_mesh({"data": 1}, devices=jax.devices()[:1])
    prefetcher = ShardedPrefetcher(stream, make_data_sharding(mesh),
                                   buffer_size=1)
    next(prefetcher)  # batch 1 consumed; the thread now blocks on 2
    time.sleep(0.3)   # let it reach the blocking full-queue poll
    prefetcher.close(timeout_secs=0.5)
    assert _wait_workers_dead(plane) == 0
    prefetcher._thread.join(timeout=5.0)
    assert not prefetcher._thread.is_alive()

  def test_stack_batches_closes_inner_stream(self):
    plane = HostDataPlane(CountSource(1000), LAYOUT, num_workers=1,
                          copy=False)
    stream = _PlaneStream(plane, lambda parsed: (parsed, None))
    stream.require_copies()  # the stacking contract
    assert not stream.release_after_transfer
    stacked = stack_batches(stream, 2)
    features, _ = next(stacked)
    assert features.to_flat_dict()["x"].shape == (2, 4, 3)
    stacked.close()
    assert _wait_workers_dead(plane) == 0

  @pytest.mark.slow
  def test_episode_generator_through_plane(self, tmp_path):
    spec = TensorSpecStruct()
    spec.obs = ExtendedTensorSpec(shape=(3,), dtype=np.float32,
                                  name="obs", is_sequence=True)
    spec.task = ExtendedTensorSpec(shape=(2,), dtype=np.float32,
                                   name="task")
    rng = np.random.default_rng(1)
    path = os.path.join(str(tmp_path), "episodes.tfrecord")
    write_episode_tfrecord(
        path,
        [{"obs": rng.standard_normal((t, 3)).astype(np.float32),
          "task": rng.standard_normal(2).astype(np.float32)}
         for t in (3, 5, 4, 6, 2, 5, 4, 3)],
        spec)
    gen = TFRecordEpisodeInputGenerator(
        file_patterns=path, batch_size=4, sequence_length=5,
        shuffle_buffer_size=8, seed=3, num_workers=1)
    gen.set_specification(spec, None)
    stream = gen.create_dataset(Mode.TRAIN)
    try:
      features, _ = next(stream)
      flat = features.to_flat_dict()
      assert flat["obs"].shape == (4, 5, 3)
      assert flat["task"].shape == (4, 2)
      # True pre-pad lengths ride along for masking.
      assert flat["sequence_length"].shape == (4,)
      assert flat["sequence_length"].dtype == np.int32
      assert (flat["sequence_length"] >= 2).all()
      assert (flat["sequence_length"] <= 5).all()
    finally:
      stream.close()
