"""Tests for the ginlite config engine."""

import pytest

from tensor2robot_tpu import config as gin


@pytest.fixture(autouse=True)
def clean():
  gin.clear_config()
  yield
  gin.clear_config()


@gin.configurable
def make_widget(size=1, color="red", factory=None):
  return {"size": size, "color": color, "factory": factory}


@gin.configurable
def make_gadget(widget=None, scale=1.0):
  return {"widget": widget, "scale": scale}


@gin.configurable
class Engine:

  def __init__(self, power=10, name="eng"):
    self.power = power
    self.name = name


@gin.configurable
def needs_binding(value=gin.REQUIRED):
  return value


class TestBindings:

  def test_simple_binding(self):
    gin.parse_config("make_widget.size = 5")
    assert make_widget()["size"] == 5

  def test_explicit_arg_wins(self):
    gin.parse_config("make_widget.size = 5")
    assert make_widget(size=9)["size"] == 9

  def test_module_qualified(self):
    gin.parse_config("test_config.make_widget.color = 'blue'")
    assert make_widget()["color"] == "blue"

  def test_class_configurable(self):
    gin.parse_config("Engine.power = 99")
    e = Engine()
    assert e.power == 99 and e.name == "eng"
    assert isinstance(e, Engine)

  def test_required_unbound_raises(self):
    with pytest.raises(gin.GinError, match="needs_binding.value"):
      needs_binding()

  def test_required_bound(self):
    gin.parse_config("needs_binding.value = [1, 2]")
    assert needs_binding() == [1, 2]

  def test_unknown_param_raises(self):
    gin.parse_config("make_widget.nonexistent = 1")
    with pytest.raises(gin.GinError, match="nonexistent"):
      make_widget()

  def test_bind_and_query_parameter(self):
    gin.bind_parameter("make_widget.size", 7)
    assert gin.query_parameter("make_widget.size") == 7
    assert make_widget()["size"] == 7


class TestValues:

  def test_literals(self):
    for text, expected in [
        ("1", 1), ("1.5", 1.5), ("'abc'", "abc"), ("True", True),
        ("None", None), ("[1, 2]", [1, 2]), ("(1, 'a')", (1, "a")),
        ("{'k': 3}", {"k": 3}),
    ]:
      assert gin.parse_value(text) == expected

  def test_reference_injects_callable(self):
    gin.parse_config("""
      make_widget.size = 3
      make_gadget.widget = @make_widget
    """)
    out = make_gadget()
    assert callable(out["widget"])
    assert out["widget"]()["size"] == 3

  def test_evaluated_reference(self):
    gin.parse_config("""
      make_widget.size = 4
      make_gadget.widget = @make_widget()
    """)
    assert make_gadget()["widget"]["size"] == 4

  def test_reference_inside_list(self):
    gin.parse_config("make_gadget.widget = [@make_widget(), 7]")
    out = make_gadget()["widget"]
    assert out[1] == 7 and out[0]["size"] == 1

  def test_macro(self):
    gin.parse_config("""
      SIZE = 12
      make_widget.size = %SIZE
    """)
    assert make_widget()["size"] == 12

  def test_string_with_at_sign_not_a_ref(self):
    gin.parse_config("make_widget.color = 'user@host'")
    assert make_widget()["color"] == "user@host"

  def test_multiline_value(self):
    gin.parse_config("""
      make_widget.factory = [
          1,
          2,
          3,
      ]
    """)
    assert make_widget()["factory"] == [1, 2, 3]


class TestScopes:

  def test_scoped_binding(self):
    gin.parse_config("""
      make_widget.size = 1
      train/make_widget.size = 100
    """)
    assert make_widget()["size"] == 1
    with gin.config_scope("train"):
      assert make_widget()["size"] == 100

  def test_scoped_reference(self):
    gin.parse_config("""
      train/make_widget.size = 50
      make_gadget.widget = @train/make_widget()
    """)
    assert make_gadget()["widget"]["size"] == 50


class TestFilesAndDump:

  def test_parse_file_and_include(self, tmp_path):
    base = tmp_path / "base.gin"
    base.write_text("make_widget.size = 2\n")
    top = tmp_path / "top.gin"
    top.write_text(f"include '{base}'\nmake_widget.color = 'green'\n")
    gin.parse_config_files_and_bindings([str(top)],
                                        ["make_gadget.scale = 3.0"])
    assert make_widget() == {"size": 2, "color": "green", "factory": None}
    assert make_gadget()["scale"] == 3.0

  def test_config_str_roundtrip(self):
    gin.parse_config("""
      SIZE = 5
      make_widget.size = %SIZE
      train/make_widget.color = 'red'
    """)
    dumped = gin.config_str()
    gin.clear_config()
    gin.parse_config(dumped)
    assert make_widget()["size"] == 5

  def test_operative_config(self):
    gin.parse_config("make_widget.size = 8\nmake_widget.color = 'k'")
    make_widget()
    dump = gin.operative_config_str()
    assert "make_widget.size = 8" in dump


class TestReviewRegressions:
  """Pinned behaviors from code-review findings."""

  def test_unknown_configurable_binding_raises_at_parse(self):
    with pytest.raises(gin.GinError, match="No configurable matching"):
      gin.parse_config("fnn.x = 42")  # typo'd target

  def test_unknown_binding_skipped_with_skip_unknown(self):
    gin.parse_config("fnn.x = 42", skip_unknown=True)  # no raise

  def test_fully_qualified_binding_applies(self):
    gin.parse_config("tests.test_config.make_widget.size = 77")
    assert make_widget()["size"] == 77

  def test_compound_scope_beats_bare_scope(self):
    gin.parse_config("""
      a/b/make_widget.size = 1
      b/make_widget.size = 2
    """)
    with gin.config_scope("a"):
      with gin.config_scope("b"):
        assert make_widget()["size"] == 1  # most specific scope wins

  def test_external_configurable_does_not_mutate_original(self):
    class Plain:
      def __init__(self, x=1):
        self.x = x

    wrapped = gin.external_configurable(Plain, name="PlainThing")
    gin.bind_parameter("PlainThing.x", 9)
    assert Plain().x == 1       # original untouched
    assert wrapped().x == 9     # wrapper injects
    assert isinstance(wrapped(), Plain)

  def test_lazy_registration_in_process(self, tmp_path, monkeypatch):
    import sys

    (tmp_path / "lazy_reg_target_mod.py").write_text(
        "from tensor2robot_tpu import config as gin\n"
        "@gin.configurable\n"
        "def lazy_reg_fn(value=0):\n"
        "  return value\n")
    monkeypatch.syspath_prepend(str(tmp_path))
    gin.register_lazy_configurables("lazy_reg_target_mod",
                                    ("lazy_reg_fn",))
    assert "lazy_reg_target_mod" not in sys.modules
    gin.parse_config("lazy_reg_fn.value = 5")  # triggers the import
    assert sys.modules["lazy_reg_target_mod"].lazy_reg_fn() == 5

  def test_lazy_package_registers_data_configurables(self):
    """run_t2r_trainer regression: `tensor2robot_tpu.data` resolves its
    exports lazily (PEP 562 — worker spawns must not pay the jax
    import), but a config binding one of its configurables must still
    parse right after the bare package import. Subprocess: the trainer
    registration path with clean module state."""
    import subprocess
    import sys

    code = (
        "import importlib, sys\n"
        "importlib.import_module('tensor2robot_tpu.data')\n"
        "assert 'jax' not in sys.modules, 'package import dragged jax'\n"
        "from tensor2robot_tpu import config as gin\n"
        "gin.parse_config('RandomInputGenerator.batch_size = 4')\n"
        "assert gin.query_parameter(\n"
        "    'RandomInputGenerator.batch_size') == 4\n"
    )
    subprocess.run([sys.executable, "-c", code], check=True,
                   timeout=120)
