"""Tests for the QT-Opt family: CEM, Q-network, learner, replay buffer.

The reference shipped only the model + handoff (SURVEY.md §3); the
in-repo learner/replay system is new capability, tested here at the
unit level plus a learning sanity check on a synthetic bandit.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensor2robot_tpu.data.abstract_input_generator import Mode
from tensor2robot_tpu.research.qtopt import (
    GraspingQModel,
    QTOptLearner,
    ReplayBuffer,
    cem_maximize,
    train_qtopt,
)
from tensor2robot_tpu.specs import TensorSpecStruct, make_random_tensors
from tensor2robot_tpu.telemetry.records import read_records

RNG = jax.random.PRNGKey(0)


def _tiny_model(**kwargs):
  kwargs.setdefault("image_size", 16)
  kwargs.setdefault("torso_filters", (8,))
  kwargs.setdefault("head_filters", (8,))
  kwargs.setdefault("dense_sizes", (16,))
  kwargs.setdefault("action_dim", 2)
  return GraspingQModel(**kwargs)


class TestCEM:

  def test_finds_quadratic_maximum(self):
    # score(a) = -|a - target|^2, batch of 3 different targets.
    targets = jnp.asarray([[0.5, -0.3], [0.0, 0.8], [-0.6, -0.6]])

    def score_fn(actions):  # [B, P, A] -> [B, P]
      return -jnp.sum(
          jnp.square(actions - targets[:, None, :]), axis=-1)

    result = cem_maximize(score_fn, RNG, batch_size=3, action_dim=2,
                          iterations=5, population=128, num_elites=12)
    np.testing.assert_allclose(np.asarray(result.best_action),
                               np.asarray(targets), atol=0.08)

  def test_respects_bounds(self):
    def score_fn(actions):
      return jnp.sum(actions, axis=-1)  # pushes to the high corner

    result = cem_maximize(score_fn, RNG, batch_size=2, action_dim=3,
                          iterations=4, population=64, num_elites=8,
                          low=-0.5, high=0.5)
    assert float(jnp.max(jnp.abs(result.best_action))) <= 0.5 + 1e-6

  def test_best_score_monotone_in_iterations(self):
    def score_fn(actions):
      return -jnp.sum(jnp.square(actions - 0.3), axis=-1)

    r1 = cem_maximize(score_fn, RNG, 1, 2, iterations=1, population=32,
                      num_elites=4)
    r5 = cem_maximize(score_fn, RNG, 1, 2, iterations=5, population=32,
                      num_elites=4)
    assert float(r5.best_score[0]) >= float(r1.best_score[0])

  def test_jits_cleanly(self):
    def score_fn(actions):
      return -jnp.sum(jnp.square(actions), axis=-1)

    jitted = jax.jit(lambda rng: cem_maximize(
        score_fn, rng, batch_size=2, action_dim=2, iterations=2,
        population=16, num_elites=4))
    result = jitted(RNG)
    assert result.best_action.shape == (2, 2)


class TestGraspingQModel:

  def test_forward_shapes(self):
    model = _tiny_model()
    state = model.create_train_state(RNG)
    feats = make_random_tensors(
        model.get_feature_specification(Mode.PREDICT), batch_size=4,
        seed=0)
    feats = jax.tree_util.tree_map(jnp.asarray, feats)
    out = model.predict_step(state, feats)
    assert out["q_value"].shape == (4,)

  def test_supervised_train_step(self):
    model = _tiny_model()
    state = model.create_train_state(RNG)
    feats = make_random_tensors(
        model.get_feature_specification(Mode.TRAIN), batch_size=8,
        seed=0)
    labels = make_random_tensors(
        model.get_label_specification(Mode.TRAIN), batch_size=8, seed=1)
    state, metrics = jax.jit(model.train_step)(
        state, jax.tree_util.tree_map(jnp.asarray, feats),
        jax.tree_util.tree_map(jnp.asarray, labels), RNG)
    assert np.isfinite(float(metrics["loss"]))


class TestScorePopulation:
  """The linearity-split CEM scoring must match the tiled-head path."""

  @pytest.mark.parametrize("use_batch_norm", [True, False])
  def test_matches_tiled_head(self, use_batch_norm):
    from tensor2robot_tpu.research.qtopt import cem
    from tensor2robot_tpu.models.critic_model import Q_VALUE

    model = GraspingQModel(use_batch_norm=use_batch_norm)
    net = model.network
    feats = make_random_tensors(
        model.get_feature_specification(Mode.TRAIN), batch_size=3,
        seed=0)
    feats = jax.tree_util.tree_map(jnp.asarray, feats)
    variables = model.create_inference_state(
        RNG, batch_size=3).variables
    flat = dict(feats.to_flat_dict())
    image = flat.pop("image")
    flat.pop("action")
    actions = jax.random.uniform(jax.random.PRNGKey(1), (3, 5, 4),
                                 minval=-1.0, maxval=1.0)

    encoded = net.apply(variables, image, train=False, method="encode")
    q_pop = net.apply(variables, encoded, flat, actions,
                      method="score_population")
    tiled = cem.make_q_score_fn(
        net.apply, variables,
        TensorSpecStruct.from_flat_dict(
            {**flat, "image": image, "action": jnp.zeros((3, 4))}),
        q_key=Q_VALUE)
    q_ref = tiled(actions)
    # Exact up to bf16 reassociation of the linear split.
    np.testing.assert_allclose(np.asarray(q_pop), np.asarray(q_ref),
                               atol=5e-3)

  def test_learner_uses_population_path(self):
    """make_encoded_q_score_fn must pick score_population when present."""
    from tensor2robot_tpu.research.qtopt import cem
    from tensor2robot_tpu.models.critic_model import Q_VALUE

    model = GraspingQModel()
    feats = make_random_tensors(
        model.get_feature_specification(Mode.TRAIN), batch_size=2,
        seed=0)
    feats = jax.tree_util.tree_map(jnp.asarray, feats)
    variables = model.create_inference_state(
        RNG, batch_size=2).variables
    score_fn = cem.make_encoded_q_score_fn(
        model.network, variables, feats, q_key=Q_VALUE)
    assert score_fn.__name__ == "population_score_fn"
    scores = score_fn(jnp.zeros((2, 6, 4)))
    assert scores.shape == (2, 6)
    assert np.isfinite(np.asarray(scores)).all()


class TestReplayBuffer:

  def _spec(self):
    learner = QTOptLearner(_tiny_model())
    return learner.transition_specification()

  def test_add_sample_round_trip(self):
    buf = ReplayBuffer(self._spec(), capacity=64)
    batch = make_random_tensors(self._spec(), batch_size=32, seed=0)
    buf.add(batch)
    assert len(buf) == 32
    sample = buf.sample(16)
    flat = sample.to_flat_dict()
    assert flat["image"].shape == (16, 16, 16, 3)
    assert flat["image"].dtype == np.uint8  # stored in wire dtype
    assert set(flat) == set(batch.to_flat_dict())

  def test_ring_wraparound(self):
    buf = ReplayBuffer(self._spec(), capacity=16)
    for seed in range(3):
      buf.add(make_random_tensors(self._spec(), batch_size=10,
                                  seed=seed))
    assert len(buf) == 16

  def test_empty_raises(self):
    buf = ReplayBuffer(self._spec(), capacity=8)
    with pytest.raises(ValueError, match="empty"):
      buf.sample(2)

  def test_missing_key_raises(self):
    buf = ReplayBuffer(self._spec(), capacity=8)
    with pytest.raises(KeyError):
      buf.add(TensorSpecStruct.from_flat_dict(
          {"image": np.zeros((2, 16, 16, 3), np.uint8)}))


class TestQTOptLearner:

  def test_bellman_step_runs(self):
    model = _tiny_model()
    learner = QTOptLearner(model, cem_population=8, cem_iterations=1,
                           cem_elites=2)
    state = learner.create_state(RNG)
    batch = make_random_tensors(learner.transition_specification(),
                                batch_size=8, seed=0)
    batch = jax.tree_util.tree_map(jnp.asarray, batch)
    new_state, metrics = jax.jit(learner.train_step)(state, batch, RNG)
    assert np.isfinite(float(metrics["loss"]))
    assert 0.0 <= float(metrics["target_mean"]) <= 1.0
    # Target network moved toward the online net, but only by tau.
    leaf = jax.tree_util.tree_leaves(new_state.target_params)[0]
    assert np.isfinite(np.asarray(leaf)).all()

  def test_policy_returns_bounded_actions(self):
    model = _tiny_model()
    learner = QTOptLearner(model, cem_population=16, cem_iterations=2,
                           cem_elites=4, action_low=-1.0,
                           action_high=1.0)
    state = learner.create_state(RNG)
    policy = jax.jit(learner.build_policy())
    obs = make_random_tensors(
        TensorSpecStruct.from_flat_dict(
            {"image": model.get_feature_specification(
                Mode.PREDICT).to_flat_dict()["image"]}),
        batch_size=3, seed=0)
    obs = jax.tree_util.tree_map(jnp.asarray, obs)
    action = policy(state, obs, RNG)
    assert action.shape == (3, 2)
    assert float(jnp.max(jnp.abs(action))) <= 1.0 + 1e-6
    # Serving contexts hold only the critic TrainState (no target
    # net); the policy must accept it directly and act identically.
    action_ts = policy(state.train_state, obs, RNG)
    np.testing.assert_array_equal(np.asarray(action),
                                  np.asarray(action_ts))

  def test_learner_learns_synthetic_bandit(self):
    """Reward = 1 iff action ~ fixed target: Q must rank it higher."""
    model = _tiny_model(use_batch_norm=False)
    learner = QTOptLearner(model, gamma=0.0, cem_population=16,
                           cem_iterations=2, cem_elites=4)
    state = learner.create_state(RNG)
    step = jax.jit(learner.train_step, donate_argnums=0)

    rng = np.random.default_rng(0)
    target_action = np.array([0.4, -0.2], np.float32)
    spec = learner.transition_specification()

    def make_batch(n=64):
      batch = make_random_tensors(spec, batch_size=n,
                                  seed=int(rng.integers(1 << 30)))
      flat = batch.to_flat_dict()
      actions = rng.uniform(-1, 1, (n, 2)).astype(np.float32)
      dist = np.linalg.norm(actions - target_action, axis=-1)
      flat["action"] = actions
      flat["reward"] = (dist < 0.4).astype(np.float32)[:, None]
      flat["done"] = np.ones((n, 1), np.float32)  # bandit: one step
      return TensorSpecStruct.from_flat_dict(flat)

    for i in range(60):
      state, metrics = step(state, make_batch(),
                            jax.random.fold_in(RNG, i))

    # Evaluate: Q(good action) vs Q(bad action) on fresh states.
    feats = make_random_tensors(
        model.get_feature_specification(Mode.PREDICT), batch_size=16,
        seed=7)
    flat = feats.to_flat_dict()
    good = dict(flat, action=np.tile(target_action, (16, 1)))
    bad = dict(flat, action=np.tile(
        np.array([-0.8, 0.8], np.float32), (16, 1)))
    ts = state.train_state
    q_good = model.predict_step(
        ts, TensorSpecStruct.from_flat_dict(good))["q_value"]
    q_bad = model.predict_step(
        ts, TensorSpecStruct.from_flat_dict(bad))["q_value"]
    assert float(jnp.mean(q_good)) > float(jnp.mean(q_bad))


class TestToyGraspEnv:

  def test_render_and_grade(self):
    from tensor2robot_tpu.research.qtopt import ToyGraspEnv
    env = ToyGraspEnv(image_size=16, seed=0)
    obs, positions = env.reset_batch(8)
    assert obs["image"].shape == (8, 16, 16, 3)
    assert obs["image"].dtype == np.uint8
    # Grasping exactly at the object always succeeds; far away never.
    perfect = np.concatenate([positions, np.zeros((8, 0))], axis=1)
    assert env.grade(perfect, positions).mean() == 1.0
    assert env.grade(-perfect, positions).mean() < 1.0

  def test_transitions_match_learner_spec(self):
    from tensor2robot_tpu.research.qtopt import ToyGraspEnv
    model = _tiny_model()
    learner = QTOptLearner(model)
    env = ToyGraspEnv(image_size=16, action_dim=2, seed=0)
    transitions = env.sample_transitions(4)
    spec = learner.transition_specification().to_flat_dict()
    assert set(transitions) == set(spec)
    for key, spec_entry in spec.items():
      assert transitions[key].shape == (4,) + tuple(spec_entry.shape), key


class TestGraspSuccessEval:
  """Collect → fused Bellman training → CEM policy → success eval.

  The closed-loop QT-Opt proof the r2 verdict flagged as missing: the
  learned CEM policy must decisively beat the random baseline on the
  grasping bandit, and the success hook must log per checkpoint.
  """

  @pytest.mark.slow
  def test_policy_learns_to_grasp_and_hook_logs(self, tmp_path):
    from tensor2robot_tpu.hooks import QTOptSuccessEvalHook
    from tensor2robot_tpu.models import optimizers as opt_lib
    from tensor2robot_tpu.research.qtopt import (
        ReplayBuffer,
        ToyGraspEnv,
        evaluate_grasp_policy,
    )

    model = GraspingQModel(
        image_size=16, action_dim=2, torso_filters=(16, 32),
        head_filters=(32,), dense_sizes=(32, 32),
        create_optimizer_fn=lambda: opt_lib.create_optimizer(
            learning_rate=1e-3))
    learner = QTOptLearner(model, cem_population=8, cem_iterations=1,
                           cem_elites=2)
    env = ToyGraspEnv(image_size=16, action_dim=2, seed=0)
    replay = ReplayBuffer(learner.transition_specification(),
                          capacity=8192)
    replay.add(env.sample_transitions(8192))

    model_dir = str(tmp_path / "qtopt_grasp")
    hook = QTOptSuccessEvalHook(
        learner,
        eval_kwargs={"num_episodes": 128, "image_size": 16, "seed": 5,
                     "cem_population": 64, "cem_iterations": 3})
    state = train_qtopt(
        learner=learner,
        model_dir=model_dir,
        replay_buffer=replay,
        max_train_steps=400,
        batch_size=64,
        save_checkpoints_steps=400,
        log_every_steps=100,
        hooks=[hook],
    )

    metrics = evaluate_grasp_policy(
        learner, state, num_episodes=256, image_size=16, seed=7,
        cem_population=64, cem_iterations=3)
    # Random grasping succeeds ~10% of the time at this threshold; the
    # trained CEM policy must be decisively better.
    assert metrics["random_baseline_success_rate"] < 0.3
    assert metrics["success_rate"] > max(
        0.5, 2.5 * metrics["random_baseline_success_rate"]), metrics

    # The per-checkpoint protocol line landed next to the train metrics.
    path = os.path.join(model_dir, "metrics_success_eval.jsonl")
    records = read_records(path)
    assert records and "success_rate" in records[-1]
    assert records[-1]["step"] == 400


class TestOnlineActor:
  """The async actor/learner loop: on-policy collection → replay →
  Bellman training, with the policy-state handoff via the checkpoint
  hook (the in-process shape of the reference's actor fleet)."""

  def _tiny(self):
    from tensor2robot_tpu.models import optimizers as opt_lib

    model = GraspingQModel(
        image_size=16, action_dim=2, torso_filters=(16, 32),
        head_filters=(32,), dense_sizes=(32, 32),
        create_optimizer_fn=lambda: opt_lib.create_optimizer(
            learning_rate=1e-3))
    return QTOptLearner(model, cem_population=16, cem_iterations=2,
                        cem_elites=4)

  def test_bootstrap_then_on_policy_collection(self):
    from tensor2robot_tpu.research.qtopt import (
        GraspActor,
        ReplayBuffer,
        ToyGraspEnv,
    )

    learner = self._tiny()
    replay = ReplayBuffer(learner.transition_specification(),
                          capacity=2048)
    env = ToyGraspEnv(image_size=16, action_dim=2, seed=3)
    actor = GraspActor(learner, replay, env=env, batch_episodes=32,
                       epsilon=0.0, seed=3)
    # No state yet: pure random bootstrap.
    r_random = actor.collect_once()
    assert len(replay) == 32
    assert 0.0 <= r_random <= 1.0
    # With a state: the CEM policy acts (any state works mechanically).
    actor.update_state(learner.create_state(RNG))
    actor.collect_once()
    assert len(replay) == 64
    assert actor.episodes_collected == 64

  @pytest.mark.slow
  def test_online_loop_learns_from_its_own_data(self, tmp_path):
    """Replay starts EMPTY: the actor's random bootstrap fills it, the
    trainer learns, checkpoints refresh the acting policy, and the
    final policy must decisively beat random — the full online RL
    loop turning on self-collected data only."""
    from tensor2robot_tpu.research.qtopt import (
        ActorStateRefreshHook,
        GraspActor,
        ReplayBuffer,
        ToyGraspEnv,
        evaluate_grasp_policy,
    )

    learner = self._tiny()
    replay = ReplayBuffer(learner.transition_specification(),
                          capacity=8192)
    env = ToyGraspEnv(image_size=16, action_dim=2, seed=11)
    actor = GraspActor(learner, replay, env=env, batch_episodes=128,
                       epsilon=0.2, seed=11)
    actor.start()  # random bootstrap unblocks min_replay_size
    try:
      state = train_qtopt(
          learner=learner,
          model_dir=str(tmp_path / "online"),
          replay_buffer=replay,
          max_train_steps=500,
          batch_size=64,
          min_replay_size=512,
          save_checkpoints_steps=100,
          log_every_steps=250,
          hooks=[ActorStateRefreshHook(actor)],
      )
    finally:
      actor.stop()

    assert actor.episodes_collected >= 1024  # kept collecting
    metrics = evaluate_grasp_policy(
        learner, state, num_episodes=256, image_size=16, seed=7,
        cem_population=64, cem_iterations=3)
    assert metrics["success_rate"] > max(
        0.5, 2.5 * metrics["random_baseline_success_rate"]), metrics


class TestTrainQTOpt:

  def test_end_to_end_loop(self, tmp_path):
    model = _tiny_model()
    learner = QTOptLearner(model, cem_population=8, cem_iterations=1,
                           cem_elites=2)
    model_dir = str(tmp_path / "qtopt")
    state = train_qtopt(
        learner=learner,
        model_dir=model_dir,
        max_train_steps=4,
        batch_size=8,
        save_checkpoints_steps=4,
        log_every_steps=2,
        prefill_random=True,
    )
    assert int(np.asarray(jax.device_get(state.step))) == 4
    records = read_records(
        os.path.join(model_dir, "metrics_train.jsonl"))
    assert "grad_steps_per_sec" in records[-1]
    # Feed-boundness is a logged trainer signal, bounded like a
    # fraction.
    for record in records:
      assert 0.0 <= record["input_wait_fraction"] <= 1.0
    # Checkpoint resumes.
    state2 = train_qtopt(
        learner=learner,
        model_dir=model_dir,
        max_train_steps=4,
        batch_size=8,
        prefill_random=True,
    )
    assert int(np.asarray(jax.device_get(state2.step))) == 4

  def test_steps_per_dispatch_matches_per_step_training(self, tmp_path):
    """K-scanned dispatches (`iterations_per_loop` semantics) must be
    numerically identical to per-step dispatch: same replay stream
    (same buffer seed), same per-step PRNG folding, so the final
    params and step count agree exactly."""
    from tensor2robot_tpu.research.qtopt import ReplayBuffer
    from tensor2robot_tpu.specs import make_random_tensors

    def run(k, name):
      model = _tiny_model()
      learner = QTOptLearner(model, cem_population=8,
                             cem_iterations=1, cem_elites=2)
      replay = ReplayBuffer(learner.transition_specification(),
                            capacity=64, seed=7)
      replay.add(make_random_tensors(
          learner.transition_specification(), batch_size=64, seed=3))
      return train_qtopt(
          learner=learner,
          model_dir=str(tmp_path / name),
          replay_buffer=replay,
          max_train_steps=6,
          batch_size=8,
          save_checkpoints_steps=6,
          log_every_steps=3,
          steps_per_dispatch=k,
      )

    base = run(1, "k1")
    scanned = run(3, "k3")
    assert int(np.asarray(jax.device_get(scanned.step))) == 6
    for (path, a), b in zip(
        jax.tree_util.tree_leaves_with_path(
            jax.device_get(base.train_state.params)),
        jax.tree_util.tree_leaves(
            jax.device_get(scanned.train_state.params))):
      np.testing.assert_allclose(
          np.asarray(a), np.asarray(b), atol=1e-6, rtol=1e-6,
          err_msg=str(path))

  def test_steps_per_dispatch_rejects_misaligned_cadence(self,
                                                         tmp_path):
    model = _tiny_model()
    learner = QTOptLearner(model, cem_population=8, cem_iterations=1,
                           cem_elites=2)
    with pytest.raises(ValueError, match="multiple of"):
      train_qtopt(
          learner=learner,
          model_dir=str(tmp_path / "bad"),
          max_train_steps=10,
          batch_size=8,
          save_checkpoints_steps=5,
          log_every_steps=5,
          prefill_random=True,
          steps_per_dispatch=4,
      )
