"""Replicated serving tier tests (ISSUE 17): placement, dedup,
speculative CEM, router failover, the serving_replica_crash fault
class, and the multi-process front tier.

The pins that keep the tier honest:

  * rendezvous placement is BYTE-IDENTICAL across modules —
    `replay.sampler.rendezvous_choose` (the router's rule) vs
    `fleet.actor.home_shard` (the jax-free local copy actors use) —
    and a membership change remaps ONLY the lost replica's tenants
    (mirroring the replay-shard pin in test_fleet_cross_host.py);
  * a dedup hit is bitwise-equal to the uncached path, entries are
    version-keyed, and a publish invalidates them;
  * a speculative refinement NEVER crosses a param version swap —
    version read before dispatch, checked before insert, stamped at
    serve time;
  * the router fails over on replica death (TimeoutError/
    ConnectionError) but NEVER on RpcError (a healthy replica
    shedding by policy);
  * `serving_replica_crash` generates only when explicitly requested
    with `num_fronts`, and the default 7-class plan digest is
    untouched.
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from tensor2robot_tpu.fleet import faults
from tensor2robot_tpu.fleet import rpc as rpc_lib
from tensor2robot_tpu.fleet.actor import home_shard
from tensor2robot_tpu.replay.sampler import (
    rendezvous_choose,
    rendezvous_rank,
    rendezvous_spread,
    rendezvous_weight,
)
from tensor2robot_tpu.serving.dedup import (
    ObservationDedupCache,
    observation_key,
)
from tensor2robot_tpu.serving.router import (
    NoReplicasError,
    ServingRouter,
)
from tensor2robot_tpu.serving.speculative import SpeculativeCEM

KEYS = [f"tenant-{i}" for i in range(200)]


class TestRendezvousPlacement:

  def test_byte_parity_with_home_shard(self):
    # THE cross-module pin: the router's canonical rule and the
    # actors' jax-free local copy must agree on every key at every
    # fleet size, or tenants and episodes land on different owners.
    for n in range(1, 9):
      for key in KEYS:
        assert rendezvous_choose(key, range(n)) == home_shard(key, n)

  def test_weight_deterministic_and_bucket_sensitive(self):
    assert rendezvous_weight("k", 3) == rendezvous_weight("k", 3)
    weights = {rendezvous_weight("k", b) for b in range(16)}
    assert len(weights) == 16  # 64-bit digests: collisions ~ never

  def test_rank_is_a_permutation(self):
    buckets = [5, 2, 9, 0]
    rank = rendezvous_rank("some-key", buckets)
    assert sorted(rank) == sorted(buckets)
    assert rank[0] == rendezvous_choose("some-key", buckets)

  def test_membership_change_remaps_only_lost_bucket(self):
    # The HRW property the whole tier leans on: when replica `lost`
    # dies, every tenant homed elsewhere KEEPS its placement (and its
    # warm arena residency); only the dead replica's tenants move.
    buckets = list(range(5))
    before = {k: rendezvous_choose(k, buckets) for k in KEYS}
    for lost in buckets:
      survivors = [b for b in buckets if b != lost]
      moved = 0
      for key in KEYS:
        after = rendezvous_choose(key, survivors)
        if before[key] == lost:
          moved += 1
          assert after != lost
        else:
          assert after == before[key], (
              f"{key} moved {before[key]}→{after} though {lost} died")
      assert moved > 0  # the lost bucket owned SOMETHING

  def test_spread_properties(self):
    buckets = range(6)
    spread = rendezvous_spread("hot", buckets, k=3)
    assert len(spread) == 3
    assert len(set(spread)) == 3
    assert spread[0] == rendezvous_choose("hot", buckets)
    assert spread == rendezvous_rank("hot", buckets)[:3]
    # k beyond the membership truncates to the full ranking.
    assert rendezvous_spread("hot", buckets, k=99) == (
        rendezvous_rank("hot", buckets))

  def test_degenerate_inputs_raise(self):
    with pytest.raises(ValueError):
      rendezvous_choose("k", [])
    with pytest.raises(ValueError):
      rendezvous_spread("k", [1, 2], k=0)


class TestObservationDedupCache:

  def _obs(self, seed, dtype=np.float32):
    rng = np.random.default_rng(seed)
    return {"img": rng.random((4, 4)).astype(dtype),
            "pose": rng.random(3).astype(dtype)}

  def test_hit_is_bitwise_equal_to_uncached_path(self):
    # The engine is deterministic for identical input+params, so the
    # cache replays its EXACT output: same object, same bytes.
    calls = []

    def engine(obs):
      calls.append(1)
      return np.asarray([obs["pose"].sum()], np.float64)

    cache = ObservationDedupCache(capacity=8)
    obs = self._obs(0)
    key = cache.key(obs)
    uncached = engine(obs)
    cache.put(key, 0, uncached)
    hit = cache.get(key, 0)
    assert hit is uncached
    assert hit.tobytes() == engine(obs).tobytes()
    assert len(calls) == 2  # the hit itself never touched the engine

  def test_get_is_version_keyed(self):
    cache = ObservationDedupCache(capacity=8)
    cache.put("k", 3, "action-v3")
    assert cache.get("k", 3) == "action-v3"
    assert cache.get("k", 4) is None  # stale stamp = miss
    assert cache.stats()["misses"] == 1

  def test_invalidate_on_publish(self):
    cache = ObservationDedupCache(capacity=8)
    cache.put("old", 1, "a")
    cache.put("new", 2, "b")
    assert cache.invalidate(2) == 1  # only the v1 entry dropped
    assert cache.get("new", 2) == "b"
    assert cache.get("old", 1) is None
    assert cache.invalidate(None) == 1  # full clear
    assert cache.stats()["size"] == 0

  def test_lru_bound_and_eviction(self):
    cache = ObservationDedupCache(capacity=3)
    for i in range(5):
      cache.put(f"k{i}", 0, i)
    stats = cache.stats()
    assert stats["size"] == 3
    assert stats["evictions"] == 2
    assert cache.get("k0", 0) is None   # oldest evicted
    assert cache.get("k4", 0) == 4      # newest resident

  def test_quantization_absorbs_float_jitter(self):
    obs = self._obs(1)
    jittered = {k: v + 1e-4 for k, v in obs.items()}  # < half a step
    moved = {k: v + 0.5 for k, v in obs.items()}
    assert observation_key(obs) == observation_key(jittered)
    assert observation_key(obs) != observation_key(moved)

  def test_key_covers_names_dtypes_shapes(self):
    a = {"x": np.zeros(4, np.float32)}
    assert observation_key(a) != observation_key(
        {"y": np.zeros(4, np.float32)})
    assert observation_key(a) != observation_key(
        {"x": np.zeros(4, np.int32)})
    assert observation_key(a) != observation_key(
        {"x": np.zeros((2, 2), np.float32)})
    assert observation_key(a) == observation_key(dict(a))


class _Gate:
  """A full_predict fake whose dispatch blocks until released."""

  def __init__(self, result):
    self.release = threading.Event()
    self.dispatched = threading.Event()
    self.result = result

  def __call__(self, obs):
    self.dispatched.set()
    assert self.release.wait(10.0)
    return self.result


def _wait(predicate, timeout=10.0):
  deadline = time.monotonic() + timeout
  while time.monotonic() < deadline:
    if predicate():
      return True
    time.sleep(0.005)
  return False


class TestSpeculativeCEM:

  OBS = {"img": np.ones((2, 2), np.float32)}
  FAST = np.array([1.0])
  FULL = np.array([2.0])

  def test_fast_then_refined(self):
    spec = SpeculativeCEM(
        fast_predict=lambda obs: self.FAST,
        full_predict=lambda obs: self.FULL,
        version_fn=lambda: 0)
    try:
      first = spec.predict(self.OBS)
      assert first is self.FAST
      assert _wait(lambda: spec.stats()["refines"] >= 1)
      second = spec.predict(self.OBS)
      assert second is self.FULL
      stats = spec.stats()
      assert stats["fast_served"] == 1
      assert stats["refined_served"] == 1
    finally:
      spec.close()

  def test_refinement_never_crosses_version_swap(self):
    # THE pin: params swap while the full program runs — the refined
    # action is stamped with the dead version and must never serve.
    version = {"v": 0}
    gate = _Gate(self.FULL)
    spec = SpeculativeCEM(
        fast_predict=lambda obs: self.FAST,
        full_predict=gate,
        version_fn=lambda: version["v"])
    try:
      assert spec.predict(self.OBS) is self.FAST
      assert gate.dispatched.wait(10.0)  # refinement in flight
      version["v"] = 1                   # the hot-swap lands
      gate.release.set()
      assert _wait(lambda: spec.stats()["refine_discarded"] >= 1)
      # The repeat must take the fast path again — no stale serve.
      assert spec.predict(self.OBS) is self.FAST
      assert spec.stats()["refined_served"] == 0
      assert spec.stats()["refines"] == 0
    finally:
      gate.release.set()
      spec.close()

  def test_queued_refinement_discarded_on_version_swap(self):
    # A refinement still WAITING when the swap lands is skipped before
    # dispatch (its result could only be stale).
    version = {"v": 0}
    gate = _Gate(self.FULL)
    spec = SpeculativeCEM(
        fast_predict=lambda obs: self.FAST,
        full_predict=gate,
        version_fn=lambda: version["v"])
    try:
      spec.predict(self.OBS)              # occupies the worker
      assert gate.dispatched.wait(10.0)
      other = {"img": np.zeros((2, 2), np.float32)}
      spec.predict(other)                 # queued behind the gate
      version["v"] = 1
      gate.release.set()
      assert _wait(lambda: spec.stats()["refine_discarded"] >= 2)
      assert spec.stats()["refines"] == 0
    finally:
      gate.release.set()
      spec.close()

  def test_on_publish_clears_refined_cache(self):
    version = {"v": 0}
    spec = SpeculativeCEM(
        fast_predict=lambda obs: self.FAST,
        full_predict=lambda obs: self.FULL,
        version_fn=lambda: version["v"])
    try:
      spec.predict(self.OBS)
      assert _wait(lambda: spec.stats()["refines"] >= 1)
      assert spec.predict(self.OBS) is self.FULL
      version["v"] = 1
      spec.on_publish(1)
      assert spec.predict(self.OBS) is self.FAST
    finally:
      spec.close()

  def test_refine_overflow_drops_without_blocking(self):
    gate = _Gate(self.FULL)
    spec = SpeculativeCEM(
        fast_predict=lambda obs: self.FAST,
        full_predict=gate,
        version_fn=lambda: 0,
        refine_queue=1)
    try:
      for i in range(4):
        obs = {"img": np.full((2, 2), float(i), np.float32)}
        assert spec.predict(obs) is self.FAST  # hot path never waits
      assert spec.stats()["refine_dropped"] >= 1
    finally:
      gate.release.set()
      spec.close()


class _FakeFront:
  """A loopback RpcServer speaking the front's predict surface."""

  def __init__(self, index: int):
    self.index = index
    self.version = 0
    self.calls = 0
    self.reject = False
    self.server = rpc_lib.RpcServer(self._handle)
    self.address = self.server.address

  def _handle(self, method, payload, ctx):
    if method == "predict":
      self.calls += 1
      if self.reject:
        raise ValueError("admission shed")
      return {"action": np.array([float(self.index)]),
              "params_version": self.version,
              "front_index": self.index}
    if method == rpc_lib.DISCONNECT_METHOD:
      return None
    raise ValueError(f"unknown method {method}")

  def close(self):
    # Don't wait out the 5s join: a thread parked in accept()/recv()
    # on a closed fd never wakes in-process (production unblocks via
    # peer disconnect or process exit); the daemon threads are
    # harmless here and waiting 3x5s per test blows the tier-1
    # budget.
    self.server.close(timeout_secs=0.2)


@pytest.fixture()
def fronts():
  replicas = {i: _FakeFront(i) for i in range(3)}
  yield replicas
  for front in replicas.values():
    front.close()


class TestServingRouter:

  OBS = {"img": np.ones((2, 2), np.float32)}

  def _router(self, replicas, **kwargs):
    return ServingRouter(
        {i: f.address for i, f in replicas.items()}, **kwargs)

  def test_placement_is_the_hrw_ranking(self, fronts):
    with self._router(fronts) as router:
      for tenant in ("a", "b", "hot"):
        assert router.placement(tenant) == rendezvous_spread(
            tenant, range(3), k=3)

  def test_predict_routes_to_the_home_replica(self, fronts):
    with self._router(fronts) as router:
      for tenant in KEYS[:20]:
        home = rendezvous_choose(tenant, range(3))
        action = router.predict(tenant, self.OBS)
        assert action[0] == float(home)

  def test_rpc_error_never_fails_over(self, fronts):
    # A healthy replica shedding by policy (RequestRejected et al.)
    # surfaces to the caller; failing over would stampede the
    # survivors exactly when one replica asks for backpressure.
    with self._router(fronts) as router:
      tenant = next(t for t in KEYS
                    if rendezvous_choose(t, range(3)) == 1)
      fronts[1].reject = True
      calls_elsewhere = fronts[0].calls + fronts[2].calls
      with pytest.raises(rpc_lib.RpcError):
        router.predict(tenant, self.OBS)
      assert sorted(router.alive()) == [0, 1, 2]  # still healthy
      assert fronts[0].calls + fronts[2].calls == calls_elsewhere
      assert router.stats()["shed"] == 1

  def test_replica_death_sheds_only_its_tenants(self, fronts):
    with self._router(fronts) as router:
      before = {t: router.predict(t, self.OBS)[0] for t in KEYS[:40]}
      victim = 2
      fronts[victim].close()
      after = {}
      for tenant in KEYS[:40]:
        after[tenant] = router.predict(tenant, self.OBS)[0]
      assert victim not in router.alive()
      assert router.stats()["failovers"] >= 1
      for tenant in KEYS[:40]:
        if before[tenant] != float(victim):
          # The replay-shard pin, at the router: survivors' tenants
          # never move on another replica's death.
          assert after[tenant] == before[tenant]
        else:
          assert after[tenant] != float(victim)
          assert after[tenant] == float(rendezvous_choose(
              tenant, [0, 1]))

  def test_all_dead_raises_no_replicas(self, fronts):
    with self._router(fronts) as router:
      for front in fronts.values():
        front.close()
      with pytest.raises(NoReplicasError):
        router.predict("anyone", self.OBS)

  def test_mark_alive_rejoins_placement(self, fronts):
    with self._router(fronts) as router:
      router.mark_dead(0)
      assert router.alive() == [1, 2]
      router.mark_alive(0)
      assert router.alive() == [0, 1, 2]

  def test_spread_round_robins_the_hot_tenant(self, fronts):
    with self._router(fronts, spread=2) as router:
      targets = {router.predict("hot", self.OBS)[0] for _ in range(8)}
      expected = set(
          float(i) for i in rendezvous_spread("hot", range(3), k=2))
      assert targets == expected

  def test_dedup_short_circuits_repeats(self, fronts):
    with self._router(fronts, dedup_capacity=16) as router:
      router.predict("t", self.OBS)
      served = sum(f.calls for f in fronts.values())
      for _ in range(5):
        router.predict("t", self.OBS)
      assert sum(f.calls for f in fronts.values()) == served
      assert router.dedup_stats()["hits"] == 5

  def test_dedup_is_tenant_scoped(self, fronts):
    # Two tenants streaming the SAME frame must NOT share cached
    # actions — they can be entirely different models. (Found by an
    # end-to-end drive: a cross-tenant hit short-circuited the
    # network and hid a replica death from the router.)
    with self._router(fronts, dedup_capacity=16) as router:
      router.predict("tenant-a", self.OBS)
      before = sum(f.calls for f in fronts.values())
      router.predict("tenant-b", self.OBS)
      assert sum(f.calls for f in fronts.values()) == before + 1
      assert router.dedup_stats()["hits"] == 0
      router.predict("tenant-a", self.OBS)  # same-tenant repeat hits
      assert router.dedup_stats()["hits"] == 1

  def test_notify_published_invalidates_dedup(self, fronts):
    with self._router(fronts, dedup_capacity=16) as router:
      for front in fronts.values():
        front.version = 0
      router.predict("t", self.OBS)
      for front in fronts.values():
        front.version = 7
      router.notify_published(7)
      served = sum(f.calls for f in fronts.values())
      router.predict("t", self.OBS)  # must re-dispatch: stale entry
      assert sum(f.calls for f in fronts.values()) == served + 1
      # ...and the fresh reply re-seeds the cache at the new version.
      router.predict("t", self.OBS)
      assert sum(f.calls for f in fronts.values()) == served + 1


class TestServingReplicaCrashFaults:

  def test_default_plan_classes_unchanged(self):
    # The seed-7 digest pin in test_fleet_faults.py depends on the
    # default class tuple staying the original seven; the new class is
    # strictly opt-in.
    assert faults.SERVING_REPLICA_CRASH not in faults.FAULT_CLASSES
    assert len(faults.FAULT_CLASSES) == 7
    assert faults.ALL_FAULT_CLASSES == (
        faults.FAULT_CLASSES + (faults.SERVING_REPLICA_CRASH,))

  def test_generate_requires_num_fronts(self):
    with pytest.raises(ValueError, match="num_fronts"):
      faults.FaultPlan.generate(
          seed=3, num_actors=2,
          classes=(faults.SERVING_REPLICA_CRASH,))

  def test_generate_targets_a_front(self):
    plan = faults.FaultPlan.generate(
        seed=3, num_actors=2,
        classes=(faults.SERVING_REPLICA_CRASH,), num_fronts=2)
    assert len(plan.events) == 1
    event = plan.events[0]
    assert event.fault == faults.SERVING_REPLICA_CRASH
    assert event.target in ("front-0", "front-1")
    assert event.mode == "hard"
    # Deterministic across calls: the replay pin generalizes.
    again = faults.FaultPlan.generate(
        seed=3, num_actors=2,
        classes=(faults.SERVING_REPLICA_CRASH,), num_fronts=2)
    assert plan.digest() == again.digest()

  def test_on_serve_seam_fires_once_at_threshold(self):
    event = faults.FaultEvent(
        fault=faults.SERVING_REPLICA_CRASH, target="front-0", at=3)
    plan = faults.FaultPlan(seed=0, events=(event,))
    injector = faults.FaultInjector(plan, "front-0")
    assert injector.on_serve(1) is None
    assert injector.on_serve(2) is None
    fired = injector.on_serve(3)
    assert fired is event
    assert injector.on_serve(4) is None  # one-shot
    assert injector.injected[0]["fault"] == (
        faults.SERVING_REPLICA_CRASH)

  def test_on_serve_ignores_other_roles(self):
    event = faults.FaultEvent(
        fault=faults.SERVING_REPLICA_CRASH, target="front-1", at=1)
    plan = faults.FaultPlan(seed=0, events=(event,))
    injector = faults.FaultInjector(plan, "front-0")
    assert injector.on_serve(100) is None


@pytest.mark.slow
class TestFrontTierEndToEnd:
  """The multi-process pin: two REAL front replicas over TCP behind
  the router — predict for every tenant, one publish fanning out over
  the broadcast tree to both replicas, and a hard replica kill that
  the router sheds around without orchestrator help. This is the
  tier-shaped integration the unit pins above can't see (real
  sockets, real spawn, real arena swaps)."""

  def test_replicated_tier_end_to_end(self):
    import jax

    from tensor2robot_tpu.fleet.front import FrontTier
    from tensor2robot_tpu.fleet.host import _build_learner
    from tensor2robot_tpu.fleet.orchestrator import FleetConfig
    from tensor2robot_tpu.specs import make_random_tensors

    config = FleetConfig(
        num_actors=1, env="mujoco_pose", image_size=16, action_dim=2,
        torso_filters=(8,), head_filters=(8,), dense_sizes=(16,),
        cem_population=8, cem_iterations=1, cem_elites=2,
        serve_max_batch=4, transport="tcp", broadcast_degree=2,
        front_hosts=2, front_tenants=("a", "b"),
        launch_timeout_secs=240.0, seed=0)
    learner = _build_learner(config)
    state0 = learner.create_state(
        jax.random.PRNGKey(config.seed), batch_size=2)
    acting0 = state0.train_state.replace(opt_state=None)
    obs = make_random_tensors(
        learner.observation_specification(), batch_size=1, seed=0)

    tier = FrontTier(config, 2).launch()
    router = ServingRouter(
        tier.addresses, authkey=config.authkey, transport="tcp")
    try:
      # Every tenant gets a real engine answer through the router.
      for tenant in ("a", "b"):
        action = np.asarray(router.predict(tenant, obs))
        assert action.size > 0 and np.all(np.isfinite(action))
      assert router.params_version == 0

      # ONE publish to the tree root reaches BOTH replicas.
      assert tier.publish(acting0, step=7) == 7
      for index in (0, 1):
        client = tier._client(index)
        try:
          scalars = client.call("metrics_scalars", {})
        finally:
          if index != 0:
            client.close()
        assert scalars["front_publishes"] >= 1.0, (index, scalars)
      # The router learns the new version from the next reply.
      router.predict("a", obs)
      assert router.params_version == 7

      # Kill tenant a's HOME replica: the very next predict fails
      # over inside the call, and the victim leaves the placement.
      victim = router.placement("a")[0]
      tier.kill(victim)
      action = np.asarray(router.predict("a", obs))
      assert action.size > 0 and np.all(np.isfinite(action))
      assert victim not in router.alive()
      assert victim not in router.placement("a")
      assert router.stats()["failovers"] >= 1
    finally:
      router.close()
      tier.close()
