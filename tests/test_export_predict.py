"""Tests for the serving path: export generators, predictors, async hook.

Covers the reference's robot-fleet handoff contract (SURVEY.md §4.4):
trainer exports SavedModels with spec assets; robot-side predictors
rebuild specs from assets and serve numpy predict without the model
class.
"""

import os

import jax
import numpy as np
import pytest

from tensor2robot_tpu import specs
from tensor2robot_tpu.data.abstract_input_generator import Mode
from tensor2robot_tpu.data.random_input_generator import (
    RandomInputGenerator,
)
from tensor2robot_tpu.export import (
    SavedModelExportGenerator,
    latest_export_dir,
)
from tensor2robot_tpu.hooks import AsyncExportHook
from tensor2robot_tpu.predictors import (
    CheckpointPredictor,
    SavedModelPredictor,
)
from tensor2robot_tpu import train_eval
from tensor2robot_tpu.utils.mocks import MockT2RModel


@pytest.fixture(scope="module")
def trained(tmp_path_factory):
  """One short training run shared by the serving tests."""
  model_dir = str(tmp_path_factory.mktemp("served_model"))
  model = MockT2RModel()
  state = train_eval.train_eval_model(
      model=model,
      model_dir=model_dir,
      input_generator_train=RandomInputGenerator(batch_size=8),
      max_train_steps=4,
      save_checkpoints_steps=2,
      log_every_steps=2,
  )
  return model, state, model_dir


@pytest.mark.slow
class TestSavedModelExport:

  def test_export_creates_artifact_with_assets(self, trained):
    model, state, model_dir = trained
    gen = SavedModelExportGenerator()
    path = gen.export(model, jax.device_get(state), model_dir)
    assert os.path.isdir(path)
    assets = specs.read_assets(
        os.path.join(path, "assets.extra", specs.ASSET_FILENAME))
    flat = assets["feature_spec"].to_flat_dict()
    wire = specs.flatten_spec_structure(
        model.preprocessor.get_in_feature_specification(
            Mode.PREDICT)).to_flat_dict()
    assert set(flat) == set(wire)
    assert assets["global_step"] == 4

  def test_latest_export_dir_picks_newest(self, trained, tmp_path):
    base = str(tmp_path / "exports")
    for ts in ("100", "200", "50"):
      os.makedirs(os.path.join(base, ts))
    assert latest_export_dir(base).endswith("200")

  def test_savedmodel_predictor_round_trip(self, trained):
    model, state, model_dir = trained
    predictor = SavedModelPredictor(os.path.join(model_dir, "export"))
    assert predictor.restore(timeout_secs=0)
    assert predictor.model_version > 0
    assert predictor.global_step == 4
    batch = specs.make_random_tensors(
        predictor.feature_specification, batch_size=3, seed=1)
    out = predictor.predict(batch.to_flat_dict())
    value = next(iter(out.values()))
    assert value.shape[0] == 3

  def test_predictor_validates_inputs(self, trained):
    model, state, model_dir = trained
    predictor = SavedModelPredictor(os.path.join(model_dir, "export"))
    predictor.restore(timeout_secs=0)
    batch = specs.make_random_tensors(
        predictor.feature_specification, batch_size=2, seed=1)
    flat = batch.to_flat_dict()
    key = next(iter(flat))
    flat[key] = flat[key][..., :-1]  # corrupt trailing dim
    with pytest.raises(specs.SpecValidationError):
      predictor.predict(flat)

  def test_unrestored_predictor_raises(self, tmp_path):
    predictor = SavedModelPredictor(str(tmp_path / "nothing"))
    assert not predictor.restore(timeout_secs=0)
    with pytest.raises(ValueError, match="restore"):
      predictor.predict({})


class TestRawWireServing:
  """data_format='raw' specs ride the exported tf.Example signature:
  the same graph parser serves serialized protos with near-memcpy
  decode (no image codec robot-side)."""

  @pytest.mark.slow
  def test_raw_spec_proto_signature_round_trip(self, tmp_path):
    import tensorflow as tf

    from tensor2robot_tpu.data import tfexample
    from tensor2robot_tpu.specs import (
        ExtendedTensorSpec,
        TensorSpecStruct,
    )

    class RawImageModel(MockT2RModel):

      def get_feature_specification(self, mode):
        st = TensorSpecStruct()
        st.x = ExtendedTensorSpec(shape=(4, 4, 3), dtype=np.uint8,
                                  name="x", data_format="raw")
        return st

      def create_network(self):
        import flax.linen as nn
        import jax.numpy as jnp

        class Net(nn.Module):

          @nn.compact
          def __call__(self, features, train=False):
            flat = features.to_flat_dict() \
                if hasattr(features, "to_flat_dict") else features
            x = flat["x"].astype(jnp.float32).reshape(
                (flat["x"].shape[0], -1)) / 255.0
            out = nn.Dense(2)(x)
            return {"output": out}

        return Net()

    model = RawImageModel()
    state = model.create_inference_state(jax.random.PRNGKey(0))
    model_dir = str(tmp_path)
    export_dir = SavedModelExportGenerator().export(
        model, jax.device_get(state), model_dir)
    loaded = tf.saved_model.load(export_dir)
    # Raw specs are NOT sequences, so the proto signature builds.
    assert "parse_tf_example" in loaded.signatures

    rng = np.random.default_rng(3)
    images = rng.integers(0, 255, (2, 4, 4, 3)).astype(np.uint8)
    serialized = [
        tfexample.encode_example(
            {"x": img}, model.get_feature_specification(Mode.PREDICT))
        for img in images
    ]
    from_protos = loaded.signatures["parse_tf_example"](
        examples=tf.constant(serialized))
    direct = loaded.signatures["serving_default"](
        x=tf.constant(images))
    np.testing.assert_allclose(
        np.asarray(from_protos["output"]),
        np.asarray(direct["output"]), atol=1e-5)


class TestCheckpointPredictor:

  def test_restore_and_predict(self, trained):
    model, state, model_dir = trained
    predictor = CheckpointPredictor(model, checkpoint_dir=model_dir)
    assert predictor.restore(timeout_secs=0)
    assert predictor.model_version == 4
    batch = specs.make_random_tensors(
        predictor.feature_specification, batch_size=2, seed=2)
    out = predictor.predict(batch.to_flat_dict())
    value = next(iter(out.values()))
    assert value.shape[0] == 2

  def test_init_randomly(self):
    model = MockT2RModel()
    predictor = CheckpointPredictor(model)
    predictor.init_randomly()
    batch = specs.make_random_tensors(
        predictor.feature_specification, batch_size=2, seed=3)
    out = predictor.predict(batch.to_flat_dict())
    assert next(iter(out.values())).shape[0] == 2

  def test_no_checkpoint_yet(self, tmp_path):
    model = MockT2RModel()
    predictor = CheckpointPredictor(
        model, checkpoint_dir=str(tmp_path / "empty"))
    assert not predictor.restore(timeout_secs=0)


class TestAsyncExportHook:

  def test_hook_exports_on_checkpoint(self, tmp_path):
    model_dir = str(tmp_path / "hooked")
    hook = AsyncExportHook(SavedModelExportGenerator(), block=True)
    train_eval.train_eval_model(
        model=MockT2RModel(),
        model_dir=model_dir,
        input_generator_train=RandomInputGenerator(batch_size=8),
        max_train_steps=2,
        save_checkpoints_steps=2,
        hooks=[hook],
    )
    assert hook.export_paths
    assert latest_export_dir(os.path.join(model_dir, "export"))

  def test_hook_cadence(self, tmp_path):
    hook = AsyncExportHook(SavedModelExportGenerator(),
                           export_every_n_checkpoints=2, block=True)
    train_eval.train_eval_model(
        model=MockT2RModel(),
        model_dir=str(tmp_path / "cadence"),
        input_generator_train=RandomInputGenerator(batch_size=8),
        max_train_steps=4,
        save_checkpoints_steps=1,
        hooks=[hook],
    )
    # 4 checkpoints (+ final dedupe) at every-2 cadence -> 2 exports.
    assert len(hook.export_paths) == 2
