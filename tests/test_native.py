"""Native host-data-path kernels: exactness vs numpy + fallback.

The C++ gather/scatter must be BIT-identical to the numpy fancy-index
path it accelerates — the replay buffer swaps between them based on
toolchain availability, so any divergence would make training data
depend on whether g++ exists.
"""

import numpy as np
import pytest

from tensor2robot_tpu.utils import native


class TestNativeKernels:

  def test_library_builds_in_image(self):
    """The image ships g++; the library must actually build here so
    the native path (not just the fallback) is what CI exercises."""
    assert native.native_available()

  @pytest.mark.parametrize("dtype", [np.uint8, np.float32, np.int64])
  def test_gather_matches_numpy(self, dtype):
    rng = np.random.default_rng(0)
    src = (rng.integers(0, 255, (1000, 7, 3)).astype(dtype)
           if np.issubdtype(dtype, np.integer)
           else rng.standard_normal((1000, 7, 3)).astype(dtype))
    idx = rng.integers(0, 1000, size=333)
    np.testing.assert_array_equal(native.gather_rows(src, idx),
                                  src[idx])

  def test_gather_large_multithread_path(self):
    """Rows big enough to cross the threading threshold (>1 MB)."""
    rng = np.random.default_rng(1)
    src = rng.integers(0, 255, (512, 64, 64, 3)).astype(np.uint8)
    idx = rng.integers(0, 512, size=256)
    np.testing.assert_array_equal(
        native.gather_rows(src, idx, num_threads=4), src[idx])

  def test_gather_into_preallocated_out(self):
    rng = np.random.default_rng(2)
    src = rng.standard_normal((100, 5)).astype(np.float32)
    idx = rng.integers(0, 100, size=40)
    out = np.empty((40, 5), np.float32)
    result = native.gather_rows(src, idx, out=out)
    assert result is out
    np.testing.assert_array_equal(out, src[idx])

  def test_scatter_matches_numpy(self):
    rng = np.random.default_rng(3)
    dst = np.zeros((200, 6, 2), np.float32)
    expected = dst.copy()
    idx = rng.permutation(200)[:50]  # distinct, like ring-buffer slots
    src = rng.standard_normal((50, 6, 2)).astype(np.float32)
    native.scatter_rows(dst, idx, src)
    expected[idx] = src
    np.testing.assert_array_equal(dst, expected)

  def test_gather_negative_indices_match_numpy(self):
    rng = np.random.default_rng(5)
    src = rng.standard_normal((30, 4)).astype(np.float32)
    idx = np.array([-1, 0, -30, 5])
    np.testing.assert_array_equal(native.gather_rows(src, idx),
                                  src[idx])

  def test_gather_out_of_bounds_raises(self):
    """Same IndexError with or without the toolchain — training data
    must never depend on whether g++ was present."""
    src = np.zeros((10, 2), np.float32)
    with pytest.raises(IndexError, match="out of bounds"):
      native.gather_rows(src, np.array([3, 10]))
    with pytest.raises(IndexError, match="out of bounds"):
      native.gather_rows(src, np.array([-11]))

  def test_scatter_shape_mismatch_raises(self):
    dst = np.zeros((10, 3), np.float32)
    with pytest.raises(ValueError, match="does not match"):
      native.scatter_rows(dst, np.array([0, 1]),
                          np.zeros((2, 4), np.float32))
    with pytest.raises(ValueError, match="does not match"):
      native.scatter_rows(dst, np.array([0, 1]),
                          np.zeros((3, 3), np.float32))

  def test_noncontiguous_falls_back(self):
    """A transposed (non-C-contiguous) source silently uses numpy."""
    rng = np.random.default_rng(4)
    src = rng.standard_normal((6, 50)).astype(np.float32).T
    assert not src.flags.c_contiguous
    idx = rng.integers(0, 50, size=20)
    np.testing.assert_array_equal(native.gather_rows(src, idx),
                                  src[idx])
