"""Tests for device-side preprocessors and image transformations."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensor2robot_tpu.data import Mode
from tensor2robot_tpu.preprocessors import (
    ImagePreprocessor,
    NoOpPreprocessor,
    TPUCompatPreprocessorWrapper,
    image_transformations as imt,
)
from tensor2robot_tpu.specs import ExtendedTensorSpec, TensorSpecStruct


def model_feature_spec(mode=None):
  st = TensorSpecStruct()
  st.image = ExtendedTensorSpec(shape=(8, 8, 3), dtype=np.float32,
                                name="image", data_format="jpeg")
  st.state = ExtendedTensorSpec(shape=(4,), dtype=np.float32, name="state")
  return st


def model_label_spec(mode=None):
  st = TensorSpecStruct()
  st.target = ExtendedTensorSpec(shape=(2,), dtype=np.float32,
                                 name="target")
  return st


class TestImageTransformations:

  def setup_method(self):
    self.key = jax.random.PRNGKey(0)
    self.images = jax.random.uniform(self.key, (4, 16, 16, 3))

  def test_center_crop(self):
    out = imt.center_crop(self.images, 8, 8)
    assert out.shape == (4, 8, 8, 3)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(self.images[:, 4:12, 4:12, :]))

  def test_random_crop_shape_and_content(self):
    out = imt.random_crop(self.key, self.images, 8, 8)
    assert out.shape == (4, 8, 8, 3)
    # Every crop must be a contiguous subwindow: check pixel membership.
    src = np.asarray(self.images[0]).reshape(-1, 3)
    crop = np.asarray(out[0]).reshape(-1, 3)
    assert all(any(np.allclose(p, s) for s in src) for p in crop[:5])

  def test_random_crop_full_size_identity(self):
    out = imt.random_crop(self.key, self.images, 16, 16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(self.images))

  def test_resize(self):
    out = imt.resize(self.images, 4, 4)
    assert out.shape == (4, 4, 4, 3)

  def test_flip(self):
    out = imt.random_flip_left_right(self.key, self.images)
    assert out.shape == self.images.shape

  def test_to_float_uint8(self):
    img = (np.arange(12, dtype=np.uint8).reshape(1, 2, 2, 3) * 20)
    out = imt.to_float(jnp.asarray(img))
    assert out.dtype == jnp.float32
    assert float(out.max()) <= 1.0

  def test_brightness_contrast_saturation_hue(self):
    ones = jnp.ones((2, 4, 4, 3)) * 0.5
    bright = imt.adjust_brightness(ones, jnp.array([0.1, -0.1]))
    np.testing.assert_allclose(np.asarray(bright[0]), 0.6, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(bright[1]), 0.4, rtol=1e-5)
    # Contrast of a constant image is identity.
    contrast = imt.adjust_contrast(ones, jnp.array([1.7, 0.2]))
    np.testing.assert_allclose(np.asarray(contrast), 0.5, atol=1e-5)
    # Saturation of gray is identity.
    sat = imt.adjust_saturation(ones, jnp.array([2.0, 0.0]))
    np.testing.assert_allclose(np.asarray(sat), 0.5, atol=1e-5)
    # Zero hue rotation is identity up to the YIQ matrices' precision
    # (the standard 3-decimal matrices are approximate inverses).
    hue = imt.adjust_hue(self.images, jnp.zeros((4,)))
    np.testing.assert_allclose(np.asarray(hue), np.asarray(self.images),
                               atol=5e-3)

  def test_photometric_distortions_jit_and_range(self):
    distort = jax.jit(imt.apply_photometric_image_distortions)
    out = distort(self.key, self.images)
    assert out.shape == self.images.shape
    assert float(out.min()) >= 0.0 and float(out.max()) <= 1.0
    # Different keys → different outputs.
    out2 = distort(jax.random.PRNGKey(1), self.images)
    assert not np.allclose(np.asarray(out), np.asarray(out2))


class TestNoOpPreprocessor:

  def test_identity(self):
    p = NoOpPreprocessor(model_feature_spec, model_label_spec)
    assert p.get_in_feature_specification(Mode.TRAIN) == \
        p.get_out_feature_specification(Mode.TRAIN)
    feats = TensorSpecStruct({"x": jnp.ones((2, 3))})
    out_f, out_l = p.preprocess(feats, None, Mode.TRAIN)
    assert out_f is feats and out_l is None


class TestImagePreprocessor:

  def make(self, distort=True):
    return ImagePreprocessor(
        model_feature_spec, model_label_spec,
        src_height=12, src_width=12, distort=distort)

  def test_in_spec_is_uint8_src_size(self):
    p = self.make()
    in_spec = p.get_in_feature_specification(Mode.TRAIN)
    assert in_spec["image"].shape == (12, 12, 3)
    assert in_spec["image"].dtype == np.dtype(np.uint8)
    # Non-image features unchanged.
    assert in_spec["state"].shape == (4,)

  def test_train_preprocess_crops_and_casts(self):
    p = self.make()
    batch = TensorSpecStruct()
    batch.image = jnp.asarray(
        np.random.default_rng(0).integers(0, 255, (2, 12, 12, 3),
                                          dtype=np.uint8))
    batch.state = jnp.ones((2, 4), jnp.float32)
    out_f, _ = jax.jit(
        lambda f: p.preprocess(f, None, Mode.TRAIN,
                               jax.random.PRNGKey(0)))(batch)
    assert out_f["image"].shape == (2, 8, 8, 3)
    assert out_f["image"].dtype == jnp.float32
    assert float(out_f["image"].max()) <= 1.0

  def test_eval_is_deterministic_center_crop(self):
    p = self.make()
    image = np.zeros((1, 12, 12, 3), np.uint8)
    image[0, 2:10, 2:10, :] = 255  # center block
    batch = TensorSpecStruct({"image": jnp.asarray(image),
                              "state": jnp.zeros((1, 4))})
    out_f, _ = p.preprocess(batch, None, Mode.EVAL)
    np.testing.assert_allclose(np.asarray(out_f["image"]), 1.0)


class TestTPUCompatWrapper:

  def test_cast_and_scale(self):
    base = NoOpPreprocessor(
        lambda mode: TensorSpecStruct(
            {"img": ExtendedTensorSpec(shape=(4, 4, 3), dtype=np.uint8,
                                       name="img")}),
        lambda mode: None)
    wrapper = TPUCompatPreprocessorWrapper(base, model_dtype=jnp.bfloat16)
    out_spec = wrapper.get_out_feature_specification(Mode.TRAIN)
    assert out_spec["img"].dtype == jnp.bfloat16.dtype
    # In-spec still uint8 (cheap wire format).
    in_spec = wrapper.get_in_feature_specification(Mode.TRAIN)
    assert in_spec["img"].dtype == np.dtype(np.uint8)
    batch = TensorSpecStruct(
        {"img": jnp.full((2, 4, 4, 3), 255, jnp.uint8)})
    out_f, _ = wrapper.preprocess(batch, None, Mode.TRAIN)
    assert out_f["img"].dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out_f["img"].astype(jnp.float32)),
                               1.0)
