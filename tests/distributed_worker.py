"""Worker binary for the multi-process jax.distributed test.

Launched (2×) by tests/test_distributed.py with the framework's env
launch contract (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES /
JAX_PROCESS_ID). Each process owns 2 virtual CPU devices; after
`maybe_initialize_distributed()` the mesh spans all 4 and the SAME
GSPMD programs a single process would build run across both — a psum
and one sharded QT-Opt train step, each process feeding only its local
batch shard (the multi-host infeed contract of
`data/prefetch.device_put_batch`).

Prints `DISTRIBUTED_OK <process_id> <loss>` on success; the parent
asserts the marker and that both processes agree on the loss.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=2")

import numpy as np  # noqa: E402
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from tensor2robot_tpu.parallel.distributed import (  # noqa: E402
    maybe_initialize_distributed,
)

# Env-triggered: this is the launch contract production binaries use
# (bin/run_t2r_trainer.py calls this before any device use).
assert maybe_initialize_distributed(), "env trigger did not fire"

import jax.numpy as jnp  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from tensor2robot_tpu import specs  # noqa: E402
from tensor2robot_tpu.data.prefetch import (  # noqa: E402
    device_put_batch,
    make_data_sharding,
)
from tensor2robot_tpu.parallel import create_mesh  # noqa: E402
from tensor2robot_tpu.parallel.mesh import shard_map_compat  # noqa: E402
from tensor2robot_tpu.research.qtopt import (  # noqa: E402
    GraspingQModel,
    QTOptLearner,
)


def main():
  assert jax.process_count() == 2, jax.process_count()
  assert jax.device_count() == 2 * jax.local_device_count(), (
      jax.device_count(), jax.local_device_count())

  mesh = create_mesh({"data": jax.device_count()})

  # 1. A psum across ALL devices of BOTH processes.
  total = jax.jit(
      shard_map_compat(
          lambda x: jax.lax.psum(x, "data"),
          mesh=mesh, in_specs=P("data"), out_specs=P()),
      out_shardings=NamedSharding(mesh, P()))(
          np.arange(1.0, jax.device_count() + 1.0, dtype=np.float32))
  expected = float(sum(range(1, jax.device_count() + 1)))
  got = float(np.asarray(jax.device_get(total))[0])
  assert got == expected, (got, expected)

  # 2. One sharded QT-Opt train step over the global mesh, each
  # process contributing only its local batch shard.
  model = GraspingQModel(
      image_size=16, torso_filters=(8,), head_filters=(8,),
      dense_sizes=(16,), action_dim=2, device_dtype=jnp.float32)
  learner = QTOptLearner(model, cem_population=8, cem_iterations=1,
                         cem_elites=2)
  state = learner.create_state(jax.random.PRNGKey(0), batch_size=2)
  sharding = make_data_sharding(mesh)
  global_batch = 8
  local = specs.make_random_tensors(
      learner.transition_specification(),
      batch_size=global_batch // jax.process_count(),
      # Same seed per process is fine: the assertion is on mechanics
      # (sharded execution), not data distribution.
      seed=1 + jax.process_index())
  batch = device_put_batch(
      jax.tree_util.tree_map(np.asarray, local), sharding)

  step = jax.jit(
      learner.train_step,
      in_shardings=(None, sharding, None),
      out_shardings=(None, NamedSharding(mesh, P())))
  new_state, metrics = step(state, batch, jax.random.PRNGKey(3))
  loss = float(np.asarray(jax.device_get(metrics["loss"])))
  assert np.isfinite(loss), loss
  step_val = int(np.asarray(jax.device_get(new_state.train_state.step)))
  assert step_val == 1, step_val

  # 3. Multi-process sharded checkpoint: the state is sharded across
  # BOTH processes' devices; orbax writes each process's addressable
  # shards (no host gather — the contract train_eval's sharded save
  # relies on) and restore adopts the sharded layout with the
  # original values.
  ckpt_dir = os.environ.get("T2R_TEST_CKPT_DIR")
  if ckpt_dir:
    from tensor2robot_tpu.utils import checkpoints as ckpt_lib

    sharding_w = NamedSharding(mesh, P("data"))
    global_w = np.arange(8 * 16, dtype=np.float32).reshape(8, 16)
    w = jax.make_array_from_callback(
        global_w.shape, sharding_w, lambda idx: global_w[idx])
    writer = ckpt_lib.CheckpointWriter(ckpt_dir, max_to_keep=1)
    writer.save(0, {"w": w})
    writer.close()
    restored = ckpt_lib.restore_state(ckpt_dir, like={"w": w}, step=0)["w"]
    for shard in restored.addressable_shards:
      np.testing.assert_array_equal(
          np.asarray(shard.data), global_w[shard.index])
    # Global checksum via a cross-process reduction of the restored
    # sharded array (proves it is usable, not just readable).
    checksum = jax.jit(
        shard_map_compat(lambda x: jax.lax.psum(jnp.sum(x), "data"),
                         mesh=mesh, in_specs=P("data"), out_specs=P()),
        out_shardings=NamedSharding(mesh, P()))(restored)
    got_sum = float(np.asarray(jax.device_get(checksum)))
    assert got_sum == float(global_w.sum()), (got_sum, global_w.sum())
    print(f"CKPT_OK {jax.process_index()} {got_sum:.1f}", flush=True)

  print(f"DISTRIBUTED_OK {jax.process_index()} {loss:.6f}", flush=True)
  jax.distributed.shutdown()


if __name__ == "__main__":
  sys.exit(main())
