"""Telemetry-plane tests (ISSUE 11): the ring, the registry, the
envelope, the merge, and the flight recorder — pinned contracts:

  * the span ring stays BOUNDED under multi-threaded churn and the
    recorded/flushed/dropped accounting stays consistent;
  * the merge tool produces ONE host-clock-ordered timeline with
    per-file clock offsets applied (the cross-process ordering pin);
  * a latched fleet error produces flight-recorder dumps from the
    crashing learner, the live host, AND the orchestrator (reusing the
    crash-policy harness of tests/test_fleet.py);
  * the whole telemetry package imports WITHOUT jax (actor/worker
    processes record spans — the IMP401 worker-safe property);
  * the tracing fast paths stay cheap (the overhead gate's in-process
    twin: the bench --telemetry axis gates the steps/s A/B at <2%);
  * every `metrics_<tag>.jsonl` record the tier-1 trainers produce is
    the unified `{step, wall, role, payload}` envelope.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from tensor2robot_tpu.telemetry import core as tcore
from tensor2robot_tpu.telemetry import flightrec
from tensor2robot_tpu.telemetry import merge as merge_lib
from tensor2robot_tpu.telemetry import metrics as tmetrics
from tensor2robot_tpu.telemetry import records as trecords

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _isolated_telemetry():
  """Fresh process-global tracer/registry per test (both are
  process-wide singletons by design)."""
  tcore.reset_for_tests()
  tmetrics.reset_for_tests()
  yield
  tcore.reset_for_tests()
  tmetrics.reset_for_tests()


class TestSpanRing:

  def test_ring_bounds_under_churn(self):
    """Memory-mode ring: 8 threads × 5000 spans against capacity 512 —
    the ring never exceeds its bound, nothing crashes, and the
    recorded/dropped accounting closes."""
    tracer = tcore.Tracer().configure("churn", capacity=512)
    threads_n, per_thread = 8, 5000

    def hammer(i):
      for j in range(per_thread):
        with tracer.span("work", thread=i):
          pass

    threads = [threading.Thread(target=hammer, args=(i,))
               for i in range(threads_n)]
    for t in threads:
      t.start()
    for t in threads:
      t.join()
    total = threads_n * per_thread
    assert tracer.spans_recorded == total
    assert tracer.pending <= 512
    # Everything beyond the ring aged out (memory mode never flushes).
    assert tracer.spans_dropped == total - tracer.pending
    # The survivors are well-formed span dicts.
    snap = tracer.snapshot_spans()
    assert len(snap) == tracer.pending
    assert all(s["name"] == "work" and s["role"] == "churn"
               for s in snap)

  def test_flush_to_file_with_meta_and_offset(self, tmp_path):
    tracer = tcore.Tracer().configure("host", trace_dir=str(tmp_path))
    with tracer.span("alpha", x=1):
      pass
    tracer.set_clock_offset(0.25)
    with tracer.span("beta"):
      pass
    tracer.close()
    lines = [json.loads(line) for line in
             open(tmp_path / "trace_host.jsonl")]
    metas = [r for r in lines if r["ph"] == "M"]
    spans = [r for r in lines if r["ph"] == "X"]
    # Configure wrote one meta, set_clock_offset another.
    assert len(metas) == 2
    assert metas[0]["clock_offset"] == 0.0
    assert metas[1]["clock_offset"] == 0.25
    assert [s["name"] for s in spans] == ["alpha", "beta"]
    assert spans[0]["args"] == {"x": 1}
    assert all(s["role"] == "host" and s["pid"] == os.getpid()
               for s in spans)

  def test_auto_flush_keeps_ring_small(self, tmp_path):
    tracer = tcore.Tracer().configure("w", trace_dir=str(tmp_path))
    for _ in range(3 * tcore.FLUSH_BATCH):
      tracer.event("tick")
    # File-backed tracers flush at FLUSH_BATCH: nothing dropped.
    assert tracer.spans_dropped == 0
    assert tracer.pending < tcore.FLUSH_BATCH
    tracer.close()
    spans = [json.loads(line) for line in open(tmp_path / "trace_w.jsonl")
             if json.loads(line)["ph"] == "X"]
    assert len(spans) == 3 * tcore.FLUSH_BATCH

  def test_span_fast_paths_are_cheap(self):
    """The in-process overhead pin (the steps/s twin lives in
    bench --telemetry): disabled spans must be ~free, enabled
    memory-mode spans micro-scale. Bounds are generous for loaded CI
    hosts — they catch a lock or an I/O call landing on the hot path,
    not microarchitecture."""
    tracer = tcore.Tracer()  # unconfigured = disabled
    n = 20_000
    t0 = time.perf_counter()
    for _ in range(n):
      with tracer.span("x"):
        pass
    disabled_us = (time.perf_counter() - t0) / n * 1e6
    tracer.configure("bench", capacity=1024)
    t0 = time.perf_counter()
    for _ in range(n):
      with tracer.span("x"):
        pass
    enabled_us = (time.perf_counter() - t0) / n * 1e6
    assert disabled_us < 5.0, f"disabled span {disabled_us:.2f}µs"
    assert enabled_us < 50.0, f"enabled span {enabled_us:.2f}µs"


class TestMetricsRegistry:

  def test_snapshot_schema_and_scalars(self):
    registry = tmetrics.MetricsRegistry()
    registry.counter("replay.adds").inc(64)
    registry.gauge("replay.fill").set(0.5)
    hist = registry.histogram("serving.bucket_8_ms")
    for value in (0.2, 0.4, 1.0, 3.0, 90.0):
      hist.observe(value)
    snap = registry.snapshot()
    assert set(snap) == {"counters", "gauges", "histograms"}
    assert snap["counters"]["replay.adds"] == 64.0
    assert snap["gauges"]["replay.fill"] == 0.5
    h = snap["histograms"]["serving.bucket_8_ms"]
    assert set(h) >= {"bounds", "counts", "count", "sum", "min",
                      "max", "p50", "p95"}
    assert h["count"] == 5 and h["min"] == 0.2 and h["max"] == 90.0
    assert sum(h["counts"]) == 5
    # Quantiles are bucket-interpolated but must bracket sanely.
    assert 0.2 <= h["p50"] <= 3.0
    assert h["p95"] <= 100.0
    flat = registry.scalars()
    assert flat["replay.adds"] == 64.0
    assert "serving.bucket_8_ms_p50" in flat
    assert registry.scalars("replay.") == {
        "replay.adds": 64.0, "replay.fill": 0.5}

  def test_counter_exact_under_threads(self):
    counter = tmetrics.MetricsRegistry().counter("c")
    threads = [threading.Thread(
        target=lambda: [counter.inc() for _ in range(10_000)])
        for _ in range(8)]
    for t in threads:
      t.start()
    for t in threads:
      t.join()
    assert counter.value == 80_000.0

  def test_scalars_from_snapshot_prefix(self):
    registry = tmetrics.MetricsRegistry()
    registry.counter("actor.episodes").inc(3)
    flat = tmetrics.scalars_from_snapshot(registry.snapshot(),
                                          prefix="actor-1/")
    assert flat == {"actor-1/actor.episodes": 3.0}


class TestRecordEnvelope:

  def test_make_validate_normalize_roundtrip(self):
    record = trecords.make_record(7, {"loss": 0.5, "steps": 2.0},
                                  role="learner")
    assert trecords.validate_record(record) == []
    flat = trecords.normalize_record(record)
    assert flat["step"] == 7 and flat["role"] == "learner"
    assert flat["loss"] == 0.5

  def test_validator_rejects_malformed(self):
    assert trecords.validate_record([1, 2]) != []
    assert any("missing" in p for p in trecords.validate_record({}))
    bad = trecords.make_record(1, {"x": 1.0})
    bad["payload"]["y"] = "not-a-number"
    assert trecords.validate_record(bad) != []
    bad2 = trecords.make_record(1, {})
    bad2["extra"] = 1
    assert any("unexpected" in p for p in trecords.validate_record(bad2))

  def test_reader_normalizes_legacy_flat_records(self, tmp_path):
    path = tmp_path / "metrics_train.jsonl"
    path.write_text(
        json.dumps({"step": 5, "loss": 1.0}) + "\n" +
        json.dumps(trecords.make_record(10, {"loss": 0.5})) + "\n")
    records = trecords.read_records(str(path))
    assert [r["step"] for r in records] == [5, 10]
    assert [r["loss"] for r in records] == [1.0, 0.5]

  def test_metric_logger_emits_envelope(self, tmp_path):
    from tensor2robot_tpu.train_eval import MetricLogger

    logger = MetricLogger(str(tmp_path), role="anakin")
    logger.write("train", 4, {"loss": np.float32(0.25)})
    logger.close()
    raw = [json.loads(line) for line in
           open(tmp_path / "metrics_train.jsonl")]
    assert len(raw) == 1
    assert trecords.validate_record(raw[0]) == []
    assert raw[0]["role"] == "anakin"
    assert raw[0]["payload"] == {"loss": 0.25}


class TestMerge:

  def _write_trace(self, path, role, pid, offset, spans):
    with open(path, "w") as f:
      f.write(json.dumps({"ph": "M", "role": role, "pid": pid,
                          "wall0": 0.0, "mono0": 0.0,
                          "clock_offset": offset}) + "\n")
      for name, ts, dur in spans:
        f.write(json.dumps({"ph": "X", "name": name, "ts": ts,
                            "dur": dur, "pid": pid, "tid": 1,
                            "role": role}) + "\n")

  def test_cross_process_merge_ordering_with_offsets(self, tmp_path):
    """Two processes with skewed clocks: the merge subtracts each
    file's handshake offset, so the timeline interleaves in HOST-clock
    order — the property that makes 'is the learner input-starved or
    the host slow' answerable from one screen."""
    # Host clock: events at host-times 1.0, 3.0. The actor's clock
    # runs 10s AHEAD (offset +10): its local stamps 12.0, 14.0 are
    # host-times 2.0, 4.0 — so the true order is h1, a1, h2, a2.
    self._write_trace(tmp_path / "trace_host.jsonl", "host", 100, 0.0,
                      [("h1", 1.0, 0.1), ("h2", 3.0, 0.1)])
    self._write_trace(tmp_path / "trace_actor-0.jsonl", "actor-0",
                      200, 10.0,
                      [("a1", 12.0, 0.1), ("a2", 14.0, 0.1)])
    trace = merge_lib.merge_traces(str(tmp_path))
    assert sorted(merge_lib.roles_in(trace)) == ["actor-0", "host"]
    timed = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    assert [e["name"] for e in timed] == ["h1", "a1", "h2", "a2"]
    # ts are µs relative to the earliest corrected span and sorted.
    ts = [e["ts"] for e in timed]
    assert ts[0] == 0.0 and ts == sorted(ts)
    assert ts[1] == pytest.approx(1e6)
    # Roles render as process names for Perfetto.
    names = {e["pid"]: e["args"]["name"]
             for e in trace["traceEvents"] if e["name"] == "process_name"}
    assert names == {100: "host", 200: "actor-0"}

  def test_restart_keeps_per_incarnation_offsets(self, tmp_path):
    """Two meta lines in ONE file (a restarted role appending): each
    span uses the offset most recently stamped above it."""
    path = tmp_path / "trace_actor-0.jsonl"
    with open(path, "w") as f:
      f.write(json.dumps({"ph": "M", "role": "actor-0", "pid": 1,
                          "clock_offset": 5.0}) + "\n")
      f.write(json.dumps({"ph": "X", "name": "old", "ts": 10.0,
                          "dur": 0.1, "pid": 1, "tid": 1,
                          "role": "actor-0"}) + "\n")
      f.write(json.dumps({"ph": "M", "role": "actor-0", "pid": 2,
                          "clock_offset": 7.0}) + "\n")
      f.write(json.dumps({"ph": "X", "name": "new", "ts": 13.0,
                          "dur": 0.1, "pid": 2, "tid": 1,
                          "role": "actor-0"}) + "\n")
    trace = merge_lib.merge_traces(str(tmp_path))
    timed = {e["name"]: e["ts"]
             for e in trace["traceEvents"] if e["ph"] == "X"}
    # old: 10-5=5, new: 13-7=6 → old is t0, new lands 1s later.
    assert timed["old"] == 0.0
    assert timed["new"] == pytest.approx(1e6)

  def test_merge_cli_writes_summary_and_file(self, tmp_path):
    self._write_trace(tmp_path / "trace_learner.jsonl", "learner", 9,
                      0.0, [("step", 0.5, 0.2)])
    out = tmp_path / "merged.json"
    result = subprocess.run(
        [sys.executable, "-m", "tensor2robot_tpu.telemetry.merge",
         "--trace-dir", str(tmp_path), "--out", str(out)],
        capture_output=True, text=True, timeout=120, cwd=REPO)
    assert result.returncode == 0, result.stderr
    summary = json.loads(result.stdout.strip())
    assert summary["roles"] == ["learner"]
    assert summary["span_count"] == 1
    merged = json.load(open(out))
    assert merged["metadata"]["span_count"] == 1


class TestJaxFreeImport:

  def test_telemetry_package_imports_without_jax(self):
    # The worker-safe property (IMP401): actors and data-plane workers
    # import the WHOLE telemetry package at spawn.
    code = (
        "import sys; "
        "import tensor2robot_tpu.telemetry; "
        "import tensor2robot_tpu.telemetry.core, "
        "tensor2robot_tpu.telemetry.metrics, "
        "tensor2robot_tpu.telemetry.records, "
        "tensor2robot_tpu.telemetry.flightrec, "
        "tensor2robot_tpu.telemetry.merge; "
        "assert 'jax' not in sys.modules, 'jax leaked'; "
        "print('JAXFREE')")
    result = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=120, cwd=REPO)
    assert result.returncode == 0, result.stderr
    assert "JAXFREE" in result.stdout

  def test_telemetry_is_in_t2rcheck_scopes(self):
    from tensor2robot_tpu.analysis import cli
    from tensor2robot_tpu.analysis import import_rules

    assert "tensor2robot_tpu/telemetry" in cli._CONCURRENCY_PATHS
    assert "tensor2robot_tpu.telemetry" in \
        import_rules.WORKER_SAFE_MODULES


class TestFlightRecorder:

  def test_dump_and_read(self, tmp_path):
    tcore.configure("host")
    with tcore.span("last_op", key=1):
      pass
    tmetrics.counter("replay.adds").inc(5)
    path = flightrec.dump(str(tmp_path), "test latch",
                          extra={"who": "me"})
    assert path
    dumps = flightrec.read_dumps(str(tmp_path))
    assert len(dumps) == 1
    dump = dumps[0]
    assert dump["reason"] == "test latch"
    assert dump["role"] == "host"
    assert dump["extra"] == {"who": "me"}
    assert any(s["name"] == "last_op" for s in dump["spans"])
    assert dump["metrics"]["counters"]["replay.adds"] == 5.0

  @pytest.mark.slow
  def test_flight_record_on_latched_fleet_error(self, tmp_path):
    """The crash-policy harness (tests/test_fleet.py): an injected
    learner crash latches a FleetError — and now every reachable
    process leaves a flight record: the dying learner (its own except
    path), the still-live host (the orchestrator's flight_record RPC),
    and the orchestrator itself (heartbeat ages + restart counts)."""
    from tensor2robot_tpu.fleet import Fleet, FleetConfig, FleetError

    config = FleetConfig(
        num_actors=2, env="toy_grasp", image_size=16, action_dim=2,
        torso_filters=(8,), head_filters=(8,), dense_sizes=(16,),
        cem_population=8, cem_iterations=1, cem_elites=2,
        batch_size=16, max_train_steps=16, min_replay_size=32,
        publish_every_steps=8, log_every_steps=8,
        batch_episodes=8, serve_max_batch=4,
        replay_capacity=512, replay_shards=1,
        heartbeat_timeout_secs=0.0, launch_timeout_secs=240.0,
        # Short leash: the learner crashes at step 4, so the normal
        # path is ~20s — a wedged run must fail fast instead of
        # eating the tier-1 budget.
        run_timeout_secs=180.0, seed=0,
        learner_crash_after_steps=4)
    model_dir = str(tmp_path / "fleet")
    fleet = Fleet(config, model_dir)
    with pytest.raises(FleetError, match="learner died"):
      fleet.run()
    dumps = flightrec.read_dumps(
        flightrec.flightrec_dir(model_dir))
    by_role = {d["role"]: d for d in dumps}
    assert "learner" in by_role, f"roles: {sorted(by_role)}"
    assert "injected learner crash" in by_role["learner"]["reason"]
    # The learner's last spans survived (the train loop records one
    # per dispatch).
    assert any(s["name"] == "qtopt.dispatch"
               for s in by_role["learner"]["spans"])
    assert "orchestrator" in by_role
    orch = by_role["orchestrator"]
    assert "learner died" in orch["reason"]
    assert "t2r-fleet-learner" in orch["extra"]["heartbeat_ages_secs"]
    assert "host" in by_role
    assert by_role["host"]["metrics"]["counters"].get(
        "replay.adds", 0.0) > 0.0
    # The run's traces survived too — the post-mortem timeline merges.
    trace = merge_lib.merge_traces(
        os.path.join(model_dir, "telemetry"))
    assert "learner" in merge_lib.roles_in(trace)


@pytest.mark.slow
class TestEnvelopeFromTrainers:
  """Schema validation over records the REAL trainers produce (the
  tier-1 smoke configs): trainer + qtopt-learner loops both emit the
  unified envelope. (The anakin producer is covered at tier-1 by
  TestRecordEnvelope.test_metric_logger_emits_envelope — its logger is
  MetricLogger(role='anakin') — and at tier-2 by the full run here.)"""

  def _validate_file(self, path, expected_role):
    raw = [json.loads(line) for line in open(path)]
    assert raw
    for record in raw:
      assert trecords.validate_record(record) == [], record
      assert record["role"] == expected_role
      assert record["wall"] > 0

  def test_train_eval_and_qtopt_records_are_enveloped(self, tmp_path):
    from tensor2robot_tpu import train_eval
    from tensor2robot_tpu.data import RandomInputGenerator
    from tensor2robot_tpu.research.qtopt import (
        GraspingQModel,
        QTOptLearner,
    )
    from tensor2robot_tpu.research.qtopt.train_qtopt import train_qtopt
    from tensor2robot_tpu.utils.mocks import MockT2RModel

    supervised = str(tmp_path / "supervised")
    train_eval.train_eval_model(
        model=MockT2RModel(),
        model_dir=supervised,
        input_generator_train=RandomInputGenerator(batch_size=8),
        input_generator_eval=RandomInputGenerator(batch_size=8),
        max_train_steps=4, eval_steps=1, save_checkpoints_steps=4,
        log_every_steps=2)
    self._validate_file(
        os.path.join(supervised, "metrics_train.jsonl"), "trainer")
    self._validate_file(
        os.path.join(supervised, "metrics_eval.jsonl"), "trainer")

    qtopt_dir = str(tmp_path / "qtopt")
    learner = QTOptLearner(
        GraspingQModel(image_size=16, torso_filters=(8,),
                       head_filters=(8,), dense_sizes=(16,),
                       action_dim=2),
        cem_population=8, cem_iterations=1, cem_elites=2)
    train_qtopt(learner=learner, model_dir=qtopt_dir,
                prefill_random=True, max_train_steps=4, batch_size=8,
                log_every_steps=2, save_checkpoints_steps=4, seed=0)
    self._validate_file(
        os.path.join(qtopt_dir, "metrics_train.jsonl"), "trainer")
    # The compile-cache tap surfaced in the ordinary train log (the
    # CompileWatch gap, closed): the first interval records the
    # trace-time compile requests.
    records = trecords.read_records(
        os.path.join(qtopt_dir, "metrics_train.jsonl"))
    assert "compile_cache.requests" in records[-1]


class TestPrometheusAdapter:
  """The Prometheus text-format endpoint (ISSUE 12 satellite): a
  ~50-line adapter over `MetricsRegistry.snapshot()` — counters as
  `_total`, gauges verbatim, histograms as CUMULATIVE `le` buckets
  closed by `+Inf`, names sanitized to the exposition charset."""

  def _publish(self):
    tmetrics.counter("replay.add_rows").inc(7)
    tmetrics.gauge("serving.queue_depth").set(3.0)
    hist = tmetrics.histogram("serving.bucket_8_ms",
                              bounds=(1.0, 10.0))
    hist.observe(0.5)
    hist.observe(5.0)
    hist.observe(50.0)

  def test_render_scrape_format(self):
    from tensor2robot_tpu.telemetry import prometheus

    self._publish()
    body = prometheus.render_text()
    lines = body.splitlines()
    # Counters: sanitized (dots → underscores), `_total`-suffixed.
    assert "# TYPE t2r_replay_add_rows_total counter" in lines
    assert "t2r_replay_add_rows_total 7.0" in lines
    assert "# TYPE t2r_serving_queue_depth gauge" in lines
    assert "t2r_serving_queue_depth 3.0" in lines
    # Histogram: cumulative buckets, +Inf closes at total count.
    assert "# TYPE t2r_serving_bucket_8_ms histogram" in lines
    assert 't2r_serving_bucket_8_ms_bucket{le="1.0"} 1' in lines
    assert 't2r_serving_bucket_8_ms_bucket{le="10.0"} 2' in lines
    assert 't2r_serving_bucket_8_ms_bucket{le="+Inf"} 3' in lines
    assert "t2r_serving_bucket_8_ms_sum 55.5" in lines
    assert "t2r_serving_bucket_8_ms_count 3" in lines
    assert body.endswith("\n")

  def test_metric_names_sanitize_to_exposition_charset(self):
    import re

    from tensor2robot_tpu.telemetry import prometheus

    tmetrics.counter("fleet.actor-0.steps").inc()
    body = prometheus.render_text()
    name_re = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{|\s)")
    for line in body.splitlines():
      if line.startswith("#"):
        continue
      assert name_re.match(line), line
    assert "t2r_fleet_actor_0_steps_total 1.0" in body

  def _parse_exposition(self, body):
    """Minimal text-format (0.0.4) parser: returns
    ({family: type}, [(name, labels_dict, value)]). The unit tests run
    the rendered body through THIS instead of grepping lines, so label
    syntax and family grouping are checked structurally."""
    import re as _re

    line_re = _re.compile(
        r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{([^}]*)\})?\s+(\S+)$")
    label_re = _re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|'
                           r'\\.)*)"')
    types = {}
    samples = []
    for line in body.splitlines():
      if not line:
        continue
      if line.startswith("# TYPE "):
        _, _, family, kind = line.split(" ")
        assert family not in types, f"duplicate TYPE for {family}"
        types[family] = kind
        continue
      if line.startswith("#"):
        continue
      match = line_re.match(line)
      assert match, f"unparseable sample line: {line!r}"
      name, raw_labels, value = match.groups()
      labels = dict(label_re.findall(raw_labels or ""))
      samples.append((name, labels, float(value)))
    return types, samples

  def test_tenant_prefixes_render_as_labels(self):
    """ISSUE 13 satellite: `serving.<tenant>.*` metrics become ONE
    family per metric with a `tenant=` label; reserved serving
    namespaces (arena/front/admission) stay label-free."""
    from tensor2robot_tpu.telemetry import prometheus

    tmetrics.counter("serving.robotA.dispatches").inc(4)
    tmetrics.counter("serving.robotB.dispatches").inc(9)
    tmetrics.counter("serving.robotA.admission.dropped").inc(2)
    tmetrics.counter("serving.arena.loads").inc(3)
    tmetrics.counter("serving.dispatches").inc(13)  # front-wide total
    hist_bounds = (1.0, 10.0)
    tmetrics.histogram("serving.robotA.bucket_8_ms",
                       bounds=hist_bounds).observe(0.5)
    tmetrics.histogram("serving.robotB.bucket_8_ms",
                       bounds=hist_bounds).observe(5.0)
    tmetrics.gauge("serving.robotA.queue_depth").set(2.0)

    body = prometheus.render_text()
    types, samples = self._parse_exposition(body)

    def sample(name, **labels):
      rows = [value for n, l, value in samples
              if n == name and l == labels]
      assert len(rows) == 1, (name, labels, rows)
      return rows[0]

    # One family, two tenant series + the unlabeled front-wide total.
    assert types["t2r_serving_dispatches_total"] == "counter"
    assert sample("t2r_serving_dispatches_total", tenant="robotA") == 4
    assert sample("t2r_serving_dispatches_total", tenant="robotB") == 9
    assert sample("t2r_serving_dispatches_total") == 13
    # Nested tenant namespaces keep their tail.
    assert sample("t2r_serving_admission_dropped_total",
                  tenant="robotA") == 2
    # Reserved namespace: a POOL metric, not a tenant called "arena".
    assert sample("t2r_serving_arena_loads_total") == 3
    assert not [l for n, l, _ in samples
                if n == "t2r_serving_arena_loads_total" and l]
    # Gauges carry the label too.
    assert sample("t2r_serving_queue_depth", tenant="robotA") == 2.0
    # Histograms: per-tenant bucket series under one family/TYPE.
    assert types["t2r_serving_bucket_8_ms"] == "histogram"
    assert sample("t2r_serving_bucket_8_ms_bucket",
                  tenant="robotA", le="1.0") == 1
    assert sample("t2r_serving_bucket_8_ms_bucket",
                  tenant="robotB", le="1.0") == 0
    assert sample("t2r_serving_bucket_8_ms_bucket",
                  tenant="robotB", le="+Inf") == 1
    assert sample("t2r_serving_bucket_8_ms_count",
                  tenant="robotA") == 1
    assert sample("t2r_serving_bucket_8_ms_sum",
                  tenant="robotB") == 5.0

  def test_two_segment_serving_names_stay_unlabeled(self):
    # `serving.bucket_8_ms` / `serving.microbatch_rows` (the
    # single-model engine's names) have no tenant segment and must
    # render exactly as before the label feature.
    from tensor2robot_tpu.telemetry import prometheus

    tmetrics.histogram("serving.bucket_8_ms",
                       bounds=(1.0, 10.0)).observe(0.5)
    tmetrics.gauge("serving.microbatch_queue_depth").set(1.0)
    body = prometheus.render_text()
    assert 't2r_serving_bucket_8_ms_bucket{le="1.0"} 1' in body
    assert "t2r_serving_microbatch_queue_depth 1.0" in body

  def test_http_endpoint_scrapes_live_registry(self):
    import urllib.request

    from tensor2robot_tpu.telemetry import prometheus

    self._publish()
    endpoint = prometheus.serve(port=0)
    try:
      url = f"http://127.0.0.1:{endpoint.port}/metrics"
      with urllib.request.urlopen(url, timeout=5) as resp:
        assert resp.status == 200
        assert resp.headers["Content-Type"].startswith("text/plain")
        body = resp.read().decode("utf-8")
      assert "t2r_replay_add_rows_total 7.0" in body
      # Scrape-time snapshot: a later publish shows on the NEXT pull.
      tmetrics.counter("replay.add_rows").inc(1)
      with urllib.request.urlopen(url, timeout=5) as resp:
        assert "t2r_replay_add_rows_total 8.0" in resp.read().decode(
            "utf-8")
      with pytest.raises(urllib.error.HTTPError):
        urllib.request.urlopen(
            f"http://127.0.0.1:{endpoint.port}/other", timeout=5)
    finally:
      endpoint.close()
