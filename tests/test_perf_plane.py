"""Always-on performance plane tests (ISSUE 15): live MFU attribution,
resource watermarks, the alert sentinel, trace-flow correlation, and
the run report — pinned contracts:

  * live `perf.mfu` equals bench MFU for the same config/denominator
    within 1e-6 relative: both ride `utils.profiling.analytic_flops`
    (bench re-imports it) and `telemetry.perf.mfu_value`, published by
    all three trainers incl. the pod modes (device-count aware);
  * sentinel semantics: EWMA warmup never fires, a sustained breach
    fires exactly once (hysteresis) and re-arms on recovery, a
    page-severity breach in a REAL 2-actor fleet (slow_host stimulus
    through the ISSUE-14 fault seams) produces flight records;
  * the resource sampler publishes rsrc.* gauges with monotone peak
    watermarks and never raises out of a broken source;
  * fleet RPC spans correlate client↔server by `req` id as Perfetto
    flow events in the merged timeline;
  * the report CLI folds a run dir into one markdown page (smoke
    against a synthetic run; tier1.sh runs it against the committed
    artifacts/telemetry merged trace).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from tensor2robot_tpu.telemetry import core as tcore
from tensor2robot_tpu.telemetry import merge as merge_lib
from tensor2robot_tpu.telemetry import metrics as tmetrics
from tensor2robot_tpu.telemetry import perf as perf_lib
from tensor2robot_tpu.telemetry import sentinel as sentinel_lib

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PEAK = 1.0e12  # the test roofline (CPU has no table entry)


@pytest.fixture(autouse=True)
def _isolated_telemetry():
  tcore.reset_for_tests()
  tmetrics.reset_for_tests()
  perf_lib.stop_resource_sampler()
  perf_lib.set_plane_enabled(None)
  yield
  perf_lib.stop_resource_sampler()
  perf_lib.set_plane_enabled(None)
  tcore.reset_for_tests()
  tmetrics.reset_for_tests()


def _expected_mfu(record, flops, devices):
  return perf_lib.mfu_value(record["grad_steps_per_sec"], flops,
                            PEAK, devices=devices)


class TestSharedDenominator:
  """One MFU code path: bench's and the live gauges' (the ISSUE-15
  shared-path pin)."""

  def test_bench_reexports_profiling_analytic_flops(self):
    import bench
    from tensor2robot_tpu.utils import profiling
    assert bench.analytic_flops is profiling.analytic_flops
    assert bench._same_conv_taps is profiling._same_conv_taps

  def test_profiling_mfu_delegates_to_perf_formula(self, monkeypatch):
    from tensor2robot_tpu.utils import profiling
    monkeypatch.setenv("T2R_PEAK_FLOPS_OVERRIDE", str(PEAK))
    for rate, flops in ((12.5, 3.1e9), (700.0, 1.0e8)):
      assert profiling.mfu(rate, flops) == perf_lib.mfu_value(
          rate, flops, PEAK)

  def test_mfu_value_devices_and_unknowables(self):
    assert perf_lib.mfu_value(10.0, 1e9, 1e12) == pytest.approx(0.01)
    # Device-count aware: peak scales, MFU stays per-chip.
    assert perf_lib.mfu_value(10.0, 4e9, 1e12, devices=4) == (
        pytest.approx(0.01))
    assert perf_lib.mfu_value(10.0, None, 1e12) is None
    assert perf_lib.mfu_value(10.0, 1e9, None) is None


class TestPerfMeter:

  def test_publish_sets_gauges_and_busy_fraction(self):
    import time
    meter = perf_lib.PerfMeter(flops_per_step=100.0, peak_flops=1e3,
                               devices=2, enabled=True)
    with meter.dispatch("x.dispatch"):
      time.sleep(0.01)
    out = meter.publish(steps_per_sec=5.0, interval_secs=0.1)
    assert out["perf.flops_per_sec"] == pytest.approx(500.0)
    assert out["perf.mfu"] == pytest.approx(5.0 * 100.0 / (1e3 * 2))
    assert 0.0 < out["perf.device_time_fraction"] <= 1.0
    gauges = tmetrics.registry().snapshot()["gauges"]
    assert gauges["perf.mfu"] == pytest.approx(out["perf.mfu"])
    # The accumulator resets per interval.
    out2 = meter.publish(5.0, 0.1)
    assert out2["perf.device_time_fraction"] == 0.0

  def test_unknown_peak_publishes_no_mfu(self):
    meter = perf_lib.PerfMeter(flops_per_step=100.0, peak_flops=None,
                               enabled=True)
    out = meter.publish(5.0, 0.1)
    assert "perf.mfu" not in out
    assert "perf.flops_per_sec" in out
    assert "perf.device_time_fraction" in out

  def test_disabled_plane_publishes_nothing(self):
    meter = perf_lib.PerfMeter(flops_per_step=100.0, peak_flops=1e3,
                               enabled=False)
    assert meter.publish(5.0, 0.1) == {}
    assert tmetrics.registry().snapshot()["gauges"] == {}


class TestResourceSampler:

  def test_rss_and_peak_watermarks(self):
    sampler = perf_lib.ResourceSampler(watched_gauges=())
    sampler.sample_once()
    gauges = tmetrics.registry().snapshot()["gauges"]
    assert gauges["rsrc.host_rss_bytes"] > 0
    assert gauges["rsrc.host_rss_bytes_peak"] >= (
        gauges["rsrc.host_rss_bytes"] * 0.99)

  def test_watched_gauge_peak_is_monotone(self):
    fill = tmetrics.gauge("replay.fill")
    sampler = perf_lib.ResourceSampler(
        sources=[lambda: {}], watched_gauges=("replay.fill",))
    for value in (0.2, 0.9, 0.4):
      fill.set(value)
      sampler.sample_once()
    gauges = tmetrics.registry().snapshot()["gauges"]
    assert gauges["rsrc.replay.fill_peak"] == pytest.approx(0.9)

  def test_broken_source_is_skipped_not_raised(self):
    def broken():
      raise RuntimeError("boom")

    sampler = perf_lib.ResourceSampler(
        sources=[broken, lambda: {"ok": 1.0}], watched_gauges=())
    sampler.sample_once()  # must not raise
    assert tmetrics.registry().snapshot()["gauges"]["rsrc.ok"] == 1.0

  def test_process_singleton_respects_plane_switch(self):
    perf_lib.set_plane_enabled(False)
    assert perf_lib.start_resource_sampler() is None
    perf_lib.set_plane_enabled(True)
    sampler = perf_lib.start_resource_sampler()
    assert sampler is not None
    assert perf_lib.start_resource_sampler() is sampler  # idempotent
    perf_lib.stop_resource_sampler()


class TestSentinelSemantics:

  def test_ewma_warmup_never_fires(self):
    watch = sentinel_lib.Watch(name="w", metric="m", kind="ewma_drop",
                               threshold=0.2, warmup=5, sustain=1)
    sentinel = sentinel_lib.Sentinel([watch])
    # Five warmup evaluations on a COLLAPSING value: still no fire.
    for value in (1.0, 0.5, 0.1, 0.01, 0.001):
      assert sentinel.evaluate({"m": value}) == []

  def test_sustained_breach_fires_once_with_hysteresis(self):
    watch = sentinel_lib.Watch(name="w", metric="m", kind="ewma_drop",
                               threshold=0.2, warmup=2, sustain=2)
    sentinel = sentinel_lib.Sentinel([watch])
    fired = [len(sentinel.evaluate({"m": value}))
             for value in (1.0, 1.0,          # warmup
                           0.5, 0.5, 0.5, 0.5,  # breach sustained
                           1.0,                # recovery re-arms
                           0.5, 0.5)]          # second event train
    # One alert per sustained event train, at the sustain threshold.
    assert fired == [0, 0, 0, 1, 0, 0, 0, 0, 1]
    counters = tmetrics.registry().snapshot()["counters"]
    assert counters["alert.fired"] == 2.0
    assert counters["alert.w"] == 2.0

  def test_baseline_absorbs_only_healthy_values(self):
    watch = sentinel_lib.Watch(name="w", metric="m", kind="ewma_drop",
                               threshold=0.2, warmup=1, sustain=10 ** 6)
    sentinel = sentinel_lib.Sentinel([watch])
    sentinel.evaluate({"m": 1.0})
    for _ in range(50):  # a sustained breach never reaching sustain
      sentinel.evaluate({"m": 0.5})
    state = sentinel._states[("w", "m")]
    assert state.ewma == pytest.approx(1.0)  # not dragged down

  def test_increase_kind_counts_warm_increments(self):
    watch = sentinel_lib.Watch(name="recompile",
                               metric="compile_cache.misses",
                               kind="increase", warmup=1, sustain=1)
    sentinel = sentinel_lib.Sentinel([watch])
    fired = [len(sentinel.evaluate({"compile_cache.misses": value}))
             for value in (3.0, 3.0, 4.0, 4.0, 6.0)]
    # First evaluation is the cold-compile baseline; each later
    # increment is one warm-path recompile alert.
    assert fired == [0, 0, 1, 0, 1]

  def test_role_prefixed_metric_names_the_role(self, tmp_path):
    watch = sentinel_lib.Watch(name="timeouts",
                               metric="fleet.rpc.timeouts",
                               kind="above", threshold=0.0, warmup=0)
    alerts_path = str(tmp_path / "alerts.jsonl")
    sentinel = sentinel_lib.Sentinel([watch], alerts_path=alerts_path)
    fired = sentinel.evaluate({"actor-1/fleet.rpc.timeouts": 2.0})
    assert [a["role"] for a in fired] == ["actor-1"]
    sentinel.close()
    read = sentinel_lib.read_alerts(alerts_path)
    assert len(read) == 1 and read[0]["metric"] == (
        "actor-1/fleet.rpc.timeouts")

  def test_page_severity_invokes_hook_once(self):
    pages = []
    watch = sentinel_lib.Watch(name="p", metric="m", kind="above",
                               threshold=1.0, warmup=0,
                               severity="page")
    sentinel = sentinel_lib.Sentinel([watch], on_page=pages.append)
    for value in (2.0, 2.0, 2.0):
      sentinel.evaluate({"m": value})
    assert len(pages) == 1 and pages[0]["rule"] == "p"

  def test_watch_validation(self):
    with pytest.raises(ValueError):
      sentinel_lib.Watch(name="x", metric="m", kind="sideways")
    with pytest.raises(ValueError):
      sentinel_lib.Watch(name="x", metric="m", severity="shrug")


def _read_perf_record(model_dir):
  from tensor2robot_tpu.telemetry.records import read_records
  records = read_records(os.path.join(model_dir, "metrics_train.jsonl"))
  assert records
  record = records[-1]
  assert "perf.device_time_fraction" in record
  assert 0.0 <= record["perf.device_time_fraction"] <= 1.0
  return record


class TestTrainerLiveMfu:
  """The acceptance pin: live perf.mfu == bench MFU (same config,
  same denominator) within 1e-6 relative, all three trainers, pod
  modes device-count aware."""

  def test_train_qtopt_live_mfu_matches_bench_formula(
      self, tmp_path, monkeypatch):
    import jax

    from tensor2robot_tpu.research.qtopt import (
        GraspingQModel,
        QTOptLearner,
    )
    from tensor2robot_tpu.research.qtopt.train_qtopt import train_qtopt
    from tensor2robot_tpu.utils import profiling

    monkeypatch.setenv("T2R_PEAK_FLOPS_OVERRIDE", str(PEAK))
    learner = QTOptLearner(
        GraspingQModel(image_size=16, torso_filters=(8,),
                       head_filters=(8,), dense_sizes=(16,),
                       action_dim=2),
        cem_population=8, cem_iterations=1, cem_elites=2)
    batch = 16
    state = train_qtopt(
        learner=learner, model_dir=str(tmp_path), prefill_random=True,
        max_train_steps=32, batch_size=batch, log_every_steps=16,
        save_checkpoints_steps=32, seed=0)
    record = _read_perf_record(str(tmp_path))
    # Bench's formula over bench's denominator — the exact same
    # analytic_flops call bench_config makes, devices = the mesh.
    flops = profiling.analytic_flops(
        "qtopt_step", learner=learner, batch_size=batch,
        params=state.train_state.params)
    expected = _expected_mfu(record, flops, jax.device_count())
    assert record["perf.mfu"] == pytest.approx(expected, rel=1e-6)
    assert record["perf.flops_per_sec"] == pytest.approx(
        record["grad_steps_per_sec"] * flops, rel=1e-6)

  # pmap at num_devices=0 = the FULL 8-virtual-device conftest mesh
  # (the acceptance criterion's pod mode); shard_map at 2 bounds the
  # compile bill while pinning the second pod substrate.
  @pytest.mark.parametrize("pod_program,num_devices",
                           [("pmap", 0), ("shard_map", 2)])
  def test_train_anakin_pod_live_mfu_device_count_aware(
      self, tmp_path, monkeypatch, pod_program, num_devices):
    import jax

    from tensor2robot_tpu.envs import train_anakin
    from tensor2robot_tpu.research.qtopt import (
        GraspingQModel,
        QTOptLearner,
    )
    from tensor2robot_tpu.utils import profiling

    monkeypatch.setenv("T2R_PEAK_FLOPS_OVERRIDE", str(PEAK))
    learner = QTOptLearner(
        GraspingQModel(image_size=16, torso_filters=(8,),
                       head_filters=(8,), dense_sizes=(16,),
                       action_dim=2),
        cem_population=8, cem_iterations=1, cem_elites=2)
    batch = 16
    d = num_devices or jax.local_device_count()
    kwargs = dict(env_family="pose", num_envs=16, rollout_length=2,
                  train_batches_per_iter=4, batch_size=batch,
                  replay_capacity=256, max_train_steps=16,
                  log_every_steps=8, save_checkpoints_steps=16,
                  seed=0, num_devices=num_devices,
                  pod_program=pod_program)
    if pod_program == "shard_map":
      kwargs["sharding_rules"] = "qtopt"
    state = train_anakin(learner=learner,
                         model_dir=str(tmp_path / pod_program),
                         **kwargs)
    record = _read_perf_record(str(tmp_path / pod_program))
    # Per-device analytic count × D over peak × D: MFU stays the
    # per-chip fraction at any pod size.
    flops = profiling.analytic_flops(
        "qtopt_step", learner=learner, batch_size=batch,
        params=state.train_state.params) * d
    expected = _expected_mfu(record, flops, d)
    assert record["perf.mfu"] == pytest.approx(expected, rel=1e-6)

  def test_train_eval_publishes_utilization(self, tmp_path,
                                            monkeypatch):
    import jax

    from tensor2robot_tpu.data import Mode, RandomInputGenerator
    from tensor2robot_tpu.train_eval import train_eval_model
    from tensor2robot_tpu.utils.mocks import MockT2RModel

    monkeypatch.setenv("T2R_PEAK_FLOPS_OVERRIDE", str(PEAK))
    train_eval_model(
        model=MockT2RModel(),
        model_dir=str(tmp_path),
        input_generator_train=RandomInputGenerator(batch_size=16),
        max_train_steps=20, log_every_steps=10,
        save_checkpoints_steps=20, eval_steps=0)
    record = _read_perf_record(str(tmp_path))
    if "perf.mfu" in record:
      # The generic trainer's denominator is XLA's count of the AOT
      # program; the FORMULA is still the one shared path —
      # mfu ≡ flops_per_sec / (peak × devices) by construction.
      assert record["perf.mfu"] == pytest.approx(
          record["perf.flops_per_sec"] / (PEAK * jax.device_count()),
          rel=1e-6)

  def test_quiet_tiny_run_fires_no_alerts(self, tmp_path):
    """Sentinel rides every trainer at log cadence; a healthy tiny
    run must write no alerts.jsonl."""
    from tensor2robot_tpu.research.qtopt import (
        GraspingQModel,
        QTOptLearner,
    )
    from tensor2robot_tpu.research.qtopt.train_qtopt import train_qtopt

    learner = QTOptLearner(
        GraspingQModel(image_size=16, torso_filters=(8,),
                       head_filters=(8,), dense_sizes=(16,),
                       action_dim=2),
        cem_population=8, cem_iterations=1, cem_elites=2)
    train_qtopt(learner=learner, model_dir=str(tmp_path),
                prefill_random=True, max_train_steps=32,
                batch_size=16, log_every_steps=8,
                save_checkpoints_steps=32, seed=0)
    assert sentinel_lib.read_alerts(
        str(tmp_path / "telemetry" / "alerts.jsonl")) == []


class TestRpcFlowCorrelation:

  def test_req_ids_link_client_and_server_spans(self, tmp_path):
    from tensor2robot_tpu.fleet.rpc import RpcClient, RpcServer

    tcore.configure("host", trace_dir=str(tmp_path))
    with RpcServer(lambda m, p, ctx: p, authkey=b"t") as server:
      with RpcClient(server.address, authkey=b"t") as client:
        for value in range(4):
          assert client.call("echo", value) == value
    tcore.get_tracer().close()
    trace = merge_lib.merge_traces(str(tmp_path))
    assert trace["metadata"]["rpc_flows"] == 4
    flows = [e for e in trace["traceEvents"]
             if e.get("cat") == "rpc_flow"]
    assert len(flows) == 8  # one s/f pair per call
    by_id = {}
    for event in flows:
      by_id.setdefault(event["id"], []).append(event["ph"])
    assert all(sorted(phs) == ["f", "s"] for phs in by_id.values())
    # The span args carry matching req ids on both sides.
    spans = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
    client_reqs = {e["args"]["req"] for e in spans
                   if e["name"] == "rpc_call.echo"}
    server_reqs = {e["args"]["req"] for e in spans
                   if e["name"] == "rpc.echo"}
    assert client_reqs == server_reqs and len(client_reqs) == 4

  def test_unpaired_req_emits_no_flow(self, tmp_path):
    tracer = tcore.Tracer().configure("solo", trace_dir=str(tmp_path))
    with tracer.span("rpc_call.lost", req="1-2-3"):
      pass
    tracer.close()
    trace = merge_lib.merge_traces(str(tmp_path))
    assert trace["metadata"]["rpc_flows"] == 0


class TestSentinelFleetE2E:
  """The page path against a REAL 2-actor fleet: one injected
  slow_host stall (ISSUE-14 fault seams) → the stalled client times
  out and recovers → the orchestrator's page-severity watch fires
  exactly one alert train → flight records land, role-named, exactly
  like the hang path's."""

  @pytest.mark.slow
  def test_slow_host_pages_with_flight_record(self, tmp_path):
    from tensor2robot_tpu import config as gin
    from tensor2robot_tpu.fleet import Fleet, FleetConfig
    from tensor2robot_tpu.fleet import faults as faults_lib
    from tensor2robot_tpu.telemetry import flightrec

    plan = faults_lib.FaultPlan(seed=3, events=(
        faults_lib.FaultEvent(
            fault=faults_lib.SLOW_HOST, target="host", at=4,
            duration_secs=3.0, method="sample"),))
    config = FleetConfig(
        num_actors=2, env="pose", image_size=16, action_dim=2,
        torso_filters=(8,), head_filters=(8,), dense_sizes=(16,),
        cem_population=8, cem_iterations=1, cem_elites=2,
        batch_size=16, max_train_steps=16, min_replay_size=32,
        publish_every_steps=8, log_every_steps=8, batch_episodes=8,
        serve_max_batch=4, replay_capacity=512, replay_shards=2,
        heartbeat_timeout_secs=0.0, launch_timeout_secs=240.0,
        run_timeout_secs=600.0, telemetry_poll_secs=0.5,
        rpc_call_timeout_secs=1.0, rpc_max_retries=2,
        fault_plan=plan, seed=0)
    gin.bind_parameter("fleet_watches.rpc_timeout_severity", "page")
    try:
      Fleet(config, str(tmp_path)).run()
    finally:
      gin.clear_config()
    alerts = sentinel_lib.read_alerts(
        str(tmp_path / "telemetry" / "alerts.jsonl"))
    timeout_alerts = [a for a in alerts
                      if a["rule"] == "rpc_timeouts"]
    assert len(timeout_alerts) == 1, alerts
    alert = timeout_alerts[0]
    assert alert["severity"] == "page"
    assert alert["role"] in ("learner", "actor-0", "actor-1")
    dumps = flightrec.read_dumps(flightrec.flightrec_dir(
        str(tmp_path)))
    page_dumps = [d for d in dumps
                  if "sentinel page" in str(d.get("reason", ""))]
    # The orchestrator's own view (heartbeat ages, restart counts —
    # the hang path's exact artifact shape) plus the host's ring.
    roles = {d["role"] for d in page_dumps}
    assert "orchestrator" in roles, dumps
    assert "host" in roles, dumps
    orch = next(d for d in page_dumps if d["role"] == "orchestrator")
    assert alert["role"] in orch["reason"]  # names the offender
    assert "heartbeat_ages_secs" in orch.get("extra", {})


class TestReportCli:

  def _synthetic_run(self, tmp_path):
    from tensor2robot_tpu.telemetry import records as trecords
    run = tmp_path / "run"
    run.mkdir()
    with open(run / "metrics_train.jsonl", "w") as f:
      for step in (10, 20, 30):
        record = trecords.make_record(step, {
            "grad_steps_per_sec": 100.0 + step,
            "perf.mfu": 0.2 + step / 1000.0,
            "perf.device_time_fraction": 0.8,
            "rsrc.host_rss_bytes_peak": 1.0e9,
        }, role="trainer", wall=1000.0 + step)
        f.write(json.dumps(record) + "\n")
    with open(run / "alerts.jsonl", "w") as f:
      f.write(json.dumps({
          "rule": "mfu_drop", "metric": "perf.mfu",
          "role": "trainer", "value": 0.1, "baseline": 0.22,
          "threshold": 0.25, "kind": "ewma_drop",
          "severity": "warn", "wall": 1020.0}) + "\n")
    tracer = tcore.Tracer().configure("trainer",
                                      trace_dir=str(run))
    with tracer.span("qtopt.dispatch", step=1):
      pass
    tracer.close()
    return run

  def test_report_builds_and_renders_all_sections(self, tmp_path):
    from tensor2robot_tpu.telemetry import report as report_lib

    run = self._synthetic_run(tmp_path)
    report = report_lib.build_report(str(run))
    assert report["metrics"]["train"]["mfu"]["last"] == (
        pytest.approx(0.23))
    assert report["watermarks"]["rsrc.host_rss_bytes_peak"] == 1.0e9
    assert [a["rule"] for a in report["alerts"]] == ["mfu_drop"]
    assert report["span_summary"][0]["span"] == "qtopt.dispatch"
    markdown = report_lib.render_markdown(report)
    for heading in ("## Rates", "## MFU timeline (train)",
                    "## Resource watermarks", "## Alerts",
                    "## Span summary"):
      assert heading in markdown, heading
    assert "alert.mfu_drop" in markdown

  def test_report_cli_smoke(self, tmp_path):
    run = self._synthetic_run(tmp_path)
    out_md = tmp_path / "report.md"
    out_json = tmp_path / "report.json"
    result = subprocess.run(
        [sys.executable, "-m", "tensor2robot_tpu.telemetry.report",
         "--run-dir", str(run), "--out", str(out_md),
         "--json", str(out_json)],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, timeout=120)
    assert result.returncode == 0, result.stderr
    markdown = out_md.read_text()
    assert "# Run report" in markdown and "## Alerts" in markdown
    loaded = json.loads(out_json.read_text())
    assert loaded["alerts"] and loaded["metrics"]["train"]["records"] == 3

  def test_report_cli_empty_dir_exits_nonzero(self, tmp_path):
    empty = tmp_path / "empty"
    empty.mkdir()
    result = subprocess.run(
        [sys.executable, "-m", "tensor2robot_tpu.telemetry.report",
         "--run-dir", str(empty)],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, timeout=120)
    assert result.returncode == 1

  def test_report_reads_premerged_gz_trace(self, tmp_path):
    """The committed artifacts/telemetry layout: only a merged .gz
    timeline — the report must still render a span summary (the
    tier1.sh smoke's in-process twin)."""
    import gzip

    from tensor2robot_tpu.telemetry import report as report_lib

    run = tmp_path / "artifacts"
    run.mkdir()
    trace = {"traceEvents": [
        {"ph": "X", "name": "rpc.act", "cat": "host", "ts": 0.0,
         "dur": 1500.0, "pid": 1, "tid": 1}]}
    with gzip.open(run / "fleet_trace.json.gz", "wt") as f:
      json.dump(trace, f)
    report = report_lib.build_report(str(run))
    assert report["span_summary"] == [
        {"role": "host", "span": "rpc.act", "count": 1,
         "total_ms": 1.5, "mean_ms": 1.5}]
    assert report_lib.has_content(report)


class TestGoodputGauge:

  def test_front_publishes_per_tenant_goodput(self):
    """The serving front's completion loop feeds the goodput window;
    pin the gauge arithmetic through the internal seam (the full
    open-loop path is bench_serving_front's job)."""
    from tensor2robot_tpu.serving import front as front_lib

    entry = front_lib._Tenant("tenA", max_queue=4, seed=0,
                              takes_rng=False)
    front = front_lib.ServingFront.__new__(front_lib.ServingFront)
    front._tenants = {"tenA": entry}
    front._goodput_rows = 30.0
    front._goodput_t0 = -1.0  # window long since open
    entry.goodput_rows = 10.0
    entry.goodput_t0 = -1.0
    front._roll_goodput_windows(now=1.0)
    gauges = tmetrics.registry().snapshot()["gauges"]
    assert gauges["serving.tenA.goodput_rows_per_sec"] == (
        pytest.approx(5.0))
    assert gauges["perf.goodput_rows_per_sec"] == pytest.approx(15.0)
    # Idle windows keep rolling: a later zero-row close decays the
    # gauge to 0 instead of freezing the burst value (review finding).
    front._roll_goodput_windows(now=3.0)
    gauges = tmetrics.registry().snapshot()["gauges"]
    assert gauges["serving.tenA.goodput_rows_per_sec"] == 0.0
    assert gauges["perf.goodput_rows_per_sec"] == 0.0
