"""Cross-host transport tests: the wire contract is proven, not assumed.

What ISSUE 16 pins:

  * FRAMING EDGES — partial reads (a dribbling sender), disconnect
    mid-frame (`EOFError`, the stdlib-connection signal rpc.py's
    retry machinery keys on), bad magic and oversized declared
    lengths (`FrameError` BEFORE allocation), send-side oversizes
    (`ValueError`, connection stays healthy);
  * ZERO-COPY — an 8 MiB array crosses bitwise-identical, arrives as
    a VIEW of the receive buffer (`np.shares_memory`), and both
    sides count 0 user-space payload copies;
  * AUTH — mutual HMAC handshake; a wrong key is rejected on both
    sides and never retried;
  * RPC PARITY — the deadline/retry/poisoning contract and the fault
    seams behave identically over "tcp" and "loopback" (same seeded
    FaultPlan, same recovery, digest unchanged);
  * SHARDED REPLAY MATH — rendezvous home-shard stability,
    proportional fan-out counts, shard-major concatenation;
  * BROADCAST TREE — the heap-layout children/depth mapping covers
    every host exactly once;
  * a 2-serving-host / 2-shard fleet runs END-TO-END over TCP with
    per-hop lag measured and a clean, zero-leak shutdown.
"""

from __future__ import annotations

import multiprocessing as mp
import socket
import threading
import time

import numpy as np
import pytest

from tensor2robot_tpu.fleet import actor as actor_lib
from tensor2robot_tpu.fleet import faults
from tensor2robot_tpu.fleet import rpc as rpc_lib
from tensor2robot_tpu.fleet import transport
from tensor2robot_tpu.fleet.orchestrator import (
    Fleet,
    FleetConfig,
    broadcast_children,
    broadcast_depths,
)
from tensor2robot_tpu.fleet.rpc import RpcClient, RpcError, RpcServer
from tensor2robot_tpu.replay.sampler import (
    concat_shard_major,
    shard_fanout_counts,
)
from tensor2robot_tpu.telemetry import metrics as tmetrics


@pytest.fixture(autouse=True)
def _fresh_registry():
  tmetrics.reset_for_tests()
  rpc_lib.set_fault_injector(None)
  yield
  rpc_lib.set_fault_injector(None)
  tmetrics.reset_for_tests()


def _conn_pair(**kwargs):
  a, b = socket.socketpair()
  return (transport.TcpConnection(a, **kwargs),
          transport.TcpConnection(b, **kwargs))


def _frame_bytes(obj) -> bytes:
  return b"".join(bytes(v) for v in transport.encode_frame(obj))


class TestWireFraming:

  def test_roundtrip_plain_objects(self):
    left, right = _conn_pair()
    try:
      for obj in ("ok", None, 17, {"a": [1, 2], "b": ("x", 3.5)}):
        left.send(obj)
        assert right.recv() == obj
    finally:
      left.close()
      right.close()

  def test_partial_reads_dribbling_sender(self):
    # TCP may deliver ONE byte per read; recv must reassemble the
    # frame across arbitrarily small fragments.
    raw, sock = socket.socketpair()
    conn = transport.TcpConnection(sock)
    payload = {"arr": np.arange(999, dtype=np.int32), "tag": "drip"}
    wire = _frame_bytes(payload)

    def dribble():
      for i in range(0, len(wire), 7):
        raw.sendall(wire[i:i + 7])
        if i < 140:  # pace the interesting region (header + lengths)
          time.sleep(0.001)

    thread = threading.Thread(target=dribble, daemon=True)
    thread.start()
    try:
      got = conn.recv()
      assert got["tag"] == "drip"
      np.testing.assert_array_equal(got["arr"], payload["arr"])
      thread.join(timeout=5.0)
    finally:
      raw.close()
      conn.close()

  def test_disconnect_mid_frame_raises_eof(self):
    raw, sock = socket.socketpair()
    conn = transport.TcpConnection(sock)
    wire = _frame_bytes({"x": np.zeros(4096, np.float64)})
    raw.sendall(wire[:len(wire) // 2])
    raw.close()
    try:
      with pytest.raises(EOFError):
        conn.recv()
    finally:
      conn.close()

  def test_bad_magic_raises_frame_error(self):
    raw, sock = socket.socketpair()
    conn = transport.TcpConnection(sock)
    raw.sendall(b"nope" + bytes(transport._HEADER.size - 4))
    try:
      with pytest.raises(transport.FrameError):
        conn.recv()
    finally:
      raw.close()
      conn.close()

  def test_oversized_declared_frame_rejected_before_allocation(self):
    raw, sock = socket.socketpair()
    conn = transport.TcpConnection(sock, max_frame_bytes=1 << 16)
    # A header declaring a 1 TiB body: the guard must fire on the
    # DECLARED length (allocating it would be the vulnerability).
    raw.sendall(transport._HEADER.pack(transport.MAGIC, 1 << 40, 0))
    try:
      with pytest.raises(transport.FrameError, match="declares"):
        conn.recv()
    finally:
      raw.close()
      conn.close()

  def test_send_side_oversize_is_value_error(self):
    left, right = _conn_pair(max_frame_bytes=1 << 12)
    try:
      with pytest.raises(ValueError, match="max_frame_bytes"):
        left.send(np.zeros(1 << 14, np.uint8))
      # The connection stays healthy: nothing hit the wire.
      left.send("still alive")
      assert right.recv() == "still alive"
    finally:
      left.close()
      right.close()

  def test_large_array_bitwise_with_zero_user_space_copies(self):
    # The ≤1-copy-per-side contract, PROVEN: the received array is a
    # VIEW of the connection's receive buffer (so the kernel→user
    # read was the only receive-side copy), and both instrumentation
    # counters report zero extra payload copies.
    rng = np.random.default_rng(7)
    payload = rng.random(1 << 20, np.float64)  # 8 MiB
    a, b = socket.socketpair()
    left = transport.TcpConnection(a)
    right = transport.TcpConnection(b, track_buffers=True)
    sender = threading.Thread(target=left.send, args=(payload,),
                              daemon=True)
    sender.start()
    try:
      got = right.recv()
      sender.join(timeout=30.0)
      assert got.dtype == payload.dtype and got.shape == payload.shape
      assert got.tobytes() == payload.tobytes()  # bitwise pin
      assert left.last_send_oob_copies == 0
      assert right.last_recv_oob_copies == 0
      assert len(right.last_recv_buffers) == 1
      backing = np.frombuffer(right.last_recv_buffers[0], np.uint8)
      assert np.shares_memory(got, backing)
    finally:
      left.close()
      right.close()

  def test_wire_counters_account_frames_and_buffers(self):
    left, right = _conn_pair()
    try:
      left.send(np.zeros(1024, np.float32))
      right.recv()
      snap = tmetrics.registry().snapshot()["counters"]
      assert snap["fleet.wire.frames_sent"] >= 1.0
      assert snap["fleet.wire.frames_received"] >= 1.0
      assert snap["fleet.wire.oob_buffers_sent"] >= 1.0
      assert snap["fleet.wire.bytes_sent"] > 4096.0
      assert snap["fleet.wire.bytes_sent"] == snap[
          "fleet.wire.bytes_received"]
    finally:
      left.close()
      right.close()


class TestHandshake:

  def test_mutual_auth_then_frames_flow(self):
    listener = transport.TcpListener(authkey=b"secret-1")
    accepted = []
    thread = threading.Thread(
        target=lambda: accepted.append(listener.accept()), daemon=True)
    thread.start()
    client = transport.connect_tcp(listener.address, b"secret-1")
    thread.join(timeout=10.0)
    try:
      assert accepted, "accept never completed"
      client.send({"n": 3})
      assert accepted[0].recv() == {"n": 3}
    finally:
      client.close()
      for conn in accepted:
        conn.close()
      listener.close()

  def test_wrong_key_rejected_both_sides(self):
    listener = transport.TcpListener(authkey=b"right-key")
    errors = []

    def accept_one():
      try:
        listener.accept()
      except Exception as e:  # noqa: BLE001
        errors.append(e)

    thread = threading.Thread(target=accept_one, daemon=True)
    thread.start()
    with pytest.raises(mp.AuthenticationError):
      transport.connect_tcp(listener.address, b"wrong-key")
    thread.join(timeout=10.0)
    listener.close()
    # The server saw the same mismatch — and as AuthenticationError,
    # never a bare OSError (which the rpc accept loop reads as
    # "listener closed" and would stop serving on).
    assert len(errors) == 1
    assert isinstance(errors[0], mp.AuthenticationError)

  def test_listener_requires_authkey(self):
    with pytest.raises(ValueError, match="authkey"):
      transport.TcpListener(authkey=b"")


class TestRpcOverTcp:

  def test_roundtrip_error_and_disconnect(self):
    seen = []

    def handler(method, payload, ctx):
      if method == rpc_lib.DISCONNECT_METHOD:
        seen.append("disconnect")
        return None
      if method == "boom":
        raise ValueError("application error")
      return {"echo": payload}

    server = RpcServer(handler, transport="tcp")
    try:
      client = RpcClient(server.address, transport="tcp",
                         call_timeout_secs=10.0)
      big = np.arange(1 << 18, dtype=np.float32)  # 1 MiB via RPC
      reply = client.call("act", {"obs": big})
      np.testing.assert_array_equal(reply["echo"]["obs"], big)
      with pytest.raises(RpcError, match="application error"):
        client.call("boom")
      client.close()
      deadline = time.monotonic() + 5.0
      while not seen and time.monotonic() < deadline:
        time.sleep(0.01)
      assert seen == ["disconnect"]
    finally:
      server.close()

  def test_wrong_authkey_client_raises_immediately(self):
    server = RpcServer(lambda m, p, c: p, transport="tcp",
                       authkey=b"fleet-a")
    try:
      t0 = time.monotonic()
      with pytest.raises(mp.AuthenticationError):
        RpcClient(server.address, transport="tcp", authkey=b"fleet-b",
                  connect_timeout_secs=20.0)
      # Auth mismatch must NOT burn the connect-retry window (that
      # path is for a still-warming server, not a wrong fleet).
      assert time.monotonic() - t0 < 10.0
      # ...and the server keeps serving afterwards.
      client = RpcClient(server.address, transport="tcp",
                         authkey=b"fleet-a")
      assert client.call("ping", 9) == 9
      client.close()
    finally:
      server.close()

  def test_deadline_and_poisoning_parity(self):
    release = threading.Event()

    def handler(method, payload, ctx):
      if method == "slow":
        release.wait(timeout=10.0)
      return payload

    server = RpcServer(handler, transport="tcp")
    try:
      client = RpcClient(server.address, transport="tcp",
                         call_timeout_secs=0.3, max_retries=0)
      with pytest.raises(TimeoutError):
        client.call("slow", 1)
      client.close()
    finally:
      release.set()
      server.close()


class TestFaultParityAcrossTransports:

  def test_same_plan_same_recovery_both_transports(self):
    # One seeded plan, replayed over loopback AND tcp: the fault
    # seams live in the SHARED rpc code paths, so both transports
    # must inject identically — and the plan digest cannot drift.
    def run(transport_name: str) -> str:
      tmetrics.reset_for_tests()  # per-transport counter window
      plan = faults.FaultPlan(seed=11, events=(faults.FaultEvent(
          fault=faults.RPC_DROP, target="learner", at=1,
          method="ping"),))
      digest = plan.digest()
      rpc_lib.set_fault_injector(faults.FaultInjector(plan, "learner"))
      server = RpcServer(lambda m, p, c: p, transport=transport_name)
      try:
        client = RpcClient(server.address, transport=transport_name,
                           call_timeout_secs=0.3, max_retries=2)
        assert client.call("ping", 5) == 5  # dropped once, recovered
        assert client.reconnects == 1
        snap = tmetrics.registry().snapshot()["counters"]
        assert snap["fleet.faults.injected.rpc_drop"] == 1.0
        assert snap["fleet.rpc.recovered"] >= 1.0
        client.close()
      finally:
        rpc_lib.set_fault_injector(None)
        server.close()
      assert plan.digest() == digest
      return digest

    assert run("loopback") == run("tcp")


class TestShardedReplayMath:

  def test_fanout_counts_proportional_and_exact(self):
    counts = shard_fanout_counts(64, (100, 100, 100, 100))
    assert counts == (16, 16, 16, 16)
    counts = shard_fanout_counts(10, (30, 10, 0))
    assert sum(counts) == 10
    assert counts[2] == 0  # empty shard draws nothing
    assert counts[0] > counts[1]

  def test_fanout_edge_cases(self):
    assert shard_fanout_counts(0, (5, 5)) == (0, 0)
    assert shard_fanout_counts(3, (0, 7)) == (0, 3)
    with pytest.raises(ValueError, match="empty"):
      shard_fanout_counts(4, (0, 0))
    with pytest.raises(ValueError):
      shard_fanout_counts(-1, (5,))
    # Deterministic: same sizes, same counts, every time.
    sizes = (17, 5, 29, 3)
    assert all(shard_fanout_counts(16, sizes)
               == shard_fanout_counts(16, sizes) for _ in range(5))

  def test_concat_shard_major_preserves_shard_order(self):
    parts = [
        {"a": np.full(2, 0), "b": np.zeros((2, 3))},
        {"a": np.full(3, 1), "b": np.ones((3, 3))},
    ]
    out = concat_shard_major(parts)
    np.testing.assert_array_equal(out["a"], [0, 0, 1, 1, 1])
    assert out["b"].shape == (5, 3)
    with pytest.raises(ValueError):
      concat_shard_major([])

  def test_home_shard_rendezvous_stability(self):
    homes4 = {f"actor-{i}": actor_lib.home_shard(f"actor-{i}", 4)
              for i in range(64)}
    # In range, deterministic, and every shard is somebody's home.
    assert set(homes4.values()) == {0, 1, 2, 3}
    assert homes4 == {a: actor_lib.home_shard(a, 4) for a in homes4}
    # Rendezvous property: dropping the LAST shard only remaps the
    # actors that lived there — everyone else keeps their home.
    homes3 = {a: actor_lib.home_shard(a, 3) for a in homes4}
    for a, home in homes4.items():
      if home < 3:
        assert homes3[a] == home
    with pytest.raises(ValueError):
      actor_lib.home_shard("actor-0", 0)


class TestBroadcastTree:

  def test_children_and_depths_heap_layout(self):
    assert broadcast_children(0, 5, 2) == [1, 2]
    assert broadcast_children(1, 5, 2) == [3, 4]
    assert broadcast_children(2, 5, 2) == []
    assert broadcast_depths(5, 2) == [0, 1, 1, 2, 2]
    assert broadcast_depths(1, 2) == [0]
    # Degree 1 degenerates to a chain.
    assert broadcast_depths(4, 1) == [0, 1, 2, 3]

  def test_every_host_reached_exactly_once(self):
    for num_hosts in (1, 2, 3, 7, 16):
      for degree in (1, 2, 3):
        reached = [0]
        for i in range(num_hosts):
          reached.extend(broadcast_children(i, num_hosts, degree))
        assert sorted(reached) == list(range(num_hosts))

  def test_config_validation(self):
    with pytest.raises(ValueError, match="transport"):
      FleetConfig(transport="carrier-pigeon")
    with pytest.raises(ValueError, match="replay_hosts"):
      FleetConfig(serving_hosts=2, replay_hosts=0)
    with pytest.raises(ValueError, match="broadcast_degree"):
      FleetConfig(broadcast_degree=0)


class TestTcpFleetEndToEnd:

  @pytest.mark.slow
  def test_multi_host_tcp_fleet_runs_clean(self, tmp_path):
    # The whole ISSUE-16 topology at once: 2 serving hosts (root +
    # one broadcast child), 2 replay shards, everything over TCP.
    config = FleetConfig(
        num_actors=2, env="toy_grasp", image_size=16, action_dim=2,
        torso_filters=(8,), head_filters=(8,), dense_sizes=(16,),
        cem_population=8, cem_iterations=1, cem_elites=2,
        batch_size=16, max_train_steps=16, min_replay_size=32,
        publish_every_steps=8, log_every_steps=8,
        batch_episodes=8, serve_max_batch=4,
        replay_capacity=512, replay_shards=1,
        heartbeat_timeout_secs=0.0, launch_timeout_secs=240.0,
        run_timeout_secs=420.0, seed=0,
        transport="tcp", serving_hosts=2, replay_hosts=2,
        broadcast_degree=2, telemetry_dir="off")
    fleet = Fleet(config, str(tmp_path))
    result = fleet.run()
    assert result.clean_shutdown
    assert result.env_steps_per_sec > 0
    assert result.publishes >= 1
    # Per-hop lag: actors on the root stamp hop 0, actors served by
    # the replica stamp hop 1 — both must have recorded rows.
    by_hop = result.param_refresh_lag.get("by_hop", {})
    assert set(by_hop) == {"0", "1"}
    assert all(h["rows"] > 0 for h in by_hop.values())
    # The replay plane lived on the shard hosts, namespaced per shard.
    assert result.replay_staleness
    assert all(key.startswith("shard") for key in result.replay_staleness)
    shard_details = result.metrics["replay_shards"]
    assert sorted(s["shard_index"] for s in shard_details) == [0, 1]
    assert all(s["store"]["adds_total"] > 0 for s in shard_details)
    # The replica forwarded the root's publications down the tree.
    replicas = result.metrics["serving_replicas"]
    assert [r["host_index"] for r in replicas] == [1]
    assert result.metrics["broadcast"]["forwards"] >= 1
    assert replicas[0]["params_version"] >= 1
    # Zero leaked children (the shutdown barrier's contract).
    assert not [p for p in mp.active_children()
                if p.name.startswith("t2r-fleet")]
