"""Tests for the multi-tenant serving front (ISSUE 13).

Pins the contracts docs/SERVING.md §"Multi-tenant front" promises:
  * arena: budgeted LRU residency — loading past the budget evicts the
    least-recently-dispatched tenant; an evicted tenant's reload is
    COMPILE-CACHE-WARM (`cache_misses == 0`, the startup/compile_cache
    seam); a single tenant over the whole budget is a config error;
  * admission: per-tenant token-bucket rate + bounded queue with the
    replay overflow contract — "drop" rejects + counts immediately,
    "block" applies backpressure up to its deadline then counts a drop;
    shed counters land in the telemetry registry
    (`serving.<tenant>.admission.*`);
  * front: one continuous-batching dispatcher serves every tenant
    round-robin (a deep queue cannot starve a shallow one), per-caller
    results are exactly the tenant's own rows, `submit()` after
    `close()` fails fast;
  * hot-swap under multi-tenant traffic: swapping tenant A's params
    mid-traffic never stalls or recompiles tenant B (zero-recompile
    pin via `engine.compile_count()`);
  * SLO accounting keys on the per-tenant `serving.<t>.bucket_<n>_ms`
    histograms the engine already publishes.

The model bodies are tiny pure matmuls: the contracts under test are
scheduling, budgeting, and accounting — not network math (the engine's
numerics are pinned in tests/test_serving.py).
"""

import threading
import time

import numpy as np
import pytest

import jax

from tensor2robot_tpu.serving import (
    AdmissionController,
    ModelArena,
    RequestRejected,
    ServingFront,
    TenantPolicy,
)
from tensor2robot_tpu.serving import arena as arena_lib
from tensor2robot_tpu.serving import engine as engine_lib
from tensor2robot_tpu.startup import compile_cache
from tensor2robot_tpu.telemetry import metrics as tmetrics
from tensor2robot_tpu.telemetry import prometheus


@pytest.fixture(autouse=True)
def _isolate():
  """Fresh registry per test; detach the persistent compile cache so a
  tmp-path cache never leaks into later tests' engines."""
  tmetrics.reset_for_tests()
  yield
  compile_cache.reset_compilation_cache_config()
  tmetrics.reset_for_tests()


def make_loader(scale, side=8, calls=None):
  """Loader for a tenant whose output is `x @ (scale * I)` — outputs
  identify the tenant AND the params generation."""
  def loader():
    if calls is not None:
      calls.append(scale)
    params = {"w": np.eye(side, dtype=np.float32) * scale}
    def fn(state, feats):
      return {"y": feats["x"] @ state["w"]}
    example = {"x": np.zeros((1, side), np.float32)}
    return fn, params, example
  return loader


def ones(n, side=8):
  return {"x": np.ones((n, side), np.float32)}


def make_front(tmp_path, admission=None, **front_kwargs):
  arena = ModelArena(budget_bytes=None,
                     cache_dir=str(tmp_path / "xla_cache"))
  return ServingFront(arena, admission, **front_kwargs)


def park_dispatcher(front, tenant="slow"):
  """Parks the front's dispatcher inside `tenant`'s predict (a slow
  device program) until the returned event is set — the deterministic
  queue-buildup rig for the bound tests. Loads no longer park the
  dispatcher (they run on arena threads, ISSUE 14), so the park point
  is the dispatch itself: the tenant must already be registered
  `preload=True`. Returns (release_event, the parked request's
  future)."""
  engine = front.arena.engine(tenant)
  release = threading.Event()
  entered = threading.Event()
  orig_predict = engine.predict

  def blocking_predict(*args, **kwargs):
    entered.set()
    release.wait(timeout=30.0)
    return orig_predict(*args, **kwargs)

  engine.predict = blocking_predict
  parked = front.submit(tenant, ones(1))
  assert entered.wait(timeout=10.0)  # dispatcher is now parked
  return release, parked


class TestArena:

  def test_lru_eviction_at_budget(self, tmp_path):
    arena = ModelArena(budget_bytes=2 * 8 * 8 * 4,
                       cache_dir=str(tmp_path / "cache"))
    for tenant, scale in (("a", 1.0), ("b", 2.0), ("c", 3.0)):
      arena.register(tenant, make_loader(scale), max_batch=1)
    arena.engine("a")
    arena.engine("b")
    arena.engine("a")  # LRU touch: b is now least recent
    assert arena.resident_tenants() == ("b", "a")
    arena.engine("c")  # over budget: evicts b, not a
    assert set(arena.stats()["resident"]) == {"a", "c"}
    assert arena.evictions == 1
    assert arena.resident_bytes() <= arena.budget_bytes
    snap = tmetrics.registry().snapshot()
    assert snap["counters"]["serving.arena.evictions"] == 1.0
    assert snap["gauges"]["serving.arena.resident_models"] == 2.0

  def test_eviction_reload_is_compile_cache_warm(self, tmp_path):
    """THE arena perf contract: an evicted tenant's reload
    deserializes every bucket from the persistent cache instead of
    recompiling — `cache_misses == 0` on the reload."""
    arena = ModelArena(budget_bytes=None,
                       cache_dir=str(tmp_path / "cache"))
    arena.register("a", make_loader(5.0), max_batch=2)
    engine = arena.engine("a")
    out = engine.predict(ones(1))
    np.testing.assert_allclose(out["y"], 5.0)
    assert arena.evict("a")
    reloaded = arena.engine("a")
    assert reloaded is not engine
    stats = arena.stats()
    assert stats["reloads"] == 1
    assert stats["reload_cache_misses"] == 0, stats
    assert stats["last_load"]["cache_misses"] == 0
    out = reloaded.predict(ones(2))
    np.testing.assert_allclose(out["y"], 5.0)

  def test_single_tenant_over_budget_raises(self, tmp_path):
    arena = ModelArena(budget_bytes=16,
                       cache_dir=str(tmp_path / "cache"))
    arena.register("big", make_loader(1.0), max_batch=1)
    with pytest.raises(ValueError, match="budget"):
      arena.engine("big")

  def test_tenant_id_validation(self, tmp_path):
    arena = ModelArena(cache_dir=str(tmp_path / "cache"))
    with pytest.raises(ValueError, match="reserved"):
      arena.register("arena", make_loader(1.0))
    with pytest.raises(ValueError, match="must match"):
      arena.register("bad.tenant", make_loader(1.0))
    with pytest.raises(KeyError):
      arena.engine("never_registered")
    arena.register("ok-tenant_1", make_loader(1.0))
    with pytest.raises(ValueError, match="already registered"):
      arena.register("ok-tenant_1", make_loader(1.0))

  def test_reserved_ids_match_prometheus_namespaces(self):
    # The adapter's label heuristic and the arena's id validation must
    # agree, or a tenant could impersonate a subsystem namespace.
    assert (arena_lib.RESERVED_TENANT_IDS
            == prometheus.RESERVED_SERVING_NAMESPACES)

  def test_swap_state_resident_vs_evicted(self, tmp_path):
    arena = ModelArena(cache_dir=str(tmp_path / "cache"))
    arena.register("a", make_loader(1.0), max_batch=1)
    new_params = {"w": np.eye(8, dtype=np.float32) * 9.0}
    assert not arena.swap_state("a", new_params)  # not resident yet
    engine = arena.engine("a")
    assert arena.swap_state("a", new_params, learner_step=7)
    np.testing.assert_allclose(engine.predict(ones(1))["y"], 9.0)
    assert engine.params_learner_step == 7
    with pytest.raises(KeyError):
      arena.swap_state("ghost", new_params)

  def test_released_engine_fails_fast_not_corrupt(self, tmp_path):
    """Eviction retires the engine: a stale handle's predict raises a
    clear error (never dispatches on dropped params), while the arena
    path reloads transparently."""
    arena = ModelArena(cache_dir=str(tmp_path / "cache"))
    arena.register("a", make_loader(2.0), max_batch=1)
    stale = arena.engine("a")
    arena.evict("a")
    assert stale.released
    with pytest.raises(RuntimeError, match="released"):
      stale.predict(ones(1))
    with pytest.raises(RuntimeError, match="released"):
      stale.swap_state({"w": np.eye(8, dtype=np.float32)})
    np.testing.assert_allclose(arena.engine("a").predict(ones(1))["y"],
                               2.0)

  def test_reload_uses_loader_fresh_state(self, tmp_path):
    """The loader is the source of truth on reload: a production
    loader re-reads the newest checkpoint, so eviction never serves
    stale params after reload."""
    calls = []
    arena = ModelArena(cache_dir=str(tmp_path / "cache"))
    arena.register("a", make_loader(4.0, calls=calls), max_batch=1)
    arena.engine("a")
    arena.evict("a")
    arena.engine("a")
    assert calls == [4.0, 4.0]  # loader ran once per load

  def test_async_cold_load_counts_one_miss_no_pickup_hit(self, tmp_path):
    """A cold engine_async load is ONE logical dispatch: the miss at
    load start, then the dispatcher's post-load re-touch, must not
    also count a warm hit (the sync engine() path counts that same
    dispatch once) — genuine warm touches afterwards still do."""
    arena = ModelArena(cache_dir=str(tmp_path / "cache"))
    arena.register("a", make_loader(2.0), max_batch=1)
    before = tmetrics.registry().snapshot()["counters"]
    engine, future = arena.engine_async("a")
    assert engine is None
    future.result(timeout=30.0)
    engine, future = arena.engine_async("a")  # the pickup re-touch
    assert engine is not None and future is None
    mid = tmetrics.registry().snapshot()["counters"]
    assert (mid.get("serving.arena.misses", 0.0)
            - before.get("serving.arena.misses", 0.0)) == 1.0
    assert (mid.get("serving.arena.hits", 0.0)
            - before.get("serving.arena.hits", 0.0)) == 0.0
    arena.engine_async("a")  # a real warm hit counts
    after = tmetrics.registry().snapshot()["counters"]
    assert (after.get("serving.arena.hits", 0.0)
            - before.get("serving.arena.hits", 0.0)) == 1.0


class TestAdmission:

  def test_token_bucket_sheds_over_burst(self, tmp_path):
    policy = TenantPolicy(rate_rps=0.01, burst=2, overflow="drop",
                          slo_ms=1000.0)
    with make_front(tmp_path) as front:
      front.register_tenant("a", make_loader(1.0), policy=policy,
                            max_batch=2, preload=True)
      futures = [front.submit("a", ones(1)) for _ in range(2)]
      with pytest.raises(RequestRejected) as exc:
        front.submit("a", ones(1))
      assert exc.value.reason == "rate"
      assert exc.value.tenant == "a"
      for future in futures:
        np.testing.assert_allclose(future.result()["y"], 1.0)
    snap = tmetrics.registry().snapshot()
    assert snap["counters"]["serving.a.admission.dropped"] == 1.0
    assert snap["counters"]["serving.a.admission.shed_rate"] == 1.0
    assert snap["counters"]["serving.a.admission.admitted"] == 2.0

  def test_token_bucket_refills(self, tmp_path):
    policy = TenantPolicy(rate_rps=200.0, burst=1, overflow="drop",
                          slo_ms=1000.0)
    with make_front(tmp_path) as front:
      front.register_tenant("a", make_loader(1.0), policy=policy,
                            max_batch=1, preload=True)
      front.predict("a", ones(1))
      time.sleep(0.05)  # 200 rps: ~10 tokens refill
      np.testing.assert_allclose(
          front.predict("a", ones(1))["y"], 1.0)

  def test_block_policy_waits_for_tokens(self, tmp_path):
    policy = TenantPolicy(rate_rps=50.0, burst=1, overflow="block",
                          block_timeout_secs=5.0, slo_ms=1000.0)
    with make_front(tmp_path) as front:
      front.register_tenant("a", make_loader(1.0), policy=policy,
                            max_batch=1, preload=True)
      front.predict("a", ones(1))  # spends the burst
      t0 = time.perf_counter()
      out = front.predict("a", ones(1))  # waits ~20ms for a token
      waited = time.perf_counter() - t0
      np.testing.assert_allclose(out["y"], 1.0)
      assert waited >= 0.01, waited

  def _front_with_stuck_dispatcher(self, tmp_path, policy):
    """A front whose dispatcher is parked inside a slow tenant's
    DISPATCH — deterministic queue buildup for the bound tests (a
    slow LOAD no longer parks the dispatcher: ISSUE 14 async arena
    loads, pinned in TestFront)."""
    front = make_front(tmp_path)
    front.register_tenant("slow", make_loader(3.0),
                          policy=TenantPolicy(slo_ms=1000.0),
                          preload=True)
    front.register_tenant("x", make_loader(1.0), policy=policy,
                          preload=True)
    release, slow_future = park_dispatcher(front)
    return front, release, slow_future

  def test_bounded_queue_drop_counts_and_rejects(self, tmp_path):
    policy = TenantPolicy(max_queue=2, overflow="drop", slo_ms=1000.0)
    front, release, slow_future = self._front_with_stuck_dispatcher(
        tmp_path, policy)
    try:
      queued = [front.submit("x", ones(1)) for _ in range(2)]
      with pytest.raises(RequestRejected) as exc:
        front.submit("x", ones(1))
      assert exc.value.reason == "queue_full"
    finally:
      release.set()
    for future in queued:
      np.testing.assert_allclose(future.result(timeout=30)["y"], 1.0)
    np.testing.assert_allclose(
        slow_future.result(timeout=30)["y"], 3.0)
    front.close()
    snap = tmetrics.registry().snapshot()
    assert snap["counters"]["serving.x.admission.shed_queue"] == 1.0
    assert snap["counters"]["serving.x.admission.dropped"] == 1.0

  def test_bounded_queue_block_deadline_drops(self, tmp_path):
    policy = TenantPolicy(max_queue=1, overflow="block",
                          block_timeout_secs=0.3, slo_ms=1000.0)
    front, release, slow_future = self._front_with_stuck_dispatcher(
        tmp_path, policy)
    try:
      first = front.submit("x", ones(1))
      t0 = time.perf_counter()
      with pytest.raises(RequestRejected) as exc:
        front.submit("x", ones(1))
      waited = time.perf_counter() - t0
      assert exc.value.reason == "queue_full"
      assert waited >= 0.25, waited  # actually blocked to the deadline
    finally:
      release.set()
    np.testing.assert_allclose(first.result(timeout=30)["y"], 1.0)
    slow_future.result(timeout=30)
    front.close()

  def test_burst_below_max_batch_rejected_at_registration(self, tmp_path):
    # A bucket of depth burst can never grant max_batch tokens — every
    # full-size request would shed forever; loud at registration.
    with make_front(tmp_path) as front:
      with pytest.raises(ValueError, match="burst"):
        front.register_tenant(
            "a", make_loader(1.0), max_batch=8,
            policy=TenantPolicy(rate_rps=100.0, burst=4))
      # Unlimited-rate tenants have no bucket: any burst is fine.
      front.register_tenant(
          "b", make_loader(1.0), max_batch=8,
          policy=TenantPolicy(rate_rps=None, burst=1, slo_ms=1000.0))
    # The guard must also see the CONTROLLER'S default policy — the
    # one a policy=None tenant actually inherits (gin-configured).
    front = make_front(tmp_path,
                       AdmissionController(rate_rps=100.0, burst=4,
                                           slo_ms=1000.0))
    try:
      with pytest.raises(ValueError, match="burst"):
        front.register_tenant("c", make_loader(1.0), max_batch=8)
      front.register_tenant("d", make_loader(1.0), max_batch=4)
    finally:
      front.close()

  def test_queue_shed_refunds_rate_tokens(self):
    # A request shed at the QUEUE gate must not charge the tenant's
    # rate budget: its tokens come back (rate ~0 so no refill noise).
    controller = AdmissionController()
    controller.register("t", TenantPolicy(rate_rps=0.001, burst=2,
                                          slo_ms=100.0))
    assert controller.admit("t", 2)      # spends the whole burst
    assert not controller.admit("t", 2)  # empty: shed at rate
    controller.queue_full("t", 2)        # queue shed refunds
    assert controller.admit("t", 2)      # budget restored
    snap = tmetrics.registry().snapshot()
    # admitted counts only AFTER the queue gate (the front calls
    # count_admitted post-enqueue): admit() alone must not count it.
    assert "serving.t.admission.admitted" not in snap["counters"]
    controller.count_admitted("t", 2)
    snap = tmetrics.registry().snapshot()
    assert snap["counters"]["serving.t.admission.admitted"] == 2.0
    assert snap["counters"]["serving.t.admission.shed_rate"] == 2.0
    assert snap["counters"]["serving.t.admission.shed_queue"] == 2.0
    assert snap["counters"]["serving.t.admission.dropped"] == 4.0

  def test_close_during_block_wait_counts_shed(self, tmp_path):
    """A close() racing a queue-full block wait must still account the
    request (refund + shed counters) before failing fast — admitted
    and dropped partition offered load even across shutdown."""
    policy = TenantPolicy(max_queue=1, overflow="block",
                          block_timeout_secs=30.0, slo_ms=1000.0)
    front, release, slow_future = self._front_with_stuck_dispatcher(
        tmp_path, policy)
    first = front.submit("x", ones(1))  # fills the queue
    outcome = {}

    def blocked_submit():
      try:
        front.submit("x", ones(1))
        outcome["kind"] = "enqueued"
      except RequestRejected:
        outcome["kind"] = "rejected"
      except RuntimeError:
        outcome["kind"] = "closed"

    submitter = threading.Thread(target=blocked_submit)
    submitter.start()
    time.sleep(0.3)  # parked in the deadline_slices wait
    threading.Timer(1.0, release.set).start()
    front.close()
    submitter.join(timeout=10)
    assert outcome["kind"] == "closed", outcome
    snap = tmetrics.registry().snapshot()
    assert snap["counters"]["serving.x.admission.shed_queue"] == 1.0
    assert snap["counters"]["serving.x.admission.dropped"] == 1.0
    # The request that DID enqueue was still served by the drain.
    np.testing.assert_allclose(first.result(timeout=30)["y"], 1.0)
    slow_future.result(timeout=30)

  def test_slo_report_keys_on_bucket_histograms(self):
    # Synthesized per-tenant dispatch histograms: the report must merge
    # a tenant's buckets and score them against its slo_ms.
    controller = AdmissionController(slo_ms=10.0)
    controller.register("a")
    controller.register("b", TenantPolicy(slo_ms=1.0))
    bounds = (1.0, 10.0, 100.0)
    hist_a1 = tmetrics.histogram("serving.a.bucket_1_ms", bounds=bounds)
    hist_a2 = tmetrics.histogram("serving.a.bucket_2_ms", bounds=bounds)
    for value in (0.5, 5.0):
      hist_a1.observe(value)
    hist_a2.observe(50.0)
    tmetrics.histogram("serving.b.bucket_1_ms", bounds=bounds)
    # End-to-end view: queueing-inclusive request_ms diverges from the
    # dispatch view under load — both must be reported.
    e2e = tmetrics.histogram("serving.a.request_ms", bounds=bounds)
    for value in (0.5, 50.0, 50.0, 50.0):
      e2e.observe(value)
    report = controller.slo_report()
    assert report["a"]["count"] == 3
    assert report["a"]["slo_ms"] == 10.0
    # 2 of 3 observations ≤ 10ms (bucket-exact: 10.0 is a bucket edge).
    assert report["a"]["in_slo_fraction"] == pytest.approx(
        2 / 3, abs=1e-3)
    assert report["a"]["p50_ms"] <= 10.0 < report["a"]["p99_ms"]
    assert report["a"]["e2e_count"] == 4
    assert report["a"]["e2e_in_slo_fraction"] == pytest.approx(
        0.25, abs=1e-3)
    assert report["a"]["e2e_p95_ms"] > report["a"]["p95_ms"]
    assert report["b"]["count"] == 0
    assert "e2e_count" not in report["b"]

  def test_slo_report_overflow_bucket_is_honest(self):
    """Observations above the top histogram bound must not read as
    in-SLO unless the observed max proves it, and the tail quantile
    reports the observed max, not the clamped top bound."""
    controller = AdmissionController()
    controller.register("t", TenantPolicy(slo_ms=200.0))
    hist = tmetrics.histogram("serving.t.bucket_1_ms",
                              bounds=(1.0, 10.0, 100.0))
    hist.observe(0.5)
    hist.observe(50_000.0)  # a multi-minute stall in the overflow
    report = controller.slo_report()
    # SLO 200 > top bound 100: the stall is NOT blessed as in-SLO.
    assert report["t"]["in_slo_fraction"] == pytest.approx(0.5)
    # The tail reads the observed max, not 100.0.
    assert report["t"]["p99_ms"] == pytest.approx(50_000.0)
    # With an SLO the observed max provably satisfies, overflow counts.
    controller2 = AdmissionController()
    controller2.register("u", TenantPolicy(slo_ms=1e9))
    tmetrics.histogram("serving.u.bucket_1_ms",
                       bounds=(1.0, 10.0)).observe(500.0)
    assert (controller2.slo_report()["u"]["in_slo_fraction"]
            == pytest.approx(1.0))

  def test_claim_batch_tolerates_finished_futures(self):
    # A racing close() may have already failed a queued request; the
    # dispatcher's claim must skip it, not die mid-batch.
    from concurrent.futures import Future

    from tensor2robot_tpu.serving import coalesce

    class Req:
      def __init__(self):
        self.future = Future()
        self.n = 1
        self.features = {"x": np.zeros((1, 2), np.float32)}

    live, cancelled, failed = Req(), Req(), Req()
    cancelled.future.cancel()
    failed.future.set_exception(RuntimeError("closed before dispatch"))
    claimed = coalesce.claim_batch([live, cancelled, failed])
    assert claimed == [live]


class TestFront:

  def test_cross_tenant_results_are_exact(self, tmp_path):
    with make_front(tmp_path) as front:
      front.register_tenant("a", make_loader(2.0), max_batch=4,
                            preload=True)
      front.register_tenant("b", make_loader(10.0), max_batch=4,
                            preload=True)
      barrier = threading.Barrier(8)
      results = {}

      def caller(index, tenant, scale):
        feats = {"x": np.full((1, 8), float(index), np.float32)}
        barrier.wait()
        results[index] = (front.predict(tenant, feats), scale, index)

      threads = [
          threading.Thread(
              target=caller,
              args=(i, "a" if i % 2 else "b", 2.0 if i % 2 else 10.0))
          for i in range(8)
      ]
      for thread in threads:
        thread.start()
      for thread in threads:
        thread.join(timeout=60)
      assert len(results) == 8
      for out, scale, index in results.values():
        np.testing.assert_allclose(out["y"], scale * index)
      # Coalescing across the 8 callers: strictly fewer dispatches.
      assert front.dispatches < 8
      assert set(front.dispatches_per_tenant) == {"a", "b"}
      # The wakeup channel is a coalesced FLAG, not a token per
      # request — sustained load must not grow it.
      assert front._work.qsize() <= 1

  def test_cold_tenant_load_never_blocks_other_tenants(self, tmp_path):
    """ISSUE 14 satellite pin: a cold tenant's load runs OFF the
    dispatcher thread — tenant B keeps completing requests end to end
    while the load is in flight, and the cold tenant's request is
    served once its load lands."""
    gate = threading.Event()
    entered = threading.Event()
    base_loader = make_loader(3.0)

    def cold_loader():
      entered.set()
      gate.wait(timeout=30.0)
      return base_loader()

    front = make_front(tmp_path)
    front.register_tenant("cold", cold_loader,
                          policy=TenantPolicy(slo_ms=1000.0))
    front.register_tenant("b", make_loader(1.0), preload=True)
    try:
      cold_future = front.submit("cold", ones(1))
      assert entered.wait(timeout=10.0)  # load started (arena thread)
      # Full round trips through the SAME dispatcher the load would
      # previously have parked: every one must complete while the
      # cold load is still gated open.
      for _ in range(10):
        np.testing.assert_allclose(
            front.predict("b", ones(1))["y"], 1.0)
      assert not cold_future.done()  # the load outlived all 10
    finally:
      gate.set()
    np.testing.assert_allclose(cold_future.result(timeout=30)["y"], 3.0)
    front.close()

  def test_failed_load_fails_queued_requests_and_submit_retries(
      self, tmp_path):
    """A loader failure surfaces on the queued requests' futures (the
    dispatcher never dies), and the tenant's NEXT submit triggers a
    fresh load attempt."""
    calls = []

    def flaky_loader():
      calls.append(1)
      if len(calls) == 1:
        raise RuntimeError("flaky loader boom")
      return make_loader(2.0)()

    front = make_front(tmp_path)
    front.register_tenant("f", flaky_loader,
                          policy=TenantPolicy(slo_ms=1000.0))
    doomed = front.submit("f", ones(1))
    with pytest.raises(RuntimeError, match="flaky loader boom"):
      doomed.result(timeout=30)
    out = front.predict("f", ones(1))  # retried load, now warm
    np.testing.assert_allclose(out["y"], 2.0)
    front.close()

  def test_round_robin_fair_share(self, tmp_path):
    """A deep queue (6 waiting requests) must not starve a shallow one
    (2): round-robin serves B's first dispatch before A's last."""
    front = make_front(tmp_path)
    front.register_tenant("slow", make_loader(1.0),
                          policy=TenantPolicy(slo_ms=1000.0),
                          preload=True)
    front.register_tenant("a", make_loader(1.0), max_batch=2,
                          preload=True)
    front.register_tenant("b", make_loader(2.0), max_batch=2,
                          preload=True)
    order = []

    def track(tenant):
      def _done(_):
        order.append(tenant)
      return _done

    release, stuck = park_dispatcher(front)
    try:
      futures = []
      for _ in range(6):
        future = front.submit("a", ones(1))
        future.add_done_callback(track("a"))
        futures.append(future)
      for _ in range(2):
        future = front.submit("b", ones(1))
        future.add_done_callback(track("b"))
        futures.append(future)
    finally:
      release.set()
    for future in futures:
      future.result(timeout=30)
    stuck.result(timeout=30)
    front.close()
    first_b = order.index("b")
    last_a = len(order) - 1 - order[::-1].index("a")
    assert first_b < last_a, order

  def test_cancelled_request_never_poisons_co_batched_callers(
      self, tmp_path):
    """A caller cancelling its queued future must not cost the
    requests coalesced around it their results (the claim-then-deliver
    contract in serving/coalesce.py)."""
    front = make_front(tmp_path)
    front.register_tenant("slow", make_loader(1.0),
                          policy=TenantPolicy(slo_ms=1000.0),
                          preload=True)
    front.register_tenant("x", make_loader(5.0), max_batch=4,
                          preload=True)
    release, stuck = park_dispatcher(front)
    try:
      before = front.submit("x", ones(1))
      doomed = front.submit("x", ones(1))
      after = front.submit("x", ones(1))
      assert doomed.cancel()  # still queued: cancel wins
    finally:
      release.set()
    # The co-batched neighbors get exactly their own rows.
    np.testing.assert_allclose(before.result(timeout=30)["y"], 5.0)
    np.testing.assert_allclose(after.result(timeout=30)["y"], 5.0)
    assert doomed.cancelled()
    stuck.result(timeout=30)
    front.close()

  def test_microbatcher_tolerates_cancelled_requests(self):
    """Same contract on the single-model path (shared coalesce)."""
    from tensor2robot_tpu.serving import BucketedServingEngine
    from tensor2robot_tpu.serving import MicroBatcher

    params = {"w": np.eye(4, dtype=np.float32) * 3.0}
    engine = BucketedServingEngine(
        lambda state, feats: {"y": feats["x"] @ state["w"]},
        params, {"x": np.zeros((1, 4), np.float32)}, max_batch=4)
    engine.warmup()
    with MicroBatcher(engine, max_wait_us=100_000) as batcher:
      first = batcher.submit({"x": np.ones((1, 4), np.float32)})
      second = batcher.submit({"x": np.ones((1, 4), np.float32)})
      won = second.cancel()  # racing the dispatcher: either side may win
      np.testing.assert_allclose(
          first.result(timeout=30)["y"], 3.0)
      if won:
        assert second.cancelled()
      else:
        np.testing.assert_allclose(
            second.result(timeout=30)["y"], 3.0)

  def test_submit_after_close_fails_fast(self, tmp_path):
    front = make_front(tmp_path)
    front.register_tenant("a", make_loader(1.0), preload=True)
    front.predict("a", ones(1))
    front.close()
    with pytest.raises(RuntimeError, match="closed"):
      front.submit("a", ones(1))

  def test_unknown_tenant_and_oversized_request(self, tmp_path):
    with make_front(tmp_path) as front:
      front.register_tenant("a", make_loader(1.0), max_batch=2,
                            preload=True)
      with pytest.raises(KeyError):
        front.submit("ghost", ones(1))
      with pytest.raises(ValueError, match="max_batch"):
        front.submit("a", ones(3))

  def test_rng_tenants_get_folded_keys(self, tmp_path):
    def loader():
      params = {"w": np.zeros((1,), np.float32)}
      def fn(state, feats, rng):
        noise = jax.random.uniform(rng, (1, 1))
        return {"y": feats["x"][:, :1] * 0.0 + state["w"] + noise}
      example = {"x": np.zeros((1, 8), np.float32)}
      return fn, params, example

    with make_front(tmp_path) as front:
      front.register_tenant("cem", loader, takes_rng=True,
                            preload=True)
      first = front.predict("cem", ones(1))["y"]
      second = front.predict("cem", ones(1))["y"]
      # Distinct dispatches fold distinct keys: noise differs.
      assert not np.array_equal(first, second)

  def test_completion_metrics_published(self, tmp_path):
    with make_front(tmp_path) as front:
      front.register_tenant(
          "a", make_loader(1.0),
          policy=TenantPolicy(slo_ms=60_000.0), preload=True)
      for _ in range(3):
        front.predict("a", ones(1))
    snap = tmetrics.registry().snapshot()
    assert snap["counters"]["serving.a.completions"] == 3.0
    assert snap["counters"]["serving.a.slo_ok"] == 3.0
    assert snap["histograms"]["serving.a.request_ms"]["count"] == 3
    # The engine's per-tenant dispatch histograms exist too — the SLO
    # accounting seam.
    assert any(name.startswith("serving.a.bucket_")
               for name in snap["histograms"])


class TestMultiTenantHotSwap:

  def test_swap_a_never_stalls_or_recompiles_b(self, tmp_path):
    """ISSUE 13 satellite: hot-swapping tenant A's checkpoint under
    multi-tenant traffic must not stall or recompile tenant B."""
    with make_front(tmp_path) as front:
      front.register_tenant("a", make_loader(1.0), max_batch=2,
                            preload=True)
      front.register_tenant("b", make_loader(100.0), max_batch=2,
                            preload=True)
      front.predict("b", ones(1))  # warm the dispatch path
      compiles_before = engine_lib.compile_count()

      stop = threading.Event()
      b_outputs = []
      b_errors = []

      def b_traffic():
        while not stop.is_set():
          try:
            out = front.predict("b", ones(1))
            b_outputs.append(float(out["y"][0, 0]))
          except Exception as exc:  # noqa: BLE001 — the pin IS no-error
            b_errors.append(exc)
            return

      threads = [threading.Thread(target=b_traffic) for _ in range(2)]
      for thread in threads:
        thread.start()
      served_before_swaps = len(b_outputs)
      for generation in range(2, 7):
        new_params = {"w": np.eye(8, dtype=np.float32) * generation}
        assert front.arena.swap_state("a", new_params,
                                      learner_step=generation)
        # A's swap is visible immediately...
        np.testing.assert_allclose(
            front.predict("a", ones(1))["y"], float(generation))
      time.sleep(0.1)
      stop.set()
      for thread in threads:
        thread.join(timeout=30)

      assert not b_errors, b_errors[:1]
      # B kept serving THROUGH the swaps (not just before/after).
      assert len(b_outputs) > served_before_swaps + 5
      assert all(value == 100.0 for value in b_outputs)
      # Zero recompiles anywhere: swaps keep shapes, buckets stay hot.
      assert engine_lib.compile_count() == compiles_before
