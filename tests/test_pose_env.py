"""End-to-end tests for the pose_env research family.

The reference's proof-of-life config (SURVEY.md §8 step 5): collect →
TFRecord → train → checkpoint → predict → env eval, all spec-driven.
"""

import os

import numpy as np
import pytest

from tensor2robot_tpu.telemetry.records import read_records
from tensor2robot_tpu import train_eval
from tensor2robot_tpu.data.abstract_input_generator import Mode
from tensor2robot_tpu.data.tfrecord_input_generator import (
    TFRecordInputGenerator,
)
from tensor2robot_tpu.data.random_input_generator import (
    RandomInputGenerator,
)
from tensor2robot_tpu.predictors import CheckpointPredictor
from tensor2robot_tpu.research.pose_env import (
    PoseEnv,
    PoseEnvRegressionModel,
    collect_random_episodes,
    evaluate_pose_model,
)


def _tiny_model(**kwargs):
  return PoseEnvRegressionModel(
      image_size=32, filters=(8, 16), embedding_size=32,
      hidden_sizes=(32,), **kwargs)


class TestPoseEnv:

  def test_env_renders_block_at_pose(self):
    env = PoseEnv(image_size=32, seed=3)
    obs = env.reset()
    assert obs["image"].shape == (32, 32, 3)
    assert obs["image"].dtype == np.uint8
    # The red block must be visible: red channel dominates somewhere.
    red = obs["image"][..., 0].astype(int) - obs["image"][..., 1]
    assert red.max() > 80

  def test_env_poses_vary_and_stay_in_workspace(self):
    env = PoseEnv(seed=0)
    poses = []
    for _ in range(10):
      env.reset()
      poses.append(env.pose.copy())
    poses = np.stack(poses)
    assert np.all(poses >= -0.4) and np.all(poses <= 0.4)
    assert poses.std(axis=0).min() > 0.05

  def test_collect_writes_tfrecords(self, tmp_path):
    path = collect_random_episodes(
        str(tmp_path / "data.tfrecord"), num_episodes=8, image_size=32)
    assert os.path.getsize(path) > 0

  def test_specs(self):
    model = _tiny_model()
    feat = model.get_feature_specification(Mode.TRAIN)
    assert feat.image.shape == (32, 32, 3)
    label = model.get_label_specification(Mode.TRAIN)
    assert label.target_pose.shape == (2,)


@pytest.mark.slow
class TestPoseEnvEndToEnd:

  @pytest.fixture(scope="class")
  def run(self, tmp_path_factory):
    """collect → tfrecord-train → checkpoint; shared across asserts."""
    root = tmp_path_factory.mktemp("pose_e2e")
    data_path = collect_random_episodes(
        str(root / "train.tfrecord"), num_episodes=64, image_size=32,
        seed=0)
    model = _tiny_model()
    model_dir = str(root / "model")
    train_eval.train_eval_model(
        model=model,
        model_dir=model_dir,
        input_generator_train=TFRecordInputGenerator(
            file_patterns=data_path, shuffle_buffer_size=64, seed=1),
        input_generator_eval=TFRecordInputGenerator(
            file_patterns=data_path, shuffle=False, repeat=False),
        max_train_steps=40,
        eval_steps=2,
        batch_size=16,
        save_checkpoints_steps=40,
        log_every_steps=10,
    )
    return model, model_dir

  def test_loss_decreases(self, run):
    _, model_dir = run
    records = read_records(
        os.path.join(model_dir, "metrics_train.jsonl"))
    assert records[-1]["mse"] < records[0]["mse"]

  def test_eval_metrics_written(self, run):
    _, model_dir = run
    path = os.path.join(model_dir, "metrics_eval.jsonl")
    records = read_records(path)
    assert records and "pose_error" in records[-1]

  def test_env_eval_through_predictor(self, run):
    model, model_dir = run
    predictor = CheckpointPredictor(model, checkpoint_dir=model_dir)
    assert predictor.restore(timeout_secs=0)
    metrics = evaluate_pose_model(
        predictor.predict, num_episodes=8, image_size=32)
    assert set(metrics) >= {"mean_pose_error", "success_rate"}
    # Always predicting the workspace center scores ~0.31 on uniform
    # ±0.4 poses; the bar sits below that so a predictor serving
    # garbage (e.g. unrestored batch-norm stats) fails here.
    assert metrics["mean_pose_error"] < 0.25

  def test_random_generator_also_works(self, tmp_path):
    model = _tiny_model()
    train_eval.train_eval_model(
        model=model,
        model_dir=str(tmp_path / "rand"),
        input_generator_train=RandomInputGenerator(batch_size=8),
        max_train_steps=2,
        log_every_steps=1,
    )

  def test_success_eval_hook_logs_per_checkpoint(self, tmp_path):
    """The BASELINE protocol hook: success_rate per checkpoint."""
    from tensor2robot_tpu.hooks import SuccessEvalHook

    model = _tiny_model()
    model_dir = str(tmp_path / "hooked")
    train_eval.train_eval_model(
        model=model,
        model_dir=model_dir,
        input_generator_train=RandomInputGenerator(batch_size=8),
        max_train_steps=4,
        save_checkpoints_steps=2,
        log_every_steps=2,
        hooks=[SuccessEvalHook(
            eval_fn=evaluate_pose_model,
            eval_kwargs={"num_episodes": 4, "image_size": 32,
                         "seed": 9})],
    )
    path = os.path.join(model_dir, "metrics_success_eval.jsonl")
    records = read_records(path)
    # One protocol line per checkpoint, each carrying success_rate.
    assert [r["step"] for r in records] == [2, 4]
    assert all("success_rate" in r for r in records)

  def test_shipped_config_resolves_protocol_hook(self):
    """The gin-bound SuccessEvalHook must RESOLVE, not just parse:
    eval_fn is the real evaluate_pose_model and the kwargs carry the
    500-episode BASELINE protocol."""
    from tensor2robot_tpu import config as gin
    import tensor2robot_tpu.train_eval  # noqa: F401
    import tensor2robot_tpu.research.pose_env  # noqa: F401
    import tensor2robot_tpu.hooks  # noqa: F401
    import tensor2robot_tpu.data  # noqa: F401

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tensor2robot_tpu", "research", "pose_env", "configs",
        "train_pose_env.gin")
    gin.clear_config()
    try:
      gin.parse_config_files_and_bindings([path], [])
      hooks = [h.resolve() for h in
               gin.query_parameter("train_eval_model.hooks")]
      assert hooks[0]._eval_fn is evaluate_pose_model
      assert hooks[0]._eval_kwargs["num_episodes"] >= 500
    finally:
      gin.clear_config()


class TestMuJoCoPoseEnv:
  """The physics-backed variant: MuJoCo contact dynamics settle the
  block; the label is the SETTLED pose (round 5 — closes the
  numpy-env substitution's physics half; rendering stays numpy, no GL
  stack in the image)."""

  def test_physics_moves_the_block_before_it_settles(self):
    from tensor2robot_tpu.research.pose_env import MuJoCoPoseEnv

    env = MuJoCoPoseEnv(seed=3)
    movements = []
    for _ in range(5):
      obs = env.reset()
      assert obs["image"].shape == (env.image_size, env.image_size, 3)
      movements.append(float(np.linalg.norm(
          env.pose - env.last_drop_pose)))
      assert env.last_settle_steps > 10  # dynamics actually stepped
    # The settled pose is physics-derived, not the commanded drop
    # pose — a kinematic env would move zero.
    assert np.mean(movements) > 0.01, movements

  def test_zero_settle_steps_is_a_config_error_at_init(self):
    """A step budget < 1 must raise at construction (it used to
    surface as a NameError deep inside _settle_once — round-5 advisor
    finding)."""
    import pytest

    from tensor2robot_tpu.research.pose_env import MuJoCoPoseEnv

    with pytest.raises(ValueError, match="max_settle_steps"):
      MuJoCoPoseEnv(seed=0, max_settle_steps=0)

  def test_settled_poses_stay_in_workspace_and_are_deterministic(self):
    from tensor2robot_tpu.research.pose_env import MuJoCoPoseEnv
    from tensor2robot_tpu.research.pose_env.pose_env import (
        WORKSPACE_HIGH,
        WORKSPACE_LOW,
    )

    env_a = MuJoCoPoseEnv(seed=11)
    env_b = MuJoCoPoseEnv(seed=11)
    for _ in range(4):
      env_a.reset()
      env_b.reset()
      assert np.all(env_a.pose >= WORKSPACE_LOW)
      assert np.all(env_a.pose <= WORKSPACE_HIGH)
      np.testing.assert_array_equal(env_a.pose, env_b.pose)

  def test_collect_and_eval_take_the_physics_env(self, tmp_path):
    from tensor2robot_tpu.research.pose_env import (
        MuJoCoPoseEnv,
        collect_random_episodes,
        evaluate_pose_model,
    )

    path = collect_random_episodes(
        str(tmp_path / "physics.tfrecord"), num_episodes=4,
        env_cls=MuJoCoPoseEnv, seed=2)
    assert os.path.exists(path)
    seen = []

    def oracle(batch):
      seen.append(batch["image"].shape)
      return {"inference_output": np.zeros((1, 2), np.float32)}

    metrics = evaluate_pose_model(
        oracle, num_episodes=4, env_cls=MuJoCoPoseEnv, seed=2)
    assert metrics["num_episodes"] == 4.0
    assert len(seen) == 4
    assert np.isfinite(metrics["mean_pose_error"])

  def test_physics_gin_config_parses(self):
    from tensor2robot_tpu import config as gin
    import tensor2robot_tpu.train_eval  # noqa: F401
    import tensor2robot_tpu.research.pose_env  # noqa: F401
    import tensor2robot_tpu.data  # noqa: F401
    import tensor2robot_tpu.hooks  # noqa: F401
    from tensor2robot_tpu.research.pose_env import MuJoCoPoseEnv

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tensor2robot_tpu", "research", "pose_env", "configs",
        "train_pose_env_physics.gin")
    gin.clear_config()
    try:
      gin.parse_config_files_and_bindings([path], [])
      hooks = [h.resolve() for h in
               gin.query_parameter("train_eval_model.hooks")]
      env_cls = hooks[0]._eval_kwargs["env_cls"]
      resolved = env_cls.resolve() if hasattr(env_cls, "resolve") \
          else env_cls
      # The ref may resolve to the class or a factory for it; both
      # must produce the physics env.
      made = resolved() if not isinstance(resolved, type) else resolved
      assert (made is MuJoCoPoseEnv
              or isinstance(made, MuJoCoPoseEnv)), made
    finally:
      gin.clear_config()
