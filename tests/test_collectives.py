"""Collective-audit: pin the collectives GSPMD inserts per mesh layout.

The sharding rules (`parallel/sharding.py`, `parallel/ring_attention.py`)
never call collectives directly — XLA's SPMD partitioner inserts them
from sharding annotations. That indirection is the design (SURVEY.md §3
parallelism: annotate, let XLA insert, profile), but it means a
sharding-rule regression fails SILENTLY: params quietly replicate, the
grad all-reduce disappears, and everything still computes — just slower
and fatter. These tests compile the real sharded train step for each
supported layout and assert on the HLO instruction counts, so the
partitioned program's communication structure is a tested contract:

  * data×fsdp      — gradient all-reduce + zero-style param all-gathers
  * data×fsdp×model — plus tensor-parallel activation reductions
  * data×seq ring   — collective-permutes only (no sequence gather!)

Counts are pinned EXACTLY only where the algorithm forces them (the
MoE dispatch/return all-to-all pair, ring/pipeline permutes, the
zero-gather guarantees). Counts the partitioner/combiner CHOOSES
(fused gradient reduces, resharding all-to-alls, recompute gathers)
are asserted as bounds or as differences between layouts — a compiler
upgrade that merges two reshards is not a regression; a layout whose
param gathers or gradient reduce disappear is.
"""

import functools
import re

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from tensor2robot_tpu import specs
from tensor2robot_tpu.parallel import (
    DATA_AXIS,
    EXPERT_AXIS,
    FSDP_AXIS,
    MODEL_AXIS,
    SEQ_AXIS,
    STAGE_AXIS,
    batch_sharding,
    create_mesh,
    sequence_sharding,
    state_sharding,
)
from tensor2robot_tpu.parallel.ring_attention import ring_attention
from tensor2robot_tpu.research.qtopt import GraspingQModel, QTOptLearner

COLLECTIVES = ("all-reduce", "all-gather", "reduce-scatter",
               "collective-permute", "all-to-all")


def collective_counts(hlo_text: str):
  """Counts collective INSTRUCTIONS (not metadata mentions) in HLO.

  Matches both scalar-typed (`= f32[...] all-reduce(`) and
  tuple-typed (`= (f32[...], ...) all-to-all(`) instruction forms —
  multi-operand collectives (e.g. the MoE all-to-alls) lower to the
  tuple form, which a bare `\\S+` type pattern silently misses. The
  type is matched non-greedily rather than by balancing parens:
  real-TPU HLO embeds tiled layouts like `f32[256,64]{1,0:T(8,128)}`
  whose inner parens would defeat a `\\([^)]*\\)` alternation.
  (`.` does not cross newlines, so the match stays on the
  instruction's own line; async `-done` halves don't match and
  double-count because the op name must be followed directly by `(`.)
  """
  return {
      op: len(re.findall(rf"= .+? {op}(?:-start)?\(", hlo_text))
      for op in COLLECTIVES
  }


@functools.lru_cache(maxsize=None)
def compile_qtopt_step(axes, strategy):
  """The exact sharded-train-step construction train_eval/dryrun use.

  `axes` is a tuple of (name, size) pairs (hashable for the cache —
  the comparative tests diff two layouts without recompiling).
  """
  axes = dict(axes)
  n = int(np.prod(list(axes.values())))
  mesh = create_mesh(axes, devices=jax.devices()[:n])
  model = GraspingQModel(
      image_size=16, torso_filters=(8,), head_filters=(8,),
      dense_sizes=(16,), action_dim=2, device_dtype=jnp.float32)
  learner = QTOptLearner(model, cem_population=8, cem_iterations=1,
                         cem_elites=2)
  state = learner.create_state(jax.random.PRNGKey(0), batch_size=2)
  sharding = state_sharding(mesh, state, strategy=strategy,
                            min_size_to_shard=2 ** 8)
  transitions = specs.make_random_tensors(
      learner.transition_specification(), batch_size=16, seed=0)
  transitions = jax.tree_util.tree_map(jnp.asarray, transitions)
  ds = batch_sharding(mesh)
  step = jax.jit(
      learner.train_step,
      in_shardings=(sharding, ds, NamedSharding(mesh, P())),
      out_shardings=(sharding, NamedSharding(mesh, P())))
  lowered = step.lower(
      jax.device_put(state, sharding), jax.device_put(transitions, ds),
      jax.random.PRNGKey(1))
  return collective_counts(lowered.compile().as_text())


class TestTrainStepCollectives:

  def test_fsdp_mesh_gradient_reduce_and_param_gathers(self):
    counts = compile_qtopt_step(
        ((DATA_AXIS, 4), (FSDP_AXIS, 2)), "fsdp")
    # Gradient + metric reductions over data×fsdp, including the
    # TUPLE-form fused param-gradient all-reduce the pre-fix regex
    # missed entirely (this file asserted `all-reduce == 1` for two
    # rounds because only one scalar-typed reduce matched). Zero
    # would mean device rows silently diverge. How many the combiner
    # fuses into is its choice — pinned in round 4 as exactly 9; a
    # bound survives toolchain bumps.
    assert counts["all-reduce"] >= 1, counts
    # Zero-style param/optimizer sharding: fsdp-sharded tensors
    # all-gather for use (forward + recompute). Near-zero would mean
    # the state silently replicated — the regression this file exists
    # for (measured: 7; the replicated baseline below measures 1).
    assert counts["all-gather"] >= 4, counts
    # This layout has no ring axis: permutes are algorithmically
    # impossible, so that zero IS exact. The all-to-alls are
    # partitioner-chosen reshards (measured: 5) — not pinned.
    assert counts["collective-permute"] == 0, counts

  def test_tp_mesh_adds_tensor_parallel_reductions(self):
    fsdp = compile_qtopt_step(
        ((DATA_AXIS, 4), (FSDP_AXIS, 2)), "fsdp")
    counts = compile_qtopt_step(
        ((DATA_AXIS, 2), (FSDP_AXIS, 2), (MODEL_AXIS, 2)), "tp")
    # Megatron-style partial-sum reductions of activations (forward
    # AND backward) on top of the gradient/metric reduces: strictly
    # more all-reduces and param/activation gathers than the pure-fsdp
    # layout (measured: 15 vs 9 reduces, 41 vs 7 gathers).
    assert counts["all-reduce"] > fsdp["all-reduce"], (counts, fsdp)
    assert counts["all-gather"] > fsdp["all-gather"], (counts, fsdp)

  def test_fsdp_vs_replicated_baseline(self):
    """Same step with NO state sharding: the param gathers disappear.

    Proves the fsdp all-gathers are attributable to the fsdp rules
    (partitioner-chosen input reshard gathers remain here — measured:
    1). The fused tuple gradient all-reduce is still present — with
    replicated state the partitioner still shards the batched compute
    over the mesh and reduces gradients, it just never needs to gather
    parameters. (Rounds 2–3 read this layout as "fully
    de-parallelized, zero all-reduces"; that was the tuple-blind
    regex, not the program.)
    """
    fsdp = compile_qtopt_step(
        ((DATA_AXIS, 4), (FSDP_AXIS, 2)), "fsdp")
    counts = compile_qtopt_step(((DATA_AXIS, 4), (FSDP_AXIS, 2)),
                                "replicated")
    assert counts["all-reduce"] >= 1, counts
    # Re-pin (jax 0.4.37): the replicated baseline's absolute
    # all-gather count is partitioner-CHOSEN input-reshard traffic
    # (measured 1 on the round-4 toolchain, 5 here — the combiner now
    # splits reshards it used to fuse), and this file's own philosophy
    # says chosen counts get bounds or differences, never absolutes.
    # The `<= 2` pin was a disguised absolute; the contract that
    # matters — fsdp param gathers exist ON TOP of whatever reshard
    # gathers the baseline has — is the difference below (measured
    # 10 vs 5).
    assert counts["all-gather"] < fsdp["all-gather"], (fsdp, counts)
    # The zero-style param gathers are the DIFFERENCE between the two
    # layouts, whatever the combiner does within each.
    assert fsdp["all-gather"] - counts["all-gather"] >= 3, (
        fsdp, counts)


class TestRingCollectives:

  @pytest.fixture()
  def qkv_sharded(self):
    mesh = create_mesh({DATA_AXIS: 2, SEQ_AXIS: 4})
    rng = np.random.default_rng(0)
    q, k, v = (jnp.asarray(rng.standard_normal((2, 32, 2, 8)),
                           jnp.float32) for _ in range(3))
    sh = sequence_sharding(mesh)
    return mesh, [jax.device_put(x, sh) for x in (q, k, v)]

  def test_forward_is_permutes_only(self, qkv_sharded):
    mesh, args = qkv_sharded
    fwd = jax.jit(lambda q, k, v: ring_attention(
        q, k, v, mesh=mesh, causal=True))
    counts = collective_counts(fwd.lower(*args).compile().as_text())
    # K and V each rotate via ONE permute inside the scanned ring
    # body. Crucially zero all-gathers: the whole point is that no
    # device ever materializes the full sequence.
    assert counts["collective-permute"] == 2, counts
    assert counts["all-gather"] == 0, counts
    assert counts["all-reduce"] == 0, counts

  def test_backward_permutes_cotangents_around_the_ring(
      self, qkv_sharded):
    mesh, args = qkv_sharded
    grad = jax.jit(jax.grad(
        lambda q, k, v: jnp.sum(ring_attention(
            q, k, v, mesh=mesh, causal=True, block_impl="flash",
            flash_interpret=True) ** 2), argnums=(0, 1, 2)))
    counts = collective_counts(grad.lower(*args).compile().as_text())
    # Flash-block ring is statically unrolled: (ring-1)=3 steps × K,V
    # = 6 forward permutes, mirrored by 6 transposed permutes carrying
    # dk/dv cotangents backward around the ring.
    assert counts["collective-permute"] == 12, counts
    assert counts["all-gather"] == 0, counts


class TestMoECollectives:
  """Expert parallelism: the communication is exactly two all-to-alls.

  Dispatch (tokens out to their experts' devices) and return (expert
  outputs back home). Zero all-gathers: no device ever materializes
  all experts' weights or all devices' tokens — the regression this
  pins is expert weights silently replicating.
  """

  def _module_and_args(self):
    from tensor2robot_tpu.parallel import MoEMLP

    mesh = create_mesh({DATA_AXIS: 2, EXPERT_AXIS: 4})
    module = MoEMLP(num_experts=8, hidden_dim=16, k=2,
                    capacity_factor=2.0, mesh=mesh, dtype=jnp.float32)
    x = jnp.asarray(
        np.random.default_rng(0).standard_normal((4, 16, 8)),
        jnp.float32)
    variables = module.init(jax.random.PRNGKey(0), x)
    return module, variables, x

  def test_forward_is_two_all_to_alls(self):
    module, variables, x = self._module_and_args()
    fwd = jax.jit(
        lambda v, x: module.apply(v, x, mutable=["aux_loss"])[0])
    counts = collective_counts(fwd.lower(variables, x)
                               .compile().as_text())
    assert counts["all-to-all"] == 2, counts
    assert counts["all-gather"] == 0, counts
    assert counts["collective-permute"] == 0, counts

  def test_backward_transposes_to_all_to_alls(self):
    from tensor2robot_tpu.parallel import collect_aux_losses

    module, variables, x = self._module_and_args()

    def loss(params, x):
      out, state = module.apply({"params": params}, x,
                                mutable=["aux_loss"])
      return jnp.sum(out ** 2) + 0.01 * collect_aux_losses(state)

    grad = jax.jit(jax.grad(loss))
    counts = collective_counts(
        grad.lower(variables["params"], x).compile().as_text())
    # Forward's dispatch/return pair + their transposes = 4, minus
    # whatever adjacent pairs XLA's combiner merges (measured: 3).
    # The algorithmic content is bounds: at least the forward pair
    # survives, at most the un-merged 4. The all-reduces (aux pmean +
    # transpose + router gradient reduction) are combiner-chosen;
    # at least one must exist or the router gradient is lost.
    assert 2 <= counts["all-to-all"] <= 4, counts
    assert counts["all-reduce"] >= 1, counts
    assert counts["all-gather"] == 0, counts


class TestPipelineCollectives:
  """Pipeline stages communicate by ppermute inside the tick scan.

  One forward permute (activations one hop down the ring) regardless
  of microbatch count — it lives INSIDE the scanned tick body. The
  backward adds the reversed-loop permute carrying cotangents back up.
  """

  def _stage_and_args(self):
    import flax.linen as nn

    from tensor2robot_tpu.layers.transformer import TransformerBlock
    from tensor2robot_tpu.parallel import (
        init_stage_params,
        pipeline_apply,
        stage_sharding,
    )

    class _Stage(nn.Module):

      @nn.compact
      def __call__(self, x):
        return TransformerBlock(num_heads=2, head_dim=4,
                                dtype=jnp.float32)(x)

    mesh = create_mesh({DATA_AXIS: 2, STAGE_AXIS: 4})
    stage = _Stage()
    x = jnp.asarray(
        np.random.default_rng(1).standard_normal((8, 4, 8)),
        jnp.float32)
    params = init_stage_params(lambda r: stage.init(r, x[:1]),
                               jax.random.PRNGKey(0), 4)
    params = jax.device_put(params, stage_sharding(mesh, params))
    run = lambda p, x: pipeline_apply(  # noqa: E731
        stage.apply, p, x, mesh=mesh, num_microbatches=2)
    return run, params, x

  def test_forward_permutes_once_per_tick(self):
    run, params, x = self._stage_and_args()
    counts = collective_counts(
        jax.jit(run).lower(params, x).compile().as_text())
    assert counts["collective-permute"] == 1, counts
    # The last-stage output broadcast (an explicit psum over the stage
    # ring) forces at least one all-reduce; the entry reshard gathers
    # are partitioner-chosen (measured: 1 each).
    assert counts["all-reduce"] >= 1, counts
    assert counts["all-gather"] <= 2, counts
    assert counts["all-to-all"] == 0, counts

  def test_backward_adds_the_reverse_permute(self):
    run, params, x = self._stage_and_args()
    grad = jax.jit(jax.grad(
        lambda p, x: jnp.sum(run(p, x) ** 2)))
    counts = collective_counts(
        grad.lower(params, x).compile().as_text())
    # Forward permute + the reversed-scan permute carrying activation
    # cotangents back up the ring.
    assert counts["collective-permute"] == 2, counts
    assert counts["all-to-all"] == 0, counts
