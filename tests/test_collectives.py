"""Collective-audit: pin the collectives GSPMD inserts per mesh layout.

The sharding rules (`parallel/sharding.py`, `parallel/ring_attention.py`)
never call collectives directly — XLA's SPMD partitioner inserts them
from sharding annotations. That indirection is the design (SURVEY.md §3
parallelism: annotate, let XLA insert, profile), but it means a
sharding-rule regression fails SILENTLY: params quietly replicate, the
grad all-reduce disappears, and everything still computes — just slower
and fatter. These tests compile the real sharded train step for each
supported layout and assert on the HLO instruction counts, so the
partitioned program's communication structure is a tested contract:

  * data×fsdp      — gradient all-reduce + zero-style param all-gathers
  * data×fsdp×model — plus tensor-parallel activation reductions
  * data×seq ring   — collective-permutes only (no sequence gather!)

Counts are exact for the pinned jax/XLA in the image; if a toolchain
bump legitimately changes them, update the constants alongside a check
that the shape of the communication (which ops, roughly how many) still
matches the layout's story.
"""

import re

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from tensor2robot_tpu import specs
from tensor2robot_tpu.parallel import (
    DATA_AXIS,
    FSDP_AXIS,
    MODEL_AXIS,
    SEQ_AXIS,
    batch_sharding,
    create_mesh,
    sequence_sharding,
    state_sharding,
)
from tensor2robot_tpu.parallel.ring_attention import ring_attention
from tensor2robot_tpu.research.qtopt import GraspingQModel, QTOptLearner

COLLECTIVES = ("all-reduce", "all-gather", "reduce-scatter",
               "collective-permute", "all-to-all")


def collective_counts(hlo_text: str):
  """Counts collective INSTRUCTIONS (not metadata mentions) in HLO."""
  return {
      op: len(re.findall(rf"= \S+ {op}(?:-start)?\(", hlo_text))
      for op in COLLECTIVES
  }


def compile_qtopt_step(axes, strategy):
  """The exact sharded-train-step construction train_eval/dryrun use."""
  n = int(np.prod(list(axes.values())))
  mesh = create_mesh(axes, devices=jax.devices()[:n])
  model = GraspingQModel(
      image_size=16, torso_filters=(8,), head_filters=(8,),
      dense_sizes=(16,), action_dim=2, device_dtype=jnp.float32)
  learner = QTOptLearner(model, cem_population=8, cem_iterations=1,
                         cem_elites=2)
  state = learner.create_state(jax.random.PRNGKey(0), batch_size=2)
  sharding = state_sharding(mesh, state, strategy=strategy,
                            min_size_to_shard=2 ** 8)
  transitions = specs.make_random_tensors(
      learner.transition_specification(), batch_size=16, seed=0)
  transitions = jax.tree_util.tree_map(jnp.asarray, transitions)
  ds = batch_sharding(mesh)
  step = jax.jit(
      learner.train_step,
      in_shardings=(sharding, ds, NamedSharding(mesh, P())),
      out_shardings=(sharding, NamedSharding(mesh, P())))
  lowered = step.lower(
      jax.device_put(state, sharding), jax.device_put(transitions, ds),
      jax.random.PRNGKey(1))
  return collective_counts(lowered.compile().as_text())


class TestTrainStepCollectives:

  def test_fsdp_mesh_gradient_reduce_and_param_gathers(self):
    counts = compile_qtopt_step({DATA_AXIS: 4, FSDP_AXIS: 2}, "fsdp")
    # One fused gradient all-reduce over data×fsdp. Zero would mean
    # each device row trains on its own shard and silently diverges.
    assert counts["all-reduce"] == 1, counts
    # Zero-style param/optimizer sharding: every fsdp-sharded tensor
    # all-gathers for use (forward + recompute). Zero would mean the
    # state silently replicated — the regression this file exists for.
    # (Was 9 before the round-4 CEM-head concatenate rewrite; the
    # head restructure let GSPMD merge two gathers.)
    assert counts["all-gather"] == 7, counts
    # This layout needs no permutes / transposes of the batch.
    assert counts["collective-permute"] == 0, counts
    assert counts["all-to-all"] == 0, counts

  def test_tp_mesh_adds_tensor_parallel_reductions(self):
    counts = compile_qtopt_step(
        {DATA_AXIS: 2, FSDP_AXIS: 2, MODEL_AXIS: 2}, "tp")
    # Megatron-style partial-sum reductions of activations (forward
    # AND backward) on top of the gradient reduce: strictly more
    # all-reduces than the pure-fsdp layout's single fused one.
    assert counts["all-reduce"] == 6, counts
    assert counts["all-gather"] == 41, counts
    assert counts["all-to-all"] == 0, counts

  def test_fsdp_vs_replicated_baseline(self):
    """Same step with NO state sharding: the param gathers disappear.

    Proves the all-gathers above are attributable to the fsdp rules.
    Instructive wrinkle this pins: with every output replicated and
    the model this tiny, the cost-based partitioner decides sharded
    compute isn't worth it — it gathers the batch inputs and runs the
    step replicated, so there is no gradient all-reduce at all (one
    fused input all-gather since the round-4 CEM-head rewrite; three
    separate ones before). Exactly the silent de-parallelization mode
    this audit exists to surface: replicated-state DP leaves the
    sharding decision to a cost model, while the fsdp/tp rules above
    FORCE distributed state and thereby sharded compute.
    """
    counts = compile_qtopt_step({DATA_AXIS: 4, FSDP_AXIS: 2},
                                "replicated")
    assert counts["all-reduce"] == 0, counts
    assert counts["all-gather"] == 1, counts


class TestRingCollectives:

  @pytest.fixture()
  def qkv_sharded(self):
    mesh = create_mesh({DATA_AXIS: 2, SEQ_AXIS: 4})
    rng = np.random.default_rng(0)
    q, k, v = (jnp.asarray(rng.standard_normal((2, 32, 2, 8)),
                           jnp.float32) for _ in range(3))
    sh = sequence_sharding(mesh)
    return mesh, [jax.device_put(x, sh) for x in (q, k, v)]

  def test_forward_is_permutes_only(self, qkv_sharded):
    mesh, args = qkv_sharded
    fwd = jax.jit(lambda q, k, v: ring_attention(
        q, k, v, mesh=mesh, causal=True))
    counts = collective_counts(fwd.lower(*args).compile().as_text())
    # K and V each rotate via ONE permute inside the scanned ring
    # body. Crucially zero all-gathers: the whole point is that no
    # device ever materializes the full sequence.
    assert counts["collective-permute"] == 2, counts
    assert counts["all-gather"] == 0, counts
    assert counts["all-reduce"] == 0, counts

  def test_backward_permutes_cotangents_around_the_ring(
      self, qkv_sharded):
    mesh, args = qkv_sharded
    grad = jax.jit(jax.grad(
        lambda q, k, v: jnp.sum(ring_attention(
            q, k, v, mesh=mesh, causal=True, block_impl="flash",
            flash_interpret=True) ** 2), argnums=(0, 1, 2)))
    counts = collective_counts(grad.lower(*args).compile().as_text())
    # Flash-block ring is statically unrolled: (ring-1)=3 steps × K,V
    # = 6 forward permutes, mirrored by 6 transposed permutes carrying
    # dk/dv cotangents backward around the ring.
    assert counts["collective-permute"] == 12, counts
    assert counts["all-gather"] == 0, counts
