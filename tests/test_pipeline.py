"""Pipeline parallelism: schedule exactness + gradients through the ring.

The property under test: `pipeline_apply` over a stage mesh computes
EXACTLY the sequential composition of its stages — the GPipe schedule
(scan over ticks + ppermute) is pure plumbing. The sequential fallback
(mesh=None) doubles as the oracle.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import flax.linen as nn

from tensor2robot_tpu.layers.transformer import TransformerBlock
from tensor2robot_tpu.parallel import (
    DATA_AXIS,
    STAGE_AXIS,
    create_mesh,
    init_stage_params,
    pipeline_apply,
    stage_sharding,
)


class _Stage(nn.Module):
  """One pipeline stage: a shape-preserving transformer block."""

  @nn.compact
  def __call__(self, x):
    return TransformerBlock(num_heads=2, head_dim=4,
                            dtype=jnp.float32)(x)


def _build(num_stages, rng=0, batch=8, t=4, width=8):
  stage = _Stage()
  x = jnp.asarray(
      np.random.default_rng(rng).standard_normal((batch, t, width)),
      jnp.float32)
  params = init_stage_params(
      lambda r: stage.init(r, x[:1]), jax.random.PRNGKey(rng),
      num_stages)
  return stage, params, x


def _sequential(stage, params, x):
  for s in range(jax.tree_util.tree_leaves(params)[0].shape[0]):
    p = jax.tree_util.tree_map(lambda l, s=s: l[s], params)
    x = stage.apply(p, x)
  return x


class TestSequentialFallback:

  @pytest.mark.parametrize("remat", [False, True])
  def test_no_stage_axis_matches_loop(self, remat):
    stage, params, x = _build(num_stages=3)
    out = pipeline_apply(stage.apply, params, x, mesh=None,
                         num_microbatches=2, remat=remat)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_sequential(stage, params, x)),
        atol=1e-6)
    if remat:  # the fallback's remat branch must also differentiate
      g = jax.grad(lambda p: jnp.sum(pipeline_apply(
          stage.apply, p, x, mesh=None, num_microbatches=2,
          remat=True) ** 2))(params)
      assert all(np.isfinite(np.asarray(l)).all()
                 for l in jax.tree_util.tree_leaves(g))


class TestPipelinedSchedule:

  @pytest.fixture(params=[
      {STAGE_AXIS: 4},
      {DATA_AXIS: 2, STAGE_AXIS: 4},
      {STAGE_AXIS: 8},
  ])
  def mesh(self, request):
    n = int(np.prod(list(request.param.values())))
    return create_mesh(request.param, devices=jax.devices()[:n])

  @pytest.mark.parametrize("num_microbatches", [1, 2, 4])
  def test_matches_sequential(self, mesh, num_microbatches):
    num_stages = mesh.shape[STAGE_AXIS]
    stage, params, x = _build(num_stages)
    sharded = jax.device_put(params, stage_sharding(mesh, params))
    out = jax.jit(lambda p, x: pipeline_apply(
        stage.apply, p, x, mesh=mesh,
        num_microbatches=num_microbatches))(sharded, x)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_sequential(stage, params, x)),
        atol=1e-5)

  def test_gradients_flow_back_up_the_ring(self, mesh):
    """grad through the pipelined schedule == grad of the sequential
    composition, for params of EVERY stage (cotangents must ppermute
    backward through all of them) and for the input."""
    num_stages = mesh.shape[STAGE_AXIS]
    stage, params, x = _build(num_stages)

    def loss_pipe(p, x):
      return jnp.sum(pipeline_apply(
          stage.apply, p, x, mesh=mesh, num_microbatches=2) ** 2)

    def loss_seq(p, x):
      return jnp.sum(_sequential(stage, p, x) ** 2)

    sharded = jax.device_put(params, stage_sharding(mesh, params))
    gp, gx = jax.jit(jax.grad(loss_pipe, argnums=(0, 1)))(sharded, x)
    sp, sx = jax.grad(loss_seq, argnums=(0, 1))(params, x)
    for (path, a), b in zip(
        jax.tree_util.tree_leaves_with_path(sp),
        jax.tree_util.tree_leaves(gp)):
      assert float(np.abs(np.asarray(a)).max()) > 0.0, (
          jax.tree_util.keystr(path))  # the oracle itself is nonzero
      # rtol covers f32 accumulation-order noise on large-magnitude
      # grads (deep stage stacks compound to |g| ~ 1e2-1e3).
      np.testing.assert_allclose(
          np.asarray(b), np.asarray(a), rtol=1e-4, atol=1e-4,
          err_msg=jax.tree_util.keystr(path))
    np.testing.assert_allclose(np.asarray(gx), np.asarray(sx),
                               rtol=1e-4, atol=1e-4)

  def test_remat_gradients_match_non_remat(self, mesh):
    """remat=True recomputes activations but must change NOTHING
    about values: forward and per-stage gradients identical."""
    num_stages = mesh.shape[STAGE_AXIS]
    stage, params, x = _build(num_stages)
    sharded = jax.device_put(params, stage_sharding(mesh, params))

    def loss(remat):
      def fn(p, x):
        return jnp.sum(pipeline_apply(
            stage.apply, p, x, mesh=mesh, num_microbatches=2,
            remat=remat) ** 2)
      return fn

    # Forward values first: remat must not perturb the primal.
    fwd = lambda remat: jax.jit(lambda p, x: pipeline_apply(  # noqa: E731
        stage.apply, p, x, mesh=mesh, num_microbatches=2,
        remat=remat))(sharded, x)
    np.testing.assert_allclose(np.asarray(fwd(True)),
                               np.asarray(fwd(False)), atol=1e-6)

    g_plain = jax.jit(jax.grad(loss(False)))(sharded, x)
    g_remat = jax.jit(jax.grad(loss(True)))(sharded, x)
    # Same rtol as the schedule-gradient test: recompute order shifts
    # f32 accumulation on deep stage stacks (|g| ~ 1e2-1e3).
    for (path, a), b in zip(
        jax.tree_util.tree_leaves_with_path(g_plain),
        jax.tree_util.tree_leaves(g_remat)):
      np.testing.assert_allclose(
          np.asarray(b), np.asarray(a), rtol=1e-4, atol=1e-4,
          err_msg=jax.tree_util.keystr(path))

  def test_rejects_indivisible_batch(self, mesh):
    stage, params, x = _build(mesh.shape[STAGE_AXIS], batch=6)
    data = mesh.shape.get(DATA_AXIS, 1)
    bad = 4 if (6 % (4 * data)) else 5
    with pytest.raises(ValueError, match="must be a multiple"):
      pipeline_apply(stage.apply, params, x, mesh=mesh,
                     num_microbatches=bad)
