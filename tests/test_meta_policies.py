"""Export-and-adapt through serving: the meta-model robot handoff.

VERDICT r2 item 4 / SURVEY §3 `meta_learning/meta_policies.py`: a
trained MAML model and a trained SNAIL model are exported to SavedModel
via jax2tf and driven through `SavedModelPredictor` + `MetaPolicy` with
demonstration conditioning. The bar is behavioral: adapted predictions
must measurably beat unadapted / wrong-demonstration ones THROUGH THE
EXPORTED ARTIFACT, not just through the python model class.
"""

import numpy as np
import pytest

import jax

from tensor2robot_tpu.data.abstract_input_generator import Mode
from tensor2robot_tpu.export import SavedModelExportGenerator
from tensor2robot_tpu.meta_learning import MAMLModel, MetaPolicy
from tensor2robot_tpu.models import optimizers as opt_lib
from tensor2robot_tpu.predictors import (
    CheckpointPredictor,
    SavedModelPredictor,
)
from tensor2robot_tpu.specs import (
    ExtendedTensorSpec,
    TensorSpecStruct,
)
from tensor2robot_tpu.utils.mocks import MockT2RModel

N_COND, N_INF = 8, 8


class SineModel(MockT2RModel):
  """Scalar regression base: x -> a*sin(x + phase), per-task (a, phase)."""

  def get_feature_specification(self, mode):
    st = TensorSpecStruct()
    st.x = ExtendedTensorSpec(shape=(1,), dtype=np.float32, name="x")
    return st

  def get_label_specification(self, mode):
    st = TensorSpecStruct()
    st.target = ExtendedTensorSpec(shape=(1,), dtype=np.float32,
                                   name="target")
    return st


def _sample_sine_tasks(rng, num_tasks, n):
  phases = rng.uniform(0, np.pi, (num_tasks, 1, 1))
  amps = rng.uniform(0.5, 2.0, (num_tasks, 1, 1))
  x = rng.uniform(-np.pi, np.pi, (num_tasks, n, 1)).astype(np.float32)
  y = (amps * np.sin(x + phases)).astype(np.float32)
  return x, y, phases, amps


@pytest.fixture(scope="module")
def trained_maml(tmp_path_factory):
  """Meta-trains the sine MAML and exports it to SavedModel."""
  model = MAMLModel(
      base_model=SineModel(output_size=1, hidden_sizes=(32, 32)),
      num_inner_steps=3, inner_lr=0.1,
      num_condition_samples_per_task=N_COND,
      num_inference_samples_per_task=N_INF,
      create_optimizer_fn=lambda: opt_lib.create_optimizer(
          optimizer_name="adam", learning_rate=1e-3),
  )
  state = model.create_train_state(jax.random.PRNGKey(0))
  train_step = jax.jit(model.train_step)
  rng = np.random.default_rng(0)
  for i in range(200):
    x, y, _, _ = _sample_sine_tasks(rng, 16, N_COND + N_INF)
    feats = TensorSpecStruct.from_flat_dict({
        "condition/x": x[:, :N_COND], "inference/x": x[:, N_COND:]})
    labels = TensorSpecStruct.from_flat_dict({
        "condition/target": y[:, :N_COND],
        "inference/target": y[:, N_COND:]})
    state, _ = train_step(state, feats, labels, jax.random.PRNGKey(i))

  model_dir = str(tmp_path_factory.mktemp("maml_export"))
  export_dir = SavedModelExportGenerator().export(
      model, jax.device_get(state), model_dir)
  return model, state, model_dir, export_dir


def _task_error(policy, rng, with_demos, wrong_demos=False):
  """Mean |prediction − truth| over fresh tasks through the policy."""
  errors = []
  for _ in range(8):
    x, y, phase, amp = _sample_sine_tasks(rng, 1, N_COND + 1)
    demo_x, demo_y = x[0, :N_COND], y[0, :N_COND]
    query_x, query_y = x[0, -1], y[0, -1]
    if with_demos:
      if wrong_demos:
        # Anti-task: same inputs, labels from the phase-shifted task.
        demo_y = (amp[0] * np.sin(demo_x + phase[0] + np.pi)
                  ).astype(np.float32)
      policy.set_task({"x": demo_x}, {"target": demo_y})
    else:
      policy.reset_task()
    out = policy.predict({"x": query_x})
    prediction = np.asarray(
        out.get("inference_output", next(iter(out.values()))))
    errors.append(float(np.abs(prediction.reshape(-1)[0]
                               - query_y[0])))
  return float(np.mean(errors))


@pytest.mark.slow
class TestMAMLThroughSavedModel:

  def test_policy_infers_meta_layout(self, trained_maml):
    _, _, _, export_dir = trained_maml
    predictor = SavedModelPredictor(export_dir + "/..")
    # export() returns the timestamped dir; the predictor polls the base.
    predictor = SavedModelPredictor(
        export_dir.rsplit("/", 1)[0])
    assert predictor.restore(timeout_secs=0)
    policy = MetaPolicy(predictor)
    assert policy.num_condition == N_COND
    assert policy.num_inference == N_INF

  def test_adapted_beats_wrong_demos_through_export(self, trained_maml):
    _, _, _, export_dir = trained_maml
    predictor = SavedModelPredictor(export_dir.rsplit("/", 1)[0])
    assert predictor.restore(timeout_secs=0)
    policy = MetaPolicy(predictor)
    adapted = _task_error(policy, np.random.default_rng(7),
                          with_demos=True)
    anti = _task_error(policy, np.random.default_rng(7),
                       with_demos=True, wrong_demos=True)
    # Conditioning on the true task's demonstrations must matter
    # through the exported artifact: the anti-task demos steer the
    # adapted model the wrong way.
    assert adapted < anti * 0.7, (adapted, anti)

  def test_adapted_beats_zero_shot_through_checkpoint(self,
                                                      trained_maml):
    model, state, model_dir, _ = trained_maml
    predictor = CheckpointPredictor(model, checkpoint_dir=model_dir)
    predictor._state = jax.device_get(state)  # serve in-memory state
    predictor._restored_step = int(np.asarray(state.step))
    policy = MetaPolicy(predictor)
    adapted = _task_error(policy, np.random.default_rng(3),
                          with_demos=True)
    zero_shot = _task_error(policy, np.random.default_rng(3),
                            with_demos=False)
    assert adapted < zero_shot * 0.8, (adapted, zero_shot)


@pytest.mark.slow
class TestPoseEnvMAMLThroughSavedModel:
  """The research-family MAML (pose_env) through the exported artifact.

  Task family: per-task constant pose offsets (a miscalibrated camera
  per task); demonstrations reveal the offset, adaptation must absorb
  it. The bar is behavioral through the SavedModel: adapted
  predictions track each task's offset direction.
  """

  @pytest.fixture(scope="class")
  def trained_pose_maml(self, tmp_path_factory):
    from tensor2robot_tpu.research.pose_env import PoseEnv
    from tensor2robot_tpu.research.pose_env.pose_env_maml_models import (
        PoseEnvRegressionModelMAML,
    )

    nc = ni = 4
    model = PoseEnvRegressionModelMAML(
        image_size=24, filters=(8, 16), embedding_size=32,
        hidden_sizes=(32,), num_inner_steps=2, inner_lr=0.1,
        num_condition_samples_per_task=nc,
        num_inference_samples_per_task=ni,
        create_optimizer_fn=lambda: opt_lib.create_optimizer(
            optimizer_name="adam", learning_rate=1e-3),
    )
    state = model.create_train_state(jax.random.PRNGKey(0))
    train_step = jax.jit(model.train_step)
    env = PoseEnv(image_size=24, seed=0)
    rng = np.random.default_rng(0)

    def meta_batch(num_tasks=8):
      offsets = rng.uniform(-0.3, 0.3, (num_tasks, 1, 2)
                            ).astype(np.float32)
      images, poses = [], []
      for _ in range(num_tasks):
        task_i, task_p = [], []
        for _ in range(nc + ni):
          obs = env.reset()
          task_i.append(obs["image"])
          task_p.append(env.pose)
        images.append(np.stack(task_i))
        poses.append(np.stack(task_p))
      images = np.stack(images)
      targets = np.stack(poses) + offsets  # per-task miscalibration
      feats = TensorSpecStruct.from_flat_dict({
          "condition/image": images[:, :nc],
          "inference/image": images[:, nc:]})
      labels = TensorSpecStruct.from_flat_dict({
          "condition/target_pose": targets[:, :nc],
          "inference/target_pose": targets[:, nc:]})
      return feats, labels, offsets

    for i in range(150):
      feats, labels, _ = meta_batch()
      state, _ = train_step(state, feats, labels, jax.random.PRNGKey(i))

    model_dir = str(tmp_path_factory.mktemp("pose_maml_export"))
    # batch_polymorphic=False: symbolic batch dims can't trace through
    # the conv encoder under the per-task vmap; serving uses task
    # batch 1 (exactly what MetaPolicy feeds).
    export_dir = SavedModelExportGenerator(
        include_tf_example_signature=False,
        batch_polymorphic=False).export(
            model, jax.device_get(state), model_dir)
    return model, export_dir, env

  def test_adaptation_absorbs_task_offset_through_export(
      self, trained_pose_maml):
    from tensor2robot_tpu.research.pose_env import PoseEnv

    _, export_dir, _ = trained_pose_maml
    predictor = SavedModelPredictor(export_dir.rsplit("/", 1)[0])
    assert predictor.restore(timeout_secs=0)
    policy = MetaPolicy(predictor)

    env = PoseEnv(image_size=24, seed=77)
    rng = np.random.default_rng(7)
    shifts = []
    for _ in range(6):
      offset = rng.uniform(-0.3, 0.3, (2,)).astype(np.float32)
      demo_images, demo_targets = [], []
      for _ in range(4):
        obs = env.reset()
        demo_images.append(obs["image"])
        demo_targets.append(env.pose + offset)
      query = env.reset()
      policy.set_task(
          {"image": np.stack(demo_images)},
          {"target_pose": np.stack(demo_targets).astype(np.float32)})
      adapted = np.asarray(policy.predict({"image": query["image"]})[
          "inference_output"]).reshape(-1)[:2]
      policy.set_task(
          {"image": np.stack(demo_images)},
          {"target_pose": np.stack(
              [t - offset for t in demo_targets]).astype(np.float32)})
      unshifted = np.asarray(policy.predict({"image": query["image"]})[
          "inference_output"]).reshape(-1)[:2]
      # Adaptation on offset demos must move predictions along the
      # offset direction relative to zero-offset demos.
      delta = adapted - unshifted
      shifts.append(float(np.dot(delta, offset)
                          / (np.linalg.norm(offset) ** 2 + 1e-8)))
    # On average the adapted shift recovers a substantial fraction of
    # the task offset, proven through the exported SavedModel.
    assert np.mean(shifts) > 0.3, shifts


@pytest.mark.slow
class TestSNAILThroughSavedModel:

  @pytest.fixture(scope="class")
  def trained_snail(self, tmp_path_factory):
    """Trains the vrgripper SNAIL on copy-the-demo-action tasks.

    Task structure: every step of a task shares one constant action
    (the task id in disguise), observable ONLY through the
    demonstration actions — pure in-context conditioning.
    """
    from tensor2robot_tpu.research.vrgripper import (
        VRGripperSNAILModel,
    )

    nc = ni = 4
    model = VRGripperSNAILModel(
        image_size=16, filters=(8,), embedding_size=16,
        snail_filters=16, num_condition_samples_per_task=nc,
        num_inference_samples_per_task=ni,
        create_optimizer_fn=lambda: opt_lib.create_optimizer(
            optimizer_name="adam", learning_rate=2e-3),
    )
    state = model.create_train_state(jax.random.PRNGKey(0))
    train_step = jax.jit(model.train_step)
    rng = np.random.default_rng(0)

    def meta_batch(num_tasks=8):
      action = rng.uniform(-1, 1, (num_tasks, 1, 3)).astype(np.float32)
      def obs(n):
        return {
            "image": rng.integers(
                0, 255, (num_tasks, n, 16, 16, 3)).astype(np.uint8),
            "gripper_pose": rng.normal(
                size=(num_tasks, n, 3)).astype(np.float32),
        }
      cond, inf = obs(nc), obs(ni)
      feats = TensorSpecStruct.from_flat_dict({
          **{f"condition/{k}": v for k, v in cond.items()},
          **{f"inference/{k}": v for k, v in inf.items()}})
      labels = TensorSpecStruct.from_flat_dict({
          "condition/action": np.tile(action, (1, nc, 1)),
          "inference/action": np.tile(action, (1, ni, 1))})
      return feats, labels

    for i in range(120):
      feats, labels = meta_batch()
      state, metrics = train_step(state, feats, labels,
                                  jax.random.PRNGKey(i))
    model_dir = str(tmp_path_factory.mktemp("snail_export"))
    export_dir = SavedModelExportGenerator(
        include_tf_example_signature=False).export(
            model, jax.device_get(state), model_dir)
    return model, export_dir

  def test_demo_actions_condition_exported_model(self, trained_snail):
    _, export_dir = trained_snail
    predictor = SavedModelPredictor(export_dir.rsplit("/", 1)[0])
    assert predictor.restore(timeout_secs=0)
    policy = MetaPolicy(predictor)

    rng = np.random.default_rng(5)
    obs = {
        "image": rng.integers(0, 255, (16, 16, 3)).astype(np.uint8),
        "gripper_pose": rng.normal(size=(3,)).astype(np.float32),
    }
    demo_obs = {
        "image": rng.integers(0, 255, (4, 16, 16, 3)).astype(np.uint8),
        "gripper_pose": rng.normal(size=(4, 3)).astype(np.float32),
    }
    errors = []
    for target in (np.float32([0.8, -0.5, 0.3]),
                   np.float32([-0.7, 0.6, -0.2])):
      demos = np.tile(target[None], (4, 1))
      policy.set_task(demo_obs, {"action": demos})
      out = policy.predict(obs)
      prediction = np.asarray(out["action"]).reshape(-1)
      errors.append(float(np.abs(prediction - target).mean()))
    # The exported SNAIL must track whichever demonstration actions it
    # is conditioned on — the same observation maps to both targets.
    assert max(errors) < 0.25, errors
