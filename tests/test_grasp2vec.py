"""Tests for the Grasp2Vec research family.

Same learning-sanity depth as the other families (SURVEY.md §5): the
synthetic scenes have real compositional structure, so the tests assert
that embedding arithmetic actually learns — retrieval decisively beats
chance through the predictor, matched goals out-score mismatched ones —
not just that shapes line up.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensor2robot_tpu.telemetry.records import read_records
from tensor2robot_tpu import train_eval
from tensor2robot_tpu.data.abstract_input_generator import Mode
from tensor2robot_tpu.data.tfrecord_input_generator import (
    TFRecordInputGenerator,
)
from tensor2robot_tpu.models import optimizers as opt_lib
from tensor2robot_tpu.predictors import CheckpointPredictor
from tensor2robot_tpu.research.grasp2vec import (
    GOAL_EMBEDDING,
    GOAL_REWARD,
    Grasp2VecModel,
    GraspSceneGenerator,
    POSTGRASP_EMBEDDING,
    PREGRASP_EMBEDDING,
    SCENE_SPATIAL,
    collect_grasp_triplets,
    evaluate_retrieval,
    goal_localization_heatmap,
    goal_similarity_reward,
    heatmap_argmax,
    npairs_loss,
)

IMG = 32
NUM_TYPES = 4


def tiny_model(**kwargs):
  kwargs.setdefault(
      "create_optimizer_fn",
      lambda: opt_lib.create_optimizer(learning_rate=1e-3))
  return Grasp2VecModel(
      image_size=IMG, embedding_size=32, stage_sizes=(1,),
      num_filters=8, **kwargs)


class TestSceneGenerator:

  def test_triplet_shapes_and_structure(self):
    gen = GraspSceneGenerator(image_size=IMG, num_object_types=NUM_TYPES,
                              num_distractors=2, seed=0)
    t = gen.sample()
    for key in ("pregrasp_image", "postgrasp_image", "goal_image"):
      assert t[key].shape == (IMG, IMG, 3)
      assert t[key].dtype == np.uint8
    # Post differs from pre exactly where the target was removed.
    diff = np.any(t["pregrasp_image"] != t["postgrasp_image"], axis=-1)
    assert diff.any()
    cy, cx = np.argwhere(diff).mean(axis=0)
    tx, ty = t["target_center"]
    # Painted region centers on target_center (paint is [y, x]-indexed).
    assert abs(cy - ty) < 3 and abs(cx - tx) < 3

  def test_goal_gallery_one_image_per_type(self):
    gen = GraspSceneGenerator(image_size=IMG, num_object_types=NUM_TYPES)
    gallery = gen.goal_gallery()
    assert gallery.shape == (NUM_TYPES, IMG, IMG, 3)
    # All gallery entries pairwise distinct (distinct palette colors).
    for i in range(NUM_TYPES):
      for j in range(i + 1, NUM_TYPES):
        assert (gallery[i] != gallery[j]).any()


class TestNPairsLoss:

  def test_aligned_embeddings_score_lower(self):
    rng = np.random.default_rng(0)
    emb = jnp.asarray(rng.normal(size=(8, 16)).astype(np.float32))
    aligned, _ = npairs_loss(emb, emb)
    shuffled, _ = npairs_loss(emb, jnp.roll(emb, 3, axis=0))
    assert float(aligned) < float(shuffled)

  def test_duplicate_ids_are_not_penalized(self):
    emb = jnp.eye(4, 8, dtype=jnp.float32) * 4.0
    # Rows 0 and 1 are the same object: retrieval of either is correct.
    ids = jnp.asarray([7, 7, 2, 3])
    dup = emb.at[1].set(emb[0])
    loss_dup, metrics = npairs_loss(dup, dup, object_ids=ids)
    assert float(metrics["retrieval_top1"]) == 1.0
    loss_unique, _ = npairs_loss(emb, emb, object_ids=None)
    # Duplicates with id-aware targets shouldn't blow the loss up vs
    # the unique-rows case.
    assert float(loss_dup) < float(loss_unique) + 1.0

  def test_goal_similarity_reward_signs(self):
    d = 8
    obj = jnp.zeros((1, d)).at[0, 2].set(3.0)
    pre = obj + 1.0
    post = jnp.ones((1, d))
    match = goal_similarity_reward(pre, post, obj)
    mismatch = goal_similarity_reward(
        pre, post, jnp.zeros((1, d)).at[0, 5].set(3.0))
    assert float(match[0]) > 0.99
    assert float(mismatch[0]) < 0.1


class TestHeatmap:

  def test_localization_peaks_at_matching_location(self):
    b, h, w, d = 2, 5, 6, 8
    spatial = np.zeros((b, h, w, d), np.float32)
    goal = np.zeros((b, d), np.float32)
    goal[0, 1] = 1.0
    goal[1, 3] = 1.0
    spatial[0, 2, 4, 1] = 5.0   # object 0 lives at (2, 4)
    spatial[1, 4, 0, 3] = 5.0
    heat = goal_localization_heatmap(
        jnp.asarray(spatial), jnp.asarray(goal), temperature=0.1)
    rows, cols = heatmap_argmax(heat)
    assert (int(rows[0]), int(cols[0])) == (2, 4)
    assert (int(rows[1]), int(cols[1])) == (4, 0)
    np.testing.assert_allclose(np.asarray(heat.sum(axis=(1, 2))), 1.0,
                               rtol=1e-5)


@pytest.mark.slow
class TestGrasp2VecEndToEnd:

  @pytest.fixture(scope="class")
  def run(self, tmp_path_factory):
    """collect → train → checkpoint, shared across asserts."""
    root = tmp_path_factory.mktemp("g2v_e2e")
    data_path = collect_grasp_triplets(
        str(root / "train.tfrecord"), num_episodes=192, image_size=IMG,
        num_object_types=NUM_TYPES, num_distractors=1, seed=0)
    model = tiny_model()
    model_dir = str(root / "model")
    train_eval.train_eval_model(
        model=model,
        model_dir=model_dir,
        input_generator_train=TFRecordInputGenerator(
            file_patterns=data_path, shuffle_buffer_size=192, seed=1),
        input_generator_eval=TFRecordInputGenerator(
            file_patterns=data_path, shuffle=False, repeat=False),
        max_train_steps=120,
        eval_steps=2,
        batch_size=16,
        save_checkpoints_steps=120,
        log_every_steps=20,
    )
    return model, model_dir

  def test_loss_decreases(self, run):
    _, model_dir = run
    records = read_records(
        os.path.join(model_dir, "metrics_train.jsonl"))
    assert records[-1]["loss"] < records[0]["loss"]

  def test_in_batch_retrieval_learns(self, run):
    _, model_dir = run
    records = read_records(
        os.path.join(model_dir, "metrics_train.jsonl"))
    # Chance is ~1/16 plus duplicate mass; learned should be decisive.
    assert records[-1]["retrieval_top1"] > 0.5

  def test_gallery_retrieval_through_predictor(self, run):
    model, model_dir = run
    predictor = CheckpointPredictor(model, checkpoint_dir=model_dir)
    assert predictor.restore(timeout_secs=0)
    metrics = evaluate_retrieval(
        predictor.predict, num_queries=32, image_size=IMG,
        num_object_types=NUM_TYPES, num_distractors=1, seed=9)
    assert metrics["chance_top1"] == pytest.approx(1.0 / NUM_TYPES)
    # Decisively above chance (0.25): embedding arithmetic must have
    # isolated the removed object, not the scene background.
    assert metrics["retrieval_top1"] >= 0.6

  def test_matched_goal_outscores_mismatched(self, run):
    model, model_dir = run
    predictor = CheckpointPredictor(model, checkpoint_dir=model_dir)
    assert predictor.restore(timeout_secs=0)
    gen = GraspSceneGenerator(image_size=IMG,
                              num_object_types=NUM_TYPES,
                              num_distractors=1, seed=11)
    triplets = [gen.sample() for _ in range(16)]
    batch = {k: np.stack([t[k] for t in triplets])
             for k in ("pregrasp_image", "postgrasp_image",
                       "goal_image")}
    out = predictor.predict(batch)
    matched = np.asarray(out[GOAL_REWARD])
    # Mismatched: pair each scene with the NEXT query's goal image.
    batch["goal_image"] = np.roll(batch["goal_image"], 1, axis=0)
    ids = np.array([int(t["object_id"]) for t in triplets])
    keep = ids != np.roll(ids, 1)  # only truly different objects
    mismatched = np.asarray(predictor.predict(batch)[GOAL_REWARD])
    assert matched.mean() > mismatched[keep].mean() + 0.2

  def test_savedmodel_export_round_trip(self, run):
    """jax2tf export serves the same embeddings as the checkpoint."""
    from tensor2robot_tpu.export import SavedModelExportGenerator
    from tensor2robot_tpu.predictors import SavedModelPredictor
    from tensor2robot_tpu.utils import checkpoints as ckpt_lib

    model, model_dir = run
    state = model.create_inference_state(jax.random.PRNGKey(0))
    variables = ckpt_lib.restore_variables(
        model_dir, like={"params": state.params,
                         "batch_stats": state.batch_stats or {}})
    state = state.replace(params=variables["params"],
                          batch_stats=variables["batch_stats"])
    export_dir = SavedModelExportGenerator(
        include_tf_example_signature=False).export(
            model, jax.device_get(state), model_dir)
    predictor = SavedModelPredictor(export_dir.rsplit("/", 1)[0])
    assert predictor.restore(timeout_secs=0)

    gen = GraspSceneGenerator(image_size=IMG,
                              num_object_types=NUM_TYPES,
                              num_distractors=1, seed=21)
    triplets = [gen.sample() for _ in range(4)]
    batch = {k: np.stack([t[k] for t in triplets])
             for k in ("pregrasp_image", "postgrasp_image",
                       "goal_image")}
    exported = predictor.predict(batch)
    checkpoint = CheckpointPredictor(model, checkpoint_dir=model_dir)
    assert checkpoint.restore(timeout_secs=0)
    native = checkpoint.predict(batch)
    for key in (PREGRASP_EMBEDDING, GOAL_EMBEDDING, GOAL_REWARD):
      np.testing.assert_allclose(
          np.asarray(exported[key]), np.asarray(native[key]),
          atol=2e-2, rtol=2e-2)

  def test_predict_outputs_complete(self, run):
    model, model_dir = run
    predictor = CheckpointPredictor(model, checkpoint_dir=model_dir)
    assert predictor.restore(timeout_secs=0)
    gen = GraspSceneGenerator(image_size=IMG,
                              num_object_types=NUM_TYPES, seed=5)
    t = gen.sample()
    out = predictor.predict(
        {k: t[k][None] for k in ("pregrasp_image", "postgrasp_image",
                                 "goal_image")})
    for key in (PREGRASP_EMBEDDING, POSTGRASP_EMBEDDING, GOAL_EMBEDDING,
                GOAL_REWARD, SCENE_SPATIAL):
      assert key in out and np.isfinite(np.asarray(out[key])).all()
    assert np.asarray(out[SCENE_SPATIAL]).ndim == 4


class TestGoalConditionedRewardHandoff:
  """The paper's pipeline: grasp2vec labels goal-conditioned QT-Opt."""

  @pytest.mark.slow
  def test_reward_separates_matched_from_mismatched(self, run=None):
    # Train a quick model inline (class-scoped e2e fixture lives in
    # another class); tiny and fast is enough for separation.
    import jax
    from tensor2robot_tpu.research.grasp2vec import (
        make_grasp2vec_reward_fn,
    )
    from tensor2robot_tpu.specs import TensorSpecStruct

    model = tiny_model()
    state = model.create_train_state(jax.random.PRNGKey(0))
    gen = GraspSceneGenerator(image_size=IMG,
                              num_object_types=NUM_TYPES,
                              num_distractors=1, seed=0)
    train_step = jax.jit(model.train_step)
    import jax.numpy as jnp_
    for i in range(120):
      triplets = [gen.sample() for _ in range(16)]
      feats = TensorSpecStruct.from_flat_dict({
          k: jnp_.asarray(np.stack([t[k] for t in triplets]))
          for k in ("pregrasp_image", "postgrasp_image", "goal_image")})
      labels = TensorSpecStruct.from_flat_dict({
          "object_id": jnp_.asarray(
              np.stack([t["object_id"] for t in triplets]))})
      state, _ = train_step(state, feats, labels, jax.random.PRNGKey(i))

    reward_fn = make_grasp2vec_reward_fn(model, state, threshold=0.5)
    eval_gen = GraspSceneGenerator(image_size=IMG,
                                   num_object_types=NUM_TYPES,
                                   num_distractors=1, seed=7)
    triplets = [eval_gen.sample() for _ in range(24)]
    pre = np.stack([t["pregrasp_image"] for t in triplets])
    post = np.stack([t["postgrasp_image"] for t in triplets])
    goal = np.stack([t["goal_image"] for t in triplets])
    ids = np.array([int(t["object_id"]) for t in triplets])

    matched = reward_fn(pre, post, goal)
    rolled = np.roll(goal, 1, axis=0)
    keep = ids != np.roll(ids, 1)
    mismatched = reward_fn(pre, post, rolled)
    # Self-supervised success labels: matched mostly 1, mismatched
    # (different object) mostly 0.
    assert matched["reward"].mean() > 0.75, matched["reward"].mean()
    assert mismatched["reward"][keep].mean() < 0.3
    self._state = (model, state)  # reuse in the relabel test

  def test_relabeled_transitions_train_goal_conditioned_qtopt(self):
    import jax
    import jax.numpy as jnp_
    from tensor2robot_tpu.research.grasp2vec import (
        GOAL_EMBEDDING_FEATURE,
        make_grasp2vec_reward_fn,
        relabel_transitions,
    )
    from tensor2robot_tpu.research.qtopt import (
        GraspingQModel,
        QTOptLearner,
        ReplayBuffer,
    )

    g2v = tiny_model()
    g2v_state = g2v.create_train_state(jax.random.PRNGKey(0))
    reward_fn = make_grasp2vec_reward_fn(g2v, g2v_state, threshold=0.4)

    gen = GraspSceneGenerator(image_size=IMG,
                              num_object_types=NUM_TYPES,
                              num_distractors=1, seed=3)
    triplets = [gen.sample() for _ in range(16)]
    rng = np.random.default_rng(0)
    transitions = relabel_transitions(
        reward_fn,
        np.stack([t["pregrasp_image"] for t in triplets]),
        np.stack([t["postgrasp_image"] for t in triplets]),
        np.stack([t["goal_image"] for t in triplets]),
        actions=rng.uniform(-1, 1, (16, 2)).astype(np.float32),
    )
    assert set(np.unique(transitions["reward"])) <= {0.0, 1.0}

    # Goal-conditioned Q: ψ(goal) rides as an extra state feature.
    q_model = GraspingQModel(
        image_size=IMG, action_dim=2, torso_filters=(8,),
        head_filters=(8,), dense_sizes=(16,),
        extra_state_features={
            GOAL_EMBEDDING_FEATURE: (g2v.embedding_size,)})
    learner = QTOptLearner(q_model, cem_population=4,
                           cem_iterations=1, cem_elites=2)
    spec_keys = set(learner.transition_specification().to_flat_dict())
    assert set(transitions) == spec_keys, (
        set(transitions) ^ spec_keys)
    replay = ReplayBuffer(learner.transition_specification(),
                          capacity=64)
    replay.add(transitions)
    state = learner.create_state(jax.random.PRNGKey(1))
    batch = replay.sample(8)
    batch = jax.tree_util.tree_map(jnp_.asarray, batch)
    state, metrics = jax.jit(learner.train_step)(
        state, batch, jax.random.PRNGKey(3))
    assert np.isfinite(float(metrics["loss"]))


class TestShippedConfig:

  def test_config_parses_and_builds_model(self):
    from tensor2robot_tpu import config as gin
    import tensor2robot_tpu.train_eval  # noqa: F401 registers
    import tensor2robot_tpu.research.grasp2vec  # noqa: F401
    import tensor2robot_tpu.data  # noqa: F401
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tensor2robot_tpu", "research", "grasp2vec", "configs",
        "train_grasp2vec.gin")
    gin.clear_config()
    try:
      gin.parse_config_files_and_bindings([path], [])
      model = gin.query_parameter("train_eval_model.model").resolve()
      assert model.get_feature_specification(Mode.TRAIN) is not None
      assert model.embedding_size == 128
    finally:
      gin.clear_config()
