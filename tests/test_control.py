"""Closed-loop control plane tests (ISSUE 18, docs/CONTROL.md).

Pins the policy plane's contracts:

  * the rule grammar — window means, hysteresis re-arm bands,
    sustained-breach streaks, EWMA baselines that absorb only healthy
    values, per-second rate kinds, per-role `aggregate="each"`;
  * the controller — per-rule cooldowns, the GLOBAL rate-based
    actuation budget, deterministic rule-order precedence under that
    budget, dry-run (charges cooldown + budget, never touches an
    actuator, never silences a page), decision records that validate
    under the telemetry envelope schema;
  * escalation tiers — the sentinel's act tier routes through
    `Controller.handle_alert`, a successful remediation DEMOTES a
    page, and flight records stay the terminal tier;
  * the package is jax-free (subprocess pin) and inside the t2rcheck
    CON3xx / IMP401 scopes;
  * (slow) the e2e remediation smoke: a killed front replica is
    detected, respawned at its index under the front restart budget,
    and rejoins a live `ServingRouter` via the observer seam with no
    manual step.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time

import pytest

from tensor2robot_tpu.control import actuators as actuators_lib
from tensor2robot_tpu.control import controller as controller_lib
from tensor2robot_tpu.control import policies as policies_lib
from tensor2robot_tpu.control import rules as rules_lib
from tensor2robot_tpu.control.actuators import (
    ActuationError,
    Actuator,
    DegradationLadder,
    fleet_actuators,
)
from tensor2robot_tpu.control.controller import (
    Controller,
    OUTCOMES,
    read_decisions,
)
from tensor2robot_tpu.control.rules import ControlRule, RuleState
from tensor2robot_tpu.telemetry import metrics as tmetrics
from tensor2robot_tpu.telemetry import records as trecords
from tensor2robot_tpu.telemetry import sentinel as sentinel_lib

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _rule(**kw):
  base = dict(name="r", metric="m", action="act", kind="above",
              threshold=10.0)
  base.update(kw)
  return ControlRule(**base)


def _evaluate_series(rule, values, t0=1000.0, dt=1.0):
  """Feeds `values` one second apart; returns the trigger bitmap."""
  state = RuleState(rule.window)
  out = []
  for i, value in enumerate(values):
    result = rules_lib.evaluate(rule, state, value, now=t0 + i * dt)
    out.append(result["triggered"])
  return out


class _Lever:
  """One recording actuator; optionally always-raises."""

  def __init__(self, fail=False):
    self.calls = []
    self._fail = fail

  def __call__(self, params, decision):
    if self._fail:
      raise ActuationError("broken lever")
    self.calls.append((dict(params), decision["rule"]))
    return {"ok": True}


def _controller(rules, lever=None, **kw):
  lever = lever if lever is not None else _Lever()
  kw.setdefault("registry", tmetrics.MetricsRegistry())
  ctrl = Controller(
      rules, {"act": Actuator("act", lever)}, **kw)
  return ctrl, lever


class TestRuleGrammar:

  def test_window_mean_and_sustain(self):
    rule = _rule(window=2, sustain=2)
    # Window means: [20]=20, [20,0]=10 (not >10), [0,30]=15, [30,30]=30
    # — the sustain streak only completes on the 4th observation.
    assert _evaluate_series(rule, [20.0, 0.0, 30.0, 30.0]) == [
        False, False, False, True]

  def test_hysteresis_rearm_band(self):
    rule = _rule(threshold=10.0, clear=5.0, cooldown_secs=0.0)
    # Fires at 12; stays DISARMED through 12 and 7 (inside the band);
    # re-arms only at 4 (<= clear); fires again at 12.
    assert _evaluate_series(rule, [12.0, 12.0, 7.0, 4.0, 12.0]) == [
        True, False, False, False, True]

  def test_clear_must_sit_on_healthy_side(self):
    with pytest.raises(ValueError):
      _rule(kind="above", threshold=10.0, clear=11.0)
    with pytest.raises(ValueError):
      _rule(kind="below", threshold=10.0, clear=9.0)

  def test_ewma_drop_baseline_ignores_breaches(self):
    rule = _rule(kind="ewma_drop", threshold=0.5, warmup=2, alpha=0.5,
                 cooldown_secs=0.0, clear=None)
    state = RuleState(rule.window)
    for i, value in enumerate([1.0, 1.0]):  # warmup: never fires
      result = rules_lib.evaluate(rule, state, value, now=1000.0 + i)
      assert not result["triggered"]
    # A 70% drop against the ~1.0 baseline fires...
    result = rules_lib.evaluate(rule, state, 0.3, now=1002.0)
    assert result["triggered"] and result["baseline"] == pytest.approx(
        1.0)
    # ...and the breach value did NOT drag the baseline down (only
    # healthy observations feed the EWMA).
    assert state.ewma == pytest.approx(1.0)

  def test_rate_above_per_second(self):
    rule = _rule(kind="rate_above", threshold=5.0, warmup=1,
                 cooldown_secs=0.0)
    state = RuleState(rule.window)
    # First observation only establishes the counter baseline.
    assert not rules_lib.evaluate(rule, state, 100.0,
                                  now=1000.0)["triggered"]
    # +20 over 2s = 10/s > 5/s; the computed rate rides in the
    # result's baseline (value stays the raw counter reading).
    result = rules_lib.evaluate(rule, state, 120.0, now=1002.0)
    assert result["triggered"]
    assert result["baseline"] == pytest.approx(10.0)

  def test_each_aggregate_resolves_roles(self):
    scalars = {"front0/perf.mfu": 0.4, "front1/perf.mfu": 0.1,
               "learner/perf.mfu": 0.5, "perf.mfux": 9.9}
    targets = rules_lib.resolve_metric("perf.mfu", "each", scalars)
    assert targets == [("front0/perf.mfu", 0.4),
                       ("front1/perf.mfu", 0.1),
                       ("learner/perf.mfu", 0.5)]
    # Folding aggregates collapse to the bare metric name.
    assert rules_lib.resolve_metric("perf.mfu", "max", scalars) == [
        ("perf.mfu", 0.5)]

  def test_bad_kind_and_aggregate_rejected(self):
    with pytest.raises(ValueError):
      _rule(kind="sideways")
    with pytest.raises(ValueError):
      _rule(aggregate="median")


class TestController:

  def test_cooldown_pin(self):
    ctrl, lever = _controller(
        [_rule(cooldown_secs=60.0)], max_actions=10)
    ctrl.step({"m": 20.0}, now=1000.0)
    ctrl.step({"m": 20.0}, now=1001.0)  # hysteresis: still disarmed
    outcomes = [d["outcome"] for d in ctrl.decisions]
    assert outcomes == ["actuated"]
    # Re-arm (no clear → re-arms on any non-breach), breach again
    # INSIDE the cooldown: triggered but skipped, and the skip is
    # recorded with the remaining cooldown.
    ctrl.step({"m": 1.0}, now=1002.0)
    ctrl.step({"m": 20.0}, now=1003.0)
    assert [d["outcome"] for d in ctrl.decisions] == [
        "actuated", "cooldown"]
    assert ctrl.decisions[-1]["cooldown_remaining_secs"] > 0
    assert len(lever.calls) == 1
    # Past the cooldown the same breach actuates again.
    ctrl.step({"m": 1.0}, now=1070.0)
    ctrl.step({"m": 20.0}, now=1071.0)
    assert len(lever.calls) == 2

  def test_global_budget_and_rule_order_determinism(self):
    # Two rules breach in the same pass with ONE action of budget:
    # table order decides, deterministically, who gets it.
    rules = [_rule(name="first", cooldown_secs=0.0),
             _rule(name="second", cooldown_secs=0.0)]
    for _ in range(3):  # determinism: same outcome every time
      ctrl, lever = _controller(
          [r for r in rules], max_actions=1, budget_window_secs=0.0)
      ctrl.step({"m": 20.0}, now=1000.0)
      by_rule = {d["rule"]: d["outcome"] for d in ctrl.decisions}
      assert by_rule == {"first": "actuated", "second": "budget"}
      assert [r for _, r in lever.calls] == ["first"]
      assert ctrl.budget_remaining(1000.0) == 0

  def test_budget_window_slides(self):
    ctrl, lever = _controller(
        [_rule(cooldown_secs=0.0)], max_actions=1,
        budget_window_secs=30.0)
    ctrl.step({"m": 20.0}, now=1000.0)
    ctrl.step({"m": 1.0}, now=1001.0)
    ctrl.step({"m": 20.0}, now=1002.0)  # budget spent
    assert [d["outcome"] for d in ctrl.decisions] == [
        "actuated", "budget"]
    ctrl.step({"m": 1.0}, now=1030.0)
    ctrl.step({"m": 20.0}, now=1040.0)  # window slid: budget back
    assert [d["outcome"] for d in ctrl.decisions][-1] == "actuated"
    assert len(lever.calls) == 2

  def test_dry_run_never_actuates_but_charges(self):
    lever = _Lever(fail=True)  # would raise if ever applied
    ctrl, _ = _controller(
        [_rule(name="a", cooldown_secs=0.0),
         _rule(name="b", cooldown_secs=0.0)],
        lever=lever, dry_run=True, max_actions=1,
        budget_window_secs=0.0)
    ctrl.step({"m": 20.0}, now=1000.0)
    by_rule = {d["rule"]: d["outcome"] for d in ctrl.decisions}
    # Dry-run charges the budget exactly like live mode — the
    # would-act log IS the live actuation schedule.
    assert by_rule == {"a": "would_act", "b": "budget"}
    assert ctrl.stats()["actuated"] == 0

  def test_actuator_error_is_contained(self):
    ctrl, _ = _controller([_rule()], lever=_Lever(fail=True))
    ctrl.step({"m": 20.0}, now=1000.0)
    decision = ctrl.decisions[-1]
    assert decision["outcome"] == "error"
    assert "broken lever" in decision["error"]
    assert ctrl.stats()["error"] == 1

  def test_unknown_action_rejected_at_construction(self):
    with pytest.raises(ValueError, match="unknown actuator"):
      Controller([_rule(action="warp_core")],
                 {"act": Actuator("act", _Lever())},
                 registry=tmetrics.MetricsRegistry())
    with pytest.raises(ValueError, match="duplicate"):
      Controller([_rule(), _rule()],
                 {"act": Actuator("act", _Lever())},
                 registry=tmetrics.MetricsRegistry())

  def test_decision_records_validate(self, tmp_path):
    path = str(tmp_path / "control_decisions.jsonl")
    ctrl, _ = _controller(
        [_rule(cooldown_secs=60.0, aggregate="each")], max_actions=10,
        decisions_path=path)
    ctrl.step({"front0/m": 20.0, "front1/m": 1.0}, step=7,
              now=1000.0)
    ctrl.step({"front0/m": 1.0}, now=1001.0)
    ctrl.step({"front0/m": 20.0}, now=1002.0)  # cooldown skip
    ctrl.close()
    records = read_decisions(path)
    assert len(records) == 2
    for record in records:
      trecords.validate_record(record)  # envelope schema holds
    first = records[0]
    assert first["step"] == 7
    assert first["role"] == "front0"  # per-role targeting recorded
    assert first["payload"]["control.r.outcome"] == float(
        OUTCOMES.index("actuated"))
    assert first["payload"]["control.r.actuated"] == 1.0
    assert records[1]["payload"]["control.r.outcome"] == float(
        OUTCOMES.index("cooldown"))

  def test_handle_alert_remediation_and_fallthrough(self):
    ctrl, lever = _controller(
        [_rule(alert="mfu_drop", cooldown_secs=0.0)], max_actions=10)
    alert = {"rule": "mfu_drop", "metric": "front0/perf.mfu",
             "value": 0.1, "role": "front0"}
    assert ctrl.handle_alert(alert) is True
    assert lever.calls and lever.calls[-1][1] == "r"
    # An alert no rule is bound to falls through (the page proceeds;
    # `alert_unhandled` only counts BOUND alerts whose remediation
    # did not actuate, so it stays zero here).
    assert ctrl.handle_alert({"rule": "who", "value": 0.0}) is False
    assert ctrl.stats()["alert_handled"] == 1
    assert ctrl.stats()["alert_unhandled"] == 0

  def test_dry_run_alert_never_silences_pages(self):
    ctrl, _ = _controller(
        [_rule(alert="mfu_drop", cooldown_secs=0.0)], dry_run=True)
    assert ctrl.handle_alert(
        {"rule": "mfu_drop", "value": 0.1}) is False


class TestEscalationTiers:
  """Sentinel severities map to tiers: log → act → page, with the
  controller's act hook demoting remediated pages (ISSUE 18)."""

  def _watch(self, severity):
    return sentinel_lib.Watch(name="w", metric="m", kind="above",
                              threshold=10.0, warmup=0,
                              severity=severity)

  def test_act_severity_routes_through_hook_and_never_pages(self):
    acted, paged = [], []
    sentinel = sentinel_lib.Sentinel(
        [self._watch("act")], on_act=lambda a: acted.append(a) or True,
        on_page=lambda a: paged.append(a),
        registry=tmetrics.MetricsRegistry())
    [record] = sentinel.evaluate({"m": 20.0})
    assert record["escalation"] == "act" and record["handled"]
    assert acted and not paged

  def test_remediated_page_demotes(self):
    paged = []
    registry = tmetrics.MetricsRegistry()
    sentinel = sentinel_lib.Sentinel(
        [self._watch("page")], on_act=lambda a: True,
        on_page=lambda a: paged.append(a), registry=registry)
    [record] = sentinel.evaluate({"m": 20.0})
    assert record["escalation"] == "act"  # demoted: no flight record
    assert not paged
    assert registry.scalars()["alert.remediated"] == 1.0

  def test_unremediated_page_escalates(self):
    paged = []
    registry = tmetrics.MetricsRegistry()
    sentinel = sentinel_lib.Sentinel(
        [self._watch("page")], on_act=lambda a: False,
        on_page=lambda a: paged.append(a), registry=registry)
    [record] = sentinel.evaluate({"m": 20.0})
    assert record["escalation"] == "page" and not record["handled"]
    assert paged
    assert registry.scalars()["alert.paged"] == 1.0

  def test_page_without_hooks_still_pages(self):
    registry = tmetrics.MetricsRegistry()
    sentinel = sentinel_lib.Sentinel([self._watch("page")],
                                     registry=registry)
    [record] = sentinel.evaluate({"m": 20.0})
    assert record["escalation"] == "page"
    assert registry.scalars()["alert.paged"] == 1.0


class TestDegradationLadder:

  def test_shed_order_exhaustion_and_restore(self):
    retunes = []
    ladder = DegradationLadder(
        ("bulk", "batch"),
        retune=lambda t, rate_rps=None: retunes.append((t, rate_rps)),
        shed_rate_rps=2.0)
    assert ladder.shed_next() == "bulk"
    assert ladder.shed_next() == "batch"
    assert ladder.shed_next() is None  # exhausted → next rule pages
    assert retunes == [("bulk", 2.0), ("batch", 2.0)]
    assert ladder.restore() == ("bulk", "batch")
    assert retunes[-2:] == [("bulk", None), ("batch", None)]


class TestStandardPolicyTable:

  def test_fleet_rules_resolve_against_fleet_actuators(self):
    class _FakeFleet:
      num_actors, num_fronts = 2, 1
      def scale_to(self, n): pass
      def scale_fronts_to(self, n): pass
      def kick(self, role): pass
      def retune_admission(self, tenant, **kw): return {}
    rules = policies_lib.fleet_rules(env_steps_per_sec_min=10.0,
                                     env_steps_per_sec_max=100.0)
    # Construction validates: unique names, every action resolves.
    ctrl = Controller(
        rules, fleet_actuators(_FakeFleet()),
        registry=tmetrics.MetricsRegistry())
    assert [r.name for r in ctrl.rules][0] == "slow_host_respawn"
    # The slow-host rule is the sentinel's mfu_drop remediation and
    # evaluates per role (it must name WHO to kick).
    slow = ctrl.rules[0]
    assert slow.alert == "mfu_drop" and slow.aggregate == "each"
    # Degradation precedes restore; page never appears (paging is the
    # sentinel's fallback, not a standing rule).
    names = [r.name for r in ctrl.rules]
    assert names.index("overload_shed") < names.index(
        "recovered_restore")
    assert all(r.action != "page" for r in ctrl.rules)

  def test_offered_load_prescale_rule(self):
    # Predictive pre-scale (ISSUE 19): default OFF; when a slope bound
    # is set, a rate_above rule on the admitted-rows counter scales
    # the front tier BEFORE the reactive p95 rule can breach — so it
    # must sit ahead of front_p95_scale_up in actuation priority.
    base = [r.name for r in policies_lib.fleet_rules()]
    assert "front_offered_prescale" not in base
    rules = policies_lib.fleet_rules(offered_load_slope_max=200.0,
                                     tenant="policy", max_fronts=3)
    names = [r.name for r in rules]
    assert names.index("front_offered_prescale") < names.index(
        "front_p95_scale_up")
    rule = next(r for r in rules
                if r.name == "front_offered_prescale")
    assert rule.kind == "rate_above"
    assert rule.metric == "serving.policy.admission.admitted"
    assert rule.threshold == 200.0
    assert rule.action == "scale_fronts"
    assert rule.action_params == {"delta": 1, "min": 1, "max": 3}
    # Worst replica's offered load, not the average: one hot front
    # must be enough to pre-scale.
    assert rule.aggregate == "max"

  def test_respawn_role_requires_concrete_role(self):
    acts = fleet_actuators(object())
    with pytest.raises(ActuationError):
      acts["respawn_role"].apply({}, {"role": "fleet"})


class TestPackageScope:

  def test_control_package_is_jax_free(self):
    code = (
        "import sys; "
        "import tensor2robot_tpu.control; "
        "import tensor2robot_tpu.control.rules, "
        "tensor2robot_tpu.control.controller, "
        "tensor2robot_tpu.control.actuators, "
        "tensor2robot_tpu.control.policies; "
        "assert 'jax' not in sys.modules, 'jax leaked'; "
        "print('JAXFREE')")
    result = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=120, cwd=REPO)
    assert result.returncode == 0, result.stderr
    assert "JAXFREE" in result.stdout

  def test_control_is_in_t2rcheck_scopes(self):
    from tensor2robot_tpu.analysis import cli
    from tensor2robot_tpu.analysis import import_rules

    assert "tensor2robot_tpu/control" in cli._CONCURRENCY_PATHS
    assert "tensor2robot_tpu.control" in \
        import_rules.WORKER_SAFE_MODULES


@pytest.mark.slow
class TestFleetRemediationEndToEnd:
  """The seeded e2e smoke: kill a front replica under a live fleet —
  supervision detects it, respawns it at its index under the front
  restart budget, and the observer seam rejoins it to a real
  `ServingRouter` via `mark_alive` with NO manual step."""

  def test_killed_front_respawns_and_rejoins_router(self, tmp_path):
    import numpy as np

    from tensor2robot_tpu.fleet.orchestrator import Fleet, FleetConfig
    from tensor2robot_tpu.serving.router import ServingRouter

    config = FleetConfig(
        num_actors=1, env="mujoco_pose", image_size=16, action_dim=2,
        torso_filters=(8,), head_filters=(8,), dense_sizes=(16,),
        cem_population=8, cem_iterations=1, cem_elites=2,
        batch_size=8, batch_episodes=2, max_train_steps=2000,
        publish_every_steps=1000, serve_max_batch=4,
        transport="tcp", front_hosts=2, front_tenants=("a", "b"),
        front_respawn=True, max_front_restarts=2,
        telemetry_poll_secs=0.0, launch_timeout_secs=240.0,
        run_timeout_secs=900.0, seed=0)
    fleet = Fleet(config, str(tmp_path))
    events = []
    fleet.launch()
    try:
      router = ServingRouter(dict(fleet._addresses["fronts"]),
                             authkey=config.authkey, transport="tcp")
      try:
        def observer(event, index, address):
          events.append((event, index))
          if event in ("respawned", "added"):
            router.mark_alive(index, address)
          else:
            router.mark_dead(index)
        fleet.add_front_observer(observer)

        from tensor2robot_tpu.specs import make_random_tensors
        import jax  # noqa: F401 — spec sampling only
        from tensor2robot_tpu.fleet.host import _build_learner
        learner = _build_learner(config)
        obs = make_random_tensors(
            learner.observation_specification(), batch_size=1, seed=0)
        for tenant in ("a", "b"):
          assert np.asarray(router.predict(tenant, obs)).size > 0

        victim = router.placement("a")[0]
        fleet._fronts[victim].kill()
        deadline = time.monotonic() + 300.0
        while time.monotonic() < deadline:
          fleet._supervise_once()
          if any(r["target"] == f"front-{victim}"
                 for r in fleet.recoveries):
            break
          time.sleep(0.2)
        else:
          pytest.fail(f"front {victim} never recovered; "
                      f"events={events}")

        # Recovery accounting: a real MTTR, NO membership shrink.
        [recovery] = [r for r in fleet.recoveries
                      if r["target"] == f"front-{victim}"]
        assert recovery["mttr_ms"] > 0
        assert fleet.front_failures == []
        assert ("respawned", victim) in events
        # The respawned replica is live placement again — predicts
        # for its tenants answer without any manual rejoin.
        assert victim in router.alive()
        for tenant in ("a", "b"):
          assert np.asarray(router.predict(tenant, obs)).size > 0
        # ...and it SURVIVES that traffic: mark_alive flushed the
        # stale pre-kill sockets, so the respawned replica is not
        # demoted straight back to dead by its first checkout (a
        # failure mode failover masks whenever another replica
        # exists).
        assert victim in router.alive()
      finally:
        router.close()
    finally:
      fleet.shutdown(collect_metrics=False)
