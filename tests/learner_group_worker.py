"""Worker binary for the learner-group N=1 bitwise pin.

Launched twice by tests/test_fleet.py (TestLearnerGroup): once
`plain` (single learner, jax.distributed never initialized) and once
`group` (adopt an ephemeral coordinator → `maybe_initialize_distributed`
— exactly the bring-up `fleet.learner` runs when `learner_hosts > 1`,
collapsed to world_size=1). Both run the identical seeded train_qtopt
recipe and dump the final params; the parent compares BITWISE. The
ISSUE-19 acceptance pin: the group machinery at N=1 IS the
single-learner path, not an approximation of it.

Usage: learner_group_worker.py {plain|group} <out.npz> <model_dir>
"""

import os
import sys
import types

os.environ["JAX_PLATFORMS"] = "cpu"

mode, outfile, model_dir = sys.argv[1], sys.argv[2], sys.argv[3]
assert mode in ("plain", "group"), mode

if mode == "group":
  from tensor2robot_tpu.fleet import proc
  from tensor2robot_tpu.parallel.distributed import (
      ephemeral_coordinator_address,
  )

  # The fleet orchestrator's handoff: coordinator address via the env
  # launch contract, adopted before jax wakes up.
  proc.adopt_coordinator(ephemeral_coordinator_address(),
                         num_processes=1, process_id=0)

import numpy as np  # noqa: E402
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from tensor2robot_tpu.parallel import (  # noqa: E402
    maybe_initialize_distributed,
)

initialized = maybe_initialize_distributed()
if mode == "group":
  assert initialized, "group trigger did not fire"
  assert jax.process_count() == 1
else:
  assert not initialized, "plain worker must stay un-distributed"

from tensor2robot_tpu.fleet.learner import (  # noqa: E402
    learner_group_plan,
)
from tensor2robot_tpu.models import optimizers as opt_lib  # noqa: E402
from tensor2robot_tpu.research.qtopt import (  # noqa: E402
    GraspingQModel,
    QTOptLearner,
    ReplayBuffer,
    train_qtopt,
)
from tensor2robot_tpu.specs import make_random_tensors  # noqa: E402


def main():
  model = GraspingQModel(
      image_size=16, action_dim=2, torso_filters=(8,),
      head_filters=(8,), dense_sizes=(16,),
      create_optimizer_fn=lambda: opt_lib.create_optimizer(
          learning_rate=1e-3))
  learner = QTOptLearner(model, cem_population=8, cem_iterations=1,
                         cem_elites=2)
  spec = learner.transition_specification()
  replay = ReplayBuffer(spec, capacity=64, seed=7)
  replay.add(make_random_tensors(spec, batch_size=64, seed=3))
  # The group path sizes its feed through the plan; at world_size=1
  # the local shard IS the global batch.
  plan = learner_group_plan(
      types.SimpleNamespace(batch_size=8), world_size=1, rank=0)
  assert plan["publishes"]
  state = train_qtopt(
      learner=learner,
      model_dir=model_dir,
      replay_buffer=replay,
      max_train_steps=6,
      batch_size=plan["local_batch_size"] if mode == "group" else 8,
      save_checkpoints_steps=6,
      log_every_steps=3,
      seed=0)
  params = jax.device_get(state.train_state.params)
  leaves = jax.tree_util.tree_leaves_with_path(params)
  np.savez(outfile, **{jax.tree_util.keystr(path): np.asarray(leaf)
                       for path, leaf in leaves})
  print("BITWISE_OK", mode)


if __name__ == "__main__":
  main()
