"""Checkpoint portability across mesh layouts (restore-with-resharding).

A pod training run and a single-chip serving run (or a relayout after
a topology change) must share checkpoints: orbax restores against a
`like` tree whose shardings the restored arrays ADOPT
(`utils/checkpoints._abstract_like`). These tests pin that contract
for the new layouts — expert-sharded MoE states and stage-stacked
pipeline params — value-exact in both directions.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import flax.linen as nn

from tensor2robot_tpu.layers.transformer import (
    CausalTransformer,
    TransformerBlock,
)
from tensor2robot_tpu.parallel import (
    DATA_AXIS,
    EXPERT_AXIS,
    STAGE_AXIS,
    create_mesh,
    expert_sharding,
    init_stage_params,
    stage_sharding,
)
from tensor2robot_tpu.utils import checkpoints as ckpt_lib


def _values_equal(a, b):
  for (path, x), y in zip(jax.tree_util.tree_leaves_with_path(a),
                          jax.tree_util.tree_leaves(b)):
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(x)), np.asarray(jax.device_get(y)),
        err_msg=jax.tree_util.keystr(path))


def _save(tmp_path, tree):
  writer = ckpt_lib.CheckpointWriter(str(tmp_path))
  writer.save(0, tree)
  writer.close()


class TestExpertShardedCheckpoints:

  def test_ep_state_restores_replicated_and_back(self, tmp_path):
    """Pod(ep) → single-chip(replicated) → pod(ep): values survive
    both relayouts exactly and restored leaves carry the target
    shardings."""
    mesh = create_mesh({DATA_AXIS: 2, EXPERT_AXIS: 4})
    model = CausalTransformer(width=16, depth=2, num_heads=2,
                              max_len=8, dtype=jnp.float32,
                              moe_experts=8, moe_every=2)
    x = jnp.asarray(
        np.random.default_rng(0).standard_normal((2, 8, 8)),
        jnp.float32)
    params = model.init(jax.random.PRNGKey(0), x)["params"]
    sharded = jax.device_put(
        params, expert_sharding(mesh, params, min_size_to_shard=64))
    _save(tmp_path, sharded)

    # Restore replicated (single-process serving shape).
    host = jax.tree_util.tree_map(np.asarray, params)
    restored_host = ckpt_lib.restore_state(str(tmp_path), like=host)
    _values_equal(restored_host, sharded)

    # Restore back onto the expert layout: leaves adopt the sharding.
    restored_ep = ckpt_lib.restore_state(str(tmp_path), like=sharded)
    _values_equal(restored_ep, sharded)
    ew = restored_ep["block1"]["moe"]["moe_expert_w_in"]
    assert ew.sharding.spec[0] == EXPERT_AXIS, ew.sharding

  def test_fsdp_trained_state_restores_onto_expert_mesh(self, tmp_path):
    """A checkpoint written under one rule set restores under another
    (relayout after topology change) — same bytes, new placement."""
    from tensor2robot_tpu.parallel import FSDP_AXIS, fsdp_sharding

    mesh_a = create_mesh({DATA_AXIS: 4, FSDP_AXIS: 2})
    model = CausalTransformer(width=16, depth=2, num_heads=2,
                              max_len=8, dtype=jnp.float32,
                              moe_experts=4, moe_every=2)
    x = jnp.asarray(
        np.random.default_rng(1).standard_normal((2, 8, 8)),
        jnp.float32)
    params = model.init(jax.random.PRNGKey(1), x)["params"]
    under_fsdp = jax.device_put(
        params, fsdp_sharding(mesh_a, params, min_size_to_shard=64))
    _save(tmp_path, under_fsdp)

    mesh_b = create_mesh({DATA_AXIS: 2, EXPERT_AXIS: 4})
    like = jax.device_put(
        params, expert_sharding(mesh_b, params, min_size_to_shard=64))
    restored = ckpt_lib.restore_state(str(tmp_path), like=like)
    _values_equal(restored, under_fsdp)
    ew = restored["block1"]["moe"]["moe_expert_w_in"]
    assert ew.sharding.spec[0] == EXPERT_AXIS, ew.sharding


class TestStageShardedCheckpoints:

  def test_pipeline_stage_params_roundtrip(self, tmp_path):
    class _Stage(nn.Module):

      @nn.compact
      def __call__(self, x):
        return TransformerBlock(num_heads=2, head_dim=4,
                                dtype=jnp.float32)(x)

    mesh = create_mesh({DATA_AXIS: 2, STAGE_AXIS: 4})
    stage = _Stage()
    x = jnp.asarray(
        np.random.default_rng(2).standard_normal((4, 4, 8)),
        jnp.float32)
    params = init_stage_params(lambda r: stage.init(r, x[:1]),
                               jax.random.PRNGKey(2), 4)
    sharded = jax.device_put(params, stage_sharding(mesh, params))
    _save(tmp_path, sharded)

    host = jax.tree_util.tree_map(np.asarray, params)
    restored_host = ckpt_lib.restore_state(str(tmp_path), like=host)
    _values_equal(restored_host, sharded)

    restored_staged = ckpt_lib.restore_state(str(tmp_path),
                                             like=sharded)
    _values_equal(restored_staged, sharded)
    leaf = jax.tree_util.tree_leaves(restored_staged)[0]
    assert leaf.sharding.spec[0] == STAGE_AXIS, leaf.sharding


class TestRulesSeamReshardRoundtrip:
  """ISSUE 12 satellite: the gather/shard-fns reshard contract.

  A checkpoint saved under a 1-DEVICE mesh restores onto the
  8-virtual-device fsdp mesh via `restore_state_on_mesh` (layout from
  the rules table, not from `like`'s placement), and a checkpoint
  saved from THAT sharded state restores back onto the 1-device mesh
  — params bitwise both ways, gathered through
  `make_shard_and_gather_fns`' gather fns."""

  def _params(self):
    rng = np.random.default_rng(3)
    return {
        "torso_conv_0": {"kernel": jnp.asarray(
            rng.standard_normal((3, 3, 3, 64)), jnp.float32)},
        "q_head": {"dense_0": {
            "kernel": jnp.asarray(rng.standard_normal((128, 64)),
                                  jnp.float32),
            "bias": jnp.asarray(rng.standard_normal((64,)),
                                jnp.float32)}},
    }

  def test_one_device_save_restores_onto_fsdp_mesh_and_back(
      self, tmp_path):
    from tensor2robot_tpu.parallel import (
        FSDP_AXIS,
        ShardLargest,
        make_shard_and_gather_fns,
        match_partition_rules,
    )

    rules = ((r".*", ShardLargest(FSDP_AXIS)),)
    params = self._params()

    # Save under a 1-device mesh (single-chip trainer shape).
    mesh_1 = create_mesh({FSDP_AXIS: 1}, devices=jax.devices()[:1])
    on_one = jax.device_put(
        params, jax.tree_util.tree_map(
            lambda s: jax.sharding.NamedSharding(mesh_1, s),
            match_partition_rules(rules, params, mesh_1,
                                  min_size_to_shard=64),
            is_leaf=lambda x: isinstance(
                x, jax.sharding.PartitionSpec)))
    _save(tmp_path / "one", on_one)

    # Restore onto the 8-virtual-device fsdp mesh, layout from the
    # rules table (NOT from `like`, which is host-resident).
    mesh_8 = create_mesh({FSDP_AXIS: 8})
    host_like = jax.tree_util.tree_map(np.asarray, params)
    restored_8 = ckpt_lib.restore_state_on_mesh(
        str(tmp_path / "one"), like=host_like, mesh=mesh_8,
        rules=rules, min_size_to_shard=64)
    kernel = restored_8["torso_conv_0"]["kernel"]
    assert FSDP_AXIS in [ax for ax in kernel.sharding.spec if ax], (
        kernel.sharding)

    # Bitwise through the GATHER fns: every leaf gathered from the
    # 8-way layout equals the saved host values exactly.
    specs_8 = match_partition_rules(rules, params, mesh_8,
                                    min_size_to_shard=64)
    _, gather_fns = make_shard_and_gather_fns(mesh_8, specs_8)
    gathered = jax.tree_util.tree_map(lambda f, x: f(x), gather_fns,
                                      restored_8)
    jax.tree_util.tree_map(np.testing.assert_array_equal, gathered,
                           host_like)

    # And back: save the 8-way state, restore onto the 1-device mesh.
    _save(tmp_path / "eight", restored_8)
    restored_1 = ckpt_lib.restore_state_on_mesh(
        str(tmp_path / "eight"), like=host_like, mesh=mesh_1,
        rules=rules, min_size_to_shard=64)
    _, gather_1 = make_shard_and_gather_fns(
        mesh_1, match_partition_rules(rules, params, mesh_1,
                                      min_size_to_shard=64))
    back = jax.tree_util.tree_map(lambda f, x: f(x), gather_1,
                                  restored_1)
    jax.tree_util.tree_map(np.testing.assert_array_equal, back,
                           host_like)

  def test_family_rules_drive_restore(self, tmp_path):
    """The gin-facing shape: a family NAME selects the table."""
    from tensor2robot_tpu.parallel import FSDP_AXIS, family_rules

    params = self._params()
    _save(tmp_path, jax.tree_util.tree_map(np.asarray, params))
    mesh = create_mesh({FSDP_AXIS: 8})
    restored = ckpt_lib.restore_state_on_mesh(
        str(tmp_path), like=jax.tree_util.tree_map(np.asarray, params),
        mesh=mesh, rules=family_rules("qtopt"))
    kernel = restored["torso_conv_0"]["kernel"]
    # qtopt table: conv kernels ride ShardLargest(fsdp); 1728 > 2**10.
    assert FSDP_AXIS in [ax for ax in kernel.sharding.spec if ax]
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(jax.device_get(a)), np.asarray(b)),
        restored, params)
