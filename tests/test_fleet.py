"""Fleet orchestrator tests: the failure paths ARE the product.

The lifecycle contract of docs/FLEET.md, pinned:

  * an actor crash mid-episode never lands partial rows (the staged
    half-episode is aborted on disconnect, across the process
    boundary);
  * the restart policy respawns a crashed actor whose session reopen
    discards stale staged state; the abort policy takes the fleet
    down;
  * learner death is detected and the actors exit;
  * the shutdown barrier (normal AND after an injected crash) leaks
    zero child processes and zero shm segments;
  * a two-actor fleet runs end-to-end on CPU with the param
    publication channel live (`param_refresh_lag` measured, policy
    versions monotonic);
  * fleet actor processes import WITHOUT jax (the Podracer actors-
    are-cheap property).
"""

from __future__ import annotations

import multiprocessing as mp
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from tensor2robot_tpu.fleet import (
    Fleet,
    FleetConfig,
    FleetError,
    RpcClient,
    RpcError,
    RpcServer,
)
from tensor2robot_tpu.fleet import host as host_lib

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tiny_config(**overrides) -> FleetConfig:
  base = dict(
      num_actors=2, env="toy_grasp", image_size=16, action_dim=2,
      torso_filters=(8,), head_filters=(8,), dense_sizes=(16,),
      cem_population=8, cem_iterations=1, cem_elites=2,
      batch_size=16, max_train_steps=16, min_replay_size=32,
      publish_every_steps=8, log_every_steps=8,
      batch_episodes=8, serve_max_batch=4,
      replay_capacity=512, replay_shards=1,
      heartbeat_timeout_secs=0.0, launch_timeout_secs=240.0,
      run_timeout_secs=420.0, seed=0)
  base.update(overrides)
  return FleetConfig(**base)


def _shm_entries():
  try:
    return set(os.listdir("/dev/shm"))
  except FileNotFoundError:  # non-Linux: nothing to pin
    return set()


def _assert_no_new_shm(before):
  """Zero-shm-leak pin: once the fleet handle is released (callers
  `del` their Fleet first — while it lives, its own stop Events /
  heartbeat Values legitimately hold `sem.mp-*` entries), /dev/shm is
  back to baseline. The contract is about what SURVIVES the fleet."""
  import gc

  gc.collect()
  deadline = time.monotonic() + 10.0
  while time.monotonic() < deadline:
    if not _shm_entries() - before:
      return
    time.sleep(0.1)
  assert _shm_entries() - before == set()


def _fleet_children():
  return [p for p in mp.active_children()
          if p.name.startswith("t2r-fleet")]


def _transitions(n=4, size=16):
  return {
      "image": np.zeros((n, size, size, 3), np.uint8),
      "action": np.zeros((n, 2), np.float32),
      "reward": np.ones((n, 1), np.float32),
      "done": np.ones((n, 1), np.float32),
      "next_image": np.zeros((n, size, size, 3), np.uint8),
  }


class TestRpc:
  """Transport-level contract: errors travel, disconnects fire."""

  def test_roundtrip_error_and_disconnect_callback(self):
    seen = {"disconnects": 0}

    def handler(method, payload, ctx):
      if method == "echo":
        ctx["n"] = ctx.get("n", 0) + 1
        return {"payload": payload, "call": ctx["n"]}
      if method == "boom":
        raise ValueError("intentional")
      if method == "__disconnect__":
        seen["disconnects"] += 1
        seen["calls_at_disconnect"] = ctx.get("n", 0)
        return None
      raise KeyError(method)

    with RpcServer(handler, authkey=b"test") as server:
      client = RpcClient(server.address, authkey=b"test")
      assert client.call("echo", 1) == {"payload": 1, "call": 1}
      assert client.call("echo", "x")["call"] == 2
      with pytest.raises(RpcError, match="intentional"):
        client.call("boom")
      # The connection survives a handler error.
      assert client.call("echo", None)["call"] == 3
      client.close()
      deadline = time.monotonic() + 5
      while seen["disconnects"] == 0 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert seen["disconnects"] == 1
    assert seen["calls_at_disconnect"] == 3

  def test_ephemeral_coordinator_addresses_are_distinct(self):
    from tensor2robot_tpu.parallel.distributed import (
        ephemeral_coordinator_address,
    )

    first = ephemeral_coordinator_address()
    second = ephemeral_coordinator_address()
    assert first.startswith("127.0.0.1:")
    # Two concurrent launches (two fleets, bench + tests) must never
    # be handed the same port.
    assert first != second


class TestParamsVersion:
  """The hot-swap publication counter (the param_refresh_lag seam)."""

  def test_engine_version_monotonic_and_learner_step_stamped(self):
    import jax

    from tensor2robot_tpu import specs
    from tensor2robot_tpu.data.abstract_input_generator import Mode
    from tensor2robot_tpu.serving import BucketedServingEngine
    from tensor2robot_tpu.utils.mocks import MockT2RModel

    model = MockT2RModel()
    state = model.create_inference_state(jax.random.PRNGKey(0))
    wire = specs.flatten_spec_structure(
        model.preprocessor.get_in_feature_specification(Mode.PREDICT))
    example = specs.make_random_tensors(wire, batch_size=1, seed=0)
    engine = BucketedServingEngine(model.predict_step, state, example,
                                   max_batch=2)
    assert engine.params_version == 0
    assert engine.params_learner_step == 0
    engine.swap_state(state, learner_step=40)
    assert engine.params_version == 1
    assert engine.params_learner_step == 40
    # A swap without a stamp keeps the previous learner step (a
    # non-learner swapper must not reset the lag clock).
    engine.swap_state(state)
    assert engine.params_version == 2
    assert engine.params_learner_step == 40
    engine.swap_state(state, learner_step=80)
    assert engine.params_version == 3
    assert engine.params_learner_step == 80


class TestPoseGraspBandit:
  """The adapter that lets GraspActor drive the pose envs."""

  def test_reset_grade_shapes_and_threshold(self):
    from tensor2robot_tpu.research.pose_env.grasp_bandit import (
        PoseGraspBandit,
    )
    from tensor2robot_tpu.research.pose_env.pose_env import (
        WORKSPACE_HIGH,
    )

    bandit = PoseGraspBandit(image_size=16, physics=False, seed=3,
                             success_threshold=0.1)
    observations, poses = bandit.reset_batch(5)
    assert observations["image"].shape == (5, 16, 16, 3)
    assert observations["image"].dtype == np.uint8
    assert poses.shape == (5, 2)
    # A perfect grasp (the pose mapped back to [-1, 1]) succeeds; the
    # far corner fails.
    perfect = poses / WORKSPACE_HIGH
    assert bandit.grade(perfect, poses).all()
    miss = -np.sign(perfect) * np.ones_like(perfect)
    assert bandit.grade(miss, poses).sum() == 0

  def test_physics_variant_settles_poses(self):
    from tensor2robot_tpu.research.pose_env.grasp_bandit import (
        PoseGraspBandit,
    )

    bandit = PoseGraspBandit(image_size=16, physics=True, seed=5)
    _, poses = bandit.reset_batch(2)
    # Settled poses differ from the commanded drop (contact dynamics
    # moved the block) — the physics is real, not a relabeled RNG.
    assert not np.allclose(poses[-1], bandit.env.last_drop_pose)


class TestActorImportClosure:

  def test_actor_modules_import_without_jax(self):
    # The Podracer actors-are-cheap property: everything a fleet actor
    # process imports must stay jax-free (no XLA runtime per actor).
    code = (
        "import sys; "
        "import tensor2robot_tpu.fleet.actor, "
        "tensor2robot_tpu.fleet.pod, "
        "tensor2robot_tpu.fleet.rpc, tensor2robot_tpu.fleet.proc, "
        "tensor2robot_tpu.research.qtopt.actor, "
        "tensor2robot_tpu.research.qtopt.grasping_env, "
        "tensor2robot_tpu.research.pose_env.grasp_bandit; "
        "assert 'jax' not in sys.modules, 'jax leaked'; "
        "print('JAXFREE')")
    result = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=120, cwd=REPO)
    assert result.returncode == 0, result.stderr
    assert "JAXFREE" in result.stdout

  def test_fleet_is_in_t2rcheck_concurrency_scope(self):
    from tensor2robot_tpu.analysis import cli

    assert "tensor2robot_tpu/fleet" in cli._CONCURRENCY_PATHS

  def test_entry_binary_import_initializes_no_backend(self):
    # multiprocessing's spawn re-imports `__main__` in every fleet
    # child BEFORE its target runs, and the shipped binary is that
    # __main__ — so its import closure must not execute any jax
    # computation: an initialized XLA backend makes the learner
    # group's `jax.distributed.initialize` raise (found by driving
    # qtopt_fleet_hybrid.gin through the real run_t2r_trainer; a
    # module-level `jnp.array` constant was enough to trip it).
    # This subprocess run is the e2e WITNESS; the static guarantee is
    # JAX205 (analysis/spmd_rules.py), which scans the COMPUTED entry
    # import closure so new modules are covered without editing any
    # list here (tests/test_analysis.py::TestSpmdRules).
    code = (
        "import tensor2robot_tpu.bin.run_t2r_trainer; "
        "from jax._src import xla_bridge; "
        "assert not xla_bridge.backends_are_initialized(), "
        "'entry import ran a jax computation'; "
        "print('BACKEND_FREE')")
    result = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=180, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert result.returncode == 0, result.stderr
    assert "BACKEND_FREE" in result.stdout


class TestHostSessionAbort:
  """The mid-episode crash contract across the process boundary."""

  @pytest.fixture(scope="class")
  def host(self):
    ctx = mp.get_context("spawn")
    config = _tiny_config()
    parent_conn, child_conn = ctx.Pipe()
    stop = ctx.Event()
    heartbeat = ctx.Value("d", 0.0)
    process = ctx.Process(
        target=host_lib.host_main,
        args=(config, child_conn, stop, heartbeat),
        name="t2r-fleet-host", daemon=True)
    process.start()
    child_conn.close()
    assert parent_conn.poll(240.0), "host never reported ready"
    address = tuple(parent_conn.recv()["address"])
    parent_conn.close()
    yield config, address
    stop.set()
    process.join(timeout=30.0)
    if process.is_alive():
      process.terminate()
      process.join(5.0)
    assert process.exitcode == 0

  def test_dropped_connection_aborts_staged_episode(self, host):
    config, address = host
    actor = RpcClient(address, authkey=config.authkey)
    actor.call("begin_episode", "actor-crashy")
    actor.call("append", {"actor_id": "actor-crashy",
                          "transitions": _transitions()})
    # The actor process "dies" mid-episode: connection drops with the
    # episode staged but never ended.
    actor.close()

    observer = RpcClient(address, authkey=config.authkey)
    deadline = time.monotonic() + 10
    aborted = 0.0
    while time.monotonic() < deadline:
      metrics = observer.call("metrics")
      aborted = metrics["service"]["replay_aborted_episodes"]
      if aborted >= 1.0:
        break
      time.sleep(0.05)
    assert aborted >= 1.0
    # Not one staged row landed.
    assert observer.call("size") == 0
    assert metrics["store"]["adds_total"] == 0.0

    # A committed episode DOES land (the abort above was surgical) and
    # carries the refresh-lag stamp.
    committer = RpcClient(address, authkey=config.authkey)
    payload = {"actor_id": "actor-ok", "transitions": _transitions(),
               "policy_version": 0, "policy_learner_step": 0}
    assert committer.call("commit", payload) is True
    deadline = time.monotonic() + 10
    while observer.call("size") < 4 and time.monotonic() < deadline:
      time.sleep(0.05)
    assert observer.call("size") == 4
    assert observer.call("metrics")["param_refresh_lag"]["rows"] == 4
    committer.close()
    observer.close()

  def test_acting_state_serves_params_once_per_version(self, host):
    # The pod param seam (ISSUE 19): `acting_state` returns the full
    # publication on a version move and a stamp-only reply otherwise,
    # so a polling pod pays the state transfer once per publication.
    config, address = host
    pod = RpcClient(address, authkey=config.authkey)
    first = pod.call("acting_state", {"have_version": -1})
    # Version 0 exists from engine construction — a pod's first
    # refresh always lands acting params.
    assert first["params_version"] >= 0
    assert first["state"] is not None
    assert "params_learner_step" in first
    assert "params_hop" in first
    second = pod.call(
        "acting_state", {"have_version": first["params_version"]})
    assert second["state"] is None
    assert second["params_version"] == first["params_version"]
    assert second["params_learner_step"] == first["params_learner_step"]
    pod.close()


class TestLearnerGroup:
  """The multi-process learner-group contract (ISSUE 19)."""

  def test_plan_roles_shards_and_publication(self):
    from tensor2robot_tpu.fleet.learner import learner_group_plan

    config = _tiny_config()  # batch_size=16
    solo = learner_group_plan(config, world_size=1, rank=0)
    assert solo == {"role": "learner", "local_batch_size": 16,
                    "publishes": True}
    chief = learner_group_plan(config, world_size=2, rank=0)
    assert chief["role"] == "learner"
    assert chief["local_batch_size"] == 8
    assert chief["publishes"] is True
    peer = learner_group_plan(config, world_size=2, rank=1)
    assert peer["role"] == "learner-r1"
    assert peer["local_batch_size"] == 8
    assert peer["publishes"] is False

  def test_plan_rejects_bad_geometry(self):
    from tensor2robot_tpu.fleet.learner import learner_group_plan

    config = _tiny_config()
    with pytest.raises(ValueError, match="divide"):
      learner_group_plan(config, world_size=3, rank=0)
    with pytest.raises(ValueError, match="rank"):
      learner_group_plan(config, world_size=2, rank=2)

  def test_config_rejects_unsound_group_geometry(self):
    with pytest.raises(ValueError, match="divide"):
      _tiny_config(learner_hosts=2, batch_size=15)
    with pytest.raises(ValueError, match="fatal"):
      _tiny_config(learner_hosts=2, learner_crash_policy="resume")
    with pytest.raises(ValueError, match="collector"):
      _tiny_config(num_actors=0, pod_hosts=0)

  def test_non_chief_rank_owns_no_host_side_surface(
      self, tmp_path, monkeypatch):
    # The rank-0-only side-effect pin: a rank-1 process runs the same
    # loop (its batch shard feeds the shared GSPMD program) and makes
    # the COLLECTIVE checkpoint-save calls (orbax barriers pair across
    # ranks; primary-host ownership keeps process 0 the data writer),
    # but owns none of the chief's host-side surfaces — no train
    # metrics, no sentinel pages.
    import jax

    from tensor2robot_tpu.models import optimizers as opt_lib
    from tensor2robot_tpu.research.qtopt import (
        GraspingQModel,
        QTOptLearner,
        train_qtopt,
    )

    model = GraspingQModel(
        image_size=16, action_dim=2, torso_filters=(8,),
        head_filters=(8,), dense_sizes=(16,),
        create_optimizer_fn=lambda: opt_lib.create_optimizer(
            learning_rate=1e-3))
    learner = QTOptLearner(model, cem_population=8, cem_iterations=1,
                           cem_elites=2)
    monkeypatch.setattr(jax, "process_index", lambda: 1)
    model_dir = str(tmp_path / "rank1")
    state = train_qtopt(
        learner=learner, model_dir=model_dir, max_train_steps=4,
        batch_size=8, save_checkpoints_steps=4, log_every_steps=2,
        prefill_random=True)
    assert int(np.asarray(state.step)) == 4  # it DID train
    # ckpt/ is the collective surface (here process_count is 1, so
    # this mocked rank doubles as orbax's primary host); every
    # chief-only file — metrics_train.jsonl and friends — is absent.
    assert os.listdir(model_dir) == ["ckpt"]

  def test_single_member_group_is_bitwise_single_learner(
      self, tmp_path):
    # The N=1 acceptance pin: the learner-group path (coordinator
    # adoption → jax.distributed init → plan-sized batch) produces
    # BITWISE the single-learner params — the group machinery is the
    # existing path at world_size=1, not an approximation of it.
    import subprocess

    worker = os.path.join(REPO, "tests", "learner_group_worker.py")
    outputs = {}
    for mode in ("plain", "group"):
      outfile = str(tmp_path / f"{mode}.npz")
      env = {k: v for k, v in os.environ.items()
             if not k.startswith(("JAX_", "XLA_", "TPU"))}
      env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
      env["TF_CPP_MIN_LOG_LEVEL"] = "2"
      result = subprocess.run(
          [sys.executable, worker, mode, outfile,
           str(tmp_path / mode)],
          env=env, capture_output=True, text=True, timeout=300)
      assert result.returncode == 0, (
          f"{mode} worker failed:\n{result.stdout}\n{result.stderr}")
      assert "BITWISE_OK" in result.stdout
      outputs[mode] = dict(np.load(outfile))
    assert set(outputs["plain"]) == set(outputs["group"])
    for key, plain in outputs["plain"].items():
      grouped = outputs["group"][key]
      assert plain.dtype == grouped.dtype, key
      assert np.array_equal(plain, grouped), key


class TestPodUnits:
  """The pod module's pure seams (jax-free, like the module import)."""

  def test_env_family_maps_onto_functional_envs(self):
    from tensor2robot_tpu.fleet.pod import pod_env_family

    assert pod_env_family("pose") == "pose"
    assert pod_env_family("mujoco_pose") == "pose"
    assert pod_env_family("procgen") == "procgen"
    with pytest.raises(ValueError, match="functional"):
      pod_env_family("toy_grasp")

  def test_trim_devices_largest_dividing_prefix(self):
    from tensor2robot_tpu.fleet.pod import trim_devices

    devices = [f"d{i}" for i in range(8)]
    assert trim_devices(devices, 32) == devices  # 8 | 32
    assert trim_devices(devices, 12) == devices[:6]
    assert trim_devices(devices, 7) == devices[:7]
    assert trim_devices(devices[:3], 16) == devices[:2]
    assert trim_devices(devices[:1], 5) == devices[:1]  # always valid

  def test_pod_home_shard_remap_is_minimal(self):
    # Rendezvous placement over the `pod-N` id namespace: shrinking
    # the shard set remaps ONLY pods homed on the removed shard, and
    # growing it moves pods ONLY onto the new shard — everyone else's
    # segments keep landing where they always did.
    from tensor2robot_tpu.fleet.actor import home_shard

    pods = [f"pod-{k}" for k in range(32)]
    with_three = {p: home_shard(p, 3) for p in pods}
    with_two = {p: home_shard(p, 2) for p in pods}
    displaced = [p for p in pods if with_three[p] == 2]
    assert displaced  # the pin is vacuous if nobody homed on shard 2
    for p in pods:
      if with_three[p] != 2:
        assert with_two[p] == with_three[p], p
      if with_two[p] != with_three[p]:
        assert with_three[p] == 2, p


class TestFleetLifecycle:
  """Whole-topology runs: the expensive, load-bearing pins."""

  @pytest.mark.slow
  def test_two_actor_smoke_end_to_end(self, tmp_path):
    shm_before = _shm_entries()
    # distributed_learner=True also exercises the collision-safe
    # ephemeral-coordinator handoff end to end (a 1-process gloo
    # cluster in the learner child).
    config = _tiny_config(env="mujoco_pose", distributed_learner=True)
    fleet = Fleet(config, str(tmp_path / "fleet"))
    result = fleet.run()

    assert result.clean_shutdown
    assert result.metrics["store"]["adds_total"] > 0
    assert result.env_steps_per_sec > 0
    # The learner ran to max_train_steps and its rate was measured
    # over the learner-step window.
    assert result.metrics["learner_window"]["last_step"] == 16
    assert result.learner_steps_per_sec > 0
    # The publication channel was live: the final checkpoint publishes
    # too, so >= 2 refreshes reached the serving engine, versions are
    # monotonic, and committed rows carry lag attribution.
    assert result.publishes >= 2
    assert result.params_version == result.publishes
    assert result.param_refresh_lag["rows"] > 0
    assert result.param_refresh_lag["max"] >= 0
    # The learner's training batches have a measured staleness
    # distribution (ages in learner steps).
    staleness = [s for s in result.replay_staleness.values() if s]
    assert staleness and staleness[0]["rows"] > 0
    # The shutdown barrier: no child processes, no shm segments.
    assert _fleet_children() == []
    del fleet
    _assert_no_new_shm(shm_before)

  @pytest.mark.slow
  def test_actor_crash_restart_lands_no_partial_rows(self, tmp_path):
    shm_before = _shm_entries()
    config = _tiny_config(
        actor_crash_after_episodes=2, actor_crash_mode="mid_episode",
        crash_actor_index=0, max_actor_restarts=2)
    fleet = Fleet(config, str(tmp_path / "fleet"))
    result = fleet.run()

    service = result.metrics["service"]
    # The crash was real (the orchestrator restarted the actor), the
    # reopen aborted the staged half-episode, and every row that DID
    # land arrived in whole batch_episodes-sized commits — a partial
    # episode would break the divisibility.
    assert result.actor_restarts >= 1
    assert service["replay_actor_restarts"] >= 1.0
    assert service["replay_aborted_episodes"] >= 1.0
    assert result.metrics["store"]["adds_total"] % config.batch_episodes == 0
    assert result.clean_shutdown
    assert _fleet_children() == []
    del fleet
    _assert_no_new_shm(shm_before)

  @pytest.mark.slow
  def test_learner_death_detected_and_actors_exit(self, tmp_path):
    shm_before = _shm_entries()
    config = _tiny_config(learner_crash_after_steps=4)
    fleet = Fleet(config, str(tmp_path / "fleet"))
    with pytest.raises(FleetError, match="learner died"):
      fleet.run()
    # The abort teardown stopped every actor and the host — crash
    # shutdown leaks nothing either.
    assert _fleet_children() == []
    del fleet
    _assert_no_new_shm(shm_before)

  @pytest.mark.slow
  def test_actor_abort_policy_takes_fleet_down(self, tmp_path):
    config = _tiny_config(
        actor_crash_after_episodes=1, actor_crash_mode="hard",
        actor_crash_policy="abort")
    fleet = Fleet(config, str(tmp_path / "fleet"))
    with pytest.raises(FleetError, match="actor 0 died"):
      fleet.run()
    assert _fleet_children() == []


class TestHybridPodracer:
  """ISSUE 19 end-to-end: Anakin pods and the learner group live in
  the supervised fleet, under the same atomic-commit and rank-0-only
  publication contracts the unit pins promise."""

  @pytest.mark.slow
  def test_pod_commits_land_whole_across_pod_kill(self, tmp_path):
    from tensor2robot_tpu.fleet import faults

    shm_before = _shm_entries()
    # A pods-only fleet (num_actors=0) with one planned mid-segment
    # kill: the staged wire batch is aborted on disconnect, the
    # restart policy respawns pod-0, and every landed row arrived in
    # whole segment-sized commits.
    plan = faults.FaultPlan(seed=0, events=(faults.FaultEvent(
        fault=faults.ACTOR_CRASH, target="pod-0", at=2,
        mode="mid_episode"),))
    config = _tiny_config(
        num_actors=0, pod_hosts=1, envs_per_pod=8,
        pod_rollout_length=2, env="mujoco_pose", fault_plan=plan,
        max_actor_restarts=2, restart_window_secs=600.0)
    fleet = Fleet(config, str(tmp_path / "fleet"))
    result = fleet.run()

    assert result.clean_shutdown
    assert result.actor_restarts >= 1  # the pod respawn is counted
    assert [r["target"] for r in result.recoveries] == ["pod-0"]
    assert result.recoveries[0]["fault"] == "actor_crash"
    assert result.recoveries[0]["mttr_ms"] > 0
    service = result.metrics["service"]
    assert service["replay_aborted_episodes"] >= 1.0
    segment_rows = config.envs_per_pod * config.pod_rollout_length
    committed = int(service["replay_committed_transitions"])
    assert committed > 0
    assert committed % segment_rows == 0
    assert _fleet_children() == []
    del fleet
    _assert_no_new_shm(shm_before)

  @pytest.mark.slow
  def test_hybrid_fleet_end_to_end(self, tmp_path):
    shm_before = _shm_entries()
    # The full hybrid topology, tiny: one process actor and one Anakin
    # pod feed the same replay plane while a 2-process learner group
    # trains over the shared mesh — and only rank 0 publishes (the
    # publication counter and the engine version counter must agree,
    # which a double-publishing rank 1 would break).
    config = _tiny_config(
        env="mujoco_pose", num_actors=1, pod_hosts=1,
        envs_per_pod=8, pod_rollout_length=2, learner_hosts=2)
    fleet = Fleet(config, str(tmp_path / "fleet"))
    result = fleet.run()

    assert result.clean_shutdown
    assert result.metrics["store"]["adds_total"] > 0
    assert result.metrics["learner_window"]["last_step"] == 16
    assert result.publishes >= 2
    assert result.params_version == result.publishes
    assert result.param_refresh_lag["rows"] > 0
    assert _fleet_children() == []
    del fleet
    _assert_no_new_shm(shm_before)
