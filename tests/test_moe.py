"""MoE layer + expert parallelism: routing semantics and EP exactness.

The key property under test: with ample capacity, the expert-parallel
shard_map path (tokens grouped per device, two all-to-alls) computes
EXACTLY the single-device dense formulation — grouping only changes
which tokens drop when an expert overflows, never the math of routed
tokens. Gradient parity covers the all-to-all transpose path.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tensor2robot_tpu.parallel import (
    DATA_AXIS,
    EXPERT_AXIS,
    MoEMLP,
    collect_aux_losses,
    create_mesh,
    expert_capacity,
    moe_mlp,
    top_k_routing,
)


def _params(rng, model_dim, num_experts, hidden):
  r = np.random.default_rng(rng)
  return dict(
      router=jnp.asarray(
          r.standard_normal((model_dim, num_experts)), jnp.float32),
      w_in=jnp.asarray(
          r.standard_normal((num_experts, model_dim, hidden)) * 0.1,
          jnp.float32),
      b_in=jnp.zeros((num_experts, hidden), jnp.float32),
      w_out=jnp.asarray(
          r.standard_normal((num_experts, hidden, model_dim)) * 0.1,
          jnp.float32),
      b_out=jnp.zeros((num_experts, model_dim), jnp.float32),
  )


class TestRouting:

  def test_capacity_formula(self):
    assert expert_capacity(64, 4, 2, 1.0) == 32
    assert expert_capacity(64, 4, 2, 2.0) == 64
    assert expert_capacity(2, 8, 1, 1.0) == 1  # floor at one slot

  def test_top1_dispatch_respects_capacity(self):
    # 4 tokens all preferring expert 0, capacity 2: tokens 0 and 1
    # get slots, tokens 2 and 3 drop (all-zero dispatch rows).
    logits = jnp.asarray([[9.0, 0.0]] * 4)
    dispatch, combine, _ = top_k_routing(logits, capacity=2, k=1)
    occupancy = dispatch.sum(axis=(1, 2))
    np.testing.assert_array_equal(occupancy, [1, 1, 0, 0])
    # Every occupied slot is distinct.
    assert float(dispatch[:, 0].sum(0).max()) == 1.0
    # Kept tokens combine with weight 1 (top-1 renormalizes to the
    # single kept gate); dropped tokens combine to zero.
    np.testing.assert_allclose(
        combine.sum(axis=(1, 2)), [1.0, 1.0, 0.0, 0.0], atol=1e-6)

  def test_top2_splits_mass_between_two_experts(self):
    logits = jnp.asarray([[2.0, 1.0, -5.0, -5.0]] * 2)
    dispatch, combine, _ = top_k_routing(logits, capacity=4, k=2)
    # Each token occupies a slot in BOTH its top experts.
    np.testing.assert_array_equal(dispatch.sum(axis=(1, 2)), [2, 2])
    per_expert = combine.sum(axis=2)
    # Renormalized over the two kept gates: softmax(2,1) ratio.
    expected = jax.nn.softmax(jnp.asarray([2.0, 1.0]))
    np.testing.assert_allclose(per_expert[0, :2], expected, atol=1e-6)
    np.testing.assert_allclose(combine.sum(axis=(1, 2)), [1, 1],
                               atol=1e-6)

  def test_aux_loss_is_one_at_perfect_balance(self):
    # Uniform logits: every expert gets mean prob 1/E and (argmax
    # ties resolve to expert 0, so use distinct per-token maxima).
    n, e = 8, 4
    logits = jnp.eye(e)[jnp.arange(n) % e] * 5.0
    _, _, aux = top_k_routing(logits, capacity=4, k=1)
    # f_e = 1/4 each; p_e sums to 1 -> aux = E * sum(f*p) with p
    # symmetric across experts = 1.
    np.testing.assert_allclose(float(aux), 1.0, atol=1e-5)


class TestDenseMoE:

  def test_shapes_and_finite(self):
    p = _params(0, model_dim=8, num_experts=4, hidden=16)
    x = jnp.asarray(
        np.random.default_rng(1).standard_normal((32, 8)), jnp.float32)
    out, aux = moe_mlp(x, **p, k=2, capacity_factor=2.0)
    assert out.shape == (32, 8)
    assert np.isfinite(np.asarray(out)).all()
    assert float(aux) >= 1.0 - 1e-5  # 1.0 is the balanced minimum

  def test_dropped_tokens_output_zero(self):
    # One expert, capacity 1 via tiny factor: token 0 keeps its slot,
    # the rest drop and must output exactly zero (residual carries
    # them in a transformer block).
    p = _params(0, model_dim=4, num_experts=1, hidden=8)
    x = jnp.ones((4, 4), jnp.float32)
    out, _ = moe_mlp(x, **p, k=1, capacity_factor=0.25)
    assert float(jnp.abs(out[0]).sum()) > 0.0
    np.testing.assert_array_equal(np.asarray(out[1:]), 0.0)


class TestExpertParallel:
  """The EP path vs the dense oracle on the 8-device mesh."""

  @pytest.fixture(params=[{EXPERT_AXIS: 8},
                          {DATA_AXIS: 2, EXPERT_AXIS: 4}])
  def mesh(self, request):
    return create_mesh(request.param)

  def _build(self, mesh, dtype=jnp.float32, k=2):
    module = MoEMLP(num_experts=8, hidden_dim=16, k=k,
                    capacity_factor=4.0, mesh=mesh, dtype=dtype)
    x = jnp.asarray(
        np.random.default_rng(2).standard_normal((4, 16, 8)), dtype)
    ref = MoEMLP(num_experts=8, hidden_dim=16, k=k,
                 capacity_factor=4.0, mesh=None, dtype=dtype)
    variables = ref.init(jax.random.PRNGKey(0), x)
    return module, ref, variables, x

  @pytest.mark.parametrize("k", [1, 2])
  def test_forward_matches_dense(self, mesh, k):
    """k=1 is Switch routing, k=2 GShard — both exact under EP."""
    module, ref, variables, x = self._build(mesh, k=k)
    out_ref, _ = ref.apply(variables, x, mutable=["aux_loss"])
    out_ep, state = jax.jit(
        lambda v, x: module.apply(v, x, mutable=["aux_loss"])
    )(variables, x)
    np.testing.assert_allclose(np.asarray(out_ep), np.asarray(out_ref),
                               atol=1e-5)
    # Aux loss: global mean across groups == the one-group value only
    # when groups are balanced; both must at least be sane scalars.
    aux = collect_aux_losses(state)
    assert np.isfinite(float(aux)) and float(aux) >= 1.0 - 1e-5

  def test_gradients_match_dense(self, mesh):
    """Output-path grads match the dense oracle exactly.

    The aux loss is deliberately EXCLUDED from this loss: it averages
    a per-group quadratic (f_e·p_e), so its value/gradient genuinely
    depend on grouping — covered by its own test below. The routed
    output does not: each token's combine weights depend only on its
    own gates, so with ample capacity every gradient (router included,
    via the combine weights) is grouping-invariant.
    """
    module, ref, variables, x = self._build(mesh)

    def loss(mod):
      def fn(params, x):
        out, _ = mod.apply({"params": params}, x,
                           mutable=["aux_loss"])
        return jnp.sum(out.astype(jnp.float32) ** 2)
      return fn

    g_ref = jax.grad(loss(ref))(variables["params"], x)
    g_ep = jax.jit(jax.grad(loss(module)))(variables["params"], x)
    flat_ref = jax.tree_util.tree_leaves_with_path(g_ref)
    flat_ep = jax.tree_util.tree_leaves(g_ep)
    assert len(flat_ref) == len(flat_ep)
    for (path, a), b in zip(flat_ref, flat_ep):
      np.testing.assert_allclose(
          np.asarray(b), np.asarray(a), atol=2e-4,
          err_msg=jax.tree_util.keystr(path))

  def test_aux_loss_differentiable_through_ep(self, mesh):
    """The sharded aux loss backprops to the router (finite, nonzero)."""
    module, _, variables, x = self._build(mesh)

    def aux_only(params, x):
      _, state = module.apply({"params": params}, x,
                              mutable=["aux_loss"])
      return collect_aux_losses(state)

    g = jax.jit(jax.grad(aux_only))(variables["params"], x)
    router_g = np.asarray(
        jax.tree_util.tree_leaves({"router": g["router"]})[0])
    assert np.isfinite(router_g).all()
    assert float(np.abs(router_g).max()) > 0.0

  def test_rejects_indivisible_experts(self):
    mesh = create_mesh({EXPERT_AXIS: 8})
    module = MoEMLP(num_experts=6, hidden_dim=8, mesh=mesh)
    x = jnp.zeros((2, 8, 4))
    with pytest.raises(ValueError, match="must be a multiple"):
      module.init(jax.random.PRNGKey(0), x)


class TestMoETransformer:
  """The trunk integration: moe_experts swaps MLPs on the cadence."""

  def test_moe_blocks_on_every_other_layer(self):
    from tensor2robot_tpu.layers.transformer import CausalTransformer

    model = CausalTransformer(width=16, depth=4, num_heads=2,
                              max_len=8, dtype=jnp.float32,
                              moe_experts=4, moe_every=2)
    x = jnp.ones((2, 8, 8), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x)
    params = variables["params"]
    # Blocks 1 and 3 (1-indexed cadence 2) are MoE; 0 and 2 dense.
    assert "moe" in params["block1"] and "moe" in params["block3"]
    assert "mlp_in" in params["block0"] and "mlp_in" in params["block2"]

    # Apply with params only: passing init's collected aux_loss back
    # in would APPEND this call's sow to it (flax tuple semantics).
    out, state = model.apply({"params": params}, x,
                             mutable=["aux_loss"])
    assert out.shape == (2, 8, 16)
    # Two MoE blocks → two sown aux scalars.
    assert len(jax.tree_util.tree_leaves(state["aux_loss"])) == 2

  def test_moe_transformer_gradients_finite(self):
    from tensor2robot_tpu.layers.transformer import CausalTransformer

    model = CausalTransformer(width=16, depth=2, num_heads=2,
                              max_len=8, dtype=jnp.float32,
                              moe_experts=4, moe_every=1)
    x = jnp.asarray(
        np.random.default_rng(0).standard_normal((2, 8, 8)),
        jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x)

    def loss(params):
      out, state = model.apply({"params": params}, x,
                               mutable=["aux_loss"])
      return jnp.mean(out ** 2) + 0.01 * collect_aux_losses(state)

    grads = jax.grad(loss)(variables["params"])
    for path, leaf in jax.tree_util.tree_leaves_with_path(grads):
      assert np.isfinite(np.asarray(leaf)).all(), (
          jax.tree_util.keystr(path))


class TestAuxCollection:

  def test_collect_handles_missing_collection(self):
    assert float(collect_aux_losses({})) == 0.0

  def test_sown_aux_is_collected(self):
    module = MoEMLP(num_experts=4, hidden_dim=8, mesh=None,
                    dtype=jnp.float32)
    x = jnp.ones((2, 4, 8), jnp.float32)
    variables = module.init(jax.random.PRNGKey(0), x)
    _, state = module.apply(variables, x, mutable=["aux_loss"])
    assert float(collect_aux_losses(state)) > 0.0
