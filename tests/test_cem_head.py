"""Fused CEM head-tail kernel: interpret-mode exactness vs the oracle."""

import numpy as np

import jax
import jax.numpy as jnp

from tensor2robot_tpu.ops import fused_cem_head_tail

B, P, C, H, W, C1, C2 = 4, 64, 64, 8, 8, 64, 64


def _params(seed=0):
  rng = np.random.default_rng(seed)
  f = lambda *s: jnp.asarray(  # noqa: E731
      rng.standard_normal(s) * 0.3, jnp.bfloat16)
  a1, enc0, v = f(B, P, C), f(B, H, W, C1), f(C, H, W, C1)
  ck = f(3, 3, C1, C2)
  bn_scale = f(C2).astype(jnp.float32)
  bn_shift = f(C2).astype(jnp.float32)
  dense = ((f(C2, 64), f(64)), (f(64, 64), f(64)), (f(64, 1), f(1)))
  act = jax.lax.dot_general(
      a1.reshape(B * P, C), v.reshape(C, -1),
      (((1,), (0,)), ((), ())),
      preferred_element_type=jnp.bfloat16).reshape(B, P, H, W, C1)
  return act, enc0, ck, bn_scale, bn_shift, dense


def _reference(act, enc0, ck, bn_scale, bn_shift, dense):
  x = jax.nn.relu(act.astype(jnp.float32)
                  + enc0.astype(jnp.float32)[:, None])
  x = x.reshape(B * P, H, W, C1).astype(jnp.bfloat16)
  y = jax.lax.conv_general_dilated(
      x, ck, (2, 2), "SAME",
      dimension_numbers=("NHWC", "HWIO", "NHWC"),
      preferred_element_type=jnp.float32)
  y = jax.nn.relu(y * bn_scale + bn_shift)
  h = jnp.mean(y, axis=(1, 2)).astype(jnp.bfloat16)
  for i, (w, b) in enumerate(dense):
    h = jax.lax.dot_general(
        h, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) + b.astype(jnp.float32)
    if i < len(dense) - 1:
      h = jax.nn.relu(h).astype(jnp.bfloat16)
  return h.reshape(B, P)


class TestFusedCEMHeadTail:

  def test_matches_xla_tail(self):
    act, enc0, ck, bs, bsh, dense = _params()
    ref = np.asarray(_reference(act, enc0, ck, bs, bsh, dense))
    got = np.asarray(fused_cem_head_tail(
        act, enc0, ck, bs, bsh, dense, interpret=True, block_b=2))
    np.testing.assert_allclose(got, ref, atol=2e-3, rtol=2e-3)

  def test_block_size_independence(self):
    act, enc0, ck, bs, bsh, dense = _params(1)
    outs = [np.asarray(fused_cem_head_tail(
        act, enc0, ck, bs, bsh, dense, interpret=True, block_b=bb))
        for bb in (1, 2, 4)]
    np.testing.assert_allclose(outs[0], outs[1], atol=1e-5)
    np.testing.assert_allclose(outs[0], outs[2], atol=1e-5)
