"""Multi-process jax.distributed: the initialize path EXECUTES.

Round-3 verdict #28: `maybe_initialize_distributed`'s real path had
never run anywhere — only the single-process no-op was tested. Here
two OS processes (2 virtual CPU devices each) form a 4-device cluster
through the framework's env launch contract, run a cross-process psum
and one sharded QT-Opt train step, and must agree on the loss. This is
the same code path a v5e pod binary takes, with DCN standing in for
the loopback coordinator.
"""

import os
import subprocess
import sys
from concurrent.futures import ThreadPoolExecutor

import pytest

from tensor2robot_tpu.parallel.distributed import (
    ephemeral_coordinator_address,
)


@pytest.mark.slow
def test_two_process_cluster_runs_sharded_train_step(tmp_path):
  repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
  worker = os.path.join(repo, "tests", "distributed_worker.py")
  # The coordinator-side port pick the fleet orchestrator uses too:
  # bench + tests on one machine must never race on a fixed port.
  coordinator = ephemeral_coordinator_address()

  # Scrub jax/tpu config the parent test session forced (cpu platform,
  # 8 fake devices): each worker sets its own.
  env = {k: v for k, v in os.environ.items()
         if not k.startswith(("JAX_", "XLA_", "TPU"))}
  env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
  env["JAX_COORDINATOR_ADDRESS"] = coordinator
  env["JAX_NUM_PROCESSES"] = "2"
  env["TF_CPP_MIN_LOG_LEVEL"] = "2"
  # Shared dir for the cross-process sharded-checkpoint round trip.
  env["T2R_TEST_CKPT_DIR"] = str(tmp_path / "ckpt")

  procs = []
  try:
    for i in range(2):
      worker_env = dict(env)
      worker_env["JAX_PROCESS_ID"] = str(i)
      procs.append(subprocess.Popen(
          [sys.executable, worker],
          env=worker_env, stdout=subprocess.PIPE,
          stderr=subprocess.STDOUT, text=True))

    # Drain both pipes CONCURRENTLY: a worker blocking on a full
    # stdout pipe would stall its SPMD collective and hang its peer.
    with ThreadPoolExecutor(max_workers=2) as pool:
      futures = [pool.submit(p.communicate, None, 520) for p in procs]
      outputs = [f.result(timeout=540)[0] for f in futures]
  finally:
    for p in procs:
      if p.poll() is None:
        p.kill()

  for i, (proc, out) in enumerate(zip(procs, outputs)):
    assert proc.returncode == 0, (
        f"worker {i} failed (rc={proc.returncode}):\n{out[-3000:]}")

  losses = []
  for i, out in enumerate(outputs):
    marker = [line for line in out.splitlines()
              if line.startswith("DISTRIBUTED_OK")]
    assert marker, f"worker {i} printed no marker:\n{out[-2000:]}"
    pid, loss = marker[0].split()[1:]
    assert int(pid) == i
    losses.append(float(loss))
    # The sharded checkpoint round-trip (each process saving only its
    # addressable shards, restore + cross-process checksum) ran too.
    assert any(line.startswith("CKPT_OK") for line in
               out.splitlines()), f"worker {i}: no CKPT_OK:\n{out[-2000:]}"
  # Replicated metrics: both processes must see the SAME global loss —
  # the signature of one SPMD program spanning both, not two
  # independent runs.
  assert losses[0] == pytest.approx(losses[1], abs=1e-6), losses
