"""Picklable data-plane worker sources for tests/test_data_plane.py.

A separate MINIMAL module (numpy + os only) on purpose: these classes
cross the spawn boundary by qualified name, and every import this
module makes is paid at every worker-process spawn. Keeping it tiny —
together with `tensor2robot_tpu.data`'s lazy package init — keeps a
pure-numpy plane worker free of the jax/TF imports that would
otherwise dominate test wall-clock.
"""

import os

import numpy as np


class CountSource:
  """Yields n batches total, stamped with their global index."""

  def __init__(self, n):
    self.n = n

  def __call__(self, widx, nworkers):
    for i in range(widx, self.n, nworkers):
      yield {"x": np.full((4, 3), i, np.float32),
             "y": np.full((4,), i, np.int64)}


class CrashSource:
  """One good batch, then an exception mid-stream."""

  def __call__(self, widx, nworkers):
    yield {"x": np.zeros((4, 3), np.float32),
           "y": np.zeros((4,), np.int64)}
    raise ValueError(f"boom from worker {widx}")


class HardDeathSource:
  """One good batch, then the process dies without a word."""

  def __call__(self, widx, nworkers):
    yield {"x": np.zeros((4, 3), np.float32),
           "y": np.zeros((4,), np.int64)}
    os._exit(3)


class SilentExitSource:
  """One good batch, then a CLEAN exit (code 0) with no done marker —
  the death mode exit-code-only polling cannot see."""

  def __call__(self, widx, nworkers):
    yield {"x": np.zeros((4, 3), np.float32),
           "y": np.zeros((4,), np.int64)}
    os._exit(0)


class DieWhileSiblingsProduceSource:
  """Worker 0 streams forever; every OTHER worker hard-dies after a
  few batches — the busy-queue crash-detection case (siblings keep the
  full queue non-empty, so the empty-window poll alone never fires)."""

  def __call__(self, widx, nworkers):
    i = 0
    while True:
      if widx != 0 and i >= 3:
        os._exit(5)
      yield {"x": np.full((4, 3), widx, np.float32),
             "y": np.full((4,), i, np.int64)}
      i += 1


class StallSource:
  """A few good batches, then the worker stalls (slow decode stand-in):
  the consumer's next poll blocks until close() tears the plane down."""

  def __init__(self, n=1, stall_secs=60.0):
    self.n = n
    self.stall_secs = stall_secs

  def __call__(self, widx, nworkers):
    import time

    for i in range(self.n):
      yield {"x": np.full((4, 3), i, np.float32),
             "y": np.full((4,), i, np.int64)}
    time.sleep(self.stall_secs)
