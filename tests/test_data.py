"""Tests for input generators, tf.Example codec, and device prefetch."""

import numpy as np
import pytest

import jax

from tensor2robot_tpu import specs
from tensor2robot_tpu.data import (
    Mode,
    RandomInputGenerator,
    ShardedPrefetcher,
    TFRecordInputGenerator,
    make_data_sharding,
    prefetch_to_mesh,
    write_tfrecord,
)
from tensor2robot_tpu.data import tfexample
from tensor2robot_tpu.specs import ExtendedTensorSpec, TensorSpecStruct


def feature_spec():
  st = TensorSpecStruct()
  st.image = ExtendedTensorSpec(shape=(12, 10, 3), dtype=np.uint8,
                                name="img", data_format="jpeg")
  st.pose = ExtendedTensorSpec(shape=(6,), dtype=np.float32, name="pose")
  st.count = ExtendedTensorSpec(shape=(1,), dtype=np.int64, name="count")
  return st


def label_spec():
  st = TensorSpecStruct()
  st.target = ExtendedTensorSpec(shape=(2,), dtype=np.float32,
                                 name="target")
  return st


class FakeModel:
  preprocessor = None

  def get_feature_specification(self, mode):
    return feature_spec()

  def get_label_specification(self, mode):
    return label_spec()


class TestRandomInputGenerator:

  def test_yields_conforming_batches(self):
    gen = RandomInputGenerator(batch_size=4)
    gen.set_specification_from_model(FakeModel(), Mode.TRAIN)
    it = gen.create_dataset(Mode.TRAIN)
    features, labels = next(it)
    packed = specs.validate_and_pack(feature_spec(), features)
    assert packed["image"].shape == (4, 12, 10, 3)
    assert labels["target"].shape == (4, 2)

  def test_batches_differ_across_steps(self):
    gen = RandomInputGenerator(batch_size=2)
    gen.set_specification(feature_spec(), label_spec())
    it = gen.create_dataset(Mode.TRAIN)
    (f1, _), (f2, _) = next(it), next(it)
    assert not np.array_equal(f1["pose"], f2["pose"])

  def test_requires_specs(self):
    gen = RandomInputGenerator(batch_size=2)
    with pytest.raises(ValueError, match="set_specification"):
      next(gen.create_dataset(Mode.TRAIN))


class TestTFExampleCodec:

  def test_roundtrip(self):
    fs = feature_spec()
    rng = np.random.default_rng(0)
    # A smooth gradient image: jpeg-friendly, so the round-trip is tight.
    yy, xx = np.mgrid[0:12, 0:10]
    image = np.stack([yy * 20, xx * 25, (yy + xx) * 10],
                     axis=-1).astype(np.uint8)
    example = {
        "image": image,
        "pose": rng.standard_normal(6).astype(np.float32),
        "count": np.array([3], np.int64),
    }
    serialized = tfexample.encode_example(example, fs)
    batch = tfexample.parse_example_batch(
        np.array([serialized, serialized]), fs)
    assert batch["image"].shape == (2, 12, 10, 3)
    # jpeg is lossy; require close-ish pixels.
    assert np.abs(batch["image"][0].astype(int) - image.astype(int)).mean() < 8
    np.testing.assert_allclose(batch["pose"][0], example["pose"], rtol=1e-6)
    np.testing.assert_array_equal(batch["count"][1], example["count"])

  def test_png_lossless(self):
    st = TensorSpecStruct()
    st.img = ExtendedTensorSpec(shape=(8, 8, 3), dtype=np.uint8,
                                name="i", data_format="png")
    image = np.random.default_rng(1).integers(
        0, 255, (8, 8, 3), dtype=np.uint8)
    serialized = tfexample.encode_example({"img": image}, st)
    batch = tfexample.parse_example_batch(np.array([serialized]), st)
    np.testing.assert_array_equal(batch["img"][0], image)

  def test_varlen_pad_and_truncate(self):
    st = TensorSpecStruct()
    st.x = ExtendedTensorSpec(shape=(4,), dtype=np.float32, name="x",
                              varlen=True)
    import tensorflow as tf
    short = tf.train.Example(features=tf.train.Features(feature={
        "x": tf.train.Feature(float_list=tf.train.FloatList(
            value=[1.0, 2.0]))})).SerializeToString()
    long = tf.train.Example(features=tf.train.Features(feature={
        "x": tf.train.Feature(float_list=tf.train.FloatList(
            value=[1, 2, 3, 4, 5, 6]))})).SerializeToString()
    batch = tfexample.parse_example_batch(np.array([short, long]), st)
    np.testing.assert_array_equal(batch["x"][0], [1, 2, 0, 0])
    np.testing.assert_array_equal(batch["x"][1], [1, 2, 3, 4])

  def test_raw_wire_lossless_roundtrip(self):
    """data_format='raw': tensors ride as C-order bytes — exact for
    any dtype, no codec. The decode-CPU escape hatch for hosts that
    can't jpeg-decode at chip rate."""
    st = TensorSpecStruct()
    st.img = ExtendedTensorSpec(shape=(8, 8, 3), dtype=np.uint8,
                                name="i", data_format="raw")
    st.depth = ExtendedTensorSpec(shape=(4, 4), dtype=np.float32,
                                  name="d", data_format="raw")
    rng = np.random.default_rng(2)
    img = rng.integers(0, 255, (8, 8, 3), dtype=np.uint8)
    depth = rng.standard_normal((4, 4)).astype(np.float32)
    serialized = tfexample.encode_example({"img": img, "depth": depth},
                                          st)
    batch = tfexample.parse_example_batch(
        np.array([serialized, serialized]), st)
    np.testing.assert_array_equal(batch["img"][1], img)
    np.testing.assert_array_equal(batch["depth"][0], depth)

  def test_raw_wire_graph_matches_eager(self):
    import tensorflow as tf

    st = TensorSpecStruct()
    st.img = ExtendedTensorSpec(shape=(6, 5, 3), dtype=np.uint8,
                                name="i", data_format="raw")
    rng = np.random.default_rng(3)
    img = rng.integers(0, 255, (6, 5, 3), dtype=np.uint8)
    serialized = tfexample.encode_example({"img": img}, st)
    eager = tfexample.parse_example_batch(np.array([serialized]), st)
    graph = tfexample.graph_parse_example(
        tf.constant([serialized]), st)
    np.testing.assert_array_equal(np.asarray(graph["img"]),
                                  eager["img"])
    np.testing.assert_array_equal(eager["img"][0], img)

  def test_sequence_spec_rejected(self):
    st = TensorSpecStruct()
    st.x = ExtendedTensorSpec(shape=(4,), dtype=np.float32, name="x",
                              is_sequence=True)
    with pytest.raises(ValueError, match="add_sequence_length"):
      tfexample.build_feature_map(st)

  @pytest.mark.parametrize("data_format", ["raw", "png"])
  def test_sequence_spec_rejected_for_bytes_formats(self, data_format):
    """Raw/image SEQUENCE specs must hit the same SequenceExample
    error — binding one byte string per example would silently fuse
    the time axis into the wire blob."""
    st = TensorSpecStruct()
    st.x = ExtendedTensorSpec(shape=(4, 4, 3), dtype=np.uint8,
                              name="x", is_sequence=True,
                              data_format=data_format)
    with pytest.raises(ValueError, match="add_sequence_length"):
      tfexample.build_feature_map(st)

  def test_raw_wire_length_mismatch_raises_eager_and_graph(self):
    """A record written against a different raw shape must ERROR in
    both parsers — the graph path would otherwise silently fuse
    examples across the batch dim (reshape absorbs the bytes)."""
    import tensorflow as tf

    written = TensorSpecStruct()
    written.x = ExtendedTensorSpec(shape=(4,), dtype=np.uint8,
                                   name="x", data_format="raw")
    declared = TensorSpecStruct()
    declared.x = ExtendedTensorSpec(shape=(8,), dtype=np.uint8,
                                    name="x", data_format="raw")
    serialized = tfexample.encode_example(
        {"x": np.arange(4, dtype=np.uint8)}, written)
    with pytest.raises(ValueError, match="wire holds 4 bytes"):
      tfexample.parse_example_batch(
          np.array([serialized, serialized]), declared)
    with pytest.raises(Exception, match="byte lengths"):
      tfexample.graph_parse_example(
          tf.constant([serialized, serialized]), declared)

  def test_missing_required_feature_raises(self):
    with pytest.raises(ValueError, match="pose"):
      tfexample.encode_example({"image": np.zeros((12, 10, 3), np.uint8),
                                "count": np.zeros((1,), np.int64)},
                               feature_spec())


def episode_spec():
  st = TensorSpecStruct()
  st.image = ExtendedTensorSpec(shape=(8, 8, 3), dtype=np.uint8,
                                name="frame", data_format="png",
                                is_sequence=True)
  st.state = ExtendedTensorSpec(shape=(3,), dtype=np.float32,
                                name="state", is_sequence=True)
  st.task_id = ExtendedTensorSpec(shape=(1,), dtype=np.int64,
                                  name="task_id")
  return st


def episode_label_spec():
  st = TensorSpecStruct()
  st.action = ExtendedTensorSpec(shape=(2,), dtype=np.float32,
                                 name="action", is_sequence=True)
  return st


def make_episode(rng, t):
  return {
      "image": rng.integers(0, 255, (t, 8, 8, 3), dtype=np.uint8),
      "state": rng.standard_normal((t, 3)).astype(np.float32),
      "task_id": np.array([7], np.int64),
      "action": rng.standard_normal((t, 2)).astype(np.float32),
  }


class TestGraphParsers:
  """The tf.data-graph parsers must match the eager parsers exactly.

  These are the production path (parse + image decode inside
  `dataset.map(num_parallel_calls=AUTOTUNE)`, SURVEY §4.3) and the body
  of the exported parse_tf_example signature; the eager parsers are the
  contract they are tested against.
  """

  def _example_batch(self):
    fs = feature_spec()
    rng = np.random.default_rng(0)
    yy, xx = np.mgrid[0:12, 0:10]
    examples = []
    for i in range(3):
      examples.append({
          "image": np.stack([yy * 2 * (i + 1), xx * 3, (yy + xx) * i],
                            axis=-1).astype(np.uint8),
          "pose": rng.standard_normal(6).astype(np.float32),
          "count": np.array([i], np.int64),
      })
    serialized = np.array(
        [tfexample.encode_example(e, fs) for e in examples],
        dtype=object)
    return fs, serialized

  def test_example_graph_matches_eager(self):
    import tensorflow as tf
    fs, serialized = self._example_batch()
    eager = tfexample.parse_example_batch(serialized, fs)
    graph = tf.function(
        lambda s: tfexample.graph_parse_example(s, fs))(
            tf.convert_to_tensor(serialized))
    for key, value in eager.to_flat_dict().items():
      got = np.asarray(graph[key])
      assert got.dtype == value.dtype, key
      np.testing.assert_array_equal(got, value, err_msg=key)

  def test_example_graph_varlen(self):
    import tensorflow as tf
    st = TensorSpecStruct()
    st.x = ExtendedTensorSpec(shape=(4,), dtype=np.float32, name="x",
                              varlen=True)
    short = tf.train.Example(features=tf.train.Features(feature={
        "x": tf.train.Feature(float_list=tf.train.FloatList(
            value=[1.0, 2.0]))})).SerializeToString()
    long = tf.train.Example(features=tf.train.Features(feature={
        "x": tf.train.Feature(float_list=tf.train.FloatList(
            value=[1, 2, 3, 4, 5, 6]))})).SerializeToString()
    graph = tf.function(
        lambda s: tfexample.graph_parse_example(s, st))(
            tf.convert_to_tensor(np.array([short, long])))
    np.testing.assert_array_equal(np.asarray(graph["x"]),
                                  [[1, 2, 0, 0], [1, 2, 3, 4]])

  def test_sequence_graph_matches_eager(self):
    import tensorflow as tf
    st = TensorSpecStruct()
    st.frames = ExtendedTensorSpec(
        shape=(6, 5, 3), dtype=np.uint8, name="frames",
        data_format="png", is_sequence=True)
    st.action = ExtendedTensorSpec(
        shape=(2,), dtype=np.float32, name="act", is_sequence=True)
    st.task_id = ExtendedTensorSpec(shape=(1,), dtype=np.int64,
                                    name="task")
    rng = np.random.default_rng(2)
    episodes = []
    for t in (2, 5):  # ragged: one under, one over sequence_length=4
      episodes.append({
          "frames": rng.integers(0, 255, (t, 6, 5, 3)).astype(np.uint8),
          "action": rng.standard_normal((t, 2)).astype(np.float32),
          "task_id": np.array([t], np.int64),
      })
    serialized = np.array([
        tfexample.encode_sequence_example(e, st) for e in episodes],
        dtype=object)
    eager = tfexample.parse_sequence_example_batch(serialized, st, 4)
    graph = tf.function(
        lambda s: tfexample.graph_parse_sequence_example(s, st, 4))(
            tf.convert_to_tensor(serialized))
    for key, value in eager.to_flat_dict().items():
      got = np.asarray(graph[key])
      assert got.shape == value.shape, key
      np.testing.assert_array_equal(got, value, err_msg=key)
    np.testing.assert_array_equal(
        np.asarray(graph[tfexample.SEQUENCE_LENGTH_KEY]), [2, 4])

  def test_pipeline_feeds_faster_than_chip(self, tmp_path):
    """Throughput microbench: host pipeline vs the measured step rate.

    The bench chip consumes ~232 batches/s at batch 256 (BENCH_DETAIL);
    a single-host tf.data pipeline can't match a 64-image-per-example
    rate on shared CI hardware, so the assertion here is a sanity
    floor — the real number is printed for the record. Run on a
    production host, the AUTOTUNE-parallel decode path is the one that
    scales with cores; the old eager path was single-threaded.
    """
    import time
    fs = feature_spec()
    rng = np.random.default_rng(0)
    examples = [{
        "image": rng.integers(0, 255, (12, 10, 3)).astype(np.uint8),
        "pose": rng.standard_normal(6).astype(np.float32),
        "count": np.array([1], np.int64),
        "target": rng.standard_normal(2).astype(np.float32),
    } for _ in range(256)]
    path = str(tmp_path / "bench.tfrecord")
    write_tfrecord(path, examples, fs, label_spec())
    gen = TFRecordInputGenerator(file_patterns=path, batch_size=64,
                                 shuffle_buffer_size=256, seed=0)
    gen.set_specification(fs, label_spec())
    it = gen.create_dataset(Mode.TRAIN)
    next(it)  # warm the pipeline
    n = 30
    t0 = time.perf_counter()
    for _ in range(n):
      next(it)
    rate = n / (time.perf_counter() - t0)
    print(f"\npipeline: {rate:.1f} batches/s (batch=64, jpeg decode)")
    assert rate > 5.0  # sanity floor; single-threaded eager was ~this


class TestSequenceExampleCodec:

  def test_roundtrip_pads_and_reports_lengths(self):
    fs = episode_spec()
    rng = np.random.default_rng(0)
    ep_short = make_episode(rng, 3)
    ep_long = make_episode(rng, 6)
    serialized = np.array([
        tfexample.encode_sequence_example(ep_short, fs),
        tfexample.encode_sequence_example(ep_long, fs),
    ])
    batch = tfexample.parse_sequence_example_batch(
        serialized, fs, sequence_length=4)
    # Static [B, T, ...] shapes with zero padding / truncation.
    assert batch["image"].shape == (2, 4, 8, 8, 3)
    assert batch["state"].shape == (2, 4, 3)
    assert batch["task_id"].shape == (2, 1)
    np.testing.assert_array_equal(
        batch[tfexample.SEQUENCE_LENGTH_KEY], [3, 4])
    # png is lossless: frames round-trip exactly; padding is zeros.
    np.testing.assert_array_equal(batch["image"][0, :3],
                                  ep_short["image"])
    np.testing.assert_array_equal(batch["image"][0, 3],
                                  np.zeros((8, 8, 3), np.uint8))
    np.testing.assert_allclose(batch["state"][1], ep_long["state"][:4],
                               rtol=1e-6)
    np.testing.assert_array_equal(batch["task_id"][1], [7])

  def test_raw_sequence_roundtrip_eager_and_graph(self):
    """Raw frames in episodes: exact round-trip, zero time padding,
    and graph/eager parity (the graph path zero-fills '' padding via
    decode_raw's fixed_length)."""
    import tensorflow as tf

    st = TensorSpecStruct()
    st.image = ExtendedTensorSpec(shape=(8, 8, 3), dtype=np.uint8,
                                  name="frame", data_format="raw",
                                  is_sequence=True)
    st.goal = ExtendedTensorSpec(shape=(2,), dtype=np.float32,
                                 name="goal", data_format="raw")
    rng = np.random.default_rng(5)
    ep = {
        "image": rng.integers(0, 255, (3, 8, 8, 3), dtype=np.uint8),
        "goal": rng.standard_normal(2).astype(np.float32),
    }
    serialized = np.array([tfexample.encode_sequence_example(ep, st)])
    eager = tfexample.parse_sequence_example_batch(
        serialized, st, sequence_length=4)
    np.testing.assert_array_equal(eager["image"][0, :3], ep["image"])
    np.testing.assert_array_equal(eager["image"][0, 3],
                                  np.zeros((8, 8, 3), np.uint8))
    np.testing.assert_array_equal(eager["goal"][0], ep["goal"])
    graph = tfexample.graph_parse_sequence_example(
        tf.constant(serialized), st, sequence_length=4)
    np.testing.assert_array_equal(np.asarray(graph["image"]),
                                  eager["image"])
    np.testing.assert_array_equal(np.asarray(graph["goal"]),
                                  eager["goal"])

  def test_raw_sequence_frame_length_mismatch_raises_in_graph(self):
    """Mismatched raw frames must error in the graph parser too —
    fixed_length would otherwise zero-fill/truncate them into
    plausible garbage ('' time padding stays allowed)."""
    import tensorflow as tf

    written = TensorSpecStruct()
    written.f = ExtendedTensorSpec(shape=(4,), dtype=np.uint8,
                                   name="f", data_format="raw",
                                   is_sequence=True)
    declared = TensorSpecStruct()
    declared.f = ExtendedTensorSpec(shape=(8,), dtype=np.uint8,
                                    name="f", data_format="raw",
                                    is_sequence=True)
    serialized = np.array([tfexample.encode_sequence_example(
        {"f": np.arange(8, dtype=np.uint8).reshape(2, 4)}, written)])
    with pytest.raises(Exception, match="byte lengths"):
      np.asarray(tfexample.graph_parse_sequence_example(
          tf.constant(serialized), declared, sequence_length=3)["f"])
    with pytest.raises(ValueError, match="wire holds 4 bytes"):
      tfexample.parse_sequence_example_batch(serialized, declared,
                                             sequence_length=3)

  def test_mismatched_sequence_lengths_rejected(self):
    fs = episode_spec()
    rng = np.random.default_rng(1)
    ep = make_episode(rng, 3)
    ep["state"] = ep["state"][:2]
    with pytest.raises(ValueError, match="share a length"):
      tfexample.encode_sequence_example(ep, fs)

  def test_missing_required_sequence_feature_raises(self):
    fs = episode_spec()
    with pytest.raises(ValueError, match="state"):
      tfexample.encode_sequence_example(
          {"image": np.zeros((2, 8, 8, 3), np.uint8),
           "task_id": np.array([0], np.int64)}, fs)


class TestEpisodeGenerator:

  def test_end_to_end(self, tmp_path):
    from tensor2robot_tpu.data import (
        TFRecordEpisodeInputGenerator,
        write_episode_tfrecord,
    )
    fs, ls = episode_spec(), episode_label_spec()
    rng = np.random.default_rng(0)
    episodes = [make_episode(rng, t) for t in [3, 5, 4, 6]]
    path = str(tmp_path / "episodes.tfrecord")
    write_episode_tfrecord(path, episodes, fs, ls)

    gen = TFRecordEpisodeInputGenerator(
        file_patterns=path, batch_size=2, sequence_length=5,
        shuffle=False)
    gen.set_specification(fs, ls)
    features, labels = next(gen.create_dataset(Mode.TRAIN))
    assert features["image"].shape == (2, 5, 8, 8, 3)
    assert features["state"].shape == (2, 5, 3)
    assert features["task_id"].shape == (2, 1)
    np.testing.assert_array_equal(features["sequence_length"], [3, 5])
    assert labels["action"].shape == (2, 5, 2)

  def test_meta_batch_from_episodes(self):
    from tensor2robot_tpu.meta_learning import meta_batch_from_episodes
    rng = np.random.default_rng(0)
    features = TensorSpecStruct.from_flat_dict({
        "state": rng.standard_normal((2, 6, 3)).astype(np.float32),
        "sequence_length": np.array([6, 6], np.int32),
    })
    labels = TensorSpecStruct.from_flat_dict({
        "action": rng.standard_normal((2, 6, 2)).astype(np.float32)})
    mf, ml = meta_batch_from_episodes(features, labels,
                                      num_condition=4, num_inference=2)
    assert mf["condition/state"].shape == (2, 4, 3)
    assert mf["inference/state"].shape == (2, 2, 3)
    assert "sequence_length" not in mf
    assert ml["condition/action"].shape == (2, 4, 2)
    np.testing.assert_array_equal(
        mf["inference/state"],
        np.asarray(features["state"])[:, 4:6])

  def test_too_short_episode_raises(self):
    from tensor2robot_tpu.meta_learning import meta_batch_from_episodes
    features = TensorSpecStruct.from_flat_dict({
        "state": np.zeros((2, 3, 3), np.float32)})
    with pytest.raises(ValueError, match="time"):
      meta_batch_from_episodes(features, None, num_condition=4,
                               num_inference=2)

  def test_padded_short_episode_dropped_via_true_lengths(self):
    # A zero-padded [B, 16, ...] batch LOOKS long enough; the true
    # lengths say otherwise: short episodes are dropped (ragged real
    # datasets must not abort the iterator), all-short raises.
    from tensor2robot_tpu.meta_learning import meta_batch_from_episodes
    state = np.zeros((2, 16, 3), np.float32)
    state[1] = 7.0
    features = TensorSpecStruct.from_flat_dict({
        "state": state,
        "sequence_length": np.array([3, 16], np.int32)})
    mf, _ = meta_batch_from_episodes(features, None, num_condition=4,
                                     num_inference=4)
    assert mf["condition/state"].shape == (1, 4, 3)
    np.testing.assert_array_equal(mf["condition/state"],
                                  state[1:2, :4])
    all_short = TensorSpecStruct.from_flat_dict({
        "state": np.zeros((2, 16, 3), np.float32),
        "sequence_length": np.array([3, 5], np.int32)})
    with pytest.raises(ValueError, match="zero padding"):
      meta_batch_from_episodes(all_short, None, num_condition=4,
                               num_inference=4)

  def test_meta_generator_constant_task_dim_under_raggedness(self):
    # Ragged datasets must not shrink the task dim (every distinct task
    # count would retrace the jitted step) nor abort on an all-short
    # batch: the generator buffers surviving episodes across batches.
    from tensor2robot_tpu.meta_learning import EpisodeMetaInputGenerator
    from tensor2robot_tpu.data.abstract_input_generator import (
        AbstractInputGenerator,
    )

    spec = TensorSpecStruct.from_flat_dict({
        "state": ExtendedTensorSpec(shape=(3,), dtype=np.float32,
                                    name="state", is_sequence=True)})

    class RaggedEpisodes(AbstractInputGenerator):
      # Batches of 2 episodes with true lengths cycling through a
      # pattern that includes an ALL-short batch.
      lengths = [(8, 3), (2, 2), (8, 8), (3, 8)]

      def _create_dataset(self, mode, batch_size):
        i = 0
        while True:
          lens = self.lengths[i % len(self.lengths)]
          i += 1
          yield (TensorSpecStruct.from_flat_dict({
              "state": np.full((2, 8, 3), i, np.float32),
              "sequence_length": np.array(lens, np.int32)}), None)

    inner = RaggedEpisodes()
    inner.set_specification(spec)
    gen = EpisodeMetaInputGenerator(
        inner, num_condition_samples_per_task=4,
        num_inference_samples_per_task=4, batch_size=2)
    gen.set_specification(spec)
    it = gen.create_dataset(Mode.TRAIN, batch_size=2)
    shapes = [next(it)[0]["condition/state"].shape for _ in range(4)]
    assert shapes == [(2, 4, 3)] * 4

  def test_context_keys_tiled_not_sliced(self):
    from tensor2robot_tpu.meta_learning import meta_batch_from_episodes
    goal = np.arange(20, dtype=np.float32).reshape(2, 10)
    features = TensorSpecStruct.from_flat_dict({
        "state": np.zeros((2, 8, 3), np.float32),
        "goal": goal})
    mf, _ = meta_batch_from_episodes(features, None, num_condition=4,
                                     num_inference=2,
                                     context_keys=("goal",))
    assert mf["condition/goal"].shape == (2, 4, 10)
    assert mf["inference/goal"].shape == (2, 2, 10)
    np.testing.assert_array_equal(mf["condition/goal"][:, 0], goal)
    np.testing.assert_array_equal(mf["condition/goal"][:, 3], goal)

  def test_reserved_sequence_length_spec_key_rejected(self):
    st = TensorSpecStruct()
    st.x = ExtendedTensorSpec(shape=(2,), dtype=np.float32, name="x",
                              is_sequence=True)
    st.sequence_length = ExtendedTensorSpec(shape=(1,), dtype=np.int64,
                                            name="seq_len")
    with pytest.raises(ValueError, match="reserved"):
      tfexample.parse_sequence_example_batch(
          np.array([b""]), st, sequence_length=2)


class TestTFRecordGenerator:

  def test_end_to_end(self, tmp_path):
    fs, ls = feature_spec(), label_spec()
    rng = np.random.default_rng(0)
    examples = []
    for _ in range(8):
      examples.append({
          "image": rng.integers(0, 255, (12, 10, 3), dtype=np.uint8),
          "pose": rng.standard_normal(6).astype(np.float32),
          "count": np.array([1], np.int64),
          "target": rng.standard_normal(2).astype(np.float32),
      })
    path = str(tmp_path / "data.tfrecord")
    write_tfrecord(path, examples, fs, ls)

    gen = TFRecordInputGenerator(file_patterns=path, batch_size=4,
                                 shuffle=False, seed=0)
    gen.set_specification(fs, ls)
    features, labels = next(gen.create_dataset(Mode.TRAIN))
    assert features["image"].shape == (4, 12, 10, 3)
    assert labels["target"].shape == (4, 2)
    specs.validate_and_pack(fs, features)

  def test_eval_mode_finite(self, tmp_path):
    fs = TensorSpecStruct()
    fs.x = ExtendedTensorSpec(shape=(2,), dtype=np.float32, name="x")
    examples = [{"x": np.ones(2, np.float32)} for _ in range(6)]
    path = str(tmp_path / "d.tfrecord")
    write_tfrecord(path, examples, fs)
    gen = TFRecordInputGenerator(file_patterns=path, batch_size=2,
                                 shuffle=False)
    gen.set_specification(fs)
    batches = list(gen.create_dataset(Mode.EVAL))
    assert len(batches) == 3

  def test_no_files_raises(self):
    gen = TFRecordInputGenerator(file_patterns="/nonexistent/*.tfrecord",
                                 batch_size=2)
    gen.set_specification(feature_spec())
    with pytest.raises(ValueError, match="No TFRecord files"):
      next(gen.create_dataset(Mode.TRAIN))


class TestPrefetch:

  def test_sharded_prefetch_over_mesh(self):
    devices = jax.devices()
    assert len(devices) == 8, "conftest must provide 8 virtual devices"
    mesh = jax.sharding.Mesh(np.array(devices), ("data",))
    gen = RandomInputGenerator(batch_size=16)
    gen.set_specification(feature_spec(), label_spec())
    prefetcher = prefetch_to_mesh(
        gen.create_dataset(Mode.TRAIN), mesh, buffer_size=2)
    features, labels = next(iter(prefetcher))
    assert isinstance(features["pose"], jax.Array)
    assert features["pose"].shape == (16, 6)
    # Batch axis is sharded 8 ways.
    assert len(features["pose"].sharding.device_set) == 8
    shard_shapes = {s.data.shape for s in features["pose"].addressable_shards}
    assert shard_shapes == {(2, 6)}
    assert labels["target"].shape == (16, 2)

  def test_error_propagates(self):
    def bad_iterator():
      yield {"x": np.zeros((8, 2), np.float32)}
      raise RuntimeError("boom")

    mesh = jax.sharding.Mesh(np.array(jax.devices()), ("data",))
    prefetcher = ShardedPrefetcher(
        bad_iterator(), make_data_sharding(mesh), buffer_size=1)
    it = iter(prefetcher)
    next(it)
    with pytest.raises(RuntimeError, match="boom"):
      next(it)

  def test_finite_iterator_stops(self):
    mesh = jax.sharding.Mesh(np.array(jax.devices()), ("data",))
    data = iter([{"x": np.zeros((8, 2), np.float32)}] * 3)
    prefetcher = ShardedPrefetcher(data, make_data_sharding(mesh))
    assert len(list(prefetcher)) == 3

  def test_slow_consumer_still_sees_all_items_and_sentinel(self):
    # Regression: the done-sentinel must not be dropped when the queue
    # is full at iterator exhaustion (deadlocked the consumer).
    import time
    mesh = jax.sharding.Mesh(np.array(jax.devices()), ("data",))
    data = iter([{"x": np.zeros((8, 2), np.float32)}] * 5)
    prefetcher = ShardedPrefetcher(data, make_data_sharding(mesh),
                                   buffer_size=1)
    time.sleep(0.5)  # let the worker fill the queue and finish
    assert len(list(prefetcher)) == 5

  def test_close_unblocks_abandoned_stream(self):
    # Infinite generator; consumer abandons after 1 batch; close() must
    # terminate the worker thread.
    def infinite():
      while True:
        yield {"x": np.zeros((8, 2), np.float32)}

    mesh = jax.sharding.Mesh(np.array(jax.devices()), ("data",))
    prefetcher = ShardedPrefetcher(infinite(), make_data_sharding(mesh),
                                   buffer_size=2)
    next(iter(prefetcher))
    prefetcher.close()
    assert not prefetcher._thread.is_alive()


class TestStackBatches:
  """The steps_per_dispatch host-side stacker (data/prefetch.py)."""

  def test_groups_k_batches(self):
    from tensor2robot_tpu.data.prefetch import stack_batches

    stream = ({"x": np.full((2, 3), i, np.float32)} for i in range(6))
    stacks = list(stack_batches(stream, 3))
    assert len(stacks) == 2
    assert stacks[0]["x"].shape == (3, 2, 3)
    np.testing.assert_array_equal(stacks[1]["x"][:, 0, 0], [3, 4, 5])

  def test_finite_stream_ends_cleanly_mid_stack(self):
    """PEP 479 guard: the inner StopIteration must NOT surface as a
    RuntimeError — a finite input stream ends the run cleanly (the
    trainer's final off-interval checkpoint depends on it)."""
    from tensor2robot_tpu.data.prefetch import stack_batches

    stream = ({"x": np.zeros((2,), np.float32)} for _ in range(5))
    stacks = list(stack_batches(stream, 2))  # 5 = 2 stacks + 1 dropped
    assert len(stacks) == 2
