"""End-to-end trainer tests (reference: train_eval_test.py pattern —
MockT2RModel + random input generators, then assert on-disk artifacts)."""

import glob
import json
import os
import threading

import jax
import numpy as np
import pytest

from tensor2robot_tpu import train_eval
from tensor2robot_tpu.data import Mode, RandomInputGenerator
from tensor2robot_tpu.hooks import Hook
from tensor2robot_tpu.utils import checkpoints as ckpt_lib
from tensor2robot_tpu.utils.mocks import MockT2RModel
from tensor2robot_tpu.telemetry.records import read_records


class RecordingHook(Hook):

  def __init__(self):
    self.began = False
    self.steps = []
    self.checkpoints = []
    self.ended = False

  def begin(self, model, model_dir):
    self.began = True

  def after_step(self, step, metrics):
    self.steps.append(step)

  def after_checkpoint(self, step, state, model_dir):
    self.checkpoints.append(step)

  def end(self, step, state, model_dir):
    self.ended = True


def test_train_eval_end_to_end(tmp_path):
  model_dir = str(tmp_path / "m")
  hook = RecordingHook()
  state = train_eval.train_eval_model(
      model=MockT2RModel(),
      model_dir=model_dir,
      input_generator_train=RandomInputGenerator(batch_size=16),
      input_generator_eval=RandomInputGenerator(batch_size=16),
      max_train_steps=20,
      eval_steps=3,
      save_checkpoints_steps=10,
      log_every_steps=5,
      hooks=[hook],
  )
  assert int(np.asarray(jax.device_get(state.step))) == 20
  # Checkpoints at 10 and 20.
  assert ckpt_lib.list_steps(model_dir) == [10, 20]
  # Hooks fired.
  assert hook.began and hook.ended
  assert hook.checkpoints == [10, 20]
  assert len(hook.steps) == 20
  # Metrics written.
  records = read_records(
      os.path.join(model_dir, "metrics_train.jsonl"))
  assert records[-1]["step"] == 20
  assert "loss" in records[-1] and "steps_per_sec" in records[-1]
  # The feed-boundness signal rides every train log record: the share
  # of the interval's wall spent blocked in the prefetcher.
  for record in records:
    assert 0.0 <= record["input_wait_fraction"] <= 1.0
  assert "stall_fraction" in records[-1]
  eval_lines = open(
      os.path.join(model_dir, "metrics_eval.jsonl")).readlines()
  assert len(eval_lines) >= 1


def test_resume_from_checkpoint(tmp_path):
  model_dir = str(tmp_path / "m")
  common = dict(
      model=MockT2RModel(),
      model_dir=model_dir,
      input_generator_train=RandomInputGenerator(batch_size=8),
      max_train_steps=10,
      save_checkpoints_steps=5,
      log_every_steps=5,
  )
  train_eval.train_eval_model(**common)
  assert ckpt_lib.latest_step(model_dir) == 10
  # Second call with a higher cap resumes at 10, trains to 15.
  common["max_train_steps"] = 15
  state = train_eval.train_eval_model(**common)
  assert int(np.asarray(jax.device_get(state.step))) == 15
  assert 15 in ckpt_lib.list_steps(model_dir)


def test_eval_only(tmp_path):
  model_dir = str(tmp_path / "m")
  state = train_eval.train_eval_model(
      model=MockT2RModel(),
      model_dir=model_dir,
      input_generator_eval=RandomInputGenerator(batch_size=8),
      max_train_steps=0,
      eval_steps=2,
  )
  eval_lines = open(
      os.path.join(model_dir, "metrics_eval.jsonl")).readlines()
  assert len(eval_lines) == 1


def test_train_loss_decreases(tmp_path):
  model_dir = str(tmp_path / "m")
  train_eval.train_eval_model(
      model=MockT2RModel(),
      model_dir=model_dir,
      input_generator_train=RandomInputGenerator(batch_size=32, seed=3),
      max_train_steps=200,
      save_checkpoints_steps=200,
      log_every_steps=10,
  )
  records = read_records(
      os.path.join(model_dir, "metrics_train.jsonl"))
  # Random targets: loss should shrink toward the target variance floor.
  assert records[-1]["loss"] < records[0]["loss"]


def test_continuous_eval(tmp_path):
  model_dir = str(tmp_path / "m")
  model = MockT2RModel()
  # Produce two checkpoints first.
  train_eval.train_eval_model(
      model=model,
      model_dir=model_dir,
      input_generator_train=RandomInputGenerator(batch_size=8),
      max_train_steps=10,
      save_checkpoints_steps=5,
  )
  results = train_eval.continuous_eval(
      model=model,
      model_dir=model_dir,
      input_generator_eval=RandomInputGenerator(batch_size=8),
      eval_steps=2,
      timeout_secs=0.5,
      poll_interval_secs=0.1,
      max_evals=5,
  )
  # Latest checkpoint evaluated; then timeout ends the loop.
  assert 10 in results
  assert "loss" in results[10]


def test_mesh_sharded_training_runs_on_8_devices(tmp_path):
  from tensor2robot_tpu.parallel import mesh as mesh_lib
  mesh = mesh_lib.create_mesh({"data": 8})
  model_dir = str(tmp_path / "m")
  state = train_eval.train_eval_model(
      model=MockT2RModel(),
      model_dir=model_dir,
      input_generator_train=RandomInputGenerator(batch_size=16),
      max_train_steps=5,
      save_checkpoints_steps=5,
      mesh=mesh,
  )
  # Params replicated over all 8 devices.
  leaf = jax.tree_util.tree_leaves(state.params)[0]
  assert len(leaf.sharding.device_set) == 8


def test_fsdp_strategy_trains_and_resumes(tmp_path):
  """sharding_strategy='fsdp' through the MAIN trainer: params land
  sharded over the fsdp axis, training runs, and resume restores onto
  the same layout."""
  from jax.sharding import PartitionSpec as P

  from tensor2robot_tpu.parallel import FSDP_AXIS
  from tensor2robot_tpu.parallel import mesh as mesh_lib

  mesh = mesh_lib.create_mesh({"data": 4, "fsdp": 2})
  model_dir = str(tmp_path / "m")
  # Wide enough that the hidden kernel crosses min_size_to_shard.
  kwargs = dict(
      model=MockT2RModel(hidden_sizes=(64,)),
      model_dir=model_dir,
      input_generator_train=RandomInputGenerator(batch_size=16),
      save_checkpoints_steps=5,
      mesh=mesh,
      sharding_strategy="fsdp",
      min_size_to_shard=64,
  )
  state = train_eval.train_eval_model(max_train_steps=5, **kwargs)
  sharded_leaves = [
      leaf for leaf in jax.tree_util.tree_leaves(state.params)
      if any(axis == FSDP_AXIS
             for axis in (leaf.sharding.spec or P()))]
  assert sharded_leaves, {  # at least one param actually fsdp-sharded
      jax.tree_util.keystr(path): leaf.sharding for path, leaf in
      jax.tree_util.tree_leaves_with_path(state.params)}
  # Resume: second call picks up the checkpoint and continues sharded.
  state = train_eval.train_eval_model(max_train_steps=8, **kwargs)
  assert int(np.asarray(jax.device_get(state.step))) == 8


def test_fsdp_trained_model_exports_and_serves(tmp_path):
  """Pod-style training hands off to robot-style serving: a model
  trained with fsdp-sharded state exports a SavedModel (the exporter
  gathers shards host-side) and the predictor round-trips it."""
  from tensor2robot_tpu.export import (
      SavedModelExportGenerator,
      latest_export_dir,
  )
  from tensor2robot_tpu.parallel import mesh as mesh_lib
  from tensor2robot_tpu.predictors import SavedModelPredictor
  from tensor2robot_tpu.specs import make_random_tensors

  mesh = mesh_lib.create_mesh({"data": 4, "fsdp": 2})
  model = MockT2RModel(hidden_sizes=(64,))
  model_dir = str(tmp_path / "m")
  train_eval.train_eval_model(
      model=model,
      model_dir=model_dir,
      input_generator_train=RandomInputGenerator(batch_size=16),
      max_train_steps=5,
      save_checkpoints_steps=5,
      mesh=mesh,
      sharding_strategy="fsdp",
      min_size_to_shard=64,
      create_exporters_fn=lambda m: [SavedModelExportGenerator()],
  )
  export_base = SavedModelExportGenerator().export_dir_base(model_dir)
  assert latest_export_dir(export_base) is not None
  predictor = SavedModelPredictor(export_base)
  assert predictor.restore(timeout_secs=0)
  batch = make_random_tensors(
      model.preprocessor.get_in_feature_specification(Mode.PREDICT),
      batch_size=3, seed=7)
  out = predictor.predict(
      {k: np.asarray(v) for k, v in batch.to_flat_dict().items()})
  values = np.asarray(list(out.values())[0])
  assert values.shape[0] == 3
  assert np.isfinite(values).all()


def test_mesh_and_strategy_configurable_from_gin():
  """The full sharded-training surface is reachable from .gin files:
  mesh layout AND strategy are bindings, no Python required."""
  from tensor2robot_tpu import config as gin
  import tensor2robot_tpu.parallel  # noqa: F401 — registers create_mesh

  gin.clear_config()
  try:
    gin.parse_config_files_and_bindings([], [
        'train_eval_model.mesh = @create_mesh()',
        'create_mesh.axis_shapes = {"data": 4, "fsdp": 2}',
        'train_eval_model.sharding_strategy = "fsdp"',
    ])
    mesh = gin.query_parameter("train_eval_model.mesh").resolve()
    assert dict(mesh.shape) == {"data": 4, "fsdp": 2}
    assert gin.query_parameter(
        "train_eval_model.sharding_strategy") == "fsdp"
  finally:
    gin.clear_config()


def test_distributed_init_noops_single_process():
  """Single-process launches must not try to form a cluster."""
  from tensor2robot_tpu.parallel import maybe_initialize_distributed
  from tensor2robot_tpu.parallel import distributed as dist_mod
  assert not dist_mod._INITIALIZED
  assert maybe_initialize_distributed() is False
  assert not dist_mod._INITIALIZED


def test_tensor_parallel_rules_compile_on_mesh():
  """The TP sharding rules must produce an executable program.

  The driver's dryrun covers the full learner; this is the in-suite
  guard that `tensor_parallel_sharding` stays compilable: a dense
  kernel splits its output dim over `model`, its input dim over
  `fsdp`, and matmul against a data-sharded batch executes.
  """
  import jax
  import jax.numpy as jnp
  from jax.sharding import NamedSharding, PartitionSpec as P
  from tensor2robot_tpu.parallel import (
      DATA_AXIS,
      FSDP_AXIS,
      MODEL_AXIS,
      batch_sharding,
      create_mesh,
      tensor_parallel_sharding,
  )

  mesh = create_mesh({DATA_AXIS: 2, FSDP_AXIS: 2, MODEL_AXIS: 2})
  params = {"kernel": jnp.ones((64, 128)), "bias": jnp.ones((128,))}
  shardings = tensor_parallel_sharding(mesh, params,
                                       min_size_to_shard=2 ** 6)
  assert shardings["kernel"].spec == P(FSDP_AXIS, MODEL_AXIS)
  params = jax.device_put(params, shardings)
  batch = jax.device_put(jnp.ones((8, 64)), batch_sharding(mesh))

  @jax.jit
  def forward(params, x):
    return jnp.mean(x @ params["kernel"] + params["bias"])

  with mesh:
    out = forward(params, batch)
  assert bool(jnp.isfinite(out))


def test_steps_per_dispatch_matches_per_step_training(tmp_path):
  """K-scanned dispatches (the reference's iterations_per_loop) must
  be numerically identical to per-step dispatch: same deterministic
  generator stream, same per-step PRNG folding."""
  def run(k, name):
    return train_eval.train_eval_model(
        model=MockT2RModel(),
        model_dir=str(tmp_path / name),
        input_generator_train=RandomInputGenerator(batch_size=8,
                                                   seed=5),
        max_train_steps=6,
        save_checkpoints_steps=6,
        log_every_steps=3,
        steps_per_dispatch=k,
    )

  base = run(1, "k1")
  scanned = run(3, "k3")
  assert int(np.asarray(jax.device_get(scanned.step))) == 6
  for (path, a), b in zip(
      jax.tree_util.tree_leaves_with_path(
          jax.device_get(base.params)),
      jax.tree_util.tree_leaves(jax.device_get(scanned.params))):
    np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), atol=1e-6, rtol=1e-6,
        err_msg=str(path))


def test_steps_per_dispatch_rejects_misaligned_cadence(tmp_path):
  with pytest.raises(ValueError, match="multiple of"):
    train_eval.train_eval_model(
        model=MockT2RModel(),
        model_dir=str(tmp_path / "bad"),
        input_generator_train=RandomInputGenerator(batch_size=8),
        max_train_steps=10,
        save_checkpoints_steps=5,
        log_every_steps=5,
        steps_per_dispatch=4,
    )


def test_completed_run_reinvoked_with_k_noops(tmp_path):
  """Re-invoking a finished run with steps_per_dispatch>1 must no-op
  (resume sees step >= max_train_steps), not raise on alignment."""
  kwargs = dict(
      model=MockT2RModel(),
      model_dir=str(tmp_path / "m"),
      input_generator_train=RandomInputGenerator(batch_size=8),
  )
  train_eval.train_eval_model(
      max_train_steps=5, save_checkpoints_steps=5, log_every_steps=5,
      **kwargs)
  # Resume step 5 is NOT a multiple of K=4, but the run is already
  # complete at max_train_steps=4: the alignment check must not fire
  # for a no-op invocation (cadences here are K-aligned, so only the
  # resume-alignment guard is exercised).
  state = train_eval.train_eval_model(
      max_train_steps=4, save_checkpoints_steps=4, log_every_steps=4,
      steps_per_dispatch=4, **kwargs)
  assert int(np.asarray(jax.device_get(state.step))) == 5
