"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip sharding is tested without TPU hardware by splitting the host
CPU into 8 virtual XLA devices (SURVEY.md §5 lesson: add the multi-chip
tests the reference lacked). Must run before jax initializes its backends.
"""

import os

# Overwrite, not setdefault: the environment pre-sets JAX_PLATFORMS=axon
# (the real TPU tunnel); tests always run on the virtual CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
  os.environ["XLA_FLAGS"] = (
      xla_flags + " --xla_force_host_platform_device_count=8").strip()

# Keep TF (used only for TFRecord IO / jax2tf export) off any accelerator.
os.environ.setdefault("CUDA_VISIBLE_DEVICES", "-1")
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "2")

# Belt and braces: jax may already be imported (pytest plugin autoload),
# in which case the env var was read too early. The config update works
# as long as no backend has been initialized yet.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
