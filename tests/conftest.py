"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip sharding is tested without TPU hardware by splitting the host
CPU into 8 virtual XLA devices (SURVEY.md §5 lesson: add the multi-chip
tests the reference lacked). Must run before jax initializes its backends.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
  os.environ["XLA_FLAGS"] = (
      xla_flags + " --xla_force_host_platform_device_count=8").strip()

# Keep TF (used only for TFRecord IO / jax2tf export) off any accelerator.
os.environ.setdefault("CUDA_VISIBLE_DEVICES", "-1")
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "2")
