"""Profiling utilities: xplane wire-format reader + MFU math."""

import os
import struct

import numpy as np

from tensor2robot_tpu.utils import profiling, xplane


def _varint(value: int) -> bytes:
  out = b""
  while True:
    bits = value & 0x7F
    value >>= 7
    if value:
      out += bytes([bits | 0x80])
    else:
      return out + bytes([bits])


def _field(number: int, wire: int, payload: bytes) -> bytes:
  return _varint((number << 3) | wire) + (
      _varint(int.from_bytes(payload, "little")) if wire == 0
      else _varint(len(payload)) + payload)


def _varint_field(number: int, value: int) -> bytes:
  return _varint((number << 3) | 0) + _varint(value)


def _msg_field(number: int, payload: bytes) -> bytes:
  return _varint((number << 3) | 2) + _varint(len(payload)) + payload


class TestXplaneReader:

  def test_parses_synthetic_trace(self, tmp_path):
    """Hand-encode an XSpace with one TPU plane, two ops, two events
    each — the reader must aggregate durations by op name."""
    # XEventMetadata {id=1, name=2}; map entry {key=1, value=2}.
    def event_metadata(meta_id, name):
      inner = (_varint_field(1, meta_id)
               + _msg_field(2, name.encode()))
      return _msg_field(4, _varint_field(1, meta_id)
                        + _msg_field(2, inner))

    # XEvent {metadata_id=1, duration_ps=3}.
    def event(meta_id, duration_ps):
      return _msg_field(4, _varint_field(1, meta_id)
                        + _varint_field(3, duration_ps))

    line = _msg_field(3, event(1, 2_000_000) + event(1, 3_000_000)
                      + event(2, 500_000))
    plane = (_msg_field(2, b"/device:TPU:0")
             + line
             + event_metadata(1, "%fusion.1")
             + event_metadata(2, "%copy.9"))
    host_plane = (_msg_field(2, b"/host:CPU")
                  + _msg_field(3, event(1, 9_000_000))
                  + event_metadata(1, "python"))
    xspace = _msg_field(1, plane) + _msg_field(1, host_plane)

    path = tmp_path / "t.xplane.pb"
    path.write_bytes(xspace)
    totals = xplane.op_times_ms(str(tmp_path))
    assert totals == {"%fusion.1": 0.005, "%copy.9": 0.0005}
    top = xplane.top_ops(str(tmp_path), k=1)
    assert top == [("%fusion.1", 0.005)]

  def test_empty_dir(self, tmp_path):
    assert xplane.op_times_ms(str(tmp_path)) == {}


class TestMFU:

  def test_known_device_peak(self):
    class FakeDevice:
      device_kind = "TPU v5 lite"
    assert profiling.device_peak_flops(FakeDevice()) == 197e12
    assert profiling.mfu(100.0, 197e8, FakeDevice()) == 0.01

  def test_unknown_device_returns_none(self):
    class FakeDevice:
      device_kind = "QPU mystery"
    assert profiling.device_peak_flops(FakeDevice()) is None
    assert profiling.mfu(1.0, 1.0, FakeDevice()) is None
