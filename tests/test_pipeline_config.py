"""Pipeline parallelism as a config-reachable framework capability.

`parallel/pipeline.py`'s GPipe schedule got trunk integration in round
5 (round-4 verdict: "a library primitive, not a framework capability"):
`PipelinedCausalTransformer` stacks the trunk's blocks into stages
under the ``stages`` param contract, `state_sharding` grew a
"pipeline" strategy, and the vrgripper transformer family + a shipped
.gin reach it by config. These tests pin that whole path:

  * pipelined output/gradients == the sequential fallback on the SAME
    stacked params (checkpoint portability: train on a pod, serve on
    one chip),
  * the "pipeline" sharding rules place stage-stacked leaves on
    `stage` and raise rather than silently replicate,
  * the shipped .gin trains end-to-end through `train_eval_model` on
    a data×stage mesh and the checkpoint restores into a mesh-free
    serving model.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from tensor2robot_tpu.telemetry.records import read_records
from tensor2robot_tpu.layers.pipelined_transformer import (
    PipelinedCausalTransformer,
)
from tensor2robot_tpu.parallel import (
    DATA_AXIS,
    STAGE_AXIS,
    create_mesh,
    pipeline_sharding,
    state_sharding,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tiny_pipelined_model_kwargs(**overrides):
  """One copy of the small pipelined-BC model config (the serving and
  compose tests must stay on the SAME architecture)."""
  kwargs = dict(
      image_size=24, filters=(8,), embedding_size=16, width=32,
      depth=4, num_heads=2, max_context_length=64,
      attention_impl="reference", pipeline_stages=4,
      pipeline_microbatches=2)
  kwargs.update(overrides)
  return kwargs


def _trunk(mesh, **overrides):
  kwargs = dict(width=32, depth=4, num_heads=2, max_len=16,
                num_stages=4, num_microbatches=2, mesh=mesh,
                dtype=jnp.float32)
  kwargs.update(overrides)
  return PipelinedCausalTransformer(**kwargs)


class TestPipelinedTrunk:

  @pytest.fixture(scope="class")
  def mesh(self):
    return create_mesh({DATA_AXIS: 2, STAGE_AXIS: 4})

  @pytest.mark.slow
  def test_matches_sequential_fallback(self, mesh):
    """Same stacked params, pipelined (data×stage mesh) vs the
    sequential-scan fallback (mesh=None): identical outputs AND
    parameter gradients — the portability contract that lets a
    pod-trained pipelined checkpoint serve on one chip."""
    x = jnp.asarray(
        np.random.default_rng(0).standard_normal((8, 16, 8)),
        jnp.float32)
    pipelined = _trunk(mesh)
    sequential = _trunk(None)
    variables = sequential.init(jax.random.PRNGKey(0), x)

    np.testing.assert_allclose(
        np.asarray(pipelined.apply(variables, x)),
        np.asarray(sequential.apply(variables, x)),
        atol=1e-5, rtol=1e-5)

    pp_grads = jax.grad(
        lambda v: jnp.sum(pipelined.apply(v, x) ** 2))(variables)
    seq_grads = jax.grad(
        lambda v: jnp.sum(sequential.apply(v, x) ** 2))(variables)
    flat_pp = jax.tree_util.tree_leaves_with_path(pp_grads)
    flat_seq = jax.tree.leaves(seq_grads)
    assert flat_pp and len(flat_pp) == len(flat_seq)
    for (path, pg), sg in zip(flat_pp, flat_seq):
      np.testing.assert_allclose(
          np.asarray(pg), np.asarray(sg), atol=5e-4, rtol=5e-4,
          err_msg=str(path))

  def test_remat_preserves_values(self, mesh):
    x = jnp.asarray(
        np.random.default_rng(1).standard_normal((8, 16, 8)),
        jnp.float32)
    plain = _trunk(mesh)
    remat = _trunk(mesh, remat=True)
    variables = plain.init(jax.random.PRNGKey(0), x)
    np.testing.assert_allclose(
        np.asarray(remat.apply(variables, x)),
        np.asarray(plain.apply(variables, x)),
        atol=1e-6, rtol=1e-6)

  def test_depth_must_split_into_stages(self):
    x = jnp.zeros((2, 8, 4), jnp.float32)
    with pytest.raises(ValueError, match="num_stages"):
      _trunk(None, depth=3).init(jax.random.PRNGKey(0), x)

  def test_ring_attention_inside_stages_rejected(self):
    """Sequence parallelism can't nest inside the stage shard_map;
    the guard must name the real constraint (without it the mesh is
    silently dropped and _attend raises a misleading error)."""
    x = jnp.zeros((8, 8, 4), jnp.float32)
    with pytest.raises(ValueError, match="pipeline stages"):
      _trunk(None, attention_impl="ring_flash").init(
          jax.random.PRNGKey(0), x)

  def test_stage_params_carry_stage_dim(self):
    x = jnp.zeros((2, 8, 4), jnp.float32)
    variables = _trunk(None).init(jax.random.PRNGKey(0), x)
    stages = variables["params"]["stages"]
    for path, leaf in jax.tree_util.tree_leaves_with_path(stages):
      assert leaf.shape[0] == 4, (path, leaf.shape)


class TestPipelineSharding:

  def test_places_stage_leaves_on_stage_axis(self):
    mesh = create_mesh({DATA_AXIS: 2, STAGE_AXIS: 4})
    x = jnp.zeros((2, 8, 4), jnp.float32)
    params = _trunk(None).init(jax.random.PRNGKey(0), x)["params"]
    shardings = state_sharding(mesh, params, strategy="pipeline",
                               min_size_to_shard=64)
    for path, sh in jax.tree_util.tree_leaves_with_path(shardings):
      names = [str(getattr(k, "key", "")) for k in path]
      if "stages" in names:
        assert sh.spec == P(STAGE_AXIS), (path, sh)
      else:
        assert STAGE_AXIS not in jax.tree.leaves(
            tuple(sh.spec)), (path, sh)

  def test_indivisible_stage_dim_raises(self):
    mesh = create_mesh({DATA_AXIS: 1, STAGE_AXIS: 8})
    tree = {"stages": {"w": jnp.zeros((4, 16, 16))}}
    with pytest.raises(ValueError, match="not divisible"):
      pipeline_sharding(mesh, tree)


@pytest.mark.slow
class TestPipelinedBCByConfig:
  """The shipped .gin trains the pipelined family end to end."""

  @pytest.fixture(scope="class")
  def run(self, tmp_path_factory):
    from tensor2robot_tpu import config as gin
    from tensor2robot_tpu import train_eval
    import tensor2robot_tpu.research.vrgripper as vrgripper
    import tensor2robot_tpu.data  # noqa: F401
    import tensor2robot_tpu.parallel  # noqa: F401

    root = tmp_path_factory.mktemp("pp_bc")
    data = vrgripper.collect_demo_episodes(
        str(root / "demos.tfrecord"), num_episodes=32, image_size=24,
        seed=7, action_noise=0.1)
    model_dir = str(root / "model")
    path = os.path.join(
        REPO, "tensor2robot_tpu", "research", "vrgripper", "configs",
        "train_vrgripper_transformer_pipeline.gin")
    gin.clear_config()
    try:
      gin.parse_config_files_and_bindings([path], [
          f"train_eval_model.model_dir = '{model_dir}'",
          "train_eval_model.max_train_steps = 6",
          "train_eval_model.save_checkpoints_steps = 6",
          "train_eval_model.log_every_steps = 2",
          "train_eval_model.batch_size = 8",
          f"train/TFRecordEpisodeInputGenerator.file_patterns = '{data}'",
          "train/TFRecordEpisodeInputGenerator.sequence_length = 8",
          "train/TFRecordEpisodeInputGenerator.batch_size = 8",
          "VRGripperTransformerModel.image_size = 24",
          "VRGripperTransformerModel.filters = (8,)",
          "VRGripperTransformerModel.embedding_size = 16",
          "VRGripperTransformerModel.width = 32",
          "VRGripperTransformerModel.num_heads = 2",
          "VRGripperTransformerModel.max_context_length = 64",
      ])
      model = gin.query_parameter("train_eval_model.model").resolve()
      state = train_eval.train_eval_model()
    finally:
      gin.clear_config()
    return model, model_dir, state

  def test_trains_and_checkpoints_on_the_stage_mesh(self, run):
    model, model_dir, state = run
    records = read_records(
        os.path.join(model_dir, "metrics_train.jsonl"))
    assert records, "no train metrics written"
    assert np.isfinite(records[-1]["loss"])
    # The trunk actually trained stage-stacked and stage-sharded.
    stages = state.params["trunk"]["stages"]
    leaves = jax.tree.leaves(stages)
    assert leaves and all(l.shape[0] == 4 for l in leaves)
    assert any(
        STAGE_AXIS in jax.tree.leaves(tuple(l.sharding.spec))
        for l in leaves), "stage weights not sharded over `stage`"

  def test_checkpoint_serves_on_mesh_free_model(self, run):
    """Pod-trained pipelined checkpoint → single-chip serving model
    (sequential fallback over the same stacked params)."""
    from tensor2robot_tpu.research.vrgripper import (
        VRGripperTransformerModel,
    )
    from tensor2robot_tpu.utils import checkpoints as ckpt_lib

    _, model_dir, _ = run
    serving = VRGripperTransformerModel(
        device_dtype=jnp.float32, **_tiny_pipelined_model_kwargs())
    state = serving.create_inference_state(jax.random.PRNGKey(0))
    variables = ckpt_lib.restore_variables(
        model_dir, like={"params": state.params,
                         "batch_stats": state.batch_stats or {}})
    state = state.replace(params=variables["params"])
    policy = serving.make_context_policy(state, context_length=8)
    rng = np.random.default_rng(3)
    out = policy({
        "image": rng.integers(0, 255, (1, 24, 24, 3)).astype(np.uint8),
        "gripper_pose": rng.standard_normal((1, 3)).astype(np.float32),
    })
    assert out["action"].shape == (1, 3)
    assert np.isfinite(out["action"]).all()


def test_pipeline_strategy_composes_with_steps_per_dispatch(tmp_path):
  """The two round-5 trainer capabilities compose: a stage-sharded
  pipelined model trains through K-scanned dispatches (the scan body
  carries the stage-stacked TrainState with its pipeline shardings)."""
  from tensor2robot_tpu import train_eval
  from tensor2robot_tpu.data import RandomInputGenerator
  from tensor2robot_tpu.models import optimizers as opt_lib
  from tensor2robot_tpu.research.vrgripper import (
      VRGripperTransformerModel,
  )

  mesh = create_mesh({DATA_AXIS: 2, STAGE_AXIS: 4})
  model = VRGripperTransformerModel(
      mesh=mesh, device_dtype=jnp.float32,
      create_optimizer_fn=lambda: opt_lib.create_optimizer(
          learning_rate=1e-3),
      **_tiny_pipelined_model_kwargs())
  state = train_eval.train_eval_model(
      model=model,
      model_dir=str(tmp_path / "m"),
      input_generator_train=RandomInputGenerator(
          batch_size=8, sequence_length=8),
      max_train_steps=4,
      save_checkpoints_steps=4,
      log_every_steps=2,
      batch_size=8,
      init_batch_size=8,
      mesh=mesh,
      sharding_strategy="pipeline",
      steps_per_dispatch=2,
  )
  assert int(np.asarray(jax.device_get(state.step))) == 4
  stages = state.params["trunk"]["stages"]
  assert any(
      STAGE_AXIS in jax.tree.leaves(tuple(l.sharding.spec))
      for l in jax.tree.leaves(stages))
