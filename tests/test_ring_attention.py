"""Ring attention: exactness, causality, gradients, mesh layouts.

Sequence parallelism is exactness-critical: the block-online softmax
must reproduce full attention bit-for-bit-ish regardless of how many
devices the sequence is cut across, and gradients must flow through
the ppermute ring for it to be usable in training.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tensor2robot_tpu.parallel import (
    DATA_AXIS,
    SEQ_AXIS,
    create_mesh,
)
from tensor2robot_tpu.parallel.ring_attention import (
    attention_reference,
    ring_attention,
    sequence_sharding,
)

B, T, H, D = 2, 64, 2, 16


def _qkv(seed=0):
  rng = np.random.default_rng(seed)
  mk = lambda: jnp.asarray(  # noqa: E731
      rng.standard_normal((B, T, H, D)).astype(np.float32))
  return mk(), mk(), mk()


class TestRingAttention:

  @pytest.mark.parametrize("causal", [False, True])
  def test_matches_reference_on_seq8_mesh(self, causal):
    q, k, v = _qkv()
    mesh = create_mesh({SEQ_AXIS: 8})
    expected = attention_reference(q, k, v, causal=causal)
    sharding = sequence_sharding(mesh)
    args = [jax.device_put(x, sharding) for x in (q, k, v)]
    got = ring_attention(*args, mesh=mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               atol=2e-5, rtol=2e-5)

  def test_matches_on_data_x_seq_mesh(self):
    q, k, v = _qkv(1)
    mesh = create_mesh({DATA_AXIS: 2, SEQ_AXIS: 4})
    expected = attention_reference(q, k, v, causal=True)
    sharding = sequence_sharding(mesh)
    args = [jax.device_put(x, sharding) for x in (q, k, v)]
    got = ring_attention(*args, mesh=mesh, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               atol=2e-5, rtol=2e-5)

  def test_single_device_fallback_is_reference(self):
    q, k, v = _qkv(2)
    got = ring_attention(q, k, v, mesh=None, causal=True)
    expected = attention_reference(q, k, v, causal=True)
    np.testing.assert_array_equal(np.asarray(got),
                                  np.asarray(expected))

  def test_non_divisible_batch_warns_and_replicates(self):
    """Small-batch serving on a data-sharded mesh still works — the
    batch replicates (with a warning) instead of failing in
    shard_map; training layouts never hit this (local_batch_size
    enforces divisibility)."""
    rng = np.random.default_rng(9)
    q, k, v = (jnp.asarray(
        rng.standard_normal((3, 32, 2, 8)).astype(np.float32))
        for _ in range(3))
    mesh = create_mesh({DATA_AXIS: 2, SEQ_AXIS: 4})
    expected = attention_reference(q, k, v, causal=True)
    with pytest.warns(RuntimeWarning, match="does not divide"):
      got = ring_attention(q, k, v, mesh=mesh, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               atol=2e-5, rtol=2e-5)

  def test_indivisible_sequence_raises(self):
    mesh = create_mesh({SEQ_AXIS: 8})
    q = jnp.zeros((1, 12, 1, 8))
    with pytest.raises(ValueError, match="divide"):
      ring_attention(q, q, q, mesh=mesh)

  def test_gradients_flow_and_match(self):
    """d(loss)/d(q,k,v) through the ring == through the reference."""
    q, k, v = _qkv(3)
    mesh = create_mesh({SEQ_AXIS: 8})
    sharding = sequence_sharding(mesh)

    def ring_loss(q, k, v):
      return jnp.sum(
          ring_attention(q, k, v, mesh=mesh, causal=True) ** 2)

    def ref_loss(q, k, v):
      return jnp.sum(attention_reference(q, k, v, causal=True) ** 2)

    args = [jax.device_put(x, sharding) for x in (q, k, v)]
    ring_grads = jax.grad(ring_loss, argnums=(0, 1, 2))(*args)
    ref_grads = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for rg, eg in zip(ring_grads, ref_grads):
      np.testing.assert_allclose(np.asarray(rg), np.asarray(eg),
                                 atol=5e-4, rtol=5e-4)

  @pytest.mark.parametrize("causal", [False, True])
  def test_flash_blocks_match_reference(self, causal):
    """ring(flash per-device blocks) == full attention: the pallas
    kernel's partials combine exactly via logsumexp across the ring."""
    q, k, v = _qkv(6)
    mesh = create_mesh({SEQ_AXIS: 8})
    expected = attention_reference(q, k, v, causal=causal)
    sharding = sequence_sharding(mesh)
    args = [jax.device_put(x, sharding) for x in (q, k, v)]
    got = ring_attention(*args, mesh=mesh, causal=causal,
                         block_impl="flash", flash_interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               atol=5e-5, rtol=5e-5)

  @pytest.mark.parametrize("causal", [False, True])
  @pytest.mark.slow
  def test_flash_block_gradients_match(self, causal):
    """jax.grad through ring(flash blocks) == reference autodiff.

    This is the TPU production training path: the pallas kernel's
    (out, lse) custom VJP composes with the lse-softmax merge, the
    lax.cond block-skip, and the ppermute rotations. Forward-only
    until round 4 — this test pins the backward."""
    q, k, v = _qkv(7)
    mesh = create_mesh({SEQ_AXIS: 8})
    sharding = sequence_sharding(mesh)

    def ring_loss(q, k, v):
      return jnp.sum(
          ring_attention(q, k, v, mesh=mesh, causal=causal,
                         block_impl="flash",
                         flash_interpret=True) ** 2)

    def ref_loss(q, k, v):
      return jnp.sum(attention_reference(q, k, v, causal=causal) ** 2)

    args = [jax.device_put(x, sharding) for x in (q, k, v)]
    ring_grads = jax.grad(ring_loss, argnums=(0, 1, 2))(*args)
    ref_grads = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for rg, eg in zip(ring_grads, ref_grads):
      np.testing.assert_allclose(np.asarray(rg), np.asarray(eg),
                                 atol=5e-4, rtol=5e-4)

  def test_jits_under_mesh(self):
    q, k, v = _qkv(4)
    mesh = create_mesh({SEQ_AXIS: 8})
    sharding = sequence_sharding(mesh)
    args = [jax.device_put(x, sharding) for x in (q, k, v)]
    fn = jax.jit(lambda q, k, v: ring_attention(
        q, k, v, mesh=mesh, causal=True))
    out = fn(*args)
    assert out.shape == (B, T, H, D)
    assert np.isfinite(np.asarray(out)).all()
