"""Cold-start subsystem tests (startup/ + trainer/predictor wiring).

Pins the contracts docs/STARTUP.md promises:
  * persistent-cache round-trip: a second process with the same cache
    dir performs ZERO XLA compilations (cache_misses == 0, every
    program a cache hit) — counted via jax.monitoring, not wall clock;
  * overlap correctness: a resume with overlapped
    restore/compile/input is bitwise-identical to the serial path;
  * startup phase timings are written for the bench probes to read;
  * `CheckpointWriter.save()` stays async once the retention window is
    full (finished saves are pruned by completion, not only by wait());
  * the trainer's split metrics: pure train-loop steps_per_sec +
    stall_fraction;
  * `continuous_eval` reports per-checkpoint restore+eval wall time;
  * predictor restore ∥ engine compile-ahead overlap;
  * the `bench.py --coldstart --dry-run` smoke.
"""

import json
import os
import shutil
import subprocess
import sys

import numpy as np
import pytest

import jax

from tensor2robot_tpu import train_eval
from tensor2robot_tpu.data import Mode, RandomInputGenerator
from tensor2robot_tpu.predictors import CheckpointPredictor
from tensor2robot_tpu.serving import BucketedServingEngine
from tensor2robot_tpu.specs import make_random_tensors
from tensor2robot_tpu.startup import compile_cache
from tensor2robot_tpu.startup import orchestrator
from tensor2robot_tpu.utils import checkpoints as ckpt_lib
from tensor2robot_tpu.utils.mocks import MockT2RModel
from tensor2robot_tpu.telemetry.records import read_records

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _subprocess_env():
  env = dict(os.environ)
  env["JAX_PLATFORMS"] = "cpu"
  env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
  return env


class TestCompileCache:

  def test_configure_writes_entries_and_is_idempotent(self, tmp_path):
    cache_dir = str(tmp_path / "cache")
    try:
      resolved = compile_cache.configure_compilation_cache(
          cache_dir=cache_dir)
      assert resolved == os.path.abspath(cache_dir)
      # Second call with the same dir: no-op, same answer.
      assert compile_cache.configure_compilation_cache(
          cache_dir=cache_dir) == resolved
      with compile_cache.CompileWatch() as watch:
        out = jax.jit(lambda x: (x * 3.0).sum() + 1.0)(
            np.ones((33, 33), np.float32))
        out.block_until_ready()
      assert watch.cache_misses >= 1
      assert compile_cache.cache_entry_count(cache_dir) >= 1
    finally:
      compile_cache.reset_compilation_cache_config()

  def test_unconfigured_is_noop(self):
    assert compile_cache.configure_compilation_cache() is None

  def test_persistent_cache_roundtrip_across_processes(self, tmp_path):
    """THE warm-restart contract: the second process with the same
    cache dir compiles 0 programs — every compile request is served
    from the persistent cache."""
    cache_dir = str(tmp_path / "cache")
    code = (
        "import numpy as np\n"
        "import jax, jax.numpy as jnp\n"
        "from tensor2robot_tpu.startup import (CompileWatch,\n"
        "    configure_compilation_cache)\n"
        f"configure_compilation_cache(cache_dir={cache_dir!r})\n"
        "with CompileWatch() as w:\n"
        "  out = jax.jit(lambda x: jnp.sin(x) @ x + 2.0)(\n"
        "      np.ones((48, 48), np.float32))\n"
        "  out.block_until_ready()\n"
        "print('WATCH', w.cache_hits, w.cache_misses)\n")
    results = []
    for _ in range(2):
      out = subprocess.run(
          [sys.executable, "-c", code], env=_subprocess_env(),
          capture_output=True, text=True, timeout=600, check=True)
      line = [l for l in out.stdout.splitlines()
              if l.startswith("WATCH ")][-1]
      hits, misses = map(int, line.split()[1:])
      results.append((hits, misses))
    (first_hits, first_misses), (second_hits, second_misses) = results
    assert first_misses >= 1            # cold: really compiled
    assert second_misses == 0           # warm: zero XLA compilations
    assert second_hits >= first_misses  # every program deserialized


class TestOverlappedStartup:

  def _run(self, model_dir, max_steps, overlap, hidden=(8,)):
    return train_eval.train_eval_model(
        model=MockT2RModel(hidden_sizes=hidden),
        model_dir=model_dir,
        input_generator_train=RandomInputGenerator(batch_size=8, seed=5),
        input_generator_eval=RandomInputGenerator(batch_size=8, seed=6),
        max_train_steps=max_steps,
        eval_steps=2,
        save_checkpoints_steps=3,
        log_every_steps=3,
        overlap_startup=overlap,
    )

  def test_resume_overlap_matches_serial_bitwise(self, tmp_path):
    """Overlapped restore + AOT-compiled step == the serial path,
    bitwise: same checkpoint, same generator stream, same PRNG."""
    base = str(tmp_path / "base")
    self._run(base, max_steps=3, overlap=False)
    fork = str(tmp_path / "fork")
    shutil.copytree(base, fork)
    serial = self._run(base, max_steps=6, overlap=False)
    overlapped = self._run(fork, max_steps=6, overlap=True)
    assert int(np.asarray(jax.device_get(overlapped.step))) == 6
    for (path, a), b in zip(
        jax.tree_util.tree_leaves_with_path(jax.device_get(
            serial.params)),
        jax.tree_util.tree_leaves(jax.device_get(overlapped.params))):
      np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                    err_msg=str(path))

  def test_fresh_start_overlap_matches_serial_bitwise(self, tmp_path):
    serial = self._run(str(tmp_path / "s"), max_steps=6, overlap=False)
    overlapped = self._run(str(tmp_path / "o"), max_steps=6,
                           overlap=True)
    for a, b in zip(
        jax.tree_util.tree_leaves(jax.device_get(serial.params)),
        jax.tree_util.tree_leaves(jax.device_get(overlapped.params))):
      np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

  def test_startup_timings_written(self, tmp_path):
    model_dir = str(tmp_path / "m")
    self._run(model_dir, max_steps=3, overlap=True)
    self._run(model_dir, max_steps=6, overlap=True)  # resume
    with open(os.path.join(model_dir,
                           orchestrator.STARTUP_TIMINGS_FILE)) as f:
      timings = json.load(f)
    assert timings["mode"] == "overlapped"
    # The resume run overlapped all three phases.
    assert set(timings["phase_seconds"]) == {"compile", "restore",
                                             "input"}
    assert timings["total_seconds"] > 0

  def test_run_overlapped_surfaces_errors_after_join(self):
    def ok():
      return 42

    def boom():
      raise RuntimeError("phase failed")

    report = orchestrator.run_overlapped({"a": ok, "b": boom})
    assert report.results["a"] == 42
    assert "b" in report.errors
    with pytest.raises(RuntimeError, match="phase failed"):
      report.raise_first()

  def test_stall_fraction_and_pure_steps_per_sec(self, tmp_path):
    model_dir = str(tmp_path / "m")
    train_eval.train_eval_model(
        model=MockT2RModel(),
        model_dir=model_dir,
        input_generator_train=RandomInputGenerator(batch_size=8, seed=1),
        input_generator_eval=RandomInputGenerator(batch_size=8, seed=2),
        max_train_steps=20,
        eval_steps=2,
        eval_every_steps=10,
        save_checkpoints_steps=10,
        log_every_steps=5,
    )
    records = read_records(
        os.path.join(model_dir, "metrics_train.jsonl"))
    assert len(records) >= 3
    for record in records:
      assert record["steps_per_sec"] > 0
      assert 0.0 <= record["stall_fraction"] <= 1.0
    # Intervals containing a save and an eval must see a nonzero
    # stall; step 15's interval (no save, no eval) only pays the
    # previous metric write.
    stalled = [r["stall_fraction"] for r in records
               if r["step"] in (15, 20)]
    assert any(s > 0 for s in stalled)

  def test_continuous_eval_reports_restore_eval_walltime(self, tmp_path):
    model_dir = str(tmp_path / "m")
    model = MockT2RModel()
    train_eval.train_eval_model(
        model=model,
        model_dir=model_dir,
        input_generator_train=RandomInputGenerator(batch_size=8),
        max_train_steps=10,
        save_checkpoints_steps=5,
    )
    results = train_eval.continuous_eval(
        model=model,
        model_dir=model_dir,
        input_generator_eval=RandomInputGenerator(batch_size=8),
        eval_steps=2,
        timeout_secs=0.5,
        poll_interval_secs=0.1,
        max_evals=5,
    )
    metrics = results[10]
    assert metrics["restore_secs"] > 0
    assert metrics["eval_secs"] > 0
    assert metrics["restore_and_eval_secs"] == pytest.approx(
        metrics["restore_secs"] + metrics["eval_secs"])


class TestCheckpointWriterAsyncGC:

  def _tiny_state(self, value):
    return {"w": np.full((4,), value, np.float32)}

  def _wait_finalized(self, writer, model_dir, step, timeout=30.0):
    import time
    deadline = time.time() + timeout
    path = os.path.join(model_dir, ckpt_lib.CKPT_SUBDIR, str(step),
                        "state")
    while time.time() < deadline:
      if os.path.isdir(path):
        return
      time.sleep(0.01)
    raise AssertionError(f"save {step} never finalized")

  def test_save_does_not_block_after_retention_window_fills(
      self, tmp_path, monkeypatch):
    """THE steady-state contract: once prior saves have finished,
    save() must never fall back to a full synchronous wait() even
    with the retention window full (the pre-fix behavior: every
    GC victim looked 'pending' forever, silently degrading async
    checkpointing to synchronous)."""
    model_dir = str(tmp_path / "m")
    writer = ckpt_lib.CheckpointWriter(model_dir, max_to_keep=2)
    waits = []
    real_wait = writer.wait
    monkeypatch.setattr(
        writer, "wait", lambda: (waits.append(1), real_wait())[1])
    try:
      for i, step in enumerate((1, 2, 3, 4, 5)):
        # Steady state: the PREVIOUS save has long finished when the
        # next one arrives (poll its atomic-rename finalization).
        writer.save(step, self._tiny_state(i))
        self._wait_finalized(writer, model_dir, step)
      assert not waits, (
          "save() blocked on a full wait() despite every prior save "
          "having finished")
      # Retention still enforced.
      assert ckpt_lib.list_steps(model_dir) == [4, 5]
    finally:
      monkeypatch.setattr(writer, "wait", real_wait)
      writer.close()

  def test_inflight_victim_still_waits(self, tmp_path):
    """The pathological case (max_to_keep < save cadence) keeps its
    correctness blocking: a victim genuinely in flight forces a
    wait, never a delete-under-write."""
    model_dir = str(tmp_path / "m")
    writer = ckpt_lib.CheckpointWriter(model_dir, max_to_keep=1)
    try:
      for step in (1, 2, 3):
        writer.save(step, self._tiny_state(step))
      writer.wait()
      assert ckpt_lib.list_steps(model_dir) == [3]
    finally:
      writer.close()


class TestPredictorOverlap:

  def _seed_checkpoint(self, model, ckpt_dir):
    state = model.create_inference_state(jax.random.PRNGKey(0))
    writer = ckpt_lib.CheckpointWriter(ckpt_dir, max_to_keep=None)
    writer.save(1, state)
    writer.close()

  def test_restore_overlaps_compile_ahead(self, tmp_path):
    model = MockT2RModel()
    ckpt_dir = str(tmp_path / "ckpt")
    self._seed_checkpoint(model, ckpt_dir)
    predictor = CheckpointPredictor(
        model, checkpoint_dir=ckpt_dir, max_batch=4,
        warmup=True, overlap_startup=True)
    try:
      assert predictor.restore(timeout_secs=0)
      # After restore() the compile-ahead has been joined: every
      # bucket is a finished executable.
      assert predictor.serving_engine.compiled_buckets == (1, 2, 4)
      assert predictor.warmup_seconds > 0
      spec = predictor.feature_specification
      batch = make_random_tensors(spec, batch_size=3, seed=0)
      out = predictor.predict(
          {k: np.asarray(v) for k, v in batch.to_flat_dict().items()})
      values = np.asarray(list(out.values())[0])
      assert values.shape[0] == 3
      assert np.isfinite(values).all()
    finally:
      predictor.close()

  def test_engine_warmup_async_idempotent_and_race_safe(self):
    model = MockT2RModel()
    state = model.create_inference_state(jax.random.PRNGKey(0))
    spec = model.preprocessor.get_in_feature_specification(Mode.PREDICT)
    from tensor2robot_tpu import specs as specs_lib
    example = make_random_tensors(
        specs_lib.flatten_spec_structure(spec), batch_size=1, seed=0)
    engine = BucketedServingEngine(model.predict_step, state, example,
                                   max_batch=4)
    thread = engine.warmup_async()
    assert engine.warmup_async() is thread  # idempotent
    # A request racing the warmup thread is serialized by the compile
    # lock and must return a correct result immediately.
    out = engine.predict(example)
    assert np.isfinite(
        np.asarray(jax.tree_util.tree_leaves(out)[0])).all()
    engine.wait_warmup()
    assert engine.compiled_buckets == (1, 2, 4)


@pytest.mark.slow
class TestColdstartBenchSmoke:

  def test_coldstart_dry_run(self):
    """The tier-1 smoke: setup/cold/warm tiny trainer probes through
    bench.py, warm run provably compile-free."""
    out = subprocess.run(
        [sys.executable, "bench.py", "--coldstart", "--dry-run"],
        env=_subprocess_env(), capture_output=True, text=True,
        timeout=1200, cwd=REPO_ROOT)
    assert out.returncode == 0, out.stdout[-2000:] + out.stderr[-2000:]
    smoke = json.loads(out.stdout.strip().splitlines()[-1])
    assert smoke["coldstart_dry_run"] == "ok"
    assert smoke["cold_cache_misses"] > 0
    assert smoke["warm_cache_misses"] == 0
    assert smoke["warm_zero_xla_compilations"] is True
