"""Tests for the spec system (parity with utils/tensorspec_utils_test.py [U])."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensor2robot_tpu import specs
from tensor2robot_tpu.specs import ExtendedTensorSpec, TensorSpecStruct


class TestExtendedTensorSpec:

  def test_basic_construction(self):
    s = ExtendedTensorSpec(shape=(64, 64, 3), dtype=np.uint8, name="img")
    assert s.shape == (64, 64, 3)
    assert s.dtype == np.dtype(np.uint8)
    assert s.name == "img"
    assert not s.is_optional and not s.is_sequence and not s.varlen

  def test_bfloat16(self):
    s = ExtendedTensorSpec(shape=(8,), dtype="bfloat16")
    assert s.dtype == jnp.bfloat16.dtype
    sds = s.to_shape_dtype_struct(batch_size=4)
    assert sds.shape == (4, 8)
    assert sds.dtype == jnp.bfloat16

  def test_rejects_undefined_shape(self):
    with pytest.raises(ValueError):
      ExtendedTensorSpec(shape=(-1, 3), dtype=np.float32)

  def test_rejects_bad_data_format(self):
    with pytest.raises(ValueError):
      ExtendedTensorSpec(shape=(2,), dtype=np.uint8, data_format="bmp")

  def test_from_spec_overrides(self):
    s = ExtendedTensorSpec(shape=(3,), dtype=np.float32, name="a")
    t = ExtendedTensorSpec.from_spec(s, name="b", is_optional=True)
    assert t.shape == s.shape and t.dtype == s.dtype
    assert t.name == "b" and t.is_optional

  def test_from_array(self):
    arr = np.zeros((5, 2), np.int32)
    s = ExtendedTensorSpec.from_array(arr, name="x")
    assert s.shape == (5, 2) and s.dtype == np.dtype(np.int32)

  def test_sequence_shape_dtype_struct(self):
    s = ExtendedTensorSpec(shape=(7,), dtype=np.float32, is_sequence=True)
    sds = s.to_shape_dtype_struct(batch_size=2, sequence_length=5)
    assert sds.shape == (2, 5, 7)

  def test_hashable_and_frozen(self):
    s = ExtendedTensorSpec(shape=(3,), dtype=np.float32)
    assert hash(s) == hash(ExtendedTensorSpec(shape=(3,), dtype=np.float32))
    with pytest.raises(Exception):
      s.shape = (4,)  # frozen dataclass


class TestTensorSpecStruct:

  def make(self):
    st = TensorSpecStruct()
    st.image = ExtendedTensorSpec(shape=(32, 32, 3), dtype=np.uint8,
                                  name="image", data_format="jpeg")
    st.pose = ExtendedTensorSpec(shape=(6,), dtype=np.float32, name="pose")
    return st

  def test_attribute_and_item_access(self):
    st = self.make()
    assert st.image is st["image"]
    assert list(st.keys()) == ["image", "pose"]

  def test_nested_path_access(self):
    st = TensorSpecStruct()
    st["a/b/c"] = ExtendedTensorSpec(shape=(1,), dtype=np.float32)
    sub = st.a
    assert isinstance(sub, TensorSpecStruct)
    assert "b/c" in sub.to_flat_dict()
    assert st["a/b"]["c"].shape == (1,)

  def test_nested_assignment_of_struct(self):
    st = TensorSpecStruct()
    inner = TensorSpecStruct()
    inner.x = ExtendedTensorSpec(shape=(2,), dtype=np.float32)
    st.sub = inner
    assert st["sub/x"].shape == (2,)
    assert isinstance(st.sub, TensorSpecStruct)

  def test_dict_init_nested(self):
    st = TensorSpecStruct({
        "obs": {"img": ExtendedTensorSpec(shape=(4,), dtype=np.float32)},
        "act": ExtendedTensorSpec(shape=(2,), dtype=np.float32),
    })
    assert st["obs/img"].shape == (4,)
    assert st.act.shape == (2,)

  def test_insertion_order_preserved(self):
    st = TensorSpecStruct()
    for name in ["z", "a", "m"]:
      st[name] = ExtendedTensorSpec(shape=(1,), dtype=np.float32)
    assert st.keys() == ["z", "a", "m"]

  def test_delete(self):
    st = self.make()
    del st.image
    assert "image" not in st
    with pytest.raises(AttributeError):
      _ = st.image

  def test_leaf_overwrites_subtree(self):
    st = TensorSpecStruct()
    st["a/b"] = ExtendedTensorSpec(shape=(1,), dtype=np.float32)
    st["a"] = ExtendedTensorSpec(shape=(2,), dtype=np.float32)
    assert st.a.shape == (2,)
    assert "a/b" not in st

  def test_pytree_roundtrip(self):
    st = self.make()
    leaves, treedef = jax.tree_util.tree_flatten(st)
    assert len(leaves) == 2
    back = jax.tree_util.tree_unflatten(treedef, leaves)
    assert back == st

  def test_jit_through_struct(self):
    # A TensorSpecStruct of arrays can pass through jit directly.
    batch = TensorSpecStruct()
    batch.x = jnp.ones((4, 3))
    batch.y = jnp.ones((4,))

    @jax.jit
    def f(b):
      out = TensorSpecStruct()
      out.z = b.x.sum(axis=-1) + b.y
      return out

    out = f(batch)
    assert out.z.shape == (4,)
    np.testing.assert_allclose(np.asarray(out.z), 4.0 * np.ones((4,)))

  def test_equality_with_mapping(self):
    st = TensorSpecStruct()
    st.x = 1
    assert st == {"x": 1}


class TestPacking:

  def specs2(self):
    st = TensorSpecStruct()
    st.image = ExtendedTensorSpec(shape=(8, 8, 3), dtype=np.float32,
                                  name="image")
    st.action = ExtendedTensorSpec(shape=(4,), dtype=np.float32,
                                   name="action")
    st.aux = ExtendedTensorSpec(shape=(2,), dtype=np.float32, name="aux",
                                is_optional=True)
    return st

  def test_flatten_nested_mixture(self):
    flat = specs.flatten_spec_structure({
        "a": [ExtendedTensorSpec(shape=(1,), dtype=np.float32),
              ExtendedTensorSpec(shape=(2,), dtype=np.float32)],
        "b": {"c": ExtendedTensorSpec(shape=(3,), dtype=np.float32)},
    })
    assert set(flat.to_flat_dict()) == {"a/0", "a/1", "b/c"}

  def test_validate_and_pack_ok(self):
    st = self.specs2()
    data = {
        "image": np.zeros((2, 8, 8, 3), np.float32),
        "action": np.zeros((2, 4), np.float32),
    }
    packed = specs.validate_and_pack(st, data, ignore_batch=True)
    assert set(packed.keys()) == {"image", "action"}

  def test_optional_present_is_kept(self):
    st = self.specs2()
    data = {
        "image": np.zeros((2, 8, 8, 3), np.float32),
        "action": np.zeros((2, 4), np.float32),
        "aux": np.zeros((2, 2), np.float32),
    }
    packed = specs.validate_and_pack(st, data)
    assert "aux" in packed

  def test_missing_required_raises(self):
    st = self.specs2()
    with pytest.raises(specs.SpecValidationError, match="action"):
      specs.validate_and_pack(st, {
          "image": np.zeros((2, 8, 8, 3), np.float32)})

  def test_shape_mismatch_raises(self):
    st = self.specs2()
    with pytest.raises(specs.SpecValidationError, match="shape"):
      specs.validate_and_pack(st, {
          "image": np.zeros((2, 8, 8, 3), np.float32),
          "action": np.zeros((2, 5), np.float32)})

  def test_dtype_mismatch_raises(self):
    st = self.specs2()
    with pytest.raises(specs.SpecValidationError, match="dtype"):
      specs.validate_and_pack(st, {
          "image": np.zeros((2, 8, 8, 3), np.float32),
          "action": np.zeros((2, 4), np.int32)})

  def test_extra_tensors_dropped(self):
    st = self.specs2()
    packed = specs.validate_and_pack(st, {
        "image": np.zeros((2, 8, 8, 3), np.float32),
        "action": np.zeros((2, 4), np.float32),
        "junk": np.zeros((2, 1), np.float32)})
    assert "junk" not in packed

  def test_filter_required(self):
    st = self.specs2()
    req = specs.filter_required_flat_tensor_spec_structure(st)
    assert set(req.to_flat_dict()) == {"image", "action"}

  def test_pack_flat_sequence(self):
    st = self.specs2()
    leaves = [np.zeros(s.shape, s.dtype)
              for s in specs.flatten_spec_structure(st).values()]
    packed = specs.pack_flat_sequence_to_spec_structure(st, leaves)
    assert packed.keys() == ["image", "action", "aux"]

  def test_replace_dtype(self):
    st = self.specs2()
    out = specs.replace_dtype(st, np.float32, jnp.bfloat16)
    assert out["image"].dtype == jnp.bfloat16.dtype

  def test_sequence_validation(self):
    st = TensorSpecStruct()
    st.obs = ExtendedTensorSpec(shape=(3,), dtype=np.float32,
                                is_sequence=True)
    ok = np.zeros((2, 5, 3), np.float32)  # batch, time, features
    specs.validate_and_pack(st, {"obs": ok})
    with pytest.raises(specs.SpecValidationError):
      specs.validate_and_pack(st, {"obs": np.zeros((2, 3), np.float32)})

  def test_add_sequence_length(self):
    st = TensorSpecStruct()
    st.obs = ExtendedTensorSpec(shape=(3,), dtype=np.float32,
                                is_sequence=True)
    out = specs.add_sequence_length(st, 5)
    assert out.obs.shape == (5, 3) and not out.obs.is_sequence


class TestSerialization:

  def test_roundtrip(self):
    st = TensorSpecStruct()
    st.image = ExtendedTensorSpec(shape=(16, 16, 3), dtype=np.uint8,
                                  name="image", data_format="jpeg")
    st["nested/pose"] = ExtendedTensorSpec(
        shape=(6,), dtype="bfloat16", is_optional=True, varlen=False)
    labels = TensorSpecStruct()
    labels.target = ExtendedTensorSpec(shape=(2,), dtype=np.float32,
                                       is_sequence=True)
    ser = specs.serialize_assets(st, label_spec=labels, global_step=42)
    out = specs.deserialize_assets(ser)
    assert out["feature_spec"]["image"] == st.image
    assert out["feature_spec"]["nested/pose"] == st["nested/pose"]
    assert out["label_spec"]["target"] == labels.target
    assert out["global_step"] == 42

  def test_file_roundtrip(self, tmp_path):
    st = TensorSpecStruct()
    st.x = ExtendedTensorSpec(shape=(3,), dtype=np.float32, name="x")
    path = str(tmp_path / "t2r_assets.json")
    specs.write_assets(path, st)
    out = specs.read_assets(path)
    assert out["feature_spec"]["x"] == st.x


class TestRandomData:

  def test_conforms_to_specs(self):
    st = TensorSpecStruct()
    st.image = ExtendedTensorSpec(shape=(8, 8, 3), dtype=np.uint8,
                                  name="image")
    st.pose = ExtendedTensorSpec(shape=(6,), dtype=np.float32)
    st.idx = ExtendedTensorSpec(shape=(1,), dtype=np.int64)
    batch = specs.make_random_tensors(st, batch_size=4, seed=1)
    packed = specs.validate_and_pack(st, batch)
    assert packed["image"].shape == (4, 8, 8, 3)
    assert packed["pose"].dtype == np.float32

  def test_deterministic(self):
    st = TensorSpecStruct()
    st.x = ExtendedTensorSpec(shape=(5,), dtype=np.float32)
    a = specs.make_random_tensors(st, batch_size=2, seed=7)
    b = specs.make_random_tensors(st, batch_size=2, seed=7)
    np.testing.assert_array_equal(a["x"], b["x"])

  def test_sequence_and_optional(self):
    st = TensorSpecStruct()
    st.obs = ExtendedTensorSpec(shape=(3,), dtype=np.float32,
                                is_sequence=True)
    st.extra = ExtendedTensorSpec(shape=(1,), dtype=np.float32,
                                  is_optional=True)
    batch = specs.make_random_tensors(
        st, batch_size=2, sequence_length=6, include_optional=False)
    assert batch["obs"].shape == (2, 6, 3)
    assert "extra" not in batch

  def test_bfloat16_generation(self):
    st = TensorSpecStruct()
    st.x = ExtendedTensorSpec(shape=(4,), dtype="bfloat16")
    batch = specs.make_random_tensors(st, batch_size=2)
    assert batch["x"].dtype == jnp.bfloat16.dtype
