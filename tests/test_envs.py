"""Tests for the on-device vectorized env subsystem (ISSUE 9).

Pins the functional-env contract (docs/ENVS.md): host-vs-device pose
parity on matched geometry, auto-reset semantics at episode
boundaries, same-key scenario determinism (the JaxARC property), the
rollout engine's replay-wire-spec output, the jit-once guarantee (no
retrace across iterations), and the --trainer=anakin e2e loop.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensor2robot_tpu.telemetry.records import read_records
from tensor2robot_tpu.envs import (
    AutoResetEnv,
    BatchedEnv,
    JaxEnvBandit,
    PoseBanditEnv,
    ProcGenGraspEnv,
    evaluate_scenarios,
    host_parity_env,
    make_anakin_collect_fn,
    make_batched,
    make_collect_fn,
    train_anakin,
)
from tensor2robot_tpu.envs.rollout import flatten_time, rollout
from tensor2robot_tpu.research.qtopt import (
    GraspingQModel,
    QTOptLearner,
)

RNG = jax.random.PRNGKey(0)


def _tiny_learner(image_size=16, **learner_kwargs):
  model = GraspingQModel(image_size=image_size, torso_filters=(8,),
                         head_filters=(8,), dense_sizes=(16,),
                         action_dim=2)
  learner_kwargs.setdefault("cem_population", 8)
  learner_kwargs.setdefault("cem_iterations", 1)
  learner_kwargs.setdefault("cem_elites", 2)
  return QTOptLearner(model, **learner_kwargs)


class TestHostDeviceParity:
  """The pose env mirrors `PoseGraspBandit` on matched geometry."""

  def test_reward_parity_on_matched_geometry(self):
    from tensor2robot_tpu.research.pose_env.grasp_bandit import (
        PoseGraspBandit,
    )

    host = PoseGraspBandit(image_size=16, physics=False, seed=3)
    device = host_parity_env(host)
    _, poses = host.reset_batch(64)
    actions = np.random.default_rng(0).uniform(
        -1, 1, (64, 2)).astype(np.float32)
    host_rewards = host.grade(actions, poses)
    device_rewards = np.asarray(jax.device_get(jax.vmap(
        device.grasp_reward)(jnp.asarray(actions),
                             jnp.asarray(poses))))
    # Same float32 math on both sides; a mixed batch (some successes)
    # proves the comparison isn't vacuous.
    np.testing.assert_array_equal(host_rewards, device_rewards)
    assert 0.0 < host_rewards.mean() < 1.0 or host_rewards.mean() == 0.0

  def test_step_reward_equals_host_grade(self):
    from tensor2robot_tpu.research.pose_env.grasp_bandit import (
        grade_grasp,
    )

    env = PoseBanditEnv(image_size=16)
    state = env.reset(RNG)
    action = jnp.asarray([0.3, -0.2])
    _, _, reward, done = env.step(state, action, RNG)
    expected = grade_grasp(np.asarray(action)[None],
                           np.asarray(state.pose)[None],
                           threshold=0.1)[0]
    assert float(reward) == float(expected)
    assert bool(done)  # single-step bandit

  def test_noiseless_frames_bitwise_equal(self):
    from tensor2robot_tpu.research.pose_env.pose_env import PoseEnv

    host = PoseEnv(image_size=16, seed=5, noise=0.0)
    host_obs = host.reset()
    device = PoseBanditEnv(image_size=16, noise=0.0)
    device_obs = device.observe(
        device.state_at(host.pose, jax.random.PRNGKey(0)))
    np.testing.assert_array_equal(np.asarray(device_obs["image"]),
                                  host_obs["image"])


class TestAutoReset:

  def test_resets_at_step_limit(self):
    env = PoseBanditEnv(image_size=8, max_episode_steps=3)
    wrapped = AutoResetEnv(env)
    state = wrapped.reset(RNG)
    pose0 = np.asarray(state.pose)
    miss = jnp.asarray([1.0, 1.0])  # corner: never within threshold
    key = jax.random.PRNGKey(1)
    for t in range(2):
      state, _, reward, done = wrapped.step(
          state, miss, jax.random.fold_in(key, t))
      assert not bool(done) and float(reward) == 0.0
      # Mid-episode: same block, advancing clock.
      np.testing.assert_array_equal(np.asarray(state.pose), pose0)
      assert int(state.t) == t + 1
    state, obs, reward, done = wrapped.step(
        state, miss, jax.random.fold_in(key, 2))
    assert bool(done)
    # The returned state is a FRESH episode: clock zeroed, new block.
    assert int(state.t) == 0
    assert not np.array_equal(np.asarray(state.pose), pose0)

  def test_terminal_obs_is_old_episode(self):
    env = PoseBanditEnv(image_size=8, noise=0.0, max_episode_steps=1)
    wrapped = AutoResetEnv(env)
    state = wrapped.reset(RNG)
    pose0 = np.asarray(state.pose)
    new_state, obs, _, done = wrapped.step(
        state, jnp.asarray([1.0, 1.0]), jax.random.PRNGKey(1))
    assert bool(done)
    old_frame = env.observe(
        env.state_at(pose0, jax.random.PRNGKey(9)))["image"]
    np.testing.assert_array_equal(np.asarray(obs["image"]),
                                  np.asarray(old_frame))
    fresh_frame = wrapped.observe(new_state)["image"]
    assert not np.array_equal(np.asarray(fresh_frame),
                              np.asarray(old_frame))

  def test_success_ends_episode(self):
    env = PoseBanditEnv(image_size=8, max_episode_steps=5)
    state = env.reset(RNG)
    hit = state.pose / jnp.asarray(0.4)  # exact grasp, normalized
    _, _, reward, done = env.step(state, hit, RNG)
    assert float(reward) == 1.0 and bool(done)


class TestScenarioDeterminism:
  """JaxARC property: the key IS the scenario."""

  def test_same_key_same_scenario(self):
    env = ProcGenGraspEnv(image_size=16)
    a = env.reset(jax.random.PRNGKey(7))
    b = env.reset(jax.random.PRNGKey(7))
    for leaf_a, leaf_b in zip(jax.tree_util.tree_leaves(a),
                              jax.tree_util.tree_leaves(b)):
      np.testing.assert_array_equal(np.asarray(leaf_a),
                                    np.asarray(leaf_b))
    np.testing.assert_array_equal(
        np.asarray(env.observe(a)["image"]),
        np.asarray(env.observe(b)["image"]))

  def test_different_keys_differ(self):
    env = ProcGenGraspEnv(image_size=16)
    a = env.reset(jax.random.PRNGKey(7))
    b = env.reset(jax.random.PRNGKey(8))
    assert not np.array_equal(np.asarray(a.pose), np.asarray(b.pose))

  def test_scenario_diversity_and_buckets(self):
    env = ProcGenGraspEnv(image_size=16, max_distractors=3)
    states = jax.vmap(env.reset)(jax.random.split(RNG, 128))
    buckets = np.asarray(jax.vmap(env.scenario_bucket)(states))
    # All four buckets appear and geometry actually varies.
    assert set(buckets.tolist()) == {0, 1, 2, 3}
    assert np.asarray(states.half_extent).std() > 0
    assert np.asarray(states.workspace).std() > 0

  def test_sweep_digests_reproduce(self):
    learner = _tiny_learner()
    state = learner.create_state(RNG)
    env = ProcGenGraspEnv(image_size=16, action_dim=2)
    a = evaluate_scenarios(learner, state, env=env,
                           num_scenarios=32, seed=3)
    b = evaluate_scenarios(learner, state, env=env,
                           num_scenarios=32, seed=3)
    c = evaluate_scenarios(learner, state, env=env,
                           num_scenarios=32, seed=4)
    assert a["action_digest"] == b["action_digest"]
    assert a["scenario_digest"] == b["scenario_digest"]
    assert a["scenario_digest"] != c["scenario_digest"]
    assert sum(row["count"] for row in a["per_bucket"].values()) == 32


class TestRolloutEngine:

  def test_batch_matches_replay_wire_spec(self):
    learner = _tiny_learner()
    env = PoseBanditEnv(image_size=16, action_dim=2)
    init_fn, collect_fn = make_collect_fn(
        learner, env, num_envs=4, rollout_length=3, epsilon=0.5)
    states = jax.jit(init_fn)(RNG)
    state = learner.create_state(RNG)
    _, batch = jax.jit(collect_fn)(state, states,
                                   jax.random.PRNGKey(2))
    spec = learner.transition_specification().to_flat_dict()
    assert set(batch) == set(spec)
    for key, sp in spec.items():
      assert batch[key].shape == (12,) + tuple(sp.shape), key
      assert batch[key].dtype == sp.dtype, key
    # Wire batches feed the replay plane unchanged.
    from tensor2robot_tpu.research.qtopt import ReplayBuffer
    replay = ReplayBuffer(learner.transition_specification(),
                          capacity=64)
    replay.add({k: np.asarray(v) for k, v in batch.items()})
    assert len(replay) == 12

  def test_per_env_keys_are_independent(self):
    env = PoseBanditEnv(image_size=8)
    batched = BatchedEnv(env, 16)
    states = batched.reset(RNG)
    poses = np.asarray(states.pose)
    assert np.unique(poses, axis=0).shape[0] == 16

  def test_jit_once_across_iterations(self):
    learner = _tiny_learner()
    env = PoseBanditEnv(image_size=16, action_dim=2)
    init_fn, collect_fn = make_collect_fn(
        learner, env, num_envs=4, rollout_length=2)
    traces = {"count": 0}

    def counted(learner_state, env_states, key):
      traces["count"] += 1
      return collect_fn(learner_state, env_states, key)

    collect = jax.jit(counted)
    state = learner.create_state(RNG)
    env_states = jax.jit(init_fn)(RNG)
    for t in range(4):
      env_states, batch = collect(state, env_states,
                                  jax.random.fold_in(RNG, t))
    float(batch["reward"].sum())
    assert traces["count"] == 1  # one trace, many dispatches

  def test_done_rows_present_and_rewards_graded(self):
    env = PoseBanditEnv(image_size=8)  # single-step: every row done
    batched = make_batched(env, 8)

    def random_policy(obs, key):
      del obs
      return jax.random.uniform(key, (8, 2), minval=-1.0, maxval=1.0)

    states = batched.reset(RNG)
    _, traj = jax.jit(
        lambda st, key: rollout(batched, random_policy, st, key, 4))(
            states, jax.random.PRNGKey(3))
    flat = flatten_time(traj)
    np.testing.assert_array_equal(np.asarray(flat["done"]),
                                  np.ones((32, 1), np.float32))
    rewards = np.asarray(flat["reward"])
    assert set(np.unique(rewards)).issubset({0.0, 1.0})

  def test_anakin_scaleout_matches_wire(self):
    learner = _tiny_learner()
    env = PoseBanditEnv(image_size=16, action_dim=2)
    devices = jax.local_devices()[:2]
    init_fn, collect_fn = make_anakin_collect_fn(
        learner, env, num_envs=4, rollout_length=2, devices=devices)
    state = learner.create_state(RNG)
    env_states = init_fn(RNG)
    _, batch = collect_fn(state, env_states, jax.random.PRNGKey(2))
    from tensor2robot_tpu.envs import flatten_devices
    flat = flatten_devices(batch)
    assert flat["image"].shape == (8, 16, 16, 3)
    assert flat["action"].shape == (8, 2)


class TestJaxEnvBandit:
  """The host adapter: functional envs as GraspActor scenario sources."""

  def test_bandit_interface(self):
    bandit = JaxEnvBandit(env=ProcGenGraspEnv(image_size=16), seed=0)
    obs, poses = bandit.reset_batch(8)
    assert obs["image"].shape == (8, 16, 16, 3)
    assert obs["image"].dtype == np.uint8
    assert poses.shape == (8, 2)
    assert bandit.last_buckets is not None
    rewards = bandit.grade(
        np.zeros((8, 2), np.float32), poses)
    assert rewards.shape == (8,)
    transitions = bandit.sample_transitions(8)
    assert set(transitions) == {"image", "action", "reward", "done",
                                "next_image"}

  def test_grasp_actor_collects_through_bandit(self):
    from tensor2robot_tpu.research.qtopt import (
        GraspActor,
        ReplayBuffer,
    )

    learner = _tiny_learner()
    replay = ReplayBuffer(learner.transition_specification(),
                          capacity=128)
    actor = GraspActor(
        learner, replay,
        env=JaxEnvBandit(env=ProcGenGraspEnv(image_size=16), seed=1),
        batch_episodes=8, epsilon=0.5, seed=2)
    actor.collect_once()  # bootstrap (random policy)
    actor.update_state(learner.create_state(RNG))
    actor.collect_once()  # CEM policy through the adapter
    assert len(replay) == 16
    assert actor.episodes_collected == 16


class TestTrainAnakin:

  def test_e2e_smoke(self, tmp_path):
    learner = _tiny_learner()
    state = train_anakin(
        learner=learner,
        model_dir=str(tmp_path),
        env_family="pose",
        num_envs=16,
        rollout_length=2,
        train_batches_per_iter=4,
        batch_size=16,
        replay_capacity=128,
        max_train_steps=16,
        log_every_steps=8,
        save_checkpoints_steps=16,
        seed=0)
    assert int(state.step) == 16
    rows = read_records(str(tmp_path / "metrics_train.jsonl"))
    assert rows, "no train metrics written"
    for row in rows:
      # Zero by construction: acting and training params are the same
      # arrays inside one program.
      assert row["param_refresh_lag_steps"] == 0.0
      assert 0.0 <= row["replay_fill"] <= 1.0
      assert row["env_steps_per_sec"] > 0
    from tensor2robot_tpu.utils import checkpoints as ckpt_lib
    assert ckpt_lib.latest_step(str(tmp_path)) == 16

  def test_cadence_must_divide(self, tmp_path):
    learner = _tiny_learner()
    with pytest.raises(ValueError):
      train_anakin(learner=learner, model_dir=str(tmp_path),
                   num_envs=4, rollout_length=1,
                   train_batches_per_iter=4, batch_size=4,
                   max_train_steps=10,  # not a multiple of 4
                   log_every_steps=4, save_checkpoints_steps=4)

  def test_rejects_extra_state_features(self, tmp_path):
    model = GraspingQModel(image_size=16, torso_filters=(8,),
                           head_filters=(8,), dense_sizes=(16,),
                           action_dim=2,
                           extra_state_features={"gripper": (1,)})
    learner = QTOptLearner(model, cem_population=4,
                           cem_iterations=1, cem_elites=2)
    with pytest.raises(ValueError, match="extra keys"):
      train_anakin(learner=learner, model_dir=str(tmp_path),
                   num_envs=4, rollout_length=1,
                   train_batches_per_iter=1, batch_size=4,
                   max_train_steps=1, log_every_steps=1,
                   save_checkpoints_steps=1)

class TestPodAnakin:
  """Pod mode (ISSUE 10): the ENTIRE collect-and-learn iteration as
  one pmap'd SPMD program — per-device env shards and replay rings,
  per-device Bellman batches, gradients pmean'd over the device axis
  before the replicated Adam+Polyak update."""

  POD_KWARGS = dict(
      env_family="pose", num_envs=16, rollout_length=2,
      train_batches_per_iter=4, batch_size=16, replay_capacity=128,
      max_train_steps=16, log_every_steps=8,
      save_checkpoints_steps=16, seed=0)

  def test_pod_smoke_metrics_and_exact_resume(self, tmp_path):
    learner = _tiny_learner()
    state = train_anakin(learner=learner, model_dir=str(tmp_path),
                         num_devices=2, **self.POD_KWARGS)
    # Returned state is the unreplicated device-0 replica.
    assert int(state.step) == 16
    rows = read_records(str(tmp_path / "metrics_train.jsonl"))
    assert rows
    for row in rows:
      # Zero by construction at ANY device count: acting params ARE
      # the training params inside the one pmap'd program.
      assert row["param_refresh_lag_steps"] == 0.0
      assert row["devices"] == 2
      assert row["global_batch_size"] == 32
      # Bellman throughput counts one per-device batch per step.
      assert row["bellman_batches_per_sec"] == pytest.approx(
          2 * row["grad_steps_per_sec"])
      assert 0.0 <= row["replay_fill"] <= 1.0
    # (The cross-device param-checksum agreement asserted at every log
    # boundary inside the loop did not fire — replicas stayed equal.)
    from tensor2robot_tpu.utils import checkpoints as ckpt_lib
    assert ckpt_lib.latest_step(str(tmp_path)) == 16
    # Resume restores the learner exactly: a second call at the same
    # max step trains zero iterations and returns the checkpoint.
    resumed = train_anakin(learner=learner, model_dir=str(tmp_path),
                           num_devices=2, **self.POD_KWARGS)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(jax.device_get(a)),
            np.asarray(jax.device_get(b))),
        state.train_state.params, resumed.train_state.params)

  def test_pmean_parity_and_replication_invariant(self):
    """Statistical pin of the pmean'd update: a 2-device pmap step
    over two half batches equals the explicitly-averaged per-half
    gradients applied once (the DEFINITION of the pmean'd update —
    per-device batch-norm and loss semantics included), and the
    per-device results are bitwise IDENTICAL across the axis (the
    replication invariant pmean exists to preserve)."""
    import optax
    from tensor2robot_tpu.data.abstract_input_generator import Mode
    from tensor2robot_tpu.models import optimizers as opt_lib
    from tensor2robot_tpu.specs import (
        TensorSpecStruct,
        make_random_tensors,
    )

    # SGD, not Adam: the parity bound must survive the optimizer.
    # Adam's first step is ~sign(g)·lr, which flips on near-zero
    # gradients under any last-ulp noise; SGD keeps the update linear
    # in the pmean'd gradient so the tolerance is meaningful.
    model = GraspingQModel(
        image_size=16, torso_filters=(8,), head_filters=(8,),
        dense_sizes=(16,), action_dim=2,
        create_optimizer_fn=lambda: opt_lib.create_optimizer(
            optimizer_name="sgd", learning_rate=0.1))
    state = model.create_train_state(jax.random.PRNGKey(0),
                                     batch_size=2)
    feats = make_random_tensors(
        model.get_feature_specification(Mode.TRAIN), batch_size=32,
        seed=1)
    feats = {k: jnp.asarray(v) for k, v in feats.items()}
    labels = {"target_q": jax.random.uniform(jax.random.PRNGKey(2),
                                             (32, 1))}
    rng = jax.random.PRNGKey(3)
    struct = TensorSpecStruct.from_flat_dict

    devices = jax.local_devices()[:2]
    split = lambda t: jax.tree_util.tree_map(  # noqa: E731
        lambda x: x.reshape((2, 16) + x.shape[1:]), t)
    pod_step = jax.pmap(
        lambda s, f, l, r: model.train_step(
            s, struct(f), struct(l), r, axis_name="pod"),
        axis_name="pod", devices=devices, in_axes=(0, 0, 0, None))
    got, got_metrics = pod_step(
        jax.device_put_replicated(state, devices), split(feats),
        split(labels), rng)

    # Replication invariant: both replicas hold bitwise-equal params.
    for leaf in jax.tree_util.tree_leaves(
        jax.device_get(got.params)):
      np.testing.assert_array_equal(np.asarray(leaf)[0],
                                    np.asarray(leaf)[1])

    # Reference: per-half gradients (same per-device BN/loss
    # semantics), explicitly averaged, applied once.
    def half(f, l):
      grad_fn = jax.value_and_grad(model.loss_fn, has_aux=True)
      (loss, (_, stats)), grads = grad_fn(
          state.params, state.batch_stats, struct(f), struct(l),
          rng, Mode.TRAIN)
      return loss, stats, grads
    half = jax.jit(half)
    halves = [jax.tree_util.tree_map(lambda x, i=i: x[i * 16:
                                                      (i + 1) * 16],
                                     t)
              for t in (feats, labels) for i in (0, 1)]
    l0, s0, g0 = half(halves[0], halves[2])
    l1, s1, g1 = half(halves[1], halves[3])
    mean2 = lambda a, b: jax.tree_util.tree_map(  # noqa: E731
        lambda x, y: (x + y) / 2, a, b)

    @jax.jit
    def apply(grads):
      updates, _ = model.tx.update(grads, state.opt_state,
                                   state.params)
      return optax.apply_updates(state.params, updates)

    ref_params = apply(mean2(g0, g1))
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(jax.device_get(a)),
            np.asarray(jax.device_get(b))[0], rtol=1e-4, atol=1e-5),
        ref_params, got.params)
    # Cross-replica batch stats: pmean of the per-half BN statistics.
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(jax.device_get(a)),
            np.asarray(jax.device_get(b))[0], rtol=1e-4, atol=1e-5),
        mean2(s0, s1), got.batch_stats)
    # Metrics are pmean'd: device-0 reports the global mean loss.
    np.testing.assert_allclose(float(got_metrics["loss"][0]),
                               (float(l0) + float(l1)) / 2,
                               rtol=1e-4, atol=1e-5)

  def test_pod_validates_devices_and_divisibility(self, tmp_path):
    learner = _tiny_learner()
    with pytest.raises(ValueError, match="divide"):
      train_anakin(learner=learner, model_dir=str(tmp_path),
                   num_envs=6, rollout_length=1, num_devices=4,
                   train_batches_per_iter=1, batch_size=4,
                   max_train_steps=1, log_every_steps=1,
                   save_checkpoints_steps=1)
    with pytest.raises(ValueError, match="devices are visible"):
      train_anakin(learner=learner, model_dir=str(tmp_path),
                   num_envs=64, rollout_length=1, num_devices=64,
                   train_batches_per_iter=1, batch_size=4,
                   max_train_steps=1, log_every_steps=1,
                   save_checkpoints_steps=1)

  def test_pod_ignores_shard_weight_update_with_warning(
      self, tmp_path, caplog):
    """pmap replicas are single-device programs: the GSPMD constraint
    has no mesh to act on, so pod mode warns and proceeds."""
    import logging

    learner = _tiny_learner()
    with caplog.at_level(logging.WARNING,
                         logger="tensor2robot_tpu.envs.rollout"):
      state = train_anakin(
          learner=learner, model_dir=str(tmp_path), env_family="pose",
          num_envs=4, rollout_length=1, train_batches_per_iter=1,
          batch_size=4, replay_capacity=16, max_train_steps=2,
          log_every_steps=2, save_checkpoints_steps=2, num_devices=2,
          shard_weight_update=True, seed=0)
    assert int(state.step) == 2
    assert any("shard_weight_update" in r.message
               for r in caplog.records)

  def test_single_program_shard_weight_update_smoke(self, tmp_path):
    """The PR-6 composition on the jit+mesh path: a short single-
    program run with the flag on completes and checkpoints on the
    8-virtual-device mesh (moments constrained by the update
    sharding; 1-device meshes are the pinned bitwise no-op)."""
    learner = _tiny_learner()
    state = train_anakin(
        learner=learner, model_dir=str(tmp_path), env_family="pose",
        num_envs=8, rollout_length=1, train_batches_per_iter=2,
        batch_size=8, replay_capacity=32, max_train_steps=4,
        log_every_steps=2, save_checkpoints_steps=4,
        shard_weight_update=True, seed=0)
    assert int(np.asarray(jax.device_get(state.step))) == 4

  @pytest.mark.slow
  def test_pod_one_device_bitwise_vs_single_program(self):
    """THE equivalence pin: at D=1 the pmap'd pod program reproduces
    the PR-9 single-device jitted program BITWISE — same PRNG
    streams, same ring schedule, same updates. XLA:CPU's LLVM
    backend makes per-module FMA-contraction choices (jit- and
    pmap-compiled modules of the same jaxpr drift by 1 ulp/step in
    the conv/dense backward), so the pin runs in a subprocess under
    an FMA-less ISA cap — program equivalence is exactly what
    remains once the compiler's contraction freedom is removed."""
    import os
    import subprocess
    import sys
    import textwrap

    script = textwrap.dedent("""
        import tempfile
        import numpy as np, jax
        from tensor2robot_tpu.envs import train_anakin
        from tensor2robot_tpu.research.qtopt import (
            GraspingQModel, QTOptLearner)

        def tiny():
          model = GraspingQModel(image_size=16, torso_filters=(8,),
                                 head_filters=(8,), dense_sizes=(16,),
                                 action_dim=2)
          return QTOptLearner(model, cem_population=8,
                              cem_iterations=1, cem_elites=2)

        kwargs = dict(env_family="pose", num_envs=16,
                      rollout_length=2, train_batches_per_iter=4,
                      batch_size=16, replay_capacity=128,
                      max_train_steps=16, log_every_steps=8,
                      save_checkpoints_steps=16, seed=0)
        with tempfile.TemporaryDirectory() as t1:
          single = train_anakin(learner=tiny(), model_dir=t1, **kwargs)
        with tempfile.TemporaryDirectory() as t2:
          pod = train_anakin(learner=tiny(), model_dir=t2,
                             num_devices=1, **kwargs)
        for tag, a, b in (
            ("params", single.train_state.params,
             pod.train_state.params),
            ("batch_stats", single.train_state.batch_stats,
             pod.train_state.batch_stats),
            ("opt_state", single.train_state.opt_state,
             pod.train_state.opt_state),
            ("target_params", single.target_params,
             pod.target_params)):
          la = jax.tree_util.tree_leaves(jax.device_get(a))
          lb = jax.tree_util.tree_leaves(jax.device_get(b))
          for x, y in zip(la, lb):
            assert np.array_equal(np.asarray(x), np.asarray(y)), tag
        print("BITWISE_OK")
    """)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = ("--xla_force_host_platform_device_count=2 "
                        "--xla_cpu_max_isa=SSE4_2")
    out = subprocess.run(
        [sys.executable, "-c", script], env=env, capture_output=True,
        text=True, timeout=900,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert out.returncode == 0, out.stderr[-4000:]
    assert "BITWISE_OK" in out.stdout

  @pytest.mark.slow
  def test_pod_two_devices_close_to_single_program(self, tmp_path):
    """Device-count invariance, statistically pinned end to end: a
    2-device pod run (same total envs, same per-device batch) stays
    a working learner — finite losses, full replay ring, and a final
    collect reward in the same regime as the single-program run."""
    learner = _tiny_learner()
    single = train_anakin(
        learner=learner, model_dir=str(tmp_path / "single"),
        **self.POD_KWARGS)
    pod = train_anakin(
        learner=learner, model_dir=str(tmp_path / "pod"),
        num_devices=2, **self.POD_KWARGS)
    rows_s = read_records(str(tmp_path / "single" / "metrics_train.jsonl"))
    rows_p = read_records(str(tmp_path / "pod" / "metrics_train.jsonl"))
    assert int(single.step) == int(pod.step) == 16
    assert np.isfinite(rows_p[-1]["loss"])
    # Same collection volume per iteration: both fill the ring at the
    # same rate even though the pod splits it across two shards.
    assert rows_p[-1]["replay_fill"] == rows_s[-1]["replay_fill"]
    # Both learners' Bellman targets live on the same sigmoid scale.
    assert abs(rows_p[-1]["target_mean"]
               - rows_s[-1]["target_mean"]) < 0.25


class TestScenarioSuccessEvalHook:
  """Per-checkpoint procgen robustness sweeps land in the metrics log
  AND the success-protocol artifact family (ISSUE 10 satellite)."""

  def test_checkpoint_sweep_logs_and_appends(self, tmp_path):
    from tensor2robot_tpu.hooks import ScenarioSuccessEvalHook

    learner = _tiny_learner()
    state = learner.create_state(RNG)
    env = ProcGenGraspEnv(image_size=16, action_dim=2)
    hook = ScenarioSuccessEvalHook(learner=learner, env=env,
                                   num_scenarios=32, seed=3)
    hook.begin(learner.model, str(tmp_path))
    # train_anakin hands hooks the device-0 critic TrainState.
    hook.after_checkpoint(500, state.train_state, str(tmp_path))
    hook.after_checkpoint(1000, state.train_state, str(tmp_path))

    rows = read_records(str(tmp_path / "metrics_scenario_eval.jsonl"))
    assert [r["step"] for r in rows] == [500, 1000]
    assert 0.0 <= rows[0]["success_rate"] <= 1.0
    assert "random_baseline_success_rate" in rows[0]
    assert any(k.startswith("bucket_") for k in rows[0])

    art = tmp_path / "success_protocol" / "scenarios_by_checkpoint.jsonl"
    records = [json.loads(line) for line in open(art)]
    assert [r["step"] for r in records] == [500, 1000]
    assert records[0]["phase"] == "checkpoint_sweep"
    assert records[0]["per_bucket"]
    # Seeded sweep: every checkpoint scored on the SAME scenario set.
    assert (records[0]["scenario_digest"]
            == records[1]["scenario_digest"])

  def test_every_n_checkpoints_thins(self, tmp_path):
    from tensor2robot_tpu.hooks import ScenarioSuccessEvalHook

    learner = _tiny_learner()
    state = learner.create_state(RNG)
    hook = ScenarioSuccessEvalHook(
        learner=learner, env=ProcGenGraspEnv(image_size=16,
                                             action_dim=2),
        num_scenarios=16, seed=1, every_n_checkpoints=2)
    hook.begin(learner.model, str(tmp_path))
    for step in (100, 200, 300):
      hook.after_checkpoint(step, state.train_state, str(tmp_path))
    rows = read_records(str(tmp_path / "metrics_scenario_eval.jsonl"))
    assert [r["step"] for r in rows] == [100, 300]


class TestTrainAnakinLearning:

  @pytest.mark.slow
  def test_anakin_learns_pose_bandit(self, tmp_path):
    # Training-quality check (slow lane): on-device online QT-Opt
    # should beat the random baseline on the pose bandit. Recipe
    # mirrors test_qtopt's proven toy-grasp clone (lr 1e-3, the
    # (16,32)/(32,)/(32,32) tower); measured on this host:
    # success 1.0 vs random ~0.09 at 600 steps in ~23s.
    from tensor2robot_tpu.models import optimizers as opt_lib

    model = GraspingQModel(
        image_size=16, action_dim=2, torso_filters=(16, 32),
        head_filters=(32,), dense_sizes=(32, 32),
        create_optimizer_fn=lambda: opt_lib.create_optimizer(
            learning_rate=1e-3))
    learner = QTOptLearner(model, cem_population=16,
                           cem_iterations=2, cem_elites=4)
    env = PoseBanditEnv(image_size=16, action_dim=2,
                        success_threshold=0.15)
    state = train_anakin(
        learner=learner, model_dir=str(tmp_path), env=env,
        num_envs=128, rollout_length=2, train_batches_per_iter=4,
        batch_size=128, replay_capacity=4096, max_train_steps=600,
        log_every_steps=200, save_checkpoints_steps=600, epsilon=0.3,
        seed=0)
    sweep = evaluate_scenarios(learner, state, env=env,
                               num_scenarios=256, seed=9,
                               cem_population=64, cem_iterations=3)
    assert sweep["success_rate"] > max(
        3 * sweep["random_baseline_success_rate"], 0.5), sweep


class TestShardMapPodProgram:
  """The jit+shard_map pod program over the named `pod` mesh axis
  (ISSUE 12): env shards / rings / Bellman batches ride
  PartitionSpec("pod"), training runs as GSPMD jit — so ZeRO
  (`shard_weight_update`) composes with the pod axis instead of being
  warn-ignored, and D=1 is bitwise the pmap pod program."""

  POD_KWARGS = dict(
      env_family="pose", num_envs=16, rollout_length=2,
      train_batches_per_iter=4, batch_size=16, replay_capacity=128,
      max_train_steps=16, log_every_steps=8,
      save_checkpoints_steps=16, seed=0)

  def test_smoke_metrics_and_exact_resume(self, tmp_path):
    learner = _tiny_learner()
    state = train_anakin(learner=learner, model_dir=str(tmp_path),
                         num_devices=2, pod_program="shard_map",
                         **self.POD_KWARGS)
    assert int(np.asarray(jax.device_get(state.step))) == 16
    rows = read_records(str(tmp_path / "metrics_train.jsonl"))
    assert rows
    for row in rows:
      # Same contract as the pmap pod program: acting params ARE the
      # training params inside the one jitted program.
      assert row["param_refresh_lag_steps"] == 0.0
      assert row["devices"] == 2
      assert row["global_batch_size"] == 32
      assert row["bellman_batches_per_sec"] == pytest.approx(
          2 * row["grad_steps_per_sec"])
      assert 0.0 <= row["replay_fill"] <= 1.0
      assert np.isfinite(row["loss"])
    resumed = train_anakin(learner=learner, model_dir=str(tmp_path),
                           num_devices=2, pod_program="shard_map",
                           **self.POD_KWARGS)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(jax.device_get(a)),
            np.asarray(jax.device_get(b))),
        state.train_state.params, resumed.train_state.params)

  def test_zero_shards_moments_across_pod_axis(self, tmp_path,
                                               caplog):
    """THE composition pin: shard_weight_update in shard_map pod mode
    leaves optimizer moments sharded P over the `pod` axis — no
    warn-ignore path — while params stay replicated."""
    import logging

    from tensor2robot_tpu.envs.rollout import POD_AXIS

    learner = _tiny_learner(image_size=16)
    with caplog.at_level(logging.WARNING,
                         logger="tensor2robot_tpu.envs.rollout"):
      state = train_anakin(
          learner=learner, model_dir=str(tmp_path),
          num_devices=2, pod_program="shard_map",
          shard_weight_update=True, update_shard_min_size=64,
          sharding_rules="qtopt", **self.POD_KWARGS)
    # No warn-ignore: the flag composes instead of being dropped.
    assert not any("shard_weight_update" in r.message
                   for r in caplog.records)
    pod_sharded = [
        leaf for leaf in jax.tree_util.tree_leaves(
            state.train_state.opt_state)
        if hasattr(leaf, "sharding")
        and POD_AXIS in [ax for ax in leaf.sharding.spec if ax]]
    assert pod_sharded, "no optimizer moment rides the pod axis"
    for leaf in jax.tree_util.tree_leaves(state.train_state.params):
      assert leaf.sharding.spec == jax.sharding.PartitionSpec()

  def test_rejects_unknown_pod_program_and_family(self, tmp_path):
    learner = _tiny_learner()
    with pytest.raises(ValueError, match="pod_program"):
      train_anakin(learner=learner, model_dir=str(tmp_path),
                   num_devices=2, pod_program="spmd",
                   **self.POD_KWARGS)
    with pytest.raises(ValueError, match="unknown model family"):
      train_anakin(learner=learner, model_dir=str(tmp_path),
                   num_devices=2, pod_program="shard_map",
                   sharding_rules="nope", **self.POD_KWARGS)

  def test_two_devices_close_to_pmap_pod(self, tmp_path):
    """Program-substrate invariance, statistically pinned: the
    shard_map program at D=2 matches the pmap program's collection
    volume exactly and lands its Bellman targets in the same regime
    (global-batch GSPMD training vs per-device pmean'd training are
    numerically different schedules, not different learners)."""
    learner = _tiny_learner()
    pmap_state = train_anakin(
        learner=learner, model_dir=str(tmp_path / "pmap"),
        num_devices=2, **self.POD_KWARGS)
    sm_state = train_anakin(
        learner=learner, model_dir=str(tmp_path / "sm"),
        num_devices=2, pod_program="shard_map", **self.POD_KWARGS)
    rows_p = read_records(str(tmp_path / "pmap" /
                              "metrics_train.jsonl"))
    rows_s = read_records(str(tmp_path / "sm" /
                              "metrics_train.jsonl"))
    assert int(pmap_state.step) == int(
        np.asarray(jax.device_get(sm_state.step))) == 16
    assert rows_s[-1]["replay_fill"] == rows_p[-1]["replay_fill"]
    assert np.isfinite(rows_s[-1]["loss"])
    assert abs(rows_s[-1]["target_mean"]
               - rows_p[-1]["target_mean"]) < 0.25

  @pytest.mark.slow
  def test_shardmap_one_device_bitwise_vs_pmap_pod(self):
    """THE equivalence pin (acceptance, ISSUE 12): at D=1 the
    jit+shard_map pod program reproduces the pmap pod program BITWISE
    on params/opt_state/batch_stats/target_params — same PRNG
    schedule, same ring schedule, same updates. Runs in a subprocess
    under an FMA-less ISA cap (`--xla_cpu_max_isa=SSE4_2`), the PR-10
    methodology: jit- and pmap-compiled modules of the same jaxpr may
    differ by per-module FMA-contraction choices, and program
    equivalence is what remains once that freedom is removed."""
    import subprocess
    import sys
    import textwrap

    script = textwrap.dedent("""
        import tempfile
        import numpy as np, jax
        from tensor2robot_tpu.envs import train_anakin
        from tensor2robot_tpu.research.qtopt import (
            GraspingQModel, QTOptLearner)

        def tiny():
          model = GraspingQModel(image_size=16, torso_filters=(8,),
                                 head_filters=(8,), dense_sizes=(16,),
                                 action_dim=2)
          return QTOptLearner(model, cem_population=8,
                              cem_iterations=1, cem_elites=2)

        kwargs = dict(env_family="pose", num_envs=16,
                      rollout_length=2, train_batches_per_iter=4,
                      batch_size=16, replay_capacity=128,
                      max_train_steps=16, log_every_steps=8,
                      save_checkpoints_steps=16, seed=0)
        with tempfile.TemporaryDirectory() as t1:
          pmap_pod = train_anakin(learner=tiny(), model_dir=t1,
                                  num_devices=1, **kwargs)
        with tempfile.TemporaryDirectory() as t2:
          sm_pod = train_anakin(learner=tiny(), model_dir=t2,
                                num_devices=1,
                                pod_program="shard_map", **kwargs)
        for tag, a, b in (
            ("params", pmap_pod.train_state.params,
             sm_pod.train_state.params),
            ("batch_stats", pmap_pod.train_state.batch_stats,
             sm_pod.train_state.batch_stats),
            ("opt_state", pmap_pod.train_state.opt_state,
             sm_pod.train_state.opt_state),
            ("target_params", pmap_pod.target_params,
             sm_pod.target_params)):
          la = jax.tree_util.tree_leaves(jax.device_get(a))
          lb = jax.tree_util.tree_leaves(jax.device_get(b))
          for x, y in zip(la, lb):
            assert np.array_equal(np.asarray(x), np.asarray(y)), tag
        print("BITWISE_OK")
    """)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = ("--xla_force_host_platform_device_count=2 "
                        "--xla_cpu_max_isa=SSE4_2")
    out = subprocess.run(
        [sys.executable, "-c", script], env=env, capture_output=True,
        text=True, timeout=900,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert out.returncode == 0, out.stderr[-4000:]
    assert "BITWISE_OK" in out.stdout

  @pytest.mark.slow
  def test_zero_rewrap_across_device_counts_does_not_stack(
      self, tmp_path):
    """Bench rows reuse ONE learner across device counts: the keyed
    `wrap_optimizer(key="shard_weight_update")` must REPLACE the
    previous pod-mesh wrap, not stack a constraint pinned to a dead
    mesh's devices (the full-bench failure this regression-pins)."""
    learner = _tiny_learner()
    kwargs = {**self.POD_KWARGS, "max_train_steps": 8,
              "log_every_steps": 4, "save_checkpoints_steps": 8}
    for run, dcount in enumerate((2, 4)):
      state = train_anakin(
          learner=learner, model_dir=str(tmp_path / str(run)),
          num_devices=dcount, pod_program="shard_map",
          shard_weight_update=True, update_shard_min_size=64,
          **kwargs)
      assert int(np.asarray(jax.device_get(state.step))) == 8
    # And the flag-OFF leak direction: a later run WITHOUT the flag on
    # the same learner must get the identity re-wrap, not the previous
    # run's pod-mesh-pinned ZeRO constraint — its moments replicate.
    state = train_anakin(
        learner=learner, model_dir=str(tmp_path / "off"),
        num_devices=2, pod_program="shard_map",
        shard_weight_update=False, **kwargs)
    assert int(np.asarray(jax.device_get(state.step))) == 8
    for leaf in jax.tree_util.tree_leaves(state.train_state.opt_state):
      if hasattr(leaf, "sharding"):
        assert leaf.sharding.spec == jax.sharding.PartitionSpec(), leaf
