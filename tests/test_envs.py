"""Tests for the on-device vectorized env subsystem (ISSUE 9).

Pins the functional-env contract (docs/ENVS.md): host-vs-device pose
parity on matched geometry, auto-reset semantics at episode
boundaries, same-key scenario determinism (the JaxARC property), the
rollout engine's replay-wire-spec output, the jit-once guarantee (no
retrace across iterations), and the --trainer=anakin e2e loop.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensor2robot_tpu.envs import (
    AutoResetEnv,
    BatchedEnv,
    JaxEnvBandit,
    PoseBanditEnv,
    ProcGenGraspEnv,
    evaluate_scenarios,
    host_parity_env,
    make_anakin_collect_fn,
    make_batched,
    make_collect_fn,
    train_anakin,
)
from tensor2robot_tpu.envs.rollout import flatten_time, rollout
from tensor2robot_tpu.research.qtopt import (
    GraspingQModel,
    QTOptLearner,
)

RNG = jax.random.PRNGKey(0)


def _tiny_learner(image_size=16, **learner_kwargs):
  model = GraspingQModel(image_size=image_size, torso_filters=(8,),
                         head_filters=(8,), dense_sizes=(16,),
                         action_dim=2)
  learner_kwargs.setdefault("cem_population", 8)
  learner_kwargs.setdefault("cem_iterations", 1)
  learner_kwargs.setdefault("cem_elites", 2)
  return QTOptLearner(model, **learner_kwargs)


class TestHostDeviceParity:
  """The pose env mirrors `PoseGraspBandit` on matched geometry."""

  def test_reward_parity_on_matched_geometry(self):
    from tensor2robot_tpu.research.pose_env.grasp_bandit import (
        PoseGraspBandit,
    )

    host = PoseGraspBandit(image_size=16, physics=False, seed=3)
    device = host_parity_env(host)
    _, poses = host.reset_batch(64)
    actions = np.random.default_rng(0).uniform(
        -1, 1, (64, 2)).astype(np.float32)
    host_rewards = host.grade(actions, poses)
    device_rewards = np.asarray(jax.device_get(jax.vmap(
        device.grasp_reward)(jnp.asarray(actions),
                             jnp.asarray(poses))))
    # Same float32 math on both sides; a mixed batch (some successes)
    # proves the comparison isn't vacuous.
    np.testing.assert_array_equal(host_rewards, device_rewards)
    assert 0.0 < host_rewards.mean() < 1.0 or host_rewards.mean() == 0.0

  def test_step_reward_equals_host_grade(self):
    from tensor2robot_tpu.research.pose_env.grasp_bandit import (
        grade_grasp,
    )

    env = PoseBanditEnv(image_size=16)
    state = env.reset(RNG)
    action = jnp.asarray([0.3, -0.2])
    _, _, reward, done = env.step(state, action, RNG)
    expected = grade_grasp(np.asarray(action)[None],
                           np.asarray(state.pose)[None],
                           threshold=0.1)[0]
    assert float(reward) == float(expected)
    assert bool(done)  # single-step bandit

  def test_noiseless_frames_bitwise_equal(self):
    from tensor2robot_tpu.research.pose_env.pose_env import PoseEnv

    host = PoseEnv(image_size=16, seed=5, noise=0.0)
    host_obs = host.reset()
    device = PoseBanditEnv(image_size=16, noise=0.0)
    device_obs = device.observe(
        device.state_at(host.pose, jax.random.PRNGKey(0)))
    np.testing.assert_array_equal(np.asarray(device_obs["image"]),
                                  host_obs["image"])


class TestAutoReset:

  def test_resets_at_step_limit(self):
    env = PoseBanditEnv(image_size=8, max_episode_steps=3)
    wrapped = AutoResetEnv(env)
    state = wrapped.reset(RNG)
    pose0 = np.asarray(state.pose)
    miss = jnp.asarray([1.0, 1.0])  # corner: never within threshold
    key = jax.random.PRNGKey(1)
    for t in range(2):
      state, _, reward, done = wrapped.step(
          state, miss, jax.random.fold_in(key, t))
      assert not bool(done) and float(reward) == 0.0
      # Mid-episode: same block, advancing clock.
      np.testing.assert_array_equal(np.asarray(state.pose), pose0)
      assert int(state.t) == t + 1
    state, obs, reward, done = wrapped.step(
        state, miss, jax.random.fold_in(key, 2))
    assert bool(done)
    # The returned state is a FRESH episode: clock zeroed, new block.
    assert int(state.t) == 0
    assert not np.array_equal(np.asarray(state.pose), pose0)

  def test_terminal_obs_is_old_episode(self):
    env = PoseBanditEnv(image_size=8, noise=0.0, max_episode_steps=1)
    wrapped = AutoResetEnv(env)
    state = wrapped.reset(RNG)
    pose0 = np.asarray(state.pose)
    new_state, obs, _, done = wrapped.step(
        state, jnp.asarray([1.0, 1.0]), jax.random.PRNGKey(1))
    assert bool(done)
    old_frame = env.observe(
        env.state_at(pose0, jax.random.PRNGKey(9)))["image"]
    np.testing.assert_array_equal(np.asarray(obs["image"]),
                                  np.asarray(old_frame))
    fresh_frame = wrapped.observe(new_state)["image"]
    assert not np.array_equal(np.asarray(fresh_frame),
                              np.asarray(old_frame))

  def test_success_ends_episode(self):
    env = PoseBanditEnv(image_size=8, max_episode_steps=5)
    state = env.reset(RNG)
    hit = state.pose / jnp.asarray(0.4)  # exact grasp, normalized
    _, _, reward, done = env.step(state, hit, RNG)
    assert float(reward) == 1.0 and bool(done)


class TestScenarioDeterminism:
  """JaxARC property: the key IS the scenario."""

  def test_same_key_same_scenario(self):
    env = ProcGenGraspEnv(image_size=16)
    a = env.reset(jax.random.PRNGKey(7))
    b = env.reset(jax.random.PRNGKey(7))
    for leaf_a, leaf_b in zip(jax.tree_util.tree_leaves(a),
                              jax.tree_util.tree_leaves(b)):
      np.testing.assert_array_equal(np.asarray(leaf_a),
                                    np.asarray(leaf_b))
    np.testing.assert_array_equal(
        np.asarray(env.observe(a)["image"]),
        np.asarray(env.observe(b)["image"]))

  def test_different_keys_differ(self):
    env = ProcGenGraspEnv(image_size=16)
    a = env.reset(jax.random.PRNGKey(7))
    b = env.reset(jax.random.PRNGKey(8))
    assert not np.array_equal(np.asarray(a.pose), np.asarray(b.pose))

  def test_scenario_diversity_and_buckets(self):
    env = ProcGenGraspEnv(image_size=16, max_distractors=3)
    states = jax.vmap(env.reset)(jax.random.split(RNG, 128))
    buckets = np.asarray(jax.vmap(env.scenario_bucket)(states))
    # All four buckets appear and geometry actually varies.
    assert set(buckets.tolist()) == {0, 1, 2, 3}
    assert np.asarray(states.half_extent).std() > 0
    assert np.asarray(states.workspace).std() > 0

  def test_sweep_digests_reproduce(self):
    learner = _tiny_learner()
    state = learner.create_state(RNG)
    env = ProcGenGraspEnv(image_size=16, action_dim=2)
    a = evaluate_scenarios(learner, state, env=env,
                           num_scenarios=32, seed=3)
    b = evaluate_scenarios(learner, state, env=env,
                           num_scenarios=32, seed=3)
    c = evaluate_scenarios(learner, state, env=env,
                           num_scenarios=32, seed=4)
    assert a["action_digest"] == b["action_digest"]
    assert a["scenario_digest"] == b["scenario_digest"]
    assert a["scenario_digest"] != c["scenario_digest"]
    assert sum(row["count"] for row in a["per_bucket"].values()) == 32


class TestRolloutEngine:

  def test_batch_matches_replay_wire_spec(self):
    learner = _tiny_learner()
    env = PoseBanditEnv(image_size=16, action_dim=2)
    init_fn, collect_fn = make_collect_fn(
        learner, env, num_envs=4, rollout_length=3, epsilon=0.5)
    states = jax.jit(init_fn)(RNG)
    state = learner.create_state(RNG)
    _, batch = jax.jit(collect_fn)(state, states,
                                   jax.random.PRNGKey(2))
    spec = learner.transition_specification().to_flat_dict()
    assert set(batch) == set(spec)
    for key, sp in spec.items():
      assert batch[key].shape == (12,) + tuple(sp.shape), key
      assert batch[key].dtype == sp.dtype, key
    # Wire batches feed the replay plane unchanged.
    from tensor2robot_tpu.research.qtopt import ReplayBuffer
    replay = ReplayBuffer(learner.transition_specification(),
                          capacity=64)
    replay.add({k: np.asarray(v) for k, v in batch.items()})
    assert len(replay) == 12

  def test_per_env_keys_are_independent(self):
    env = PoseBanditEnv(image_size=8)
    batched = BatchedEnv(env, 16)
    states = batched.reset(RNG)
    poses = np.asarray(states.pose)
    assert np.unique(poses, axis=0).shape[0] == 16

  def test_jit_once_across_iterations(self):
    learner = _tiny_learner()
    env = PoseBanditEnv(image_size=16, action_dim=2)
    init_fn, collect_fn = make_collect_fn(
        learner, env, num_envs=4, rollout_length=2)
    traces = {"count": 0}

    def counted(learner_state, env_states, key):
      traces["count"] += 1
      return collect_fn(learner_state, env_states, key)

    collect = jax.jit(counted)
    state = learner.create_state(RNG)
    env_states = jax.jit(init_fn)(RNG)
    for t in range(4):
      env_states, batch = collect(state, env_states,
                                  jax.random.fold_in(RNG, t))
    float(batch["reward"].sum())
    assert traces["count"] == 1  # one trace, many dispatches

  def test_done_rows_present_and_rewards_graded(self):
    env = PoseBanditEnv(image_size=8)  # single-step: every row done
    batched = make_batched(env, 8)

    def random_policy(obs, key):
      del obs
      return jax.random.uniform(key, (8, 2), minval=-1.0, maxval=1.0)

    states = batched.reset(RNG)
    _, traj = jax.jit(
        lambda st, key: rollout(batched, random_policy, st, key, 4))(
            states, jax.random.PRNGKey(3))
    flat = flatten_time(traj)
    np.testing.assert_array_equal(np.asarray(flat["done"]),
                                  np.ones((32, 1), np.float32))
    rewards = np.asarray(flat["reward"])
    assert set(np.unique(rewards)).issubset({0.0, 1.0})

  def test_anakin_scaleout_matches_wire(self):
    learner = _tiny_learner()
    env = PoseBanditEnv(image_size=16, action_dim=2)
    devices = jax.local_devices()[:2]
    init_fn, collect_fn = make_anakin_collect_fn(
        learner, env, num_envs=4, rollout_length=2, devices=devices)
    state = learner.create_state(RNG)
    env_states = init_fn(RNG)
    _, batch = collect_fn(state, env_states, jax.random.PRNGKey(2))
    from tensor2robot_tpu.envs import flatten_devices
    flat = flatten_devices(batch)
    assert flat["image"].shape == (8, 16, 16, 3)
    assert flat["action"].shape == (8, 2)


class TestJaxEnvBandit:
  """The host adapter: functional envs as GraspActor scenario sources."""

  def test_bandit_interface(self):
    bandit = JaxEnvBandit(env=ProcGenGraspEnv(image_size=16), seed=0)
    obs, poses = bandit.reset_batch(8)
    assert obs["image"].shape == (8, 16, 16, 3)
    assert obs["image"].dtype == np.uint8
    assert poses.shape == (8, 2)
    assert bandit.last_buckets is not None
    rewards = bandit.grade(
        np.zeros((8, 2), np.float32), poses)
    assert rewards.shape == (8,)
    transitions = bandit.sample_transitions(8)
    assert set(transitions) == {"image", "action", "reward", "done",
                                "next_image"}

  def test_grasp_actor_collects_through_bandit(self):
    from tensor2robot_tpu.research.qtopt import (
        GraspActor,
        ReplayBuffer,
    )

    learner = _tiny_learner()
    replay = ReplayBuffer(learner.transition_specification(),
                          capacity=128)
    actor = GraspActor(
        learner, replay,
        env=JaxEnvBandit(env=ProcGenGraspEnv(image_size=16), seed=1),
        batch_episodes=8, epsilon=0.5, seed=2)
    actor.collect_once()  # bootstrap (random policy)
    actor.update_state(learner.create_state(RNG))
    actor.collect_once()  # CEM policy through the adapter
    assert len(replay) == 16
    assert actor.episodes_collected == 16


class TestTrainAnakin:

  def test_e2e_smoke(self, tmp_path):
    learner = _tiny_learner()
    state = train_anakin(
        learner=learner,
        model_dir=str(tmp_path),
        env_family="pose",
        num_envs=16,
        rollout_length=2,
        train_batches_per_iter=4,
        batch_size=16,
        replay_capacity=128,
        max_train_steps=16,
        log_every_steps=8,
        save_checkpoints_steps=16,
        seed=0)
    assert int(state.step) == 16
    rows = [json.loads(line)
            for line in open(tmp_path / "metrics_train.jsonl")]
    assert rows, "no train metrics written"
    for row in rows:
      # Zero by construction: acting and training params are the same
      # arrays inside one program.
      assert row["param_refresh_lag_steps"] == 0.0
      assert 0.0 <= row["replay_fill"] <= 1.0
      assert row["env_steps_per_sec"] > 0
    from tensor2robot_tpu.utils import checkpoints as ckpt_lib
    assert ckpt_lib.latest_step(str(tmp_path)) == 16

  def test_cadence_must_divide(self, tmp_path):
    learner = _tiny_learner()
    with pytest.raises(ValueError):
      train_anakin(learner=learner, model_dir=str(tmp_path),
                   num_envs=4, rollout_length=1,
                   train_batches_per_iter=4, batch_size=4,
                   max_train_steps=10,  # not a multiple of 4
                   log_every_steps=4, save_checkpoints_steps=4)

  def test_rejects_extra_state_features(self, tmp_path):
    model = GraspingQModel(image_size=16, torso_filters=(8,),
                           head_filters=(8,), dense_sizes=(16,),
                           action_dim=2,
                           extra_state_features={"gripper": (1,)})
    learner = QTOptLearner(model, cem_population=4,
                           cem_iterations=1, cem_elites=2)
    with pytest.raises(ValueError, match="extra keys"):
      train_anakin(learner=learner, model_dir=str(tmp_path),
                   num_envs=4, rollout_length=1,
                   train_batches_per_iter=1, batch_size=4,
                   max_train_steps=1, log_every_steps=1,
                   save_checkpoints_steps=1)

  @pytest.mark.slow
  def test_anakin_learns_pose_bandit(self, tmp_path):
    # Training-quality check (slow lane): on-device online QT-Opt
    # should beat the random baseline on the pose bandit. Recipe
    # mirrors test_qtopt's proven toy-grasp clone (lr 1e-3, the
    # (16,32)/(32,)/(32,32) tower); measured on this host:
    # success 1.0 vs random ~0.09 at 600 steps in ~23s.
    from tensor2robot_tpu.models import optimizers as opt_lib

    model = GraspingQModel(
        image_size=16, action_dim=2, torso_filters=(16, 32),
        head_filters=(32,), dense_sizes=(32, 32),
        create_optimizer_fn=lambda: opt_lib.create_optimizer(
            learning_rate=1e-3))
    learner = QTOptLearner(model, cem_population=16,
                           cem_iterations=2, cem_elites=4)
    env = PoseBanditEnv(image_size=16, action_dim=2,
                        success_threshold=0.15)
    state = train_anakin(
        learner=learner, model_dir=str(tmp_path), env=env,
        num_envs=128, rollout_length=2, train_batches_per_iter=4,
        batch_size=128, replay_capacity=4096, max_train_steps=600,
        log_every_steps=200, save_checkpoints_steps=600, epsilon=0.3,
        seed=0)
    sweep = evaluate_scenarios(learner, state, env=env,
                               num_scenarios=256, seed=9,
                               cem_population=64, cem_iterations=3)
    assert sweep["success_rate"] > max(
        3 * sweep["random_baseline_success_rate"], 0.5), sweep
