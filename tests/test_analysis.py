"""Tests for the t2rcheck static-analysis suite (ISSUE 5).

Every rule ID gets a POSITIVE fixture (a snippet that must trigger it)
and a NEGATIVE fixture (the corrected form that must not), plus the
mechanics every rule shares: inline pragmas, the baseline ledger, the
CLI exit-code contract, the no-jax-import invariant of the AST path,
and the tier-1 guarantee that every shipped .gin config validates.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from tensor2robot_tpu.analysis import findings as findings_lib
from tensor2robot_tpu.analysis.concurrency_rules import (
    run_concurrency_rules,
)
from tensor2robot_tpu.analysis.findings import (
    Baseline,
    Finding,
    PragmaIndex,
    RULE_CATALOG,
)
from tensor2robot_tpu.analysis.fleet_rules import run_fleet_rules
from tensor2robot_tpu.analysis.import_rules import (
    import_closure,
    run_import_rules,
)
from tensor2robot_tpu.analysis.jax_rules import run_jax_rules
from tensor2robot_tpu.analysis.spmd_rules import (
    ENTRY_BINARY,
    run_spmd_rules,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write(tmp_path, name, code):
  path = tmp_path / name
  path.write_text(textwrap.dedent(code))
  return str(path)


def _rules(found):
  return {f.rule for f in found}


# ---------------------------------------------------------------------------
# JAX tracing-hazard rules
# ---------------------------------------------------------------------------

class TestJaxRules:

  def test_jax201_host_sync_positive(self, tmp_path):
    _write(tmp_path, "mod.py", """
        import jax

        @jax.jit
        def step(state, batch):
          out = state + batch
          jax.block_until_ready(out)
          loss = out.sum().item()
          return loss
    """)
    found = run_jax_rules([str(tmp_path)], str(tmp_path))
    assert "JAX201" in _rules(found)
    assert sum(f.rule == "JAX201" for f in found) == 2

  def test_jax201_float_on_traced_arg(self, tmp_path):
    _write(tmp_path, "mod.py", """
        import jax

        @jax.jit
        def step(x):
          return float(x) + 1.0
    """)
    assert "JAX201" in _rules(
        run_jax_rules([str(tmp_path)], str(tmp_path)))

  def test_jax201_negative_outside_trace(self, tmp_path):
    _write(tmp_path, "mod.py", """
        import jax

        def host_loop(state):
          jax.block_until_ready(state)  # fine: not traced
          return state
    """)
    assert _rules(run_jax_rules([str(tmp_path)], str(tmp_path))) == set()

  def test_jax202_impure_calls_positive(self, tmp_path):
    _write(tmp_path, "mod.py", """
        import time
        import numpy as np
        import jax

        @jax.jit
        def step(x):
          print("stepping")
          t = time.time()
          noise = np.random.normal(size=3)
          return x + noise.sum() + t
    """)
    found = run_jax_rules([str(tmp_path)], str(tmp_path))
    assert sum(f.rule == "JAX202" for f in found) == 3

  def test_jax202_reaches_transitive_callee(self, tmp_path):
    # The hazard hides one call deep: reachability must follow it.
    _write(tmp_path, "mod.py", """
        import time
        import jax

        def helper(x):
          return x * time.time()

        @jax.jit
        def step(x):
          return helper(x)
    """)
    found = run_jax_rules([str(tmp_path)], str(tmp_path))
    assert any(f.rule == "JAX202" and f.scope == "helper"
               for f in found)

  def test_jax202_negative_pure(self, tmp_path):
    _write(tmp_path, "mod.py", """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(x):
          return jnp.sum(x ** 2)
    """)
    assert _rules(run_jax_rules([str(tmp_path)], str(tmp_path))) == set()

  def test_jax203_tracer_branch_positive(self, tmp_path):
    _write(tmp_path, "mod.py", """
        import jax

        @jax.jit
        def step(x, loss):
          if loss > 0:
            x = x * 2
          return x
    """)
    assert "JAX203" in _rules(
        run_jax_rules([str(tmp_path)], str(tmp_path)))

  def test_jax203_negative_static_idioms(self, tmp_path):
    # None-checks, bare-container truthiness and raise-guards are the
    # trace-time-static idioms the rule documents as excluded.
    _write(tmp_path, "mod.py", """
        import jax

        @jax.jit
        def step(x, rng, batch_stats, block):
          if rng is None:
            rng = 0
          if batch_stats:
            x = x + 1
          if block % 2:
            raise ValueError("bad block")
          return x
    """)
    assert _rules(run_jax_rules([str(tmp_path)], str(tmp_path))) == set()

  def test_jax204_global_mutation_positive(self, tmp_path):
    _write(tmp_path, "mod.py", """
        import jax

        COUNT = 0

        @jax.jit
        def step(x):
          global COUNT
          COUNT += 1
          return x
    """)
    assert "JAX204" in _rules(
        run_jax_rules([str(tmp_path)], str(tmp_path)))

  def test_pallas_kernel_is_device_code_not_host_sync(self, tmp_path):
    """The Pallas carve-outs (ISSUE 7): pl.load/pl.store/ref indexing
    and Python branches on static block params inside a kernel are
    device code — zero findings, zero pragmas."""
    _write(tmp_path, "mod.py", """
        import functools
        from jax.experimental import pallas as pl

        def _kernel(x_ref, o_ref, *, block: int):
          if block > 4:  # static block-param branch: the kernel idiom
            val = pl.load(x_ref, (slice(None),))
          else:
            val = x_ref[...]
          pl.store(o_ref, (slice(None),), val * 2)

        def run(x):
          kernel = functools.partial(_kernel, block=8)
          return pl.pallas_call(kernel, out_shape=None)(x)
    """)
    assert _rules(run_jax_rules([str(tmp_path)], str(tmp_path))) == set()

  def test_pallas_kernel_still_scanned_for_impurity(self, tmp_path):
    """Pallas-aware ≠ pallas-blind: kernels ARE traced device code,
    so a genuine hazard inside one (host clock) is still flagged —
    through both the direct-name and the partial-variable entry."""
    _write(tmp_path, "mod.py", """
        import functools
        import time
        from jax.experimental import pallas as pl

        def _bad_kernel(x_ref, o_ref):
          time.sleep(0.1)
          o_ref[...] = x_ref[...]

        def run(x):
          return pl.pallas_call(_bad_kernel, out_shape=None)(x)

        def _bad_kernel2(x_ref, o_ref, *, n: int):
          t = time.time()
          o_ref[...] = x_ref[...] + t

        def run2(x):
          kernel = functools.partial(_bad_kernel2, n=4)
          return pl.pallas_call(kernel, out_shape=None)(x)
    """)
    found = run_jax_rules([str(tmp_path)], str(tmp_path))
    assert sum(f.rule == "JAX202" for f in found) == 2
    assert {f.scope for f in found} == {"_bad_kernel", "_bad_kernel2"}

  def test_pallas_partial_vars_resolve_per_scope(self, tmp_path):
    """Two functions both naming their partial `kernel` must resolve
    to their OWN kernels — a module-wide name map would let the
    second shadow the first and miss its hazard."""
    _write(tmp_path, "mod.py", """
        import functools
        import time
        from jax.experimental import pallas as pl

        def _hazard_kernel(x_ref, o_ref, *, n: int):
          time.sleep(0.1)
          o_ref[...] = x_ref[...]

        def _clean_kernel(x_ref, o_ref, *, n: int):
          o_ref[...] = x_ref[...]

        def run_hazard(x):
          kernel = functools.partial(_hazard_kernel, n=2)
          return pl.pallas_call(kernel, out_shape=None)(x)

        def run_clean(x):
          kernel = functools.partial(_clean_kernel, n=2)
          return pl.pallas_call(kernel, out_shape=None)(x)
    """)
    found = run_jax_rules([str(tmp_path)], str(tmp_path))
    assert [f.rule for f in found] == ["JAX202"]
    assert found[0].scope == "_hazard_kernel"

  def test_entry_detection_call_form_and_scan(self, tmp_path):
    # jax.jit(fn) / jax.lax.scan(body, ...) call forms, not decorators.
    _write(tmp_path, "mod.py", """
        import time
        import jax

        def body(carry, x):
          time.sleep(0.1)
          return carry, x

        def train():
          return jax.lax.scan(body, 0, None, length=3)

        def step(x):
          return x * time.time()

        jitted = jax.jit(step)
    """)
    found = run_jax_rules([str(tmp_path)], str(tmp_path))
    scopes = {f.scope for f in found if f.rule == "JAX202"}
    assert scopes == {"body", "step"}


# ---------------------------------------------------------------------------
# Concurrency & lifecycle rules
# ---------------------------------------------------------------------------

class TestConcurrencyRules:

  def test_con301_blocking_under_lock_positive(self, tmp_path):
    _write(tmp_path, "mod.py", """
        import subprocess
        import threading
        import time


        class Worker:

          def __init__(self):
            self._lock = threading.Lock()

          def slow(self):
            with self._lock:
              time.sleep(1.0)
              subprocess.run(["ls"])
              with open("/tmp/x") as f:
                return f.read()
    """)
    found = run_concurrency_rules([str(tmp_path)], str(tmp_path))
    assert sum(f.rule == "CON301" for f in found) == 3

  def test_con301_negative_outside_lock(self, tmp_path):
    _write(tmp_path, "mod.py", """
        import threading
        import time


        class Worker:

          def __init__(self):
            self._lock = threading.Lock()
            self._value = 0

          def ok(self):
            with self._lock:
              self._value += 1
            time.sleep(1.0)  # after release: fine
    """)
    found = run_concurrency_rules([str(tmp_path)], str(tmp_path))
    assert "CON301" not in _rules(found)

  def test_con301_untimed_queue_get_under_lock(self, tmp_path):
    _write(tmp_path, "mod.py", """
        import queue
        import threading


        class Pipe:

          def __init__(self):
            self._lock = threading.Lock()
            self._queue = queue.Queue(maxsize=4)

          def bad(self):
            with self._lock:
              return self._queue.get()
    """)
    found = run_concurrency_rules([str(tmp_path)], str(tmp_path))
    assert "CON301" in _rules(found)

  def test_con302_untimed_get_positive_and_fixed_negative(
      self, tmp_path):
    _write(tmp_path, "mod.py", """
        import queue


        class Consumer:

          def __init__(self):
            self._queue = queue.Queue(maxsize=2)

          def bad(self):
            return self._queue.get()

          def good(self):
            while True:
              try:
                return self._queue.get(timeout=0.1)
              except queue.Empty:
                continue

          def also_good(self):
            return self._queue.get_nowait()
    """)
    found = run_concurrency_rules([str(tmp_path)], str(tmp_path))
    con302 = [f for f in found if f.rule == "CON302"]
    assert len(con302) == 1 and con302[0].scope == "Consumer.bad"

  def test_con302_put_on_unbounded_queue_is_fine(self, tmp_path):
    _write(tmp_path, "mod.py", """
        import queue


        class Producer:

          def __init__(self):
            self._queue = queue.Queue()   # unbounded: put never blocks

          def ok(self, item):
            self._queue.put(item)
    """)
    found = run_concurrency_rules([str(tmp_path)], str(tmp_path))
    assert "CON302" not in _rules(found)

  def test_con302_put_on_bounded_queue_flags(self, tmp_path):
    _write(tmp_path, "mod.py", """
        import queue


        class Producer:

          def __init__(self):
            self._queue = queue.Queue(maxsize=2)

          def bad(self, item):
            self._queue.put(item)
    """)
    found = run_concurrency_rules([str(tmp_path)], str(tmp_path))
    assert "CON302" in _rules(found)

  def test_con303_lock_order_cycle_positive(self, tmp_path):
    _write(tmp_path, "a_mod.py", """
        import threading


        class Store:

          def __init__(self):
            self._alock = threading.Lock()
            self._block = threading.Lock()

          def forward(self):
            with self._alock:
              with self._block:
                return 1

          def backward(self):
            with self._block:
              with self._alock:
                return 2
    """)
    found = run_concurrency_rules([str(tmp_path)], str(tmp_path))
    assert "CON303" in _rules(found)

  def test_con303_cross_function_cycle_via_calls(self, tmp_path):
    # f holds A and calls g (acquires B); h holds B and calls k
    # (acquires A): the interprocedural edge set must close the cycle.
    _write(tmp_path, "mod.py", """
        import threading


        class Split:

          def __init__(self):
            self._alock = threading.Lock()
            self._block = threading.Lock()

          def take_b(self):
            with self._block:
              return 1

          def take_a(self):
            with self._alock:
              return 2

          def f(self):
            with self._alock:
              return self.take_b()

          def h(self):
            with self._block:
              return self.take_a()
    """)
    found = run_concurrency_rules([str(tmp_path)], str(tmp_path))
    assert "CON303" in _rules(found)

  def test_con303_cycle_through_lock_free_intermediate(self, tmp_path):
    # f holds A → g (NO lock) → h acquires B; reverse path closes the
    # cycle. The eventual-acquires fixpoint must cross the lock-free
    # hop g (code-review regression).
    _write(tmp_path, "mod.py", """
        import threading


        class Hops:

          def __init__(self):
            self._alock = threading.Lock()
            self._block = threading.Lock()

          def h_takes_b(self):
            with self._block:
              return 1

          def g_lockfree(self):
            return self.h_takes_b()

          def f(self):
            with self._alock:
              return self.g_lockfree()

          def reverse(self):
            with self._block:
              with self._alock:
                return 2
    """)
    found = run_concurrency_rules([str(tmp_path)], str(tmp_path))
    assert "CON303" in _rules(found)

  def test_con303_multi_item_with_orders_locks(self, tmp_path):
    # `with A, B:` acquires in item order — it must contribute the
    # A->B edge so the reverse nesting elsewhere closes a cycle
    # (code-review regression).
    _write(tmp_path, "mod.py", """
        import threading


        class Combined:

          def __init__(self):
            self._alock = threading.Lock()
            self._block = threading.Lock()

          def both_at_once(self):
            with self._alock, self._block:
              return 1

          def reverse(self):
            with self._block:
              with self._alock:
                return 2
    """)
    found = run_concurrency_rules([str(tmp_path)], str(tmp_path))
    assert "CON303" in _rules(found)

  def test_con301_re_compile_under_lock_not_flagged(self, tmp_path):
    # `.compile` only blocks when the receiver is a jit/AOT object;
    # a regex compile under a lock is microseconds (code-review
    # regression). The jitted form must still flag.
    _write(tmp_path, "mod.py", """
        import re
        import threading


        class Patterns:

          def __init__(self):
            self._lock = threading.Lock()
            self._jitted = None

          def ok(self, expr):
            with self._lock:
              return re.compile(expr)

          def bad(self, args):
            with self._lock:
              return self._jitted.lower(args).compile()
    """)
    found = run_concurrency_rules([str(tmp_path)], str(tmp_path))
    con301 = [f for f in found if f.rule == "CON301"]
    assert [f.scope for f in con301] == ["Patterns.bad"], con301

  def test_con303_negative_consistent_order(self, tmp_path):
    _write(tmp_path, "mod.py", """
        import threading


        class Store:

          def __init__(self):
            self._alock = threading.Lock()
            self._block = threading.Lock()

          def one(self):
            with self._alock:
              with self._block:
                return 1

          def two(self):
            with self._alock:
              with self._block:
                return 2
    """)
    found = run_concurrency_rules([str(tmp_path)], str(tmp_path))
    assert "CON303" not in _rules(found)

  def test_con304_leaked_resource_positive(self, tmp_path):
    _write(tmp_path, "mod.py", """
        from multiprocessing import shared_memory


        def leaky(n):
          shm = shared_memory.SharedMemory(create=True, size=n)
          return shm.name   # the handle is dropped: nothing can close
    """)
    found = run_concurrency_rules([str(tmp_path)], str(tmp_path))
    assert "CON304" in _rules(found)

  def test_con304_class_without_teardown_positive(self, tmp_path):
    _write(tmp_path, "mod.py", """
        import subprocess


        class Launcher:

          def __init__(self):
            self._proc = subprocess.Popen(["sleep", "100"])
    """)
    found = run_concurrency_rules([str(tmp_path)], str(tmp_path))
    assert "CON304" in _rules(found)

  def test_con304_negative_finally_and_teardown(self, tmp_path):
    _write(tmp_path, "mod.py", """
        import subprocess
        from multiprocessing import shared_memory


        class Launcher:

          def __init__(self):
            self._proc = subprocess.Popen(["sleep", "100"])

          def close(self):
            self._proc.terminate()


        def careful(n):
          shm = shared_memory.SharedMemory(create=True, size=n)
          try:
            return bytes(shm.buf[:4])
          finally:
            shm.close()
            shm.unlink()


        def transfer(n):
          shm = shared_memory.SharedMemory(create=True, size=n)
          return shm   # ownership moves to the caller
    """)
    found = run_concurrency_rules([str(tmp_path)], str(tmp_path))
    assert "CON304" not in _rules(found)


# ---------------------------------------------------------------------------
# Import hygiene
# ---------------------------------------------------------------------------

class TestImportRules:

  def test_imp401_clean_on_this_repo(self):
    assert run_import_rules(REPO_ROOT) == []

  def test_imp401_positive_on_seeded_tree(self, tmp_path):
    pkg = tmp_path / "tensor2robot_tpu"
    (pkg / "data").mkdir(parents=True)
    (pkg / "config").mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "config" / "__init__.py").write_text("")
    (pkg / "config" / "ginlite.py").write_text("x = 1\n")
    (pkg / "data" / "__init__.py").write_text("")
    (pkg / "data" / "shm_ring.py").write_text("import numpy\n")
    # plane -> helper -> jax: a TRANSITIVE reach, two hops deep.
    (pkg / "data" / "plane.py").write_text(
        "from tensor2robot_tpu.data import helper\n")
    (pkg / "data" / "helper.py").write_text("import jax\n")
    found = run_import_rules(str(tmp_path))
    assert [f.rule for f in found] == ["IMP401"]
    assert "tensor2robot_tpu.data.helper" in found[0].message

  def test_import_closure_computed_from_entry_binary(self):
    # The entry binary's spawn closure is COMPUTED, not enumerated:
    # the module whose jnp constant broke PR 19's fleet spawn is in
    # it, and so is everything the closure walks through — a new
    # module joining the entry import graph is covered automatically.
    closure = import_closure(ENTRY_BINARY, REPO_ROOT)
    assert "tensor2robot_tpu.train_eval" in closure
    assert ("tensor2robot_tpu.preprocessors.image_transformations"
            in closure)
    assert "tensor2robot_tpu" in closure  # ancestor packages execute

  def test_import_closure_empty_off_repo(self, tmp_path):
    # Fixture trees must not inherit repo facts.
    assert import_closure(ENTRY_BINARY, str(tmp_path)) == set()


# ---------------------------------------------------------------------------
# Fleet RPC wire contract: FLT501/FLT502 (ISSUE 20)
# ---------------------------------------------------------------------------

class TestFleetRules:

  DISPATCHER = """
      DISCONNECT_METHOD = "__disconnect__"


      class Handler:

        def handle(self, method, payload, ctx):
          if method == "ping":
            return 1
          if method in ("alpha", "beta"):
            return 2
          if method == DISCONNECT_METHOD:
            return None
          raise ValueError(method)
  """

  def test_flt501_unhandled_method(self, tmp_path):
    _write(tmp_path, "mod.py", self.DISPATCHER + """
      def go(client):
        client.call("pong", {})
        client.call_once("alpha")
        client.call("ping")
        client.call("beta")
    """)
    found = run_fleet_rules([str(tmp_path)], str(tmp_path))
    assert [f.rule for f in found] == ["FLT501"]
    assert "'pong'" in found[0].message
    assert found[0].scope == "go"

  def test_flt501_negative_all_handled(self, tmp_path):
    _write(tmp_path, "mod.py", self.DISPATCHER + """
      def go(client):
        client.call("ping")
        client.call_once("alpha", {})
        client.call("beta")
    """)
    assert run_fleet_rules([str(tmp_path)], str(tmp_path)) == []

  def test_flt501_literal_through_forwarder(self, tmp_path):
    # The orchestrator pattern: `_aux_call(entry, "m", ...)` forwards
    # its method parameter into `client.call` — literals at the
    # forwarder's call sites are wire sends.
    _write(tmp_path, "mod.py", self.DISPATCHER + """
      class Fleet:

        def _aux_call(self, entry, method, payload=None):
          client = self._clients[entry["name"]]
          return client.call(method, payload)

        def go(self, entry):
          self._aux_call(entry, "ping")
          self._aux_call(entry, "tpyo")
          self._aux_call(entry, "alpha")
          self._aux_call(entry, "beta")
    """)
    found = run_fleet_rules([str(tmp_path)], str(tmp_path))
    assert [f.rule for f in found] == ["FLT501"]
    assert "'tpyo'" in found[0].message

  def test_flt502_dead_handler_and_disconnect_exempt(self, tmp_path):
    _write(tmp_path, "mod.py", self.DISPATCHER + """
      def go(client):
        client.call("ping")
        client.call("alpha")
    """)
    found = run_fleet_rules([str(tmp_path)], str(tmp_path))
    # "beta" is handled but never sent; the server-synthesized
    # disconnect method must NOT count as dead.
    assert [f.rule for f in found] == ["FLT502"]
    assert "'beta'" in found[0].message
    assert found[0].scope == "Handler.handle"

  def test_silent_without_dispatchers_in_scope(self, tmp_path):
    # A --paths subset with no handle() in sight must not spray
    # FLT501 over every send.
    _write(tmp_path, "mod.py", """
        def go(client):
          client.call("anything", {})
    """)
    assert run_fleet_rules([str(tmp_path)], str(tmp_path)) == []

  def test_silent_without_sends_in_scope(self, tmp_path):
    # ...and a handler-only scope must not report every arm dead.
    _write(tmp_path, "mod.py", self.DISPATCHER)
    assert run_fleet_rules([str(tmp_path)], str(tmp_path)) == []

  def test_repo_wire_contract_closes(self):
    # The live contract: every literal send in fleet/ + serving/
    # resolves against the dispatcher union, and no arm is dead —
    # with zero pragmas.
    found = run_fleet_rules(
        [os.path.join(REPO_ROOT, "tensor2robot_tpu/fleet"),
         os.path.join(REPO_ROOT, "tensor2robot_tpu/serving")],
        REPO_ROOT)
    assert found == []


# ---------------------------------------------------------------------------
# Distributed SPMD correctness: SPMD601/JAX205 (ISSUE 20)
# ---------------------------------------------------------------------------

class TestSpmdRules:

  def test_spmd601_chief_gated_save_transitive(self, tmp_path):
    # The reverted PR-19 bug form: a chief-gated call reaching the
    # orbax writer's collective save one hop down — rank 0 wedges in
    # `sync_global_processes` while peers train on.
    _write(tmp_path, "bug.py", """
        import jax

        def _flush(writer, state):
          writer.save(0, state)

        def train(writer, state):
          if jax.process_index() == 0:
            _flush(writer, state)
    """)
    found = run_spmd_rules([str(tmp_path)], str(tmp_path))
    assert [f.rule for f in found] == ["SPMD601"]
    assert "writer.save" in found[0].message
    assert found[0].scope == "train"

  def test_spmd601_direct_collective_under_assigned_gate(
      self, tmp_path):
    # `chief = jax.process_index() == 0` makes `chief` a gate name;
    # the collective sits directly in the gated branch.
    _write(tmp_path, "bug.py", """
        import jax
        from jax.experimental import multihost_utils

        def train(state):
          flag = jax.process_index() == 0
          if flag:
            multihost_utils.sync_global_processes("save")
    """)
    found = run_spmd_rules([str(tmp_path)], str(tmp_path))
    assert [f.rule for f in found] == ["SPMD601"]
    assert "sync_global_processes" in found[0].message

  def test_spmd601_negative_every_rank_saves(self, tmp_path):
    # HEAD's corrected pattern: the save is unconditional, the chief
    # gate guards only host-side logging.
    _write(tmp_path, "good.py", """
        import jax

        def train(writer, logger, state, step):
          chief = jax.process_index() == 0
          if chief:
            logger.write("train", step)
          writer.save(step, state)
          writer.close()
    """)
    assert run_spmd_rules([str(tmp_path)], str(tmp_path)) == []

  def test_spmd601_rank_raise_guard_clean(self, tmp_path):
    _write(tmp_path, "mod.py", """
        def plan(rank, world_size):
          if not 0 <= rank < world_size:
            raise ValueError(f"bad rank {rank}")
          return {"role": "learner" if rank == 0 else "peer"}
    """)
    assert run_spmd_rules([str(tmp_path)], str(tmp_path)) == []

  def test_train_qtopt_head_clean_with_zero_pragmas(self):
    # The acceptance pin: the every-rank-calls-save loop passes the
    # rule on merit, not via suppression.
    path = os.path.join(
        REPO_ROOT, "tensor2robot_tpu/research/qtopt/train_qtopt.py")
    assert run_spmd_rules([path], REPO_ROOT) == []
    with open(path, encoding="utf-8") as f:
      assert "disable=SPMD601" not in f.read()

  def test_jax205_module_level_jnp_constant(self, tmp_path):
    _write(tmp_path, "consts.py", """
        import jax.numpy as jnp

        YIQ = jnp.array([[0.299, 0.587, 0.114]])
    """)
    found = run_spmd_rules([str(tmp_path)], str(tmp_path))
    assert [f.rule for f in found] == ["JAX205"]
    assert "jnp.array" in found[0].message

  def test_jax205_transitive_module_level_call(self, tmp_path):
    _write(tmp_path, "table.py", """
        import jax.numpy as jnp

        def _build():
          return jnp.eye(3)

        TABLE = _build()
    """)
    found = run_spmd_rules([str(tmp_path)], str(tmp_path))
    assert [f.rule for f in found] == ["JAX205"]
    assert "_build" in found[0].message

  def test_jax205_negatives(self, tmp_path):
    # All the module-level shapes that must NOT flag: numpy
    # constants, jnp inside functions, pytree registration, config
    # flips, lazy jit wrapping, and the __main__ guard (spawn
    # children import under __mp_main__, so it never runs).
    _write(tmp_path, "ok.py", """
        import jax
        import jax.numpy as jnp
        import numpy as np

        RGB = np.array([1.0, 2.0])
        jax.tree_util.register_pytree_node(dict, id, id)
        jax.config.update("jax_enable_x64", False)

        def compute(x):
          return jnp.asarray(x)

        compute_fast = jax.jit(compute)

        if __name__ == "__main__":
          print(compute(jnp.ones(2)))
    """)
    assert run_spmd_rules([str(tmp_path)], str(tmp_path)) == []

  def test_jax205_entry_closure_escalation(self, tmp_path):
    # A seeded tree with its own entry binary: the hazard module is
    # in the computed spawn closure, so the finding carries the
    # jax.distributed escalation.
    pkg = tmp_path / "tensor2robot_tpu"
    (pkg / "bin").mkdir(parents=True)
    (pkg / "__init__.py").write_text("")
    (pkg / "bin" / "__init__.py").write_text("")
    (pkg / "bin" / "run_t2r_trainer.py").write_text(
        "from tensor2robot_tpu import consts\n")
    (pkg / "consts.py").write_text(
        "import jax.numpy as jnp\nYIQ = jnp.array([1.0])\n")
    found = run_spmd_rules([str(tmp_path)], str(tmp_path))
    assert [f.rule for f in found] == ["JAX205"]
    assert "spawn import closure" in found[0].message

  def test_repo_spmd_clean(self):
    # The whole package passes both rules with the baseline EMPTY.
    found = run_spmd_rules(
        [os.path.join(REPO_ROOT, "tensor2robot_tpu")], REPO_ROOT)
    assert found == []

  def test_pragma_suppresses_new_families(self, tmp_path):
    _write(tmp_path, "mod.py", """
        import jax.numpy as jnp

        # count-gated uniform branch, documented:
        # t2rcheck: disable=JAX205
        YIQ = jnp.array([1.0])
    """)
    found = run_spmd_rules([str(tmp_path)], str(tmp_path))
    active, suppressed = findings_lib.apply_pragmas(
        found, str(tmp_path))
    assert active == [] and [f.rule for f in suppressed] == ["JAX205"]

  def test_fingerprints_survive_witness_line_motion(self):
    # Witness chains embed "line N of file" — the fingerprint
    # normalizer must strip the digits so baselines survive motion.
    a = Finding("SPMD601", "a.py", 9, "train",
                "reaches `writer.save` (line 5 of a.py)")
    b = Finding("SPMD601", "a.py", 40, "train",
                "reaches `writer.save` (line 88 of a.py)")
    assert a.fingerprint() == b.fingerprint()

  def test_cli_json_carries_new_rule_ids(self, tmp_path):
    _write(tmp_path, "bad.py", """
        import jax

        DISCONNECT_METHOD = "__disconnect__"

        class H:
          def handle(self, method, payload, ctx):
            if method == "ping":
              return 1
            raise ValueError(method)

        def go(client):
          client.call("pong")

        def train(writer, state):
          if jax.process_index() == 0:
            writer.save(0, state)
    """)
    result = subprocess.run(
        [sys.executable, "-m", "tensor2robot_tpu.analysis",
         "--checks", "fleet,spmd", "--paths", str(tmp_path),
         "--root", str(tmp_path), "--json"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120)
    assert result.returncode == 1, result.stdout + result.stderr
    payload = json.loads(result.stdout)
    rules = {f["rule"] for f in payload["new"]}
    assert {"FLT501", "FLT502", "SPMD601"} <= rules

  def test_new_families_in_defaults_and_catalog(self):
    from tensor2robot_tpu.analysis import cli

    parser = cli.build_parser()
    defaults = parser.get_default("checks")
    assert "fleet" in defaults and "spmd" in defaults
    assert cli._FLEET_PATHS == ("tensor2robot_tpu/fleet",
                                "tensor2robot_tpu/serving")
    for rule in ("FLT501", "FLT502", "SPMD601", "JAX205"):
      assert rule in RULE_CATALOG
    assert "fleet" in findings_lib.FAMILIES
    assert "spmd" in findings_lib.FAMILIES


# ---------------------------------------------------------------------------
# Pragmas + baseline mechanics
# ---------------------------------------------------------------------------

class TestSuppression:

  def test_inline_pragma_same_line_and_line_above(self):
    index = PragmaIndex(textwrap.dedent("""
        x = 1
        y = queue.get()  # t2rcheck: disable=CON302
        # t2rcheck: disable=JAX201,JAX202
        z = arr.item()
    """))
    assert index.suppresses("CON302", 3)
    assert index.suppresses("JAX201", 5)
    assert index.suppresses("JAX202", 5)
    assert not index.suppresses("CON302", 5)
    assert not index.suppresses("CON302", 2)

  def test_file_level_pragma(self):
    index = PragmaIndex("# t2rcheck: disable-file=CON301\ncode = 1\n")
    assert index.suppresses("CON301", 999)
    assert not index.suppresses("CON302", 999)

  def test_pragma_suppresses_end_to_end(self, tmp_path):
    code = """
        import queue


        class Consumer:

          def __init__(self):
            self._queue = queue.Queue(maxsize=2)

          def blocking_by_design(self):
            # callers own the liveness contract here
            # t2rcheck: disable=CON302
            return self._queue.get()
    """
    _write(tmp_path, "mod.py", code)
    found = run_concurrency_rules([str(tmp_path)], str(tmp_path))
    active, suppressed = findings_lib.apply_pragmas(
        found, str(tmp_path))
    assert active == [] and len(suppressed) == 1

  def test_fingerprint_survives_line_motion(self):
    a = Finding("CON302", "x/y.py", 10, "C.m", "blocking get")
    b = Finding("CON302", "x/y.py", 99, "C.m", "blocking get")
    c = Finding("CON302", "x/OTHER.py", 10, "C.m", "blocking get")
    assert a.fingerprint() == b.fingerprint()
    assert a.fingerprint() != c.fingerprint()

  def test_baseline_roundtrip_and_split(self, tmp_path):
    old = Finding("CON302", "a.py", 5, "f", "legacy debt")
    new = Finding("CON301", "b.py", 9, "g", "fresh bug")
    path = str(tmp_path / "baseline.json")
    Baseline().write(path, [old])
    loaded = Baseline.load(path)
    fresh, known = loaded.split([old, new])
    assert [f.rule for f in fresh] == ["CON301"]
    assert [f.rule for f in known] == ["CON302"]

  def test_committed_baseline_is_empty(self):
    # The zero-findings contract of ISSUE 5: debt never accumulates
    # silently — the committed ledger stays empty.
    baseline = Baseline.load(
        os.path.join(REPO_ROOT, findings_lib.DEFAULT_BASELINE))
    assert baseline.fingerprints == set()


# ---------------------------------------------------------------------------
# CLI contract
# ---------------------------------------------------------------------------

class TestCli:

  def test_ast_path_never_imports_jax_and_repo_is_clean(self):
    # BOTH halves of the lint.sh stage-1 contract in one subprocess:
    # the repo lints clean, and linting it did not import jax.
    code = (
        "import sys\n"
        "from tensor2robot_tpu.analysis.cli import main\n"
        "rc = main(['--checks', 'jax,concurrency,imports,obs,"
        "fleet,spmd'])\n"
        "assert 'jax' not in sys.modules, 'AST path imported jax'\n"
        "sys.exit(rc)\n")
    result = subprocess.run(
        [sys.executable, "-c", code], cwd=REPO_ROOT,
        capture_output=True, text=True, timeout=120)
    assert result.returncode == 0, result.stdout + result.stderr

  def test_cli_exits_nonzero_on_seeded_violation(self, tmp_path):
    _write(tmp_path, "bad.py", """
        import queue


        class Consumer:

          def __init__(self):
            self._queue = queue.Queue(maxsize=2)

          def bad(self):
            return self._queue.get()
    """)
    result = subprocess.run(
        [sys.executable, "-m", "tensor2robot_tpu.analysis",
         "--checks", "concurrency", "--paths", str(tmp_path),
         "--root", str(tmp_path)],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120)
    assert result.returncode == 1, result.stdout + result.stderr
    assert "CON302" in result.stdout

  def test_cli_exits_nonzero_on_seeded_jax_violation(self, tmp_path):
    _write(tmp_path, "bad.py", """
        import time
        import jax

        @jax.jit
        def step(x):
          return x * time.time()
    """)
    result = subprocess.run(
        [sys.executable, "-m", "tensor2robot_tpu.analysis",
         "--checks", "jax", "--paths", str(tmp_path),
         "--root", str(tmp_path)],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120)
    assert result.returncode == 1, result.stdout + result.stderr
    assert "JAX202" in result.stdout

  def test_cli_exits_nonzero_on_seeded_import_violation(self, tmp_path):
    pkg = tmp_path / "tensor2robot_tpu"
    for sub in ("data", "config"):
      (pkg / sub).mkdir(parents=True)
      (pkg / sub / "__init__.py").write_text("")
    (pkg / "__init__.py").write_text("")
    (pkg / "config" / "ginlite.py").write_text("x = 1\n")
    (pkg / "data" / "shm_ring.py").write_text("import jax\n")
    (pkg / "data" / "plane.py").write_text("")
    result = subprocess.run(
        [sys.executable, "-m", "tensor2robot_tpu.analysis",
         "--checks", "imports", "--root", str(tmp_path)],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120)
    assert result.returncode == 1, result.stdout + result.stderr
    assert "IMP401" in result.stdout

  def test_cli_json_output(self, tmp_path):
    _write(tmp_path, "bad.py", """
        import queue
        q = queue.Queue(maxsize=1)
        item = q.get()
    """)
    result = subprocess.run(
        [sys.executable, "-m", "tensor2robot_tpu.analysis",
         "--checks", "concurrency", "--paths", str(tmp_path),
         "--root", str(tmp_path), "--json"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120)
    payload = json.loads(result.stdout)
    assert result.returncode == 1
    assert payload["new"][0]["rule"] == "CON302"

  def test_write_baseline_then_clean(self, tmp_path):
    _write(tmp_path, "bad.py", """
        import queue
        q = queue.Queue(maxsize=1)
        item = q.get()
    """)
    baseline = str(tmp_path / "baseline.json")
    common = [sys.executable, "-m", "tensor2robot_tpu.analysis",
              "--checks", "concurrency", "--paths", str(tmp_path),
              "--root", str(tmp_path), "--baseline", baseline]
    first = subprocess.run(common + ["--write-baseline"],
                           cwd=REPO_ROOT, capture_output=True,
                           text=True, timeout=120)
    assert first.returncode == 0, first.stdout + first.stderr
    second = subprocess.run(common, cwd=REPO_ROOT,
                            capture_output=True, text=True,
                            timeout=120)
    assert second.returncode == 0, second.stdout + second.stderr
    assert "1 baselined" in second.stdout

  def test_list_rules_covers_catalog(self):
    result = subprocess.run(
        [sys.executable, "-m", "tensor2robot_tpu.analysis",
         "--list-rules"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120)
    assert result.returncode == 0
    for rule in RULE_CATALOG:
      assert rule in result.stdout


# ---------------------------------------------------------------------------
# Observability hygiene: OBS501 metric-catalog lint (ISSUE 15)
# ---------------------------------------------------------------------------

class TestObsRules:

  CATALOG = """\
  # catalog fixture
  | `replay.adds` | counter | rows |
  | `fleet.rpc.{timeouts,retries}` | counter | ledger |
  | `serving.<tenant>.request_ms` | histogram | latency |
  | prose mentioning a bare `<rest>` placeholder |
  """

  def _run(self, tmp_path, code, catalog=None):
    from tensor2robot_tpu.analysis.obs_rules import run_obs_rules
    _write(tmp_path, "mod.py", code)
    catalog_path = _write(tmp_path, "CATALOG.md",
                          catalog if catalog is not None
                          else self.CATALOG)
    return run_obs_rules([str(tmp_path / "mod.py")], str(tmp_path),
                         catalog_path=catalog_path)

  def test_undocumented_literal_positive(self, tmp_path):
    found = self._run(tmp_path, """
        from tensor2robot_tpu.telemetry import metrics as tmetrics
        tmetrics.counter("replay.undocumented_total").inc()
        """)
    assert _rules(found) == {"OBS501"}
    assert "replay.undocumented_total" in found[0].message

  def test_documented_brace_and_placeholder_negative(self, tmp_path):
    found = self._run(tmp_path, """
        from tensor2robot_tpu.telemetry import metrics as tmetrics
        tmetrics.counter("replay.adds").inc()
        tmetrics.counter("fleet.rpc.retries").inc()
        tmetrics.histogram("serving.tenant_a.request_ms").observe(1.0)
        """)
    assert found == [], [f.render() for f in found]

  def test_bare_placeholder_never_blinds_the_rule(self, tmp_path):
    # The fixture catalog contains a bare `<rest>` in prose; it must
    # NOT compile into a match-everything wildcard.
    found = self._run(tmp_path, """
        from tensor2robot_tpu.telemetry import metrics as tmetrics
        tmetrics.gauge("anything.at_all").set(1.0)
        """)
    assert _rules(found) == {"OBS501"}

  def test_undotted_helper_strings_ignored(self, tmp_path):
    found = self._run(tmp_path, """
        class Thing:
          def counter(self, name):
            return name
        Thing().counter("not_a_metric")
        """)
    assert found == []

  def test_missing_catalog_is_a_finding(self, tmp_path):
    from tensor2robot_tpu.analysis.obs_rules import run_obs_rules
    _write(tmp_path, "mod.py", "x = 1\n")
    found = run_obs_rules([str(tmp_path)], str(tmp_path),
                          catalog_path=str(tmp_path / "missing.md"))
    assert _rules(found) == {"OBS501"}
    assert "catalog missing" in found[0].message

  def test_repo_is_clean(self):
    # The shipped contract: every literal metric name in the package
    # is documented in docs/OBSERVABILITY.md (baseline stays EMPTY).
    from tensor2robot_tpu.analysis.obs_rules import run_obs_rules
    package = os.path.join(REPO_ROOT, "tensor2robot_tpu")
    found = run_obs_rules([package], REPO_ROOT)
    assert found == [], [f.render() for f in found]


# ---------------------------------------------------------------------------
# Gin static validation (imports the framework: the one heavy family)
# ---------------------------------------------------------------------------

class TestGinValidation:

  def test_all_shipped_configs_validate(self):
    # The tier-1 guarantee of ISSUE 5: every shipped experiment config
    # resolves every binding/ref/macro against real signatures.
    from tensor2robot_tpu.analysis.gin_check import (
        discover_configs,
        run_gin_rules,
    )
    package = os.path.join(REPO_ROOT, "tensor2robot_tpu")
    configs = discover_configs([package])
    assert len(configs) == 20, configs  # re-pin when shipping new ones
    found = run_gin_rules([package], REPO_ROOT)
    assert found == [], [f.render() for f in found]

  def test_typoed_param_rejected(self, tmp_path):
    from tensor2robot_tpu.analysis.gin_check import run_gin_rules
    (tmp_path / "typo.gin").write_text(
        "PoseEnvRegressionModel.image_sie = 64\n")
    found = run_gin_rules([str(tmp_path)], str(tmp_path))
    assert [f.rule for f in found] == ["GIN102"]
    assert "image_sie" in found[0].message

  def test_kwargs_forwarding_follows_mro(self, tmp_path):
    # The param must be accepted when ANY class up the chain takes it
    # (kwargs forwarding) and rejected when none does.
    from tensor2robot_tpu.analysis.gin_check import run_gin_rules
    (tmp_path / "mro.gin").write_text(
        "PoseEnvRegressionModel.aux_loss_weight = 0.5\n")
    assert run_gin_rules([str(tmp_path)], str(tmp_path)) == []

  def test_unknown_configurable_and_ref(self, tmp_path):
    from tensor2robot_tpu.analysis.gin_check import run_gin_rules
    (tmp_path / "unknown.gin").write_text(
        "NoSuchThing.param = 1\n"
        "train_eval_model.model = @AlsoMissing()\n")
    rules = {f.rule for f in
             run_gin_rules([str(tmp_path)], str(tmp_path))}
    assert rules == {"GIN101", "GIN104"}

  def test_dangling_macro_and_defined_macro(self, tmp_path):
    from tensor2robot_tpu.analysis.gin_check import run_gin_rules
    (tmp_path / "macros.gin").write_text(
        "BATCH = 64\n"
        "train_eval_model.batch_size = %BATCH\n"
        "train_eval_model.eval_steps = %MISSING\n")
    found = run_gin_rules([str(tmp_path)], str(tmp_path))
    assert [f.rule for f in found] == ["GIN103"]
    assert "MISSING" in found[0].message

  def test_denylisted_param_and_parse_error(self, tmp_path):
    from tensor2robot_tpu import config as gin
    from tensor2robot_tpu.analysis.gin_check import run_gin_rules

    @gin.configurable("analysis_denylist_probe", denylist=["secret"])
    def probe(secret=1, ok=2):  # noqa: F841 - registered, not called
      return secret, ok

    (tmp_path / "deny.gin").write_text(
        "analysis_denylist_probe.secret = 3\n"
        "analysis_denylist_probe.ok = 4\n"
        "???not a gin statement\n")
    rules = [f.rule for f in
             run_gin_rules([str(tmp_path)], str(tmp_path))]
    assert "GIN105" in rules, rules   # denylisted `secret`
    assert "GIN107" in rules, rules   # the unparseable line
    assert len(rules) == 2, rules     # `ok` binds cleanly

  def test_missing_include_flagged(self, tmp_path):
    from tensor2robot_tpu.analysis.gin_check import run_gin_rules
    (tmp_path / "inc.gin").write_text("include 'nope/missing.gin'\n")
    assert [f.rule for f in
            run_gin_rules([str(tmp_path)], str(tmp_path))] == ["GIN106"]

  def test_include_closure_defines_macros(self, tmp_path):
    # A macro defined in an INCLUDED file resolves for the includer —
    # gin's call-time macro semantics, order-free.
    from tensor2robot_tpu.analysis.gin_check import run_gin_rules
    (tmp_path / "base.gin").write_text("BATCH = 32\n")
    (tmp_path / "top.gin").write_text(
        "train_eval_model.batch_size = %BATCH\n"
        f"include '{tmp_path / 'base.gin'}'\n")
    found = [f for f in run_gin_rules([str(tmp_path)], str(tmp_path))]
    assert found == [], [f.render() for f in found]

  def test_validation_does_not_mutate_registry(self):
    from tensor2robot_tpu import config as gin
    from tensor2robot_tpu.analysis.gin_check import validate_config_file
    gin.clear_config()
    config = os.path.join(
        REPO_ROOT, "tensor2robot_tpu", "research", "pose_env",
        "configs", "train_pose_env.gin")
    validate_config_file(config, REPO_ROOT)
    assert gin.config_str() == ""  # validate-only: no bindings landed


class TestShardingRulesCoverage:
  """GIN108 (ISSUE 12): every sharding rules table matches every
  param of its model family — unmatched-param and dead-regex
  findings; the shipped tables stay clean (baseline stays empty)."""

  def test_repo_family_tables_produce_no_findings(self):
    from tensor2robot_tpu.analysis.gin_check import (
        run_sharding_rules_checks,
    )
    found = run_sharding_rules_checks()
    assert found == [], [f.render() for f in found]

  def test_unmatched_param_flagged(self):
    import numpy as np
    from tensor2robot_tpu.analysis.gin_check import (
        run_sharding_rules_checks,
    )
    from tensor2robot_tpu.parallel import Replicate
    families = {"fixture": (
        ((r"/kernel$", Replicate()),),
        [{"layer": {"kernel": np.zeros((4,)),
                    "bias": np.zeros((4,))}}])}
    found = run_sharding_rules_checks(families)
    assert [f.rule for f in found] == ["GIN108"]
    assert "layer/bias" in found[0].message
    assert "matches no sharding rule" in found[0].message

  def test_dead_regex_flagged(self):
    import numpy as np
    from tensor2robot_tpu.analysis.gin_check import (
        run_sharding_rules_checks,
    )
    from tensor2robot_tpu.parallel import Replicate, ShardLargest
    families = {"fixture": (
        ((r"/stale_name$", ShardLargest()),
         (r".*", Replicate())),
        [{"layer": {"kernel": np.zeros((4,))}}])}
    found = run_sharding_rules_checks(families)
    assert [f.rule for f in found] == ["GIN108"]
    assert "stale_name" in found[0].message
    assert "dead regex" in found[0].message

  def test_final_catchall_default_is_exempt(self):
    """A fully-covering table keeps its safety-net default without a
    dead-regex finding — only NON-final dead rules flag."""
    import numpy as np
    from tensor2robot_tpu.analysis.gin_check import (
        run_sharding_rules_checks,
    )
    from tensor2robot_tpu.parallel import Replicate, ShardLargest
    families = {"fixture": (
        ((r"/kernel$", ShardLargest()),
         (r".*", Replicate())),
        [{"layer": {"kernel": np.zeros((4,))}}])}
    assert run_sharding_rules_checks(families) == []

  def test_broken_template_does_not_blind_other_families(self,
                                                         monkeypatch):
    """One family whose template construction fails must report ITS
    finding and still surface coverage findings for the others."""
    import numpy as np
    from tensor2robot_tpu.analysis.gin_check import (
        run_sharding_rules_checks,
    )
    from tensor2robot_tpu.parallel import Replicate, rules as rules_lib

    fake_rules = {"broken": ((r".*", Replicate()),),
                  "gappy": ((r"/kernel$", Replicate()),)}
    monkeypatch.setattr(rules_lib, "FAMILY_RULES", fake_rules)
    monkeypatch.setattr(rules_lib, "family_rules",
                        lambda name: fake_rules[name])

    def templates(name):
      if name == "broken":
        raise ImportError("no such module")
      return [{"layer": {"kernel": np.zeros((4,)),
                         "bias": np.zeros((4,))}}]

    monkeypatch.setattr(rules_lib, "family_param_templates", templates)
    found = run_sharding_rules_checks()
    assert [f.rule for f in found] == ["GIN108", "GIN108"]
    assert "template construction failed" in found[0].message
    assert "layer/bias" in found[1].message  # 'gappy' still checked

  def test_gin_family_runs_the_coverage_check(self, tmp_path):
    """GIN108 rides `run_gin_rules` — the lint entry point scripts/
    lint.sh and tier-1 invoke."""
    from tensor2robot_tpu.analysis.gin_check import run_gin_rules
    found = run_gin_rules([str(tmp_path)], str(tmp_path))
    assert [f for f in found if f.rule == "GIN108"] == []
