"""Tests for the VRGripper / Watch-Try-Learn research family.

Mirrors test_qtopt.py's depth: env sanity, model train steps,
episode→transition munging, meta-BC (MAML + SNAIL), WTL, and an
end-to-end collect→train→predict→closed-loop-eval run.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensor2robot_tpu.data import (
    Mode,
    RandomInputGenerator,
    TFRecordEpisodeInputGenerator,
)
from tensor2robot_tpu.meta_learning import EpisodeMetaInputGenerator
from tensor2robot_tpu.research.vrgripper import (
    TransitionInputGenerator,
    VRGripperEnv,
    VRGripperMAMLModel,
    VRGripperRegressionModel,
    VRGripperSNAILModel,
    VRGripperWTLModel,
    collect_demo_episodes,
    collect_expert_episode,
    episode_batch_to_transitions,
    evaluate_gripper_policy,
    sample_wtl_meta_batch,
)
from tensor2robot_tpu.specs import TensorSpecStruct, make_random_tensors

IMG = 24  # small images keep CPU-mesh tests fast


def fast_adam(lr=3e-3):
  import functools
  from tensor2robot_tpu.models import create_optimizer
  return functools.partial(create_optimizer, learning_rate=lr)


def tiny_bc_model(**kwargs):
  kwargs.setdefault("image_size", IMG)
  kwargs.setdefault("filters", (8, 16))
  kwargs.setdefault("embedding_size", 32)
  kwargs.setdefault("hidden_sizes", (32,))
  kwargs.setdefault("create_optimizer_fn", fast_adam())
  return VRGripperRegressionModel(**kwargs)


def random_batch(model, batch=4, seed=0):
  f = make_random_tensors(model.get_feature_specification(Mode.TRAIN),
                          batch_size=batch, seed=seed)
  l = make_random_tensors(model.get_label_specification(Mode.TRAIN),
                          batch_size=batch, seed=seed + 1)
  dev = lambda t: jax.tree_util.tree_map(jnp.asarray, t)
  return dev(f), dev(l)


class TestVRGripperEnv:

  def test_expert_succeeds(self):
    env = VRGripperEnv(image_size=IMG, seed=0)
    successes = []
    for _ in range(10):
      obs = env.reset()
      done = False
      while not done:
        obs, _, done = env.step(env.expert_action())
      successes.append(env.success())
    assert np.mean(successes) > 0.9

  def test_episode_structure(self):
    env = VRGripperEnv(image_size=IMG, seed=1)
    ep = collect_expert_episode(env)
    t = len(ep["action"])
    assert 1 <= t <= env.max_steps
    assert ep["image"].shape == (t, IMG, IMG, 3)
    assert ep["gripper_pose"].shape == (t, 3)
    assert ep["reward"].shape == (t, 1)
    # Terminal reward reflects the expert's success.
    assert ep["reward"][-1, 0] == 1.0

  def test_offset_changes_expert_target(self):
    env = VRGripperEnv(image_size=IMG, seed=2)
    env.reset(task_offset=np.array([0.2, 0.0], np.float32))
    target_with = env.target.copy()
    env._offset = np.zeros(2, np.float32)
    assert np.linalg.norm(target_with - env.target) > 0.1


class TestVRGripperBCModels:

  def test_mse_train_step(self):
    model = tiny_bc_model()
    state = model.create_train_state(jax.random.PRNGKey(0))
    f, l = random_batch(model)
    state, metrics = jax.jit(model.train_step)(
        state, f, l, jax.random.PRNGKey(0))
    assert np.isfinite(float(metrics["loss"]))
    assert "mse" in metrics

  def test_mdn_train_step_and_sampling(self):
    model = tiny_bc_model(num_mixture_components=3)
    state = model.create_train_state(jax.random.PRNGKey(0))
    f, l = random_batch(model)
    state, metrics = jax.jit(model.train_step)(
        state, f, l, jax.random.PRNGKey(0))
    assert "nll" in metrics and np.isfinite(float(metrics["loss"]))
    outputs = model.predict_step(state, f)
    assert outputs["action"].shape == (4, 3)
    sampled = model.sample_action(state, f, jax.random.PRNGKey(1))
    assert sampled.shape == (4, 3)
    # Stochastic samples differ from the greedy mode action.
    assert not np.allclose(np.asarray(sampled),
                           np.asarray(outputs["action"]))

  @pytest.mark.slow
  def test_bc_learns_expert(self):
    # Clone the scripted expert from its own demos; the policy must
    # beat the do-nothing baseline by a wide margin on action error.
    model = tiny_bc_model()
    state = model.create_train_state(jax.random.PRNGKey(0))
    env = VRGripperEnv(image_size=IMG, seed=0)
    rng = np.random.default_rng(0)
    eps = [collect_expert_episode(env, rng=rng) for _ in range(24)]
    obs = np.concatenate([e["image"] for e in eps])
    poses = np.concatenate([e["gripper_pose"] for e in eps])
    acts = np.concatenate([e["action"] for e in eps])
    step = jax.jit(model.train_step)
    n = len(acts)
    losses = []
    for i in range(250):
      idx = rng.choice(n, 32)
      f = TensorSpecStruct.from_flat_dict(
          {"image": jnp.asarray(obs[idx]),
           "gripper_pose": jnp.asarray(poses[idx])})
      l = TensorSpecStruct.from_flat_dict(
          {"action": jnp.asarray(acts[idx])})
      state, metrics = step(state, f, l, jax.random.PRNGKey(i))
      losses.append(float(metrics["loss"]))
    # Predicting the dataset-mean action scores ≈ E[a²] ≈ 0.69 here;
    # a working clone must land far below it.
    assert np.mean(losses[-10:]) < 0.25, losses[-10:]


class TestEpisodeToTransitions:

  def test_masks_padding(self):
    features = TensorSpecStruct.from_flat_dict({
        "x": np.arange(24, dtype=np.float32).reshape(2, 6, 2),
        "sequence_length": np.array([3, 5], np.int32)})
    labels = TensorSpecStruct.from_flat_dict({
        "a": np.ones((2, 6, 1), np.float32)})
    f, l = episode_batch_to_transitions(
        features, labels, sequence_keys=frozenset({"x", "a"}))
    assert f["x"].shape == (8, 2)  # 3 + 5 real steps
    assert l["a"].shape == (8, 1)
    np.testing.assert_array_equal(f["x"][:3],
                                  np.arange(6).reshape(3, 2))

  def test_context_repeated(self):
    features = TensorSpecStruct.from_flat_dict({
        "x": np.zeros((2, 3, 2), np.float32),
        "task": np.array([[1.0], [2.0]], np.float32)})
    f, _ = episode_batch_to_transitions(
        features, None, sequence_keys=frozenset({"x"}))
    np.testing.assert_array_equal(f["task"].reshape(-1),
                                  [1, 1, 1, 2, 2, 2])

  def test_missing_sequence_keys_warns(self):
    """The rank-heuristic time-axis fallback must be loud: a [B, D]
    context key ahead of the sequence keys silently flips the guess."""
    import warnings as warnings_lib

    features = TensorSpecStruct.from_flat_dict({
        "x": np.zeros((2, 3, 2), np.float32)})
    with pytest.warns(RuntimeWarning, match="sequence_keys"):
      episode_batch_to_transitions(features, None)
    # Spec-derived keys: silent.
    with warnings_lib.catch_warnings():
      warnings_lib.simplefilter("error")
      episode_batch_to_transitions(
          features, None, sequence_keys=frozenset({"x"}))

  def test_generator_rebatches(self, tmp_path):
    path = str(tmp_path / "demos.tfrecord")
    collect_demo_episodes(path, num_episodes=12, image_size=IMG,
                          seed=0)
    model = tiny_bc_model()
    gen = TransitionInputGenerator(
        TFRecordEpisodeInputGenerator(
            file_patterns=path, sequence_length=12, shuffle=False),
        batch_size=16, seed=0)
    gen.set_specification_from_model(model, Mode.TRAIN)
    it = gen.create_dataset(Mode.TRAIN)
    for _ in range(3):
      f, l = next(it)
      assert f["image"].shape == (16, IMG, IMG, 3)
      assert f["gripper_pose"].shape == (16, 3)
      assert l["action"].shape == (16, 3)


class TestMetaBCModels:

  def _meta_batch(self, model, batch=2, seed=0):
    f = make_random_tensors(
        model.preprocessor.get_in_feature_specification(Mode.TRAIN),
        batch_size=batch, seed=seed)
    l = make_random_tensors(
        model.preprocessor.get_in_label_specification(Mode.TRAIN),
        batch_size=batch, seed=seed + 1)
    dev = lambda t: jax.tree_util.tree_map(jnp.asarray, t)
    return dev(f), dev(l)

  def test_maml_train_step(self):
    model = VRGripperMAMLModel(
        image_size=IMG, filters=(8,), embedding_size=16,
        hidden_sizes=(16,), num_condition_samples_per_task=2,
        num_inference_samples_per_task=2)
    state = model.create_train_state(jax.random.PRNGKey(0))
    f, l = self._meta_batch(model)
    state, metrics = jax.jit(model.train_step)(
        state, f, l, jax.random.PRNGKey(0))
    assert np.isfinite(float(metrics["loss"]))
    assert "post_adaptation_loss" in metrics

  @pytest.mark.slow
  def test_snail_train_step_and_predict(self):
    model = VRGripperSNAILModel(
        image_size=IMG, filters=(8,), embedding_size=16,
        snail_filters=8, num_condition_samples_per_task=3,
        num_inference_samples_per_task=2)
    state = model.create_train_state(jax.random.PRNGKey(0))
    f, l = self._meta_batch(model)
    state, metrics = jax.jit(model.train_step)(
        state, f, l, jax.random.PRNGKey(0))
    assert np.isfinite(float(metrics["loss"]))
    # Predict with demonstration actions in features.
    pf = make_random_tensors(
        model.preprocessor.get_in_feature_specification(Mode.PREDICT),
        batch_size=2, seed=3)
    outputs = jax.jit(model.predict_step)(
        state, jax.tree_util.tree_map(jnp.asarray, pf))
    assert outputs["action"].shape == (2, 2, 3)

  @pytest.mark.slow
  def test_snail_uses_demonstrations(self):
    # In-context learning sanity: the task is "output the constant
    # action revealed by the demos". A correct SNAIL conditions on the
    # demo actions; after training, predictions must track the demoed
    # action, not the average.
    model = VRGripperSNAILModel(
        image_size=IMG, filters=(8,), embedding_size=16,
        snail_filters=16, num_condition_samples_per_task=3,
        num_inference_samples_per_task=2,
        create_optimizer_fn=fast_adam())
    state = model.create_train_state(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    step = jax.jit(model.train_step)

    def make_batch(seed):
      r = np.random.default_rng(seed)
      tasks = 8
      task_action = r.uniform(-1, 1, (tasks, 1, 3)).astype(np.float32)
      f = {}
      for split, n in (("condition", 3), ("inference", 2)):
        f[f"{split}/image"] = r.integers(
            0, 255, (tasks, n, IMG, IMG, 3)).astype(np.uint8)
        f[f"{split}/gripper_pose"] = r.standard_normal(
            (tasks, n, 3)).astype(np.float32)
      l = {"condition/action": np.tile(task_action, (1, 3, 1)),
           "inference/action": np.tile(task_action, (1, 2, 1))}
      dev = lambda d: jax.tree_util.tree_map(
          jnp.asarray, TensorSpecStruct.from_flat_dict(d))
      return dev(f), dev(l)

    losses = []
    for i in range(150):
      f, l = make_batch(i)
      state, metrics = step(state, f, l, jax.random.PRNGKey(i))
      losses.append(float(metrics["loss"]))
    # Predicting the mean action (0) gives mse ≈ E[a²] = 1/3; using
    # the demos must do far better.
    assert np.mean(losses[-10:]) < 0.1, losses[-10:]


class TestWTLModels:

  def test_trial_policy_shapes(self):
    model = VRGripperWTLModel(
        policy_type="trial", image_size=IMG, filters=(8,),
        embedding_size=16, hidden_sizes=(16,),
        num_condition_samples_per_task=2,
        num_inference_samples_per_task=2)
    feat = model.get_feature_specification(Mode.TRAIN)
    assert "trial" not in feat
    state = model.create_train_state(jax.random.PRNGKey(0))
    f, l = random_batch(model, batch=2)
    state, metrics = jax.jit(model.train_step)(
        state, f, l, jax.random.PRNGKey(0))
    assert np.isfinite(float(metrics["loss"]))

  def test_retrial_policy_consumes_trial(self):
    model = VRGripperWTLModel(
        policy_type="retrial", image_size=IMG, filters=(8,),
        embedding_size=16, hidden_sizes=(16,),
        num_condition_samples_per_task=2,
        num_trial_samples_per_task=2,
        num_inference_samples_per_task=2)
    feat = model.get_feature_specification(Mode.TRAIN)
    assert feat["trial/action"].shape == (2, 3)
    assert feat["trial/reward"].shape == (2, 1)
    state = model.create_train_state(jax.random.PRNGKey(0))
    f, l = random_batch(model, batch=2)
    state, metrics = jax.jit(model.train_step)(
        state, f, l, jax.random.PRNGKey(0))
    assert np.isfinite(float(metrics["loss"]))

  @pytest.mark.slow
  def test_wtl_learns_on_scripted_tasks(self):
    model = VRGripperWTLModel(
        policy_type="retrial", image_size=IMG, filters=(8,),
        embedding_size=32, hidden_sizes=(32,),
        num_condition_samples_per_task=4,
        num_trial_samples_per_task=4,
        num_inference_samples_per_task=4,
        create_optimizer_fn=fast_adam())
    state = model.create_train_state(jax.random.PRNGKey(0))
    step = jax.jit(model.train_step)
    batches = []
    for s in range(8):
      f, l = sample_wtl_meta_batch(num_tasks=4, image_size=IMG, seed=s)
      batches.append((
          jax.tree_util.tree_map(
              jnp.asarray, TensorSpecStruct.from_flat_dict(f)),
          jax.tree_util.tree_map(
              jnp.asarray, TensorSpecStruct.from_flat_dict(l))))
    losses = []
    for i in range(200):
      f, l = batches[i % len(batches)]
      state, metrics = step(state, f, l, jax.random.PRNGKey(i))
      losses.append(float(metrics["loss"]))
    assert np.mean(losses[-8:]) < np.mean(losses[:8]) * 0.5, (
        losses[:8], losses[-8:])

  def test_predict_with_demo_actions(self):
    model = VRGripperWTLModel(
        policy_type="trial", image_size=IMG, filters=(8,),
        embedding_size=16, hidden_sizes=(16,),
        num_condition_samples_per_task=2,
        num_inference_samples_per_task=3)
    state = model.create_inference_state(jax.random.PRNGKey(0))
    pf = make_random_tensors(
        model.get_feature_specification(Mode.PREDICT),
        batch_size=2, seed=0, include_optional=True)
    outputs = jax.jit(model.predict_step)(
        state, jax.tree_util.tree_map(jnp.asarray, pf))
    assert outputs["action"].shape == (2, 3, 3)


class TestShippedConfigs:

  @pytest.mark.parametrize("name", [
      "train_vrgripper_bc.gin",
      "train_vrgripper_meta.gin",
      "train_vrgripper_wtl.gin",
  ])
  def test_config_parses_and_builds_model(self, name):
    from tensor2robot_tpu import config as gin
    import tensor2robot_tpu.train_eval  # noqa: F401 registers
    import tensor2robot_tpu.research.vrgripper  # noqa: F401
    import tensor2robot_tpu.meta_learning  # noqa: F401
    import tensor2robot_tpu.data  # noqa: F401
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tensor2robot_tpu", "research", "vrgripper", "configs", name)
    gin.clear_config()
    try:
      gin.parse_config_files_and_bindings([path], [])
      model = gin.query_parameter("train_eval_model.model").resolve()
      assert model.get_feature_specification(Mode.TRAIN) is not None
    finally:
      gin.clear_config()


@pytest.mark.slow
class TestVRGripperEndToEnd:

  def test_collect_train_eval(self, tmp_path):
    from tensor2robot_tpu import train_eval
    from tensor2robot_tpu.predictors import CheckpointPredictor

    path = str(tmp_path / "demos.tfrecord")
    # Noisy demos double as state coverage (DAgger-ish) — the clone
    # must recover from off-expert states during closed-loop eval.
    collect_demo_episodes(path, num_episodes=64, image_size=IMG,
                          seed=0, action_noise=0.1)
    model = tiny_bc_model()
    model_dir = str(tmp_path / "model")
    train_eval.train_eval_model(
        model=model,
        model_dir=model_dir,
        input_generator_train=TransitionInputGenerator(
            TFRecordEpisodeInputGenerator(
                file_patterns=path, sequence_length=12, seed=1),
            batch_size=32, seed=1),
        max_train_steps=500,
        batch_size=32,
        save_checkpoints_steps=500,
        log_every_steps=200,
    )
    predictor = CheckpointPredictor(model, checkpoint_dir=model_dir)
    assert predictor.restore(timeout_secs=0)
    metrics = evaluate_gripper_policy(
        predictor.predict, num_episodes=20, image_size=IMG, seed=5)
    # The scripted expert solves ~100%; a briefly-trained clone must
    # clear a do-nothing baseline (~0 success) decisively.
    assert metrics["success_rate"] >= 0.5, metrics

  def test_meta_generator_feeds_snail(self, tmp_path):
    path = str(tmp_path / "demos.tfrecord")
    collect_demo_episodes(path, num_episodes=16, image_size=IMG,
                          seed=0)
    model = VRGripperSNAILModel(
        image_size=IMG, filters=(8,), embedding_size=16,
        snail_filters=8, num_condition_samples_per_task=3,
        num_inference_samples_per_task=2)
    gen = EpisodeMetaInputGenerator(
        TFRecordEpisodeInputGenerator(
            file_patterns=path, sequence_length=5, shuffle=False),
        num_condition_samples_per_task=3,
        num_inference_samples_per_task=2, batch_size=2)
    gen.set_specification_from_model(model, Mode.TRAIN)
    state = model.create_train_state(jax.random.PRNGKey(0))
    f, l = next(gen.create_dataset(Mode.TRAIN))
    f = jax.tree_util.tree_map(jnp.asarray, f)
    l = jax.tree_util.tree_map(jnp.asarray, l)
    state, metrics = jax.jit(model.train_step)(
        state, f, l, jax.random.PRNGKey(0))
    assert np.isfinite(float(metrics["loss"]))
