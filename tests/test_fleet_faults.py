"""Fault-injection & recovery tests (ISSUE 14).

The chaos contract of docs/FLEET.md §"Failure & recovery", pinned:

  * the fault plan is DETERMINISTIC — same seed, same schedule, any
    host (digest-pinned), and it ships picklable inside `FleetConfig`;
  * the injector fires count-based triggers exactly once per
    incarnation (respawns replay a fault-free schedule; `recurring`
    events re-arm — the crash-loop fixture);
  * `RpcClient` calls carry a PER-CALL DEADLINE: a dead or half-dead
    host raises `TimeoutError`/`ConnectionError` instead of stranding
    the caller until the heartbeat timer (the pinned ISSUE-14 hang),
    and `call()` recovers through reconnect-and-retry with the outage
    stamped into `fleet.recovery_ms`;
  * the restart budget is RATE-based: a sliding window absorbs
    occasional churn forever and trips on a crash-loop;
  * elastic membership (`Fleet.scale_to`) grows and shrinks the actor
    fleet mid-run with zero partial episode rows;
  * a fleet under a seeded multi-class fault schedule RECOVERS —
    every injected class lands in `Fleet.recoveries`/the retry
    counters, and `committed % batch_episodes == 0` holds after every
    recovery (slow lane, with learner crash-resume restoring from the
    latest checkpoint).
"""

from __future__ import annotations

import os
import pickle
import threading
import time

import pytest

from tensor2robot_tpu.fleet import (
    Fleet,
    FleetConfig,
    FleetError,
    RpcClient,
    RpcError,
    RpcServer,
)
from tensor2robot_tpu.fleet import faults
from tensor2robot_tpu.fleet import rpc as rpc_lib
from tensor2robot_tpu.telemetry import metrics as tmetrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# The seed-7 / 2-actor plan, frozen: regenerating it on ANY host must
# reproduce this digest bit-for-bit (the replay pin — a drifted
# generator would silently change every committed chaos run).
_SEED7_DIGEST = (
    "1a0cb555a8f2197709fba02331449752b8796fd59df907901bae45a3388a3d8d")


@pytest.fixture(autouse=True)
def _fresh_registry():
  tmetrics.reset_for_tests()
  rpc_lib.set_fault_injector(None)
  yield
  rpc_lib.set_fault_injector(None)
  tmetrics.reset_for_tests()


def _tiny_config(**overrides) -> FleetConfig:
  base = dict(
      num_actors=2, env="toy_grasp", image_size=16, action_dim=2,
      torso_filters=(8,), head_filters=(8,), dense_sizes=(16,),
      cem_population=8, cem_iterations=1, cem_elites=2,
      batch_size=16, max_train_steps=16, min_replay_size=32,
      publish_every_steps=8, log_every_steps=8,
      batch_episodes=8, serve_max_batch=4,
      replay_capacity=512, replay_shards=1,
      heartbeat_timeout_secs=0.0, launch_timeout_secs=240.0,
      run_timeout_secs=420.0, seed=0,
      rpc_call_timeout_secs=20.0, rpc_max_retries=2)
  base.update(overrides)
  return FleetConfig(**base)


class TestFaultPlan:

  def test_same_seed_same_plan_digest_pinned(self):
    plan_a = faults.FaultPlan.generate(seed=7, num_actors=2)
    plan_b = faults.FaultPlan.generate(seed=7, num_actors=2)
    assert plan_a.events == plan_b.events
    assert plan_a.digest() == plan_b.digest() == _SEED7_DIGEST
    # One event per class, each on a valid target.
    assert plan_a.classes() == tuple(sorted(faults.FAULT_CLASSES))
    assert faults.FaultPlan.generate(
        seed=8, num_actors=2).digest() != _SEED7_DIGEST

  def test_plan_ships_picklable_inside_fleet_config(self):
    plan = faults.FaultPlan.generate(seed=3, num_actors=2)
    config = _tiny_config(fault_plan=plan)
    clone = pickle.loads(pickle.dumps(config))
    assert clone.fault_plan.digest() == plan.digest()
    with pytest.raises(ValueError, match="fault_plan"):
      _tiny_config(fault_plan={"not": "a plan"})

  def test_unknown_class_rejected(self):
    with pytest.raises(ValueError, match="unknown fault class"):
      faults.FaultPlan.generate(seed=0, num_actors=1,
                                classes=("actor_crash", "bogus"))

  def test_for_target_filters(self):
    plan = faults.FaultPlan.generate(seed=7, num_actors=2)
    targets = {e.target for e in plan.events}
    for target in targets:
      events = plan.for_target(target)
      assert events and all(e.target == target for e in events)
    assert plan.for_target("actor-99") == ()


class TestFaultInjector:

  def _plan(self, *events):
    return faults.FaultPlan(seed=0, events=tuple(events))

  def test_on_batch_fires_once_and_respawn_is_fault_free(self):
    plan = self._plan(faults.FaultEvent(
        fault=faults.ACTOR_CRASH, target="actor-0", at=3, mode="hard"))
    injector = faults.FaultInjector(plan, "actor-0", incarnation=0)
    assert injector.active
    assert injector.on_batch(1) is None
    assert injector.on_batch(2) is None
    event = injector.on_batch(3)
    assert event is not None and event.fault == faults.ACTOR_CRASH
    assert injector.on_batch(4) is None  # fired, disarmed
    # The respawned incarnation replays a fault-free schedule.
    respawn = faults.FaultInjector(plan, "actor-0", incarnation=1)
    assert not respawn.active
    assert respawn.on_batch(3) is None
    # Other roles never see the event.
    other = faults.FaultInjector(plan, "actor-1", incarnation=0)
    assert not other.active

  def test_recurring_event_rearms_in_every_incarnation(self):
    plan = self._plan(faults.FaultEvent(
        fault=faults.ACTOR_CRASH, target="actor-0", at=1,
        mode="hard", recurring=True))
    for incarnation in (0, 1, 2):
      injector = faults.FaultInjector(plan, "actor-0",
                                      incarnation=incarnation)
      assert injector.on_batch(1) is not None, incarnation

  def test_rpc_action_counts_per_side_method_and_duration(self):
    plan = self._plan(
        faults.FaultEvent(fault=faults.RPC_DELAY, target="learner",
                          at=2, duration_secs=0.01, count=2),
        faults.FaultEvent(fault=faults.RPC_DROP, target="learner",
                          at=4, method="sample"))
    injector = faults.FaultInjector(plan, "learner")
    # Call 1: below every trigger. Calls 2-3: the delay (count=2).
    assert injector.rpc_action("client", "sample") is None
    assert injector.rpc_action("client", "sample") == ("delay", 0.01)
    assert injector.rpc_action("client", "sample") == ("delay", 0.01)
    # Call 4: the drop (method-filtered).
    assert injector.rpc_action("client", "sample") == ("drop", 0.0)
    assert injector.rpc_action("client", "sample") is None
    # A different method never matched the method-filtered drop, and
    # the server side never sees client-side classes.
    assert injector.rpc_action("client", "publish") is None
    fresh = faults.FaultInjector(plan, "learner")
    assert fresh.rpc_action("server", "sample") is None

  def test_injections_recorded_in_registry_and_log(self):
    plan = self._plan(faults.FaultEvent(
        fault=faults.LEARNER_CRASH, target="learner", at=1))
    injector = faults.FaultInjector(plan, "learner")
    assert injector.on_step(1) is not None
    snap = tmetrics.registry().snapshot()
    assert snap["counters"][
        "fleet.faults.injected.learner_crash"] == 1.0
    assert injector.injected[0]["fault"] == faults.LEARNER_CRASH


class TestRpcDeadlineRetry:
  """The ISSUE-14 satellite regression: `recv()` with no deadline
  stranded callers on a half-dead host until the 300s heartbeat
  timer. Every shape of that hang now raises within the deadline."""

  def test_unresponsive_handler_raises_timeout_not_strand(self):
    release = threading.Event()

    def handler(method, payload, ctx):
      if method == "stall":
        release.wait(timeout=30.0)
      return payload

    server = RpcServer(handler)
    try:
      client = RpcClient(server.address)
      t0 = time.monotonic()
      with pytest.raises(TimeoutError, match="no reply"):
        client.call_once("stall", timeout_secs=0.4)
      waited = time.monotonic() - t0
      assert waited < 5.0, f"caller stranded {waited:.1f}s"
      assert tmetrics.registry().snapshot()["counters"][
          "fleet.rpc.timeouts"] >= 1.0
      client.close()
    finally:
      release.set()
      server.close()

  def test_dead_server_raises_connection_error_mid_call(self):
    outcome = {}
    started = threading.Event()

    def handler(method, payload, ctx):
      started.set()
      time.sleep(30.0)
      return payload

    server = RpcServer(handler)
    client = RpcClient(server.address)

    def caller():
      try:
        client.call_once("x", timeout_secs=25.0)
      except (ConnectionError, TimeoutError) as e:
        outcome["error"] = e

    thread = threading.Thread(target=caller)
    thread.start()
    assert started.wait(timeout=10.0)
    server.close(timeout_secs=0.2)  # the host dies mid-call
    thread.join(timeout=10.0)
    assert not thread.is_alive(), "caller stranded by host death"
    assert "error" in outcome
    client.close()

  def test_retry_reconnects_and_stamps_recovery(self):
    calls = []
    release = threading.Event()

    def handler(method, payload, ctx):
      if method == "flaky":
        calls.append(1)
        if len(calls) == 1:
          release.wait(timeout=30.0)  # first call blows the deadline
      return payload

    server = RpcServer(handler)
    try:
      client = RpcClient(server.address, call_timeout_secs=0.3,
                         max_retries=2)
      assert client.call("flaky", 42) == 42
      assert client.reconnects == 1
      snap = tmetrics.registry().snapshot()["counters"]
      assert snap["fleet.rpc.retries"] >= 1.0
      assert snap["fleet.rpc.recovered"] >= 1.0
      hist = tmetrics.registry().snapshot()["histograms"][
          "fleet.recovery_ms"]
      assert hist["count"] >= 1
      client.close()
    finally:
      release.set()
      server.close()

  def test_injected_drop_recovers_through_real_machinery(self):
    # The no-mocks property: a planned rpc_drop loses the SEND, the
    # real deadline fires, the real reconnect-and-retry resends.
    plan = faults.FaultPlan(seed=0, events=(faults.FaultEvent(
        fault=faults.RPC_DROP, target="learner", at=1,
        method="ping"),))
    rpc_lib.set_fault_injector(
        faults.FaultInjector(plan, "learner"))
    server = RpcServer(lambda method, payload, ctx: payload)
    try:
      client = RpcClient(server.address, call_timeout_secs=0.3,
                         max_retries=2)
      assert client.call("ping", 5) == 5  # dropped once, recovered
      assert client.reconnects == 1
      snap = tmetrics.registry().snapshot()["counters"]
      assert snap["fleet.faults.injected.rpc_drop"] == 1.0
      assert snap["fleet.rpc.recovered"] >= 1.0
      client.close()
    finally:
      server.close()

  def test_injected_disconnect_runs_real_disconnect_path(self):
    # Server-side disconnect: the handler thread breaks out, the
    # synthetic __disconnect__ runs (the session-abort path), and the
    # client recovers on a fresh connection.
    disconnects = []

    def handler(method, payload, ctx):
      if method == rpc_lib.DISCONNECT_METHOD:
        disconnects.append(1)
        return None
      return payload

    plan = faults.FaultPlan(seed=0, events=(faults.FaultEvent(
        fault=faults.RPC_DISCONNECT, target="host", at=2),))
    rpc_lib.set_fault_injector(faults.FaultInjector(plan, "host"))
    server = RpcServer(handler)
    try:
      client = RpcClient(server.address, call_timeout_secs=5.0,
                         max_retries=2)
      assert client.call("ping", 1) == 1
      # Call 2 of "ping" (counts are per-method): the server drops the
      # connection BEFORE handling — the request is discarded, the
      # disconnect path runs, the client resends on a fresh socket.
      assert client.call("ping", 2) == 2
      assert client.reconnects == 1
      assert disconnects, "__disconnect__ never ran"
      client.close()
    finally:
      server.close()

  def test_server_side_handler_error_never_retries(self):
    attempts = []

    def handler(method, payload, ctx):
      attempts.append(method)
      raise ValueError("application error")

    server = RpcServer(handler)
    try:
      client = RpcClient(server.address, call_timeout_secs=5.0,
                         max_retries=3)
      with pytest.raises(RpcError, match="application error"):
        client.call("op")
      # The request ARRIVED; the transport must not re-send it.
      assert attempts == ["op"]
    finally:
      server.close()


class TestRateBudget:
  """The sliding-window restart budget, unit-level (no processes)."""

  def _fleet(self, tmp_path, **overrides):
    return Fleet(_tiny_config(**overrides), str(tmp_path / "m"))

  def test_window_absorbs_churn_and_trips_on_crash_loop(self, tmp_path):
    fleet = self._fleet(tmp_path, max_actor_restarts=2,
                        restart_window_secs=0.2)
    assert fleet._budget_ok("actor-0")
    fleet._charge_restart("actor-0")
    assert fleet._budget_ok("actor-0")
    fleet._charge_restart("actor-0")
    assert not fleet._budget_ok("actor-0")  # crash-loop: tripped
    time.sleep(0.25)
    # The window slid: occasional churn is absorbed forever.
    assert fleet._budget_ok("actor-0")
    # Budgets are per-target.
    assert fleet._budget_ok("actor-1")

  def test_window_zero_restores_lifetime_cap(self, tmp_path):
    fleet = self._fleet(tmp_path, max_actor_restarts=1,
                        restart_window_secs=0.0)
    fleet._charge_restart("actor-0")
    time.sleep(0.05)
    assert not fleet._budget_ok("actor-0")  # never expires

  def test_learner_budget_uses_its_own_cap(self, tmp_path):
    fleet = self._fleet(tmp_path, max_actor_restarts=5,
                        max_learner_restarts=1,
                        restart_window_secs=600.0)
    fleet._charge_restart("learner")
    assert not fleet._budget_ok("learner")
    assert fleet._budget_ok("actor-0")


def _committed(metrics):
  return int(metrics.get("service", {}).get(
      "replay_committed_transitions", -1))


class TestFleetFaultsE2E:
  """Real multi-process recoveries through the real seams."""

  @pytest.mark.slow
  def test_restart_budget_trips_on_crash_looping_actor(self, tmp_path):
    # A recurring crash re-fires in EVERY incarnation: the rate budget
    # must trip instead of respawning forever.
    plan = faults.FaultPlan(seed=0, events=(faults.FaultEvent(
        fault=faults.ACTOR_CRASH, target="actor-0", at=1,
        mode="hard", recurring=True),))
    config = _tiny_config(fault_plan=plan, max_actor_restarts=2,
                          restart_window_secs=600.0,
                          max_train_steps=64)
    fleet = Fleet(config, str(tmp_path / "m"))
    with pytest.raises(FleetError, match="budget"):
      fleet.run()
    assert fleet._restarts[0] == 2  # two respawns, then the trip

  @pytest.mark.slow
  def test_elastic_scale_up_down_lands_no_partial_rows(self, tmp_path):
    config = _tiny_config(max_train_steps=24)
    fleet = Fleet(config, str(tmp_path / "m"))
    fleet.launch()
    try:
      time.sleep(3.0)
      fleet.scale_to(3)
      assert sorted(fleet._actors) == [0, 1, 2]
      time.sleep(2.0)
      fleet.scale_to(1)
      assert sorted(fleet._actors) == [0]
      fleet.wait()
    finally:
      metrics = fleet.shutdown()
    assert metrics is not None
    committed = _committed(metrics)
    assert committed > 0
    # Scale-down drained actors mid-run; every landed episode batch is
    # whole (atomic commits + drain-after-batch).
    assert committed % config.batch_episodes == 0
    actions = [e["action"] for e in fleet.scale_events]
    assert actions == ["add", "remove", "remove"]
    assert fleet._restarts.get(0, 0) == 0  # drains never read as crashes

  @pytest.mark.slow
  def test_actor_crash_recovers_with_mttr_and_no_partial_rows(
      self, tmp_path):
    # One planned mid-episode crash: the disconnect abort discards the
    # staged half-episode, the restart policy respawns, MTTR lands in
    # `recoveries`, and the commit ledger stays whole.
    plan = faults.FaultPlan(seed=0, events=(faults.FaultEvent(
        fault=faults.ACTOR_CRASH, target="actor-0", at=2,
        mode="mid_episode"),))
    config = _tiny_config(fault_plan=plan, max_train_steps=16,
                          max_actor_restarts=3,
                          restart_window_secs=600.0)
    fleet = Fleet(config, str(tmp_path / "m"))
    result = fleet.run()
    assert result.actor_restarts == 1
    assert [r["fault"] for r in result.recoveries] == ["actor_crash"]
    assert result.recoveries[0]["target"] == "actor-0"
    assert result.recoveries[0]["mttr_ms"] > 0
    committed = _committed(result.metrics)
    assert committed > 0 and committed % config.batch_episodes == 0
    service = result.metrics["service"]
    assert service.get("replay_aborted_episodes", 0) >= 1

  @pytest.mark.slow
  def test_learner_crash_resume_restores_step_and_finishes(
      self, tmp_path):
    # The resume policy: the learner dies at step 10, the host keeps
    # the store + engine alive, the respawn restores from the step-8
    # checkpoint (publish cadence 8) and finishes the run — at most
    # one cadence of progress re-trained, zero experience lost.
    plan = faults.FaultPlan(seed=0, events=(faults.FaultEvent(
        fault=faults.LEARNER_CRASH, target="learner", at=10),))
    config = _tiny_config(fault_plan=plan,
                          learner_crash_policy="resume",
                          max_learner_restarts=2,
                          restart_window_secs=600.0,
                          max_train_steps=16)
    fleet = Fleet(config, str(tmp_path / "m"))
    result = fleet.run()
    assert result.learner_restarts == 1
    assert [r["fault"] for r in result.recoveries] == ["learner_crash"]
    assert result.recoveries[0]["mttr_ms"] > 0
    # The run FINISHED: the resumed learner reached the exact final
    # step and published its params (the host stamps them).
    window = result.metrics["learner_window"]
    assert window["last_step"] == config.max_train_steps
    assert result.metrics["params_learner_step"] == (
        config.max_train_steps)
    # The host WITNESSED the restore (a backward set_learner_step):
    # the measured restore point is the last checkpoint before the
    # crash, so the measured loss is bounded by the publish cadence —
    # the same record bench --chaos gates on.
    (resume,) = result.metrics["learner_resumes"]
    assert resume["to_step"] <= resume["from_step"] <= 10
    assert resume["from_step"] - resume["to_step"] <= (
        config.publish_every_steps)
    assert resume["to_step"] >= 10 - config.publish_every_steps
    committed = _committed(result.metrics)
    assert committed > 0 and committed % config.batch_episodes == 0

  @pytest.mark.slow
  def test_multi_class_chaos_plan_recovers_every_class(self, tmp_path):
    # The bench --chaos shape in miniature: hang + crash + client/
    # server RPC faults in ONE run, every class recovering through its
    # real path.
    plan = faults.FaultPlan(seed=0, events=(
        faults.FaultEvent(fault=faults.ACTOR_CRASH, target="actor-0",
                          at=2, mode="hard"),
        faults.FaultEvent(fault=faults.ACTOR_HANG, target="actor-1",
                          at=2, mode="hard", duration_secs=45.0),
        faults.FaultEvent(fault=faults.RPC_DROP, target="actor-1",
                          at=3, method="act"),
        faults.FaultEvent(fault=faults.RPC_DELAY, target="learner",
                          at=4, duration_secs=0.05, count=3),
        faults.FaultEvent(fault=faults.SLOW_HOST, target="host",
                          at=6, method="act", duration_secs=0.2,
                          count=4),
        faults.FaultEvent(fault=faults.RPC_DISCONNECT, target="host",
                          at=10, method="commit"),
    ))
    # The hang (45s) must outlive its detection window (5s) by far,
    # and the RUN must outlive the detection: 48 learner steps keeps
    # the learner busy well past the stale-heartbeat kill + respawn.
    config = _tiny_config(
        fault_plan=plan, max_train_steps=48,
        max_actor_restarts=3, restart_window_secs=600.0,
        actor_heartbeat_timeout_secs=5.0,
        rpc_call_timeout_secs=3.0, rpc_max_retries=3,
        telemetry_dir="off")
    fleet = Fleet(config, str(tmp_path / "m"))
    result = fleet.run()
    recovered = {r["fault"] for r in result.recoveries}
    assert recovered == {faults.ACTOR_CRASH, faults.ACTOR_HANG}
    assert all(r["mttr_ms"] > 0 for r in result.recoveries)
    assert result.actor_restarts == 2
    # MTTR is detection → recovered; the stale window the hang sat
    # undetected is reported separately and must cover the timeout.
    hang = next(r for r in result.recoveries
                if r["fault"] == faults.ACTOR_HANG)
    assert hang["stale_secs"] >= config.actor_heartbeat_timeout_secs
    committed = _committed(result.metrics)
    assert committed > 0 and committed % config.batch_episodes == 0
    window = result.metrics["learner_window"]
    assert window["last_step"] == config.max_train_steps
