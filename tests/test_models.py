"""Tests for the model abstraction, optimizers, and canonical models."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tensor2robot_tpu import specs
from tensor2robot_tpu.data import Mode, RandomInputGenerator
from tensor2robot_tpu.models import (
    ClassificationModel,
    CriticModel,
    RegressionModel,
    TrainState,
    create_lr_schedule,
    create_optimizer,
)
from tensor2robot_tpu.utils.mocks import (
    MockClassificationModel,
    MockCriticModel,
    MockT2RModel,
)


def make_batch(model, mode=Mode.TRAIN, batch_size=8, seed=0):
  features = specs.make_random_tensors(
      model.get_feature_specification(mode), batch_size=batch_size,
      seed=seed)
  labels = specs.make_random_tensors(
      model.get_label_specification(mode), batch_size=batch_size,
      seed=seed + 1)
  to_dev = lambda t: jax.tree_util.tree_map(jnp.asarray, t)
  return to_dev(features), to_dev(labels)


class TestOptimizers:

  def test_factory_names(self):
    for name in ["adam", "adamw", "sgd", "momentum", "rmsprop",
                 "adagrad", "lamb"]:
      tx = create_optimizer(optimizer_name=name, learning_rate=1e-3)
      params = {"w": jnp.ones((3,))}
      state = tx.init(params)
      grads = {"w": jnp.ones((3,))}
      updates, _ = tx.update(grads, state, params)
      assert updates["w"].shape == (3,)

  def test_unknown_raises(self):
    with pytest.raises(ValueError, match="Unknown optimizer"):
      create_optimizer(optimizer_name="nope")

  def test_grad_clipping(self):
    tx = create_optimizer(optimizer_name="sgd", learning_rate=1.0,
                          gradient_clip_norm=1.0)
    params = {"w": jnp.zeros((2,))}
    state = tx.init(params)
    grads = {"w": jnp.array([30.0, 40.0])}  # norm 50
    updates, _ = tx.update(grads, state, params)
    np.testing.assert_allclose(
        np.asarray(updates["w"]), [-0.6, -0.8], rtol=1e-5)

  def test_schedules(self):
    for schedule in ["constant", "exponential_decay", "cosine_decay",
                     "linear_decay"]:
      sched = create_lr_schedule(learning_rate=1e-2, schedule=schedule,
                                 warmup_steps=10, decay_steps=100)
      assert float(sched(0)) == pytest.approx(0.0)
      assert float(sched(10)) == pytest.approx(1e-2, rel=1e-3)

  def test_unknown_schedule(self):
    with pytest.raises(ValueError, match="Unknown lr schedule"):
      create_lr_schedule(schedule="bogus")


class TestMockRegressionModel:

  def test_create_train_state(self):
    model = MockT2RModel()
    state = model.create_train_state(jax.random.PRNGKey(0))
    assert int(state.step) == 0
    assert "backbone" in jax.tree_util.tree_leaves_with_path(
        state.params)[0][0][0].key or state.params  # params exist

  def test_train_step_reduces_loss(self):
    model = MockT2RModel()
    state = model.create_train_state(jax.random.PRNGKey(0))
    features, labels = make_batch(model)
    # Learn a fixed target mapping.
    step = jax.jit(model.train_step)
    _, first_metrics = step(state, features, labels,
                            jax.random.PRNGKey(1))
    for i in range(60):
      state, metrics = step(state, features, labels,
                            jax.random.PRNGKey(i))
    assert float(metrics["loss"]) < float(first_metrics["loss"])
    assert int(state.step) == 60

  def test_eval_and_predict_step(self):
    model = MockT2RModel()
    state = model.create_train_state(jax.random.PRNGKey(0))
    features, labels = make_batch(model, Mode.EVAL)
    metrics = jax.jit(model.eval_step)(state, features, labels)
    assert "loss" in metrics and "mae" in metrics
    outputs = jax.jit(model.predict_step)(state, features)
    assert outputs["inference_output"].shape == (8, 2)

  def test_deterministic_eval(self):
    model = MockT2RModel()
    state = model.create_train_state(jax.random.PRNGKey(0))
    features, labels = make_batch(model, Mode.EVAL)
    m1 = model.eval_step(state, features, labels)
    m2 = model.eval_step(state, features, labels)
    assert float(m1["loss"]) == float(m2["loss"])


class TestClassificationModel:

  def test_train_improves_accuracy(self):
    import functools
    model = MockClassificationModel(
        create_optimizer_fn=functools.partial(
            create_optimizer, optimizer_name="adam", learning_rate=1e-2))
    state = model.create_train_state(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    x = rng.standard_normal((64, 4)).astype(np.float32)
    y = (x.sum(axis=-1) > 0).astype(np.int64).reshape(-1, 1)
    features = {"x": jnp.asarray(x)}
    labels = {"label": jnp.asarray(y)}
    step = jax.jit(model.train_step)
    for i in range(150):
      state, metrics = step(state, features, labels,
                            jax.random.PRNGKey(i))
    assert float(metrics["accuracy"]) > 0.8


class TestCriticModel:

  def test_train_step(self):
    model = MockCriticModel()
    state = model.create_train_state(jax.random.PRNGKey(0))
    features, labels = make_batch(model)
    state, metrics = jax.jit(model.train_step)(
        state, features, labels, jax.random.PRNGKey(0))
    assert "q_loss" in metrics and np.isfinite(float(metrics["q_loss"]))

  def test_sigmoid_q_bounded(self):
    model = MockCriticModel(sigmoid_q=True)
    state = model.create_train_state(jax.random.PRNGKey(0))
    features, _ = make_batch(model)
    prep_features, _ = model.preprocessor.preprocess(
        features, None, Mode.PREDICT)
    outputs, _ = model.inference_network_fn(
        state.variables, prep_features, Mode.PREDICT)
    q = model.q_from_outputs(outputs)
    assert float(q.min()) >= 0.0 and float(q.max()) <= 1.0


class TestWarmStart:

  def test_init_from_checkpoint(self, tmp_path):
    from tensor2robot_tpu.utils import checkpoints as ckpt_lib
    model = MockT2RModel()
    state = model.create_train_state(jax.random.PRNGKey(42))
    writer = ckpt_lib.CheckpointWriter(str(tmp_path))
    writer.save(0, state)
    writer.close()

    warm = MockT2RModel(init_from_checkpoint_path=str(tmp_path))
    warm_state = warm.create_train_state(jax.random.PRNGKey(7))
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)),
        state.params, warm_state.params)

  def test_warm_start_restores_batch_stats(self, tmp_path):
    """Warm-started BN models must inherit the checkpoint's moving
    averages, not keep fresh-init ones (the predictor-path guarantee,
    extended to maybe_init_from_checkpoint)."""
    from tensor2robot_tpu.research.pose_env import PoseEnvRegressionModel
    from tensor2robot_tpu.specs import make_random_tensors
    from tensor2robot_tpu.utils import checkpoints as ckpt_lib

    model = PoseEnvRegressionModel(
        image_size=16, filters=(4,), embedding_size=8, hidden_sizes=(8,),
        use_batch_norm=True)
    state = model.create_train_state(jax.random.PRNGKey(0), batch_size=4)
    batch = make_random_tensors(
        model.preprocessor.get_in_feature_specification(Mode.TRAIN),
        batch_size=4, seed=1)
    labels = make_random_tensors(
        model.preprocessor.get_in_label_specification(Mode.TRAIN),
        batch_size=4, seed=2)
    for i in range(3):
      state, _ = jax.jit(model.train_step)(
          state, batch, labels, jax.random.PRNGKey(i))
    writer = ckpt_lib.CheckpointWriter(str(tmp_path))
    writer.save(3, jax.device_get(state))
    writer.close()

    warm = PoseEnvRegressionModel(
        image_size=16, filters=(4,), embedding_size=8, hidden_sizes=(8,),
        use_batch_norm=True, init_from_checkpoint_path=str(tmp_path))
    warm_state = warm.create_train_state(jax.random.PRNGKey(9),
                                         batch_size=4)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6),
        jax.device_get(state.batch_stats),
        jax.device_get(warm_state.batch_stats))

  def test_predictor_restores_batch_stats(self, tmp_path):
    """BN moving averages must survive the trainer→predictor handoff."""
    from tensor2robot_tpu.predictors import CheckpointPredictor
    from tensor2robot_tpu.research.pose_env import PoseEnvRegressionModel
    from tensor2robot_tpu.utils import checkpoints as ckpt_lib

    model = PoseEnvRegressionModel(
        image_size=16, filters=(4,), embedding_size=8, hidden_sizes=(8,),
        use_batch_norm=True)
    state = model.create_train_state(jax.random.PRNGKey(0), batch_size=4)
    assert state.batch_stats, "model under test must carry BN stats"
    from tensor2robot_tpu.specs import make_random_tensors
    batch = make_random_tensors(
        model.preprocessor.get_in_feature_specification(Mode.TRAIN),
        batch_size=4, seed=1)
    labels = make_random_tensors(
        model.preprocessor.get_in_label_specification(Mode.TRAIN),
        batch_size=4, seed=2)
    for i in range(3):
      state, _ = jax.jit(model.train_step)(
          state, batch, labels, jax.random.PRNGKey(i))
    writer = ckpt_lib.CheckpointWriter(str(tmp_path))
    writer.save(3, jax.device_get(state))
    writer.close()

    predictor = CheckpointPredictor(model, checkpoint_dir=str(tmp_path))
    assert predictor.restore(timeout_secs=0)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6),
        jax.device_get(state.batch_stats),
        jax.device_get(predictor._state.batch_stats))

  def test_checkpoint_roundtrip_and_polling(self, tmp_path):
    from tensor2robot_tpu.utils import checkpoints as ckpt_lib
    model = MockT2RModel()
    state = model.create_train_state(jax.random.PRNGKey(0))
    writer = ckpt_lib.CheckpointWriter(str(tmp_path), max_to_keep=2)
    for step in [0, 10, 20]:
      writer.save(step, state.replace(step=jnp.asarray(step)))
    writer.close()
    # Retention: only 2 newest kept.
    assert ckpt_lib.list_steps(str(tmp_path)) == [10, 20]
    assert ckpt_lib.latest_step(str(tmp_path)) == 20
    restored = ckpt_lib.restore_state(str(tmp_path), like=state)
    assert int(restored.step) == 20
    # Polling sees the newest immediately.
    assert ckpt_lib.wait_for_new_checkpoint(
        str(tmp_path), last_step=10, timeout_secs=1) == 20
    assert ckpt_lib.wait_for_new_checkpoint(
        str(tmp_path), last_step=20, timeout_secs=0.2) is None
