"""Unit tests for the sharding-rule contracts.

`expert_sharding` keys on the dedicated ``moe_expert_`` leaf prefix
OWNED by `MoEMLP` — mount-point independent, so experts shard no
matter what module name the trunk instantiates its MoEMLP under. (The
previous contract required the parent module to be literally named
``moe``, which silently replicated experts under any other mount —
the round-5 advisor finding these tests regression-pin.) Indivisible
expert dims raise instead of silently falling back.
`xplane.is_async_window` (the compute-table filter behind the bench's
per-op attribution) gets direct unit coverage too.
"""

import numpy as np
import pytest

import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from tensor2robot_tpu.parallel import (
    DATA_AXIS,
    EXPERT_AXIS,
    create_mesh,
    expert_sharding,
)
from tensor2robot_tpu.utils import xplane


class TestExpertShardingScope:

  @pytest.fixture()
  def mesh(self):
    return create_mesh({DATA_AXIS: 2, EXPERT_AXIS: 4})

  def test_expert_leaf_under_moe_shards_on_expert(self, mesh):
    tree = {"block1": {"moe": {
        "moe_expert_w_in": jnp.zeros((8, 16, 32))}}}
    sh = expert_sharding(mesh, tree, min_size_to_shard=64)
    assert sh["block1"]["moe"]["moe_expert_w_in"].spec == P(EXPERT_AXIS)

  def test_renamed_mount_still_shards(self, mesh):
    """THE regression for the round-5 finding: a MoEMLP mounted under
    a name other than 'moe' (here 'ffn_sparse') must still shard its
    experts — the old parent-name contract silently replicated them."""
    tree = {"block1": {"ffn_sparse": {
        "moe_expert_w_in": jnp.zeros((8, 16, 32)),
        "router": jnp.zeros((16, 8))}}}
    sh = expert_sharding(mesh, tree, min_size_to_shard=64)
    assert sh["block1"]["ffn_sparse"]["moe_expert_w_in"].spec == P(
        EXPERT_AXIS)
    # The router is not an expert weight wherever it lives.
    router_spec = sh["block1"]["ffn_sparse"]["router"].spec
    assert EXPERT_AXIS not in [ax for ax in router_spec if ax]

  def test_root_level_expert_leaf_shards(self, mesh):
    """A bare MoEMLP param tree has expert leaves at the root."""
    sh = expert_sharding(
        mesh, {"moe_expert_w_in": jnp.zeros((8, 16, 32))},
        min_size_to_shard=64)
    assert sh["moe_expert_w_in"].spec == P(EXPERT_AXIS)

  def test_optimizer_mirror_path_shards_too(self, mesh):
    """Adam moments nest the param path under opt-state prefixes; the
    leaf-name rule must still match."""
    tree = {"mu": {"trunk": {"moe": {
        "moe_expert_w_out": jnp.zeros((8, 32, 16))}}}}
    sh = expert_sharding(mesh, tree, min_size_to_shard=64)
    assert sh["mu"]["trunk"]["moe"]["moe_expert_w_out"].spec == P(
        EXPERT_AXIS)

  def test_expert_prefixed_leaf_outside_contract_uses_fsdp(self, mesh):
    """The advisor's collision case: `expert_`-prefixed params that
    are NOT MoEMLP's stacked weights (the prefix is `moe_expert_`,
    which only MoEMLP may use) follow the fsdp rules — with no fsdp
    axis in this mesh, replicate — instead of landing on the expert
    axis."""
    tree = {"policy": {"expert_demo_encoder": jnp.zeros((8, 64, 64))},
            "moe": {"expert_w_in": jnp.zeros((8, 16, 32))}}
    sh = expert_sharding(mesh, tree, min_size_to_shard=64)
    for leaf in (sh["policy"]["expert_demo_encoder"],
                 sh["moe"]["expert_w_in"]):
      assert EXPERT_AXIS not in [ax for ax in leaf.spec if ax], leaf

  def test_indivisible_expert_dim_raises(self, mesh):
    tree = {"moe": {"moe_expert_w_in": jnp.zeros((6, 16, 32))}}
    with pytest.raises(ValueError, match="not divisible"):
      expert_sharding(mesh, tree, min_size_to_shard=64)

  def test_no_expert_axis_falls_back_to_fsdp(self):
    mesh = create_mesh({DATA_AXIS: 8})
    tree = {"moe": {"moe_expert_w_in": jnp.zeros((6, 16, 32))}}
    # No expert axis: the indivisible dim is irrelevant; fsdp rules
    # (here: replicated) apply without raising.
    sh = expert_sharding(mesh, tree, min_size_to_shard=64)
    assert sh["moe"]["moe_expert_w_in"].spec == P()

  def test_moe_mlp_param_names_carry_the_contract_prefix(self):
    """The rule and the module must agree: every stacked expert param
    MoEMLP creates is `moe_expert_`-prefixed (if this breaks, experts
    replicate silently on pods)."""
    import jax as _jax
    from tensor2robot_tpu.parallel.moe import MoEMLP

    module = MoEMLP(num_experts=4, hidden_dim=8, dtype=jnp.float32)
    params = module.init(
        _jax.random.PRNGKey(0), jnp.zeros((2, 4, 8)))["params"]
    stacked = [name for name, leaf in params.items()
               if np.asarray(leaf).ndim and
               np.asarray(leaf).shape[0] == 4]
    assert stacked, params.keys()
    for name in stacked:
      assert name.startswith("moe_expert_"), name


class TestAsyncWindowFilter:
  """The per-op compute filter: -start/-done spans are wall windows
  overlapping compute (round-4's committed tables were 10/10
  copy-starts), so they must be excluded from busy-time attribution
  — and ONLY they."""

  @pytest.mark.parametrize("name", [
      "%copy-start.113 = (f32[64]...) copy-start(...)",
      "%copy-done.77 = f32[64] copy-done(...)",
      "%all-gather-start.3 = ...",
      "%all-reduce-done.9 = ...",
      "%collective-permute-start.1 = ...",
  ])
  def test_async_windows_match(self, name):
    assert xplane.is_async_window(name)

  @pytest.mark.parametrize("name", [
      "%fusion.481 = bf16[256,16,16,64] fusion(...)",
      "%convert_reduce_fusion.27 = f32[16384,64] fusion(...)",
      "%convolution.12 = ...",
      "%all-reduce.4 = ...",          # sync collective: busy time
      "%custom-call.5 = ...",
      "%multiply_add_fusion.153 = ...",
  ])
  def test_compute_ops_pass(self, name):
    assert not xplane.is_async_window(name)

  def test_top_ops_compute_only_drops_windows(self, tmp_path,
                                              monkeypatch):
    monkeypatch.setattr(
        xplane, "op_times_ms",
        lambda trace_dir, plane_filter="TPU": {
            "%copy-start.1": 75.0,
            "%fusion.2": 50.0,
            "%while": 400.0,
            "%convolution.3": 25.0,
        })
    got = xplane.top_ops("unused", k=10, hlo_only=True,
                         compute_only=True)
    assert got == [("%fusion.2", 50.0), ("%convolution.3", 25.0)]


# ---------------------------------------------------------------------------
# The rules seam (ISSUE 12)
# ---------------------------------------------------------------------------


class TestMatchPartitionRules:
  """The regex-rules engine every strategy now selects tables from."""

  def _mesh(self):
    from tensor2robot_tpu.parallel import FSDP_AXIS, create_mesh
    return create_mesh({DATA_AXIS: 2, FSDP_AXIS: 4})

  def test_first_match_wins_and_placements_resolve(self):
    from tensor2robot_tpu.parallel import (
        FSDP_AXIS,
        Replicate,
        ShardLargest,
        match_partition_rules,
    )
    mesh = self._mesh()
    tree = {"torso": {"kernel": jnp.zeros((8, 16)),
                      "bias": jnp.zeros((16,))}}
    specs = match_partition_rules(
        ((r"/bias$", Replicate()),
         (r".*", ShardLargest(FSDP_AXIS))),
        tree, mesh, min_size_to_shard=1)
    assert specs["torso"]["bias"] == P()
    assert specs["torso"]["kernel"] == P(None, FSDP_AXIS)

  def test_literal_partition_spec_used_verbatim(self):
    from tensor2robot_tpu.parallel import match_partition_rules
    specs = match_partition_rules(
        ((r".*", P(DATA_AXIS)),), {"w": jnp.zeros((4, 4))},
        self._mesh())
    assert specs["w"] == P(DATA_AXIS)

  def test_unmatched_leaf_raises(self):
    from tensor2robot_tpu.parallel import (
        Replicate,
        match_partition_rules,
    )
    with pytest.raises(ValueError, match="no partition rule matched"):
      match_partition_rules(((r"/bias$", Replicate()),),
                            {"w": jnp.zeros((4,))}, self._mesh())

  def test_opt_state_tuple_paths_match_leaf_rules(self):
    """Optax chains nest params under tuple indices (SequenceKey);
    the '/'-joined path keeps the leaf name matchable."""
    from tensor2robot_tpu.parallel import (
        FSDP_AXIS,
        ShardLargest,
        match_partition_rules,
    )
    tree = ({"mu": {"conv/kernel": jnp.zeros((8, 8))}},
            {"count": jnp.zeros(())})
    specs = match_partition_rules(
        ((r".*", ShardLargest(FSDP_AXIS)),), tree, self._mesh(),
        min_size_to_shard=1)
    assert specs[0]["mu"]["conv/kernel"] == P(FSDP_AXIS, None)
    assert specs[1]["count"] == P()  # scalars always replicate

  def test_coverage_checker_reports_unmatched_and_dead(self):
    from tensor2robot_tpu.parallel import (
        Replicate,
        ShardLargest,
        check_rules_coverage,
    )
    rules = ((r"/never_matches$", Replicate()),
             (r"/kernel$", ShardLargest()),
             (r".*", Replicate()))
    unmatched, dead = check_rules_coverage(
        ((r"/kernel$", ShardLargest()),),
        [{"a": {"kernel": jnp.zeros((4,)), "bias": jnp.zeros((4,))}}])
    assert unmatched == ["a/bias"] and dead == []
    unmatched, dead = check_rules_coverage(
        rules, [{"a": {"kernel": jnp.zeros((4,))}}])
    assert unmatched == [] and dead == [r"/never_matches$"]

  def test_every_family_table_covers_its_models(self):
    """The in-repo twin of t2rcheck GIN108: each family's table
    matches every param of its canonical models, no dead regexes."""
    from tensor2robot_tpu.parallel import (
        FAMILY_RULES,
        check_rules_coverage,
        family_param_templates,
        family_rules,
    )
    for family in FAMILY_RULES:
      unmatched, dead = check_rules_coverage(
          family_rules(family), family_param_templates(family))
      assert not unmatched, (family, unmatched)
      assert not dead, (family, dead)

  def test_shard_and_gather_fns_roundtrip(self):
    import jax
    from tensor2robot_tpu.parallel import (
        FSDP_AXIS,
        ShardLargest,
        make_shard_and_gather_fns,
        match_partition_rules,
    )
    mesh = self._mesh()
    tree = {"w": np.arange(32, dtype=np.float32).reshape(8, 4),
            "b": np.zeros((4,), np.float32)}
    specs = match_partition_rules(
        ((r".*", ShardLargest(FSDP_AXIS)),), tree, mesh,
        min_size_to_shard=1)
    shard_fns, gather_fns = make_shard_and_gather_fns(mesh, specs)
    on_device = jax.tree_util.tree_map(lambda f, x: f(x), shard_fns,
                                       tree)
    assert on_device["w"].sharding.spec == P(FSDP_AXIS, None)
    back = jax.tree_util.tree_map(lambda f, x: f(x), gather_fns,
                                  on_device)
    np.testing.assert_array_equal(back["w"], tree["w"])
    np.testing.assert_array_equal(back["b"], tree["b"])


class TestStrategySpecRegression:
  """THE refactor pin: all five mesh strategies produce specs
  identical to their pre-refactor tree-walk implementations on the
  8-device MULTICHIP axis — frozen legacy copies below, diffed
  spec-for-spec over a tree with conv/dense kernels, stacked experts,
  stage stacks, optimizer mirrors, odd shapes, and scalars."""

  @staticmethod
  def _legacy_fsdp(mesh, tree, min_size_to_shard=2 ** 10):
    import jax
    from jax.sharding import NamedSharding
    from tensor2robot_tpu.parallel import FSDP_AXIS
    if FSDP_AXIS not in mesh.axis_names:
      repl = NamedSharding(mesh, P())
      return jax.tree_util.tree_map(lambda _: repl, tree)
    size = mesh.shape[FSDP_AXIS]

    def rule(leaf):
      shape = getattr(leaf, "shape", ())
      if not shape or int(np.prod(shape)) < min_size_to_shard:
        return NamedSharding(mesh, P())
      order = sorted(range(len(shape)), key=lambda i: -shape[i])
      for dim in order:
        if shape[dim] % size == 0:
          spec = [None] * len(shape)
          spec[dim] = FSDP_AXIS
          return NamedSharding(mesh, P(*spec))
      return NamedSharding(mesh, P())

    return jax.tree_util.tree_map(rule, tree)

  @staticmethod
  def _legacy_tp(mesh, tree, min_size_to_shard=2 ** 12):
    import jax
    from jax.sharding import NamedSharding
    from tensor2robot_tpu.parallel import FSDP_AXIS, MODEL_AXIS
    legacy_fsdp = TestStrategySpecRegression._legacy_fsdp
    if MODEL_AXIS not in mesh.axis_names:
      return legacy_fsdp(mesh, tree, min_size_to_shard)
    tp = mesh.shape[MODEL_AXIS]
    fsdp = mesh.shape.get(FSDP_AXIS, 1)
    has_fsdp = FSDP_AXIS in mesh.axis_names

    def rule(leaf):
      shape = getattr(leaf, "shape", ())
      if not shape or int(np.prod(shape)) < min_size_to_shard:
        return NamedSharding(mesh, P())
      if len(shape) >= 2 and shape[-1] % tp == 0:
        spec = [None] * len(shape)
        spec[-1] = MODEL_AXIS
        if has_fsdp and shape[-2] % fsdp == 0:
          spec[-2] = FSDP_AXIS
        return NamedSharding(mesh, P(*spec))
      if shape[-1] % tp == 0:
        return NamedSharding(mesh, P(*([None] * (len(shape) - 1)),
                                     MODEL_AXIS))
      return NamedSharding(mesh, P())

    return jax.tree_util.tree_map(rule, tree)

  @staticmethod
  def _legacy_expert(mesh, tree, min_size_to_shard=2 ** 10):
    import jax
    from jax.sharding import NamedSharding
    legacy_fsdp = TestStrategySpecRegression._legacy_fsdp
    if EXPERT_AXIS not in mesh.axis_names:
      return legacy_fsdp(mesh, tree, min_size_to_shard)
    size = mesh.shape[EXPERT_AXIS]

    def name_of(key):
      return str(getattr(key, "key", getattr(key, "name", "")))

    def rule(path, leaf):
      shape = getattr(leaf, "shape", ())
      is_expert = bool(
          path and name_of(path[-1]).startswith("moe_expert_"))
      if is_expert:
        return NamedSharding(mesh, P(EXPERT_AXIS))
      return legacy_fsdp(mesh, leaf, min_size_to_shard)

    return jax.tree_util.tree_map_with_path(rule, tree)

  @staticmethod
  def _legacy_pipeline(mesh, tree, min_size_to_shard=2 ** 10):
    import jax
    from jax.sharding import NamedSharding
    from tensor2robot_tpu.parallel import STAGE_AXIS
    legacy_fsdp = TestStrategySpecRegression._legacy_fsdp
    if STAGE_AXIS not in mesh.axis_names:
      return legacy_fsdp(mesh, tree, min_size_to_shard)

    def name_of(key):
      return str(getattr(key, "key", getattr(key, "name", "")))

    def rule(path, leaf):
      if any(name_of(key) == "stages" for key in path):
        return NamedSharding(mesh, P(STAGE_AXIS))
      return legacy_fsdp(mesh, leaf, min_size_to_shard)

    return jax.tree_util.tree_map_with_path(rule, tree)

  @staticmethod
  def _legacy_replicated(mesh, tree, min_size_to_shard=0):
    import jax
    from jax.sharding import NamedSharding
    del min_size_to_shard
    repl = NamedSharding(mesh, P())
    return jax.tree_util.tree_map(lambda _: repl, tree)

  def _rich_tree(self, experts=8, stages=4):
    """Conv/dense/bn leaves + stacked experts + stage stacks + an Adam
    mirror + odd/scalar leaves — every code path the strategies take."""
    params = {
        "torso_conv_0": {"kernel": jnp.zeros((3, 3, 3, 64))},
        "torso_bn_0": {"scale": jnp.zeros((64,)),
                       "bias": jnp.zeros((64,))},
        "q_head": {"dense_0": {"kernel": jnp.zeros((128, 64)),
                               "bias": jnp.zeros((64,))}},
        "odd": {"kernel": jnp.zeros((37, 41))},
        "tiny": {"kernel": jnp.zeros((4, 4))},
        "moe": {"moe_expert_w_in": jnp.zeros((experts, 64, 128)),
                "router": jnp.zeros((64, experts))},
        "stages": {"attn": {"kernel": jnp.zeros((stages, 64, 64))}},
        "scalar": jnp.zeros(()),
    }
    return {"params": params,
            "opt_state": {"mu": params, "nu": params}}

  MESHES = (
      {DATA_AXIS: 8},
      {DATA_AXIS: 4, "fsdp": 2},
      {DATA_AXIS: 2, "fsdp": 2, "model": 2},
      {DATA_AXIS: 2, EXPERT_AXIS: 4},
      {DATA_AXIS: 2, "stage": 4},
      {"fsdp": 8},
  )

  @pytest.mark.parametrize("strategy,legacy_name", [
      ("fsdp", "_legacy_fsdp"),
      ("tp", "_legacy_tp"),
      ("ep", "_legacy_expert"),
      ("pipeline", "_legacy_pipeline"),
      ("replicated", "_legacy_replicated"),
  ])
  def test_strategy_specs_identical_to_legacy(self, strategy,
                                              legacy_name):
    import jax
    from tensor2robot_tpu.parallel import state_sharding
    legacy = getattr(self, legacy_name)
    tree = self._rich_tree()
    for axes in self.MESHES:
      mesh = create_mesh(dict(axes))
      got = state_sharding(mesh, tree, strategy=strategy)
      # state_sharding forwards its min_size default to every
      # strategy — mirror that in the legacy call.
      want = legacy(mesh, tree, min_size_to_shard=2 ** 10)
      flat_got = jax.tree_util.tree_leaves_with_path(got)
      flat_want = jax.tree_util.tree_leaves(want)
      assert len(flat_got) == len(flat_want)
      for (path, g), w in zip(flat_got, flat_want):
        assert g == w, (strategy, axes,
                        jax.tree_util.keystr(path), g.spec, w.spec)

  def test_update_sharding_axis_parameter(self):
    """`data_update_sharding(axis=...)` / `train_state_update_sharding
    (axis=...)` ride any named axis — the pod-axis ZeRO composition."""
    import jax
    from jax.sharding import Mesh
    from tensor2robot_tpu.parallel.sharding import (
        data_update_sharding,
        train_state_update_sharding,
    )
    mesh = Mesh(np.asarray(jax.devices()[:8]), ("pod",))
    tree = {"opt_state": {"mu": {"kernel": jnp.zeros((64, 64))}},
            "params": {"kernel": jnp.zeros((64, 64))}}
    upd = data_update_sharding(mesh, tree["opt_state"], axis="pod")
    assert upd["mu"]["kernel"].spec == P("pod", None)
    full = train_state_update_sharding(mesh, tree, axis="pod")
    assert full["opt_state"]["mu"]["kernel"].spec == P("pod", None)
    assert full["params"]["kernel"].spec == P()
