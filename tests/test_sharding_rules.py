"""Unit tests for the sharding-rule contracts.

`expert_sharding` keys on the dedicated ``moe_expert_`` leaf prefix
OWNED by `MoEMLP` — mount-point independent, so experts shard no
matter what module name the trunk instantiates its MoEMLP under. (The
previous contract required the parent module to be literally named
``moe``, which silently replicated experts under any other mount —
the round-5 advisor finding these tests regression-pin.) Indivisible
expert dims raise instead of silently falling back.
`xplane.is_async_window` (the compute-table filter behind the bench's
per-op attribution) gets direct unit coverage too.
"""

import numpy as np
import pytest

import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from tensor2robot_tpu.parallel import (
    DATA_AXIS,
    EXPERT_AXIS,
    create_mesh,
    expert_sharding,
)
from tensor2robot_tpu.utils import xplane


class TestExpertShardingScope:

  @pytest.fixture()
  def mesh(self):
    return create_mesh({DATA_AXIS: 2, EXPERT_AXIS: 4})

  def test_expert_leaf_under_moe_shards_on_expert(self, mesh):
    tree = {"block1": {"moe": {
        "moe_expert_w_in": jnp.zeros((8, 16, 32))}}}
    sh = expert_sharding(mesh, tree, min_size_to_shard=64)
    assert sh["block1"]["moe"]["moe_expert_w_in"].spec == P(EXPERT_AXIS)

  def test_renamed_mount_still_shards(self, mesh):
    """THE regression for the round-5 finding: a MoEMLP mounted under
    a name other than 'moe' (here 'ffn_sparse') must still shard its
    experts — the old parent-name contract silently replicated them."""
    tree = {"block1": {"ffn_sparse": {
        "moe_expert_w_in": jnp.zeros((8, 16, 32)),
        "router": jnp.zeros((16, 8))}}}
    sh = expert_sharding(mesh, tree, min_size_to_shard=64)
    assert sh["block1"]["ffn_sparse"]["moe_expert_w_in"].spec == P(
        EXPERT_AXIS)
    # The router is not an expert weight wherever it lives.
    router_spec = sh["block1"]["ffn_sparse"]["router"].spec
    assert EXPERT_AXIS not in [ax for ax in router_spec if ax]

  def test_root_level_expert_leaf_shards(self, mesh):
    """A bare MoEMLP param tree has expert leaves at the root."""
    sh = expert_sharding(
        mesh, {"moe_expert_w_in": jnp.zeros((8, 16, 32))},
        min_size_to_shard=64)
    assert sh["moe_expert_w_in"].spec == P(EXPERT_AXIS)

  def test_optimizer_mirror_path_shards_too(self, mesh):
    """Adam moments nest the param path under opt-state prefixes; the
    leaf-name rule must still match."""
    tree = {"mu": {"trunk": {"moe": {
        "moe_expert_w_out": jnp.zeros((8, 32, 16))}}}}
    sh = expert_sharding(mesh, tree, min_size_to_shard=64)
    assert sh["mu"]["trunk"]["moe"]["moe_expert_w_out"].spec == P(
        EXPERT_AXIS)

  def test_expert_prefixed_leaf_outside_contract_uses_fsdp(self, mesh):
    """The advisor's collision case: `expert_`-prefixed params that
    are NOT MoEMLP's stacked weights (the prefix is `moe_expert_`,
    which only MoEMLP may use) follow the fsdp rules — with no fsdp
    axis in this mesh, replicate — instead of landing on the expert
    axis."""
    tree = {"policy": {"expert_demo_encoder": jnp.zeros((8, 64, 64))},
            "moe": {"expert_w_in": jnp.zeros((8, 16, 32))}}
    sh = expert_sharding(mesh, tree, min_size_to_shard=64)
    for leaf in (sh["policy"]["expert_demo_encoder"],
                 sh["moe"]["expert_w_in"]):
      assert EXPERT_AXIS not in [ax for ax in leaf.spec if ax], leaf

  def test_indivisible_expert_dim_raises(self, mesh):
    tree = {"moe": {"moe_expert_w_in": jnp.zeros((6, 16, 32))}}
    with pytest.raises(ValueError, match="not divisible"):
      expert_sharding(mesh, tree, min_size_to_shard=64)

  def test_no_expert_axis_falls_back_to_fsdp(self):
    mesh = create_mesh({DATA_AXIS: 8})
    tree = {"moe": {"moe_expert_w_in": jnp.zeros((6, 16, 32))}}
    # No expert axis: the indivisible dim is irrelevant; fsdp rules
    # (here: replicated) apply without raising.
    sh = expert_sharding(mesh, tree, min_size_to_shard=64)
    assert sh["moe"]["moe_expert_w_in"].spec == P()

  def test_moe_mlp_param_names_carry_the_contract_prefix(self):
    """The rule and the module must agree: every stacked expert param
    MoEMLP creates is `moe_expert_`-prefixed (if this breaks, experts
    replicate silently on pods)."""
    import jax as _jax
    from tensor2robot_tpu.parallel.moe import MoEMLP

    module = MoEMLP(num_experts=4, hidden_dim=8, dtype=jnp.float32)
    params = module.init(
        _jax.random.PRNGKey(0), jnp.zeros((2, 4, 8)))["params"]
    stacked = [name for name, leaf in params.items()
               if np.asarray(leaf).ndim and
               np.asarray(leaf).shape[0] == 4]
    assert stacked, params.keys()
    for name in stacked:
      assert name.startswith("moe_expert_"), name


class TestAsyncWindowFilter:
  """The per-op compute filter: -start/-done spans are wall windows
  overlapping compute (round-4's committed tables were 10/10
  copy-starts), so they must be excluded from busy-time attribution
  — and ONLY they."""

  @pytest.mark.parametrize("name", [
      "%copy-start.113 = (f32[64]...) copy-start(...)",
      "%copy-done.77 = f32[64] copy-done(...)",
      "%all-gather-start.3 = ...",
      "%all-reduce-done.9 = ...",
      "%collective-permute-start.1 = ...",
  ])
  def test_async_windows_match(self, name):
    assert xplane.is_async_window(name)

  @pytest.mark.parametrize("name", [
      "%fusion.481 = bf16[256,16,16,64] fusion(...)",
      "%convert_reduce_fusion.27 = f32[16384,64] fusion(...)",
      "%convolution.12 = ...",
      "%all-reduce.4 = ...",          # sync collective: busy time
      "%custom-call.5 = ...",
      "%multiply_add_fusion.153 = ...",
  ])
  def test_compute_ops_pass(self, name):
    assert not xplane.is_async_window(name)

  def test_top_ops_compute_only_drops_windows(self, tmp_path,
                                              monkeypatch):
    monkeypatch.setattr(
        xplane, "op_times_ms",
        lambda trace_dir, plane_filter="TPU": {
            "%copy-start.1": 75.0,
            "%fusion.2": 50.0,
            "%while": 400.0,
            "%convolution.3": 25.0,
        })
    got = xplane.top_ops("unused", k=10, hlo_only=True,
                         compute_only=True)
    assert got == [("%fusion.2", 50.0), ("%convolution.3", 25.0)]
