"""Unit tests for the sharding-rule contracts tightened in round 5.

The advisor flagged `expert_sharding`'s name matching as too loose
(any path segment starting with ``expert_``) and its indivisible-dim
fallback as silent; the rule now requires the MoEMLP placement
contract (an ``expert_*`` leaf directly under a ``moe`` module, or at
the tree root for a bare MoEMLP tree) and raises on indivisibility.
`xplane.is_async_window` (the compute-table filter behind the bench's
per-op attribution) gets direct unit coverage too.
"""

import numpy as np
import pytest

import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from tensor2robot_tpu.parallel import (
    DATA_AXIS,
    EXPERT_AXIS,
    create_mesh,
    expert_sharding,
)
from tensor2robot_tpu.utils import xplane


class TestExpertShardingScope:

  @pytest.fixture()
  def mesh(self):
    return create_mesh({DATA_AXIS: 2, EXPERT_AXIS: 4})

  def test_expert_leaf_under_moe_shards_on_expert(self, mesh):
    tree = {"block1": {"moe": {"expert_w_in": jnp.zeros((8, 16, 32))}}}
    sh = expert_sharding(mesh, tree, min_size_to_shard=64)
    assert sh["block1"]["moe"]["expert_w_in"].spec == P(EXPERT_AXIS)

  def test_root_level_expert_leaf_shards(self, mesh):
    """A bare MoEMLP param tree has expert leaves at the root."""
    sh = expert_sharding(mesh, {"expert_w_in": jnp.zeros((8, 16, 32))},
                         min_size_to_shard=64)
    assert sh["expert_w_in"].spec == P(EXPERT_AXIS)

  def test_optimizer_mirror_path_shards_too(self, mesh):
    """Adam moments nest the param path under opt-state prefixes; the
    (parent == moe) scope must still match."""
    tree = {"mu": {"trunk": {"moe": {
        "expert_w_out": jnp.zeros((8, 32, 16))}}}}
    sh = expert_sharding(mesh, tree, min_size_to_shard=64)
    assert sh["mu"]["trunk"]["moe"]["expert_w_out"].spec == P(
        EXPERT_AXIS)

  def test_unrelated_expert_prefixed_leaf_uses_fsdp_rules(self, mesh):
    """The advisor's collision case: an `expert_`-prefixed param NOT
    under a moe module (here under an unrelated module) must follow
    the fsdp rules — with no fsdp axis in this mesh, replicate —
    instead of silently landing on the expert axis."""
    tree = {"policy": {"expert_demo_encoder": jnp.zeros((8, 64, 64))}}
    sh = expert_sharding(mesh, tree, min_size_to_shard=64)
    spec = sh["policy"]["expert_demo_encoder"].spec
    assert EXPERT_AXIS not in [ax for ax in spec if ax], spec

  def test_indivisible_expert_dim_raises(self, mesh):
    tree = {"moe": {"expert_w_in": jnp.zeros((6, 16, 32))}}
    with pytest.raises(ValueError, match="not divisible"):
      expert_sharding(mesh, tree, min_size_to_shard=64)

  def test_no_expert_axis_falls_back_to_fsdp(self):
    mesh = create_mesh({DATA_AXIS: 8})
    tree = {"moe": {"expert_w_in": jnp.zeros((6, 16, 32))}}
    # No expert axis: the indivisible dim is irrelevant; fsdp rules
    # (here: replicated) apply without raising.
    sh = expert_sharding(mesh, tree, min_size_to_shard=64)
    assert sh["moe"]["expert_w_in"].spec == P()


class TestAsyncWindowFilter:
  """The per-op compute filter: -start/-done spans are wall windows
  overlapping compute (round-4's committed tables were 10/10
  copy-starts), so they must be excluded from busy-time attribution
  — and ONLY they."""

  @pytest.mark.parametrize("name", [
      "%copy-start.113 = (f32[64]...) copy-start(...)",
      "%copy-done.77 = f32[64] copy-done(...)",
      "%all-gather-start.3 = ...",
      "%all-reduce-done.9 = ...",
      "%collective-permute-start.1 = ...",
  ])
  def test_async_windows_match(self, name):
    assert xplane.is_async_window(name)

  @pytest.mark.parametrize("name", [
      "%fusion.481 = bf16[256,16,16,64] fusion(...)",
      "%convert_reduce_fusion.27 = f32[16384,64] fusion(...)",
      "%convolution.12 = ...",
      "%all-reduce.4 = ...",          # sync collective: busy time
      "%custom-call.5 = ...",
      "%multiply_add_fusion.153 = ...",
  ])
  def test_compute_ops_pass(self, name):
    assert not xplane.is_async_window(name)

  def test_top_ops_compute_only_drops_windows(self, tmp_path,
                                              monkeypatch):
    monkeypatch.setattr(
        xplane, "op_times_ms",
        lambda trace_dir, plane_filter="TPU": {
            "%copy-start.1": 75.0,
            "%fusion.2": 50.0,
            "%while": 400.0,
            "%convolution.3": 25.0,
        })
    got = xplane.top_ops("unused", k=10, hlo_only=True,
                         compute_only=True)
    assert got == [("%fusion.2", 50.0), ("%convolution.3", 25.0)]
