"""Tests for the low-latency serving path (serving/ + predictors).

Pins the contracts docs/SERVING.md promises:
  * bucket table / padding math;
  * the micro-batcher coalesces N concurrent callers into fewer
    dispatches and every caller gets exactly its own rows;
  * bucket padding never changes real rows' outputs (bitwise, within
    one compiled program);
  * zero recompiles on the hot path after AOT warmup (engine compile
    counter AND jax.monitoring compile events);
  * checkpoint hot-swap mid-traffic serves only fully-restored params
    (old or new tree per dispatch, never a mix);
  * the `bench.py --serving --dry-run` smoke path runs on CPU.

Numerics note: XLA specializes code per batch shape, so outputs of
DIFFERENT bucket programs may differ by float-associativity ulps;
cross-program comparisons use a 1e-5 tolerance while same-program
comparisons (the padding-invariance pin) are exact.
"""

import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tensor2robot_tpu import specs
from tensor2robot_tpu.data.abstract_input_generator import Mode
from tensor2robot_tpu.predictors import CheckpointPredictor
from tensor2robot_tpu.serving import (
    BucketedServingEngine,
    MicroBatcher,
    bucket_for,
    bucket_table,
    pad_batch,
)
from tensor2robot_tpu.serving import engine as engine_lib
from tensor2robot_tpu.utils.mocks import MockT2RModel


def _wire_spec(model):
  return specs.flatten_spec_structure(
      model.preprocessor.get_in_feature_specification(Mode.PREDICT))


def _make_engine(max_batch=8, warmed=True):
  model = MockT2RModel()
  state = model.create_inference_state(jax.random.PRNGKey(0))
  example = specs.make_random_tensors(_wire_spec(model), batch_size=1,
                                      seed=0)
  engine = BucketedServingEngine(model.predict_step, state, example,
                                 max_batch=max_batch)
  if warmed:
    engine.warmup()
  return model, engine


class TestBucketing:

  def test_bucket_table_powers_of_two(self):
    assert bucket_table(1) == (1,)
    assert bucket_table(8) == (1, 2, 4, 8)
    assert bucket_table(6) == (1, 2, 4, 8)  # covers max_batch

  def test_bucket_for_picks_smallest_cover(self):
    table = bucket_table(8)
    assert bucket_for(1, table) == 1
    assert bucket_for(3, table) == 4
    assert bucket_for(8, table) == 8

  def test_bucket_for_overflow_raises(self):
    with pytest.raises(ValueError, match="exceeds"):
      bucket_for(9, bucket_table(8))

  def test_pad_batch_replicates_last_row(self):
    tree = {"x": np.arange(6, dtype=np.float32).reshape(3, 2)}
    padded = pad_batch(tree, 4)
    assert padded["x"].shape == (4, 2)
    np.testing.assert_array_equal(padded["x"][3], tree["x"][2])


class TestEngine:

  def test_outputs_match_plain_predict_step(self):
    model, engine = _make_engine()
    batch = specs.make_random_tensors(_wire_spec(model), batch_size=3,
                                      seed=1)
    state = model.create_inference_state(jax.random.PRNGKey(0))
    want = jax.jit(model.predict_step)(state, batch)
    got = engine.predict(batch)
    np.testing.assert_allclose(
        jax.tree_util.tree_leaves(got)[0],
        np.asarray(jax.tree_util.tree_leaves(want)[0])[:3], atol=1e-5)

  def test_padding_never_changes_outputs(self):
    """Bitwise pin, same compiled program: a 3-row request (padded
    3→4) and a 4-row request whose first 3 rows are identical must
    produce identical leading rows — pad rows cannot leak."""
    model, engine = _make_engine()
    three = specs.make_random_tensors(_wire_spec(model), batch_size=3,
                                      seed=2)
    flat3 = three.to_flat_dict()
    flat4 = {k: np.concatenate(
        [v, np.full_like(v[-1:], 7.25)]) for k, v in flat3.items()}
    out3 = engine.predict(specs.TensorSpecStruct.from_flat_dict(flat3))
    out4 = engine.predict(specs.TensorSpecStruct.from_flat_dict(flat4))
    np.testing.assert_array_equal(
        jax.tree_util.tree_leaves(out3)[0],
        jax.tree_util.tree_leaves(out4)[0][:3])

  def test_zero_recompiles_after_warmup(self):
    """THE perf contract: after warmup, no request size ≤ max_batch
    may trigger a compile — counted by the engine AND by
    jax.monitoring compile events."""
    import jax.monitoring as monitoring

    model, engine = _make_engine(max_batch=8)
    before = engine_lib.compile_count()
    events = []
    watching = {"on": True}

    def _listener(event, **kwargs):
      if watching["on"] and "compile" in event.lower():
        events.append(event)

    monitoring.register_event_listener(_listener)
    try:
      for n in (1, 2, 3, 4, 5, 7, 8, 1, 6):
        batch = specs.make_random_tensors(_wire_spec(model),
                                          batch_size=n, seed=n)
        out = engine.predict(batch)
        assert jax.tree_util.tree_leaves(out)[0].shape[0] == n
    finally:
      watching["on"] = False
    assert engine_lib.compile_count() == before
    assert not events, events
    assert engine.compiled_buckets == (1, 2, 4, 8)

  def test_hot_swap_serves_only_full_trees(self):
    """Mid-traffic checkpoint refresh: every dispatch must see an
    entirely-old or entirely-new params tree. Params are constant
    trees (c and c+1000), so a mixed tree would produce outputs in
    neither program's value band."""
    model, engine = _make_engine(max_batch=2)
    spec = _wire_spec(model)
    state = model.create_inference_state(jax.random.PRNGKey(0))

    def constant_state(c):
      return state.replace(params=jax.tree_util.tree_map(
          lambda a: jnp.full_like(a, c), state.params))

    batch = specs.make_random_tensors(spec, batch_size=1, seed=3)
    engine.swap_state(constant_state(1.0))
    want_old = jax.tree_util.tree_leaves(engine.predict(batch))[0]
    engine.swap_state(constant_state(1001.0))
    want_new = jax.tree_util.tree_leaves(engine.predict(batch))[0]
    engine.swap_state(constant_state(1.0))

    stop = threading.Event()
    bad = []

    def traffic():
      while not stop.is_set():
        got = jax.tree_util.tree_leaves(engine.predict(batch))[0]
        if not (np.array_equal(got, want_old)
                or np.array_equal(got, want_new)):
          bad.append(got)

    threads = [threading.Thread(target=traffic) for _ in range(2)]
    for t in threads:
      t.start()
    for c in (1001.0, 1.0, 1001.0, 1.0, 1001.0):
      engine.swap_state(constant_state(c))
    time.sleep(0.05)
    stop.set()
    for t in threads:
      t.join(timeout=30)
    assert not bad, bad[:1]
    assert engine.swap_count >= 7


class TestMicroBatcher:

  def test_concurrent_callers_coalesce_into_fewer_dispatches(self):
    model, engine = _make_engine(max_batch=8)
    spec = _wire_spec(model)
    batcher = MicroBatcher(engine, max_wait_us=100_000)
    barrier = threading.Barrier(6)
    results = {}

    def caller(i):
      batch = specs.make_random_tensors(spec, batch_size=1, seed=50 + i)
      barrier.wait()
      results[i] = batcher.predict(batch)

    threads = [threading.Thread(target=caller, args=(i,))
               for i in range(6)]
    for t in threads:
      t.start()
    for t in threads:
      t.join(timeout=60)
    batcher.close()
    assert len(results) == 6
    # Coalescing: 6 single-row callers in strictly fewer dispatches
    # (the first dispatch may race ahead with fewer rows queued).
    assert batcher.dispatches < 6, batcher.batch_sizes
    assert sum(batcher.batch_sizes) == 6
    # Per-caller results equal the unbatched predict of the same rows
    # (1e-5: coalesced rows may run a different bucket's program).
    for i in range(6):
      batch = specs.make_random_tensors(spec, batch_size=1, seed=50 + i)
      direct = engine.predict(batch)
      np.testing.assert_allclose(
          jax.tree_util.tree_leaves(results[i])[0],
          jax.tree_util.tree_leaves(direct)[0], atol=1e-5)

  def test_single_request_fallback_no_deadline_hold(self):
    """max_wait_us=0: a lone request dispatches immediately (the
    graceful degradation to the classic one-request path)."""
    model, engine = _make_engine(max_batch=8)
    spec = _wire_spec(model)
    with MicroBatcher(engine, max_wait_us=0) as batcher:
      batch = specs.make_random_tensors(spec, batch_size=1, seed=9)
      out = batcher.predict(batch)
      assert jax.tree_util.tree_leaves(out)[0].shape[0] == 1
      assert batcher.dispatches == 1

  def test_oversized_request_rejected(self):
    model, engine = _make_engine(max_batch=4)
    spec = _wire_spec(model)
    with MicroBatcher(engine, max_wait_us=0) as batcher:
      batch = specs.make_random_tensors(spec, batch_size=5, seed=4)
      with pytest.raises(ValueError, match="max_batch"):
        batcher.predict(batch)

  def test_submit_after_close_fails_fast(self):
    """ISSUE 13 satellite: a submit after close() must raise a clear
    error immediately — never enqueue into the dead dispatcher and
    strand its caller on a future that will never resolve."""
    model, engine = _make_engine(max_batch=4)
    spec = _wire_spec(model)
    batcher = MicroBatcher(engine, max_wait_us=0)
    batch = specs.make_random_tensors(spec, batch_size=1, seed=11)
    assert jax.tree_util.tree_leaves(
        batcher.predict(batch))[0].shape[0] == 1
    batcher.close()
    t0 = time.perf_counter()
    with pytest.raises(RuntimeError, match="closed"):
      batcher.submit(batch)
    assert time.perf_counter() - t0 < 1.0  # fail FAST, not a timeout
    # Idempotent close keeps the contract.
    batcher.close()
    with pytest.raises(RuntimeError, match="closed"):
      batcher.predict(batch)

  def test_dispatch_errors_propagate_to_callers(self):
    model, engine = _make_engine(max_batch=4)
    with MicroBatcher(engine, max_wait_us=0) as batcher:
      # Wrong feature structure dies inside the dispatch; the caller
      # must receive the exception, not hang.
      with pytest.raises(Exception):
        batcher.predict({"not_the_spec": np.zeros((1, 3), np.float32)})


class TestServingCheckpointPredictor:

  def test_serving_mode_matches_classic_path(self):
    model = MockT2RModel()
    serving = CheckpointPredictor(model, max_batch=4)
    classic = CheckpointPredictor(model)
    serving.init_randomly()
    classic.init_randomly()
    batch = specs.make_random_tensors(
        serving.feature_specification, batch_size=3, seed=6)
    flat = batch.to_flat_dict()
    got = serving.predict(flat)
    want = classic.predict(flat)
    assert set(got) == set(want)
    for k in got:
      np.testing.assert_allclose(got[k], want[k], atol=1e-5)
    assert serving.serving_engine.dispatch_count == 1
    serving.close()

  def test_restore_hot_swaps_serving_engine(self, tmp_path):
    from tensor2robot_tpu.data.random_input_generator import (
        RandomInputGenerator,
    )
    from tensor2robot_tpu import train_eval

    model_dir = str(tmp_path / "m")
    model = MockT2RModel()
    train_eval.train_eval_model(
        model=model,
        model_dir=model_dir,
        input_generator_train=RandomInputGenerator(batch_size=8),
        max_train_steps=2,
        save_checkpoints_steps=2,
        log_every_steps=2,
    )
    predictor = CheckpointPredictor(model, checkpoint_dir=model_dir,
                                    max_batch=2)
    swaps_before = predictor.serving_engine.swap_count
    assert predictor.restore(timeout_secs=0)
    assert predictor.serving_engine.swap_count == swaps_before + 1
    batch = specs.make_random_tensors(
        predictor.feature_specification, batch_size=2, seed=8)
    out = predictor.predict(batch.to_flat_dict())
    assert next(iter(out.values())).shape[0] == 2
    predictor.close()


class TestCEMPolicyServer:

  @pytest.fixture(scope="class")
  def server(self):
    from tensor2robot_tpu.research.qtopt import (
        GraspingQModel,
        QTOptLearner,
    )
    from tensor2robot_tpu.serving import CEMPolicyServer

    model = GraspingQModel(image_size=16, torso_filters=(8,),
                           head_filters=(8,), dense_sizes=(16,),
                           action_dim=2, device_dtype=jnp.float32)
    learner = QTOptLearner(model, cem_population=8, cem_iterations=1,
                           cem_elites=2)
    state = learner.create_state(jax.random.PRNGKey(0), batch_size=2)
    server = CEMPolicyServer(learner, state.train_state, max_batch=4,
                             max_wait_us=10_000, seed=0)
    yield learner, server
    server.close()

  def test_action_shapes_and_bounds(self, server):
    learner, srv = server
    obs = specs.make_random_tensors(
        learner.observation_specification(), batch_size=3, seed=1)
    actions = srv.select_actions(obs.to_flat_dict())
    assert actions.shape == (3, 2)
    assert np.all(actions >= -1.0) and np.all(actions <= 1.0)

  def test_concurrent_robots_coalesce(self, server):
    learner, srv = server
    obs_spec = learner.observation_specification()
    barrier = threading.Barrier(4)
    results = {}

    def robot(i):
      obs = specs.make_random_tensors(obs_spec, batch_size=1,
                                      seed=20 + i)
      barrier.wait()
      results[i] = srv.select_actions(obs.to_flat_dict())

    d0 = srv.batcher.dispatches
    threads = [threading.Thread(target=robot, args=(i,))
               for i in range(4)]
    for t in threads:
      t.start()
    for t in threads:
      t.join(timeout=120)
    assert len(results) == 4
    assert all(results[i].shape == (1, 2) for i in results)
    assert srv.batcher.dispatches - d0 < 4


class TestServingAssets:
  """The export→fleet serving contract: the exporter ships its
  recommended bucket table in the asset payload; the SavedModel
  predictor surfaces it."""

  def test_serving_metadata_round_trips_through_export(self, tmp_path):
    from tensor2robot_tpu.export import SavedModelExportGenerator
    from tensor2robot_tpu.predictors import SavedModelPredictor

    model = MockT2RModel()
    state = model.create_inference_state(jax.random.PRNGKey(0))
    model_dir = str(tmp_path)
    SavedModelExportGenerator(serving_max_batch=8).export(
        model, jax.device_get(state), model_dir)
    predictor = SavedModelPredictor(
        str(tmp_path / "export"))
    assert predictor.restore(timeout_secs=0)
    meta = predictor.serving_metadata
    assert meta == {"max_batch": 8, "bucket_sizes": [1, 2, 4, 8],
                    "max_wait_us": 200}

  def test_no_metadata_without_opt_in(self, tmp_path):
    from tensor2robot_tpu.export import SavedModelExportGenerator
    from tensor2robot_tpu.predictors import SavedModelPredictor

    model = MockT2RModel()
    state = model.create_inference_state(jax.random.PRNGKey(0))
    SavedModelExportGenerator().export(
        model, jax.device_get(state), str(tmp_path))
    predictor = SavedModelPredictor(str(tmp_path / "export"))
    assert predictor.restore(timeout_secs=0)
    assert predictor.serving_metadata is None


class TestServingBenchSmoke:
  """`bench.py --serving --dry-run` must keep working on CPU — it is
  the tier-1 guard on the serving bench path itself."""

  def test_dry_run_smoke(self):
    import importlib
    import sys as _sys

    _sys.path.insert(0, ".")
    try:
      bench = importlib.import_module("bench")
    finally:
      _sys.path.pop(0)
    detail = bench.bench_serving(dry_run=True)
    assert detail["batch_1"]["calls"] >= 3
    assert detail["batch_1"]["p50_ms"] > 0
    assert detail["recompiles_during_timed_phases"] == 0
    assert detail["microbatcher_curve"]
