"""Pallas flash attention: interpret-mode numerics on the CPU suite.

The kernel's compiled path is exercised on real TPU hardware (bench /
driver); here the pallas interpreter verifies the math — exactness
against the reference oracle, causal masking, block-size independence.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tensor2robot_tpu.ops import flash_attention
from tensor2robot_tpu.parallel import attention_reference

B, T, H, D = 2, 256, 2, 64


def _qkv(seed=0, dtype=jnp.float32):
  rng = np.random.default_rng(seed)
  return tuple(
      jnp.asarray(rng.standard_normal((B, T, H, D)), dtype)
      for _ in range(3))


class TestFlashAttention:

  @pytest.mark.parametrize("causal", [False, True])
  def test_matches_reference(self, causal):
    q, k, v = _qkv()
    out = flash_attention(q, k, v, causal=causal, block_q=64,
                          block_k=64, interpret=True)
    ref = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-6, rtol=2e-6)

  def test_block_size_independence(self):
    """The online softmax must not depend on the tiling."""
    q, k, v = _qkv(1)
    outs = [
        np.asarray(flash_attention(q, k, v, causal=True, block_q=bq,
                                   block_k=bk, interpret=True))
        for bq, bk in ((256, 256), (64, 128), (32, 32))
    ]
    np.testing.assert_allclose(outs[0], outs[1], atol=2e-6)
    np.testing.assert_allclose(outs[0], outs[2], atol=2e-6)

  def test_odd_length_auto_blocks(self):
    """T not divisible by the requested blocks shrinks them instead of
    failing — exactness is independent of the tiling."""
    rng = np.random.default_rng(4)
    q, k, v = (jnp.asarray(rng.standard_normal((1, 96, 2, 16)),
                           jnp.float32) for _ in range(3))
    out = flash_attention(q, k, v, causal=True, block_q=64,
                          block_k=64, interpret=True)
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-6, rtol=2e-6)

  @pytest.mark.parametrize("causal", [False, True])
  def test_gradients_match_reference(self, causal):
    """The flash custom VJP (logsumexp recompute) == autodiff oracle."""
    q, k, v = _qkv(5)

    def flash_loss(q, k, v):
      return jnp.sum(flash_attention(
          q, k, v, causal=causal, block_q=64, block_k=64,
          interpret=True) ** 2)

    def ref_loss(q, k, v):
      return jnp.sum(attention_reference(q, k, v, causal=causal) ** 2)

    got = jax.grad(flash_loss, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for g, w in zip(got, want):
      np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                 atol=5e-5, rtol=5e-5)

  @pytest.mark.parametrize("causal", [False, True])
  def test_lse_gradients_match_reference(self, causal):
    """Both outputs of `flash_attention_with_lse` carry gradients.

    The lse cotangent folds into the softmax-jacobian diagonal
    (∂lse/∂s = p); the oracle is autodiff through a materialized
    softmax + logsumexp. This is what makes the lse-weighted ring
    combine trainable.
    """
    from tensor2robot_tpu.ops.flash_attention import (
        flash_attention_with_lse,
    )
    q, k, v = _qkv(7)
    scale = 1.0 / np.sqrt(D)

    def flash_loss(q, k, v):
      out, lse = flash_attention_with_lse(
          q, k, v, causal=causal, block_q=64, block_k=64,
          interpret=True)
      # A loss using BOTH outputs, so both cotangents are nonzero.
      return jnp.sum(out ** 2) + jnp.sum(jnp.sin(lse))

    def ref_loss(q, k, v):
      s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
      if causal:
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None, None], s, -1e30)
      lse = jax.scipy.special.logsumexp(s, axis=-1)  # [B, H, T]
      p = jnp.exp(s - lse[..., None])
      out = jnp.einsum("bhqk,bkhd->bqhd", p, v)
      return jnp.sum(out ** 2) + jnp.sum(jnp.sin(lse))

    got = jax.grad(flash_loss, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for g, w in zip(got, want):
      np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                 atol=5e-5, rtol=5e-5)

  def test_matches_ring_attention_math(self):
    """Within-chip tiling and across-chip ring agree (same algorithm)."""
    from tensor2robot_tpu.parallel import (
        SEQ_AXIS,
        create_mesh,
        ring_attention,
        sequence_sharding,
    )
    q, k, v = _qkv(2)
    mesh = create_mesh({SEQ_AXIS: 8})
    sharding = sequence_sharding(mesh)
    ring = ring_attention(
        *(jax.device_put(x, sharding) for x in (q, k, v)),
        mesh=mesh, causal=True)
    flash = flash_attention(q, k, v, causal=True, block_q=64,
                            block_k=64, interpret=True)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(flash),
                               atol=2e-5, rtol=2e-5)
