// Native batch-collation kernels for the host-side data path.
//
// The replay buffer's hot loop is `sample(batch)`: a random row gather
// out of a multi-GB ring buffer into a contiguous batch for the H2D
// infeed (SURVEY.md §4.3 — the host must hide batch assembly behind
// device compute). numpy's fancy-index gather is single-threaded; on
// the many-core hosts that front TPU slices (tens of vCPUs per chip)
// the gather is memory-bound and parallelizes nearly linearly across
// row ranges. This module is that parallel gather: plain C++ threads,
// one contiguous memcpy per row, rows striped across workers.
//
// Exposed as a tiny C ABI consumed via ctypes (no pybind11 in the
// image); `tensor2robot_tpu.utils.native` compiles it on first use and
// falls back to numpy transparently when no toolchain is present.

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

// Copies rows src[idx[i]] -> dst[i] for i in [row_begin, row_end).
void gather_range(const uint8_t* src, const int64_t* idx, uint8_t* dst,
                  int64_t row_bytes, int64_t row_begin,
                  int64_t row_end) {
  for (int64_t i = row_begin; i < row_end; ++i) {
    std::memcpy(dst + i * row_bytes, src + idx[i] * row_bytes,
                static_cast<size_t>(row_bytes));
  }
}

}  // namespace

extern "C" {

// Gathers `num_rows` rows of `row_bytes` bytes each from `src` at
// `idx` into `dst`, using up to `num_threads` workers (<=0: hardware
// concurrency). Caller guarantees idx values are in range and dst has
// num_rows*row_bytes bytes.
void t2r_gather_rows(const uint8_t* src, const int64_t* idx,
                     uint8_t* dst, int64_t num_rows, int64_t row_bytes,
                     int32_t num_threads) {
  if (num_rows <= 0 || row_bytes <= 0) return;
  int64_t workers = num_threads > 0
                        ? num_threads
                        : static_cast<int64_t>(
                              std::thread::hardware_concurrency());
  if (workers < 1) workers = 1;
  // Below ~1 MB of traffic thread spawn costs more than it saves.
  const int64_t total = num_rows * row_bytes;
  if (workers > 1 && total < (1 << 20)) workers = 1;
  if (workers > num_rows) workers = num_rows;
  if (workers == 1) {
    gather_range(src, idx, dst, row_bytes, 0, num_rows);
    return;
  }
  std::vector<std::thread> threads;
  threads.reserve(static_cast<size_t>(workers));
  const int64_t chunk = (num_rows + workers - 1) / workers;
  for (int64_t w = 0; w < workers; ++w) {
    const int64_t begin = w * chunk;
    const int64_t end = begin + chunk < num_rows ? begin + chunk
                                                 : num_rows;
    if (begin >= end) break;
    threads.emplace_back(gather_range, src, idx, dst, row_bytes, begin,
                         end);
  }
  for (auto& t : threads) t.join();
}

// Scatter counterpart for the ring-buffer writer: dst[idx[i]] = src[i].
// Used by batched `add` so multi-MB episode flushes don't serialize on
// one core either.
void t2r_scatter_rows(const uint8_t* src, const int64_t* idx,
                      uint8_t* dst, int64_t num_rows,
                      int64_t row_bytes, int32_t num_threads) {
  if (num_rows <= 0 || row_bytes <= 0) return;
  int64_t workers = num_threads > 0
                        ? num_threads
                        : static_cast<int64_t>(
                              std::thread::hardware_concurrency());
  if (workers < 1) workers = 1;
  const int64_t total = num_rows * row_bytes;
  if (workers > 1 && total < (1 << 20)) workers = 1;
  if (workers > num_rows) workers = num_rows;
  std::vector<std::thread> threads;
  const int64_t chunk = (num_rows + workers - 1) / workers;
  auto scatter_range = [](const uint8_t* s, const int64_t* ix,
                          uint8_t* d, int64_t rb, int64_t b,
                          int64_t e) {
    for (int64_t i = b; i < e; ++i) {
      std::memcpy(d + ix[i] * rb, s + i * rb,
                  static_cast<size_t>(rb));
    }
  };
  if (workers == 1) {
    scatter_range(src, idx, dst, row_bytes, 0, num_rows);
    return;
  }
  threads.reserve(static_cast<size_t>(workers));
  for (int64_t w = 0; w < workers; ++w) {
    const int64_t begin = w * chunk;
    const int64_t end = begin + chunk < num_rows ? begin + chunk
                                                 : num_rows;
    if (begin >= end) break;
    threads.emplace_back(scatter_range, src, idx, dst, row_bytes,
                         begin, end);
  }
  for (auto& t : threads) t.join();
}

}  // extern "C"
