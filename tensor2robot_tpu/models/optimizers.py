"""Configurable optimizer factory on optax.

Reference parity: tensor2robot `models/optimizers.py` — gin-configurable
optimizer creation, learning-rate schedules, gradient clipping, and the
TPU cross-shard wrapping (SURVEY.md §3). TPU-native: there is no
CrossShardOptimizer equivalent to wrap — gradient all-reduce over the
mesh's data axis is inserted by GSPMD when the train step is jitted with
sharded batch / replicated params, riding ICI. What remains configurable
here is the optax chain.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Union

import optax

from tensor2robot_tpu import config as gin

ScheduleOrFloat = Union[float, optax.Schedule]


def shard_weight_update(
    tx: optax.GradientTransformation,
    mesh,
    min_size_to_shard: int = 2 ** 10,
    axis: Optional[str] = None,
) -> optax.GradientTransformation:
  """Shards `tx`'s update across the mesh's data-parallel replicas.

  The GSPMD-constraint form of "Automatic Cross-Replica Sharding of
  Weight Update in Data-Parallel Training" (PAPERS.md): gradients
  entering the chain and the optimizer state/updates leaving it are
  constrained to `parallel.sharding.data_update_sharding` — inside a
  jitted step the compiler then lowers the gradient all-reduce to
  reduce-scatter, runs the (elementwise, weight-sized) moment/update
  math on 1/N of each weight per replica, and all-gathers only the
  final updated params. Pure data-parallel replicas otherwise repeat
  the identical full update N times; at large batch that redundant
  weight-update wall is what caps MFU (the pjit/TPUv4 paper's story).

  Pair with `parallel.sharding.train_state_update_sharding` as the
  carried state's in/out shardings so the moments STAY sharded across
  steps. On a 1-device (or data-less) mesh every constraint is a
  no-op and the step is bitwise identical to `tx` (pinned by tests).

  ``axis`` selects the mesh axis the update shards over (default: the
  jit-mesh `data` axis). The shard_map pod program passes its `pod`
  axis — the composition that retires the old pod-mode warn-ignore
  path (docs/SHARDING.md).
  """
  import jax

  from tensor2robot_tpu.parallel import sharding as sharding_lib
  from tensor2robot_tpu.parallel.mesh import DATA_AXIS

  update_axis = DATA_AXIS if axis is None else axis

  def _constrain(tree):
    shardings = sharding_lib.data_update_sharding(
        mesh, tree, min_size_to_shard=min_size_to_shard,
        axis=update_axis)
    return jax.tree_util.tree_map(
        jax.lax.with_sharding_constraint, tree, shardings)

  def init(params):
    return tx.init(params)

  def update(grads, state, params=None):
    updates, new_state = tx.update(_constrain(grads), state, params)
    return _constrain(updates), _constrain(new_state)

  return optax.GradientTransformation(init, update)


@gin.configurable
def create_lr_schedule(
    learning_rate: float = 1e-4,
    schedule: str = "constant",
    warmup_steps: int = 0,
    decay_steps: int = 100_000,
    decay_rate: float = 0.96,
    end_learning_rate: float = 0.0,
    staircase: bool = False,
) -> optax.Schedule:
  """Builds a learning-rate schedule.

  Supported: constant, exponential_decay, cosine_decay, linear_decay —
  each with optional linear warmup.
  """
  if schedule == "constant":
    base = optax.constant_schedule(learning_rate)
  elif schedule == "exponential_decay":
    base = optax.exponential_decay(
        init_value=learning_rate, transition_steps=decay_steps,
        decay_rate=decay_rate, staircase=staircase,
        end_value=end_learning_rate or None)
  elif schedule == "cosine_decay":
    base = optax.cosine_decay_schedule(
        init_value=learning_rate, decay_steps=decay_steps,
        alpha=end_learning_rate / max(learning_rate, 1e-12))
  elif schedule == "linear_decay":
    base = optax.linear_schedule(
        init_value=learning_rate, end_value=end_learning_rate,
        transition_steps=decay_steps)
  else:
    raise ValueError(f"Unknown lr schedule: {schedule!r}")
  if warmup_steps > 0:
    warmup = optax.linear_schedule(0.0, learning_rate, warmup_steps)
    return optax.join_schedules([warmup, base], [warmup_steps])
  return base


@gin.configurable
def create_optimizer(
    optimizer_name: str = "adam",
    learning_rate: ScheduleOrFloat = 1e-4,
    momentum: float = 0.9,
    beta1: float = 0.9,
    beta2: float = 0.999,
    epsilon: float = 1e-8,
    weight_decay: float = 0.0,
    gradient_clip_norm: Optional[float] = None,
    gradient_clip_value: Optional[float] = None,
    use_lr_schedule: bool = False,
) -> optax.GradientTransformation:
  """gin-configurable optimizer factory (reference: create_optimizer).

  `use_lr_schedule=True` pulls the rate from `create_lr_schedule()` so
  gin configs can bind schedule parameters separately.
  """
  lr: ScheduleOrFloat = create_lr_schedule() if use_lr_schedule \
      else learning_rate
  name = optimizer_name.lower()
  if name == "adam":
    opt = optax.adam(lr, b1=beta1, b2=beta2, eps=epsilon)
  elif name == "adamw":
    opt = optax.adamw(lr, b1=beta1, b2=beta2, eps=epsilon,
                      weight_decay=weight_decay)
  elif name == "sgd":
    opt = optax.sgd(lr)
  elif name == "momentum":
    opt = optax.sgd(lr, momentum=momentum)
  elif name == "rmsprop":
    opt = optax.rmsprop(lr, momentum=momentum, eps=epsilon)
  elif name == "adagrad":
    opt = optax.adagrad(lr, eps=epsilon)
  elif name == "lamb":
    opt = optax.lamb(lr, b1=beta1, b2=beta2, eps=epsilon,
                     weight_decay=weight_decay)
  else:
    raise ValueError(f"Unknown optimizer: {optimizer_name!r}")

  chain = []
  if gradient_clip_norm is not None:
    chain.append(optax.clip_by_global_norm(gradient_clip_norm))
  if gradient_clip_value is not None:
    chain.append(optax.clip(gradient_clip_value))
  if weight_decay and name not in ("adamw", "lamb"):
    chain.append(optax.add_decayed_weights(weight_decay))
  chain.append(opt)
  return optax.chain(*chain) if len(chain) > 1 else opt
