"""Configurable optimizer factory on optax.

Reference parity: tensor2robot `models/optimizers.py` — gin-configurable
optimizer creation, learning-rate schedules, gradient clipping, and the
TPU cross-shard wrapping (SURVEY.md §3). TPU-native: there is no
CrossShardOptimizer equivalent to wrap — gradient all-reduce over the
mesh's data axis is inserted by GSPMD when the train step is jitted with
sharded batch / replicated params, riding ICI. What remains configurable
here is the optax chain.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Union

import optax

from tensor2robot_tpu import config as gin

ScheduleOrFloat = Union[float, optax.Schedule]


@gin.configurable
def create_lr_schedule(
    learning_rate: float = 1e-4,
    schedule: str = "constant",
    warmup_steps: int = 0,
    decay_steps: int = 100_000,
    decay_rate: float = 0.96,
    end_learning_rate: float = 0.0,
    staircase: bool = False,
) -> optax.Schedule:
  """Builds a learning-rate schedule.

  Supported: constant, exponential_decay, cosine_decay, linear_decay —
  each with optional linear warmup.
  """
  if schedule == "constant":
    base = optax.constant_schedule(learning_rate)
  elif schedule == "exponential_decay":
    base = optax.exponential_decay(
        init_value=learning_rate, transition_steps=decay_steps,
        decay_rate=decay_rate, staircase=staircase,
        end_value=end_learning_rate or None)
  elif schedule == "cosine_decay":
    base = optax.cosine_decay_schedule(
        init_value=learning_rate, decay_steps=decay_steps,
        alpha=end_learning_rate / max(learning_rate, 1e-12))
  elif schedule == "linear_decay":
    base = optax.linear_schedule(
        init_value=learning_rate, end_value=end_learning_rate,
        transition_steps=decay_steps)
  else:
    raise ValueError(f"Unknown lr schedule: {schedule!r}")
  if warmup_steps > 0:
    warmup = optax.linear_schedule(0.0, learning_rate, warmup_steps)
    return optax.join_schedules([warmup, base], [warmup_steps])
  return base


@gin.configurable
def create_optimizer(
    optimizer_name: str = "adam",
    learning_rate: ScheduleOrFloat = 1e-4,
    momentum: float = 0.9,
    beta1: float = 0.9,
    beta2: float = 0.999,
    epsilon: float = 1e-8,
    weight_decay: float = 0.0,
    gradient_clip_norm: Optional[float] = None,
    gradient_clip_value: Optional[float] = None,
    use_lr_schedule: bool = False,
) -> optax.GradientTransformation:
  """gin-configurable optimizer factory (reference: create_optimizer).

  `use_lr_schedule=True` pulls the rate from `create_lr_schedule()` so
  gin configs can bind schedule parameters separately.
  """
  lr: ScheduleOrFloat = create_lr_schedule() if use_lr_schedule \
      else learning_rate
  name = optimizer_name.lower()
  if name == "adam":
    opt = optax.adam(lr, b1=beta1, b2=beta2, eps=epsilon)
  elif name == "adamw":
    opt = optax.adamw(lr, b1=beta1, b2=beta2, eps=epsilon,
                      weight_decay=weight_decay)
  elif name == "sgd":
    opt = optax.sgd(lr)
  elif name == "momentum":
    opt = optax.sgd(lr, momentum=momentum)
  elif name == "rmsprop":
    opt = optax.rmsprop(lr, momentum=momentum, eps=epsilon)
  elif name == "adagrad":
    opt = optax.adagrad(lr, eps=epsilon)
  elif name == "lamb":
    opt = optax.lamb(lr, b1=beta1, b2=beta2, eps=epsilon,
                     weight_decay=weight_decay)
  else:
    raise ValueError(f"Unknown optimizer: {optimizer_name!r}")

  chain = []
  if gradient_clip_norm is not None:
    chain.append(optax.clip_by_global_norm(gradient_clip_norm))
  if gradient_clip_value is not None:
    chain.append(optax.clip(gradient_clip_value))
  if weight_decay and name not in ("adamw", "lamb"):
    chain.append(optax.add_decayed_weights(weight_decay))
  chain.append(opt)
  return optax.chain(*chain) if len(chain) > 1 else opt
