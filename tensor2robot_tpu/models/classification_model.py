"""Classification model base (reference: models/classification_model.py)."""

from __future__ import annotations

from typing import Dict, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax

from tensor2robot_tpu import config as gin
from tensor2robot_tpu.layers.core import MLP
from tensor2robot_tpu.models.abstract_model import AbstractT2RModel
from tensor2robot_tpu.models.regression_model import _DictOutput

LOGITS = "logits"


@gin.configurable
class ClassificationModel(AbstractT2RModel):
  """Softmax cross-entropy against integer labels; tracks accuracy."""

  def __init__(self,
               num_classes: int = 2,
               hidden_sizes: Sequence[int] = (64, 64),
               label_key: str = "label",
               dropout_rate: float = 0.0,
               **kwargs):
    super().__init__(**kwargs)
    self._num_classes = num_classes
    self._hidden_sizes = tuple(hidden_sizes)
    self._label_key = label_key
    self._dropout_rate = dropout_rate

  @property
  def num_classes(self) -> int:
    return self._num_classes

  def create_network(self) -> nn.Module:

    class _Logits(nn.Module):
      hidden: tuple
      num_classes: int
      dropout: float
      dtype: object

      @nn.compact
      def __call__(inner, features, train: bool = False):
        x = MLP(hidden_sizes=inner.hidden,
                output_size=inner.num_classes,
                dropout_rate=inner.dropout,
                dtype=inner.dtype)(features, train=train)
        return {LOGITS: x}

    return _Logits(self._hidden_sizes, self._num_classes,
                   self._dropout_rate, self.device_dtype)

  def model_train_fn(self, features, labels, outputs, mode
                     ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    logits = outputs[LOGITS]
    target = labels[self._label_key].reshape(logits.shape[0]).astype(
        jnp.int32)
    loss = optax.softmax_cross_entropy_with_integer_labels(
        logits, target).mean()
    accuracy = jnp.mean(
        (jnp.argmax(logits, axis=-1) == target).astype(jnp.float32))
    return loss, {"cross_entropy": loss, "accuracy": accuracy}
