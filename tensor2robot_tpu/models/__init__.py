"""Model abstraction and canonical bases (reference: tensor2robot models/)."""

from tensor2robot_tpu.models.model_interface import ModelInterface
from tensor2robot_tpu.models.abstract_model import (
    AbstractT2RModel,
    TrainState,
)
from tensor2robot_tpu.models.regression_model import (
    INFERENCE_OUTPUT,
    RegressionModel,
)
from tensor2robot_tpu.models.classification_model import (
    LOGITS,
    ClassificationModel,
)
from tensor2robot_tpu.models.critic_model import (
    Q_VALUE,
    CriticModel,
)
from tensor2robot_tpu.models.optimizers import (
    create_lr_schedule,
    create_optimizer,
)
