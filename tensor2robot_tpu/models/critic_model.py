"""Critic (Q-function) model base — the QT-Opt foundation.

Reference parity: tensor2robot `models/critic_model.py` — state+action →
scalar Q, trained by MSE against a Bellman target label (the distributed
target computation lived outside the repo; our in-repo version is in
research/qtopt). SURVEY.md §3.
"""

from __future__ import annotations

from typing import Dict, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from tensor2robot_tpu import config as gin
from tensor2robot_tpu.layers.core import MLP, flatten_and_concat
from tensor2robot_tpu.models.abstract_model import AbstractT2RModel

Q_VALUE = "q_value"


@gin.configurable
class CriticModel(AbstractT2RModel):
  """Q(state, action) regression against a target-Q label.

  Subclasses declare specs with the action under `action_key`; the
  default network concatenates state features with the action and
  regresses a scalar. Sigmoid-bounded Q (grasp-success ∈ [0,1], as in
  QT-Opt) is available via `sigmoid_q=True`, trained with cross-entropy
  on the logit, which is better-conditioned than MSE near saturation.
  """

  def __init__(self,
               hidden_sizes: Sequence[int] = (256, 256),
               action_key: str = "action",
               target_q_key: str = "target_q",
               sigmoid_q: bool = False,
               **kwargs):
    super().__init__(**kwargs)
    self._hidden_sizes = tuple(hidden_sizes)
    self._action_key = action_key
    self._target_q_key = target_q_key
    self._sigmoid_q = sigmoid_q

  @property
  def action_key(self) -> str:
    return self._action_key

  @property
  def sigmoid_q(self) -> bool:
    return self._sigmoid_q

  def create_network(self) -> nn.Module:

    class _QNet(nn.Module):
      hidden: tuple
      dtype: object

      @nn.compact
      def __call__(inner, features, train: bool = False):
        x = flatten_and_concat(features)  # state ++ action, flattened
        logit = MLP(hidden_sizes=inner.hidden, output_size=1,
                    dtype=inner.dtype)(x, train=train)
        return {Q_VALUE: logit[..., 0]}

    return _QNet(self._hidden_sizes, self.device_dtype)

  def q_from_outputs(self, outputs) -> jax.Array:
    q = outputs[Q_VALUE]
    return jax.nn.sigmoid(q) if self._sigmoid_q else q

  def model_train_fn(self, features, labels, outputs, mode
                     ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    raw = outputs[Q_VALUE]
    target = labels[self._target_q_key].reshape(raw.shape).astype(
        raw.dtype)
    if self._sigmoid_q:
      # Cross-entropy on the logit against a [0,1] target.
      loss = jnp.mean(
          jnp.maximum(raw, 0) - raw * target +
          jnp.log1p(jnp.exp(-jnp.abs(raw))))
      q = jax.nn.sigmoid(raw)
    else:
      loss = jnp.mean(jnp.square(raw - target))
      q = raw
    return loss, {"q_loss": loss, "q_mean": jnp.mean(q),
                  "target_q_mean": jnp.mean(target)}
