"""Regression model base (reference: models/regression_model.py).

Subclasses declare specs; the default network is an MLP over all float
features, the default loss MSE against `labels[label_key]`. The network
output convention is a dict with key `inference_output` (matching the
reference's serving signature naming).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from tensor2robot_tpu import config as gin
from tensor2robot_tpu.data.abstract_input_generator import Mode
from tensor2robot_tpu.layers.core import MLP
from tensor2robot_tpu.models.abstract_model import AbstractT2RModel
from tensor2robot_tpu.specs import TensorSpecStruct

INFERENCE_OUTPUT = "inference_output"


class _DictOutput(nn.Module):
  """Wraps a backbone so outputs follow the {'inference_output': ...} convention."""

  backbone: nn.Module

  @nn.compact
  def __call__(self, features, train: bool = False):
    out = self.backbone(features, train=train)
    if isinstance(out, (dict, TensorSpecStruct)):
      return out
    return {INFERENCE_OUTPUT: out}


@gin.configurable
class RegressionModel(AbstractT2RModel):
  """MSE regression against a declared label key."""

  def __init__(self,
               output_size: int = 1,
               hidden_sizes: Sequence[int] = (64, 64),
               label_key: str = "target",
               dropout_rate: float = 0.0,
               **kwargs):
    super().__init__(**kwargs)
    self._output_size = output_size
    self._hidden_sizes = tuple(hidden_sizes)
    self._label_key = label_key
    self._dropout_rate = dropout_rate

  @property
  def label_key(self) -> str:
    return self._label_key

  def create_network(self) -> nn.Module:
    return _DictOutput(MLP(
        hidden_sizes=self._hidden_sizes,
        output_size=self._output_size,
        dropout_rate=self._dropout_rate,
        dtype=self.device_dtype,
    ))

  def model_train_fn(self, features, labels, outputs, mode
                     ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    prediction = outputs[INFERENCE_OUTPUT]
    target = labels[self._label_key]
    target = target.reshape(prediction.shape).astype(prediction.dtype)
    loss = jnp.mean(jnp.square(prediction - target))
    return loss, {"mse": loss,
                  "mae": jnp.mean(jnp.abs(prediction - target))}
