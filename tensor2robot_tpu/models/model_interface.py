"""The minimal model contract the trainer and predictors depend on.

Reference parity: tensor2robot `models/model_interface.py` —
`ModelInterface` declaring the spec getters and step builders consumed by
`train_eval.train_eval_model` (SURVEY.md §2 L5).
"""

from __future__ import annotations

import abc
from typing import Any, Optional

from tensor2robot_tpu.data.abstract_input_generator import Mode
from tensor2robot_tpu.specs import TensorSpecStruct


class ModelInterface(abc.ABC):
  """What the orchestration layer needs from any model."""

  @abc.abstractmethod
  def get_feature_specification(self, mode: Mode) -> TensorSpecStruct:
    """Model-side (post-preprocessor) feature specs."""

  @abc.abstractmethod
  def get_label_specification(
      self, mode: Mode) -> Optional[TensorSpecStruct]:
    """Model-side (post-preprocessor) label specs."""

  @property
  @abc.abstractmethod
  def preprocessor(self):
    """The AbstractPreprocessor bridging wire specs to model specs."""

  @abc.abstractmethod
  def create_train_state(self, rng, batch_size: int = 1):
    """Initializes parameters + optimizer state."""

  @abc.abstractmethod
  def train_step(self, state, features, labels, rng):
    """Pure (state, batch, rng) -> (state, metrics); jit/pjit-able."""

  @abc.abstractmethod
  def eval_step(self, state, features, labels):
    """Pure (state, batch) -> metrics; jit/pjit-able."""

  @abc.abstractmethod
  def predict_step(self, state, features):
    """Pure (state, features) -> outputs; jit/pjit-able (serving path)."""
