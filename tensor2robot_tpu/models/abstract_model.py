"""AbstractT2RModel: the model_fn template, redesigned as pure JAX steps.

Reference parity: tensor2robot `models/abstract_model.py` —
`AbstractT2RModel.model_fn` with its preprocess → `inference_network_fn`
→ train/eval/predict branches, optimizer creation, and checkpoint
warm-start (`maybe_init_from_checkpoint`); SURVEY.md §4.2.

TPU-native redesign: instead of one `model_fn(features, labels, mode)`
building a TF graph per mode, the model exposes three PURE functions —
`train_step`, `eval_step`, `predict_step` — each of which traces
preprocess + network + loss into a single XLA program. The trainer jits
them over a device mesh (batch sharded on the data axis, params
replicated or sharded by the model's partitioning rules); GSPMD inserts
the gradient all-reduce the reference got from CrossShardOptimizer.
Mutable collections (batch_norm stats) and dropout RNG are threaded
explicitly, as JAX requires.
"""

from __future__ import annotations

import abc
import functools
from typing import Any, Callable, Dict, Optional, Tuple

import flax
import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from tensor2robot_tpu import config as gin
from tensor2robot_tpu import specs as specs_lib
from tensor2robot_tpu.data.abstract_input_generator import Mode
from tensor2robot_tpu.models import optimizers as opt_lib
from tensor2robot_tpu.models.model_interface import ModelInterface
from tensor2robot_tpu.preprocessors.noop_preprocessor import NoOpPreprocessor
from tensor2robot_tpu.specs import TensorSpecStruct


@flax.struct.dataclass
class TrainState:
  """Carried training state: step counter, params, mutable stats, opt."""

  step: jax.Array
  params: Any
  batch_stats: Any  # empty dict when the network has no BN-style stats
  opt_state: Any

  @property
  def variables(self) -> Dict[str, Any]:
    out = {"params": self.params}
    if self.batch_stats:
      out["batch_stats"] = self.batch_stats
    return out


class AbstractT2RModel(ModelInterface):
  """Base class for all models: specs + flax network + loss.

  Subclasses implement:
    * `get_feature_specification(mode)` / `get_label_specification(mode)`
    * `create_network() -> nn.Module` — the module is applied as
      `module(features_struct, train=<bool>)` and returns an output
      structure (dict / TensorSpecStruct / array).
    * `model_train_fn(features, labels, outputs, mode) -> (loss, scalars)`
  Optionally:
    * `model_eval_fn(...) -> scalars` (defaults to train_fn's scalars)
  """

  def __init__(self,
               preprocessor_cls: Optional[Callable] = None,
               create_optimizer_fn: Callable = opt_lib.create_optimizer,
               init_from_checkpoint_path: Optional[str] = None,
               device_dtype=jnp.float32,
               aux_loss_weight: float = 0.01,
               remat_policy: Optional[str] = None):
    """Args:
      preprocessor_cls: class (or factory) called with the two model spec
        getter fns; defaults to NoOpPreprocessor.
      create_optimizer_fn: zero-arg factory returning an
        optax.GradientTransformation (gin binds its parameters).
      init_from_checkpoint_path: warm-start checkpoint directory; params
        present in the checkpoint override fresh initializers
        (reference: maybe_init_from_checkpoint).
      device_dtype: compute dtype networks should favor (bfloat16 on TPU).
      aux_loss_weight: weight on auxiliary losses the network sows into
        the "aux_loss" collection (e.g. the MoE load-balance loss);
        irrelevant for networks that sow none.
      remat_policy: rematerialization of the loss forward under the
        gradient (docs/PERF.md sweep knob): None/"none" keeps XLA's
        default (save everything), "full" = jax.checkpoint saving
        nothing, "dots" = save MXU outputs only
        (checkpoint_dots), "dots_no_batch" = save only batch-free dot
        outputs (dots_with_no_batch_dims_saveable). Remat trades HBM
        residency of forward activations for recompute — at large
        batch that headroom buys bigger fused K-step programs. Bitwise
        identical math (recompute is exact; pinned by tests).
    """
    self._preprocessor_cls = preprocessor_cls
    self._create_optimizer_fn = create_optimizer_fn
    self._init_from_checkpoint_path = init_from_checkpoint_path
    self._device_dtype = device_dtype
    self._aux_loss_weight = aux_loss_weight
    self._remat_policy = remat_policy
    self._preprocessor = None
    self._network = None
    self._tx = None

  # ---- specs ----

  @abc.abstractmethod
  def get_feature_specification(self, mode: Mode) -> TensorSpecStruct:
    ...

  @abc.abstractmethod
  def get_label_specification(
      self, mode: Mode) -> Optional[TensorSpecStruct]:
    ...

  @property
  def device_dtype(self):
    return self._device_dtype

  @property
  def preprocessor(self):
    if self._preprocessor is None:
      cls = self._preprocessor_cls or NoOpPreprocessor
      self._preprocessor = cls(self.get_feature_specification,
                               self.get_label_specification)
    return self._preprocessor

  # ---- network ----

  @abc.abstractmethod
  def create_network(self) -> nn.Module:
    ...

  @property
  def network(self) -> nn.Module:
    if self._network is None:
      self._network = self.create_network()
    return self._network

  @property
  def tx(self):
    if self._tx is None:
      self._tx = self._create_optimizer_fn()
    return self._tx

  def wrap_optimizer(self, wrapper: Callable,
                     key: Optional[str] = None) -> None:
    """Replaces the optimizer with `wrapper(tx)` — the trainer-side
    hook for mesh-dependent transformations (e.g.
    `optimizers.shard_weight_update`, which needs the mesh that only
    the training loop knows). Call before the step is traced.

    ``key`` makes the wrap IDEMPOTENT per key: re-wrapping with the
    same key replaces the previous incarnation instead of stacking on
    top of it. Trainers that may be invoked repeatedly on one model
    (bench device-scaling rows, successive runs in one process) MUST
    pass a key — a stacked stale wrapper would otherwise pin the tx
    to a dead mesh's devices. Keyless wraps keep the raw composing
    behavior.
    """
    if key is None:
      self._tx = wrapper(self.tx)
      return
    if getattr(self, "_tx_keyed_base", None) is None:
      self._tx_keyed_base = self.tx
      self._tx_keyed_wrappers = {}
    self._tx_keyed_wrappers[key] = wrapper
    tx = self._tx_keyed_base
    for keyed_wrapper in self._tx_keyed_wrappers.values():
      tx = keyed_wrapper(tx)
    self._tx = tx

  AUX_LOSS_OUTPUT = "_aux_loss"

  def inference_network_fn(self,
                           variables: Dict[str, Any],
                           features: TensorSpecStruct,
                           mode: Mode,
                           rng: Optional[jax.Array] = None) -> Any:
    """Applies the network; returns (outputs, new_batch_stats).

    Auxiliary losses the network sows into the "aux_loss" collection
    (MoE load balance) are summed into `outputs[AUX_LOSS_OUTPUT]` for
    `loss_fn` to weight in; `predict_step` strips the key so serving
    signatures never see it.
    """
    train = mode == Mode.TRAIN
    rngs = {"dropout": rng} if (train and rng is not None) else None
    has_stats = "batch_stats" in variables
    mutable = ["aux_loss"]
    if train and has_stats:
      mutable.append("batch_stats")
    outputs, updates = self.network.apply(
        variables, features, train=train, rngs=rngs, mutable=mutable)
    if updates.get("aux_loss"):
      if not isinstance(outputs, dict):
        # Silently dropping a sown regularizer would let experts
        # collapse with no signal; the contract is explicit instead.
        raise TypeError(
            f"{type(self.network).__name__} sowed 'aux_loss' "
            f"variables but returned {type(outputs).__name__} "
            f"outputs; networks with auxiliary losses must return a "
            f"dict so the loss can be threaded through "
            f"(outputs[{self.AUX_LOSS_OUTPUT!r}]).")
      from tensor2robot_tpu.parallel.moe import collect_aux_losses
      outputs[self.AUX_LOSS_OUTPUT] = collect_aux_losses(updates)
    new_stats = (updates.get("batch_stats", {}) if train and has_stats
                 else variables.get("batch_stats", {}))
    return outputs, new_stats

  # ---- losses/metrics ----

  @abc.abstractmethod
  def model_train_fn(self,
                     features: TensorSpecStruct,
                     labels: Optional[TensorSpecStruct],
                     outputs: Any,
                     mode: Mode) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Returns (scalar loss, scalar metrics dict)."""

  def model_eval_fn(self,
                    features: TensorSpecStruct,
                    labels: Optional[TensorSpecStruct],
                    outputs: Any) -> Dict[str, jax.Array]:
    loss, scalars = self.model_train_fn(features, labels, outputs,
                                        Mode.EVAL)
    return {"loss": loss, **scalars}

  # ---- state ----

  def create_inference_state(self, rng: jax.Array,
                             batch_size: int = 1) -> TrainState:
    """Initializes network variables only — no optimizer state.

    The dummy init batch is derived mechanically from the preprocessor's
    OUT specs — the spec system seeding initialization the same way it
    seeds parsers and tests. Predictors use this directly: serving never
    needs (or pays the memory for) optimizer moments.
    """
    out_spec = self.preprocessor.get_out_feature_specification(Mode.TRAIN)
    # include_optional=False: input generators exclude optional specs
    # from real batches, so init must see the same tree structure or the
    # first jitted step diverges from the initialized params.
    dummy = specs_lib.make_random_tensors(
        out_spec, batch_size=batch_size, seed=0, include_optional=False,
        sequence_length=self.init_sequence_length)
    dummy = jax.tree_util.tree_map(jnp.asarray, dummy)
    init_rng, dropout_rng = jax.random.split(rng)
    variables = self.network.init(
        {"params": init_rng, "dropout": dropout_rng}, dummy, train=False)
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    if self._init_from_checkpoint_path:
      params, batch_stats = self.maybe_init_from_checkpoint(
          params, batch_stats)
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        batch_stats=batch_stats,
        opt_state=None,
    )

  @property
  def init_sequence_length(self):
    """Time-axis length of the dummy init batch for sequence specs.

    None → the random-data default. Models whose networks constrain T
    (e.g. sequence-parallel attention needs T divisible by the mesh's
    `seq` axis) override this so initialization traces a valid shape.
    """
    return None

  def create_train_state(self, rng: jax.Array,
                         batch_size: int = 1) -> TrainState:
    """Initializes params + batch stats + optimizer state from specs."""
    state = self.create_inference_state(rng, batch_size=batch_size)
    return state.replace(opt_state=self.tx.init(state.params))

  def maybe_init_from_checkpoint(self, params, batch_stats=None):
    """Warm-starts params (and BN stats) from `init_from_checkpoint_path`.

    BN moving averages ride along when the model carries batch_stats —
    warm-starting params alone would pair trained weights with
    fresh-init statistics, the same silent degradation the predictor
    path guards against.
    """
    from tensor2robot_tpu.utils import checkpoints as ckpt_lib
    if batch_stats:
      variables = ckpt_lib.restore_variables(
          self._init_from_checkpoint_path,
          like={"params": params, "batch_stats": batch_stats})
      return variables["params"], variables["batch_stats"]
    restored = ckpt_lib.restore_params(
        self._init_from_checkpoint_path, like=params)
    return restored, batch_stats

  # ---- steps (pure; the trainer jits these) ----

  def network_inputs_from_labels(self,
                                 features: TensorSpecStruct,
                                 labels: Optional[TensorSpecStruct],
                                 mode: Mode) -> TensorSpecStruct:
    """Hook: lift label-derived conditioning INPUTS into the features.

    Models whose networks consume parts of the labels as inputs —
    demonstration actions conditioning WTL/SNAIL policies — override
    this instead of re-implementing loss_fn. Runs after preprocessing
    in train/eval; at predict time the same inputs must arrive inside
    the feature struct directly (the condition_labels serving
    convention), so this hook is NOT called then. Default: unchanged.
    """
    del labels, mode
    return features

  def loss_fn(self, params, batch_stats, features, labels, rng,
              mode: Mode):
    variables = {"params": params}
    if batch_stats:
      variables["batch_stats"] = batch_stats
    rng_pre, rng_net = (jax.random.split(rng) if rng is not None
                        else (None, None))
    features, labels = self.preprocessor.preprocess(
        features, labels, mode, rng_pre)
    features = self.network_inputs_from_labels(features, labels, mode)
    outputs, new_stats = self.inference_network_fn(
        variables, features, mode, rng_net)
    # Pop BEFORE model_train_fn: subclass losses/metrics never see the
    # private key (predict_step shields its consumers the same way).
    aux = (outputs.pop(self.AUX_LOSS_OUTPUT, None)
           if isinstance(outputs, dict) else None)
    loss, scalars = self.model_train_fn(features, labels, outputs, mode)
    if aux is not None:
      loss = loss + self._aux_loss_weight * aux
      if "aux_loss" in scalars:
        raise ValueError(
            "model_train_fn reported a scalar named 'aux_loss'; that "
            "key is reserved for the network-sown auxiliary loss "
            f"({self.AUX_LOSS_OUTPUT}) — rename the subclass scalar.")
      scalars = {**scalars, "aux_loss": aux}
    return loss, (scalars, new_stats)

  def _loss_for_grad(self) -> Callable:
    """`loss_fn`, optionally under jax.checkpoint per `remat_policy`.

    `mode` (arg 5) is static — an enum, not a tracer. Recompute is
    exact arithmetic, so every policy is bitwise-equal to "none"; the
    choice only moves the HBM-vs-recompute trade (docs/PERF.md).
    """
    policy_name = self._remat_policy
    if policy_name in (None, "none"):
      return self.loss_fn
    policies = {
        "full": None,
        "dots": "checkpoint_dots",
        "dots_no_batch": "dots_with_no_batch_dims_saveable",
    }
    if policy_name not in policies:
      raise ValueError(
          f"remat_policy={policy_name!r} not in "
          f"{['none'] + sorted(policies)}")
    attr = policies[policy_name]
    policy = getattr(jax.checkpoint_policies, attr) if attr else None
    return jax.checkpoint(self.loss_fn, policy=policy,
                          static_argnums=(5,))

  def train_step(self, state: TrainState, features, labels,
                 rng: jax.Array, axis_name: Optional[str] = None
                 ) -> Tuple[TrainState, Dict[str, jax.Array]]:
    """One optimizer step on `features`/`labels`.

    `axis_name` (trace-time static) selects the SPMD data-parallel
    form: inside a `pmap`/`shard_map` over that axis, per-device
    gradients are `lax.pmean`'d before the optimizer — every replica
    then applies the identical update, so replicated params STAY
    replicated (the Podracer/Anakin pod contract, docs/ENVS.md).
    Batch-norm statistics and the reported metrics are pmean'd the
    same way (cross-replica batch stats; device-0 metrics are global
    means). `axis_name=None` (the default) is the unchanged
    single-program step.

    Composition of the two halves below — `train_grads` (forward/
    backward, collective-synchronized) and `apply_gradients` (the
    elementwise weight-sized update). The shard_map pod program calls
    the halves SEPARATELY so the backward runs per-device under
    `shard_map` while the update runs as jit+mesh GSPMD — the seam
    the ZeRO weight-update sharding composes through
    (docs/SHARDING.md).
    """
    grads, new_stats, metrics = self.train_grads(
        state, features, labels, rng, axis_name=axis_name)
    return self.apply_gradients(state, grads, new_stats), metrics

  def train_grads(self, state: TrainState, features, labels,
                  rng: jax.Array, axis_name: Optional[str] = None
                  ) -> Tuple[Any, Any, Dict[str, jax.Array]]:
    """The forward/backward half of `train_step`.

    Returns ``(grads, new_batch_stats, metrics)`` — gradients, batch
    stats, and loss metrics, already `lax.pmean`'d over `axis_name`
    when given. Everything collective lives here; no optimizer state
    is touched.
    """
    grad_fn = jax.value_and_grad(self._loss_for_grad(), has_aux=True)
    (loss, (scalars, new_stats)), grads = grad_fn(
        state.params, state.batch_stats, features, labels, rng, Mode.TRAIN)
    if axis_name is not None:
      grads = jax.lax.pmean(grads, axis_name)
      loss = jax.lax.pmean(loss, axis_name)
      scalars = jax.lax.pmean(scalars, axis_name)
      if new_stats:
        new_stats = jax.lax.pmean(new_stats, axis_name)
    metrics = {"loss": loss,
               "grad_norm": optax.global_norm(grads),
               **scalars}
    return grads, new_stats, metrics

  def apply_gradients(self, state: TrainState, grads: Any,
                      new_stats: Any) -> TrainState:
    """The optimizer half of `train_step`: tx.update + apply.

    Elementwise weight-sized math (plus whatever the configured optax
    chain adds), so under a mesh whose tx is wrapped with
    `optimizers.shard_weight_update` the GSPMD constraints shard it
    cross-replica — each device updates 1/N of every weight's
    moments.
    """
    updates, new_opt_state = self.tx.update(grads, state.opt_state,
                                            state.params)
    new_params = optax.apply_updates(state.params, updates)
    return state.replace(
        step=state.step + 1,
        params=new_params,
        batch_stats=new_stats,
        opt_state=new_opt_state,
    )

  def eval_step(self, state: TrainState, features,
                labels) -> Dict[str, jax.Array]:
    variables = state.variables
    features, labels = self.preprocessor.preprocess(
        features, labels, Mode.EVAL, None)
    features = self.network_inputs_from_labels(features, labels,
                                               Mode.EVAL)
    outputs, _ = self.inference_network_fn(variables, features, Mode.EVAL)
    # Same aux treatment as loss_fn, so the eval "loss" tracks the
    # optimized objective and expert collapse is visible in eval too.
    aux = (outputs.pop(self.AUX_LOSS_OUTPUT, None)
           if isinstance(outputs, dict) else None)
    metrics = self.model_eval_fn(features, labels, outputs)
    if aux is not None:
      if "aux_loss" in metrics:
        raise ValueError(
            "model_eval_fn reported a metric named 'aux_loss'; that "
            "key is reserved for the network-sown auxiliary loss "
            f"({self.AUX_LOSS_OUTPUT}) — rename the subclass metric.")
      metrics = {**metrics, "aux_loss": aux}
      # model_eval_fn's contract promises only "scalars" — a custom
      # override may not report a "loss" key at all.
      if "loss" in metrics:
        metrics["loss"] = (metrics["loss"]
                           + self._aux_loss_weight * aux)
    return metrics

  def predict_step(self, state: TrainState, features) -> Any:
    variables = state.variables
    features, _ = self.preprocessor.preprocess(
        features, None, Mode.PREDICT, None)
    outputs, _ = self.inference_network_fn(variables, features,
                                           Mode.PREDICT)
    if isinstance(outputs, dict):
      outputs.pop(self.AUX_LOSS_OUTPUT, None)
    return outputs
