"""Cold-start probes: time-to-first-step / time-to-first-prediction.

Each probe is ONE process lifetime — `bench.py --coldstart` launches
them as subprocesses so the in-process jit cache can never fake a warm
start; only the persistent compilation cache (and the orbax checkpoint)
survive between the cold and warm runs. A probe prints one
`COLDSTART_JSON {...}` marker line:

  * `time_to_first_*_secs` — wall from probe entry (imports done) to
    the first train step's metrics on host / the first prediction's
    outputs on host. Imports are excluded from the headline because
    they are identical cold and warm and unaddressable by caching;
    the parent records full subprocess wall alongside for honesty.
  * `compile_watch` — `CompileWatch` counts; a warm probe must report
    `cache_misses == 0` (every program deserialized, zero XLA
    compilations) — the proof the bench section pins.
  * trainer probes embed the trainer's own `startup_timings.json`
    (per-phase compile/restore/input wall, overlap saving).

Probe topology (same for `--tiny`, just smaller nets):

  setup  — seeds a checkpoint (trainer: 2 train steps + save; serving:
           one params checkpoint), cache DISABLED, untimed.
  probe  — resumes/restores from that checkpoint with the given cache
           dir and reports the marker. Run it twice with the same
           cache dir: run 1 is the cold measurement (and populates the
           cache), run 2 is the warm one.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

SETUP_STEPS = 2
PROBE_STEPS = 2  # the resumed run trains SETUP_STEPS → SETUP_STEPS+2


def _build_trainer_model(tiny: bool):
  if tiny:
    from tensor2robot_tpu.utils.mocks import MockT2RModel
    return MockT2RModel(), 8
  # The QT-Opt grasping critic with a deepened torso: a conv stack
  # whose XLA compile is the realistic multi-second cold-start cost
  # the cache is meant to erase. f32 device dtype: the probe must run
  # wherever the fleet restarts, including host CPU, where bf16 is
  # emulated so slowly that step-execution noise would swamp the
  # compile-time signal the cold/warm ratio measures; compile cost is
  # dtype-comparable. Batch 8 for the same reason — the measured
  # quantity is startup, not throughput.
  import jax.numpy as jnp

  from tensor2robot_tpu.research.qtopt.t2r_models import GraspingQModel
  return GraspingQModel(torso_filters=(64, 96, 96),
                        head_filters=(96, 96),
                        dense_sizes=(96, 96),
                        device_dtype=jnp.float32), 8


def _build_serving_model(tiny: bool):
  if tiny:
    from tensor2robot_tpu.utils.mocks import MockT2RModel
    return MockT2RModel()
  from tensor2robot_tpu.research.qtopt.t2r_models import GraspingQModel
  return GraspingQModel()


def trainer_setup(model_dir: str, tiny: bool) -> dict:
  """Seeds `model_dir` with a checkpoint at SETUP_STEPS (no cache)."""
  from tensor2robot_tpu import train_eval
  from tensor2robot_tpu.data import RandomInputGenerator

  model, batch_size = _build_trainer_model(tiny)
  train_eval.train_eval_model(
      model=model,
      model_dir=model_dir,
      input_generator_train=RandomInputGenerator(batch_size=batch_size,
                                                 seed=3),
      max_train_steps=SETUP_STEPS,
      save_checkpoints_steps=SETUP_STEPS,
      log_every_steps=SETUP_STEPS,
  )
  return {"setup": "ok", "steps": SETUP_STEPS}


def trainer_probe(model_dir: str, cache_dir: str, tiny: bool) -> dict:
  """Restart: resume from the seeded checkpoint, time the first step."""
  import jax
  import numpy as np

  from tensor2robot_tpu import train_eval
  from tensor2robot_tpu.data import RandomInputGenerator
  from tensor2robot_tpu.hooks import Hook
  from tensor2robot_tpu.startup import (
      CompileWatch,
      cache_entry_count,
      configure_compilation_cache,
  )
  from tensor2robot_tpu.startup.orchestrator import STARTUP_TIMINGS_FILE

  configure_compilation_cache(cache_dir=cache_dir)
  t0 = time.perf_counter()

  class FirstStepTimer(Hook):
    ttfs = None

    def after_step(self, step, metrics):
      if self.ttfs is None:
        # D2H read of a metric: the step has genuinely finished.
        float(np.asarray(jax.device_get(
            next(iter(metrics.values())))))
        self.ttfs = time.perf_counter() - t0

  timer = FirstStepTimer()
  model, batch_size = _build_trainer_model(tiny)
  with CompileWatch() as watch:
    train_eval.train_eval_model(
        model=model,
        model_dir=model_dir,
        input_generator_train=RandomInputGenerator(batch_size=batch_size,
                                                   seed=3),
        max_train_steps=SETUP_STEPS + PROBE_STEPS,
        save_checkpoints_steps=SETUP_STEPS + PROBE_STEPS,
        log_every_steps=SETUP_STEPS + PROBE_STEPS,
        hooks=[timer],
    )
  try:
    with open(os.path.join(model_dir, STARTUP_TIMINGS_FILE)) as f:
      startup_timings = json.load(f)
  except (OSError, ValueError):
    startup_timings = None
  return {
      "probe": "trainer",
      "tiny": tiny,
      "device_kind": jax.devices()[0].device_kind,
      "time_to_first_step_secs": round(timer.ttfs, 3),
      "startup_timings": startup_timings,
      "compile_watch": watch.counts(),
      "cache_entries_after": cache_entry_count(cache_dir),
  }


def serving_setup(ckpt_dir: str, tiny: bool) -> dict:
  """Seeds one params checkpoint a predictor can restore (no cache)."""
  import jax

  from tensor2robot_tpu.utils import checkpoints as ckpt_lib

  model = _build_serving_model(tiny)
  state = model.create_inference_state(jax.random.PRNGKey(0))
  writer = ckpt_lib.CheckpointWriter(ckpt_dir, max_to_keep=None)
  writer.save(1, state)
  writer.close()
  return {"setup": "ok", "step": 1}


def serving_probe(ckpt_dir: str, cache_dir: str, tiny: bool) -> dict:
  """Restart: restore ∥ compile-ahead, then time the first prediction."""
  import jax
  import numpy as np

  from tensor2robot_tpu.predictors import CheckpointPredictor
  from tensor2robot_tpu.specs import make_random_tensors
  from tensor2robot_tpu.startup import (
      CompileWatch,
      cache_entry_count,
      configure_compilation_cache,
  )

  configure_compilation_cache(cache_dir=cache_dir)
  t0 = time.perf_counter()
  model = _build_serving_model(tiny)
  max_batch = 2 if tiny else 4
  with CompileWatch() as watch:
    predictor = CheckpointPredictor(
        model, checkpoint_dir=ckpt_dir, max_batch=max_batch,
        warmup=True, overlap_startup=True)
    restored = predictor.restore(timeout_secs=0)
    restore_done = time.perf_counter() - t0
    batch = make_random_tensors(
        predictor.feature_specification, batch_size=1, seed=0)
    outputs = predictor.predict(
        {k: np.asarray(v) for k, v in batch.to_flat_dict().items()})
    float(np.asarray(next(iter(outputs.values()))).ravel()[0])
    ttfp = time.perf_counter() - t0
  result = {
      "probe": "serving",
      "tiny": tiny,
      "device_kind": jax.devices()[0].device_kind,
      "restored": bool(restored),
      "time_to_first_prediction_secs": round(ttfp, 3),
      "restore_and_warmup_secs": round(restore_done, 3),
      "engine_warmup_secs": round(predictor.warmup_seconds, 3),
      "compiled_buckets": list(predictor.serving_engine.compiled_buckets),
      "compile_watch": watch.counts(),
      "cache_entries_after": cache_entry_count(cache_dir),
  }
  predictor.close()
  return result


def main(argv=None) -> int:
  parser = argparse.ArgumentParser(description=__doc__)
  parser.add_argument("probe", choices=("trainer", "serving"))
  parser.add_argument("--model-dir", required=True,
                      help="trainer model_dir / serving checkpoint dir")
  parser.add_argument("--cache-dir", default=None,
                      help="persistent compilation cache dir "
                           "(required unless --setup)")
  parser.add_argument("--tiny", action="store_true",
                      help="mock-model variant (the tier-1 smoke)")
  parser.add_argument("--setup", action="store_true",
                      help="seed the checkpoint instead of probing")
  args = parser.parse_args(argv)

  if args.probe == "trainer":
    if args.setup:
      result = trainer_setup(args.model_dir, args.tiny)
    else:
      result = trainer_probe(args.model_dir, args.cache_dir, args.tiny)
  else:
    if args.setup:
      result = serving_setup(args.model_dir, args.tiny)
    else:
      result = serving_probe(args.model_dir, args.cache_dir, args.tiny)
  print("COLDSTART_JSON " + json.dumps(result))
  return 0


if __name__ == "__main__":
  sys.exit(main())
