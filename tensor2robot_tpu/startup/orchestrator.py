"""Overlapped startup phases: compile ∥ restore ∥ input spin-up.

A cold process start has three independent serial costs — AOT
compilation (CPU-bound in XLA, releases the GIL), orbax checkpoint
restore (disk I/O + H2D), and input-pipeline spin-up (host CPU /
tf.data) — that today run back-to-back. They touch disjoint resources,
so threads recover most of the sum; `run_overlapped` is the one shared
primitive: named thunks, all started together, all joined, per-phase
wall timings recorded, failures surfaced only AFTER every phase has
finished (a half-started phase must never leak a worker thread or a
prefetcher holding device buffers).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import threading
import time
from typing import Any, Callable, Dict, Mapping, Optional

log = logging.getLogger(__name__)

STARTUP_TIMINGS_FILE = "startup_timings.json"


@dataclasses.dataclass
class StartupReport:
  """Outcome of one `run_overlapped` call."""

  mode: str                      # "overlapped" | "serial"
  results: Dict[str, Any]        # phase name → thunk return value
  seconds: Dict[str, float]      # phase name → wall seconds
  total_seconds: float           # wall of the whole join
  errors: Dict[str, BaseException] = dataclasses.field(
      default_factory=dict)      # phase name → what it raised

  def raise_first(self, order=None) -> None:
    """Re-raises the first failed phase (in `order`, default insertion)."""
    for name in (order or self.errors):
      if name in self.errors:
        raise self.errors[name]

  @property
  def serial_seconds(self) -> float:
    """What the same phases would have cost back-to-back."""
    return sum(self.seconds.values())

  @property
  def overlap_saved_seconds(self) -> float:
    return max(self.serial_seconds - self.total_seconds, 0.0)

  def as_dict(self) -> dict:
    return {
        "mode": self.mode,
        "phase_seconds": {k: round(v, 4) for k, v in
                          self.seconds.items()},
        "total_seconds": round(self.total_seconds, 4),
        "serial_seconds": round(self.serial_seconds, 4),
        "overlap_saved_seconds": round(self.overlap_saved_seconds, 4),
    }

  def write(self, model_dir: str) -> str:
    """Persists the report (bench probes read it back)."""
    path = os.path.join(model_dir, STARTUP_TIMINGS_FILE)
    with open(path, "w") as f:
      json.dump(self.as_dict(), f, indent=2)
    return path


def run_overlapped(phases: Mapping[str, Callable[[], Any]],
                   overlap: bool = True) -> StartupReport:
  """Runs named startup thunks concurrently (or serially) and joins all.

  Args:
    phases: {name: zero-arg thunk}. Thunks must be independent — no
      phase may read another's result (pass data through the returned
      report instead).
    overlap: False runs the phases back-to-back in dict order — the
      reference serial path, kept selectable so equivalence is
      testable and a pathological environment (e.g. a jax backend
      that is not thread-safe) has an escape hatch.

  Returns a StartupReport; failures land in `report.errors` (never
  raised here) so the caller can release any sibling phase's
  resources — e.g. a prefetcher pinning device buffers — before
  calling `report.raise_first()`.
  """
  results: Dict[str, Any] = {}
  seconds: Dict[str, float] = {}
  errors: Dict[str, BaseException] = {}

  def run_one(name: str, fn: Callable[[], Any]) -> None:
    t0 = time.perf_counter()
    try:
      results[name] = fn()
    except BaseException as e:  # re-raised below, never swallowed
      errors[name] = e
    finally:
      seconds[name] = time.perf_counter() - t0

  t_start = time.perf_counter()
  if overlap:
    threads = [
        threading.Thread(target=run_one, args=(name, fn),
                         name=f"startup-{name}", daemon=True)
        for name, fn in phases.items()
    ]
    for t in threads:
      t.start()
    for t in threads:
      t.join()
  else:
    for name, fn in phases.items():
      run_one(name, fn)
  total = time.perf_counter() - t_start

  report = StartupReport(
      mode="overlapped" if overlap else "serial",
      results=results, seconds=seconds, total_seconds=total,
      errors=errors)
  if errors:
    return report
  log.info(
      "Startup (%s): %s → %.2fs wall (serial sum %.2fs, saved %.2fs)",
      report.mode,
      ", ".join(f"{k}={v:.2f}s" for k, v in seconds.items()),
      total, report.serial_seconds, report.overlap_saved_seconds)
  return report


def close_quietly(obj: Optional[Any]) -> None:
  """Best-effort close of a phase result during error unwinding."""
  if obj is None:
    return
  close = getattr(obj, "close", None)
  if close is None:
    return
  try:
    close()
  except Exception:  # already unwinding a real error
    log.warning("close() failed during startup unwinding", exc_info=True)
