"""Cold-start elimination: persistent compile cache + overlapped startup.

The north-star fleet restarts constantly — preemptible TPU workers,
rolling predictor updates (Podracer, arXiv:2104.06272, makes
preemption-tolerance a first-class property) — yet a process start
serially pays trace + XLA compile + orbax restore + input-pipeline
spin-up. This package makes restarts cheap and measured:

  * `compile_cache` — gin-configurable wiring of jax's persistent XLA
    compilation cache (`jax_compilation_cache_dir` + min-entry knobs),
    shared by the trainer, predictors, the serving engine, and bench,
    plus `CompileWatch`: a jax.monitoring tap that counts cache
    hits/misses so "the warm path compiled nothing" is provable.
  * `orchestrator` — `run_overlapped`: named startup phases on threads
    (device compile, disk restore, host input prep don't contend),
    with per-phase wall timings and the serial-vs-overlapped saving.
  * `coldstart` — subprocess probes measuring trainer
    time-to-first-step and predictor time-to-first-prediction, driven
    by `bench.py --coldstart` (cold vs. warm cache).
"""

from tensor2robot_tpu.startup.compile_cache import (
    CompileWatch,
    aval_of,
    cache_entry_count,
    configure_compilation_cache,
)
from tensor2robot_tpu.startup.orchestrator import (
    StartupReport,
    run_overlapped,
)
