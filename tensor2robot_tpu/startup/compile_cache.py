"""Persistent XLA compilation cache wiring + compile observability.

jax's persistent compilation cache keys each backend compile on the
(HLO, compile options, backend version) fingerprint and stores the
serialized executable under `jax_compilation_cache_dir`; a process that
re-traces the same program skips XLA entirely and deserializes the
cached binary (the pjit/TPUv4 scaling work, arXiv:2204.06514, is what
makes frequent restarts affordable at pod scale). This module is the
ONE place the cache is configured — trainer, predictors, serving
engine, and bench all call `configure_compilation_cache()` so a fleet
config is a single gin binding (or env var) away:

    configure_compilation_cache.cache_dir = "/mnt/fleet/xla-cache"

`CompileWatch` taps `jax.monitoring` for the cache's hit/miss events —
the proof obligation for every warm-start claim in this repo is
"`cache_misses == 0`", counted here, not inferred from wall clock.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Optional

import jax

from tensor2robot_tpu import config as gin
from tensor2robot_tpu.telemetry import metrics as tmetrics

log = logging.getLogger(__name__)

ENV_CACHE_DIR = "T2R_COMPILATION_CACHE_DIR"

# jax.monitoring event names (stable across the jax versions we pin).
_CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_CACHE_MISS_EVENT = "/jax/compilation_cache/cache_misses"
_CACHE_REQUEST_EVENT = "/jax/compilation_cache/compile_requests_use_cache"
_BACKEND_COMPILE_DURATION = "/jax/core/compile/backend_compile_duration"

_configured: Optional[tuple] = None  # (dir, min_entry_size, min_secs)
_configured_dir: Optional[str] = None


def aval_of(x):
  """ShapeDtypeStruct twin of a jax array, keeping its sharding.

  THE leaf helper for building AOT-lowering avals from live pytrees
  (trainer state, serving-engine state) — shared so the aval semantics
  cannot drift between the startup paths that compile ahead of time.
  Non-array leaves pass through untouched.
  """
  if isinstance(x, jax.Array):
    return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
  return x


@gin.configurable
def configure_compilation_cache(
    cache_dir: Optional[str] = None,
    min_entry_size_bytes: int = -1,
    min_compile_time_secs: float = 0.0,
) -> Optional[str]:
  """Points jax's persistent compilation cache at `cache_dir`.

  Idempotent and safe to call from every entry point (trainer,
  predictor, serving engine, bench): unconfigured (no gin binding, no
  `T2R_COMPILATION_CACHE_DIR` env var, no explicit arg) it is a no-op
  returning None; configured, it creates the directory and sets the
  three jax knobs. Call order vs. jit does not matter — jax consults
  the config at each compile.

  Args:
    cache_dir: cache directory; falls back to the env var. None
      disables (leaves jax's current setting untouched so an outer
      harness's cache survives).
    min_entry_size_bytes: smallest executable worth persisting
      (-1: everything — restart latency is the point here, so even
      tiny programs pay their way).
    min_compile_time_secs: only persist compiles slower than this
      (0.0: everything, same rationale).

  Returns the resolved cache dir (None when disabled).
  """
  global _configured, _configured_dir
  # Every entry point that wires the cache also gets the registry tap
  # (cache dir or not): compile traffic is telemetry either way.
  CompileWatch.install_tap()
  if not cache_dir:
    # The env var is a DEFAULT, not an override: once any caller has
    # configured a cache explicitly (a bench probe's throwaway dir, a
    # test fixture), a later no-arg call from a library entry point
    # (train_eval_model, the serving engine) must keep it — not
    # silently re-point the process at the fleet cache.
    if _configured is not None:
      return _configured_dir
    cache_dir = os.environ.get(ENV_CACHE_DIR)
  if not cache_dir:
    return _configured_dir
  cache_dir = os.path.abspath(cache_dir)
  os.makedirs(cache_dir, exist_ok=True)
  # Idempotence keys on ALL the knobs, not just the dir: an entry
  # point that configures with defaults first must not swallow a later
  # explicit reconfiguration of the min-entry thresholds.
  wanted = (cache_dir, int(min_entry_size_bytes),
            float(min_compile_time_secs))
  if _configured != wanted:
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                      int(min_entry_size_bytes))
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      float(min_compile_time_secs))
    if _configured is None or _configured[0] != cache_dir:
      _reset_jax_cache_latch()
    _configured = wanted
    _configured_dir = cache_dir
    log.info("Persistent XLA compilation cache at %s "
             "(min_entry_size_bytes=%d, min_compile_time_secs=%g)",
             cache_dir, min_entry_size_bytes, min_compile_time_secs)
  return _configured_dir


def _reset_jax_cache_latch() -> None:
  """Clears jax's once-per-process cache-initialization latch.

  jax initializes the persistent cache lazily at the FIRST compile and
  never re-reads `jax_compilation_cache_dir` afterwards — so a single
  compile anywhere in the import chain (flax init, orbax, a spec
  helper) before this module runs would silently pin the process to
  "no cache" and every warm-start claim would be wrong. The reset
  makes configuration order-independent; already-compiled programs
  simply stay in the in-process jit cache.
  """
  try:
    from jax._src import compilation_cache as _cc
    _cc.reset_cache()
  except Exception:  # private API; degrade to the lazy-init behavior
    log.warning("Could not reset jax's compilation-cache latch; the "
                "cache dir may be ignored if a compile already "
                "happened in this process.", exc_info=True)


def cache_dir() -> Optional[str]:
  """The live persistent-cache directory (None = no cache configured).

  The seam warm-load claims check BEFORE promising anything: the
  serving arena's "evicted tenants reload without recompiling"
  contract only holds with a cache configured, so it consults this at
  construction and warns loudly when the answer is None.
  """
  return _configured_dir


def donation_unsafe_with_cache() -> bool:
  """True when buffer donation must be disabled for cache safety.

  Empirically pinned on jaxlib 0.4.37's XLA:CPU: executing a
  DESERIALIZED executable that donates input buffers, in a process
  where tensorstore (an orbax restore) has been active, corrupts the
  glibc heap — `malloc(): unsorted double linked list corrupted` at
  the next unrelated allocation. The triple is exact: freshly-compiled
  + donation + restore is fine, deserialized + no-donation + restore
  is fine, deserialized + donation WITHOUT a restore is fine. A
  restart is precisely restore + deserialized programs, so with the
  persistent cache enabled on the CPU backend the trainer and the
  serving engine trade donation (a buffer-reuse optimization that
  matters on HBM-constrained accelerators, little on host CPU) for a
  warm start that doesn't segfault. TPU/GPU backends keep donation —
  the persistent cache is production-standard there.
  """
  return _configured_dir is not None and jax.default_backend() == "cpu"


def reset_compilation_cache_config() -> None:
  """Detaches jax from the persistent cache (tests restore isolation)."""
  global _configured, _configured_dir
  jax.config.update("jax_compilation_cache_dir", None)
  _reset_jax_cache_latch()
  _configured = None
  _configured_dir = None


def cache_entry_count(cache_dir: str) -> int:
  """Number of persisted executables (one `-cache` file per program)."""
  if not os.path.isdir(cache_dir):
    return 0
  return sum(1 for name in os.listdir(cache_dir)
             if name.endswith("-cache"))


class CompileWatch:
  """Counts compilation-cache traffic via `jax.monitoring`.

  Usage::

      with CompileWatch() as watch:
        ...  # everything that might compile
      assert watch.cache_misses == 0   # the warm-path proof

  `cache_misses` counts compile requests the persistent cache could
  not serve — each one is a real XLA compilation (and a subsequent
  cache write). `cache_hits` counts executables deserialized from the
  cache instead of compiled. `backend_compiles` counts trips through
  jax's backend-compile path regardless of cache state (nonzero even
  on a fully warm start — retrieval runs inside it); the zero-compile
  claim is therefore ALWAYS `cache_misses == 0` with
  `cache_requests > 0`, never `backend_compiles == 0`.

  jax.monitoring offers no unregister, so the listeners stay installed
  for the process lifetime and count only while a watch is active
  (nested watches each observe the same events).
  """

  _lock = threading.Lock()
  _active: list = []
  _installed = False

  def __init__(self):
    self.cache_hits = 0
    self.cache_misses = 0
    self.cache_requests = 0
    self.backend_compiles = 0

  @classmethod
  def _install(cls) -> None:
    with cls._lock:
      if cls._installed:
        return
      import jax.monitoring as monitoring

      # Registry twin counters: once the listeners exist, EVERY cache
      # event lands in the telemetry registry whether or not a watch
      # is active — this is what closes the CompileWatch gap (ISSUE
      # 11): warm-path recompiles surface in ordinary training logs
      # (`compile_cache.misses` in metrics_<tag>.jsonl), not only
      # under `bench.py --coldstart`. Names resolve PER EVENT (not
      # captured handles): a registry reset (test isolation) must not
      # orphan these counters for the rest of the process — compiles
      # are rare, the lookup is nothing.
      _event_names = {
          _CACHE_HIT_EVENT: "compile_cache.hits",
          _CACHE_MISS_EVENT: "compile_cache.misses",
          _CACHE_REQUEST_EVENT: "compile_cache.requests",
      }

      def on_event(event: str, **kwargs):
        name = _event_names.get(event)
        if name is not None:
          tmetrics.counter(name).inc()
        with cls._lock:
          watches = list(cls._active)
        for watch in watches:
          watch._observe_event(event)

      def on_duration(event: str, duration: float, **kwargs):
        if event == _BACKEND_COMPILE_DURATION:
          tmetrics.counter("compile_cache.backend_compiles").inc()
        with cls._lock:
          watches = list(cls._active)
        for watch in watches:
          watch._observe_duration(event)

      monitoring.register_event_listener(on_event)
      monitoring.register_event_duration_secs_listener(on_duration)
      cls._installed = True

  @classmethod
  def install_tap(cls) -> None:
    """Installs the jax.monitoring listeners WITHOUT opening a watch:
    the registry counters above start accumulating for the process
    lifetime. Trainers call this at entry so compile-cache traffic —
    especially warm-path recompiles — shows up in their logs. The
    counter names are touched on EVERY call (listener install is
    once-per-process) so the keys exist in the registry — at zero —
    even before the first cache event or after a registry reset."""
    for name in ("compile_cache.hits", "compile_cache.misses",
                 "compile_cache.requests",
                 "compile_cache.backend_compiles"):
      tmetrics.counter(name)
    cls._install()

  def _observe_event(self, event: str) -> None:
    # Compiles can run on startup-overlap threads; counter updates
    # take the class lock so none are lost.
    with type(self)._lock:
      if event == _CACHE_HIT_EVENT:
        self.cache_hits += 1
      elif event == _CACHE_MISS_EVENT:
        self.cache_misses += 1
      elif event == _CACHE_REQUEST_EVENT:
        self.cache_requests += 1

  def _observe_duration(self, event: str) -> None:
    with type(self)._lock:
      if event == _BACKEND_COMPILE_DURATION:
        self.backend_compiles += 1

  def __enter__(self) -> "CompileWatch":
    type(self)._install()
    with type(self)._lock:
      type(self)._active.append(self)
    return self

  def __exit__(self, *exc) -> bool:
    with type(self)._lock:
      type(self)._active.remove(self)
    return False

  def counts(self) -> dict:
    return {
        "cache_hits": self.cache_hits,
        "cache_misses": self.cache_misses,
        "cache_requests": self.cache_requests,
        "backend_compiles": self.backend_compiles,
    }
