"""Mixture-of-experts with expert parallelism over the `expert` axis.

The reference has no MoE and no expert parallelism (SURVEY.md §3
parallelism inventory marks EP "n/a"); the `expert` mesh axis exists so
the transformer trunk scales capacity without scaling per-token FLOPs —
the same reason the `seq` axis carries ring attention. The design is
the standard static-shape GShard/Switch formulation, built TPU-first:

  * Routing is top-k softmax gating with a STATIC per-group capacity
    C = ceil(k · tokens/E · capacity_factor): dispatch and combine are
    dense one-hot einsums over [tokens, E, C], so XLA sees fixed
    shapes — no sorts with dynamic output sizes, no ragged buffers.
    Tokens past an expert's capacity are dropped (their combine weight
    is zero and the residual stream carries them through unchanged —
    the Switch-transformer semantics).
  * Expert parallelism is a `shard_map` over the `expert` axis: each
    device routes ITS OWN tokens (router weights replicated, router
    math is tiny), then one `lax.all_to_all` carries dispatched tokens
    to the devices holding their experts and a second carries expert
    outputs back. Both are differentiable (transpose of all-to-all is
    all-to-all), so training works through the sharded path.
  * Capacity is per token-group (= per device), so device count only
    changes WHICH tokens overflow a full expert, never the math of
    routed tokens: with capacity_factor high enough that nothing
    drops, the sharded result equals the single-device reference
    exactly (tested).

`moe_mlp` is the functional core (used under shard_map and as the
single-device reference); `MoEMLP` is the flax module that owns the
params and sows the load-balance auxiliary loss.

Composition note: EP groups tokens over the data (+expert) axes. In a
mesh that ALSO has a non-trivial `seq` axis (ring attention), the MoE
layer still computes correctly, but GSPMD must reshard activations
from sequence-sharded to token-group-sharded and back around every
MoE layer — an extra all-to-all-ish cost the collective audit does
not pin. Long-context MoE layouts should put MoE cadence low
(`moe_every` high) or keep `expert` and `seq` on separate meshes.
"""

from __future__ import annotations

import functools
from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from tensor2robot_tpu.parallel.mesh import EXPERT_AXIS, shard_map_compat

_EPS = 1e-9


def expert_capacity(num_tokens: int, num_experts: int, k: int,
                    capacity_factor: float) -> int:
  """Static per-group expert capacity (≥1 so every expert has a slot)."""
  return max(1, int(np.ceil(
      k * num_tokens / num_experts * capacity_factor)))


def top_k_routing(
    logits: jax.Array,
    capacity: int,
    k: int,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
  """Builds dense dispatch/combine tensors from router logits.

  Args:
    logits: [N, E] router logits for one token group (f32).
    capacity: static slots per expert for this group.
    k: experts per token (1 = Switch, 2 = GShard-style).

  Returns:
    dispatch: [N, E, C] 0/1 — token n occupies slot c of expert e.
    combine:  [N, E, C] f32 — gate weights (renormalized over the
      token's KEPT choices) at the occupied slots.
    aux: scalar load-balance loss (Switch eq. 4: E · Σ_e f_e·p_e with
      f_e the fraction of tokens whose FIRST choice is e and p_e the
      mean router probability of e) — 1.0 at perfect balance.
  """
  n, num_experts = logits.shape
  gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

  remaining = gates
  counts = jnp.zeros((num_experts,), jnp.float32)
  dispatch = jnp.zeros((n, num_experts, capacity), jnp.float32)
  gate_sum = jnp.zeros((n,), jnp.float32)
  combine = jnp.zeros((n, num_experts, capacity), jnp.float32)
  aux = 0.0
  for choice in range(k):
    expert = jnp.argmax(remaining, axis=-1)                  # [N]
    onehot = jax.nn.one_hot(expert, num_experts)             # [N, E]
    if choice == 0:
      aux = num_experts * jnp.sum(
          jnp.mean(onehot, axis=0) * jnp.mean(gates, axis=0))
    # Slot index within each expert: tokens claim slots in order,
    # offset by the slots earlier choices already filled.
    position = (jnp.cumsum(onehot, axis=0) - onehot
                + counts[None, :])                           # [N, E]
    slot = jnp.sum(position * onehot, axis=-1).astype(jnp.int32)
    kept = (slot < capacity).astype(jnp.float32)
    gate = jnp.sum(gates * onehot, axis=-1)                  # [N]
    hot = (kept[:, None, None] * onehot[:, :, None]
           * jax.nn.one_hot(slot, capacity)[:, None, :])     # [N, E, C]
    dispatch = dispatch + hot
    combine = combine + gate[:, None, None] * hot
    gate_sum = gate_sum + gate * kept
    counts = counts + jnp.sum(onehot * kept[:, None], axis=0)
    remaining = remaining * (1.0 - onehot)
  combine = combine / jnp.maximum(gate_sum, _EPS)[:, None, None]
  return dispatch, combine, aux


def moe_mlp(
    x: jax.Array,
    router: jax.Array,
    w_in: jax.Array,
    b_in: jax.Array,
    w_out: jax.Array,
    b_out: jax.Array,
    *,
    k: int,
    capacity_factor: float,
) -> Tuple[jax.Array, jax.Array]:
  """Dense-dispatch MoE over one token group (the per-device body).

  x [N, M]; router [M, E]; w_in [E, M, H]; b_in [E, H];
  w_out [E, H, M]; b_out [E, M] → ([N, M], aux scalar).
  """
  n, _ = x.shape
  num_experts = router.shape[-1]
  capacity = expert_capacity(n, num_experts, k, capacity_factor)
  logits = x.astype(jnp.float32) @ router
  dispatch, combine, aux = top_k_routing(logits, capacity, k)
  dtype = x.dtype
  xd = jnp.einsum("nm,nec->ecm", x, dispatch.astype(dtype))
  h = jax.nn.gelu(
      jnp.einsum("ecm,emh->ech", xd, w_in) + b_in[:, None, :])
  y = jnp.einsum("ech,ehm->ecm", h, w_out) + b_out[:, None, :]
  out = jnp.einsum("ecm,nec->nm", y, combine.astype(dtype))
  return out.astype(dtype), aux


def _moe_local(x, router, w_in, b_in, w_out, b_out, *, k,
               capacity_factor, axis_name, num_experts, mean_axes):
  """Per-device body under shard_map: route local tokens, exchange.

  x local [N_local, M]; expert params local [E/P, ...]. The two
  all-to-alls are the whole EP communication story: dispatched tokens
  out to their experts' devices, expert outputs back home. `mean_axes`
  are every mesh axis the token dim is sharded over (data + expert),
  so the returned aux loss is the global mean and legitimately
  replicated.
  """
  n = x.shape[0]
  capacity = expert_capacity(n, num_experts, k, capacity_factor)
  logits = x.astype(jnp.float32) @ router
  dispatch, combine, aux = top_k_routing(logits, capacity, k)
  dtype = x.dtype
  # [E, C, M]: this device's tokens, laid out per destination expert.
  xd = jnp.einsum("nm,nec->ecm", x, dispatch.astype(dtype))
  # Exchange: split the expert dim across devices, concatenate the
  # incoming groups on the capacity dim → [E/P, C·P, M]: all devices'
  # tokens for MY experts.
  xd = jax.lax.all_to_all(xd, axis_name, split_axis=0, concat_axis=1,
                          tiled=True)
  h = jax.nn.gelu(
      jnp.einsum("ecm,emh->ech", xd, w_in) + b_in[:, None, :])
  y = jnp.einsum("ech,ehm->ecm", h, w_out) + b_out[:, None, :]
  # Inverse exchange: groups back to their home devices → [E, C, M].
  y = jax.lax.all_to_all(y, axis_name, split_axis=1, concat_axis=0,
                         tiled=True)
  out = jnp.einsum("ecm,nec->nm", y, combine.astype(dtype))
  return out.astype(dtype), jax.lax.pmean(aux, mean_axes)


class MoEMLP(nn.Module):
  """Switch/GShard-style MoE feed-forward (drop-in for a dense MLP).

  With `mesh=None` (or no non-trivial `expert` axis) runs the dense
  single-device formulation; with an `expert` axis, expert weights
  live sharded over it and tokens all-to-all to their experts. The
  load-balance auxiliary loss is sown into the "aux_loss" collection
  under "moe_aux" — training models add
  `aux_weight · sum(collected)` to their loss (see
  `collect_aux_losses`).
  """

  num_experts: int
  hidden_dim: int
  k: int = 2
  capacity_factor: float = 2.0
  mesh: Optional[Mesh] = None
  dtype: Any = jnp.bfloat16

  @nn.compact
  def __call__(self, x: jax.Array) -> jax.Array:
    b, t, model_dim = x.shape
    e, h = self.num_experts, self.hidden_dim
    init = nn.initializers.lecun_normal()
    router = self.param("router", init, (model_dim, e), jnp.float32)
    # The "moe_expert_" prefix is the contract `expert_sharding` keys
    # on: it is OWNED by this module (nothing else may name params
    # with it), so expert weights shard correctly no matter what the
    # parent trunk names its MoEMLP instance.
    w_in = self.param("moe_expert_w_in", init, (e, model_dim, h),
                      jnp.float32).astype(self.dtype)
    b_in = self.param("moe_expert_b_in", nn.initializers.zeros,
                      (e, h), jnp.float32).astype(self.dtype)
    w_out = self.param("moe_expert_w_out", init, (e, h, model_dim),
                       jnp.float32).astype(self.dtype)
    b_out = self.param("moe_expert_b_out", nn.initializers.zeros,
                       (e, model_dim), jnp.float32).astype(self.dtype)

    x = x.astype(self.dtype)
    tokens = x.reshape(b * t, model_dim)
    mesh = self.mesh
    if (mesh is None or EXPERT_AXIS not in mesh.axis_names
        or mesh.shape[EXPERT_AXIS] == 1):
      out, aux = moe_mlp(tokens, router, w_in, b_in, w_out, b_out,
                         k=self.k, capacity_factor=self.capacity_factor)
    else:
      from jax.sharding import PartitionSpec as P

      from tensor2robot_tpu.parallel.mesh import DATA_AXIS

      part = mesh.shape[EXPERT_AXIS]
      if e % part:
        raise ValueError(
            f"num_experts {e} must be a multiple of the "
            f"{EXPERT_AXIS!r} axis size {part}.")
      # Tokens group per device: the batch shards over data AND
      # expert axes jointly (standard dp×ep layout — the expert axis
      # doubles as extra data parallelism outside MoE blocks).
      token_axes = tuple(a for a in (DATA_AXIS, EXPERT_AXIS)
                         if a in mesh.axis_names)
      groups = int(np.prod([mesh.shape[a] for a in token_axes]))
      if (b * t) % groups:
        raise ValueError(
            f"token count {b}×{t} must be a multiple of the {groups} "
            f"token groups of mesh axes {token_axes}.")
      body = functools.partial(
          _moe_local, k=self.k, capacity_factor=self.capacity_factor,
          axis_name=EXPERT_AXIS, num_experts=e,
          mean_axes=token_axes)
      tok = P(token_axes)
      ep = P(EXPERT_AXIS)
      out, aux = shard_map_compat(
          body, mesh,
          in_specs=(tok, P(), ep, ep, ep, ep),
          out_specs=(tok, P()),
      )(tokens, router, w_in, b_in, w_out, b_out)
    self.sow("aux_loss", "moe_aux", aux)
    return out.reshape(b, t, model_dim)


def collect_aux_losses(variables: Any) -> jax.Array:
  """Sums every sown aux loss (0.0 when the model has none)."""
  total = jnp.asarray(0.0, jnp.float32)
  for leaf in jax.tree_util.tree_leaves(variables.get("aux_loss", {})):
    total = total + jnp.sum(jnp.asarray(leaf, jnp.float32))
  return total
