"""Device mesh construction and standard shardings.

This is the TPU-native replacement for the reference's TPUEstimator
replication (SURVEY.md §3 parallelism inventory): a named
`jax.sharding.Mesh` over which train steps are jitted. Axis conventions,
used across the framework:

  * ``data``  — batch (data-parallel); gradients all-reduce over it.
  * ``fsdp``  — optional parameter/optimizer sharding axis (zero-style);
                combined with ``data`` for the batch dimension.
  * ``model`` — tensor-parallel axis for wide layers.
  * ``seq``   — sequence/context-parallel axis (ring attention).
  * ``expert`` — expert-parallel axis (MoE layers; tokens all-to-all
                 to the devices holding their routed experts).
  * ``stage`` — pipeline-parallel axis (layer stages; activations
                ppermute stage-to-stage over microbatches).

The reference never goes beyond data parallel; the extra axes exist so
the same step functions scale to pod slices without restructuring.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tensor2robot_tpu import config as gin

DATA_AXIS = "data"
FSDP_AXIS = "fsdp"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
EXPERT_AXIS = "expert"
STAGE_AXIS = "stage"


@gin.configurable
def create_mesh(
    axis_shapes: Optional[Dict[str, int]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
  """Builds a named mesh.

  Args:
    axis_shapes: ordered {axis_name: size}; one axis may be -1 (absorbs
      remaining devices). Default: all devices on the `data` axis.
    devices: defaults to jax.devices().
  """
  devices = list(devices if devices is not None else jax.devices())
  if axis_shapes is None:
    axis_shapes = {DATA_AXIS: len(devices)}
  names = tuple(axis_shapes.keys())
  sizes = list(axis_shapes.values())
  n_devices = len(devices)
  if sizes.count(-1) > 1:
    raise ValueError("At most one mesh axis may be -1.")
  if -1 in sizes:
    known = int(np.prod([s for s in sizes if s != -1]))
    if n_devices % known != 0:
      raise ValueError(
          f"Cannot infer -1 axis: {n_devices} devices not divisible by "
          f"{known}.")
    sizes[sizes.index(-1)] = n_devices // known
  if int(np.prod(sizes)) != n_devices:
    raise ValueError(
        f"Mesh {dict(zip(names, sizes))} needs {int(np.prod(sizes))} "
        f"devices, have {n_devices}.")
  device_array = np.asarray(devices).reshape(sizes)
  return Mesh(device_array, names)


def shard_map_compat(body, mesh: Mesh, *, in_specs, out_specs):
  """`shard_map` across jax versions: the top-level `jax.shard_map`
  binding (with `check_vma`) only exists in newer jaxes; older ones
  ship it under `jax.experimental.shard_map` with the `check_rep`
  spelling. The replication check is disabled either way (pmean'd
  scalars the framework returns from per-device bodies are
  legitimately replicated, but the checker can't always prove it)."""
  if hasattr(jax, "shard_map"):
    return jax.shard_map(body, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=False)
  from jax.experimental.shard_map import shard_map

  return shard_map(body, mesh=mesh, in_specs=in_specs,
                   out_specs=out_specs, check_rep=False)


def replicated(mesh: Mesh) -> NamedSharding:
  return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh) -> NamedSharding:
  """Shards dim 0 over every data-like axis present in the mesh."""
  axes = tuple(a for a in (DATA_AXIS, FSDP_AXIS) if a in mesh.axis_names)
  return NamedSharding(mesh, P(axes if axes else None))


def local_batch_size(mesh: Mesh, global_batch_size: int) -> int:
  axes = [a for a in (DATA_AXIS, FSDP_AXIS) if a in mesh.axis_names]
  shards = int(np.prod([mesh.shape[a] for a in axes])) if axes else 1
  if global_batch_size % shards != 0:
    raise ValueError(
        f"Global batch {global_batch_size} not divisible by {shards} "
        f"data shards.")
  return global_batch_size // shards
