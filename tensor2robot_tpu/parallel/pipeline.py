"""Pipeline parallelism: layer stages over the `stage` mesh axis.

The reference has no pipeline parallelism (SURVEY.md §3 marks PP "not
needed for these CNN-scale models") and the robot-scale flagships here
don't need it either — but a complete TPU framework must scale models
whose LAYERS don't fit one chip, so the `stage` axis carries a
GPipe-style microbatched pipeline built from SPMD primitives:

  * Stage parameters live STACKED with a leading stage dim, sharded
    over the `stage` axis — each device materializes only its own
    stage's weights (the memory win that motivates PP).
  * The schedule is a single `lax.scan` over M + S - 1 ticks: stage 0
    ingests a fresh microbatch each tick, every stage applies its
    layer to the activation it holds, and activations `ppermute` one
    hop down the ring. The last stage collects finished microbatches.
    Per-device FLOPs per tick are one stage on one microbatch; the
    (S-1)/(M+S-1) bubble is the standard GPipe cost, amortized by
    more microbatches.
  * Backward needs no hand-written schedule: `jax.grad` through the
    scan + ppermute yields the reversed pipeline automatically (the
    transpose of a ppermute is the reverse ppermute), with cotangents
    flowing back up the ring.

Stages must be shape-preserving (activation in == activation out),
which transformer blocks satisfy; that invariant is what lets one
rotating buffer serve every stage.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tensor2robot_tpu.parallel.mesh import (
    DATA_AXIS,
    STAGE_AXIS,
    shard_map_compat,
)


def init_stage_params(
    init_fn: Callable[[jax.Array], Any],
    rng: jax.Array,
    num_stages: int,
) -> Any:
  """Stacks per-stage params: init_fn(rng) vmapped over S fresh rngs.

  Every leaf gains a leading [S] dim — the dim `stage_sharding`
  shards. Use with `module.init` partials:
  `init_stage_params(lambda r: stage.init(r, x_micro), rng, S)`.
  """
  return jax.vmap(init_fn)(jax.random.split(rng, num_stages))


def stage_sharding(mesh: Mesh, tree: Any) -> Any:
  """NamedShardings putting every leaf's leading stage dim on `stage`."""
  def rule(leaf):
    ndim = getattr(leaf, "ndim", 0)
    if not ndim:
      return NamedSharding(mesh, P())
    return NamedSharding(mesh, P(STAGE_AXIS))
  return jax.tree_util.tree_map(rule, tree)


def _pipeline_local(params, x, *, apply_fn, num_stages, axis_name,
                    remat):
  """Per-device body: my stage's params (leading dim 1), all microbatches.

  x: [M, mb_local, ...]; returns [M, mb_local, ...] — valid on every
  device (the last stage's collected outputs are psum-broadcast so the
  caller sees an ordinary replicated-over-stage activation).
  """
  params = jax.tree_util.tree_map(lambda l: l[0], params)
  idx = jax.lax.axis_index(axis_name)
  num_micro = x.shape[0]
  perm = [(j, (j + 1) % num_stages) for j in range(num_stages)]
  if remat:
    # GPipe's standard memory trade: store only stage boundaries,
    # recompute within-stage activations in the backward.
    # prevent_cse=False is documented safe (and faster) under scan.
    apply_fn = jax.checkpoint(apply_fn, prevent_cse=False)

  def tick(carry, t):
    state, out = carry
    # Stage 0 ingests microbatch t (clamped re-feeds past the end are
    # never collected: they would finish after the last tick).
    inp = jax.lax.dynamic_index_in_dim(
        x, jnp.minimum(t, num_micro - 1), 0, keepdims=False)
    state = jnp.where(idx == 0, inp, state)
    y = apply_fn(params, state)
    # The last stage finishes microbatch t - (S-1) this tick.
    done = t - (num_stages - 1)
    collect = (idx == num_stages - 1) & (done >= 0)
    out = jnp.where(
        collect,
        jax.lax.dynamic_update_index_in_dim(
            out, y, jnp.clip(done, 0, num_micro - 1), 0),
        out)
    state = jax.lax.ppermute(y, axis_name, perm)
    return (state, out), ()

  init = (jnp.zeros_like(x[0]), jnp.zeros_like(x))
  (_, out), _ = jax.lax.scan(
      tick, init, jnp.arange(num_micro + num_stages - 1))
  # Only the last stage holds real outputs; sum-broadcast over the
  # stage ring so out_specs can declare the result stage-replicated.
  return jax.lax.psum(jnp.where(idx == num_stages - 1, out, 0.0),
                      axis_name)


def pipeline_apply(
    apply_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,
    x: jax.Array,
    *,
    mesh: Optional[Mesh],
    num_microbatches: int,
    axis_name: str = STAGE_AXIS,
    remat: bool = False,
) -> jax.Array:
  """Runs x through S pipelined stages of `apply_fn`.

  Args:
    apply_fn: (one stage's params, activation [mb, ...]) → same-shape
      activation. Typically `stage_module.apply` with a params dict.
    stage_params: pytree with leading [S] dim on every leaf (see
      `init_stage_params`), sharded (or shardable) over `axis_name`.
    x: [B, ...] global batch; B must divide into `num_microbatches`
      (× the data-axis size when the mesh has one — the batch dim
      shards over `data`, microbatching happens on the per-shard rows).
    mesh: mesh with `axis_name`; its size S is the stage count.
    num_microbatches: M; the pipeline bubble is (S-1)/(M+S-1).
    remat: rematerialize within-stage activations in the backward
      (`jax.checkpoint` around each stage application) — activation
      memory drops from per-layer to per-stage-boundary at ~1/3 more
      FLOPs, the standard GPipe configuration for deep stages.

  Returns [B, ...] with the same sharding layout as x.

  Falls back to a sequential scan of stages when the mesh is None or
  has no non-trivial stage axis — same math, one code path for models.
  """
  if (mesh is None or axis_name not in mesh.axis_names
      or mesh.shape[axis_name] == 1):
    fn = (jax.checkpoint(apply_fn, prevent_cse=False) if remat
          else apply_fn)
    def body(h, p):
      return fn(p, h), ()
    out, _ = jax.lax.scan(body, x, stage_params)
    return out

  num_stages = mesh.shape[axis_name]
  batch = x.shape[0]
  data_size = (mesh.shape[DATA_AXIS]
               if DATA_AXIS in mesh.axis_names else 1)
  if batch % (num_microbatches * data_size):
    raise ValueError(
        f"Batch {batch} must be a multiple of num_microbatches="
        f"{num_microbatches} × data axis {data_size}.")
  # [B, ...] -> [M, B/M, ...]; rows stay contiguous per microbatch so
  # the data-axis sharding of the batch dim carries over to dim 1.
  micro = x.reshape((num_microbatches, batch // num_microbatches)
                    + x.shape[1:])

  body = functools.partial(
      _pipeline_local, apply_fn=apply_fn, num_stages=num_stages,
      axis_name=axis_name, remat=remat)
  data_axis = DATA_AXIS if DATA_AXIS in mesh.axis_names else None
  xspec = P(None, data_axis)
  out = shard_map_compat(
      body, mesh,
      in_specs=(P(STAGE_AXIS), xspec), out_specs=xspec,
  )(stage_params, micro)
  return out.reshape(x.shape)
