"""Ring attention: sequence/context parallelism over the `seq` mesh axis.

The reference never needed long-context — robot episodes are short
(SURVEY.md §3 parallelism table marks SP/CP "n/a for parity; design
mesh axes so it can be added"). The `seq` axis was reserved in
`parallel/mesh.py` for exactly this module: attention over sequences
too long for one chip's HBM, sharded on the time dimension.

Design (ring attention, Liu et al. 2023-style, built from JAX SPMD
primitives — no NCCL-ish backend to port):
  * q/k/v live sharded [B, T/P, H, D] per device over the `seq` axis
    (`shard_map` keeps XLA from trying to gather the full sequence).
  * Each device keeps its Q block resident and consumes K/V blocks as
    they rotate around the ring via `lax.ppermute` — P-1 neighbor
    exchanges over ICI, each overlapped with the block's attention
    math, never materializing the [T, T] score matrix or the full K/V.
  * Blocks combine with the flash-attention online softmax (running
    max/normalizer/accumulator in f32), so the result is EXACT
    attention, independent of P.
  * Causal masking uses global positions derived from
    `lax.axis_index` — block-diagonal triangular, fully-masked blocks
    contribute zero (guarded against -inf/0 NaNs).

`ring_attention` is the public entry: full [B, T, H, D] arrays in, the
shard_map + sharding plumbing handled here; it degrades to the exact
same math single-device, so models call one function everywhere.
"""

from __future__ import annotations

import functools
import warnings
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tensor2robot_tpu.parallel.mesh import (
    DATA_AXIS,
    SEQ_AXIS,
    shard_map_compat,
)

_NEG_INF = -1e30  # finite sentinel: avoids -inf - -inf = nan paths


def attention_reference(q: jax.Array, k: jax.Array, v: jax.Array,
                        causal: bool = False) -> jax.Array:
  """Plain softmax attention (f32 accumulation), the exactness oracle.

  q, k, v: [B, T, H, D] → [B, T, H, D].
  """
  scale = 1.0 / np.sqrt(q.shape[-1])
  s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                 k.astype(jnp.float32)) * scale
  if causal:
    t = q.shape[1]
    mask = jnp.tril(jnp.ones((t, t), bool))
    s = jnp.where(mask[None, None], s, _NEG_INF)
  p = jax.nn.softmax(s, axis=-1)
  out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
  return out.astype(q.dtype)


def _block_attend(q, k, v, mask, m, l, o, scale):
  """One flash-style block update of the (m, l, o) running state.

  q [B, Tq, H, D]; k/v [B, Tk, H, D]; mask [Tq, Tk] bool or None;
  m/l [B, H, Tq]; o [B, H, Tq, D] (all f32).
  """
  s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                 k.astype(jnp.float32)) * scale
  if mask is not None:
    s = jnp.where(mask[None, None], s, _NEG_INF)
  m_new = jnp.maximum(m, s.max(axis=-1))
  # Fully-masked-so-far rows keep m at the sentinel; exp underflows to
  # 0 harmlessly because the sentinel is finite.
  p = jnp.exp(s - m_new[..., None])
  if mask is not None:
    p = jnp.where(mask[None, None], p, 0.0)
  alpha = jnp.exp(m - m_new)
  l_new = alpha * l + p.sum(axis=-1)
  o_new = (alpha[..., None] * o
           + jnp.einsum("bhqk,bkhd->bhqd", p, v.astype(jnp.float32)))
  return m_new, l_new, o_new


def _ring_attention_local(q, k, v, axis_name: str, causal: bool):
  """Per-device body under shard_map: local Q, rotating K/V blocks."""
  ring_size = jax.lax.psum(1, axis_name)
  idx = jax.lax.axis_index(axis_name)
  batch, t_local, heads, dim = q.shape
  scale = 1.0 / np.sqrt(dim)
  rows = idx * t_local + jnp.arange(t_local)

  perm = [(j, (j - 1) % ring_size) for j in range(ring_size)]

  def step(carry, s):
    k_blk, v_blk, m, l, o = carry
    src = (idx + s) % ring_size
    mask = None
    if causal:
      cols = src * t_local + jnp.arange(t_local)
      mask = cols[None, :] <= rows[:, None]
    m, l, o = _block_attend(q, k_blk, v_blk, mask, m, l, o, scale)
    # Rotate: device j's block moves to j-1, so next step this device
    # holds the block that originated at idx + s + 1. The final
    # rotation returns K/V to their home devices (donation-friendly).
    k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
    v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
    return (k_blk, v_blk, m, l, o), ()

  init = (
      k, v,
      jnp.full((batch, heads, t_local), _NEG_INF, jnp.float32),
      jnp.zeros((batch, heads, t_local), jnp.float32),
      jnp.zeros((batch, heads, t_local, dim), jnp.float32),
  )
  (_, _, m, l, o), _ = jax.lax.scan(step, init,
                                    jnp.arange(ring_size))
  # Rows with zero mass (possible only under exotic masks) output 0.
  out = o / jnp.maximum(l[..., None], 1e-30)
  return out.transpose(0, 2, 1, 3).astype(q.dtype)


def _ring_attention_local_flash(q, k, v, axis_name: str, causal: bool,
                                ring_size: int, interpret: bool):
  """Per-device ring body running the PALLAS kernel on each block.

  The composition insight: with the ring statically unrolled, step 0
  is exactly the causal DIAGONAL block (q and k are the same local
  slice, so the kernel's in-call causal mask is the right mask), and
  every later step is either fully attended (source block in the
  past) or fully excluded (future) — a per-device SCALAR decision,
  so excluded steps skip the kernel entirely under `lax.cond`
  (halving the causal per-device FLOPs) and contribute lse = -inf.
  Partial outputs combine exactly via their logsumexps; because the
  kernel's lse output is differentiable, `jax.grad` flows through
  the whole ring (cond branches, ppermute rotations and the
  softmax-weighted merge are all standard differentiable JAX).
  """
  from tensor2robot_tpu.ops.flash_attention import (
      flash_attention_with_lse,
  )

  idx = jax.lax.axis_index(axis_name)
  perm = [(j, (j - 1) % ring_size) for j in range(ring_size)]
  batch, t_local, heads, _ = q.shape

  def attend(qq, kk, vv, block_causal):
    return flash_attention_with_lse(
        qq, kk, vv, causal=block_causal, interpret=interpret)

  def skip(qq, kk, vv):
    del kk, vv
    return (jnp.zeros_like(qq),
            jnp.full((batch, heads, t_local), _NEG_INF, jnp.float32))

  outs, lses = [], []
  for s in range(ring_size):
    if causal and s > 0:
      # Blocks from the future (src > idx) are fully excluded: skip
      # the kernel — the ppermute still rotates K/V through.
      src = (idx + s) % ring_size
      o_s, lse_s = jax.lax.cond(
          src < idx, functools.partial(attend, block_causal=False),
          skip, q, k, v)
    else:
      o_s, lse_s = attend(q, k, v, block_causal=(causal and s == 0))
    outs.append(o_s)
    lses.append(lse_s)
    if s < ring_size - 1:
      k = jax.lax.ppermute(k, axis_name, perm)
      v = jax.lax.ppermute(v, axis_name, perm)
  lse = jnp.stack(lses)                      # [S, B, H, Tq]
  weights = jax.nn.softmax(lse, axis=0)      # exact partial combine
  out = jnp.einsum("sbht,sbthd->bthd", weights,
                   jnp.stack(outs).astype(jnp.float32))
  return out.astype(q.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Optional[Mesh] = None,
    axis_name: str = SEQ_AXIS,
    causal: bool = False,
    shard_batch: bool = True,
    block_impl: str = "reference",
    flash_interpret: bool = False,
) -> jax.Array:
  """Exact attention with the sequence dim sharded over `axis_name`.

  Args:
    q, k, v: [B, T, H, D]; T must divide by the `axis_name` mesh size.
    mesh: the device mesh; None (or no/trivial `axis_name` axis) falls
      back to the single-device reference — same math, one function
      for models to call everywhere.
    causal: causal masking by global position.
    shard_batch: also shard B over the `data` axis when the mesh has
      one (the standard data × sequence 2D layout).
    block_impl: per-device block math — "reference" (jnp online
      softmax) or "flash" (the Pallas kernel per block, partials
      combined by logsumexp; the long-context production path on TPU).
    flash_interpret: run the kernel in the pallas interpreter (CPU
      tests).

  Returns [B, T, H, D], sharded like q.
  """
  if (mesh is None or axis_name not in mesh.axis_names
      or mesh.shape[axis_name] == 1):
    return attention_reference(q, k, v, causal=causal)
  if q.shape[1] % mesh.shape[axis_name]:
    raise ValueError(
        f"Sequence length {q.shape[1]} must divide the {axis_name!r} "
        f"axis size {mesh.shape[axis_name]}.")

  # B shards over `data` when it divides; otherwise it replicates so
  # the function still serves any batch. B == 1 (a model init's dummy
  # batch, single-example serving) replicates silently — that's the
  # designed path. Any other non-divisible B warns: training batches
  # are divisibility-enforced upstream (`mesh.local_batch_size`), so
  # hitting this in a train loop means the layout is wrong and every
  # data row is burning axis_size× the FLOPs.
  batch_axis = None
  if shard_batch and DATA_AXIS in mesh.axis_names:
    data_size = mesh.shape[DATA_AXIS]
    if q.shape[0] % data_size == 0:
      batch_axis = DATA_AXIS
    elif q.shape[0] != 1:
      warnings.warn(
          f"ring_attention: batch {q.shape[0]} does not divide the "
          f"{DATA_AXIS!r} axis size {data_size}; replicating the "
          "batch across it (correct but axis_size× redundant "
          "compute). Fine for small-batch serving; a training batch "
          "should be a multiple of the data axis.",
          RuntimeWarning, stacklevel=2)
  spec = P(batch_axis, axis_name, None, None)
  if block_impl == "flash":
    local = functools.partial(
        _ring_attention_local_flash, axis_name=axis_name,
        causal=causal, ring_size=mesh.shape[axis_name],
        interpret=flash_interpret)
  elif block_impl == "reference":
    local = functools.partial(
        _ring_attention_local, axis_name=axis_name, causal=causal)
  else:
    raise ValueError(f"Unknown block_impl: {block_impl!r}")
  fn = shard_map_compat(
      lambda q, k, v: local(q, k, v),
      mesh, in_specs=(spec, spec, spec), out_specs=spec)
  return fn(q, k, v)


def sequence_sharding(mesh: Mesh,
                      shard_batch: bool = True) -> NamedSharding:
  """The [B, T, ...] activation sharding matching `ring_attention`."""
  batch_axis = (DATA_AXIS if shard_batch
                and DATA_AXIS in mesh.axis_names else None)
  return NamedSharding(mesh, P(batch_axis, SEQ_AXIS))
