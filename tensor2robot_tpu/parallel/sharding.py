"""Parameter/optimizer sharding strategies over the named mesh.

The reference's only distribution strategy was TPUEstimator data
parallelism (SURVEY.md §3 parallelism inventory). Here sharding is a
first-class design axis — and since the rules-seam refactor every
strategy is a RULES-TABLE SELECTION over `parallel/rules.py`'s
`match_partition_rules` engine rather than a bespoke tree-walk: a
strategy is an ordered (param-path regex → placement) table; the
engine resolves placements against the mesh and each leaf's shape and
GSPMD inserts the all-gathers/reduce-scatters over ICI.

Strategy tables (docs/SHARDING.md):
  * fsdp: shard the LARGEST divisible dim of each leaf; leaves smaller
    than `min_size_to_shard` stay replicated (latency > memory win).
  * tp: dense kernels additionally split their output dim when
    divisible (megatron-style column parallel) — opt-in.
  * ep / pipeline: stacked expert / stage weights put their leading
    dim on the `expert` / `stage` axis via the SHARED stack regexes
    (`rules.EXPERT_STACK_RE`, `rules.STAGE_STACK_RE`) — the old
    hard-coded `moe_expert_` prefix special-case in `expert_sharding`
    is now one declarative rule.
  * data / train_state_update: the ZeRO weight-update sharding
    ("Automatic Cross-Replica Sharding of Weight Update in
    Data-Parallel Training", PAPERS.md), parameterized by `axis` so it
    composes with the shard_map pod program's `pod` axis as well as
    the jit-mesh `data` axis.

The pre-refactor outputs are regression-pinned spec-for-spec by
tests/test_sharding_rules.py on the 8-device MULTICHIP axis.
"""

from __future__ import annotations

from typing import Any

from jax.sharding import Mesh, PartitionSpec as P

from tensor2robot_tpu.parallel.mesh import (
    DATA_AXIS,
    EXPERT_AXIS,
    FSDP_AXIS,
    MODEL_AXIS,
    STAGE_AXIS,
)
from tensor2robot_tpu.parallel.rules import (
    EXPERT_STACK_RE,
    STAGE_STACK_RE,
    ColumnParallel,
    Replicate,
    Rules,
    ShardLargest,
    ShardLeading,
    match_partition_rules,
    specs_to_shardings,
)

# Path segment naming a TrainState's optimizer collection — the seam
# `train_state_update_sharding` keys the ZeRO moment sharding on.
OPT_STATE_RE = r"(^|/)opt_state(/|$)"


def _apply_rules(mesh: Mesh, tree: Any, rules: Rules,
                 min_size_to_shard: int) -> Any:
  return specs_to_shardings(mesh, match_partition_rules(
      rules, tree, mesh, min_size_to_shard=min_size_to_shard))


def fsdp_sharding(
    mesh: Mesh,
    tree: Any,
    min_size_to_shard: int = 2 ** 10,
) -> Any:
  """NamedSharding pytree: largest divisible dim of each leaf on fsdp.

  Works on arrays or ShapeDtypeStructs. Leaves without a divisible dim
  (or too small) replicate. Optimizer states mirror their param leaf by
  construction (same shapes ⇒ same rule).
  """
  return _apply_rules(mesh, tree, ((r".*", ShardLargest(FSDP_AXIS)),),
                      min_size_to_shard)


def tensor_parallel_sharding(
    mesh: Mesh,
    tree: Any,
    min_size_to_shard: int = 2 ** 12,
) -> Any:
  """Megatron-ish: 2D kernels split output dim on `model` (+fsdp on
  in-dim); falls back to the fsdp rules on a model-less mesh."""
  return _apply_rules(mesh, tree, ((r".*", ColumnParallel()),),
                      min_size_to_shard)


def expert_sharding(mesh: Mesh, tree: Any,
                    min_size_to_shard: int = 2 ** 10) -> Any:
  """fsdp rules + expert weights sharded over the `expert` axis.

  The stacked-expert rule is `rules.EXPERT_STACK_RE` — a leaf whose
  own name is ``moe_expert_``-prefixed, the prefix OWNED by `MoEMLP`
  (`parallel/moe.py` names every stacked expert param with it and
  nothing else may). Mount-point independent: a trunk may instantiate
  its MoEMLP under any module name and the experts still shard, and
  optimizer mirrors (which nest the param path under opt-state
  prefixes) match the same rule. An indivisible leading expert dim
  raises (silently falling back to fsdp would replicate expert weights
  a pod expects sharded). With no `expert` mesh axis this IS
  `fsdp_sharding`.
  """
  return _apply_rules(
      mesh, tree,
      ((EXPERT_STACK_RE, ShardLeading(EXPERT_AXIS)),
       (r".*", ShardLargest(FSDP_AXIS))),
      min_size_to_shard)


def pipeline_sharding(mesh: Mesh, tree: Any,
                      min_size_to_shard: int = 2 ** 10) -> Any:
  """fsdp rules + stage-stacked weights sharded over the `stage` axis.

  The stack rule is `rules.STAGE_STACK_RE`: every leaf under a path
  segment named ``stages`` (`layers/pipelined_transformer.
  STAGE_PARAMS_NAME`) carries a leading [num_stages] dim and puts it
  on `stage` — each device materializes only its own stage's weights
  (and their optimizer mirrors, which share the path). An indivisible
  leading dim raises. With no `stage` mesh axis this IS
  `fsdp_sharding` (the sequential-fallback layout `pipeline_apply`
  runs against).
  """
  return _apply_rules(
      mesh, tree,
      ((STAGE_STACK_RE, ShardLeading(STAGE_AXIS)),
       (r".*", ShardLargest(FSDP_AXIS))),
      min_size_to_shard)


def data_update_sharding(
    mesh: Mesh,
    tree: Any,
    min_size_to_shard: int = 2 ** 10,
    axis: str = DATA_AXIS,
) -> Any:
  """Largest-divisible-dim sharding over `axis` for each leaf.

  The weight-update sharding of "Automatic Cross-Replica Sharding of
  Weight Update in Data-Parallel Training" (PAPERS.md): params stay
  replicated for the forward/backward, but the optimizer's gradients,
  moments, and update math are sharded across the replicas — GSPMD
  turns the gradient all-reduce into reduce-scatter, each replica
  updates 1/N of the weights, and one all-gather republishes them.
  Same leaf rule as `fsdp_sharding`, on `axis` (the jit-mesh `data`
  axis by default; the shard_map pod program passes its `pod` axis).
  """
  return _apply_rules(mesh, tree, ((r".*", ShardLargest(axis)),),
                      min_size_to_shard)


def train_state_update_sharding(mesh: Mesh, state: Any,
                                min_size_to_shard: int = 2 ** 10,
                                axis: str = DATA_AXIS) -> Any:
  """Shardings for a TrainState-bearing pytree with the optimizer
  state sharded over `axis` and everything else replicated.

  Keys on the `TrainState.opt_state` field name (`OPT_STATE_RE`):
  every leaf under a path segment named ``opt_state`` follows
  `data_update_sharding`; params/batch_stats/step (and a QTOptState's
  target net) replicate. Pass the result as the state's device_put/
  in_shardings AND out_shardings — a replicated out_sharding on
  opt_state would all-gather the moments back every step and erase
  the win.
  """
  return _apply_rules(
      mesh, state,
      ((OPT_STATE_RE, ShardLargest(axis)), (r".*", Replicate())),
      min_size_to_shard)


def replicated_sharding(mesh: Mesh, tree: Any,
                        min_size_to_shard: int = 0) -> Any:
  """Every leaf fully replicated — pure data parallelism.

  The right choice for models whose state fits comfortably per-chip
  (most robot-scale networks), and the baseline the collective-audit
  tests diff fsdp/tp against.
  """
  return _apply_rules(mesh, tree, ((r".*", P()),), min_size_to_shard)


def state_sharding(mesh: Mesh, state: Any,
                   strategy: str = "fsdp",
                   min_size_to_shard: int = 2 ** 10) -> Any:
  """Shardings for a full TrainState (params + opt mirrors, scalars repl)."""
  rule_fn = {"fsdp": fsdp_sharding,
             "tp": tensor_parallel_sharding,
             "ep": expert_sharding,
             "pipeline": pipeline_sharding,
             "replicated": replicated_sharding}[strategy]
  return rule_fn(mesh, state, min_size_to_shard=min_size_to_shard)
