"""Parameter/optimizer sharding rules over the named mesh.

The reference's only distribution strategy was TPUEstimator data
parallelism (SURVEY.md §3 parallelism inventory). Here sharding is a
first-class design axis: given a mesh with `fsdp` (zero-style parameter
sharding) and/or `model` (tensor-parallel) axes, these helpers derive
NamedShardings for every leaf of a param/opt pytree, and GSPMD inserts
the all-gathers/reduce-scatters over ICI.

Heuristics (CNN/MLP-scale models; large transformers would add explicit
per-layer rules):
  * fsdp: shard the LARGEST divisible dim of each leaf; leaves smaller
    than `min_size_to_shard` stay replicated (latency > memory win).
  * model: dense kernels additionally split their output dim when
    divisible (megatron-style column parallel) — opt-in.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tensor2robot_tpu.parallel.mesh import (
    EXPERT_AXIS,
    FSDP_AXIS,
    MODEL_AXIS,
    replicated,
)


def fsdp_sharding(
    mesh: Mesh,
    tree: Any,
    min_size_to_shard: int = 2 ** 10,
) -> Any:
  """NamedSharding pytree: largest divisible dim of each leaf on fsdp.

  Works on arrays or ShapeDtypeStructs. Leaves without a divisible dim
  (or too small) replicate. Optimizer states mirror their param leaf by
  construction (same shapes ⇒ same rule).
  """
  if FSDP_AXIS not in mesh.axis_names:
    repl = NamedSharding(mesh, P())
    return jax.tree_util.tree_map(lambda _: repl, tree)
  size = mesh.shape[FSDP_AXIS]

  def rule(leaf):
    shape = getattr(leaf, "shape", ())
    if not shape or int(np.prod(shape)) < min_size_to_shard:
      return NamedSharding(mesh, P())
    order = sorted(range(len(shape)), key=lambda i: -shape[i])
    for dim in order:
      if shape[dim] % size == 0:
        spec = [None] * len(shape)
        spec[dim] = FSDP_AXIS
        return NamedSharding(mesh, P(*spec))
    return NamedSharding(mesh, P())

  return jax.tree_util.tree_map(rule, tree)


def tensor_parallel_sharding(
    mesh: Mesh,
    tree: Any,
    min_size_to_shard: int = 2 ** 12,
) -> Any:
  """Megatron-ish: 2D kernels split output dim on `model` (+fsdp on in-dim)."""
  if MODEL_AXIS not in mesh.axis_names:
    return fsdp_sharding(mesh, tree, min_size_to_shard)
  tp = mesh.shape[MODEL_AXIS]
  fsdp = mesh.shape.get(FSDP_AXIS, 1)
  has_fsdp = FSDP_AXIS in mesh.axis_names

  def rule(leaf):
    shape = getattr(leaf, "shape", ())
    if not shape or int(np.prod(shape)) < min_size_to_shard:
      return NamedSharding(mesh, P())
    if len(shape) >= 2 and shape[-1] % tp == 0:
      spec = [None] * len(shape)
      spec[-1] = MODEL_AXIS
      if has_fsdp and shape[-2] % fsdp == 0:
        spec[-2] = FSDP_AXIS
      return NamedSharding(mesh, P(*spec))
    if shape[-1] % tp == 0:
      return NamedSharding(mesh, P(*([None] * (len(shape) - 1)),
                                   MODEL_AXIS))
    return NamedSharding(mesh, P())

  return jax.tree_util.tree_map(rule, tree)


def expert_sharding(mesh: Mesh, tree: Any,
                    min_size_to_shard: int = 2 ** 10) -> Any:
  """fsdp rules + expert weights sharded over the `expert` axis.

  Keys on the `MoEMLP` param-name contract: leaves whose path contains
  an ``expert_``-prefixed name (the stacked [E, ...] expert weights)
  put their leading expert dim on `expert`; everything else (router,
  attention, dense trunk — and every optimizer mirror, which shares
  its param's path) follows the fsdp rule. With no `expert` mesh axis
  this IS `fsdp_sharding`.
  """
  if EXPERT_AXIS not in mesh.axis_names:
    return fsdp_sharding(mesh, tree, min_size_to_shard)
  size = mesh.shape[EXPERT_AXIS]

  def rule(path, leaf):
    shape = getattr(leaf, "shape", ())
    is_expert = any(
        str(getattr(key, "key", getattr(key, "name", ""))).startswith(
            "expert_") for key in path)
    if is_expert and shape and shape[0] % size == 0:
      return NamedSharding(mesh, P(EXPERT_AXIS))
    # A single array is its own pytree: fsdp_sharding returns the
    # one NamedSharding its rule picks for this leaf.
    return fsdp_sharding(mesh, leaf, min_size_to_shard)

  return jax.tree_util.tree_map_with_path(rule, tree)


def replicated_sharding(mesh: Mesh, tree: Any,
                        min_size_to_shard: int = 0) -> Any:
  """Every leaf fully replicated — pure data parallelism.

  The right choice for models whose state fits comfortably per-chip
  (most robot-scale networks), and the baseline the collective-audit
  tests diff fsdp/tp against.
  """
  del min_size_to_shard
  return jax.tree_util.tree_map(lambda _: replicated(mesh), tree)


def state_sharding(mesh: Mesh, state: Any,
                   strategy: str = "fsdp",
                   min_size_to_shard: int = 2 ** 10) -> Any:
  """Shardings for a full TrainState (params + opt mirrors, scalars repl)."""
  rule_fn = {"fsdp": fsdp_sharding,
             "tp": tensor_parallel_sharding,
             "ep": expert_sharding,
             "replicated": replicated_sharding}[strategy]
  return rule_fn(mesh, state, min_size_to_shard=min_size_to_shard)
