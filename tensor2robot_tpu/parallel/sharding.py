"""Parameter/optimizer sharding rules over the named mesh.

The reference's only distribution strategy was TPUEstimator data
parallelism (SURVEY.md §3 parallelism inventory). Here sharding is a
first-class design axis: given a mesh with `fsdp` (zero-style parameter
sharding) and/or `model` (tensor-parallel) axes, these helpers derive
NamedShardings for every leaf of a param/opt pytree, and GSPMD inserts
the all-gathers/reduce-scatters over ICI.

Heuristics (CNN/MLP-scale models; large transformers would add explicit
per-layer rules):
  * fsdp: shard the LARGEST divisible dim of each leaf; leaves smaller
    than `min_size_to_shard` stay replicated (latency > memory win).
  * model: dense kernels additionally split their output dim when
    divisible (megatron-style column parallel) — opt-in.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tensor2robot_tpu.parallel.mesh import (
    DATA_AXIS,
    EXPERT_AXIS,
    FSDP_AXIS,
    MODEL_AXIS,
    STAGE_AXIS,
    replicated,
)


def _path_key_name(key) -> str:
  """The string name of a pytree path entry (DictKey or GetAttrKey)."""
  return str(getattr(key, "key", getattr(key, "name", "")))


def fsdp_sharding(
    mesh: Mesh,
    tree: Any,
    min_size_to_shard: int = 2 ** 10,
) -> Any:
  """NamedSharding pytree: largest divisible dim of each leaf on fsdp.

  Works on arrays or ShapeDtypeStructs. Leaves without a divisible dim
  (or too small) replicate. Optimizer states mirror their param leaf by
  construction (same shapes ⇒ same rule).
  """
  if FSDP_AXIS not in mesh.axis_names:
    repl = NamedSharding(mesh, P())
    return jax.tree_util.tree_map(lambda _: repl, tree)
  size = mesh.shape[FSDP_AXIS]

  def rule(leaf):
    shape = getattr(leaf, "shape", ())
    if not shape or int(np.prod(shape)) < min_size_to_shard:
      return NamedSharding(mesh, P())
    order = sorted(range(len(shape)), key=lambda i: -shape[i])
    for dim in order:
      if shape[dim] % size == 0:
        spec = [None] * len(shape)
        spec[dim] = FSDP_AXIS
        return NamedSharding(mesh, P(*spec))
    return NamedSharding(mesh, P())

  return jax.tree_util.tree_map(rule, tree)


def tensor_parallel_sharding(
    mesh: Mesh,
    tree: Any,
    min_size_to_shard: int = 2 ** 12,
) -> Any:
  """Megatron-ish: 2D kernels split output dim on `model` (+fsdp on in-dim)."""
  if MODEL_AXIS not in mesh.axis_names:
    return fsdp_sharding(mesh, tree, min_size_to_shard)
  tp = mesh.shape[MODEL_AXIS]
  fsdp = mesh.shape.get(FSDP_AXIS, 1)
  has_fsdp = FSDP_AXIS in mesh.axis_names

  def rule(leaf):
    shape = getattr(leaf, "shape", ())
    if not shape or int(np.prod(shape)) < min_size_to_shard:
      return NamedSharding(mesh, P())
    if len(shape) >= 2 and shape[-1] % tp == 0:
      spec = [None] * len(shape)
      spec[-1] = MODEL_AXIS
      if has_fsdp and shape[-2] % fsdp == 0:
        spec[-2] = FSDP_AXIS
      return NamedSharding(mesh, P(*spec))
    if shape[-1] % tp == 0:
      return NamedSharding(mesh, P(*([None] * (len(shape) - 1)),
                                   MODEL_AXIS))
    return NamedSharding(mesh, P())

  return jax.tree_util.tree_map(rule, tree)


def expert_sharding(mesh: Mesh, tree: Any,
                    min_size_to_shard: int = 2 ** 10) -> Any:
  """fsdp rules + expert weights sharded over the `expert` axis.

  Keys on the `MoEMLP` param-name contract: a leaf is an expert weight
  iff its own name is ``moe_expert_``-prefixed — the stacked [E, ...]
  expert weights. That prefix is OWNED by `MoEMLP` (`parallel/moe.py`
  names every stacked expert param with it and nothing else may), so
  the rule is mount-point independent: a trunk may instantiate its
  MoEMLP under any module name and the experts still shard. (The old
  contract additionally required the parent module to be literally
  named ``moe``, which silently REPLICATED experts mounted under any
  other name — round-5 advisor finding.) Matching leaves put their
  leading expert dim on `expert`; an indivisible leading dim raises
  (silently falling back to fsdp would replicate expert weights a pod
  expects sharded). Everything else (router, attention, dense trunk —
  and every optimizer mirror, which shares its param's path) follows
  the fsdp rule. With no `expert` mesh axis this IS `fsdp_sharding`.
  """
  if EXPERT_AXIS not in mesh.axis_names:
    return fsdp_sharding(mesh, tree, min_size_to_shard)
  size = mesh.shape[EXPERT_AXIS]

  def rule(path, leaf):
    shape = getattr(leaf, "shape", ())
    is_expert = bool(
        path and _path_key_name(path[-1]).startswith("moe_expert_"))
    if is_expert:
      if not shape or shape[0] % size != 0:
        raise ValueError(
            f"expert weight {jax.tree_util.keystr(path)} has leading "
            f"dim {shape[:1]} not divisible by expert axis size {size}")
      return NamedSharding(mesh, P(EXPERT_AXIS))
    # A single array is its own pytree: fsdp_sharding returns the
    # one NamedSharding its rule picks for this leaf.
    return fsdp_sharding(mesh, leaf, min_size_to_shard)

  return jax.tree_util.tree_map_with_path(rule, tree)


def pipeline_sharding(mesh: Mesh, tree: Any,
                      min_size_to_shard: int = 2 ** 10) -> Any:
  """fsdp rules + stage-stacked weights sharded over the `stage` axis.

  Keys on the `PipelinedCausalTransformer` param-name contract
  (`layers/pipelined_transformer.STAGE_PARAMS_NAME`): every leaf under
  a path segment named ``stages`` carries a leading [num_stages] dim
  and puts it on `stage` — each device materializes only its own
  stage's weights (and their optimizer mirrors, which share the path).
  An indivisible leading dim raises: silently replicating stage
  weights would defeat the memory win pipelining exists for. With no
  `stage` mesh axis this IS `fsdp_sharding` (the sequential-fallback
  layout `pipeline_apply` runs against).
  """
  if STAGE_AXIS not in mesh.axis_names:
    return fsdp_sharding(mesh, tree, min_size_to_shard)
  size = mesh.shape[STAGE_AXIS]

  def rule(path, leaf):
    shape = getattr(leaf, "shape", ())
    if any(_path_key_name(key) == "stages" for key in path):
      if not shape or shape[0] % size != 0:
        raise ValueError(
            f"stage-stacked weight {jax.tree_util.keystr(path)} has "
            f"leading dim {shape[:1]} not divisible by stage axis "
            f"size {size}")
      return NamedSharding(mesh, P(STAGE_AXIS))
    return fsdp_sharding(mesh, leaf, min_size_to_shard)

  return jax.tree_util.tree_map_with_path(rule, tree)


def data_update_sharding(
    mesh: Mesh,
    tree: Any,
    min_size_to_shard: int = 2 ** 10,
) -> Any:
  """Largest-divisible-dim sharding over the DATA axis for each leaf.

  The weight-update sharding of "Automatic Cross-Replica Sharding of
  Weight Update in Data-Parallel Training" (PAPERS.md): params stay
  replicated for the forward/backward, but the optimizer's gradients,
  moments, and update math are sharded across the data-parallel
  replicas — GSPMD turns the gradient all-reduce into reduce-scatter,
  each replica updates 1/N of the weights, and one all-gather
  republishes them. Same leaf rule as `fsdp_sharding`, on `data`.
  """
  if DATA_AXIS not in mesh.axis_names:
    repl = NamedSharding(mesh, P())
    return jax.tree_util.tree_map(lambda _: repl, tree)
  size = mesh.shape[DATA_AXIS]

  def rule(leaf):
    shape = getattr(leaf, "shape", ())
    if not shape or int(np.prod(shape)) < min_size_to_shard:
      return NamedSharding(mesh, P())
    order = sorted(range(len(shape)), key=lambda i: -shape[i])
    for dim in order:
      if shape[dim] % size == 0:
        spec = [None] * len(shape)
        spec[dim] = DATA_AXIS
        return NamedSharding(mesh, P(*spec))
    return NamedSharding(mesh, P())

  return jax.tree_util.tree_map(rule, tree)


def train_state_update_sharding(mesh: Mesh, state: Any,
                                min_size_to_shard: int = 2 ** 10
                                ) -> Any:
  """Shardings for a TrainState-bearing pytree with the optimizer
  state sharded over the data axis and everything else replicated.

  Keys on the `TrainState.opt_state` field name: every leaf under a
  path segment named ``opt_state`` follows `data_update_sharding`;
  params/batch_stats/step (and a QTOptState's target net) replicate.
  Pass the result as the state's device_put/in_shardings AND
  out_shardings — a replicated out_sharding on opt_state would
  all-gather the moments back every step and erase the win.
  """
  def rule(path, leaf):
    if any(_path_key_name(key) == "opt_state" for key in path):
      return data_update_sharding(mesh, leaf, min_size_to_shard)
    return NamedSharding(mesh, P())

  return jax.tree_util.tree_map_with_path(rule, state)


def replicated_sharding(mesh: Mesh, tree: Any,
                        min_size_to_shard: int = 0) -> Any:
  """Every leaf fully replicated — pure data parallelism.

  The right choice for models whose state fits comfortably per-chip
  (most robot-scale networks), and the baseline the collective-audit
  tests diff fsdp/tp against.
  """
  del min_size_to_shard
  return jax.tree_util.tree_map(lambda _: replicated(mesh), tree)


def state_sharding(mesh: Mesh, state: Any,
                   strategy: str = "fsdp",
                   min_size_to_shard: int = 2 ** 10) -> Any:
  """Shardings for a full TrainState (params + opt mirrors, scalars repl)."""
  rule_fn = {"fsdp": fsdp_sharding,
             "tp": tensor_parallel_sharding,
             "ep": expert_sharding,
             "pipeline": pipeline_sharding,
             "replicated": replicated_sharding}[strategy]
  return rule_fn(mesh, state, min_size_to_shard=min_size_to_shard)
