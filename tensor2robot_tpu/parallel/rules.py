"""The regex-rules sharding seam: one table per model family.

This is the `match_partition_rules` / `make_shard_and_gather_fns`
pattern (SNIPPETS.md; the pjit pod-mesh story of "Scalable Training of
Language Models using JAX pjit and TPUv4", PAPERS.md) adapted to this
repo's CNN/MLP-scale families: sharding decisions live in declarative
RULES TABLES — ordered ``(param-path regex, placement)`` pairs — and
everything that places a pytree (the mesh strategies in
`parallel/sharding.py`, checkpoint restore, the shard_map pod program)
consumes a table instead of growing its own tree-walk.

Because robot-scale leaves vary in rank and size, a rule's value is a
PLACEMENT, not always a bare PartitionSpec: a placement resolves
against the mesh and the leaf's shape (divisibility, min-size) to a
concrete `PartitionSpec`. The grammar:

  * ``Replicate()`` — always `P()`.
  * ``ShardLargest(axis)`` — the fsdp/zero rule: shard the largest
    axis-divisible dim; replicate when the axis is absent, the leaf is
    under ``min_size_to_shard``, or nothing divides.
  * ``ColumnParallel()`` — the megatron rule: 2D+ kernels split their
    output dim on `model` (+`fsdp` on the input dim when divisible);
    degrades to ``ShardLargest(fsdp)`` when the mesh has no `model`
    axis.
  * ``ShardLeading(axis)`` — stacked weights (MoE experts, pipeline
    stages): leading dim on `axis`, RAISING on an indivisible leading
    dim (silent replication would defeat the memory win); degrades to
    ``ShardLargest(fsdp)`` when the axis is absent.
  * a literal ``PartitionSpec`` — used verbatim.

Rules are first-match-wins (``re.search`` over the '/'-joined param
path, the flax convention). `FAMILY_RULES` holds one table per
research family; the t2rcheck rule GIN108 statically checks that every
family table COVERS every param of its family's canonical models and
carries no dead regexes. `docs/SHARDING.md` is the narrative spec.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Callable, Dict, List, Sequence, Tuple, Union

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tensor2robot_tpu.parallel.mesh import (
    EXPERT_AXIS,
    FSDP_AXIS,
    MODEL_AXIS,
    STAGE_AXIS,
)


# ---------------------------------------------------------------------------
# Placement grammar
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Replicate:
  """Every shard holds the full leaf."""

  def spec(self, mesh: Mesh, shape, min_size: int, path: str) -> P:
    del mesh, shape, min_size, path
    return P()


@dataclasses.dataclass(frozen=True)
class ShardLargest:
  """Largest axis-divisible dim on `axis` (the fsdp/zero leaf rule).

  Ties break toward the LOWEST dim index (stable sort), leaves smaller
  than the call's ``min_size_to_shard`` replicate (latency > memory
  win at that size), and a missing mesh axis replicates everything —
  exactly the pre-rules `fsdp_sharding` semantics, regression-pinned
  by tests/test_sharding_rules.py.
  """

  axis: str = FSDP_AXIS

  def spec(self, mesh: Mesh, shape, min_size: int, path: str) -> P:
    del path
    if self.axis not in mesh.axis_names:
      return P()
    size = mesh.shape[self.axis]
    if not shape or int(np.prod(shape)) < min_size:
      return P()
    order = sorted(range(len(shape)), key=lambda i: -shape[i])
    for dim in order:
      if shape[dim] % size == 0:
        entries = [None] * len(shape)
        entries[dim] = self.axis
        return P(*entries)
    return P()


@dataclasses.dataclass(frozen=True)
class ColumnParallel:
  """Megatron-style column parallel for dense kernels.

  2D+ leaves split their output (last) dim on `model_axis` when
  divisible, additionally splitting the input (second-to-last) dim on
  `fsdp_axis` when present and divisible; rank-1 leaves may still
  split on `model_axis`. Without a `model_axis` in the mesh this IS
  ``ShardLargest(fsdp_axis)`` — the pre-rules `tensor_parallel_
  sharding` fallback.
  """

  model_axis: str = MODEL_AXIS
  fsdp_axis: str = FSDP_AXIS

  def spec(self, mesh: Mesh, shape, min_size: int, path: str) -> P:
    if self.model_axis not in mesh.axis_names:
      return ShardLargest(self.fsdp_axis).spec(mesh, shape, min_size,
                                               path)
    tp = mesh.shape[self.model_axis]
    if not shape or int(np.prod(shape)) < min_size:
      return P()
    if len(shape) >= 2 and shape[-1] % tp == 0:
      entries = [None] * len(shape)
      entries[-1] = self.model_axis
      if (self.fsdp_axis in mesh.axis_names
          and shape[-2] % mesh.shape[self.fsdp_axis] == 0):
        entries[-2] = self.fsdp_axis
      return P(*entries)
    if shape[-1] % tp == 0:
      return P(*([None] * (len(shape) - 1)), self.model_axis)
    return P()


@dataclasses.dataclass(frozen=True)
class ShardLeading:
  """Stacked weights: leading dim on `axis` (MoE experts, stages).

  An indivisible leading dim RAISES when the axis is present —
  silently replicating weights a pod expects sharded would defeat the
  memory win sharding exists for. With the axis absent the leaf
  follows ``ShardLargest(fallback_axis)`` (the sequential-fallback
  layout).
  """

  axis: str
  fallback_axis: str = FSDP_AXIS

  def spec(self, mesh: Mesh, shape, min_size: int, path: str) -> P:
    if self.axis not in mesh.axis_names:
      return ShardLargest(self.fallback_axis).spec(mesh, shape,
                                                   min_size, path)
    size = mesh.shape[self.axis]
    if not shape or shape[0] % size != 0:
      raise ValueError(
          f"stacked weight {path!r} has leading dim {shape[:1]} not "
          f"divisible by {self.axis!r} axis size {size}")
    return P(self.axis)


Placement = Union[Replicate, ShardLargest, ColumnParallel,
                  ShardLeading, P]
Rules = Sequence[Tuple[str, Placement]]


# ---------------------------------------------------------------------------
# The matcher
# ---------------------------------------------------------------------------


def _entry_str(entry) -> str:
  """One path entry as a string (DictKey/GetAttrKey/SequenceKey)."""
  for attr in ("key", "name", "idx"):
    value = getattr(entry, attr, None)
    if value is not None:
      return str(value)
  return str(entry)


def tree_path_str(path) -> str:
  """'/'-joined param path, the name rules tables match against."""
  return "/".join(_entry_str(entry) for entry in path)


def _resolve(placement: Placement, mesh: Mesh, shape, min_size: int,
             path: str) -> P:
  if isinstance(placement, P):
    return placement
  return placement.spec(mesh, tuple(shape), min_size, path)


def match_partition_rules(
    rules: Rules,
    tree: Any,
    mesh: Mesh,
    min_size_to_shard: int = 2 ** 10,
) -> Any:
  """PartitionSpec pytree: first rule whose regex `search`es the
  '/'-joined leaf path wins; its placement resolves against the mesh
  and the leaf's shape. Works on arrays or ShapeDtypeStructs (anything
  with `.shape`). Raises on a leaf no rule matches — tables are
  expected to end in a catch-all, and t2rcheck GIN108 checks family
  tables cover their families statically.
  """
  compiled = [(re.compile(pattern), placement)
              for pattern, placement in rules]

  def rule(path, leaf):
    name = tree_path_str(path)
    shape = getattr(leaf, "shape", ())
    for regex, placement in compiled:
      if regex.search(name):
        return _resolve(placement, mesh, shape, min_size_to_shard,
                        name)
    raise ValueError(
        f"no partition rule matched param {name!r} "
        f"(table has {len(compiled)} rules; add a catch-all)")

  return jax.tree_util.tree_map_with_path(rule, tree)


def _is_spec_leaf(x) -> bool:
  return isinstance(x, (P, jax.sharding.Sharding))


def specs_to_shardings(mesh: Mesh, specs: Any) -> Any:
  """PartitionSpec pytree → NamedSharding pytree over `mesh`."""
  return jax.tree_util.tree_map(
      lambda s: s if isinstance(s, jax.sharding.Sharding)
      else NamedSharding(mesh, s),
      specs, is_leaf=_is_spec_leaf)


def make_shard_and_gather_fns(
    mesh: Mesh, specs: Any
) -> Tuple[Any, Any]:
  """(shard_fns, gather_fns) pytrees of per-leaf callables.

  ``shard_fn(host_array) -> device array`` placed per the spec —
  restore-side: a checkpoint read on host lands directly in the target
  layout, whatever mesh it was SAVED under. ``gather_fn(device_array)
  -> np.ndarray`` fully gathered on host — save-side (and the
  relayout pivot: gather under mesh A, shard under mesh B). The
  checkpoint-portability contract `docs/SHARDING.md` documents;
  roundtrip-pinned by tests/test_checkpoint_resharding.py.
  """
  shardings = specs_to_shardings(mesh, specs)

  def make_shard_fn(sharding):
    def shard_fn(x):
      return jax.device_put(jax.numpy.asarray(x), sharding)
    return shard_fn

  def make_gather_fn(sharding):
    del sharding

    def gather_fn(x):
      return np.asarray(jax.device_get(x))
    return gather_fn

  shard_fns = jax.tree_util.tree_map(make_shard_fn, shardings,
                                     is_leaf=_is_spec_leaf)
  gather_fns = jax.tree_util.tree_map(make_gather_fn, shardings,
                                      is_leaf=_is_spec_leaf)
  return shard_fns, gather_fns


# ---------------------------------------------------------------------------
# Per-family rules tables
# ---------------------------------------------------------------------------

# Shared rule fragments: stacked-expert weights (the `moe_expert_`
# prefix is OWNED by `parallel/moe.MoEMLP` — this regex is the ONE
# place the contract is spelled, replacing the old hard-coded prefix
# special-case in `expert_sharding`) and stage-stacked pipeline
# weights (`layers/pipelined_transformer.STAGE_PARAMS_NAME`).
EXPERT_STACK_RE = r"(^|/)moe_expert_[^/]*$"
STAGE_STACK_RE = r"(^|/)stages(/|$)"

# One table per research family, matched against the '/'-joined param
# paths of that family's canonical models (`family_param_templates`).
# Ordered most-specific-first; every table ends in a ShardLargest
# catch-all so optimizer mirrors and future params stay covered.
# t2rcheck GIN108 pins coverage + no dead regexes.
FAMILY_RULES: Dict[str, Rules] = {
    "qtopt": (
        (r"(^|/)(torso|head)_conv_[0-9]+/kernel$",
         ShardLargest(FSDP_AXIS)),
        (r"(^|/)(torso|head)_bn_[0-9]+/(bias|scale)$", Replicate()),
        (r"(^|/)action_embed_[0-9]+/kernel$", ColumnParallel()),
        (r"(^|/)q_head/dense_[0-9]+/kernel$", ColumnParallel()),
        (r"/bias$", Replicate()),
        (r".*", ShardLargest(FSDP_AXIS)),
    ),
    "pose_env": (
        (r"(^|/)tower/conv_[0-9]+/kernel$", ShardLargest(FSDP_AXIS)),
        (r"(^|/)tower/bn_[0-9]+/(bias|scale)$", Replicate()),
        (r"(^|/)ssoftmax/log_temperature$", Replicate()),
        (r"(^|/)head/dense_[0-9]+/kernel$", ColumnParallel()),
        (r"(^|/)proj/kernel$", ColumnParallel()),
        (r"/bias$", Replicate()),
        (r".*", ShardLargest(FSDP_AXIS)),
    ),
    "grasp2vec": (
        (r"(^|/)trunk/conv_init/kernel$", ShardLargest(FSDP_AXIS)),
        (r"(^|/)stage[0-9]+_block[0-9]+/(conv[0-9]+|proj)/kernel$",
         ShardLargest(FSDP_AXIS)),
        (r"(^|/)(bn_init|bn[0-9]+|bn_proj)/(bias|scale)$",
         Replicate()),
        (r"(^|/)embed/kernel$", ColumnParallel()),
        (r"/bias$", Replicate()),
        (r".*", ShardLargest(FSDP_AXIS)),
    ),
    "vrgripper": (
        (EXPERT_STACK_RE, ShardLeading(EXPERT_AXIS)),
        (STAGE_STACK_RE, ShardLeading(STAGE_AXIS)),
        (r"(^|/)moe/router$", Replicate()),
        (r"(^|/)attn/(qkv|proj)/kernel$", ColumnParallel()),
        (r"(^|/)mlp_(in|out)/kernel$", ColumnParallel()),
        (r"(^|/)ln_[a-z0-9_]+/(bias|scale)$", Replicate()),
        (r"(^|/)positions$", Replicate()),
        (r"(^|/)tower/conv_[0-9]+/kernel$", ShardLargest(FSDP_AXIS)),
        (r"(^|/)ssoftmax/log_temperature$", Replicate()),
        (r"(^|/)(proj|joint_proj|embed|action_head)/kernel$",
         ColumnParallel()),
        (r"(^|/)trunk/dense_[0-9]+/kernel$", ColumnParallel()),
        (r"/bias$", Replicate()),
        (r".*", ShardLargest(FSDP_AXIS)),
    ),
    "meta_learning": (
        (r"(^|/)inner_lr_log$", Replicate()),
        (r"(^|/)tower/conv_[0-9]+/kernel$", ShardLargest(FSDP_AXIS)),
        (r"(^|/)tower/bn_[0-9]+/(bias|scale)$", Replicate()),
        (r"(^|/)ssoftmax/log_temperature$", Replicate()),
        (r"(^|/)head/dense_[0-9]+/kernel$", ColumnParallel()),
        (r"(^|/)proj/kernel$", ColumnParallel()),
        (r"/bias$", Replicate()),
        (r".*", ShardLargest(FSDP_AXIS)),
    ),
}


def family_rules(family: str) -> Rules:
  try:
    return FAMILY_RULES[family]
  except KeyError:
    raise ValueError(
        f"unknown model family {family!r}; known: "
        f"{', '.join(sorted(FAMILY_RULES))}") from None


def family_sharding(mesh: Mesh, tree: Any, family: str,
                    min_size_to_shard: int = 2 ** 10) -> Any:
  """NamedSharding pytree for `tree` under the family's rules table."""
  return specs_to_shardings(mesh, match_partition_rules(
      family_rules(family), tree, mesh,
      min_size_to_shard=min_size_to_shard))


_TEMPLATE_CACHE: Dict[str, List[Any]] = {}


def family_param_templates(family: str) -> List[Any]:
  """Abstract (eval_shape'd) param trees of the family's canonical
  models — what GIN108 checks the rules table against. Tiny configs:
  nothing materializes, nothing trains; variants that introduce
  distinct param groups (MoE experts, pipeline stages) get their own
  template so their regexes are exercised. Memoized: the templates
  are static shape trees, and the GIN108 lint path may ask for them
  repeatedly."""
  cached = _TEMPLATE_CACHE.get(family)
  if cached is not None:
    return cached
  templates = _build_family_param_templates(family)
  _TEMPLATE_CACHE[family] = templates
  return templates


def _build_family_param_templates(family: str) -> List[Any]:

  def abstract_params(model, batch_size: int = 2):
    state = jax.eval_shape(
        lambda rng: model.create_train_state(rng,
                                             batch_size=batch_size),
        jax.random.PRNGKey(0))
    return state.params

  if family == "qtopt":
    from tensor2robot_tpu.research.qtopt.t2r_models import (
        GraspingQModel,
    )
    return [abstract_params(GraspingQModel(
        image_size=16, torso_filters=(8,), head_filters=(8,),
        dense_sizes=(16,), action_dim=2))]
  if family == "pose_env":
    from tensor2robot_tpu.research.pose_env.pose_env_models import (
        PoseEnvRegressionModel,
    )
    return [abstract_params(PoseEnvRegressionModel())]
  if family == "grasp2vec":
    from tensor2robot_tpu.research.grasp2vec.grasp2vec_model import (
        Grasp2VecModel,
    )
    return [abstract_params(Grasp2VecModel())]
  if family == "vrgripper":
    from tensor2robot_tpu.research.vrgripper.vrgripper_models import (
        VRGripperRegressionModel,
    )
    from tensor2robot_tpu.research.vrgripper.\
        vrgripper_transformer_models import VRGripperTransformerModel
    return [
        abstract_params(VRGripperRegressionModel()),
        abstract_params(VRGripperTransformerModel(
            moe_experts=4, moe_every=2)),
        abstract_params(VRGripperTransformerModel(
            pipeline_stages=2, depth=2)),
    ]
  if family == "meta_learning":
    from tensor2robot_tpu.meta_learning.maml_model import MAMLModel
    from tensor2robot_tpu.research.pose_env.pose_env_models import (
        PoseEnvRegressionModel,
    )
    return [abstract_params(
        MAMLModel(base_model=PoseEnvRegressionModel(),
                  learn_inner_lr=True))]
  raise ValueError(f"unknown model family {family!r}")


def check_rules_coverage(
    rules: Rules, trees: Sequence[Any]
) -> Tuple[List[str], List[str]]:
  """(unmatched param paths, dead rule regexes) for a table against a
  family's param trees — the static core of t2rcheck GIN108. The
  table's FINAL rule is its declared default (catch-all) and is exempt
  from dead-regex detection: a family whose named rules already cover
  every param keeps its safety net without a finding."""
  compiled = [(pattern, re.compile(pattern)) for pattern, _ in rules]
  used = [False] * len(compiled)
  unmatched: List[str] = []
  for tree in trees:
    for path, _ in jax.tree_util.tree_leaves_with_path(tree):
      name = tree_path_str(path)
      for index, (_, regex) in enumerate(compiled):
        if regex.search(name):
          used[index] = True
          break
      else:
        unmatched.append(name)
  dead = [pattern for (pattern, _), hit in
          zip(compiled[:-1], used[:-1]) if not hit]
  return unmatched, dead
