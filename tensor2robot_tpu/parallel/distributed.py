"""Multi-host (multi-process) JAX runtime initialization.

Reference parity: the reference scaled across hosts with TPUEstimator's
cluster config (SURVEY.md §3 parallelism table "multi-slice via jax
distributed init" [U]); the JAX-native equivalent is
`jax.distributed.initialize`, after which `jax.devices()` spans every
host's chips and one `Mesh` + GSPMD program covers the whole slice —
collectives ride ICI within a slice and DCN across slices.

Call `maybe_initialize_distributed()` ONCE at binary startup, before
any jax device use. On TPU pods the runtime discovers coordinator /
process_id / process_count from the TPU metadata, so an argless
initialize is correct; off-pod multi-process runs (CPU/GPU fleets,
tests) pass the coordination triple explicitly. Single-process runs
no-op, so the same binary works from a laptop to a v5e-64.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

log = logging.getLogger(__name__)

_INITIALIZED = False


def ephemeral_coordinator_address(host: str = "127.0.0.1") -> str:
  """Picks a collision-safe coordinator address for same-host launches.

  The launch contract for same-host multi-process runs (fleets, the
  two-process distributed test, bench rehearsals): the COORDINATOR —
  the one process that spawns the others — calls this ONCE before
  spawning and hands the result to every child via
  `JAX_COORDINATOR_ADDRESS` (or the explicit flag). The OS assigns a
  port from the ephemeral range (`bind(0)`), so two concurrent fleets
  (or bench + tests) on one machine never race on a fixed port the
  way a hard-coded constant guarantees they eventually would.

  The port is released before jax binds it, so a theoretical window
  exists; ephemeral-range assignment makes a collision in that window
  vanishingly unlikely (the kernel cycles the range rather than
  re-issuing the port it just handed out), which is the practical
  difference vs. a fixed port's CERTAIN collision under concurrency.
  """
  import socket

  with socket.socket() as s:
    s.bind((host, 0))
    return f"{host}:{s.getsockname()[1]}"


def maybe_initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    force: bool = False,
) -> bool:
  """Initializes jax.distributed when a multi-process launch is detected.

  Triggers when any of:
    * explicit args (coordinator_address or force=True),
    * `JAX_COORDINATOR_ADDRESS` env (+`JAX_NUM_PROCESSES`/
      `JAX_PROCESS_ID`) — the framework's own launch contract,
    * a TPU pod environment (`TPU_WORKER_HOSTNAMES` with >1 worker),
      where the argless auto-discovery path is used.

  Idempotent; returns True when jax.distributed is (now) initialized.
  """
  global _INITIALIZED
  if _INITIALIZED:
    return True

  coordinator_address = coordinator_address or os.environ.get(
      "JAX_COORDINATOR_ADDRESS")
  if num_processes is None and "JAX_NUM_PROCESSES" in os.environ:
    num_processes = int(os.environ["JAX_NUM_PROCESSES"])
  if process_id is None and "JAX_PROCESS_ID" in os.environ:
    process_id = int(os.environ["JAX_PROCESS_ID"])

  pod_workers = [w for w in os.environ.get(
      "TPU_WORKER_HOSTNAMES", "").split(",") if w]
  on_pod = len(pod_workers) > 1

  if not (coordinator_address or on_pod or force):
    return False

  import jax

  _maybe_enable_cpu_collectives()
  kwargs = {}
  if coordinator_address:
    kwargs["coordinator_address"] = coordinator_address
  if num_processes is not None:
    kwargs["num_processes"] = num_processes
  if process_id is not None:
    kwargs["process_id"] = process_id
  jax.distributed.initialize(**kwargs)
  _INITIALIZED = True
  log.info(
      "jax.distributed initialized: process %d/%d, %d local / %d global "
      "devices.", jax.process_index(), jax.process_count(),
      jax.local_device_count(), jax.device_count())
  return True


def _maybe_enable_cpu_collectives() -> None:
  """Selects the gloo CPU collectives backend for multi-process CPU.

  XLA:CPU's default collectives cannot span processes at all
  ("Multiprocess computations aren't implemented on the CPU backend")
  — every off-accelerator multi-process run (CI, the two-process
  distributed test, a laptop fleet rehearsal) needs jax's gloo-based
  cross-process CPU collectives, selected via
  `jax_cpu_collectives_implementation` BEFORE
  `jax.distributed.initialize`. The option only governs the CPU
  backend's cross-process collectives, so it is selected whenever the
  CPU backend could end up primary: platforms unset (auto-detect on a
  CPU-only host) or explicitly naming cpu. Only an explicit
  accelerator-only selection (e.g. `JAX_PLATFORMS=tpu`) skips it; on
  jax builds without the option this degrades to the old behavior.
  """
  import jax

  platforms = (os.environ.get("JAX_PLATFORMS", "")
               or str(getattr(jax.config, "jax_platforms", None) or ""))
  if platforms and "cpu" not in platforms.lower():
    return  # accelerator-only selection: CPU backend never primary
  try:
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
  except Exception:  # older/newer jax: option renamed or absent
    log.warning("could not select gloo CPU collectives; multi-process "
                "CPU runs may fail", exc_info=True)
