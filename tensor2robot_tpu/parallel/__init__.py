"""Mesh construction and sharding rules (TPU-native distribution layer)."""

from tensor2robot_tpu.parallel.mesh import (
    DATA_AXIS,
    EXPERT_AXIS,
    FSDP_AXIS,
    MODEL_AXIS,
    SEQ_AXIS,
    STAGE_AXIS,
    batch_sharding,
    create_mesh,
    local_batch_size,
    replicated,
)
from tensor2robot_tpu.parallel.pipeline import (
    init_stage_params,
    pipeline_apply,
    stage_sharding,
)
from tensor2robot_tpu.parallel.moe import (
    MoEMLP,
    collect_aux_losses,
    expert_capacity,
    moe_mlp,
    top_k_routing,
)
from tensor2robot_tpu.parallel.distributed import (
    maybe_initialize_distributed,
)
from tensor2robot_tpu.parallel.ring_attention import (
    attention_reference,
    ring_attention,
    sequence_sharding,
)
from tensor2robot_tpu.parallel.rules import (
    FAMILY_RULES,
    ColumnParallel,
    Replicate,
    ShardLargest,
    ShardLeading,
    check_rules_coverage,
    family_param_templates,
    family_rules,
    family_sharding,
    make_shard_and_gather_fns,
    match_partition_rules,
    specs_to_shardings,
    tree_path_str,
)
from tensor2robot_tpu.parallel.sharding import (
    data_update_sharding,
    expert_sharding,
    fsdp_sharding,
    pipeline_sharding,
    state_sharding,
    tensor_parallel_sharding,
    train_state_update_sharding,
)
