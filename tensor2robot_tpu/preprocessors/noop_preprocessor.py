"""Identity preprocessor (reference: preprocessors/noop_preprocessor.py)."""

from __future__ import annotations

from typing import Optional, Tuple

import jax

from tensor2robot_tpu import config as gin
from tensor2robot_tpu.data.abstract_input_generator import Mode
from tensor2robot_tpu.preprocessors.abstract_preprocessor import (
    AbstractPreprocessor,
)
from tensor2robot_tpu.specs import TensorSpecStruct


@gin.configurable
class NoOpPreprocessor(AbstractPreprocessor):
  """Wire specs == model specs; preprocess is identity."""

  def get_in_feature_specification(self, mode: Mode) -> TensorSpecStruct:
    return self.model_feature_specification(mode)

  def get_in_label_specification(self, mode: Mode):
    return self.model_label_specification(mode)

  def get_out_feature_specification(self, mode: Mode) -> TensorSpecStruct:
    return self.model_feature_specification(mode)

  def get_out_label_specification(self, mode: Mode):
    return self.model_label_specification(mode)

  def preprocess(
      self,
      features: TensorSpecStruct,
      labels: Optional[TensorSpecStruct],
      mode: Mode,
      rng: Optional[jax.Array] = None,
  ) -> Tuple[TensorSpecStruct, Optional[TensorSpecStruct]]:
    return features, labels
