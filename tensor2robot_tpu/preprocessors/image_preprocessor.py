"""Spec-transformation image preprocessor.

Reference parity: tensor2robot `preprocessors/
spec_transformation_preprocessor.py` + the image crop/distort train
pipeline (SURVEY.md §3). Declares uint8 wire images, emits cropped /
resized / distorted float (or bfloat16) model images on device.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tensor2robot_tpu import config as gin
from tensor2robot_tpu import specs
from tensor2robot_tpu.data.abstract_input_generator import Mode
from tensor2robot_tpu.preprocessors import image_transformations as imt
from tensor2robot_tpu.preprocessors.abstract_preprocessor import (
    AbstractPreprocessor,
)
from tensor2robot_tpu.specs import ExtendedTensorSpec, TensorSpecStruct


@gin.configurable
class ImagePreprocessor(AbstractPreprocessor):
  """Crop/resize/distort the declared image keys, cast the rest.

  The model's out-spec image shapes define the target size. The wire
  (in-spec) image is `src_height × src_width` uint8; train mode random-
  crops to the target and applies photometric distortions, eval mode
  center-crops. Non-image float features pass through with a dtype cast
  to the model dtype.
  """

  def __init__(self,
               model_feature_specification_fn=None,
               model_label_specification_fn=None,
               image_keys: Optional[Sequence[str]] = None,
               src_height: int = 512,
               src_width: int = 640,
               distort: bool = True,
               max_brightness_delta: float = 0.125,
               contrast_range: Tuple[float, float] = (0.5, 1.5),
               saturation_range: Tuple[float, float] = (0.5, 1.5),
               max_hue_delta: float = 0.2,
               noise_stddev: float = 0.0):
    super().__init__(model_feature_specification_fn,
                     model_label_specification_fn)
    self._image_keys = list(image_keys) if image_keys else None
    self._src_height = src_height
    self._src_width = src_width
    self._distort = distort
    self._distort_kwargs = dict(
        max_brightness_delta=max_brightness_delta,
        contrast_range=contrast_range,
        saturation_range=saturation_range,
        max_hue_delta=max_hue_delta,
        noise_stddev=noise_stddev,
    )

  def _image_key_set(self, flat_specs) -> set:
    if self._image_keys is not None:
      return set(self._image_keys)
    return {k for k, s in flat_specs.items()
            if s.is_image or (len(s.shape) == 3 and s.shape[-1] in (1, 3))}

  def get_in_feature_specification(self, mode: Mode) -> TensorSpecStruct:
    flat = self.model_feature_specification(mode).to_flat_dict()
    image_keys = self._image_key_set(flat)
    out = {}
    for key, spec in flat.items():
      if key in image_keys:
        channels = spec.shape[-1]
        out[key] = spec.replace(
            shape=(self._src_height, self._src_width, channels),
            dtype=np.uint8)
      else:
        out[key] = spec
    return TensorSpecStruct.from_flat_dict(out)

  def get_in_label_specification(self, mode: Mode):
    return self.model_label_specification(mode)

  def get_out_feature_specification(self, mode: Mode) -> TensorSpecStruct:
    return self.model_feature_specification(mode)

  def get_out_label_specification(self, mode: Mode):
    return self.model_label_specification(mode)

  def preprocess(self, features, labels, mode: Mode,
                 rng: Optional[jax.Array] = None):
    out_specs = self.get_out_feature_specification(mode).to_flat_dict()
    image_keys = self._image_key_set(out_specs)
    flat = features.to_flat_dict()
    if rng is None:
      rng = jax.random.PRNGKey(0)
    out = {}
    for key, value in flat.items():
      spec = out_specs.get(key)
      if spec is None or key not in image_keys:
        out[key] = value if spec is None else value.astype(spec.dtype)
        continue
      th, tw = spec.shape[-3], spec.shape[-2]
      images = imt.to_float(value)
      rng, crop_key, distort_key = jax.random.split(rng, 3)
      if mode == Mode.TRAIN:
        images = imt.random_crop(crop_key, images, th, tw) \
            if (images.shape[-3], images.shape[-2]) != (th, tw) \
            else images
        if self._distort:
          distort_kwargs = dict(self._distort_kwargs)
          if images.shape[-1] != 3:
            # Hue rotation / saturation blending are RGB-only; grayscale
            # or depth channels keep brightness/contrast/noise.
            distort_kwargs["max_hue_delta"] = 0.0
            distort_kwargs["saturation_range"] = None
          images = imt.apply_photometric_image_distortions(
              distort_key, images, **distort_kwargs)
      else:
        if (images.shape[-3], images.shape[-2]) != (th, tw):
          images = imt.center_crop(images, th, tw)
      out[key] = images.astype(spec.dtype)
    return TensorSpecStruct.from_flat_dict(out), labels


@gin.configurable
class TPUCompatPreprocessorWrapper(AbstractPreprocessor):
  """Keeps uint8 on the wire, casts to the model dtype on device.

  Reference parity: the TPU-compat wrapper noted in SURVEY.md §3
  ("casting uint8→bf16/f32 on host [U-med]") — except TPU-native we cast
  AFTER the H2D transfer, so images cross PCIe/ICI as uint8 (4× fewer
  bytes than f32) and the cast fuses into the first conv.
  """

  def __init__(self, base: AbstractPreprocessor,
               model_dtype=jnp.float32, scale: bool = True):
    super().__init__()
    self._base = base
    self._model_dtype = model_dtype
    self._scale = scale

  def get_in_feature_specification(self, mode: Mode) -> TensorSpecStruct:
    return self._base.get_in_feature_specification(mode)

  def get_in_label_specification(self, mode: Mode):
    return self._base.get_in_label_specification(mode)

  def _cast_spec(self, spec_struct):
    if spec_struct is None:
      return None
    return specs.replace_dtype(spec_struct, np.uint8, self._model_dtype)

  def get_out_feature_specification(self, mode: Mode) -> TensorSpecStruct:
    return self._cast_spec(self._base.get_out_feature_specification(mode))

  def get_out_label_specification(self, mode: Mode):
    return self._cast_spec(self._base.get_out_label_specification(mode))

  def _cast(self, struct):
    if struct is None:
      return None
    flat = struct.to_flat_dict()
    out = {}
    for key, value in flat.items():
      if value.dtype == jnp.uint8:
        value = value.astype(self._model_dtype)
        if self._scale:
          value = value / jnp.asarray(255.0, self._model_dtype)
      out[key] = value
    return TensorSpecStruct.from_flat_dict(out)

  def preprocess(self, features, labels, mode: Mode,
                 rng: Optional[jax.Array] = None):
    features, labels = self._base.preprocess(features, labels, mode, rng)
    return self._cast(features), self._cast(labels)
