"""Pure-JAX batched image transformations for on-device preprocessing.

Reference parity: tensor2robot `preprocessors/image_transformations.py`
and `distortion.py` (`ApplyPhotometricImageDistortions`, random crop /
resize; SURVEY.md §3). The reference ran these host-side in tf.data;
here they are pure jax functions traced into the jitted step so XLA
fuses them with the model's first conv (HBM-bandwidth win: images cross
H2D as uint8 and are cast/normalized on device).

All functions take NHWC batches and a jax PRNG key, and are
shape-polymorphic at trace time only (static output shapes, per XLA).
Hue/saturation use the classic YIQ-rotation / grayscale-blend forms —
closed-form, MXU/VPU-friendly, no HSV branching.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def to_float(images: jax.Array, dtype=jnp.float32) -> jax.Array:
  """uint8 [0,255] → float [0,1]; passthrough for float inputs."""
  if images.dtype == jnp.uint8:
    return images.astype(dtype) / jnp.asarray(255.0, dtype)
  return images.astype(dtype)


def center_crop(images: jax.Array, height: int, width: int) -> jax.Array:
  h, w = images.shape[-3], images.shape[-2]
  top = (h - height) // 2
  left = (w - width) // 2
  return jax.lax.slice_in_dim(
      jax.lax.slice_in_dim(images, top, top + height, axis=-3),
      left, left + width, axis=-2)


def random_crop(key: jax.Array, images: jax.Array, height: int,
                width: int) -> jax.Array:
  """Per-image random crops via vmapped dynamic_slice (static out shape)."""
  batch = images.shape[0]
  h, w = images.shape[-3], images.shape[-2]
  key_t, key_l = jax.random.split(key)
  tops = jax.random.randint(key_t, (batch,), 0, h - height + 1)
  lefts = jax.random.randint(key_l, (batch,), 0, w - width + 1)

  def crop_one(image, top, left):
    start = (top, left) + (0,) * (image.ndim - 2)
    sizes = (height, width) + image.shape[2:]
    return jax.lax.dynamic_slice(image, start, sizes)

  return jax.vmap(crop_one)(images, tops, lefts)


def resize(images: jax.Array, height: int, width: int,
           method: str = "bilinear") -> jax.Array:
  shape = images.shape[:-3] + (height, width, images.shape[-1])
  return jax.image.resize(images, shape, method=method)


def random_flip_left_right(key: jax.Array, images: jax.Array) -> jax.Array:
  batch = images.shape[0]
  flips = jax.random.bernoulli(key, 0.5, (batch,))
  flipped = jnp.flip(images, axis=-2)
  return jnp.where(flips[:, None, None, None], flipped, images)


# ---------------------------------------------------------------------------
# Photometric distortions (train-time only, float images in [0, 1])
# ---------------------------------------------------------------------------

# Plain numpy on purpose: a module-level `jnp.array` is a jax
# COMPUTATION at import time, which initializes the XLA backend in any
# process whose import closure reaches this file — and a
# `jax.distributed.initialize` after that point raises (learner-group
# ranks under the real `run_t2r_trainer` binary hit exactly this:
# multiprocessing's spawn re-imports `__main__` before the child's
# `learner_main` runs). jnp consumes these np constants identically.
_RGB_TO_YIQ = np.array([[0.299, 0.587, 0.114],
                        [0.596, -0.274, -0.322],
                        [0.211, -0.523, 0.312]], dtype=np.float32)
_YIQ_TO_RGB = np.array([[1.0, 0.956, 0.621],
                        [1.0, -0.272, -0.647],
                        [1.0, -1.106, 1.703]], dtype=np.float32)


def adjust_brightness(images: jax.Array, delta: jax.Array) -> jax.Array:
  return images + jnp.reshape(delta, (-1,) + (1,) * (images.ndim - 1))


def adjust_contrast(images: jax.Array, factor: jax.Array) -> jax.Array:
  mean = images.mean(axis=(-3, -2), keepdims=True)
  factor = jnp.reshape(factor, (-1,) + (1,) * (images.ndim - 1))
  return (images - mean) * factor + mean


def adjust_saturation(images: jax.Array, factor: jax.Array) -> jax.Array:
  gray = (images * jnp.array([0.299, 0.587, 0.114])).sum(
      axis=-1, keepdims=True)
  factor = jnp.reshape(factor, (-1,) + (1,) * (images.ndim - 1))
  return gray + (images - gray) * factor


def adjust_hue(images: jax.Array, radians: jax.Array) -> jax.Array:
  """Hue rotation in YIQ space (closed form, no HSV branches)."""
  radians = jnp.reshape(radians, (-1,) + (1,) * (images.ndim - 1))
  yiq = images @ _RGB_TO_YIQ.T
  y = yiq[..., :1]
  i = yiq[..., 1:2]
  q = yiq[..., 2:3]
  cos = jnp.cos(radians)[..., 0:1]
  sin = jnp.sin(radians)[..., 0:1]
  i2 = i * cos - q * sin
  q2 = i * sin + q * cos
  return jnp.concatenate([y, i2, q2], axis=-1) @ _YIQ_TO_RGB.T


def add_gaussian_noise(key: jax.Array, images: jax.Array,
                       stddev: float) -> jax.Array:
  return images + stddev * jax.random.normal(
      key, images.shape, images.dtype)


def apply_photometric_image_distortions(
    key: jax.Array,
    images: jax.Array,
    max_brightness_delta: float = 0.125,
    contrast_range: Tuple[float, float] = (0.5, 1.5),
    saturation_range: Tuple[float, float] = (0.5, 1.5),
    max_hue_delta: float = 0.2,
    noise_stddev: float = 0.0,
    clip: bool = True,
) -> jax.Array:
  """Random per-image brightness/contrast/saturation/hue (+ noise).

  Reference parity: `ApplyPhotometricImageDistortions` (preprocessors/
  image_transformations.py [U]). Order fixed (brightness → saturation →
  hue → contrast) rather than shuffled: a traced program must have static
  op order; the random *magnitudes* still differ per image and per step.
  """
  batch = images.shape[0]
  keys = jax.random.split(key, 5)
  out = images.astype(jnp.float32)
  if max_brightness_delta > 0:
    delta = jax.random.uniform(
        keys[0], (batch,), minval=-max_brightness_delta,
        maxval=max_brightness_delta)
    out = adjust_brightness(out, delta)
  if saturation_range is not None:
    factor = jax.random.uniform(
        keys[1], (batch,), minval=saturation_range[0],
        maxval=saturation_range[1])
    out = adjust_saturation(out, factor)
  if max_hue_delta > 0:
    radians = jax.random.uniform(
        keys[2], (batch,), minval=-max_hue_delta, maxval=max_hue_delta)
    out = adjust_hue(out, radians)
  if contrast_range is not None:
    factor = jax.random.uniform(
        keys[3], (batch,), minval=contrast_range[0],
        maxval=contrast_range[1])
    out = adjust_contrast(out, factor)
  if noise_stddev > 0:
    out = add_gaussian_noise(keys[4], out, noise_stddev)
  if clip:
    out = jnp.clip(out, 0.0, 1.0)
  return out.astype(images.dtype)


def random_crop_image_and_resize(
    key: jax.Array,
    images: jax.Array,
    crop_height: int,
    crop_width: int,
    out_height: Optional[int] = None,
    out_width: Optional[int] = None,
) -> jax.Array:
  """Random crop then (optional) resize — the standard train-time combo."""
  cropped = random_crop(key, images, crop_height, crop_width)
  if out_height is not None and out_width is not None and (
      (out_height, out_width) != (crop_height, crop_width)):
    cropped = resize(cropped, out_height, out_width)
  return cropped


# Reference-compatible alias.
ApplyPhotometricImageDistortions = apply_photometric_image_distortions
