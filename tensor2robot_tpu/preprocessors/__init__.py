"""Device-side preprocessors (reference: tensor2robot preprocessors/)."""

from tensor2robot_tpu.preprocessors.abstract_preprocessor import (
    AbstractPreprocessor,
)
from tensor2robot_tpu.preprocessors.noop_preprocessor import NoOpPreprocessor
from tensor2robot_tpu.preprocessors.image_preprocessor import (
    ImagePreprocessor,
    TPUCompatPreprocessorWrapper,
)
from tensor2robot_tpu.preprocessors import image_transformations
