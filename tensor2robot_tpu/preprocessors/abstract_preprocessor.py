"""Preprocessor protocol: declared in/out spec transforms.

Reference parity: tensor2robot `preprocessors/abstract_preprocessor.py`
(`AbstractPreprocessor.{preprocess, get_in_feature_specification,
get_out_feature_specification, ...}`; SURVEY.md §3).

TPU-native redesign: `preprocess` is a PURE jax function `(features,
labels, mode, rng) -> (features, labels)` that is traced into the jitted
train/eval step — image crops, distortions, and dtype casts run on the
TPU, fused by XLA into the step program (the reference ran these in the
host tf.data pipeline; device-side preprocessing keeps the host free to
feed the infeed and the uint8→bf16 cast after transfer halves H2D
bytes). Anything not jax-traceable (jpeg decode) belongs to the data
layer, host-side, exactly as in the reference.
"""

from __future__ import annotations

import abc
from typing import Any, Optional, Tuple

import jax

from tensor2robot_tpu import specs
from tensor2robot_tpu.data.abstract_input_generator import Mode
from tensor2robot_tpu.specs import TensorSpecStruct


class AbstractPreprocessor(abc.ABC):
  """Transforms wire-side batches into model-side batches, on device.

  Spec contract (same as the reference):
    * `get_in_*_specification(mode)`  — what the data layer must deliver.
    * `get_out_*_specification(mode)` — what the model receives.
  """

  def __init__(self,
               model_feature_specification_fn=None,
               model_label_specification_fn=None):
    """Args are mode→spec callables, usually the model's spec getters."""
    self._model_feature_specification_fn = model_feature_specification_fn
    self._model_label_specification_fn = model_label_specification_fn

  def model_feature_specification(self, mode: Mode) -> TensorSpecStruct:
    if self._model_feature_specification_fn is None:
      raise ValueError("No model feature specification bound.")
    return specs.flatten_spec_structure(
        self._model_feature_specification_fn(mode))

  def model_label_specification(self, mode: Mode) -> Optional[TensorSpecStruct]:
    if self._model_label_specification_fn is None:
      return None
    spec = self._model_label_specification_fn(mode)
    return None if spec is None else specs.flatten_spec_structure(spec)

  @abc.abstractmethod
  def get_in_feature_specification(self, mode: Mode) -> TensorSpecStruct:
    ...

  @abc.abstractmethod
  def get_in_label_specification(self, mode: Mode) -> Optional[TensorSpecStruct]:
    ...

  @abc.abstractmethod
  def get_out_feature_specification(self, mode: Mode) -> TensorSpecStruct:
    ...

  @abc.abstractmethod
  def get_out_label_specification(self, mode: Mode) -> Optional[TensorSpecStruct]:
    ...

  @abc.abstractmethod
  def preprocess(
      self,
      features: TensorSpecStruct,
      labels: Optional[TensorSpecStruct],
      mode: Mode,
      rng: Optional[jax.Array] = None,
  ) -> Tuple[TensorSpecStruct, Optional[TensorSpecStruct]]:
    """Pure, jit-traceable transform from in-specs to out-specs."""
    ...
