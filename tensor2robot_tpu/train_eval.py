"""train_eval_model: the training/eval/export orchestrator.

Reference parity: tensor2robot `train_eval.py` —
`train_eval_model(model, input_generator_train, input_generator_eval,
max_train_steps, eval_steps, create_exporters_fn, use_tpu, ...)` building
an (TPU)Estimator and running train / eval / continuous-eval / export
(SURVEY.md §4.1).

TPU-native redesign: no Estimator. The model's pure `train_step` is
jitted ONCE over a named device mesh with the batch sharded along the
data axis and state replicated (or sharded per the model's rules);
GSPMD inserts the ICI all-reduce. The host loop is thin: pull a
prefetched sharded batch, call the compiled step, occasionally log /
checkpoint — state stays on device the whole time (the reference paid a
host round-trip per `iterations_per_loop`). Checkpointing is async
orbax; resume is automatic from the latest checkpoint in `model_dir`.
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Any, Callable, Dict, Iterable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from tensor2robot_tpu import config as gin
from tensor2robot_tpu.data.abstract_input_generator import (
    AbstractInputGenerator,
    Mode,
)
from tensor2robot_tpu.data import prefetch as prefetch_lib
from tensor2robot_tpu.hooks import Hook, HookList
from tensor2robot_tpu.models.model_interface import ModelInterface
from tensor2robot_tpu.parallel import mesh as mesh_lib
from tensor2robot_tpu.parallel import state_sharding
from tensor2robot_tpu.utils import checkpoints as ckpt_lib

log = logging.getLogger(__name__)

# Orbax emits dozens of INFO lines per checkpoint; keep the training log
# readable by default (users can re-raise the level explicitly).
for _noisy in ("orbax", "absl"):
  logging.getLogger(_noisy).setLevel(logging.WARNING)


class MetricLogger:
  """Scalar metric sink: stdout + JSONL file per tag (train/eval)."""

  def __init__(self, model_dir: str):
    self._model_dir = model_dir
    os.makedirs(model_dir, exist_ok=True)
    self._files: Dict[str, Any] = {}

  def write(self, tag: str, step: int, metrics: Dict[str, Any]) -> None:
    scalars = {k: float(np.asarray(v)) for k, v in metrics.items()}
    if tag not in self._files:
      self._files[tag] = open(
          os.path.join(self._model_dir, f"metrics_{tag}.jsonl"), "a")
    record = {"step": int(step), **scalars}
    self._files[tag].write(json.dumps(record) + "\n")
    self._files[tag].flush()
    rendered = ", ".join(f"{k}={v:.5g}" for k, v in scalars.items())
    log.info("[%s] step %d: %s", tag, step, rendered)

  def close(self) -> None:
    for f in self._files.values():
      f.close()
    self._files.clear()


def _compile_steps(model: ModelInterface, mesh, donate: bool = True,
                   state_shardings=None):
  """Jits train/eval steps with mesh shardings (batch on data axis).

  `state_shardings`: a NamedSharding pytree for the TrainState (from
  `parallel.state_sharding`); None replicates the state — pure data
  parallelism, the reference-equivalent default.
  """
  repl = mesh_lib.replicated(mesh)
  if state_shardings is None:
    state_shardings = repl
  batch = mesh_lib.batch_sharding(mesh)
  train_step = jax.jit(
      model.train_step,
      in_shardings=(state_shardings, batch, batch, repl),
      out_shardings=(state_shardings, repl),
      donate_argnums=(0,) if donate else (),
  )
  eval_step = jax.jit(
      model.eval_step,
      in_shardings=(state_shardings, batch, batch),
      out_shardings=repl,
  )
  return train_step, eval_step


def _run_eval(model, eval_step, state, input_generator_eval, mesh,
              eval_steps: int, batch_size: Optional[int]) -> Dict[str, float]:
  """Averages eval metrics over `eval_steps` batches."""
  stream = input_generator_eval.create_dataset(
      Mode.EVAL, batch_size=batch_size)
  prefetcher = prefetch_lib.ShardedPrefetcher(
      stream, mesh_lib.batch_sharding(mesh), buffer_size=2)
  totals: Dict[str, float] = {}
  count = 0
  try:
    for features, labels in prefetcher:
      metrics = eval_step(state, features, labels)
      for key, value in metrics.items():
        totals[key] = totals.get(key, 0.0) + float(np.asarray(value))
      count += 1
      if count >= eval_steps:
        break
  finally:
    prefetcher.close()
  if count == 0:
    return {}
  return {k: v / count for k, v in totals.items()}


@gin.configurable
def train_eval_model(
    model: ModelInterface = gin.REQUIRED,
    model_dir: str = gin.REQUIRED,
    input_generator_train: Optional[AbstractInputGenerator] = None,
    input_generator_eval: Optional[AbstractInputGenerator] = None,
    max_train_steps: int = 1000,
    eval_steps: int = 10,
    eval_every_steps: Optional[int] = None,
    save_checkpoints_steps: int = 500,
    max_checkpoints_to_keep: int = 5,
    batch_size: Optional[int] = None,
    eval_batch_size: Optional[int] = None,
    mesh: Optional[jax.sharding.Mesh] = None,
    sharding_strategy: str = "replicated",
    min_size_to_shard: int = 2 ** 10,
    create_exporters_fn: Optional[Callable] = None,
    hooks: Iterable[Hook] = (),
    log_every_steps: int = 100,
    seed: int = 0,
    init_batch_size: int = 2,
    steps_per_dispatch: int = 1,
):
  """Trains (with interleaved eval) and exports; resumes automatically.

  `sharding_strategy` selects the TrainState placement over the mesh
  (`parallel.state_sharding` rules): "replicated" (pure data
  parallelism, the default), "fsdp" (zero-style param/optimizer
  sharding over the `fsdp` axis), "tp" (megatron-style over `model`),
  "ep" (stacked expert weights over `expert` — MoE models), or
  "pipeline" (stage-stacked weights over `stage`). The batch always
  shards over the data-like axes; GSPMD inserts the collectives each
  layout needs.

  `steps_per_dispatch` (K) is the reference TPUEstimator's
  `iterations_per_loop` (SURVEY.md §4.1): K train steps run as ONE
  device program per host call — a `lax.scan` over K host-stacked
  input batches — paying host/dispatch latency once per K steps.
  Quantization semantics: log/checkpoint/eval cadences and
  max_train_steps must be multiples of K, and per-step hooks observe
  each dispatch's LAST metrics. The per-step PRNG stream is identical
  to K=1.

  Returns the final TrainState (on device, placed per the strategy).
  """
  if mesh is None:
    mesh = mesh_lib.create_mesh()
  # Validate the dispatch quantization BEFORE any side effects.
  k = prefetch_lib.validate_steps_per_dispatch(
      steps_per_dispatch,
      log_every_steps=log_every_steps,
      save_checkpoints_steps=save_checkpoints_steps,
      max_train_steps=max_train_steps,
      eval_every_steps=eval_every_steps)
  os.makedirs(model_dir, exist_ok=True)
  metric_logger = MetricLogger(model_dir)
  hook_list = HookList(list(hooks))

  # --- bind generators to the model's wire specs ---
  if input_generator_train is not None:
    input_generator_train.set_specification_from_model(model, Mode.TRAIN)
  if input_generator_eval is not None:
    input_generator_eval.set_specification_from_model(model, Mode.EVAL)

  # --- init / resume state ---
  rng = jax.random.PRNGKey(seed)
  state = model.create_train_state(rng, batch_size=init_batch_size)
  state_shardings = state_sharding(
      mesh, state, strategy=sharding_strategy,
      min_size_to_shard=min_size_to_shard)
  state = jax.device_put(state, state_shardings)
  resume_step = ckpt_lib.latest_step(model_dir)
  if resume_step is not None:
    log.info("Resuming from checkpoint at step %d in %s", resume_step,
             model_dir)
    # Restored leaves adopt `state`'s shardings — checkpoints are
    # portable across strategies/layouts (tests/test_checkpoint_resharding).
    state = ckpt_lib.restore_state(model_dir, like=state,
                                   step=resume_step)

  writer = ckpt_lib.CheckpointWriter(
      model_dir, max_to_keep=max_checkpoints_to_keep)
  train_step, eval_step = _compile_steps(
      model, mesh, state_shardings=state_shardings)

  if k > 1:
    repl = mesh_lib.replicated(mesh)
    stacked_sh = prefetch_lib.stacked_sharding(
        mesh_lib.batch_sharding(mesh))

    def k_steps(st, stacked_features, stacked_labels, rng, step0):
      return prefetch_lib.scan_k_steps(
          model.train_step, st, (stacked_features, stacked_labels),
          rng, step0)

    train_step = jax.jit(
        k_steps,
        in_shardings=(state_shardings, stacked_sh, stacked_sh,
                      repl, repl),
        out_shardings=(state_shardings, repl),
        donate_argnums=(0,),
    )
  # Resume-alignment check BEFORE hooks begin: raising later would
  # leak whatever begin() started past hook_list.end().
  step = int(np.asarray(jax.device_get(state.step)))
  if k > 1 and step % k and step < max_train_steps:
    writer.close()
    metric_logger.close()
    raise ValueError(
        f"Resumed at step {step}, not a multiple of "
        f"steps_per_dispatch={k}: boundaries would never align.")
  hook_list.begin(model, model_dir)

  final_metrics: Dict[str, Any] = {}
  train_prefetcher = None
  try:
    if input_generator_train is not None and step < max_train_steps:
      stream = input_generator_train.create_dataset(
          Mode.TRAIN, batch_size=batch_size)
      if k > 1:
        # Finite streams end cleanly mid-stack (the shared helper
        # swallows the inner StopIteration PEP 479 would otherwise
        # convert to a RuntimeError, preserving the final
        # off-interval checkpoint below).
        stream = prefetch_lib.stack_batches(stream, k)
        feed_sharding = stacked_sh
      else:
        feed_sharding = mesh_lib.batch_sharding(mesh)
      prefetcher = train_prefetcher = prefetch_lib.ShardedPrefetcher(
          stream, feed_sharding, buffer_size=2)
      step_rng = jax.random.PRNGKey(seed + 1)
      t_last = time.time()
      steps_since_log = 0
      last_saved_step = resume_step
      for features, labels in prefetcher:
        if step >= max_train_steps:
          break
        if k == 1:
          state, metrics = train_step(
              state, features, labels,
              jax.random.fold_in(step_rng, step))
        else:
          state, metrics = train_step(state, features, labels,
                                      step_rng, np.int32(step))
        step += k
        steps_since_log += k
        hook_list.after_step(step, metrics)

        if step % log_every_steps == 0 or step == max_train_steps:
          # One blocking device read per log interval only.
          scalars = jax.device_get(metrics)
          dt = time.time() - t_last
          scalars["steps_per_sec"] = steps_since_log / max(dt, 1e-9)
          metric_logger.write("train", step, scalars)
          final_metrics = scalars
          t_last = time.time()
          steps_since_log = 0

        if step % save_checkpoints_steps == 0 or step == max_train_steps:
          # Sharded state saves AS-IS: orbax copies device shards to
          # host before save() returns (so the next step's donation
          # is safe), serializes asynchronously, and each process
          # writes only its addressable shards — a host-side
          # device_get here would block, materialize the unsharded
          # state, and crash on a multi-process pod.
          writer.save(step, state)
          last_saved_step = step
          hook_list.after_checkpoint(step, state, model_dir)

        # Interleaved eval runs on its own cadence, independent of the
        # checkpoint interval.
        if (input_generator_eval is not None and eval_every_steps and
            step % eval_every_steps == 0 and step != max_train_steps):
          eval_metrics = _run_eval(
              model, eval_step, state, input_generator_eval, mesh,
              eval_steps, eval_batch_size or batch_size)
          metric_logger.write("eval", step, eval_metrics)

      # Final checkpoint if the loop ended off-interval.
      if last_saved_step != step:
        writer.save(step, state)
        hook_list.after_checkpoint(step, state, model_dir)

    # --- final eval ---
    if input_generator_eval is not None:
      eval_metrics = _run_eval(
          model, eval_step, state, input_generator_eval, mesh,
          eval_steps, eval_batch_size or batch_size)
      if eval_metrics:
        metric_logger.write("eval", step, eval_metrics)

    # --- exporters ---
    if create_exporters_fn is not None:
      for exporter in create_exporters_fn(model):
        exporter.export(model, state, model_dir)

    hook_list.end(step, state, model_dir)
  finally:
    # Close in finally: an exception mid-training must not leak the
    # prefetch worker (it pins buffered sharded batches in HBM).
    if train_prefetcher is not None:
      train_prefetcher.close()
    writer.close()
    metric_logger.close()
  return state


@gin.configurable
def continuous_eval(
    model: ModelInterface = gin.REQUIRED,
    model_dir: str = gin.REQUIRED,
    input_generator_eval: AbstractInputGenerator = gin.REQUIRED,
    eval_steps: int = 10,
    eval_batch_size: Optional[int] = None,
    mesh: Optional[jax.sharding.Mesh] = None,
    timeout_secs: Optional[float] = None,
    poll_interval_secs: float = 2.0,
    max_evals: Optional[int] = None,
    seed: int = 0,
    init_batch_size: int = 2,
):
  """Polls `model_dir` for new checkpoints and evals each one.

  Reference parity: the continuous-eval mode of `train_eval_model`
  (SURVEY.md §4.1). Returns {step: metrics} for all evaluated steps.
  """
  if mesh is None:
    mesh = mesh_lib.create_mesh()
  input_generator_eval.set_specification_from_model(model, Mode.EVAL)
  state = model.create_train_state(jax.random.PRNGKey(seed),
                                   batch_size=init_batch_size)
  state = jax.device_put(state, mesh_lib.replicated(mesh))
  _, eval_step = _compile_steps(model, mesh, donate=False)
  metric_logger = MetricLogger(model_dir)

  results: Dict[int, Dict[str, float]] = {}
  last_step = None
  try:
    while max_evals is None or len(results) < max_evals:
      new_step = ckpt_lib.wait_for_new_checkpoint(
          model_dir, last_step, timeout_secs=timeout_secs,
          poll_interval_secs=poll_interval_secs)
      if new_step is None:
        break
      state = ckpt_lib.restore_state(model_dir, like=state, step=new_step)
      metrics = _run_eval(model, eval_step, state, input_generator_eval,
                          mesh, eval_steps, eval_batch_size)
      metric_logger.write("eval", new_step, metrics)
      results[new_step] = metrics
      last_step = new_step
  finally:
    metric_logger.close()
  return results
