"""train_eval_model: the training/eval/export orchestrator.

Reference parity: tensor2robot `train_eval.py` —
`train_eval_model(model, input_generator_train, input_generator_eval,
max_train_steps, eval_steps, create_exporters_fn, use_tpu, ...)` building
an (TPU)Estimator and running train / eval / continuous-eval / export
(SURVEY.md §4.1).

TPU-native redesign: no Estimator. The model's pure `train_step` is
jitted ONCE over a named device mesh with the batch sharded along the
data axis and state replicated (or sharded per the model's rules);
GSPMD inserts the ICI all-reduce. The host loop is thin: pull a
prefetched sharded batch, call the compiled step, occasionally log /
checkpoint — state stays on device the whole time (the reference paid a
host round-trip per `iterations_per_loop`). Checkpointing is async
orbax; resume is automatic from the latest checkpoint in `model_dir`.
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Any, Callable, Dict, Iterable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from tensor2robot_tpu import config as gin
from tensor2robot_tpu import telemetry
from tensor2robot_tpu.data.abstract_input_generator import (
    AbstractInputGenerator,
    Mode,
)
from tensor2robot_tpu.data import prefetch as prefetch_lib
from tensor2robot_tpu.hooks import Hook, HookList
from tensor2robot_tpu.models.model_interface import ModelInterface
from tensor2robot_tpu.parallel import mesh as mesh_lib
from tensor2robot_tpu.parallel import state_sharding
from tensor2robot_tpu.startup import compile_cache
from tensor2robot_tpu.startup import orchestrator
from tensor2robot_tpu.utils import checkpoints as ckpt_lib

log = logging.getLogger(__name__)

# Orbax emits dozens of INFO lines per checkpoint; keep the training log
# readable by default (users can re-raise the level explicitly).
for _noisy in ("orbax", "absl"):
  logging.getLogger(_noisy).setLevel(logging.WARNING)


class MetricLogger:
  """Scalar metric sink: stdout + JSONL file per tag (train/eval).

  Every record is the unified telemetry envelope
  ``{"step", "wall", "role", "payload"}`` (telemetry.records — the
  ISSUE 11 schema every producer shares: this trainer, anakin, the
  fleet learner, the success-eval hooks). ``role`` defaults to the
  process's telemetry role; read back with
  `telemetry.records.read_records`, which also normalizes pre-envelope
  files.
  """

  def __init__(self, model_dir: str, role: Optional[str] = None):
    self._model_dir = model_dir
    self._role = role
    os.makedirs(model_dir, exist_ok=True)
    self._files: Dict[str, Any] = {}

  def write(self, tag: str, step: int, metrics: Dict[str, Any]) -> None:
    scalars = {k: float(np.asarray(v)) for k, v in metrics.items()}
    if tag not in self._files:
      self._files[tag] = open(
          os.path.join(self._model_dir, f"metrics_{tag}.jsonl"), "a")
    record = telemetry.records.make_record(step, scalars,
                                           role=self._role)
    self._files[tag].write(json.dumps(record) + "\n")
    self._files[tag].flush()
    rendered = ", ".join(f"{k}={v:.5g}" for k, v in scalars.items())
    log.info("[%s] step %d: %s", tag, step, rendered)

  def close(self) -> None:
    for f in self._files.values():
      f.close()
    self._files.clear()


def _compile_steps(model: ModelInterface, mesh, donate: bool = True,
                   state_shardings=None):
  """Jits train/eval steps with mesh shardings (batch on data axis).

  `state_shardings`: a NamedSharding pytree for the TrainState (from
  `parallel.state_sharding`); None replicates the state — pure data
  parallelism, the reference-equivalent default.
  """
  repl = mesh_lib.replicated(mesh)
  if state_shardings is None:
    state_shardings = repl
  batch = mesh_lib.batch_sharding(mesh)
  train_step = jax.jit(
      model.train_step,
      in_shardings=(state_shardings, batch, batch, repl),
      out_shardings=(state_shardings, repl),
      donate_argnums=(0,) if donate else (),
  )
  eval_step = jax.jit(
      model.eval_step,
      in_shardings=(state_shardings, batch, batch),
      out_shardings=repl,
  )
  return train_step, eval_step


def _spec_batch_avals(spec, batch_size: int, sharding):
  """Abstract [B, ...] batch pytree from a generator's (flat) wire spec.

  The generators' contract is "spec-conforming numpy batches", so the
  spec IS the aval source — AOT compilation never has to wait for the
  input pipeline to produce a first batch.
  """
  if spec is None:
    return None
  return jax.tree_util.tree_map(
      lambda s: jax.ShapeDtypeStruct(
          (batch_size,) + tuple(s.shape), np.dtype(s.dtype),
          sharding=sharding),
      spec)


def _batch_matches(avals, batch) -> bool:
  """Does a concrete batch pytree carry exactly the predicted avals?"""
  try:
    if jax.tree_util.tree_structure(avals) != \
        jax.tree_util.tree_structure(batch):
      return False
    return all(
        tuple(a.shape) == tuple(np.shape(b))
        and np.dtype(a.dtype) == np.result_type(b)
        for a, b in zip(jax.tree_util.tree_leaves(avals),
                        jax.tree_util.tree_leaves(batch)))
  except Exception:
    return False


def _checked_aot(compiled, fallback, feature_avals, label_avals, what):
  """Callable routing each batch to the AOT executable iff it matches
  the spec-predicted avals, else to the lazy jit.

  The spec contract makes a mismatch a generator bug, but a wrong
  guess must degrade to a recompile (the pre-AOT behavior), never to
  a crashed run — and a generator may diverge on ANY batch (e.g. a
  short final batch), so every call is checked: a tree compare, ~µs
  against a ms-scale dispatch.
  """
  if compiled is None:
    return fallback
  warned = []

  def call(state, features, labels, *rest):
    if (_batch_matches(feature_avals, features)
        and _batch_matches(label_avals, labels)):
      return compiled(state, features, labels, *rest)
    if not warned:
      warned.append(True)
      log.warning(
          "A batch does not match the AOT-compiled %s program's "
          "spec-predicted avals (generator diverged from its spec?); "
          "falling back to on-demand compilation for such batches.",
          what)
    return fallback(state, features, labels, *rest)

  return call


def _run_eval(model, eval_step, state, input_generator_eval, mesh,
              eval_steps: int, batch_size: Optional[int]) -> Dict[str, float]:
  """Averages eval metrics over `eval_steps` batches."""
  stream = input_generator_eval.create_dataset(
      Mode.EVAL, batch_size=batch_size)
  prefetcher = prefetch_lib.ShardedPrefetcher(
      stream, mesh_lib.batch_sharding(mesh), buffer_size=2)
  totals: Dict[str, float] = {}
  count = 0
  try:
    for features, labels in prefetcher:
      metrics = eval_step(state, features, labels)
      for key, value in metrics.items():
        totals[key] = totals.get(key, 0.0) + float(np.asarray(value))
      count += 1
      if count >= eval_steps:
        break
  finally:
    prefetcher.close()
  if count == 0:
    return {}
  return {k: v / count for k, v in totals.items()}


@gin.configurable
def train_eval_model(
    model: ModelInterface = gin.REQUIRED,
    model_dir: str = gin.REQUIRED,
    input_generator_train: Optional[AbstractInputGenerator] = None,
    input_generator_eval: Optional[AbstractInputGenerator] = None,
    max_train_steps: int = 1000,
    eval_steps: int = 10,
    eval_every_steps: Optional[int] = None,
    save_checkpoints_steps: int = 500,
    max_checkpoints_to_keep: int = 5,
    batch_size: Optional[int] = None,
    eval_batch_size: Optional[int] = None,
    mesh: Optional[jax.sharding.Mesh] = None,
    sharding_strategy: str = "replicated",
    min_size_to_shard: int = 2 ** 10,
    create_exporters_fn: Optional[Callable] = None,
    hooks: Iterable[Hook] = (),
    log_every_steps: int = 100,
    seed: int = 0,
    init_batch_size: int = 2,
    steps_per_dispatch: int = 1,
    overlap_startup: bool = True,
):
  """Trains (with interleaved eval) and exports; resumes automatically.

  `sharding_strategy` selects the TrainState placement over the mesh
  (`parallel.state_sharding` rules): "replicated" (pure data
  parallelism, the default), "fsdp" (zero-style param/optimizer
  sharding over the `fsdp` axis), "tp" (megatron-style over `model`),
  "ep" (stacked expert weights over `expert` — MoE models), or
  "pipeline" (stage-stacked weights over `stage`). The batch always
  shards over the data-like axes; GSPMD inserts the collectives each
  layout needs.

  `steps_per_dispatch` (K) is the reference TPUEstimator's
  `iterations_per_loop` (SURVEY.md §4.1): K train steps run as ONE
  device program per host call — a `lax.scan` over K host-stacked
  input batches — paying host/dispatch latency once per K steps.
  Quantization semantics: log/checkpoint/eval cadences and
  max_train_steps must be multiples of K, and per-step hooks observe
  each dispatch's LAST metrics. The per-step PRNG stream is identical
  to K=1.

  `overlap_startup` (default True) runs the three serial cold-start
  phases concurrently — AOT `.lower().compile()` of the train/eval
  programs (avals predicted from the generators' wire specs), the
  orbax resume restore, and the input pipeline's spin-up/first-batch
  prep — and writes per-phase timings to
  `<model_dir>/startup_timings.json` (see docs/STARTUP.md). False is
  the reference serial path: restore, then lazy jit at the first
  step. Both paths are bitwise-identical in results; with a
  persistent compilation cache configured
  (`startup.configure_compilation_cache`), a warm restart skips XLA
  entirely.

  Returns the final TrainState (on device, placed per the strategy).
  """
  compile_cache.configure_compilation_cache()
  if mesh is None:
    mesh = mesh_lib.create_mesh()
  # Validate the dispatch quantization BEFORE any side effects.
  k = prefetch_lib.validate_steps_per_dispatch(
      steps_per_dispatch,
      log_every_steps=log_every_steps,
      save_checkpoints_steps=save_checkpoints_steps,
      max_train_steps=max_train_steps,
      eval_every_steps=eval_every_steps)
  os.makedirs(model_dir, exist_ok=True)
  metric_logger = MetricLogger(model_dir)
  hook_list = HookList(list(hooks))

  # --- bind generators to the model's wire specs ---
  if input_generator_train is not None:
    input_generator_train.set_specification_from_model(model, Mode.TRAIN)
  if input_generator_eval is not None:
    input_generator_eval.set_specification_from_model(model, Mode.EVAL)

  # --- init / resume state ---
  rng = jax.random.PRNGKey(seed)
  state = model.create_train_state(rng, batch_size=init_batch_size)
  state_shardings = state_sharding(
      mesh, state, strategy=sharding_strategy,
      min_size_to_shard=min_size_to_shard)
  state = jax.device_put(state, state_shardings)
  resume_step = ckpt_lib.latest_step(model_dir)

  repl = mesh_lib.replicated(mesh)
  batch_sh = mesh_lib.batch_sharding(mesh)
  feed_sharding = batch_sh
  # Donation is disabled when the persistent cache is live on CPU —
  # see compile_cache.donation_unsafe_with_cache (jaxlib heap bug).
  donate = not compile_cache.donation_unsafe_with_cache()
  train_step, eval_step = _compile_steps(
      model, mesh, donate=donate, state_shardings=state_shardings)

  if k > 1:
    stacked_sh = prefetch_lib.stacked_sharding(batch_sh)
    feed_sharding = stacked_sh

    def k_steps(st, stacked_features, stacked_labels, rng, step0):
      return prefetch_lib.scan_k_steps(
          model.train_step, st, (stacked_features, stacked_labels),
          rng, step0)

    train_step = jax.jit(
        k_steps,
        in_shardings=(state_shardings, stacked_sh, stacked_sh,
                      repl, repl),
        out_shardings=(state_shardings, repl),
        donate_argnums=(0,) if donate else (),
    )

  # --- overlapped cold-start: AOT compile ∥ restore ∥ input spin-up ---
  # `will_train` over-approximates (a resume may already be past
  # max_train_steps — unknowable until the restore lands); an unused
  # prefetcher is closed without being consumed.
  will_train = input_generator_train is not None and max_train_steps > 0

  def _restore_phase():
    # Restored leaves adopt `state`'s shardings — checkpoints are
    # portable across strategies/layouts (tests/test_checkpoint_resharding).
    return ckpt_lib.restore_state(model_dir, like=state,
                                  step=resume_step)

  def _input_phase():
    stream = input_generator_train.create_dataset(
        Mode.TRAIN, batch_size=batch_size)
    if k > 1:
      # K-stacking retains each batch until the stack closes, past a
      # zero-copy data-plane stream's one-slot view lifetime — such
      # streams must copy out of the ring first.
      require_copies = getattr(stream, "require_copies", None)
      if require_copies is not None:
        require_copies()
      # Finite streams end cleanly mid-stack (the shared helper
      # swallows the inner StopIteration PEP 479 would otherwise
      # convert to a RuntimeError, preserving the final
      # off-interval checkpoint below).
      stream = prefetch_lib.stack_batches(stream, k)
    return prefetch_lib.ShardedPrefetcher(
        stream, feed_sharding, buffer_size=2)

  def _stack_avals(avals, sharding):
    return jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct((k,) + tuple(a.shape), a.dtype,
                                       sharding=sharding), avals)

  def _compile_phase():
    # Avals come from the already-initialized `state` (the restore
    # preserves shapes/dtypes/shardings by construction) and the
    # generators' wire specs — nothing here waits on disk or on the
    # input pipeline, which is the whole point.
    out: Dict[str, Any] = {}
    state_avals = jax.tree_util.tree_map(compile_cache.aval_of, state)
    rng_aval = jax.ShapeDtypeStruct((2,), np.uint32, sharding=repl)
    if will_train:
      bs = batch_size or input_generator_train.batch_size
      f_aval = _spec_batch_avals(
          input_generator_train.feature_spec, bs, batch_sh)
      l_aval = _spec_batch_avals(
          input_generator_train.label_spec, bs, batch_sh)
      if k > 1:
        f_aval = _stack_avals(f_aval, stacked_sh)
        l_aval = _stack_avals(l_aval, stacked_sh)
      out["train_avals"] = (f_aval, l_aval)
      try:
        if k > 1:
          step0_aval = jax.ShapeDtypeStruct((), np.int32, sharding=repl)
          out["train"] = train_step.lower(
              state_avals, f_aval, l_aval, rng_aval,
              step0_aval).compile()
        else:
          out["train"] = train_step.lower(
              state_avals, f_aval, l_aval, rng_aval).compile()
      except Exception:
        log.warning(
            "AOT train-step compile failed; the first step will "
            "compile on demand.", exc_info=True)
    if input_generator_eval is not None:
      ebs = (eval_batch_size or batch_size
             or input_generator_eval.batch_size)
      ef_aval = _spec_batch_avals(
          input_generator_eval.feature_spec, ebs, batch_sh)
      el_aval = _spec_batch_avals(
          input_generator_eval.label_spec, ebs, batch_sh)
      out["eval_avals"] = (ef_aval, el_aval)
      try:
        out["eval"] = eval_step.lower(
            state_avals, ef_aval, el_aval).compile()
      except Exception:
        log.warning(
            "AOT eval-step compile failed; the first eval will "
            "compile on demand.", exc_info=True)
    return out

  aot: Optional[Dict[str, Any]] = None
  train_prefetcher = None
  phases: Dict[str, Any] = {}
  if overlap_startup:
    if will_train or input_generator_eval is not None:
      phases["compile"] = _compile_phase
    if resume_step is not None:
      phases["restore"] = _restore_phase
    if will_train:
      phases["input"] = _input_phase
  if phases:
    if resume_step is not None:
      log.info("Resuming from checkpoint at step %d in %s", resume_step,
               model_dir)
    report = orchestrator.run_overlapped(phases)
    if report.errors:
      # A failed phase must not leak a sibling's resources: the input
      # prefetcher pins buffered sharded batches in device memory.
      orchestrator.close_quietly(report.results.get("input"))
      metric_logger.close()
      report.raise_first(order=("restore", "input", "compile"))
    aot = report.results.get("compile")
    state = report.results.get("restore", state)
    train_prefetcher = report.results.get("input")
    try:
      report.write(model_dir)
    except OSError:
      log.warning("Could not write %s",
                  orchestrator.STARTUP_TIMINGS_FILE, exc_info=True)
  elif resume_step is not None:
    # Serial reference path (overlap_startup=False).
    log.info("Resuming from checkpoint at step %d in %s", resume_step,
             model_dir)
    state = _restore_phase()

  writer = ckpt_lib.CheckpointWriter(
      model_dir, max_to_keep=max_checkpoints_to_keep)
  # Resume-alignment check BEFORE hooks begin: raising later would
  # leak whatever begin() started past hook_list.end().
  step = int(np.asarray(jax.device_get(state.step)))
  if k > 1 and step % k and step < max_train_steps:
    if train_prefetcher is not None:
      train_prefetcher.close()
    writer.close()
    metric_logger.close()
    raise ValueError(
        f"Resumed at step {step}, not a multiple of "
        f"steps_per_dispatch={k}: boundaries would never align.")

  if aot:
    train_callable = _checked_aot(
        aot.get("train"), train_step, *aot.get("train_avals", (None, None)),
        what="train")
    eval_callable = _checked_aot(
        aot.get("eval"), eval_step, *aot.get("eval_avals", (None, None)),
        what="eval")
  else:
    train_callable, eval_callable = train_step, eval_step

  # The always-on perf plane (ISSUE 15): resource sampler + sentinel
  # per process, and live MFU attribution at log cadence. The generic
  # trainer has no analytic model-flops formula (arbitrary models), so
  # the denominator is XLA's cost analysis of the AOT-compiled train
  # program (÷ K for the scanned dispatch) — approximate but stable
  # for the run; absent (lazy-jit fallback), perf.mfu is simply not
  # published and device_time_fraction still is.
  from tensor2robot_tpu.telemetry import perf as perf_lib
  from tensor2robot_tpu.telemetry import sentinel as sentinel_lib
  from tensor2robot_tpu.utils import profiling
  perf_lib.start_resource_sampler(
      sources=[profiling.device_memory_source()])
  watch_sentinel = sentinel_lib.build_for_run(model_dir)
  train_flops = None
  if aot and aot.get("train") is not None:
    flops_per_call = profiling.compiled_flops_per_call(aot["train"])
    if flops_per_call:
      train_flops = flops_per_call / k
  perf_meter = perf_lib.PerfMeter(
      flops_per_step=train_flops,
      peak_flops=profiling.device_peak_flops(),
      devices=mesh.size)

  final_metrics: Dict[str, Any] = {}
  try:
    # Inside the try: with overlapped startup the prefetcher is
    # already live, and a hook whose begin() raises must not leak its
    # worker (the finally below closes it along with writer/logger).
    hook_list.begin(model, model_dir)
    if input_generator_train is not None and step < max_train_steps:
      if train_prefetcher is None:
        # Serial path (or resume landed short of max_train_steps with
        # no overlapped input phase): spin up the pipeline here.
        train_prefetcher = _input_phase()
      prefetcher = train_prefetcher
      step_rng = jax.random.PRNGKey(seed + 1)
      t_last = time.time()
      steps_since_log = 0
      # Stall accounting: wall spent in checkpoint saves, interleaved
      # evals, and metric writes per log interval. `steps_per_sec` is
      # the PURE train-loop rate (stalls excluded); `stall_fraction`
      # is the interval's share lost to them — the restart/save
      # regressions this PR's bench axis watches.
      stall_secs = 0.0
      last_saved_step = resume_step
      # Input-boundness accounting (input_wait_fraction): the shared
      # TimedIterator measures wall blocked in the prefetcher's
      # __next__ per log interval.
      prefetch_iter = prefetch_lib.TimedIterator(prefetcher)
      for features, labels in prefetch_iter:
        if step >= max_train_steps:
          break
        with perf_meter.dispatch("train.dispatch", step=step):
          if k == 1:
            state, metrics = train_callable(
                state, features, labels,
                jax.random.fold_in(step_rng, step))
          else:
            state, metrics = train_callable(state, features, labels,
                                            step_rng, np.int32(step))
        step += k
        steps_since_log += k
        hook_list.after_step(step, metrics)

        if step % log_every_steps == 0 or step == max_train_steps:
          # One blocking device read per log interval only.
          scalars = jax.device_get(metrics)
          dt = time.time() - t_last
          scalars["steps_per_sec"] = steps_since_log / max(
              dt - stall_secs, 1e-9)
          scalars["stall_fraction"] = min(
              max(stall_secs / max(dt, 1e-9), 0.0), 1.0)
          scalars["input_wait_fraction"] = prefetch_iter.wait_fraction(dt)
          # Compile-cache traffic rides the train log (the CompileWatch
          # tap publishes into the registry): a nonzero miss delta
          # AFTER the first interval is a warm-path recompile.
          scalars.update(telemetry.registry().scalars("compile_cache."))
          # Resource watermarks persist with the run (the report
          # tool's watermark section reads them back).
          scalars.update(telemetry.registry().scalars("rsrc."))
          telemetry.registry().gauge("train.steps_per_sec").set(
              scalars["steps_per_sec"])
          telemetry.registry().gauge("train.stall_fraction").set(
              scalars["stall_fraction"])
          # Live utilization (perf.mfu / flops_per_sec /
          # device_time_fraction): the always-on perf plane.
          scalars.update(perf_meter.publish(
              scalars["steps_per_sec"], dt))
          final_metrics = scalars
          t_last = time.time()
          steps_since_log = 0
          t_write = time.perf_counter()
          metric_logger.write("train", step, scalars)
          if watch_sentinel is not None:
            watch_sentinel.evaluate(
                {**telemetry.registry().scalars(), **scalars},
                step=step)
          # The write itself is logging stall, charged to the
          # interval that just began.
          stall_secs = time.perf_counter() - t_write

        if step % save_checkpoints_steps == 0 or step == max_train_steps:
          # Sharded state saves AS-IS: orbax copies device shards to
          # host before save() returns (so the next step's donation
          # is safe), serializes asynchronously, and each process
          # writes only its addressable shards — a host-side
          # device_get here would block, materialize the unsharded
          # state, and crash on a multi-process pod.
          t_save = time.perf_counter()
          writer.save(step, state)
          last_saved_step = step
          hook_list.after_checkpoint(step, state, model_dir)
          stall_secs += time.perf_counter() - t_save

        # Interleaved eval runs on its own cadence, independent of the
        # checkpoint interval.
        if (input_generator_eval is not None and eval_every_steps and
            step % eval_every_steps == 0 and step != max_train_steps):
          t_eval = time.perf_counter()
          eval_metrics = _run_eval(
              model, eval_callable, state, input_generator_eval, mesh,
              eval_steps, eval_batch_size or batch_size)
          metric_logger.write("eval", step, eval_metrics)
          stall_secs += time.perf_counter() - t_eval

      # Final checkpoint if the loop ended off-interval.
      if last_saved_step != step:
        writer.save(step, state)
        hook_list.after_checkpoint(step, state, model_dir)

    # --- final eval ---
    if input_generator_eval is not None:
      eval_metrics = _run_eval(
          model, eval_callable, state, input_generator_eval, mesh,
          eval_steps, eval_batch_size or batch_size)
      if eval_metrics:
        metric_logger.write("eval", step, eval_metrics)

    # --- exporters ---
    if create_exporters_fn is not None:
      for exporter in create_exporters_fn(model):
        exporter.export(model, state, model_dir)

    hook_list.end(step, state, model_dir)
  finally:
    # Close in finally: an exception mid-training must not leak the
    # prefetch worker (it pins buffered sharded batches in HBM).
    if train_prefetcher is not None:
      train_prefetcher.close()
    writer.close()
    if watch_sentinel is not None:
      watch_sentinel.close()
    metric_logger.close()
  return state


@gin.configurable
def continuous_eval(
    model: ModelInterface = gin.REQUIRED,
    model_dir: str = gin.REQUIRED,
    input_generator_eval: AbstractInputGenerator = gin.REQUIRED,
    eval_steps: int = 10,
    eval_batch_size: Optional[int] = None,
    mesh: Optional[jax.sharding.Mesh] = None,
    timeout_secs: Optional[float] = None,
    poll_interval_secs: float = 2.0,
    max_evals: Optional[int] = None,
    seed: int = 0,
    init_batch_size: int = 2,
):
  """Polls `model_dir` for new checkpoints and evals each one.

  Reference parity: the continuous-eval mode of `train_eval_model`
  (SURVEY.md §4.1). Returns {step: metrics} for all evaluated steps.

  Each record carries `restore_secs` / `eval_secs` /
  `restore_and_eval_secs` — the per-checkpoint wall this evaluator
  lags the trainer by, i.e. the predictor-side staleness bound: a
  checkpoint cadence shorter than `restore_and_eval_secs` means this
  loop permanently falls behind.
  """
  compile_cache.configure_compilation_cache()
  if mesh is None:
    mesh = mesh_lib.create_mesh()
  input_generator_eval.set_specification_from_model(model, Mode.EVAL)
  state = model.create_train_state(jax.random.PRNGKey(seed),
                                   batch_size=init_batch_size)
  state = jax.device_put(state, mesh_lib.replicated(mesh))
  _, eval_step = _compile_steps(model, mesh, donate=False)
  metric_logger = MetricLogger(model_dir)

  results: Dict[int, Dict[str, float]] = {}
  last_step = None
  try:
    while max_evals is None or len(results) < max_evals:
      new_step = ckpt_lib.wait_for_new_checkpoint(
          model_dir, last_step, timeout_secs=timeout_secs,
          poll_interval_secs=poll_interval_secs)
      if new_step is None:
        break
      t_restore = time.perf_counter()
      state = ckpt_lib.restore_state(model_dir, like=state, step=new_step)
      restore_secs = time.perf_counter() - t_restore
      t_eval = time.perf_counter()
      metrics = _run_eval(model, eval_step, state, input_generator_eval,
                          mesh, eval_steps, eval_batch_size)
      eval_secs = time.perf_counter() - t_eval
      metrics = dict(metrics)
      metrics["restore_secs"] = restore_secs
      metrics["eval_secs"] = eval_secs
      metrics["restore_and_eval_secs"] = restore_secs + eval_secs
      metric_logger.write("eval", new_step, metrics)
      results[new_step] = metrics
      last_step = new_step
  finally:
    metric_logger.close()
  return results
