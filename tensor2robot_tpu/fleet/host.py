"""Fleet replay/serving host: one process owning the store + the engine.

The Sebulba topology (PAPERS.md Podracer): actors do not touch the
device or the replay memory — they speak RPC to ONE host process that
owns both the `ReplayWriteService`→`ReplayStore` ingestion plane and
the `CEMPolicyServer` (bucketed AOT engine + micro-batcher). Putting
inference and replay in the same process is deliberate:

  * every actor's `act` request lands in the SAME micro-batcher, so N
    actors coalesce into ~one CEM program dispatch (the serving stack's
    whole point, now fed by a process fleet instead of threads);
  * the learner's `publish` hot-swaps the engine's params in the same
    address space the actors' requests resolve against — one swap
    serves the entire actor fleet atomically;
  * `param_refresh_lag` and replay staleness are measured at the one
    choke point every transition passes through.

Metric definitions (docs/FLEET.md):

  * `param_refresh_lag` — at each committed episode, the learner's
    CURRENT step (the store's `learner_step` tag) minus the learner
    step stamped on the params the actor acted with. This is the
    end-to-end publication latency actors actually experience:
    checkpoint cadence + publish transfer + however long the episode
    took to collect.
  * replay staleness — the plane's existing definition (learner step
    at SAMPLE minus at ADD), accounted by the host-side
    `ReplayBatchSampler` every learner `sample` rides through.

Crash contract: each connection's replay sessions are aborted on
disconnect (`rpc.DISCONNECT_METHOD`), so an actor that dies mid-episode
never lands partial rows — same session-abort semantics as the
in-process service, proven across the process boundary by
tests/test_fleet.py.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, Optional, Tuple

import numpy as np

from tensor2robot_tpu import telemetry
from tensor2robot_tpu.fleet import faults as faults_lib
from tensor2robot_tpu.fleet import proc
from tensor2robot_tpu.fleet import rpc as rpc_lib
from tensor2robot_tpu.telemetry import flightrec
from tensor2robot_tpu.telemetry import metrics as tmetrics

log = logging.getLogger(__name__)

# Lag histogram bucket upper bounds, in learner steps (same labelling
# scheme as the replay plane's staleness histogram). ONE source of
# truth with the telemetry registry's step-bucket family so the
# authoritative snapshot and its registry twin can never desynchronize.
LAG_BUCKETS = tuple(int(b) for b in tmetrics.DEFAULT_STEP_BOUNDS)


class _LagStats:
  """Thread-safe accumulator for the param-refresh-lag distribution."""

  def __init__(self):
    self._lock = threading.Lock()
    self._counts = np.zeros(len(LAG_BUCKETS) + 1, np.int64)
    self._sum = 0
    self._max = 0
    self._n = 0
    self._tm_lag = tmetrics.histogram(
        "fleet.param_refresh_lag_steps", tmetrics.DEFAULT_STEP_BOUNDS)

  def record(self, lag: int, rows: int) -> None:
    lag = max(int(lag), 0)
    bucket = int(np.searchsorted(LAG_BUCKETS, lag, side="left"))
    with self._lock:
      self._counts[bucket] += rows
      self._sum += lag * rows
      self._max = max(self._max, lag)
      self._n += rows
    # Twin publication into the process registry (same step-bucket
    # family, same ROW weighting as the accumulator above), so the
    # telemetry RPC serves lag without touching this class and the
    # flight recorder captures it.
    self._tm_lag.observe(lag, n=rows)

  def snapshot(self) -> Dict[str, Any]:
    with self._lock:
      labels = [f"<={b}" for b in LAG_BUCKETS] + [f">{LAG_BUCKETS[-1]}"]
      return {
          "rows": int(self._n),
          "mean": (self._sum / self._n) if self._n else 0.0,
          "max": int(self._max),
          "histogram": {label: int(count)
                        for label, count in zip(labels, self._counts)},
      }


class _HostState:
  """Everything the host serves, plus the RPC method table."""

  def __init__(self, config):
    # jax and the model stack load HERE, in the host process — never
    # at module import (actor processes import this package jax-free).
    import jax

    from tensor2robot_tpu.replay.sampler import ReplayBatchSampler
    from tensor2robot_tpu.replay.service import ReplayWriteService
    from tensor2robot_tpu.replay.store import ReplayStore
    from tensor2robot_tpu.serving.cem_policy import CEMPolicyServer

    self._config = config
    # The host's telemetry identity: spans from the RPC layer and the
    # serving/replay planes flush to trace_host.jsonl; its clock is
    # the REFERENCE clock every handshaking client offsets against.
    telemetry.configure(
        "host", trace_dir=getattr(config, "telemetry_dir", "") or None)
    # Resource watermarks (ISSUE 15): device memory + host RSS +
    # replay/queue fill peaks as rsrc.* gauges. They live in the
    # ordinary registry, so the orchestrator's `telemetry` poll
    # aggregates them fleet-wide for free.
    from tensor2robot_tpu.telemetry import perf as perf_lib
    from tensor2robot_tpu.utils import profiling
    perf_lib.start_resource_sampler(
        sources=[profiling.device_memory_source()])
    self._learner = _build_learner(config)
    state0 = self._learner.create_state(
        jax.random.PRNGKey(config.seed), batch_size=2)
    acting0 = state0.train_state.replace(opt_state=None)
    self.policy_server = CEMPolicyServer(
        self._learner, acting0,
        max_batch=config.serve_max_batch,
        max_wait_us=config.serve_max_wait_us,
        seed=config.seed + 7)
    self.store = ReplayStore(
        self._learner.transition_specification(),
        capacity=config.replay_capacity,
        num_shards=config.replay_shards,
        seed=config.seed + 11)
    self.service = ReplayWriteService(
        self.store,
        queue_batches=config.queue_batches,
        overflow=config.overflow)
    self._sampler_cls = ReplayBatchSampler
    self._samplers: Dict[int, Any] = {}
    self._sessions: Dict[str, Any] = {}
    # Per-role registry snapshots pushed by actors/learner over the
    # `telemetry_push` RPC; the orchestrator's `telemetry` poll
    # returns them next to the host's own registry — one aggregated
    # fleet-wide view from one call.
    self._pushed_telemetry: Dict[str, Any] = {}
    self._lock = threading.Lock()
    self.lag = _LagStats()
    self.publishes = 0
    self._publish_t0: Optional[float] = None
    self._learner_window: Optional[Tuple[float, int, float, int]] = None
    self._resumes: list = []  # observed backward learner steps
    self._commit_window: Optional[Tuple[float, float]] = None
    self.shutdown_requested = threading.Event()

  # ---- wiring helpers ----

  def _session_for(self, actor_id: str, ctx: dict):
    with self._lock:
      session = self._sessions.get(actor_id)
    if session is None or session.closed:
      # A fresh claim under an existing actor_id is the restart path:
      # `service.session` counts it and aborts whatever the dead
      # incarnation staged (restart-with-session-abort).
      session = self.service.session(actor_id)
      with self._lock:
        self._sessions[actor_id] = session
    # Track the OBJECT this connection used, not just the id: a
    # hard-killed actor's connection can be detected dead AFTER its
    # replacement re-registered, and the late disconnect must abort
    # the old incarnation's session, never the new one's.
    ctx.setdefault("sessions", {})[actor_id] = session
    return session

  def _sampler(self, batch_size: int):
    with self._lock:
      sampler = self._samplers.get(batch_size)
      if sampler is None:
        sampler = self._sampler_cls(self.store, batch_size)
        self._samplers[batch_size] = sampler
    return sampler

  def _record_commit(self, rows: int, policy_learner_step) -> None:
    now = time.monotonic()
    with self._lock:
      first = self._commit_window[0] if self._commit_window else now
      self._commit_window = (first, now)
    if policy_learner_step is not None:
      self.lag.record(self.store.learner_step - int(policy_learner_step),
                      rows)

  # ---- the RPC method table ----

  def handle(self, method: str, payload: Any, ctx: dict) -> Any:
    if method == "act":
      # One atomic publication read: version and learner_step must be
      # a consistent pair (a swap between two property reads would
      # tear them). A swap landing between this read and the engine's
      # own dispatch can still attribute a single episode to the
      # adjacent publication — off by at most one refresh, which the
      # lag histogram tolerates (documented in docs/FLEET.md).
      publication = self.policy_server.engine.publication
      actions = self.policy_server.select_actions(payload)
      return {"actions": np.asarray(actions),
              "params_version": publication.version,
              "params_learner_step": publication.learner_step}
    if method == "commit":
      session = self._session_for(payload["actor_id"], ctx)
      accepted = session.add(payload["transitions"])
      if accepted:
        rows = int(next(iter(payload["transitions"].values())).shape[0])
        self._record_commit(rows, payload.get("policy_learner_step"))
      return bool(accepted)
    if method == "begin_episode":
      self._session_for(payload, ctx).begin_episode()
      return True
    if method == "append":
      self._session_for(payload["actor_id"], ctx).append(
          payload["transitions"])
      return True
    if method == "end_episode":
      session = self._session_for(payload["actor_id"], ctx)
      committed_before = session.transitions_committed
      accepted = session.end_episode()
      if accepted:
        self._record_commit(
            session.transitions_committed - committed_before,
            payload.get("policy_learner_step"))
      return bool(accepted)
    if method == "sample":
      batch = self._sampler(int(payload)).sample()
      return {k: np.asarray(v)
              for k, v in batch.to_flat_dict().items()}
    if method == "size":
      return len(self.store)
    if method == "set_learner_step":
      step = int(payload)
      self.store.set_learner_step(step)
      now = time.monotonic()
      with self._lock:
        if self._learner_window is None:
          self._learner_window = (now, step, now, step)
        else:
          t0, s0, _, last = self._learner_window
          if step < last:
            # The learner's step went BACKWARD: a crash-resume
            # restored from a checkpoint. The host is the one witness
            # with continuous state across learner incarnations, so
            # the MEASURED restore point is recorded here — the chaos
            # bench's loss-bounded-by-cadence gate reads it instead
            # of trusting config arithmetic.
            self._resumes.append({"from_step": last, "to_step": step})
          self._learner_window = (t0, s0, now, step)
      return True
    if method == "publish":
      self.policy_server.update_state(
          payload["state"], learner_step=int(payload["step"]))
      with self._lock:
        self.publishes += 1
        if self._publish_t0 is None:
          self._publish_t0 = time.monotonic()
      tmetrics.counter("fleet.param_publishes").inc()
      return self.policy_server.params_version
    if method == "metrics_scalars":
      out = self.store.metrics_scalars()
      with self._lock:
        samplers = list(self._samplers.values())
      for sampler in samplers:
        out.update(sampler.metrics_scalars())
      out["fleet_param_publishes"] = float(self.publishes)
      out["fleet_param_refresh_lag_mean"] = self.lag.snapshot()["mean"]
      return out
    if method == "metrics":
      return self.metrics()
    if method == "hello":
      engine = self.policy_server.engine
      # `monotonic` is the telemetry clock handshake: the client reads
      # its own clock around the call and derives its offset to this
      # host's CLOCK_MONOTONIC (telemetry.clock_offset_from_handshake)
      # — how the merge tool puts every process on one timeline.
      return {"max_batch": engine.max_batch,
              "capacity": self.store.capacity,
              "params_version": engine.params_version,
              "params_learner_step": engine.params_learner_step,
              "monotonic": time.monotonic()}
    if method == "telemetry":
      # The fleet-wide aggregated view (one poll): the host's own
      # registry — replay/serving/lag live HERE, at the choke point —
      # plus whatever snapshots the other roles pushed.
      with self._lock:
        pushed = dict(self._pushed_telemetry)
      return {"host": tmetrics.registry().snapshot(),
              "pushed": pushed,
              "monotonic": time.monotonic()}
    if method == "telemetry_push":
      with self._lock:
        self._pushed_telemetry[str(payload["role"])] = {
            "snapshot": payload["snapshot"],
            "wall": time.time(),
        }
      return True
    if method == "flight_record":
      # The orchestrator's latched-error hook: a still-live host dumps
      # its span ring + registry before teardown.
      return flightrec.dump(payload["out_dir"],
                            payload.get("reason", "requested"))
    if method == "shutdown":
      self.shutdown_requested.set()
      return True
    if method == rpc_lib.DISCONNECT_METHOD:
      # A dropped connection aborts every session IT opened: whatever
      # its actor staged mid-episode is discarded, never committed. The
      # identity check keeps a late-detected death from touching a
      # restarted incarnation's fresh session.
      for actor_id, session in ctx.get("sessions", {}).items():
        if not session.closed:
          session.abort()
        with self._lock:
          if self._sessions.get(actor_id) is session:
            del self._sessions[actor_id]
      return None
    raise ValueError(f"unknown fleet rpc method {method!r}")

  def metrics(self) -> Dict[str, Any]:
    with self._lock:
      learner_window = self._learner_window
      resumes = list(self._resumes)
      commit_window = self._commit_window
      samplers = list(self._samplers.items())
      publishes = self.publishes
    staleness: Dict[str, Any] = {}
    for batch_size, sampler in samplers:
      staleness[str(batch_size)] = sampler.staleness_snapshot()
    engine = self.policy_server.engine
    return {
        "store": self.store.metrics_snapshot(),
        "service": self.service.metrics_scalars(),
        "staleness": staleness,
        "param_refresh_lag": self.lag.snapshot(),
        "publishes": publishes,
        "params_version": engine.params_version,
        "params_learner_step": engine.params_learner_step,
        "learner_window": (None if learner_window is None else {
            "first_time": learner_window[0],
            "first_step": learner_window[1],
            "last_time": learner_window[2],
            "last_step": learner_window[3],
        }),
        "learner_resumes": resumes,
        "commit_window": (None if commit_window is None else {
            "first_time": commit_window[0],
            "last_time": commit_window[1],
        }),
        "serving_dispatches": engine.dispatch_count,
    }

  def close(self) -> None:
    # Intake is already stopped (the RPC server closes first); flush
    # what the writer still holds, then tear the batcher down.
    try:
      self.service.close()
    finally:
      self.policy_server.close()


def _build_learner(config):
  """The host's own QTOptLearner: the same constructor the learner
  process uses, so the published TrainState trees match structurally
  (CEM serving params here, gradient state there)."""
  from tensor2robot_tpu.research.qtopt.qtopt_learner import QTOptLearner
  from tensor2robot_tpu.research.qtopt.t2r_models import GraspingQModel

  model = GraspingQModel(
      image_size=config.image_size,
      action_dim=config.action_dim,
      torso_filters=tuple(config.torso_filters),
      head_filters=tuple(config.head_filters),
      dense_sizes=tuple(config.dense_sizes))
  return QTOptLearner(
      model,
      cem_population=config.cem_population,
      cem_iterations=config.cem_iterations,
      cem_elites=config.cem_elites,
      cem_inference=config.cem_inference)


def host_main(config, ready_conn, stop_event, heartbeat) -> None:
  """Child-process entry: build → handshake → serve → drain → exit.

  `ready_conn` (a Pipe end) carries the bound RPC address back to the
  orchestrator once the engine is warmed; the orchestrator spawns
  actors/learner only after this handshake, so clients never race a
  cold host.

  `stop_event` is the host's OWN stop signal, set by the orchestrator
  only AFTER the final metrics read — the host must outlive the
  actor/learner drain (it is the last process standing in the
  shutdown barrier). The RPC `shutdown` method is the other exit.
  """
  proc.scrub_inherited_distributed_env()
  # Server-side fault seam (slow_host stalls, injected disconnects):
  # armed BEFORE the server accepts, so call counting is deterministic
  # from the first RPC.
  faults_lib.install(config, "host")
  try:
    state = _HostState(config)
    server = rpc_lib.RpcServer(state.handle, authkey=config.authkey)
  except BaseException as e:
    # A host that dies building (bad config, compile failure) leaves
    # its last moments in the flight recorder before the orchestrator
    # sees the exit code.
    if getattr(config, "flightrec_dir", ""):
      flightrec.dump(config.flightrec_dir, f"host launch failed: {e!r}")
    raise
  try:
    ready_conn.send({"address": server.address})
    ready_conn.close()
    while not (stop_event.is_set() or state.shutdown_requested.is_set()):
      proc.beat(heartbeat)
      time.sleep(0.1)
  finally:
    from tensor2robot_tpu.telemetry import perf as perf_lib
    perf_lib.stop_resource_sampler()  # no jax calls past teardown
    server.close()
    state.close()
    telemetry.get_tracer().close()  # flush the host's trace tail
