"""Fleet replay/serving host: one process owning the store + the engine.

The Sebulba topology (PAPERS.md Podracer): actors do not touch the
device or the replay memory — they speak RPC to host processes that
own the `ReplayWriteService`→`ReplayStore` ingestion plane and the
`CEMPolicyServer` (bucketed AOT engine + micro-batcher). On a single
host both live in ONE process (the default, `replay_hosts=0`), which
is deliberate:

  * every actor's `act` request lands in the SAME micro-batcher, so N
    actors coalesce into ~one CEM program dispatch (the serving stack's
    whole point, now fed by a process fleet instead of threads);
  * the learner's `publish` hot-swaps the engine's params in the same
    address space the actors' requests resolve against — one swap
    serves the entire actor fleet atomically;
  * `param_refresh_lag` and replay staleness are measured at the one
    choke point every transition passes through.

Past one host (ISSUE 16) the same process splits along its two
planes, each behind `fleet.transport`:

  * SHARDED REPLAY — `replay_shard_main` processes each own ONE store
    shard behind a `replay.service.ReplayFront`; actors commit
    episodes to their rendezvous-hash home shard
    (`fleet.actor.home_shard`) and the learner fans sample requests
    across shards, concatenating shard-major (the PR-3 gather
    contract). Staleness and lag are accounted where each shard
    lives. Serving hosts then own NO store (`replay_hosts > 0`).
  * BROADCAST TREE — `serving_hosts` engine replicas arranged in a
    `broadcast_degree`-ary tree (heap layout: children of host i are
    i·d+1 … i·d+d). The learner publishes to the root only; each host
    swaps locally and forwards to its children, so the learner's
    uplink carries d copies instead of N — with per-hop
    `param_refresh_lag` attribution (commits stamp the acting host's
    tree depth) and `fleet.broadcast.*` wall-clock hop metrics.

Metric definitions (docs/FLEET.md):

  * `param_refresh_lag` — at each committed episode, the learner's
    CURRENT step (the store's `learner_step` tag) minus the learner
    step stamped on the params the actor acted with. This is the
    end-to-end publication latency actors actually experience:
    checkpoint cadence + publish transfer (+ broadcast hops) +
    however long the episode took to collect.
  * replay staleness — the plane's existing definition (learner step
    at SAMPLE minus at ADD), accounted by the store-side
    `ReplayBatchSampler` every learner `sample` rides through.

Crash contract: each connection's replay sessions are aborted on
disconnect (`rpc.DISCONNECT_METHOD`), so an actor that dies mid-episode
never lands partial rows — same session-abort semantics as the
in-process service, proven across the process boundary by
tests/test_fleet.py (and across the TCP transport by
tests/test_fleet_transport.py).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from tensor2robot_tpu import telemetry
from tensor2robot_tpu.fleet import faults as faults_lib
from tensor2robot_tpu.fleet import proc
from tensor2robot_tpu.fleet import rpc as rpc_lib
from tensor2robot_tpu.telemetry import flightrec
from tensor2robot_tpu.telemetry import metrics as tmetrics

# The replay plane (`replay.service.LagStats`/`ReplayFront`) is
# imported INSIDE the state constructors, never at module top: its
# import chain reaches `specs` → jax, and this module must stay in the
# jax-free actor import closure (fleet/__init__ pulls it in;
# tests/test_fleet.py pins the closure).

log = logging.getLogger(__name__)


def _server_kwargs(config) -> Dict[str, Any]:
  """The transport-seam kwargs every fleet RpcServer shares."""
  return dict(
      authkey=config.authkey,
      transport=getattr(config, "transport", "loopback"),
      sndbuf=getattr(config, "tcp_sndbuf", 0),
      rcvbuf=getattr(config, "tcp_rcvbuf", 0))


def _client_kwargs(config) -> Dict[str, Any]:
  """The transport-seam kwargs every fleet RpcClient shares."""
  return dict(
      authkey=config.authkey,
      transport=getattr(config, "transport", "loopback"),
      sndbuf=getattr(config, "tcp_sndbuf", 0),
      rcvbuf=getattr(config, "tcp_rcvbuf", 0))


def _handshake_clock(config, root_address) -> None:
  """Offsets this process's trace clock to the root host's.

  Every fleet process merges onto ONE timeline — the root serving
  host's CLOCK_MONOTONIC. Actors and the learner handshake over their
  long-lived clients; replica/shard hosts (which otherwise only
  answer) dial this transient hello at startup.
  """
  if root_address is None:
    return
  try:
    client = rpc_lib.RpcClient(
        tuple(root_address),
        call_timeout_secs=getattr(config, "rpc_call_timeout_secs",
                                  rpc_lib.DEFAULT_CALL_TIMEOUT_SECS),
        max_retries=getattr(config, "rpc_max_retries",
                            rpc_lib.DEFAULT_MAX_RETRIES),
        **_client_kwargs(config))
  except Exception:  # noqa: BLE001 — trace alignment is best-effort
    log.warning("clock handshake connect failed", exc_info=True)
    return
  try:
    t_before = time.monotonic()
    hello = client.call("hello")
    t_after = time.monotonic()
    if "monotonic" in hello:
      telemetry.get_tracer().set_clock_offset(
          telemetry.clock_offset_from_handshake(
              hello["monotonic"], t_before, t_after))
  except Exception:  # noqa: BLE001
    log.warning("clock handshake failed", exc_info=True)
  finally:
    client.close()


class _HostState:
  """Everything a serving host serves, plus the RPC method table.

  `host_index` 0 is the ROOT: the reference clock, the learner's
  control endpoint, and — when `replay_hosts == 0` — the owner of the
  whole replay plane (the original single-host fleet, unchanged).
  Indices > 0 are broadcast-tree engine replicas: same engine, same
  `act` surface, no store (actors commit to shard services).
  """

  def __init__(self, config, host_index: int = 0):
    # jax and the model stack load HERE, in the host process — never
    # at module import (actor processes import this package jax-free).
    import jax

    from tensor2robot_tpu.replay.service import (
        ReplayFront,
        ReplayWriteService,
    )
    from tensor2robot_tpu.replay.store import ReplayStore
    from tensor2robot_tpu.serving.cem_policy import CEMPolicyServer

    self._config = config
    self.host_index = int(host_index)
    role = "host" if host_index == 0 else f"host{host_index}"
    # The host's telemetry identity: spans from the RPC layer and the
    # serving/replay planes flush to trace_<role>.jsonl; the ROOT
    # host's clock is the REFERENCE clock every handshaking client
    # offsets against.
    telemetry.configure(
        role, trace_dir=getattr(config, "telemetry_dir", "") or None)
    # Resource watermarks (ISSUE 15): device memory + host RSS +
    # replay/queue fill peaks as rsrc.* gauges. They live in the
    # ordinary registry, so the orchestrator's `telemetry` poll
    # aggregates them fleet-wide for free.
    from tensor2robot_tpu.telemetry import perf as perf_lib
    from tensor2robot_tpu.utils import profiling
    perf_lib.start_resource_sampler(
        sources=[profiling.device_memory_source()])
    self._learner = _build_learner(config)
    state0 = self._learner.create_state(
        jax.random.PRNGKey(config.seed), batch_size=2)
    acting0 = state0.train_state.replace(opt_state=None)
    self.policy_server = CEMPolicyServer(
        self._learner, acting0,
        max_batch=config.serve_max_batch,
        max_wait_us=config.serve_max_wait_us,
        seed=config.seed + 7)
    # The replay plane lives here ONLY on the single-host topology;
    # with shard services (`replay_hosts > 0`) every serving host —
    # root included — is engine-only and commit/sample are shard RPCs.
    if host_index == 0 and getattr(config, "replay_hosts", 0) == 0:
      store = ReplayStore(
          self._learner.transition_specification(),
          capacity=config.replay_capacity,
          num_shards=config.replay_shards,
          seed=config.seed + 11)
      service = ReplayWriteService(
          store,
          queue_batches=config.queue_batches,
          overflow=config.overflow)
      self.replay: Optional[ReplayFront] = ReplayFront(store, service)
    else:
      self.replay = None
    # Per-role registry snapshots pushed by actors/learner over the
    # `telemetry_push` RPC; the orchestrator's `telemetry` poll
    # returns them next to the host's own registry — one aggregated
    # fleet-wide view from one call.
    self._pushed_telemetry: Dict[str, Any] = {}
    self._lock = threading.Lock()
    self.publishes = 0
    self._publish_t0: Optional[float] = None
    self._learner_window: Optional[Tuple[float, int, float, int]] = None
    self._resumes: list = []  # observed backward learner steps
    # Broadcast-tree placement, set by the orchestrator's
    # `configure_broadcast` after every serving host is up. Forward
    # CLIENTS are per-connection (`ctx`) — owned by the publishing
    # connection's handler thread, rebuilt free on reconnect — only
    # the address list is shared state.
    self._children: List[Tuple[str, int]] = []
    self._tree_depth = 0
    self._broadcast_forwards = 0
    self._tm_depth = tmetrics.gauge("fleet.broadcast.depth")
    self._tm_forwards = tmetrics.counter("fleet.broadcast.forwards")
    self._tm_publish_ms = tmetrics.histogram(
        "fleet.broadcast.publish_ms", faults_lib.RECOVERY_MS_BOUNDS)
    self.shutdown_requested = threading.Event()

  # ---- broadcast fan-out ----

  def _forward_publish(self, payload: Dict[str, Any],
                       ctx: dict) -> None:
    """Forwards a publication to this host's tree children.

    Runs on the publishing connection's handler thread with its own
    per-child clients (in `ctx` — lock-free by ownership). A child
    that cannot be reached raises out of the handler: the learner's
    publish call sees the error, exactly as if its own direct publish
    had failed — broadcast does not silently narrow the fleet.
    """
    with self._lock:
      children = list(self._children)
    if not children:
      return
    forwarded = dict(payload)
    forwarded["hop"] = int(payload.get("hop", 0)) + 1
    clients = ctx.setdefault("broadcast_clients", {})
    for child in children:
      client = clients.get(child)
      if client is None:
        client = rpc_lib.RpcClient(
            child,
            call_timeout_secs=getattr(
                self._config, "rpc_call_timeout_secs",
                rpc_lib.DEFAULT_CALL_TIMEOUT_SECS),
            max_retries=getattr(self._config, "rpc_max_retries",
                                rpc_lib.DEFAULT_MAX_RETRIES),
            **_client_kwargs(self._config))
        clients[child] = client
      client.call("publish", forwarded)
      self._tm_forwards.inc()
      with self._lock:
        self._broadcast_forwards += 1

  # ---- the RPC method table ----

  def handle(self, method: str, payload: Any, ctx: dict) -> Any:
    if method == "act":
      # One atomic publication read: version and learner_step must be
      # a consistent pair (a swap between two property reads would
      # tear them). A swap landing between this read and the engine's
      # own dispatch can still attribute a single episode to the
      # adjacent publication — off by at most one refresh, which the
      # lag histogram tolerates (documented in docs/FLEET.md).
      publication = self.policy_server.engine.publication
      actions = self.policy_server.select_actions(payload)
      return {"actions": np.asarray(actions),
              "params_version": publication.version,
              "params_learner_step": publication.learner_step,
              # The acting host's broadcast-tree depth: actors stamp
              # it into commits so lag is attributable PER HOP.
              "params_hop": self._tree_depth}
    if method == "acting_state":
      # Whole-params refresh for Anakin pods (ISSUE 19): a pod acts
      # ON ITS OWN DEVICES (the env and the Q-network are one pmapped
      # program), so instead of per-step `act` RPCs it pulls the
      # published acting state and runs with it until the version
      # moves. `have_version` makes the poll cheap: an unchanged
      # version returns the stamp alone, no state payload.
      publication = self.policy_server.engine.publication
      have = (int(payload.get("have_version", -1))
              if isinstance(payload, dict) else -1)
      reply: Dict[str, Any] = {
          "params_version": publication.version,
          "params_learner_step": publication.learner_step,
          "params_hop": self._tree_depth,
          "state": None,
      }
      if publication.version != have and publication.state is not None:
        import jax
        reply["state"] = jax.device_get(publication.state)
      return reply
    if method in ("commit", "begin_episode", "append", "end_episode",
                  "sample", "size"):
      if self.replay is None:
        raise ValueError(
            f"host {self.host_index} serves no replay "
            "(replay_hosts > 0 — commits and samples go to the shard "
            "services)")
      if method == "commit":
        return self.replay.commit(payload, ctx)
      if method == "begin_episode":
        return self.replay.begin_episode(payload, ctx)
      if method == "append":
        return self.replay.append(payload, ctx)
      if method == "end_episode":
        return self.replay.end_episode(payload, ctx)
      if method == "sample":
        return self.replay.sample(int(payload))
      return self.replay.size()
    if method == "set_learner_step":
      step = int(payload)
      if self.replay is not None:
        self.replay.set_learner_step(step)
      now = time.monotonic()
      with self._lock:
        if self._learner_window is None:
          self._learner_window = (now, step, now, step)
        else:
          t0, s0, _, last = self._learner_window
          if step < last:
            # The learner's step went BACKWARD: a crash-resume
            # restored from a checkpoint. The host is the one witness
            # with continuous state across learner incarnations, so
            # the MEASURED restore point is recorded here — the chaos
            # bench's loss-bounded-by-cadence gate reads it instead
            # of trusting config arithmetic.
            self._resumes.append({"from_step": last, "to_step": step})
          self._learner_window = (t0, s0, now, step)
      return True
    if method == "publish":
      self.policy_server.update_state(
          payload["state"], learner_step=int(payload["step"]))
      with self._lock:
        self.publishes += 1
        if self._publish_t0 is None:
          self._publish_t0 = time.monotonic()
      tmetrics.counter("fleet.param_publishes").inc()
      # Broadcast hop accounting: the learner stamps its wall clock at
      # origin; every host in the tree records origin→local-swap
      # latency (same machine, same wall clock), so hop cost is
      # visible per depth in the merged registry.
      if payload.get("origin_wall") is not None:
        self._tm_publish_ms.observe(
            max(0.0, (time.time() - float(payload["origin_wall"]))
                * 1e3))
      self._forward_publish(payload, ctx)
      return self.policy_server.params_version
    if method == "configure_broadcast":
      with self._lock:
        self._children = [tuple(c) for c in payload.get("children", ())]
        self._tree_depth = int(payload.get("depth", 0))
      self._tm_depth.set(self._tree_depth)
      return True
    if method == "metrics_scalars":
      out = (self.replay.metrics_scalars()
             if self.replay is not None else {})
      out["fleet_param_publishes"] = float(self.publishes)
      return out
    if method == "metrics":
      return self.metrics()
    if method == "hello":
      engine = self.policy_server.engine
      capacity = (self.replay.store.capacity
                  if self.replay is not None
                  else int(self._config.replay_capacity))
      # `monotonic` is the telemetry clock handshake: the client reads
      # its own clock around the call and derives its offset to this
      # host's CLOCK_MONOTONIC (telemetry.clock_offset_from_handshake)
      # — how the merge tool puts every process on one timeline.
      return {"max_batch": engine.max_batch,
              "capacity": capacity,
              "params_version": engine.params_version,
              "params_learner_step": engine.params_learner_step,
              "monotonic": time.monotonic()}
    if method == "telemetry":
      # The fleet-wide aggregated view (one poll): the host's own
      # registry — serving/lag live HERE, at the choke point — plus
      # whatever snapshots the other roles pushed.
      with self._lock:
        pushed = dict(self._pushed_telemetry)
      return {"host": tmetrics.registry().snapshot(),
              "pushed": pushed,
              "monotonic": time.monotonic()}
    if method == "telemetry_push":
      with self._lock:
        self._pushed_telemetry[str(payload["role"])] = {
            "snapshot": payload["snapshot"],
            "wall": time.time(),
        }
      return True
    if method == "flight_record":
      # The orchestrator's latched-error hook: a still-live host dumps
      # its span ring + registry before teardown.
      return flightrec.dump(payload["out_dir"],
                            payload.get("reason", "requested"))
    if method == "shutdown":
      self.shutdown_requested.set()
      return True
    if method == rpc_lib.DISCONNECT_METHOD:
      # A dropped connection aborts every session IT opened: whatever
      # its actor staged mid-episode is discarded, never committed
      # (identity-checked in the front — a late-detected death never
      # touches a restarted incarnation's fresh session). Broadcast
      # forward clients opened by this connection close with it.
      if self.replay is not None:
        self.replay.abort_sessions(ctx)
      for client in ctx.get("broadcast_clients", {}).values():
        client.close()
      return None
    raise ValueError(f"unknown fleet rpc method {method!r}")

  def metrics(self) -> Dict[str, Any]:
    with self._lock:
      learner_window = self._learner_window
      resumes = list(self._resumes)
      publishes = self.publishes
      broadcast = {
          "depth": self._tree_depth,
          "children": len(self._children),
          "forwards": self._broadcast_forwards,
      }
    if self.replay is not None:
      front = self.replay.metrics()
    else:
      front = {"store": None, "service": None, "staleness": {},
               "param_refresh_lag": None, "commit_window": None}
    engine = self.policy_server.engine
    front.update({
        "publishes": publishes,
        "params_version": engine.params_version,
        "params_learner_step": engine.params_learner_step,
        "learner_window": (None if learner_window is None else {
            "first_time": learner_window[0],
            "first_step": learner_window[1],
            "last_time": learner_window[2],
            "last_step": learner_window[3],
        }),
        "learner_resumes": resumes,
        "commit_window": front.get("commit_window"),
        "serving_dispatches": engine.dispatch_count,
        "host_index": self.host_index,
        "broadcast": broadcast,
    })
    return front

  def close(self) -> None:
    # Intake is already stopped (the RPC server closes first); flush
    # what the writer still holds, then tear the batcher down.
    try:
      if self.replay is not None:
        self.replay.close()
    finally:
      self.policy_server.close()


class _ShardState:
  """One replay shard service: a 1-shard store behind a `ReplayFront`.

  The `ReplayShardService` of ISSUE 16: each shard host owns
  `replay_capacity / replay_hosts` rows with the SAME session/commit/
  sample/lag semantics as the single-host plane (shared via
  `ReplayFront` — one implementation, two deployments), so staleness
  and `param_refresh_lag` are accounted where the shard lives.
  """

  def __init__(self, config, shard_index: int):
    from tensor2robot_tpu.replay.service import (
        ReplayFront,
        ReplayWriteService,
    )
    from tensor2robot_tpu.replay.store import ReplayStore

    self._config = config
    self.shard_index = int(shard_index)
    telemetry.configure(
        f"shard{shard_index}",
        trace_dir=getattr(config, "telemetry_dir", "") or None)
    from tensor2robot_tpu.telemetry import perf as perf_lib
    perf_lib.start_resource_sampler()
    num_hosts = max(1, int(getattr(config, "replay_hosts", 1)))
    store = ReplayStore(
        # The spec comes from the same learner constructor every other
        # process uses — structural agreement by construction.
        _build_learner(config).transition_specification(),
        capacity=max(1, config.replay_capacity // num_hosts),
        num_shards=1,  # one shard per host IS the sharding
        seed=config.seed + 11 + 97 * (shard_index + 1))
    service = ReplayWriteService(
        store,
        queue_batches=config.queue_batches,
        overflow=config.overflow)
    self.front = ReplayFront(store, service)
    self.shutdown_requested = threading.Event()

  def handle(self, method: str, payload: Any, ctx: dict) -> Any:
    if method == "commit":
      return self.front.commit(payload, ctx)
    if method == "begin_episode":
      return self.front.begin_episode(payload, ctx)
    if method == "append":
      return self.front.append(payload, ctx)
    if method == "end_episode":
      return self.front.end_episode(payload, ctx)
    if method == "sample":
      return self.front.sample(int(payload))
    if method == "size":
      return self.front.size()
    if method == "set_learner_step":
      self.front.set_learner_step(int(payload))
      return True
    if method == "metrics":
      out = self.front.metrics()
      out["shard_index"] = self.shard_index
      return out
    if method == "metrics_scalars":
      return self.front.metrics_scalars()
    if method == "hello":
      return {"capacity": self.front.store.capacity,
              "shard_index": self.shard_index,
              "monotonic": time.monotonic()}
    if method == "telemetry":
      return {"host": tmetrics.registry().snapshot(),
              "pushed": {},
              "monotonic": time.monotonic()}
    if method == "flight_record":
      return flightrec.dump(payload["out_dir"],
                            payload.get("reason", "requested"))
    if method == "shutdown":
      self.shutdown_requested.set()
      return True
    if method == rpc_lib.DISCONNECT_METHOD:
      self.front.abort_sessions(ctx)
      return None
    raise ValueError(f"unknown replay shard rpc method {method!r}")

  def close(self) -> None:
    self.front.close()


def _build_learner(config):
  """The host's own QTOptLearner: the same constructor the learner
  process uses, so the published TrainState trees match structurally
  (CEM serving params here, gradient state there)."""
  from tensor2robot_tpu.research.qtopt.qtopt_learner import QTOptLearner
  from tensor2robot_tpu.research.qtopt.t2r_models import GraspingQModel

  model = GraspingQModel(
      image_size=config.image_size,
      action_dim=config.action_dim,
      torso_filters=tuple(config.torso_filters),
      head_filters=tuple(config.head_filters),
      dense_sizes=tuple(config.dense_sizes))
  return QTOptLearner(
      model,
      cem_population=config.cem_population,
      cem_iterations=config.cem_iterations,
      cem_elites=config.cem_elites,
      cem_inference=config.cem_inference)


def host_main(config, ready_conn, stop_event, heartbeat,
              host_index: int = 0, root_address=None) -> None:
  """Child-process entry: build → handshake → serve → drain → exit.

  `ready_conn` (a Pipe end) carries the bound RPC address back to the
  orchestrator once the engine is warmed; the orchestrator spawns
  actors/learner only after this handshake, so clients never race a
  cold host.

  `stop_event` is the host's OWN stop signal, set by the orchestrator
  only AFTER the final metrics read — the host must outlive the
  actor/learner drain (it is the last process standing in the
  shutdown barrier). The RPC `shutdown` method is the other exit.

  `host_index` > 0 spawns a broadcast-tree engine replica (no store);
  `root_address` lets non-root hosts align their trace clock to the
  root's before serving.
  """
  proc.scrub_inherited_distributed_env()
  role = "host" if host_index == 0 else f"host{host_index}"
  # Server-side fault seam (slow_host stalls, injected disconnects):
  # armed BEFORE the server accepts, so call counting is deterministic
  # from the first RPC.
  faults_lib.install(config, role)
  try:
    state = _HostState(config, host_index=host_index)
    server = rpc_lib.RpcServer(state.handle, **_server_kwargs(config))
  except BaseException as e:
    # A host that dies building (bad config, compile failure) leaves
    # its last moments in the flight recorder before the orchestrator
    # sees the exit code.
    if getattr(config, "flightrec_dir", ""):
      flightrec.dump(config.flightrec_dir,
                     f"{role} launch failed: {e!r}")
    raise
  try:
    ready_conn.send({"address": server.address})
    ready_conn.close()
    if host_index != 0:
      _handshake_clock(config, root_address)
    while not (stop_event.is_set() or state.shutdown_requested.is_set()):
      proc.beat(heartbeat)
      time.sleep(0.1)
  finally:
    from tensor2robot_tpu.telemetry import perf as perf_lib
    perf_lib.stop_resource_sampler()  # no jax calls past teardown
    server.close()
    state.close()
    telemetry.get_tracer().close()  # flush the host's trace tail


def replay_shard_main(config, shard_index: int, root_address,
                      ready_conn, stop_event, heartbeat) -> None:
  """Child-process entry for one replay shard service (ISSUE 16).

  Same lifecycle contract as `host_main`: address handshake over
  `ready_conn`, heartbeat while serving, drain on `stop_event` (set
  only after the orchestrator's final metrics read) or the RPC
  `shutdown`.
  """
  proc.scrub_inherited_distributed_env()
  role = f"shard{shard_index}"
  faults_lib.install(config, role)
  try:
    state = _ShardState(config, shard_index)
    server = rpc_lib.RpcServer(state.handle, **_server_kwargs(config))
  except BaseException as e:
    if getattr(config, "flightrec_dir", ""):
      flightrec.dump(config.flightrec_dir,
                     f"{role} launch failed: {e!r}")
    raise
  try:
    ready_conn.send({"address": server.address})
    ready_conn.close()
    _handshake_clock(config, root_address)
    while not (stop_event.is_set() or state.shutdown_requested.is_set()):
      proc.beat(heartbeat)
      time.sleep(0.1)
  finally:
    from tensor2robot_tpu.telemetry import perf as perf_lib
    perf_lib.stop_resource_sampler()
    server.close()
    state.close()
    telemetry.get_tracer().close()
