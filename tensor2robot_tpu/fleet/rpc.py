"""Loopback RPC for the fleet: actors/learner ⇄ replay/serving host.

The Podracer decomposition (PAPERS.md, "Podracer architectures for
scalable RL") puts the environment loops, the inference server, the
replay service, and the learner in separate PROCESSES; what connects
them is a small request/response protocol. This module is that
protocol's transport, built on `multiprocessing.connection` (stdlib
pickle framing over a loopback TCP socket — no new dependency, and the
same `Listener`/`Client` pair a real multi-host deployment would swap
for its RPC system of choice):

  * `RpcServer` — accept loop + one handler thread per connection.
    The handler callable sees `(method, payload, ctx)` where `ctx` is
    a per-connection dict that SURVIVES until disconnect: the host
    stores each connection's replay-session ids there, and the
    synthetic `__disconnect__` call on EOF is how a crashed actor's
    staged half-episode gets aborted server-side (the session-abort
    crash contract of `replay.service`, extended across the process
    boundary).
  * `RpcClient` — blocking request/response with a PER-CALL DEADLINE
    and exponential-backoff-and-jitter retries (ISSUE 14): every call
    bounds its wait for the reply (`call_timeout_secs`, default 120s —
    a half-dead host strands nobody until a heartbeat timer fires),
    and a timed-out or dropped connection is retried through a fresh
    connection (session state needs no client-side re-establishment:
    the host re-creates an actor's session on first use of the new
    connection, aborting whatever the old one staged — `_session_for`
    keyed on actor_id). Retries are at-least-once: the replay
    session-abort contract guarantees a retried commit never lands a
    PARTIAL episode (a duplicate whole episode is possible and
    harmless — `adds_total % batch_episodes` stays 0). NOT thread-safe
    by design: one owner thread per client. A process that needs RPC
    from two threads (the learner's train loop + its prefetch thread)
    opens two clients — loopback connections are cheap, and two
    sockets beat a lock that would serialize a param publish behind a
    slow sample (and trip the CON301 blocking-under-lock rule this
    package is linted with).

Fault-injection seams (`fleet/faults.py`, chaos testing): the module
holds one process-global injector consulted on every client call
(delay / drop-the-send) and every server handler turn (stall /
disconnect). The seams sit in the REAL code paths, so an injected
drop times out through the same deadline and recovers through the
same retry machinery a production fault would.

This module must stay importable WITHOUT jax: actor processes import
it at spawn and never touch a device (tests/test_fleet.py pins the
jax-free actor import).
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
import traceback
from multiprocessing.connection import Client, Listener
from typing import Any, Callable, List, Optional, Tuple

from tensor2robot_tpu import telemetry
from tensor2robot_tpu.fleet import transport as transport_lib
from tensor2robot_tpu.telemetry import metrics as tmetrics

log = logging.getLogger(__name__)

# Transport seam (ISSUE 16). "loopback" is the stdlib
# multiprocessing.connection pair this module was born on — bitwise
# back-compat, still the single-host default. "tcp" is
# `fleet.transport`: real sockets, zero-copy out-of-band frames, the
# same authkey riding an HMAC challenge — the cross-host path. The
# deadline/retry/poisoning contract, the fault seams, and the span
# stamps below are all WRITTEN AGAINST the shared connection shape
# (send/recv/poll/close), so both transports inherit them from the
# same lines of code rather than from parallel implementations
# (tests/test_fleet_transport.py pins the parity).
TRANSPORTS = ("loopback", "tcp")

# The shared secret for connection auth. Loopback-only transport; the
# orchestrator generates a per-fleet key so two fleets on one machine
# cannot cross-connect even if they guess each other's port.
DEFAULT_AUTHKEY = b"t2r-fleet"

DISCONNECT_METHOD = "__disconnect__"

# Deadline/retry defaults (overridable per client and per call). The
# default deadline is deliberately generous — it exists to unstrand
# callers from a dead host, not to police a slow one; latency-critical
# callers pass tighter per-call values.
DEFAULT_CALL_TIMEOUT_SECS = 120.0
DEFAULT_MAX_RETRIES = 2
_BACKOFF_BASE_SECS = 0.05
_BACKOFF_MAX_SECS = 2.0

# Process-global fault injector (faults.FaultInjector or None). One
# per process is the right granularity: a fleet child is either a
# client-side process (actor/learner) or the host.
_fault_injector: Optional[Any] = None


def set_fault_injector(injector: Optional[Any]) -> None:
  """Installs (or clears, with None) this process's RPC fault seam."""
  global _fault_injector
  _fault_injector = injector


def _fault_action(side: str, method: str) -> Optional[Tuple[str, float]]:
  injector = _fault_injector
  if injector is None:
    return None
  return injector.rpc_action(side, method)


class RpcError(RuntimeError):
  """A handler raised on the server side; carries the remote traceback."""


class RpcServer:
  """Threaded request/response server over a loopback Listener."""

  def __init__(self,
               handler: Callable[[str, Any, dict], Any],
               host: str = "127.0.0.1",
               authkey: bytes = DEFAULT_AUTHKEY,
               transport: str = "loopback",
               sndbuf: int = 0,
               rcvbuf: int = 0):
    """`handler(method, payload, ctx) -> result` runs on a
    per-connection thread; exceptions it raises are serialized back to
    the caller as `RpcError` (the connection stays up). On EOF the
    synthetic `(DISCONNECT_METHOD, None, ctx)` call runs once.
    `transport`/`sndbuf`/`rcvbuf`: see `TRANSPORTS` above (buffer
    sizes apply to "tcp" only; 0 = OS default)."""
    if transport not in TRANSPORTS:
      raise ValueError(
          f"transport must be one of {TRANSPORTS}, got {transport!r}")
    self._handler = handler
    if transport == "tcp":
      self._listener = transport_lib.TcpListener(
          host, 0, authkey=authkey, sndbuf=sndbuf, rcvbuf=rcvbuf)
    else:
      self._listener = Listener((host, 0), authkey=authkey)
    self.transport = transport
    self.address: Tuple[str, int] = self._listener.address
    self._stop = threading.Event()
    self._lock = threading.Lock()
    self._conns: List[Any] = []
    self._threads: List[threading.Thread] = []
    self._accept_thread = threading.Thread(
        target=self._accept_loop, name="fleet-rpc-accept", daemon=True)
    self._accept_thread.start()

  def _accept_loop(self) -> None:
    while not self._stop.is_set():
      try:
        conn = self._listener.accept()
      except (OSError, EOFError):
        # close() closed the listener under us (the only way to
        # unblock accept); anything else on a closed socket is the
        # same shutdown signal.
        return
      except Exception:  # auth failure from a stray connector
        log.warning("fleet rpc: rejected connection", exc_info=True)
        continue
      thread = threading.Thread(
          target=self._serve, args=(conn,),
          name="fleet-rpc-conn", daemon=True)
      with self._lock:
        self._conns.append(conn)
        self._threads.append(thread)
      thread.start()

  def _serve(self, conn) -> None:
    ctx: dict = {}
    try:
      while not self._stop.is_set():
        try:
          message = conn.recv()
        except (EOFError, OSError):
          break
        # Wire format: (method, payload[, req]). `req` is the
        # client-stamped correlation id (ISSUE 15): echoed into the
        # server-side span so telemetry.merge links the
        # rpc_call.<m>/rpc.<m> pair as one Perfetto flow. Two-tuples
        # stay accepted (id-less callers).
        method, payload = message[0], message[1]
        req = message[2] if len(message) > 2 else None
        # Server-side fault seam (chaos): a stall models a slow host,
        # a disconnect models a half-dead one — the break runs the
        # REAL disconnect path below (session abort and all), and the
        # client recovers through its real reconnect-and-retry.
        action = _fault_action("server", method)
        if action is not None:
          kind, secs = action
          if kind == "delay":
            time.sleep(secs)
          elif kind == "disconnect":
            break
        try:
          # Every RPC method gets a server-side span for free: the
          # merged timeline shows act/commit/sample handler time per
          # connection thread (no-op until telemetry is configured).
          # The echoed `req` makes it one flow with the client span.
          span_args = {"req": req} if req is not None else {}
          with telemetry.span(f"rpc.{method}", **span_args):
            result = self._handler(method, payload, ctx)
          reply = ("ok", result)
        except BaseException:  # serialized back, connection stays up
          reply = ("err", traceback.format_exc())
        try:
          conn.send(reply)
        except (EOFError, OSError):
          break
    finally:
      try:
        self._handler(DISCONNECT_METHOD, None, ctx)
      except Exception:
        log.exception("fleet rpc: disconnect handler failed")
      try:
        conn.close()
      except OSError:
        pass
      with self._lock:
        if conn in self._conns:
          self._conns.remove(conn)

  def close(self, timeout_secs: float = 5.0) -> None:
    """Stops intake: closes the listener (unblocks accept) and every
    live connection (unblocks recv), then joins the handler threads."""
    self._stop.set()
    try:
      self._listener.close()
    except OSError:
      pass
    with self._lock:
      conns = list(self._conns)
      threads = list(self._threads)
    for conn in conns:
      try:
        conn.close()
      except OSError:
        pass
    deadline = time.monotonic() + timeout_secs
    for thread in threads + [self._accept_thread]:
      thread.join(timeout=max(0.0, deadline - time.monotonic()))

  def __enter__(self) -> "RpcServer":
    return self

  def __exit__(self, *exc) -> bool:
    self.close()
    return False


class RpcClient:
  """Deadline-bounded request/response client with retry. One owner
  thread per instance (see module docstring) — open a second client
  for a second thread."""

  def __init__(self,
               address: Tuple[str, int],
               authkey: bytes = DEFAULT_AUTHKEY,
               connect_timeout_secs: float = 20.0,
               call_timeout_secs: Optional[float] =
               DEFAULT_CALL_TIMEOUT_SECS,
               max_retries: int = DEFAULT_MAX_RETRIES,
               transport: str = "loopback",
               sndbuf: int = 0,
               rcvbuf: int = 0):
    """`call_timeout_secs` is the default per-call reply deadline
    (None disables — the pre-ISSUE-14 strand-forever behavior, opt-in
    only); `max_retries` bounds reconnect-and-retry attempts per
    call. A retried caller needs no session re-establishment: the
    host rebuilds sessions server-side on first use of the fresh
    connection (see the module docstring). `transport` must match the
    server's (see `TRANSPORTS`)."""
    if transport not in TRANSPORTS:
      raise ValueError(
          f"transport must be one of {TRANSPORTS}, got {transport!r}")
    self._address = tuple(address)
    self._authkey = authkey
    self._transport = transport
    self._sndbuf = sndbuf
    self._rcvbuf = rcvbuf
    self._connect_timeout = connect_timeout_secs
    self._call_timeout = call_timeout_secs
    self._max_retries = int(max_retries)
    self.reconnects = 0
    self._conn = None
    # Correlation-id sequence (ISSUE 15): every call stamps a
    # process-unique `req` into its client span AND the wire triple;
    # the server echoes it into its handler span, and telemetry.merge
    # links the pair as one Perfetto flow event. Single-owner like the
    # client itself — a bare increment is safe.
    self._req_seq = 0
    self._connect(connect_timeout_secs)

  def _connect(self, timeout_secs: float) -> None:
    deadline = time.monotonic() + timeout_secs
    last_error: Optional[BaseException] = None
    while True:
      try:
        if self._transport == "tcp":
          self._conn = transport_lib.connect_tcp(
              self._address, self._authkey,
              sndbuf=self._sndbuf, rcvbuf=self._rcvbuf)
        else:
          self._conn = Client(self._address, authkey=self._authkey)
        return
      except (ConnectionRefusedError, FileNotFoundError, OSError) as e:
        # The host process may still be warming up its engine (or
        # rebinding after a fault); retry until the window closes.
        last_error = e
        if time.monotonic() > deadline:
          raise TimeoutError(
              f"fleet rpc: no server at {self._address} after "
              f"{timeout_secs:.0f}s") from last_error
        time.sleep(0.05)

  def call_once(self, method: str, payload: Any = None,
                timeout_secs: Optional[float] = None) -> Any:
    """ONE request/response round trip — no retry, no reconnect.

    `timeout_secs` bounds the wait for the REPLY (None falls back to
    the client default; an explicit None default disables). On expiry
    raises `TimeoutError` and the connection must be considered
    POISONED (an in-flight reply may still arrive and would be read as
    the answer to the next call); on a dropped connection raises
    `ConnectionError`. `RpcError` when the server-side handler raised.
    """
    timeout = (self._call_timeout if timeout_secs is None
               else timeout_secs)
    self._req_seq += 1
    req = f"{os.getpid()}-{id(self) & 0xffffff:x}-{self._req_seq}"
    try:
      # Client-side span: the caller's view of the same RPC (queueing
      # + transport + handler), so actor-vs-host wait decomposes in
      # the merged timeline; `req` links it to the server span as one
      # flow (telemetry.merge).
      with telemetry.span(f"rpc_call.{method}", req=req):
        action = _fault_action("client", method)
        if action is not None:
          kind, secs = action
          if kind == "delay":
            time.sleep(secs)
            action = None
        if action is None:
          # (a "drop" skips the send: the request is lost in flight
          # and the REAL deadline below fires.)
          self._conn.send((method, payload, req))
        if timeout is not None and not self._conn.poll(timeout):
          tmetrics.counter("fleet.rpc.timeouts").inc()
          raise TimeoutError(
              f"fleet rpc: no reply to {method!r} in "
              f"{timeout:.0f}s")
        status, value = self._conn.recv()
    except TimeoutError:
      # Before the broad OSError clause: TimeoutError IS an OSError
      # subclass, and the deadline must never be rebranded as a
      # connection drop (callers distinguish the two).
      raise
    except (EOFError, OSError) as e:
      raise ConnectionError(
          f"fleet rpc: server dropped during {method!r}") from e
    if status == "err":
      raise RpcError(f"remote {method!r} failed:\n{value}")
    return value

  def call(self, method: str, payload: Any = None,
           timeout_secs: Optional[float] = None,
           max_retries: Optional[int] = None) -> Any:
    """Request/response with deadline + reconnect-and-retry.

    A `TimeoutError` or `ConnectionError` closes the (poisoned)
    connection, backs off exponentially with jitter, reconnects, and
    resends — up to `max_retries` times, after which the last error
    is raised. `RpcError` (a server-side handler
    exception) never retries: the request ARRIVED; re-sending it is
    the application's decision, not the transport's. Retried commits
    are at-least-once (see module docstring — partial rows can never
    land, duplicates are whole episodes).
    """
    retries = self._max_retries if max_retries is None else max_retries
    t_first_failure: Optional[float] = None
    attempt = 0
    while True:
      try:
        result = self.call_once(method, payload,
                                timeout_secs=timeout_secs)
        if t_first_failure is not None:
          # The call RECOVERED: stamp the end-to-end outage the caller
          # experienced (first failure → first success) into the
          # shared recovery histogram next to the process-level MTTRs.
          from tensor2robot_tpu.fleet import faults
          recovery_ms = (time.monotonic() - t_first_failure) * 1e3
          faults.recovery_histogram().observe(recovery_ms)
          tmetrics.counter("fleet.rpc.recovered").inc()
          telemetry.event("fleet.rpc_recovered", method=method,
                          attempts=attempt,
                          recovery_ms=round(recovery_ms, 1))
        return result
      except (TimeoutError, ConnectionError) as e:
        if t_first_failure is None:
          t_first_failure = time.monotonic()
        if attempt >= retries:
          raise
        attempt += 1
        tmetrics.counter("fleet.rpc.retries").inc()
        log.warning(
            "fleet rpc: %r failed (%s); retry %d/%d with fresh "
            "connection", method, e, attempt, retries)
        # Poisoned-on-timeout contract: never reuse the old socket.
        try:
          self._conn.close()
        except OSError:
          pass
        backoff = min(_BACKOFF_MAX_SECS,
                      _BACKOFF_BASE_SECS * (2 ** (attempt - 1)))
        # Full jitter: concurrent retriers (every actor saw the same
        # host stall) must not reconnect in lockstep.
        time.sleep(backoff * random.random())
        self._connect(self._connect_timeout)
        self.reconnects += 1
        tmetrics.counter("fleet.rpc.reconnects").inc()

  def close(self) -> None:
    if self._conn is not None:
      try:
        self._conn.close()
      except OSError:
        pass
      self._conn = None

  def __enter__(self) -> "RpcClient":
    return self

  def __exit__(self, *exc) -> bool:
    self.close()
    return False
